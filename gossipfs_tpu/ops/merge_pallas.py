"""Pallas TPU kernel for the gossip fanout max-merge — the hot op.

Per round, every receiver i merges the membership rows of its ``F`` fanout
peers with an elementwise max (the tensorized MergeMemberList, reference:
slave/slave.go:414-440):

    out[i, :] = max_f view[edges[i, f], :]

where ``view`` is the gossip view (heartbeat if the entry is gossipable,
-1 otherwise).  This is a bandwidth problem: F·N² reads with a
data-dependent row gather.  XLA's gather lowering reaches ~140 GB/s on a
v5e chip; this kernel sustains ~4-6x that by:

  * keeping the whole ``view`` in HBM and gathering rows with explicit
    async DMAs (``pltpu.make_async_copy``), ``slots``-deep double-buffered
    so the VPU max never waits on memory;
  * reshaping to ``[N, N/C, C/128, 128]`` so each gathered unit is a
    tile-aligned ``(C/128, 128)`` block (Mosaic rejects single-row slices
    of an ``(8,128)``-tiled HBM buffer); large ``block_c`` keeps the DMA
    count low — descriptor issue, not bytes, is the limiter once the view
    is narrow (core/rounds.py rebases heartbeats into ``config.view_dtype``,
    int16 or int8, cutting the gather's bytes 2-4x vs int32);
  * accumulating the F-way max entirely in VMEM — the output is written
    exactly once, so total traffic is the information floor
    (F reads + 1 write per state element).

The kernel is semantically a pure function; ``interpret=True`` runs it on
CPU for tests (tests/test_merge_pallas.py pins it against the XLA
formulation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128

# Narrowest column block the COMPILED kernel can move: the int8 lanes'
# native tile is (32, 128), so a DMA unit (C/128, 128) needs C >= 32*128.
# Below this (small N, narrow shards) the dispatch (core/rounds._use_pallas)
# stays on the XLA path; interpret mode has no tiling and runs any size.
MIN_COMPILED_BLOCK_C = 32 * LANE


def _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk, slots, sink,
                     jdim: int = 1):
    """The slotted gather pipeline shared by both kernels.

    For each receiver row r in the block: async-DMA the ``F`` sender view
    rows (``slots``-deep double-buffered so the VPU max never waits on
    memory), widen to int32 for the F-way max (v5e Mosaic has no narrow-int
    vector compare/max — the DMAs still move the narrow dtype, which is
    what the kernel is bound by), and hand the per-row maximum to ``sink``.
    ``jdim``: which grid dimension indexes the column block.
    """
    j = pl.program_id(jdim)

    def issue(r, slot):
        for f in range(n_fanout):
            pltpu.make_async_copy(
                view_ref.at[edges_ref[r, f], j],
                scratch.at[slot, f],
                sems.at[slot, f],
            ).start()

    def wait(slot):
        for f in range(n_fanout):
            # src is irrelevant for wait(); shapes must match the start
            pltpu.make_async_copy(
                view_ref.at[0, j], scratch.at[slot, f], sems.at[slot, f]
            ).wait()

    for s in range(slots - 1):
        issue(s, s)

    def body(r, _):
        slot = lax.rem(r, slots)

        @pl.when(r + slots - 1 < r_blk)
        def _():
            issue(r + slots - 1, lax.rem(r + slots - 1, slots))

        wait(slot)
        acc = scratch[slot, 0].astype(jnp.int32)
        for f in range(1, n_fanout):
            acc = jnp.maximum(acc, scratch[slot, f].astype(jnp.int32))
        sink(r, acc)
        return 0

    lax.fori_loop(0, r_blk, body, 0, unroll=False)


def _kernel(n_fanout: int, r_blk: int, slots: int):
    def kernel(edges_ref, view_ref, out_ref, scratch, sems):
        # edges_ref: [r_blk, F] int32 in SMEM (this row-block's in-edges)
        # view_ref:  [N, N/C, C/128, 128] in HBM (never copied wholesale)
        # out_ref:   [r_blk, 1, C/128, 128] in VMEM
        # scratch:   [slots, F, C/128, 128] VMEM; sems: [slots, F]
        def sink(r, acc):
            out_ref[r, 0] = acc.astype(out_ref.dtype)

        _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk, slots, sink)

    return kernel


def supported(n: int, fanout: int, n_cols: int | None = None) -> bool:
    """Whether the kernel's tiling constraints admit this problem size.

    ``n_cols`` (default: square) is the local subject count — smaller than
    ``n`` under subject-axis sharding, where each shard must still be
    lane-aligned.
    """
    if n_cols is None:
        n_cols = n
    return (
        n % LANE == 0 and n >= LANE and n_cols % LANE == 0 and n_cols >= LANE
        and fanout >= 1
    )


@functools.partial(
    jax.jit, static_argnames=("block_r", "block_c", "slots", "interpret")
)
def fanout_max_merge(
    view: jax.Array,
    edges: jax.Array,
    *,
    block_r: int = 128,
    block_c: int = 8192,
    slots: int = 4,
    interpret: bool = False,
) -> jax.Array:
    """out[i, :] = max over f of view[edges[i, f], :].

    ``view``: [N, N], any fixed-width integer dtype — production passes the
    rebased view built in core/rounds.py (``config.view_dtype``: int16 or
    int8, so 1-2 bytes/elem of DMA traffic); int32 works too.  Use -1 for
    "absent" lanes so the max ignores them.
    ``edges``: int32 [N, F] in-edge sender ids.  Defaults are the tuned v5e
    values; blocks shrink automatically for small N.
    """
    n = view.shape[0]
    fanout = edges.shape[1]
    if view.shape != (n, n):
        raise ValueError(f"view must be square [N, N], got {view.shape}")
    if not supported(n, fanout):
        raise ValueError(
            f"pallas merge needs N % {LANE} == 0 and fanout >= 1 "
            f"(N={n}, fanout={fanout}); use the XLA path"
        )
    # blocks must tile N exactly; halving bottoms out at LANE, which always
    # divides a lane-aligned N
    c_blk = min(block_c, n)
    while n % c_blk:
        c_blk //= 2
    if not interpret and c_blk < MIN_COMPILED_BLOCK_C:
        raise ValueError(
            f"compiled pallas merge needs >= {MIN_COMPILED_BLOCK_C}-wide "
            f"column blocks (got {c_blk} at N={n}); Mosaic rejects "
            "sub-tile DMA units — use interpret mode or the XLA path"
        )
    r_blk = min(block_r, n)
    while n % r_blk:
        r_blk //= 2
    n_slots = max(2, min(slots, r_blk))
    cs = c_blk // LANE

    view4 = view.reshape(n, n // c_blk, cs, LANE)
    out4 = pl.pallas_call(
        _kernel(fanout, r_blk, n_slots),
        grid=(n // r_blk, n // c_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda i, j: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (r_blk, 1, cs, LANE),
            lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, n // c_blk, cs, LANE), view.dtype),
        scratch_shapes=[
            pltpu.VMEM((n_slots, fanout, cs, LANE), view.dtype),
            pltpu.SemaphoreType.DMA((n_slots, fanout)),
        ],
        interpret=interpret,
    )(edges, view4)
    return out4.reshape(n, n)


def _fused_kernel(
    n: int, n_fanout: int, r_blk: int, slots: int,
    member: int, unknown: int, age_clamp: int, failed: int, detect_stats: bool,
):
    def kernel(
        edges_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref, sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        best_scratch, hb_vmem, age_vmem, status_vmem, scratch, sems, row_sems,
    ):
        # edges_ref: [r_blk, F] int32 SMEM — dead receivers' edges are
        #            remapped to self by the wrapper (their own view row is
        #            all -1, making the merge a no-op for them while the
        #            age advance still applies)
        # view_ref / hb/age/status_hbm: [N/R or N, ..., C/128, 128] in HBM.
        #            The receiver-row lanes are copied block-at-a-time with
        #            explicit DMAs that overlap the gather loop — VMEM-block
        #            inputs measured 5x slower here (Mosaic serialized their
        #            per-grid-step copies against the manual gather DMAs).
        # Grid (nc, n // r_blk): column block j OUTER, receiver block i
        # inner, so the per-subject reduction outputs (indexed by j only)
        # accumulate across consecutive i steps while resident in VMEM —
        # same pattern as the stripe kernels.
        j = pl.program_id(0)
        i = pl.program_id(1)

        # block-input DMAs for the receiver lanes: issued before the gather
        # loop, awaited after it — their ~3 MB fully hides under the
        # gather's F x r_blk row copies.  The lane refs stay 4-D (dynamic
        # row-block slices) so the OUTPUT lanes can alias them: each block
        # is read exactly once, strictly before its own step writes it, so
        # in-place update is safe — and drops three [N, N]-lane buffers
        # from the round's peak HBM (what bounds single-chip capacity).
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        # Phase 1 — row loop: gather + F-way max into best_scratch.  The
        # loop body stays minimal so the DMA waits dominate it; everything
        # else runs once per block, vectorized (a per-row epilogue measured
        # 2x slower than the whole unfused pipeline — tiny (cs, 128) tiles
        # serialize the VPU work against the gather waits).
        def sink(r, acc):
            best_scratch[r] = acc

        _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk,
                         slots, sink, jdim=0)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
        )

    return kernel


# Default receiver rows per fused-kernel block (config.merge_block_r
# overrides via the block_r argument).  128 rows x 16384 cols puts the
# in/out hb (int32) + age/status (int8) blocks + epilogue temporaries well
# past Mosaic's 16 MB default scoped-VMEM budget — the pallas_call below
# raises the limit, and 128 measured ~7% faster than 32 (fewer block
# boundaries) at N=16k.  The floor is 32: the int8 block tile is (32, 128).
_FUSED_BLOCK_R = 128
_FUSED_BLOCK_R_MIN = 32


# widest single column block the VMEM budget allows (gather scratch +
# receiver-lane blocks at _FUSED_BLOCK_R rows; see the pallas_call's
# vmem_limit note)
_FULL_ROW_MAX = 16_384


def blocked_cols(n_cols: int, block_c: int) -> tuple[int, int, int]:
    """The kernel-native column blocking [C_total/C, C/128, 128].

    Columns may be fewer than rows: under subject-axis sharding each shard
    blocks its local column slice independently.  Blocks must tile n_cols
    exactly; for a non-power-of-two count (e.g. 10,240) the power-of-two
    halving would shatter into tiny blocks and multiply the gather's DMA
    descriptor count, so lane-aligned widths take one full-width block
    instead whenever it fits VMEM.
    """
    c_blk = min(block_c, n_cols)
    while n_cols % c_blk:
        c_blk //= 2
    if c_blk < min(block_c, n_cols) and n_cols <= _FULL_ROW_MAX:
        c_blk = n_cols
    return (n_cols // c_blk, c_blk // LANE, LANE)


def blocked_shape(n: int, block_c: int) -> tuple[int, int, int, int]:
    """The kernel-native [N, N/C, C/128, 128] shape for an [N, N] lane.

    TPU arrays are physically tiled; reshaping [N, N] into this 4-D form
    (needed so a DMA can fetch one sender row as a tile-aligned block) is a
    real relayout pass, ~1-3 ms per lane at N=16k.  core/rounds.py therefore
    keeps the whole state in this blocked layout across the scan and
    reshapes once at entry/exit instead of every round.
    """
    return (n,) + blocked_cols(n, block_c)


def fused_merge_update(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    block_r: int = _FUSED_BLOCK_R,
    block_c: int = 8192,  # match SimConfig.merge_block_c's default
    slots: int = 4,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """2-D convenience wrapper around :func:`fused_merge_update_blocked`.

    Takes/returns [N, N] lanes; each call pays the blocked-layout reshapes,
    so the scan hot path uses the blocked variant directly.  Used by
    core/rounds.py for ring topology, where per-round edge derivation needs
    the 2-D layout anyway.
    """
    n = view.shape[0]
    shp = blocked_shape(n, block_c)
    h4, a4, s4, _cnt, _nd, _fo = fused_merge_update_blocked(
        view.reshape(shp),
        edges,
        hb.reshape(shp),
        age.reshape(shp),
        status.reshape(shp),
        shift_a.reshape(shp[1:]),
        shift_b.reshape(shp[1:]),
        alive,
        member=member,
        unknown=unknown,
        age_clamp=age_clamp,
        block_r=block_r,
        slots=slots,
        interpret=interpret,
    )
    return h4.reshape(n, n), a4.reshape(n, n), s4.reshape(n, n)


@functools.partial(
    jax.jit,
    static_argnames=(
        "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "slots", "interpret"
    ),
)
def fused_merge_update_blocked(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    slots: int = 4,
    interpret: bool = False,
) -> tuple[jax.Array, ...]:
    """Gossip merge + membership update + age advance in one pass.

    Fuses the tail of core/rounds.py ``_merge`` (un-rebase, max-merge
    advance, UNKNOWN add, fresh-stamp) and the post-merge ``age + 1`` clamp
    into the gather kernel's epilogue, so the [N, N] hb/age/status lanes
    are read and written exactly once per round instead of once by the
    kernel plus once by a separate XLA pass (~25% of round time at N=16k).

    All [N, N] lanes arrive in the :func:`blocked_shape` 4-D layout (the
    scan keeps state blocked so no per-round relayout happens).
    ``shift_a``/``shift_b`` are per-subject int32 vectors in the blocked
    [N/C, C/128, 128] form: stored->view-encoding shift and old->new
    stored-base shift (core/rounds.py ``_merge`` derives both; in int32
    mode shift_a is the view rebase base and shift_b is zero).  ``edges``
    int32 [N, F]; ``alive`` int32 [N] (receiver liveness).  Returns the
    updated (hb, age, status, member_cnt, n_det, first_obs) — the last
    three as in :func:`stripe_merge_update_blocked` (counts/stats are
    accumulated in-kernel; the stat lanes are zeros unless
    ``detect_stats``).
    """
    n, nc, cs, _ = view.shape
    fanout = edges.shape[1]
    if not supported(n, fanout):
        raise ValueError(
            f"fused merge needs N % {LANE} == 0 and fanout >= 1 "
            f"(N={n}, fanout={fanout}); use the XLA path"
        )
    c_blk = cs * LANE
    # cap rows x cols at the validated VMEM budget (128 x 16384 compiles at
    # ~85 MB of scoped VMEM; bigger blocks OOM at runtime) so an oversized
    # merge_block_r degrades to a smaller block instead of crashing
    vmem_cap_rows = max(_FUSED_BLOCK_R_MIN, (_FUSED_BLOCK_R * 16_384) // c_blk)
    r_blk = max(min(block_r, n, vmem_cap_rows), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    n_slots = max(2, min(slots, r_blk))

    # the alive gate, without a per-row vector operand: a dead receiver's
    # edges all point at itself — a dead node is never a sender, so its own
    # view row is all -1 and its merge is a no-op (only the age advance
    # applies), exactly the reference semantics for a crashed process
    self_idx = jnp.arange(n, dtype=edges.dtype)[:, None]
    edges = jnp.where((alive != 0)[:, None], edges, self_idx)
    # liveness replicated across the lane dim for clean vector broadcast
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))

    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    view4 = view
    out = pl.pallas_call(
        _fused_kernel(n, fanout, r_blk, n_slots, member, unknown, age_clamp,
                      failed, detect_stats),
        grid=(nc, n // r_blk),
        # in-place lane update: outputs 0-2 reuse the (post-tick) input
        # lane buffers — see the kernel's DMA comment for why it's safe.
        # Costs ~2 ms/round at N=16k (Mosaic pipelines aliased writes more
        # conservatively), so the stripe/arc kernels — whose sizes fit HBM
        # comfortably — stay non-aliased; HERE the three reclaimed lane
        # buffers are what fits N=49,152 on one chip at all
        input_output_aliases={2: 0, 3: 1, 4: 2},
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.VMEM((n_slots, fanout, cs, LANE), view.dtype),
            pltpu.SemaphoreType.DMA((n_slots, fanout)),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        # 128-row blocks + the block-wide epilogue's widened int32
        # temporaries put peak scoped-VMEM at ~85 MB with 16k-wide blocks —
        # far above Mosaic's 16 MB default but inside the v5e's 128 MB
        # physical VMEM
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(edges, view4, hb, age, status, alive_lanes, shift_a, shift_b)
    return tuple(out)


def _epilogue_and_count(
    best_rel, hb, age, st, recv, sa, sb,
    hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
    i, r_blk: int, member: int, unknown: int, age_clamp: int,
    failed: int, detect_stats: bool, n: int, fail=None,
):
    """Block-wide merge epilogue shared by the stripe kernels.

    MergeMemberList semantics over post-tick values (core/rounds.py
    ``_membership_update``'s int32+clip formulation; ``hb``/``age``/``st``
    arrive widened to int32, ``recv`` is the receiver-liveness mask), plus
    per-subject reductions accumulated across the consecutive receiver
    blocks that revisit the same output block (grid: j outer, i inner):

    * ``cnt_out`` — live observers holding the entry (self included — the
      caller subtracts the diagonal);
    * ``ndet_out`` / ``fobs_out`` (only when ``detect_stats``) — this
      round's detector firings per subject and the lowest firing observer
      (``n`` where no observer fired).  ``fail`` is the exact in-kernel
      fail mask when the tick ran in-kernel; otherwise the stats fall back
      to the ``status == FAILED and age == 0`` identity, valid under the
      crash-only + fresh_cooldown + no-remove-broadcast fault model (the
      detector is the only writer of FAILED, it stamps age 0, and every
      older FAILED entry has aged at least once).

    These replace full-matrix major-axis reductions in XLA, which measured
    ~6x slower than minor-axis reductions.
    """
    any_member = best_rel >= 0
    advance = recv & any_member & (st == member) & (best_rel > hb - sa)
    add = recv & any_member & (st == unknown)
    upd = advance | add
    new_hb = jnp.where(upd, best_rel + (sa - sb), hb - sb)
    if hb_out.dtype != jnp.int32:
        info = jnp.iinfo(hb_out.dtype)
        new_hb = jnp.clip(new_hb, info.min, info.max)
    hb_out[:, 0] = new_hb.astype(hb_out.dtype)
    new_age = jnp.minimum(jnp.where(upd, 0, age) + 1, age_clamp)
    age_out[:, 0] = new_age.astype(age_out.dtype)
    st_new = jnp.where(add, member, st)
    status_out[:, 0] = st_new.astype(status_out.dtype)

    part = jnp.sum((recv & (st_new == member)).astype(jnp.int32), axis=0)[None]
    if detect_stats:
        # recv-masked even though today's writers make it redundant (the
        # detector is the only writer of FAILED/age=0 and it only fires on
        # live receivers): a future writer of FAILED/age=0 — matrix events
        # or remove_broadcast on this path — must not inflate the stats
        # (ADVICE r3)
        fresh = (fail if fail is not None else (st == failed) & (age == 0)) & recv
        ndet_part = jnp.sum(fresh.astype(jnp.int32), axis=0)[None]
        rows = lax.broadcasted_iota(jnp.int32, st.shape, 0) + i * r_blk
        fobs_part = jnp.min(jnp.where(fresh, rows, n), axis=0)[None]

    @pl.when(i == 0)
    def _():
        cnt_out[...] = part
        if detect_stats:
            ndet_out[...] = ndet_part
            fobs_out[...] = fobs_part
        else:
            ndet_out[...] = jnp.zeros_like(ndet_out)
            fobs_out[...] = jnp.zeros_like(fobs_out)

    @pl.when(i > 0)
    def _():
        cnt_out[...] = cnt_out[...] + part
        if detect_stats:
            ndet_out[...] = ndet_out[...] + ndet_part
            fobs_out[...] = jnp.minimum(fobs_out[...], fobs_part)


def _stripe_kernel(
    n: int, n_fanout: int, r_blk: int, member: int, unknown: int,
    age_clamp: int, failed: int, detect_stats: bool,
):
    def kernel(
        edges_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref, sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        stripe, best_scratch, hb_vmem, age_vmem, status_vmem, stripe_sem, row_sems,
    ):
        # Grid (nc, n // r_blk): column block j OUTER, receiver block i
        # inner, so one stripe load serves every receiver block.
        j = pl.program_id(0)
        i = pl.program_id(1)

        # stripe DMA: the whole view column block [N, cs, LANE] HBM -> VMEM,
        # once per j (i == 0).  Every receiver's F-way gather then reads
        # VMEM — total HBM traffic for the view drops from F x N^2 to N^2.
        @pl.when(i == 0)
        def _():
            pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem).start()

        # 4-D lane refs with dynamic row-block slices — the layout that
        # WOULD let output lanes alias the inputs (each block is read
        # exactly once, before its own step writes it; cross-row data
        # comes only from the separate view stripe).  This kernel's sizes
        # fit HBM comfortably and aliasing measured ~2 ms/round slower
        # (Mosaic pipelines aliased writes conservatively), so only the
        # capacity-bound gather kernel passes input_output_aliases.
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        @pl.when(i == 0)
        def _():
            pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem).wait()

        # Phase 1 — F-way max per receiver row, straight from the resident
        # stripe (vector loads, no per-row DMA descriptors — the gather
        # kernel's limiter).
        def body(r, _):
            acc = stripe[edges_ref[r, 0]].astype(jnp.int32)
            for f in range(1, n_fanout):
                acc = jnp.maximum(acc, stripe[edges_ref[r, f]].astype(jnp.int32))
            best_scratch[r] = acc
            return 0

        lax.fori_loop(0, r_blk, body, 0, unroll=False)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.
        # receiver liveness, replicated across lanes by the wrapper so it
        # broadcasts over the subject dims without sublane shuffles
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
        )

    return kernel


# The stripe kernel holds one full view column block [N, cs, LANE] resident
# in VMEM.  int8's native tile is (32, 128), so cs must be a multiple of 32
# (else Mosaic pads each leading index to a full tile, 4x-ing the stripe);
# the v5e's 128 MB VMEM then bounds N x 4096 bytes — N <= 16,384 with
# headroom for the receiver-lane blocks.  Bigger problems use the gather
# kernel.
STRIPE_BLOCK_C = 4096
STRIPE_MAX_BYTES = 72 * 1024 * 1024


def stripe_supported(n: int, fanout: int, n_cols: int | None = None) -> bool:
    if n_cols is None:
        n_cols = n
    return (
        supported(n, fanout, n_cols)
        and n_cols % STRIPE_BLOCK_C == 0
        and n * STRIPE_BLOCK_C <= STRIPE_MAX_BYTES
    )


# Stripe widths the resident-round kernel accepts.  Narrower stripes trade
# per-element gather efficiency for VMEM: at c_blk=1024 the resident view
# stripe is N x 1024 bytes, which is what admits N=65,536 on one chip
# (64 MB stripe) — measured unpadded (Mosaic packs (8, 128) int8 scratch
# without rounding the sublane dim up to the (32, 128) tile).
RR_BLOCK_CS = (1024, 2048, 4096)


def rr_supported(n: int, fanout: int, c_blk: int,
                 n_cols: int | None = None) -> bool:
    if n_cols is None:
        n_cols = n
    return (
        supported(n, fanout, n_cols)
        and c_blk in RR_BLOCK_CS
        and n_cols % c_blk == 0
        and n * c_blk <= STRIPE_MAX_BYTES
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "interpret",
    ),
)
def stripe_merge_update_blocked(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    interpret: bool = False,
) -> tuple[jax.Array, ...]:
    """Gossip merge + membership update + age advance, stripe-resident.

    Same contract as :func:`fused_merge_update_blocked` (int8 view in the
    ``STRIPE_BLOCK_C`` blocked layout), different memory strategy: instead
    of per-receiver-row DMA gathers (F x N^2 HBM bytes, bound by DMA
    descriptor issue), each view column block is loaded into VMEM once and
    the F-way max reads it with vector loads — HBM view traffic drops F-fold
    and the descriptor count drops from F x N per round to ~nc.

    Returns (hb, age, status, member_cnt, n_det, first_obs): ``member_cnt``
    int32 [nc, cs, LANE] counts, per subject, the live observers whose
    updated list holds the entry (self INCLUDED — callers subtract the
    diagonal); ``n_det``/``first_obs`` carry this round's detection stats
    when ``detect_stats`` (see :func:`_epilogue_and_count`), zeros
    otherwise.
    """
    n, nc, cs, _ = view.shape
    fanout = edges.shape[1]
    if not stripe_supported(n, fanout, nc * cs * LANE):
        raise ValueError(
            f"stripe merge needs lane-aligned N, cs*LANE == {STRIPE_BLOCK_C} "
            f"and N*{STRIPE_BLOCK_C} <= {STRIPE_MAX_BYTES} B of VMEM "
            f"(N={n}, blocked cols={cs * LANE}); use the gather kernel"
        )
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2

    # dead receivers merge nothing: remap their edges to self (their own view
    # row is all -1), as in the gather kernel
    self_idx = jnp.arange(n, dtype=edges.dtype)[:, None]
    edges = jnp.where((alive != 0)[:, None], edges, self_idx)
    # liveness replicated across the lane dim for clean vector broadcast
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))

    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _stripe_kernel(n, fanout, r_blk, member, unknown, age_clamp,
                       failed, detect_stats),
        grid=(nc, n // r_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, cs, LANE), view.dtype),
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(edges, view, hb, age, status, alive_lanes, shift_a, shift_b)
    return tuple(out)


# rows per in-VMEM window-max chunk (arc kernel): each ping-pong buffer is
# (ARC_CHUNK + F - 1, cs, LANE) bfloat16 — ~8.5 MB at cs=32.  bf16 because
# v5e Mosaic has no narrow-int vector max (arith.maxsi on i8 fails to
# legalize); bf16 max is native and exact for the int8 view range.
ARC_CHUNK = 1024


def _windowmax_inplace(stripe, bufa, bufb, halo, fanout: int, nchunks: int):
    """Windowed row max, in place over the resident stripe.

    W[r] = max over view rows r..r+F-1 (mod N).  Shift-doubling to the
    largest power of two <= F, then one overlapped combine — O(log F)
    passes instead of F, amortized over every receiver reading the stripe.
    """
    halo[...] = stripe[0:fanout - 1]  # pre-overwrite wrap rows
    # largest power of two <= fanout
    p = 1 << (fanout.bit_length() - 1)

    def chunk_body(c, _):
        base = c * ARC_CHUNK
        ext = ARC_CHUNK + fanout - 1
        bufa[0:ARC_CHUNK] = stripe[pl.ds(base, ARC_CHUNK)].astype(bufa.dtype)

        @pl.when(c == nchunks - 1)
        def _():
            bufa[ARC_CHUNK:ext] = halo[...].astype(bufa.dtype)

        @pl.when(c < nchunks - 1)
        def _():
            bufa[ARC_CHUNK:ext] = stripe[
                pl.ds(base + ARC_CHUNK, fanout - 1)
            ].astype(bufa.dtype)

        # shift-doubling ping-pong: after the step with shift s,
        # the buffer holds window maxes of length 2s
        src, dst = bufa, bufb
        length = ext
        s = 1
        while s < p:
            dst[0:length - s] = jnp.maximum(
                src[0:length - s], src[pl.ds(s, length - s)]
            )
            src, dst = dst, src
            length -= s
            s *= 2
        # combine two p-windows into the F-window (overlap is fine
        # for max): W[r] = max(D_p[r], D_p[r + F - p])
        if p == fanout:
            w = src[0:ARC_CHUNK]
        else:
            w = jnp.maximum(
                src[0:ARC_CHUNK],
                src[pl.ds(fanout - p, ARC_CHUNK)],
            )
        stripe[pl.ds(base, ARC_CHUNK)] = w.astype(stripe.dtype)
        return 0

    lax.fori_loop(0, nchunks, chunk_body, 0, unroll=False)


def _arc_update_kernel(
    n: int, fanout: int, r_blk: int, member: int, unknown: int,
    age_clamp: int, failed: int, detect_stats: bool,
):
    nchunks = n // ARC_CHUNK

    def kernel(
        bases_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref,
        sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        stripe, bufa, bufb, halo, best_scratch,
        hb_vmem, age_vmem, status_vmem, stripe_sem, row_sems,
    ):
        j = pl.program_id(0)
        i = pl.program_id(1)

        # 4-D lane refs with dynamic row-block slices — aliasable layout,
        # deliberately NOT aliased (see the stripe kernel's comment: only
        # the capacity-bound gather kernel trades the ~2 ms/round aliasing
        # cost for the three reclaimed lane buffers)
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        @pl.when(i == 0)
        def _():
            cp = pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem)
            cp.start()
            cp.wait()
            _windowmax_inplace(stripe, bufa, bufb, halo, fanout, nchunks)

        # Phase 1 — one widened vector load per receiver row (the windowed
        # max did the F-way work once per stripe, O(log F) instead of F)
        def body(r, _):
            best_scratch[r] = stripe[bases_ref[r, 0]].astype(jnp.int32)
            return 0

        lax.fori_loop(0, r_blk, body, 0, unroll=False)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.  The
        # receiver-liveness gate is load-bearing here: arc bases cannot be
        # remapped to a "blank" row (every window-maxed stripe row holds
        # real values), so dead receivers are masked in the epilogue.
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
        )

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "fanout", "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "interpret",
    ),
)
def arc_merge_update_blocked(
    view: jax.Array,
    bases: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    fanout: int,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    interpret: bool = False,
) -> tuple[jax.Array, ...]:
    """Arc merge + membership update + age advance + member count, fused.

    The ``random_arc`` production kernel: combines the O(log F) windowed
    row-max (:func:`_windowmax_inplace` — senders are F consecutive rows)
    with :func:`stripe_merge_update_blocked`'s block-wide epilogue, so the hb/age/status lanes are read and written
    exactly once per round AND the per-receiver merge work is one vector
    load instead of an F-way max — the cheapest per-element round this
    module has.  Same contract as ``stripe_merge_update_blocked`` except
    senders come as arc ``bases`` int32 [N].

    (An in-kernel-tick variant of this kernel was measured and rejected:
    Mosaic's widened elementwise ran ~3x slower than the XLA tick pass it
    replaced — see BASELINE.md's round-profile notes.)
    """
    n, nc, cs, _ = view.shape
    if not stripe_supported(n, fanout, nc * cs * LANE):
        raise ValueError(
            f"arc merge update needs lane-aligned N, cs*LANE == "
            f"{STRIPE_BLOCK_C} and N*{STRIPE_BLOCK_C} <= {STRIPE_MAX_BYTES} B "
            f"(N={n}, blocked cols={cs * LANE}); use the XLA path"
        )
    if n % ARC_CHUNK:
        raise ValueError(f"arc merge update needs N % {ARC_CHUNK} == 0, got {n}")
    if not 1 < fanout <= ARC_CHUNK:
        raise ValueError(f"arc fanout must be in (1, {ARC_CHUNK}], got {fanout}")
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))
    ext = ARC_CHUNK + fanout - 1
    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _arc_update_kernel(n, fanout, r_blk, member, unknown, age_clamp,
                           failed, detect_stats),
        grid=(nc, n // r_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, 1), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, cs, LANE), view.dtype),
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((fanout - 1, cs, LANE), view.dtype),
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(bases.reshape(n, 1), view, hb, age, status, alive_lanes,
      shift_a, shift_b)
    return tuple(out)


# ---------------------------------------------------------------------------
# The resident-round kernel ("rr"): tick + gossip-view build + merge +
# membership update + every per-round reduction in ONE pallas call.
#
# Round 3 measured Mosaic's widened elementwise ~3x behind XLA and kept the
# heartbeat tick in XLA.  Round 4 re-measured and found the 3x was NOT
# Mosaic's VPU: the same epilogue ops cost ~0.75 ms via BlockSpec-pipelined
# blocks vs ~3.5 ms inside the manual-DMA stripe kernel, whose per-step
# waits serialize DMA latency against compute 512 times per round.  With
# lane blocks fetched by Mosaic's own pipeline the whole round fits in one
# kernel at XLA-class elementwise speed, and the separate XLA passes (tick
# fusion, view fusion, member-count reduction — together ~5.6 ms/round at
# N=16k) disappear:
#
#   per stripe j (grid j outer, i inner):
#     i == 0: build the GOSSIP VIEW stripe in VMEM from the raw hb/status/
#             age stripes (chunked double-buffered DMAs), recomputing the
#             heartbeat tick elementwise — the view never exists in HBM
#             (VERDICT r3 task 1: the [N, N] view materialization is gone)
#     every i: gather the F-way max from the resident view stripe, then
#             recompute the tick on the receiver block (BlockSpec-fetched)
#             and run the merge epilogue + reductions, writing each lane
#             exactly once
#
# Per-round HBM traffic drops from ~17 N^2 bytes (tick fusion 6 + view
# fusion 3 + kernel 7 + count pass 1) to ~6 N^2: the kernel's wire is TWO
# byte lanes per entry — hb int8 plus age(6b)|status(2b) PACKED into one
# biased byte (AGE_CLAMP = 63 makes age fit; config rejects deeper
# thresholds) — so the view build reads 2, the receiver sweep reads 2 and
# writes 2.  The round is ambient-bandwidth-bound (the shared chip
# delivers a fraction of its spec sheet), so a byte saved is time saved
# 1:1; the unpack (one add, one shift, one mask) rides the VPU's idle
# lanes.  The tick is recomputed twice per element (view build + receiver
# sweep) — duplicated VPU, two fewer HBM round trips, the same trade
# _round_core_fused makes in XLA (a tick-stub experiment measured the
# duplicated compute at ~0 ms: it hides entirely under the DMA waits).
#
# All arithmetic is WIDENED int32 over the packed int8 lanes, with
# per-subject int32 vectors (sa/sb/g) carrying the rebase state — the
# unclipped formulation the narrow-dtype XLA paths are proven equivalent
# to (core/rounds.py _membership_update / _gossip_view / _tick).
# ---------------------------------------------------------------------------

# rows per view-build chunk: int32 temporaries over a (chunk, cs, LANE)
# block are what bounds VMEM here (16 MB per temporary at 1024 rows)
RR_CHUNK = 256


def pack_age_status(age: jax.Array, status: jax.Array) -> jax.Array:
    """age(6b)|status(2b) into one biased int8: (age << 2 | status) - 128.

    The resident-round kernel's lane format — valid for age <= AGE_CLAMP
    (63) and status in {0, 1, 2}.  Biasing keeps the packed value inside
    signed int8 so the lane shares the hb lanes' dtype and tiling.
    """
    p = (age.astype(jnp.int32) << 2) | status.astype(jnp.int32)
    return (p - 128).astype(jnp.int8)


def unpack_age_status(asl: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Inverse of :func:`pack_age_status`; returns int32 (age, status)."""
    p = asl.astype(jnp.int32) + 128
    return p >> 2, p & 3


def _rr_tick_block(hb, age, st, act_r, ref_r, eye, g, hb_min, t_fail,
                   t_cooldown, member, failed, unknown):
    """The heartbeat tick on a widened int32 block (core/rounds.py _tick,
    lean crash-only path: fresh_cooldown on, no remove broadcast).

    Order matters and mirrors _tick exactly: small-group refresh, diagonal
    bump (sentinel-sticky), detection over the POST-refresh age, fresh
    cooldown stamp, then cooldown expiry over the post-detection lanes.
    """
    refresh = ref_r & (st == member)
    age = jnp.where(refresh, 0, age)
    bump = eye & act_r & (st == member) & (hb != hb_min)
    hb = hb + bump.astype(jnp.int32)
    age = jnp.where(bump, 0, age)
    past = (hb > g) & (hb != hb_min)
    fail = act_r & (st == member) & (~eye) & past & (age > t_fail)
    st = jnp.where(fail, failed, st)
    age = jnp.where(fail, 0, age)
    expire = (st == failed) & (age > t_cooldown)
    st = jnp.where(expire, unknown, st)
    return hb, age, st, fail


def _rr_kernel(
    n: int, n_fanout: int, r_blk: int, cs: int, chunk: int,
    member: int, unknown: int, failed: int, age_clamp: int,
    window: int, t_fail: int, t_cooldown: int, hb_min: int,
    arc: bool = False,
):
    nchunks = n // chunk
    nblocks = n // r_blk

    def kernel(
        edges_ref, flags_all,
        sa_ref, sb_ref, g_ref, hb_any, as_any,
        hb_out, as_out, cnt_out, ndet_out, fobs_out, rcnt_out,
        stripe, best_scratch, vbuf, vsems, rbuf, rsems,
        *arc_scratch,
    ):
        # The raw lanes arrive ONCE, in ANY memory space; every VMEM
        # crossing is an explicit software-pipelined DMA — BlockSpec-fetched
        # lane inputs measured ~3 ms/round slower here (Mosaic serializes
        # its own block copies against the kernel's manual DMAs, the same
        # effect the fused gather kernel hit in round 3), and passing the
        # lanes twice (BlockSpec + ANY) made XLA materialize three 0.8 ms
        # defensive copies per round.  The view-build chunks (vbuf) and the
        # receiver blocks (rbuf) ping-pong through SEPARATE buffers so the
        # first receiver block's DMA can be issued before the stripe's view
        # build and hide entirely under it (a shared buffer forced an
        # unpipelined reload after every view build).
        j = pl.program_id(0)
        i = pl.program_id(1)
        sa = sa_ref[0][None].astype(jnp.int32)
        sb = sb_ref[0][None].astype(jnp.int32)
        g = g_ref[0][None].astype(jnp.int32)

        def issue_into(buf, sems, blk_rows, rows_per, slot):
            rows = pl.ds(blk_rows * rows_per, rows_per)
            for li, lane in enumerate((hb_any, as_any)):
                pltpu.make_async_copy(
                    lane.at[j, rows], buf.at[slot, li], sems.at[slot, li]
                ).start()

        def wait_on(buf, sems, rows_per, slot):
            for li, lane in enumerate((hb_any, as_any)):
                pltpu.make_async_copy(
                    lane.at[j, pl.ds(0, rows_per)], buf.at[slot, li],
                    sems.at[slot, li],
                ).wait()

        issue = functools.partial(issue_into, vbuf, vsems)
        wait = functools.partial(wait_on, vbuf, vsems)
        rissue = functools.partial(issue_into, rbuf, rsems)
        rwait = functools.partial(wait_on, rbuf, rsems)

        # --- i == 0: build this stripe's gossip view in VMEM ------------
        # chunked double-buffered DMAs over the raw lanes; the tick is
        # recomputed on each chunk so the view reflects post-tick state.
        @pl.when(i == 0)
        def _():
            # this stripe's first receiver block rides under the view build
            rissue(0, r_blk, 0)
            issue(0, chunk, 0)

            def body(c, _):
                slot = lax.rem(c, 2)

                @pl.when(c + 1 < nchunks)
                def _():
                    issue(c + 1, chunk, lax.rem(c + 1, 2))

                wait(chunk, slot)
                hb = vbuf[slot, 0].astype(jnp.int32)
                p = vbuf[slot, 1].astype(jnp.int32) + 128
                age, st = p >> 2, p & 3
                fl = flags_all[pl.ds(c * chunk, chunk)].astype(jnp.int32)
                fl = fl.reshape(chunk, 1, LANE)
                act_r = (fl & 1) != 0
                ref_r = (fl & 2) != 0
                row_g = (lax.broadcasted_iota(jnp.int32, hb.shape, 0)
                         + c * chunk)
                col_g = (lax.broadcasted_iota(jnp.int32, hb.shape, 1) * LANE
                         + lax.broadcasted_iota(jnp.int32, hb.shape, 2)
                         + j * cs * LANE)
                eye = row_g == col_g
                hb, age, st, _fail = _rr_tick_block(
                    hb, age, st, act_r, ref_r, eye, g, hb_min,
                    t_fail, t_cooldown, member, failed, unknown,
                )
                # the gossip view: active senders' MEMBER entries within
                # the rebase window (core/rounds.py _gossip_view, int32
                # formulation); absent entries are -1
                rel = hb - sa
                goss = (
                    (st == member) & act_r
                    & (rel >= 0) & (rel <= window) & (hb != hb_min)
                )
                stripe[pl.ds(c * chunk, chunk)] = jnp.where(
                    goss, rel, -1
                ).astype(stripe.dtype)
                return 0

            lax.fori_loop(0, nchunks, body, 0, unroll=False)
            if arc:
                # arc senders are F consecutive rows: replace the stripe
                # with its windowed row-max once, so the per-receiver
                # merge below is ONE vector load instead of an F-way
                # scalar-issued gather (O(log F) vectorized passes,
                # amortized over every receiver)
                bufa, bufb, halo = arc_scratch
                _windowmax_inplace(stripe, bufa, bufb, halo, n_fanout,
                                   n // ARC_CHUNK)

        # prefetch the NEXT receiver block while this one is gathered and
        # merged; the last block of a stripe prefetches nothing (the next
        # stripe's i == 0 step issues its own block 0 under the view build)
        slot = lax.rem(i, 2)

        @pl.when(i + 1 < nblocks)
        def _():
            rissue(i + 1, r_blk, lax.rem(i + 1, 2))

        # --- every i: merge rows from the resident stripe ---------------
        # best accumulates widened (no narrow-int vector max on v5e) but
        # stores int8 — view values fit, and the narrower scratch frees
        # VMEM for bigger row blocks
        if arc:
            def gather(r, _):
                best_scratch[r] = stripe[edges_ref[r, 0]]
                return 0
        else:
            def gather(r, _):
                acc = stripe[edges_ref[r, 0]].astype(jnp.int32)
                for f in range(1, n_fanout):
                    acc = jnp.maximum(acc,
                                      stripe[edges_ref[r, f]].astype(jnp.int32))
                best_scratch[r] = acc.astype(best_scratch.dtype)
                return 0

        lax.fori_loop(0, r_blk, gather, 0, unroll=False)
        rwait(r_blk, slot)

        # --- tick recompute + merge epilogue on the receiver block ------
        hb = rbuf[slot, 0].astype(jnp.int32)
        p = rbuf[slot, 1].astype(jnp.int32) + 128
        age, st = p >> 2, p & 3
        fl = flags_all[pl.ds(i * r_blk, r_blk)].astype(jnp.int32)
        fl = fl.reshape(r_blk, 1, LANE)
        act_r = (fl & 1) != 0
        ref_r = (fl & 2) != 0
        recv = (fl & 4) != 0
        row_g = lax.broadcasted_iota(jnp.int32, hb.shape, 0) + i * r_blk
        col_g = (lax.broadcasted_iota(jnp.int32, hb.shape, 1) * LANE
                 + lax.broadcasted_iota(jnp.int32, hb.shape, 2)
                 + j * cs * LANE)
        eye = row_g == col_g
        hb, age, st, fail = _rr_tick_block(
            hb, age, st, act_r, ref_r, eye, g, hb_min,
            t_fail, t_cooldown, member, failed, unknown,
        )

        best = best_scratch[...].astype(jnp.int32)
        any_m = best >= 0
        advance = recv & any_m & (st == member) & (best > hb - sa)
        add = recv & any_m & (st == unknown)
        upd = advance | add
        new_hb = jnp.clip(jnp.where(upd, best + (sa - sb), hb - sb),
                          hb_min, -hb_min - 1)
        hb_out[0] = new_hb.astype(hb_out.dtype)
        new_age = jnp.minimum(jnp.where(upd, 0, age) + 1, age_clamp)
        st_new = jnp.where(add, member, st)
        as_out[0] = (((new_age << 2) | st_new) - 128).astype(as_out.dtype)

        # per-subject reductions, accumulated across consecutive i steps
        cnt_part = jnp.sum((recv & (st_new == member)).astype(jnp.int32),
                           axis=0)[None]
        ndet_part = jnp.sum(fail.astype(jnp.int32), axis=0)[None]
        fobs_part = jnp.min(jnp.where(fail, row_g, n), axis=0)[None]
        # per-RECEIVER member count (next round's group-size input),
        # indexed (j, i): every block written exactly once.  The sublane
        # dim is padded to 8 (Mosaic's minimum tile) — consumers read
        # row 0 only
        # reductions stay >= 2-D throughout: a rank-1 intermediate here
        # crashes the TPU lowering (layout.h implicit_dim check)
        rc = jnp.sum((st_new == member).astype(jnp.int32), axis=2)
        rc = jnp.sum(rc, axis=1, keepdims=True)
        # int16 output: a per-stripe partial count is <= cs*LANE <= 4096.
        # At the N=65,536 frontier this buffer is [N, nc*LANE] — int16
        # halves a gigabyte-class side output
        rcnt_out[...] = jnp.broadcast_to(
            rc, (rc.shape[0], LANE)
        ).astype(rcnt_out.dtype)

        @pl.when(i == 0)
        def _():
            cnt_out[...] = cnt_part
            ndet_out[...] = ndet_part
            fobs_out[...] = fobs_part

        @pl.when(i > 0)
        def _():
            cnt_out[...] = cnt_out[...] + cnt_part
            ndet_out[...] = ndet_out[...] + ndet_part
            fobs_out[...] = jnp.minimum(fobs_out[...], fobs_part)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "fanout", "member", "unknown", "failed", "age_clamp", "window",
        "t_fail", "t_cooldown", "block_r", "chunk", "interpret",
    ),
)
def resident_round_blocked(
    edges: jax.Array,
    hb: jax.Array,
    asl: jax.Array,
    flags: jax.Array,
    sa: jax.Array,
    sb: jax.Array,
    g: jax.Array,
    *,
    fanout: int | None = None,
    member: int,
    unknown: int,
    failed: int,
    age_clamp: int,
    window: int,
    t_fail: int,
    t_cooldown: int,
    block_r: int = _FUSED_BLOCK_R,
    chunk: int = RR_CHUNK,
    interpret: bool = False,
) -> tuple[jax.Array, ...]:
    """One whole gossip round (lean crash-only fault model) in one kernel.

    Contract (two int8 lanes per entry, STRIPE-MAJOR ``[nc, N, cs, LANE]``
    layout — ``blocked_shape`` transposed so each stripe's rows are
    contiguous — PRE-tick):

    * ``hb`` int8; ``asl`` the :func:`pack_age_status` byte — the kernel's
      whole HBM wire is 2 B/entry, which is what bounds the round on the
      bandwidth-shared chip.
    * ``edges`` int32 [N, F] in-edge sender ids (NOT remapped for dead
      receivers — the epilogue gates on the alive bit instead).  For the
      ``random_arc`` topology pass arc BASES int32 [N] plus ``fanout=F``:
      the kernel then window-maxes the view stripe once (O(log F)
      vectorized passes) and the per-receiver merge is a single load.
    * ``flags`` int8 [N, LANE]: bit 0 = active sender this round
      (alive & group >= min_group), bit 1 = small-group refresher,
      bit 2 = alive.  Derived per round from the carried member counts.
    * ``sa``/``sb``/``g`` int32 per-subject vectors in the blocked
      [nc, cs, LANE] form: view shift (view_base - hb_base), store shift
      (new_base - hb_base) and grace threshold (hb_grace - hb_base).
    * statics: the protocol constants; ``window`` is the int8 rebase window.

    Returns (hb', asl', member_cnt [nc,cs,LANE], n_det, first_obs,
    recv_cnt [N, nc*LANE] — per-receiver per-stripe partial member counts,
    lane-replicated: ``recv_cnt.reshape(n, nc, LANE)[:, :, 0].sum(1)`` is
    the post-merge membership-list size of each receiver, which feeds the
    NEXT round's active/refresher split (carried by the scan — the
    member-count XLA pass is gone too).
    """
    nc, n, cs, _ = hb.shape
    arc = fanout is not None
    if not arc:
        fanout = edges.shape[1]
    elif edges.ndim == 1:
        edges = edges.reshape(n, 1)
    if hb.dtype != jnp.int8:
        raise ValueError("resident round kernel requires int8 lanes")
    if arc and n % ARC_CHUNK:
        raise ValueError(f"arc resident round needs N % {ARC_CHUNK} == 0")
    if not rr_supported(n, fanout, cs * LANE, nc * cs * LANE):
        raise ValueError(
            f"resident round kernel needs lane-aligned N, cs*LANE in "
            f"{RR_BLOCK_CS} and N*cs*LANE <= {STRIPE_MAX_BYTES} B "
            f"(N={n}, blocked cols={cs * LANE}); use the stripe/XLA path"
        )
    ch = min(chunk, n)
    while n % ch:
        ch //= 2
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    hb_min = int(jnp.iinfo(jnp.int8).min)

    # stripe-major lane layout [nc, N, cs, LANE]: a stripe's rows are one
    # contiguous region, so every lane DMA block and output block is a
    # single contiguous transfer (the receiver-major layout's 4 KB-strided
    # rows bounded the kernel at ~220 GB/s effective)
    lane_blk = pl.BlockSpec((1, r_blk, cs, LANE), lambda j, i: (j, i, 0, 0),
                            memory_space=pltpu.VMEM)
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    ew = 1 if arc else fanout
    ext = ARC_CHUNK + fanout - 1
    arc_scratch = [
        pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
        pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
        pltpu.VMEM((fanout - 1, cs, LANE), jnp.int8),
    ] if arc else []
    out = pl.pallas_call(
        _rr_kernel(n, fanout, r_blk, cs, ch, member, unknown, failed,
                   age_clamp, window, t_fail, t_cooldown, hb_min, arc=arc),
        grid=(nc, n // r_blk),
        # in-place lane update: safe because every [row-block, stripe]
        # region's reads (the i==0 view-build chunk pass and the one-step-
        # early receiver prefetch) strictly precede its own step's output
        # write, and stripes never overlap.  Kills the defensive copies XLA
        # otherwise inserts for custom-call operands that are also scan
        # carries (~2.5 ms/round) and drops two [N, N] lane buffers from
        # peak HBM
        input_output_aliases={5: 0, 6: 1},
        in_specs=[
            pl.BlockSpec((r_blk, ew), lambda j, i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n, LANE), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),   # flags (resident)
            subj_spec,  # sa
            subj_spec,  # sb
            subj_spec,  # g
            pl.BlockSpec(memory_space=pl.ANY),   # hb       (manual DMAs)
            pl.BlockSpec(memory_space=pl.ANY),   # age|status packed
        ],
        out_specs=[
            lane_blk, lane_blk,
            subj_spec, subj_spec, subj_spec,
            pl.BlockSpec((r_blk, LANE), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nc, n, cs, LANE), jnp.int8),
            jax.ShapeDtypeStruct((nc, n, cs, LANE), jnp.int8),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((n, nc * LANE), jnp.int16),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, cs, LANE), jnp.int8),          # view stripe
            pltpu.VMEM((r_blk, cs, LANE), jnp.int8),      # best (narrow)
            # separate ping-pongs: view-build chunks / receiver blocks
            pltpu.VMEM((2, 2, ch, cs, LANE), jnp.int8),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((2, 2, r_blk, cs, LANE), jnp.int8),
            pltpu.SemaphoreType.DMA((2, 2)),
        ] + arc_scratch,
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=120 * 1024 * 1024),
        interpret=interpret,
    )(edges, flags, sa, sb, g, hb, asl)
    return tuple(out)


def fanout_max_merge_xla(view: jax.Array, edges: jax.Array) -> jax.Array:
    """Reference XLA formulation of the same op (gather + running max).

    Used on CPU, for unsupported shapes, and as the oracle the kernel is
    tested against.
    """
    def body(f, best):
        k = lax.dynamic_index_in_dim(edges, f, axis=1, keepdims=False)
        return jnp.maximum(best, view[k, :])

    init = jnp.full(view.shape, -1, dtype=view.dtype)
    return lax.fori_loop(0, edges.shape[1], body, init)


def arc_window_max_xla(view: jax.Array, bases: jax.Array, fanout: int) -> jax.Array:
    """XLA formulation of the arc merge: shift-doubling windowed row-max
    plus ONE row gather — F-independent traffic, identical results to
    ``fanout_max_merge_xla`` over the expanded arc edges.

    The workhorse for arc topologies off the TPU fast path (CPU runs, the
    sharded virtual-mesh correctness runs at 100k-class N, where the F-way
    gather's F x N^2 bytes are prohibitive).  Works on 2-D [N, C] and
    blocked [N, nc, cs, LANE] views alike (axis 0 is always the row).
    """
    n = view.shape[0]
    ext = jnp.concatenate([view, view[: fanout - 1]], axis=0)  # row wrap
    p = 1 << (fanout.bit_length() - 1)  # largest power of two <= fanout
    length = n + fanout - 1
    s = 1
    while s < p:
        # after the step with shift s, ext[r] = max over rows r..r+2s-1
        ext = jnp.maximum(ext[: length - s], ext[s:length])
        length -= s
        s *= 2
    if p == fanout:
        w = ext[:n]
    else:
        # two overlapping p-windows cover the F-window exactly (max is
        # idempotent): W[r] = max(D_p[r], D_p[r + F - p])
        w = jnp.maximum(ext[:n], ext[fanout - p:fanout - p + n])
    return w[bases]
