"""Pallas TPU kernel for the gossip fanout max-merge — the hot op.

Per round, every receiver i merges the membership rows of its ``F`` fanout
peers with an elementwise max (the tensorized MergeMemberList, reference:
slave/slave.go:414-440):

    out[i, :] = max_f view[edges[i, f], :]

where ``view`` is the gossip view (heartbeat if the entry is gossipable,
-1 otherwise).  This is a bandwidth problem: F·N² reads with a
data-dependent row gather.  XLA's gather lowering reaches ~140 GB/s on a
v5e chip; this kernel sustains ~4-6x that by:

  * keeping the whole ``view`` in HBM and gathering rows with explicit
    async DMAs (``pltpu.make_async_copy``), ``slots``-deep double-buffered
    so the VPU max never waits on memory;
  * reshaping to ``[N, N/C, C/128, 128]`` so each gathered unit is a
    tile-aligned ``(C/128, 128)`` block (Mosaic rejects single-row slices
    of an ``(8,128)``-tiled HBM buffer); large ``block_c`` keeps the DMA
    count low — descriptor issue, not bytes, is the limiter once the view
    is narrow (core/rounds.py rebases heartbeats into ``config.view_dtype``,
    int16 or int8, cutting the gather's bytes 2-4x vs int32);
  * accumulating the F-way max entirely in VMEM — the output is written
    exactly once, so total traffic is the information floor
    (F reads + 1 write per state element).

The kernel is semantically a pure function; ``interpret=True`` runs it on
CPU for tests (tests/test_merge_pallas.py pins it against the XLA
formulation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _kernel(n_fanout: int, r_blk: int, slots: int):
    def kernel(edges_ref, view_ref, out_ref, scratch, sems):
        # edges_ref: [r_blk, F] int32 in SMEM (this row-block's in-edges)
        # view_ref:  [N, N/C, C/128, 128] in HBM (never copied wholesale)
        # out_ref:   [r_blk, 1, C/128, 128] in VMEM
        # scratch:   [slots, F, C/128, 128] VMEM; sems: [slots, F]
        j = pl.program_id(1)

        def issue(r, slot):
            for f in range(n_fanout):
                pltpu.make_async_copy(
                    view_ref.at[edges_ref[r, f], j],
                    scratch.at[slot, f],
                    sems.at[slot, f],
                ).start()

        def wait(slot):
            for f in range(n_fanout):
                # src is irrelevant for wait(); shapes must match the start
                pltpu.make_async_copy(
                    view_ref.at[0, j], scratch.at[slot, f], sems.at[slot, f]
                ).wait()

        for s in range(slots - 1):
            issue(s, s)

        def body(r, _):
            slot = lax.rem(r, slots)

            @pl.when(r + slots - 1 < r_blk)
            def _():
                issue(r + slots - 1, lax.rem(r + slots - 1, slots))

            wait(slot)
            # v5e Mosaic can't compare/max narrow int vectors; widen to int32
            # for the VPU max and narrow on the way out.  The DMAs above and
            # the output store still move the narrow dtype — the HBM traffic,
            # which is what this kernel is bound by, stays at the view's
            # 1-2 bytes/elem.
            dtype = out_ref.dtype
            acc = scratch[slot, 0].astype(jnp.int32)
            for f in range(1, n_fanout):
                acc = jnp.maximum(acc, scratch[slot, f].astype(jnp.int32))
            out_ref[r, 0] = acc.astype(dtype)
            return 0

        lax.fori_loop(0, r_blk, body, 0, unroll=False)

    return kernel


def supported(n: int, fanout: int) -> bool:
    """Whether the kernel's tiling constraints admit this problem size."""
    return n % LANE == 0 and n >= LANE and fanout >= 1


@functools.partial(
    jax.jit, static_argnames=("block_r", "block_c", "slots", "interpret")
)
def fanout_max_merge(
    view: jax.Array,
    edges: jax.Array,
    *,
    block_r: int = 128,
    block_c: int = 8192,
    slots: int = 4,
    interpret: bool = False,
) -> jax.Array:
    """out[i, :] = max over f of view[edges[i, f], :].

    ``view``: [N, N], any fixed-width integer dtype — production passes the
    rebased view built in core/rounds.py (``config.view_dtype``: int16 or
    int8, so 1-2 bytes/elem of DMA traffic); int32 works too.  Use -1 for
    "absent" lanes so the max ignores them.
    ``edges``: int32 [N, F] in-edge sender ids.  Defaults are the tuned v5e
    values; blocks shrink automatically for small N.
    """
    n = view.shape[0]
    fanout = edges.shape[1]
    if view.shape != (n, n):
        raise ValueError(f"view must be square [N, N], got {view.shape}")
    if not supported(n, fanout):
        raise ValueError(
            f"pallas merge needs N % {LANE} == 0 and fanout >= 1 "
            f"(N={n}, fanout={fanout}); use the XLA path"
        )
    # blocks must tile N exactly; halving bottoms out at LANE, which always
    # divides a lane-aligned N
    c_blk = min(block_c, n)
    while n % c_blk:
        c_blk //= 2
    r_blk = min(block_r, n)
    while n % r_blk:
        r_blk //= 2
    n_slots = max(2, min(slots, r_blk))
    cs = c_blk // LANE

    view4 = view.reshape(n, n // c_blk, cs, LANE)
    out4 = pl.pallas_call(
        _kernel(fanout, r_blk, n_slots),
        grid=(n // r_blk, n // c_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda i, j: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (r_blk, 1, cs, LANE),
            lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, n // c_blk, cs, LANE), view.dtype),
        scratch_shapes=[
            pltpu.VMEM((n_slots, fanout, cs, LANE), view.dtype),
            pltpu.SemaphoreType.DMA((n_slots, fanout)),
        ],
        interpret=interpret,
    )(edges, view4)
    return out4.reshape(n, n)


def fanout_max_merge_xla(view: jax.Array, edges: jax.Array) -> jax.Array:
    """Reference XLA formulation of the same op (gather + running max).

    Used on CPU, for unsupported shapes, and as the oracle the kernel is
    tested against.
    """
    def body(f, best):
        k = lax.dynamic_index_in_dim(edges, f, axis=1, keepdims=False)
        return jnp.maximum(best, view[k, :])

    init = jnp.full(view.shape, -1, dtype=view.dtype)
    return lax.fori_loop(0, edges.shape[1], body, init)
