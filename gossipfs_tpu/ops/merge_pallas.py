"""Pallas TPU kernel for the gossip fanout max-merge — the hot op.

Per round, every receiver i merges the membership rows of its ``F`` fanout
peers with an elementwise max (the tensorized MergeMemberList, reference:
slave/slave.go:414-440):

    out[i, :] = max_f view[edges[i, f], :]

where ``view`` is the gossip view (heartbeat if the entry is gossipable,
-1 otherwise).  This is a bandwidth problem: F·N² reads with a
data-dependent row gather.  XLA's gather lowering reaches ~140 GB/s on a
v5e chip; this kernel sustains ~4-6x that by:

  * keeping the whole ``view`` in HBM and gathering rows with explicit
    async DMAs (``pltpu.make_async_copy``), ``slots``-deep double-buffered
    so the VPU max never waits on memory;
  * reshaping to ``[N, N/C, C/128, 128]`` so each gathered unit is a
    tile-aligned ``(C/128, 128)`` block (Mosaic rejects single-row slices
    of an ``(8,128)``-tiled HBM buffer); large ``block_c`` keeps the DMA
    count low — descriptor issue, not bytes, is the limiter once the view
    is narrow (core/rounds.py rebases heartbeats into ``config.view_dtype``,
    int16 or int8, cutting the gather's bytes 2-4x vs int32);
  * accumulating the F-way max entirely in VMEM — the output is written
    exactly once, so total traffic is the information floor
    (F reads + 1 write per state element).

The kernel is semantically a pure function; ``interpret=True`` runs it on
CPU for tests (tests/test_merge_pallas.py pins it against the XLA
formulation).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gossipfs_tpu.ops import swar

LANE = 128

# jax-version compat: the Mosaic compiler-params dataclass was named
# TPUCompilerParams before jax 0.5; resolve whichever this runtime ships.
# Fail HERE, by name, if neither exists — a silent None would surface as
# an opaque "'NoneType' object is not callable" at first kernel call
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))
if _CompilerParams is None:  # pragma: no cover - future jax renames only
    raise ImportError(
        "pallas TPU exposes neither CompilerParams nor TPUCompilerParams "
        "on this jax version — update the compat shim in ops/merge_pallas.py"
    )

# Narrowest column block the COMPILED kernel can move: the int8 lanes'
# native tile is (32, 128), so a DMA unit (C/128, 128) needs C >= 32*128.
# Below this (small N, narrow shards) the dispatch (core/rounds._use_pallas)
# stays on the XLA path; interpret mode has no tiling and runs any size.
MIN_COMPILED_BLOCK_C = 32 * LANE


def _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk, slots, sink,
                     jdim: int = 1):
    """The slotted gather pipeline shared by both kernels.

    For each receiver row r in the block: async-DMA the ``F`` sender view
    rows (``slots``-deep double-buffered so the VPU max never waits on
    memory), widen to int32 for the F-way max (v5e Mosaic has no narrow-int
    vector compare/max — the DMAs still move the narrow dtype, which is
    what the kernel is bound by), and hand the per-row maximum to ``sink``.
    ``jdim``: which grid dimension indexes the column block.
    """
    j = pl.program_id(jdim)

    def issue(r, slot):
        for f in range(n_fanout):
            pltpu.make_async_copy(
                view_ref.at[edges_ref[r, f], j],
                scratch.at[slot, f],
                sems.at[slot, f],
            ).start()

    def wait(slot):
        for f in range(n_fanout):
            # src is irrelevant for wait(); shapes must match the start
            pltpu.make_async_copy(
                view_ref.at[0, j], scratch.at[slot, f], sems.at[slot, f]
            ).wait()

    for s in range(slots - 1):
        issue(s, s)

    def body(r, _):
        slot = lax.rem(r, slots)

        @pl.when(r + slots - 1 < r_blk)
        def _():
            issue(r + slots - 1, lax.rem(r + slots - 1, slots))

        wait(slot)
        acc = scratch[slot, 0].astype(jnp.int32)
        for f in range(1, n_fanout):
            acc = jnp.maximum(acc, scratch[slot, f].astype(jnp.int32))
        sink(r, acc)
        return 0

    lax.fori_loop(0, r_blk, body, 0, unroll=False)


def _kernel(n_fanout: int, r_blk: int, slots: int):
    def kernel(edges_ref, view_ref, out_ref, scratch, sems):
        # edges_ref: [r_blk, F] int32 in SMEM (this row-block's in-edges)
        # view_ref:  [N, N/C, C/128, 128] in HBM (never copied wholesale)
        # out_ref:   [r_blk, 1, C/128, 128] in VMEM
        # scratch:   [slots, F, C/128, 128] VMEM; sems: [slots, F]
        def sink(r, acc):
            out_ref[r, 0] = acc.astype(out_ref.dtype)

        _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk, slots, sink)

    return kernel


def supported(n: int, fanout: int, n_cols: int | None = None) -> bool:
    """Whether the kernel's tiling constraints admit this problem size.

    ``n_cols`` (default: square) is the local subject count — smaller than
    ``n`` under subject-axis sharding, where each shard must still be
    lane-aligned.
    """
    if n_cols is None:
        n_cols = n
    return (
        n % LANE == 0 and n >= LANE and n_cols % LANE == 0 and n_cols >= LANE
        and fanout >= 1
    )


@functools.partial(
    jax.jit, static_argnames=("block_r", "block_c", "slots", "interpret")
)
def fanout_max_merge(
    view: jax.Array,
    edges: jax.Array,
    *,
    block_r: int = 128,
    block_c: int = 8192,
    slots: int = 4,
    interpret: bool = False,
) -> jax.Array:
    """out[i, :] = max over f of view[edges[i, f], :].

    ``view``: [N, N], any fixed-width integer dtype — production passes the
    rebased view built in core/rounds.py (``config.view_dtype``: int16 or
    int8, so 1-2 bytes/elem of DMA traffic); int32 works too.  Use -1 for
    "absent" lanes so the max ignores them.
    ``edges``: int32 [N, F] in-edge sender ids.  Defaults are the tuned v5e
    values; blocks shrink automatically for small N.
    """
    n = view.shape[0]
    fanout = edges.shape[1]
    if view.shape != (n, n):
        raise ValueError(f"view must be square [N, N], got {view.shape}")
    if not supported(n, fanout):
        raise ValueError(
            f"pallas merge needs N % {LANE} == 0 and fanout >= 1 "
            f"(N={n}, fanout={fanout}); use the XLA path"
        )
    # blocks must tile N exactly; halving bottoms out at LANE, which always
    # divides a lane-aligned N
    c_blk = min(block_c, n)
    while n % c_blk:
        c_blk //= 2
    if not interpret and c_blk < MIN_COMPILED_BLOCK_C:
        raise ValueError(
            f"compiled pallas merge needs >= {MIN_COMPILED_BLOCK_C}-wide "
            f"column blocks (got {c_blk} at N={n}); Mosaic rejects "
            "sub-tile DMA units — use interpret mode or the XLA path"
        )
    r_blk = min(block_r, n)
    while n % r_blk:
        r_blk //= 2
    n_slots = max(2, min(slots, r_blk))
    cs = c_blk // LANE

    view4 = view.reshape(n, n // c_blk, cs, LANE)
    out4 = pl.pallas_call(
        _kernel(fanout, r_blk, n_slots),
        grid=(n // r_blk, n // c_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda i, j: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (r_blk, 1, cs, LANE),
            lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, n // c_blk, cs, LANE), view.dtype),
        scratch_shapes=[
            pltpu.VMEM((n_slots, fanout, cs, LANE), view.dtype),
            pltpu.SemaphoreType.DMA((n_slots, fanout)),
        ],
        interpret=interpret,
    )(edges, view4)
    return out4.reshape(n, n)


def _fused_kernel(
    n: int, n_fanout: int, r_blk: int, slots: int,
    member: int, unknown: int, age_clamp: int, failed: int, detect_stats: bool,
    suspect: int | None = None,
):
    def kernel(
        edges_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref, sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        best_scratch, hb_vmem, age_vmem, status_vmem, scratch, sems, row_sems,
    ):
        # edges_ref: [r_blk, F] int32 SMEM — dead receivers' edges are
        #            remapped to self by the wrapper (their own view row is
        #            all -1, making the merge a no-op for them while the
        #            age advance still applies)
        # view_ref / hb/age/status_hbm: [N/R or N, ..., C/128, 128] in HBM.
        #            The receiver-row lanes are copied block-at-a-time with
        #            explicit DMAs that overlap the gather loop — VMEM-block
        #            inputs measured 5x slower here (Mosaic serialized their
        #            per-grid-step copies against the manual gather DMAs).
        # Grid (nc, n // r_blk): column block j OUTER, receiver block i
        # inner, so the per-subject reduction outputs (indexed by j only)
        # accumulate across consecutive i steps while resident in VMEM —
        # same pattern as the stripe kernels.
        j = pl.program_id(0)
        i = pl.program_id(1)

        # block-input DMAs for the receiver lanes: issued before the gather
        # loop, awaited after it — their ~3 MB fully hides under the
        # gather's F x r_blk row copies.  The lane refs stay 4-D (dynamic
        # row-block slices) so the OUTPUT lanes can alias them: each block
        # is read exactly once, strictly before its own step writes it, so
        # in-place update is safe — and drops three [N, N]-lane buffers
        # from the round's peak HBM (what bounds single-chip capacity).
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        # Phase 1 — row loop: gather + F-way max into best_scratch.  The
        # loop body stays minimal so the DMA waits dominate it; everything
        # else runs once per block, vectorized (a per-row epilogue measured
        # 2x slower than the whole unfused pipeline — tiny (cs, 128) tiles
        # serialize the VPU work against the gather waits).
        def sink(r, acc):
            best_scratch[r] = acc

        _gather_max_rows(edges_ref, view_ref, scratch, sems, n_fanout, r_blk,
                         slots, sink, jdim=0)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
            suspect=suspect,
        )

    return kernel


# Default receiver rows per fused-kernel block (config.merge_block_r
# overrides via the block_r argument).  128 rows x 16384 cols puts the
# in/out hb (int32) + age/status (int8) blocks + epilogue temporaries well
# past Mosaic's 16 MB default scoped-VMEM budget — the pallas_call below
# raises the limit, and 128 measured ~7% faster than 32 (fewer block
# boundaries) at N=16k.  The floor is 32: the int8 block tile is (32, 128).
_FUSED_BLOCK_R = 128
_FUSED_BLOCK_R_MIN = 32


# widest single column block the VMEM budget allows (gather scratch +
# receiver-lane blocks at _FUSED_BLOCK_R rows; see the pallas_call's
# vmem_limit note)
_FULL_ROW_MAX = 16_384


def blocked_cols(n_cols: int, block_c: int) -> tuple[int, int, int]:
    """The kernel-native column blocking [C_total/C, C/128, 128].

    Columns may be fewer than rows: under subject-axis sharding each shard
    blocks its local column slice independently.  Blocks must tile n_cols
    exactly; for a non-power-of-two count (e.g. 10,240) the power-of-two
    halving would shatter into tiny blocks and multiply the gather's DMA
    descriptor count, so lane-aligned widths take one full-width block
    instead whenever it fits VMEM.
    """
    c_blk = min(block_c, n_cols)
    while n_cols % c_blk:
        c_blk //= 2
    if c_blk < min(block_c, n_cols) and n_cols <= _FULL_ROW_MAX:
        c_blk = n_cols
    return (n_cols // c_blk, c_blk // LANE, LANE)


def blocked_shape(n: int, block_c: int) -> tuple[int, int, int, int]:
    """The kernel-native [N, N/C, C/128, 128] shape for an [N, N] lane.

    TPU arrays are physically tiled; reshaping [N, N] into this 4-D form
    (needed so a DMA can fetch one sender row as a tile-aligned block) is a
    real relayout pass, ~1-3 ms per lane at N=16k.  core/rounds.py therefore
    keeps the whole state in this blocked layout across the scan and
    reshapes once at entry/exit instead of every round.
    """
    return (n,) + blocked_cols(n, block_c)


def fused_merge_update(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    block_r: int = _FUSED_BLOCK_R,
    block_c: int = 8192,  # match SimConfig.merge_block_c's default
    slots: int = 4,
    interpret: bool = False,
    suspect: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """2-D convenience wrapper around :func:`fused_merge_update_blocked`.

    Takes/returns [N, N] lanes; each call pays the blocked-layout reshapes,
    so the scan hot path uses the blocked variant directly.  Used by
    core/rounds.py for ring topology, where per-round edge derivation needs
    the 2-D layout anyway.
    """
    n = view.shape[0]
    shp = blocked_shape(n, block_c)
    h4, a4, s4, _cnt, _nd, _fo = fused_merge_update_blocked(
        view.reshape(shp),
        edges,
        hb.reshape(shp),
        age.reshape(shp),
        status.reshape(shp),
        shift_a.reshape(shp[1:]),
        shift_b.reshape(shp[1:]),
        alive,
        member=member,
        unknown=unknown,
        age_clamp=age_clamp,
        block_r=block_r,
        slots=slots,
        interpret=interpret,
        suspect=suspect,
    )
    return h4.reshape(n, n), a4.reshape(n, n), s4.reshape(n, n)


@functools.partial(
    jax.jit,
    static_argnames=(
        "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "slots", "interpret", "suspect"
    ),
)
def fused_merge_update_blocked(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    slots: int = 4,
    interpret: bool = False,
    suspect: int | None = None,
) -> tuple[jax.Array, ...]:
    """Gossip merge + membership update + age advance in one pass.

    Fuses the tail of core/rounds.py ``_merge`` (un-rebase, max-merge
    advance, UNKNOWN add, fresh-stamp) and the post-merge ``age + 1`` clamp
    into the gather kernel's epilogue, so the [N, N] hb/age/status lanes
    are read and written exactly once per round instead of once by the
    kernel plus once by a separate XLA pass (~25% of round time at N=16k).

    All [N, N] lanes arrive in the :func:`blocked_shape` 4-D layout (the
    scan keeps state blocked so no per-round relayout happens).
    ``shift_a``/``shift_b`` are per-subject int32 vectors in the blocked
    [N/C, C/128, 128] form: stored->view-encoding shift and old->new
    stored-base shift (core/rounds.py ``_merge`` derives both; in int32
    mode shift_a is the view rebase base and shift_b is zero).  ``edges``
    int32 [N, F]; ``alive`` int32 [N] (receiver liveness).  Returns the
    updated (hb, age, status, member_cnt, n_det, first_obs) — the last
    three as in :func:`stripe_merge_update_blocked` (counts/stats are
    accumulated in-kernel; the stat lanes are zeros unless
    ``detect_stats``).
    """
    n, nc, cs, _ = view.shape
    fanout = edges.shape[1]
    if not supported(n, fanout):
        raise ValueError(
            f"fused merge needs N % {LANE} == 0 and fanout >= 1 "
            f"(N={n}, fanout={fanout}); use the XLA path"
        )
    c_blk = cs * LANE
    # cap rows x cols at the validated VMEM budget (128 x 16384 compiles at
    # ~85 MB of scoped VMEM; bigger blocks OOM at runtime) so an oversized
    # merge_block_r degrades to a smaller block instead of crashing
    vmem_cap_rows = max(_FUSED_BLOCK_R_MIN, (_FUSED_BLOCK_R * 16_384) // c_blk)
    r_blk = max(min(block_r, n, vmem_cap_rows), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    n_slots = max(2, min(slots, r_blk))

    # the alive gate, without a per-row vector operand: a dead receiver's
    # edges all point at itself — a dead node is never a sender, so its own
    # view row is all -1 and its merge is a no-op (only the age advance
    # applies), exactly the reference semantics for a crashed process
    self_idx = jnp.arange(n, dtype=edges.dtype)[:, None]
    edges = jnp.where((alive != 0)[:, None], edges, self_idx)
    # liveness replicated across the lane dim for clean vector broadcast
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))

    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    view4 = view
    out = pl.pallas_call(
        _fused_kernel(n, fanout, r_blk, n_slots, member, unknown, age_clamp,
                      failed, detect_stats, suspect=suspect),
        grid=(nc, n // r_blk),
        # in-place lane update: outputs 0-2 reuse the (post-tick) input
        # lane buffers — see the kernel's DMA comment for why it's safe.
        # Costs ~2 ms/round at N=16k (Mosaic pipelines aliased writes more
        # conservatively), so the stripe/arc kernels — whose sizes fit HBM
        # comfortably — stay non-aliased; HERE the three reclaimed lane
        # buffers are what fits N=49,152 on one chip at all
        input_output_aliases={2: 0, 3: 1, 4: 2},
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.VMEM((n_slots, fanout, cs, LANE), view.dtype),
            pltpu.SemaphoreType.DMA((n_slots, fanout)),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        # 128-row blocks + the block-wide epilogue's widened int32
        # temporaries put peak scoped-VMEM at ~85 MB with 16k-wide blocks —
        # far above Mosaic's 16 MB default but inside the v5e's 128 MB
        # physical VMEM
        compiler_params=_CompilerParams(vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(edges, view4, hb, age, status, alive_lanes, shift_a, shift_b)
    return tuple(out)


def _epilogue_and_count(
    best_rel, hb, age, st, recv, sa, sb,
    hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
    i, r_blk: int, member: int, unknown: int, age_clamp: int,
    failed: int, detect_stats: bool, n: int, fail=None,
    suspect: int | None = None,
):
    """Block-wide merge epilogue shared by the stripe kernels.

    MergeMemberList semantics over post-tick values (core/rounds.py
    ``_membership_update``'s int32+clip formulation; ``hb``/``age``/``st``
    arrive widened to int32, ``recv`` is the receiver-liveness mask), plus
    per-subject reductions accumulated across the consecutive receiver
    blocks that revisit the same output block (grid: j outer, i inner).

    ``suspect`` (round 11): the SWIM SUSPECT status value when the config
    arms suspicion, else None.  A SUSPECT entry is still listed — it
    advances (the advance IS the refutation: the status write below flips
    it back to MEMBER) and counts toward the membership tallies; the
    suspect/confirm transitions themselves live in the tick
    (core/rounds.py ``_tick``), which runs before these kernels.

    * ``cnt_out`` — live observers holding the entry (self included — the
      caller subtracts the diagonal);
    * ``ndet_out`` / ``fobs_out`` (only when ``detect_stats``) — this
      round's detector firings per subject and the lowest firing observer
      (``n`` where no observer fired).  ``fail`` is the exact in-kernel
      fail mask when the tick ran in-kernel; otherwise the stats fall back
      to the ``status == FAILED and age == 0`` identity, valid under the
      crash-only + fresh_cooldown + no-remove-broadcast fault model (the
      detector is the only writer of FAILED, it stamps age 0, and every
      older FAILED entry has aged at least once).

    These replace full-matrix major-axis reductions in XLA, which measured
    ~6x slower than minor-axis reductions.
    """
    any_member = best_rel >= 0
    listed = (st == member) if suspect is None else (
        (st == member) | (st == suspect)
    )
    advance = recv & any_member & listed & (best_rel > hb - sa)
    add = recv & any_member & (st == unknown)
    upd = advance | add
    new_hb = jnp.where(upd, best_rel + (sa - sb), hb - sb)
    if hb_out.dtype != jnp.int32:
        info = jnp.iinfo(hb_out.dtype)
        new_hb = jnp.clip(new_hb, info.min, info.max)
    hb_out[:, 0] = new_hb.astype(hb_out.dtype)
    new_age = jnp.minimum(jnp.where(upd, 0, age) + 1, age_clamp)
    age_out[:, 0] = new_age.astype(age_out.dtype)
    # every update writes MEMBER: an add learns the entry, and an advance
    # on a SUSPECT entry is the refutation (suspicion off: advance lanes
    # are already MEMBER, so the write is the same bits as the old
    # add-only select)
    st_new = jnp.where(upd, member, st)
    status_out[:, 0] = st_new.astype(status_out.dtype)

    listed_new = (st_new == member) if suspect is None else (
        (st_new == member) | (st_new == suspect)
    )
    part = jnp.sum((recv & listed_new).astype(jnp.int32), axis=0)[None]
    if detect_stats:
        # recv-masked even though today's writers make it redundant (the
        # detector is the only writer of FAILED/age=0 and it only fires on
        # live receivers): a future writer of FAILED/age=0 — matrix events
        # or remove_broadcast on this path — must not inflate the stats
        # (ADVICE r3)
        fresh = (fail if fail is not None else (st == failed) & (age == 0)) & recv
        ndet_part = jnp.sum(fresh.astype(jnp.int32), axis=0)[None]
        rows = lax.broadcasted_iota(jnp.int32, st.shape, 0) + i * r_blk
        fobs_part = jnp.min(jnp.where(fresh, rows, n), axis=0)[None]

    @pl.when(i == 0)
    def _():
        cnt_out[...] = part
        if detect_stats:
            ndet_out[...] = ndet_part
            fobs_out[...] = fobs_part
        else:
            ndet_out[...] = jnp.zeros_like(ndet_out)
            fobs_out[...] = jnp.zeros_like(fobs_out)

    @pl.when(i > 0)
    def _():
        cnt_out[...] = cnt_out[...] + part
        if detect_stats:
            ndet_out[...] = ndet_out[...] + ndet_part
            fobs_out[...] = jnp.minimum(fobs_out[...], fobs_part)


def _stripe_kernel(
    n: int, n_fanout: int, r_blk: int, member: int, unknown: int,
    age_clamp: int, failed: int, detect_stats: bool,
    suspect: int | None = None,
):
    def kernel(
        edges_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref, sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        stripe, best_scratch, hb_vmem, age_vmem, status_vmem, stripe_sem, row_sems,
    ):
        # Grid (nc, n // r_blk): column block j OUTER, receiver block i
        # inner, so one stripe load serves every receiver block.
        j = pl.program_id(0)
        i = pl.program_id(1)

        # stripe DMA: the whole view column block [N, cs, LANE] HBM -> VMEM,
        # once per j (i == 0).  Every receiver's F-way gather then reads
        # VMEM — total HBM traffic for the view drops from F x N^2 to N^2.
        @pl.when(i == 0)
        def _():
            pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem).start()

        # 4-D lane refs with dynamic row-block slices — the layout that
        # WOULD let output lanes alias the inputs (each block is read
        # exactly once, before its own step writes it; cross-row data
        # comes only from the separate view stripe).  This kernel's sizes
        # fit HBM comfortably and aliasing measured ~2 ms/round slower
        # (Mosaic pipelines aliased writes conservatively), so only the
        # capacity-bound gather kernel passes input_output_aliases.
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        @pl.when(i == 0)
        def _():
            pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem).wait()

        # Phase 1 — F-way max per receiver row, straight from the resident
        # stripe (vector loads, no per-row DMA descriptors — the gather
        # kernel's limiter).
        def body(r, _):
            acc = stripe[edges_ref[r, 0]].astype(jnp.int32)
            for f in range(1, n_fanout):
                acc = jnp.maximum(acc, stripe[edges_ref[r, f]].astype(jnp.int32))
            best_scratch[r] = acc
            return 0

        lax.fori_loop(0, r_blk, body, 0, unroll=False)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.
        # receiver liveness, replicated across lanes by the wrapper so it
        # broadcasts over the subject dims without sublane shuffles
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
            suspect=suspect,
        )

    return kernel


# The stripe kernel holds one full view column block [N, cs, LANE] resident
# in VMEM.  int8's native tile is (32, 128), so cs must be a multiple of 32
# (else Mosaic pads each leading index to a full tile, 4x-ing the stripe);
# the v5e's 128 MB VMEM then bounds N x 4096 bytes — N <= 16,384 with
# headroom for the receiver-lane blocks.  Bigger problems use the gather
# kernel.
STRIPE_BLOCK_C = 4096
STRIPE_MAX_BYTES = 72 * 1024 * 1024


def stripe_supported(n: int, fanout: int, n_cols: int | None = None) -> bool:
    if n_cols is None:
        n_cols = n
    return (
        supported(n, fanout, n_cols)
        and n_cols % STRIPE_BLOCK_C == 0
        and n * STRIPE_BLOCK_C <= STRIPE_MAX_BYTES
    )


# Stripe widths the resident-round kernel accepts.  Narrower stripes trade
# per-element gather efficiency for VMEM: at c_blk=1024 the resident view
# stripe is N x 1024 bytes, which is what admits N=65,536 on one chip
# (64 MB stripe) — measured unpadded (Mosaic packs (8, 128) int8 scratch
# without rounding the sublane dim up to the (32, 128) tile).
RR_BLOCK_CS = (512, 1024, 2048, 4096)


# rows per rr view-build chunk: int32 temporaries over a (chunk, cs, LANE)
# block are what bounds VMEM here (16 MB per temporary at 1024 rows).
# Defined up here because the budget helpers below take it as a default.
RR_CHUNK = 256

# rr view-build DMA pipeline depth (see the chunk-loop comment in _rr_kernel)
VSLOTS = 4


def rr_view_chunk(n: int, c_blk: int, *, resident: bool = False,
                  chunk: int = RR_CHUNK, arc_align: int = 1) -> int:
    """The view-build chunk row count the rr kernel will actually use.

    THE derivation — ``resident_round_blocked`` calls this (it is not a
    mirror of wrapper-local logic), so the budget helpers and the kernel
    can never disagree about the ring geometry; the scratch-budget lint
    (tests/test_merge_pallas.py) additionally reconciles both against
    the kernel's real ``pltpu`` allocations.  The resident cap keeps the
    widened tick temporaries (which scale with chunk x c_blk) beside the
    parked lanes; the halving preserves n-divisibility; the arc floor
    makes chunks cover whole groups."""
    ch = min(chunk, n)
    if resident:
        ch = min(ch, max(64, (1 << 18) // c_blk))
    while n % ch:
        ch //= 2
    if arc_align > 1:
        ch = max(ch, arc_align)
    return ch


def _rr_block_rows(n: int, block_r: int) -> int:
    """The receiver-block row count the rr kernel will actually use
    (shared by the wrapper and the flags-layout gate)."""
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    return r_blk


def rr_ring_supported(fanout: int, arc_align: int, chunk: int) -> bool:
    """Whether the ring-rotated aligned-arc view build admits this shape.

    Each view-build chunk must cover STRICTLY more whole groups than the
    window halo (``fanout/align - 1`` rows): the ring carry and the
    wrap-head save copy halo rows from within a single chunk's output,
    and the first chunk flushes its ``gpc - halo`` halo-free W rows — at
    ``gpc == halo`` that flush is an out-of-bounds zero-size slice
    (found by review: resident c_blk=4096 caps the chunk at 64 rows, so
    align=8 with fanout=72 hit it).  Every production shape qualifies
    (chunks cover >= 8 groups, halos are 1-2 rows); the full-T build
    remains the fallback."""
    if arc_align <= 1:
        return False
    gpc = chunk // arc_align
    nw = fanout // arc_align
    return nw == 1 or gpc >= nw


def rr_flags_compact_ok(n: int, c_blk: int, *,
                        block_r: int = _FUSED_BLOCK_R,
                        resident: bool = False, chunk: int = RR_CHUNK,
                        arc_align: int = 1) -> bool:
    """Whether the rr kernel can take the LANE-compacted flags layout.

    Compact flags pack the per-row flag byte as [N/LANE, LANE] row-major
    (1 B/row of resident VMEM instead of the lane-replicated form's
    LANE B/row — the same move that took the count accumulator from
    134 MB to 2 MB in round 5).  Every in-kernel flags slice (view-build
    chunks, receiver blocks) must then cover whole compact rows, so both
    the chunk and the receiver block must be LANE-divisible — true for
    every capacity shape (config.py already forces
    ``merge_block_r % 128 == 0`` on deep stripes); the kernel expands to
    the replicated layout otherwise."""
    ch = rr_view_chunk(n, c_blk, resident=resident, chunk=chunk,
                       arc_align=arc_align)
    r_blk = _rr_block_rows(n, block_r)
    return n % LANE == 0 and ch % LANE == 0 and r_blk % LANE == 0


def rr_flags_bytes(n: int, c_blk: int, *, block_r: int = _FUSED_BLOCK_R,
                   resident: bool = False, chunk: int = RR_CHUNK,
                   arc_align: int = 1, rotate: bool = True) -> int:
    """Resident VMEM the flags input block occupies (see
    :func:`rr_flags_compact_ok`)."""
    if rotate and rr_flags_compact_ok(
            n, c_blk, block_r=block_r, resident=resident, chunk=chunk,
            arc_align=arc_align):
        return n
    return n * LANE


def rr_supported(n: int, fanout: int, c_blk: int,
                 n_cols: int | None = None, arc_align: int = 1, *,
                 block_r: int = _FUSED_BLOCK_R, rotate: bool = True) -> bool:
    if n_cols is None:
        n_cols = n
    if arc_align > 1:
        # aligned-arc mode materializes no view stripe (write-only — the
        # gather reads the window maxes); the VMEM row cost is the
        # window scratch (ring-rotated by default: only the int8 W buffer
        # scales with rows — see rr_align_scratch_bytes) PLUS the per-row
        # buffers that scale with N regardless of stripe width: the flags
        # block (LANE-compacted where admissible) and, on deep-stripe
        # shapes, the count accumulator (int32 at N >= 32,768).  Omitting
        # those admitted a 16-way N=262,144 shape whose scratch demanded
        # 225 MB (round-5 review).  The scratch bytes come from
        # rr_align_scratch_bytes — the SAME function the kernel's own
        # resident check and rr_resident_supported use — so the
        # validation paths cannot disagree near the boundary.
        row_bytes = rr_align_scratch_bytes(
            n, fanout, c_blk, arc_align, rotate=rotate
        ) + rr_flags_bytes(n, c_blk, block_r=block_r, arc_align=arc_align,
                           rotate=rotate)
        if n_cols // c_blk > RR_ACC_STRIPES:
            # lane-compacted int32 count accumulator + the grid-resident
            # compact count OUTPUT block (both [N/LANE, LANE] int32)
            row_bytes += n * 8
        return (
            supported(n, fanout, n_cols)
            and c_blk in RR_BLOCK_CS
            and n_cols % c_blk == 0
            and row_bytes <= RR_ALIGN_VMEM_BUDGET
        )
    return (
        supported(n, fanout, n_cols)
        and c_blk in RR_BLOCK_CS
        and n_cols % c_blk == 0
        and n * c_blk <= STRIPE_MAX_BYTES
    )


# Resident-lanes VMEM budget: view stripe + both parked raw lanes
# (3 x N x c_blk bytes) must leave room for the view-build ping-pong,
# flags, best_scratch and Mosaic's widened temporaries inside the 128 MB.
# 102 MB admits the headline shape (N=16,384 at c_blk<=2048) and the
# N=32,768 frontier at c_blk=1024.
RR_RESIDENT_MAX_BYTES = 102 * 1024 * 1024
# combined ceiling for the parked lanes PLUS the aligned-arc window scratch
# (102 MB already leaves room for the view-build/receiver/iota/flag
# scratches against the 126 MB compiler limit; the aligned tbuf/wbuf may
# use part of that slack, measured ~8 MB of fixed scratch at headline
# shapes — the headline's 100.7 MB lanes + 12.6 MB aligned scratch compile)
RR_RESIDENT_ALIGN_BUDGET = 118 * 1024 * 1024

# Combined VMEM budget for the aligned-arc (stripe-free) row costs: the
# window scratch + flags + the deep-stripe count accumulator must leave
# room for the view-build/receiver/iota/flag scratches inside the 126 MB
# compiler limit.  Under the round-9 layouts (ring-rotated build +
# LANE-compacted flags) the per-row cost collapses to W's c_blk/align
# bytes + 1 flag byte (+8 accumulator bytes on deep stripes): 73 B/row
# at c_blk=512/align=8, so 112 MB admits ~1.5M rows — >= 512k at
# c_blk=512 with margin, and wider stripes at every anchor (N=262,144
# admits c_blk=2048 at 64 MB where the round-5 full-T/replicated
# layouts capped it at c_blk=512 and ~367k rows overall).  The budget
# still rejects over-size shapes eagerly instead of via a late Mosaic
# allocation failure, and the scratch-budget lint
# (tests/test_merge_pallas.py) reconciles it against the kernel's real
# allocations.
RR_ALIGN_VMEM_BUDGET = 112 * 1024 * 1024

# Stripe count above which the rr kernel switches its per-receiver count
# output from per-stripe partial blocks ([N, nc*LANE], write hidden under
# compute) to the LANE-COMPACTED accumulated form ([N/LANE, LANE] int32,
# 4 B/receiver scratch + same-shape output) — see the count section of
# _rr_kernel for the A/B numbers behind both.
RR_ACC_STRIPES = 16


def rr_align_scratch_specs(n: int, fanout: int, c_blk: int, arc_align: int,
                           *, chunk: int | None = None,
                           resident: bool = False,
                           rotate: bool = True,
                           edge_filter: bool = False) -> list:
    """The aligned-arc window scratch allocations, as ``pltpu.VMEM`` specs.

    This is the SINGLE source the kernel allocates from and the
    scratch-budget lint reconciles against :func:`rr_align_scratch_bytes`
    — the budget math can never silently drift from the kernel again.

    Ring-rotated build (the default whenever :func:`rr_ring_supported`):

    * ``W`` int8 [N/align rows] — the gather's random-access target, the
      ONLY buffer that scales with rows (c_blk/align B/row; 64 B/row at
      c_blk=512/align=8 vs the full-T build's 192);
    * ``T ring`` bf16 [groups-per-chunk + halo rows] — each chunk's group
      maxes land at a FIXED ring position; W rows flush per chunk as soon
      as their halo is complete, so T stops scaling with N entirely;
    * ``head`` bf16 [halo rows] — the first chunk's leading group maxes,
      saved to close the mod-N wrap after the last chunk.

    Fallback (chunks narrower than the halo): the round-5 full-T layout —
    bf16 group maxes for the WHOLE stripe (+wrap halo) beside W.

    ``edge_filter`` (round 11, scenario-armed aligned runs): ONE full
    int8 T (+wrap halo) and nothing else — group maxes are read directly
    by the per-receiver masked gather, so no W is precomputed and no
    ring rotates.  Same c_blk/align B/row order as the ring build's W
    (int8 either way), so the rotate-based budget the admissibility
    helpers charge remains an upper bound for scenario runs.
    """
    cs = c_blk // LANE
    nb = n // arc_align
    nw = fanout // arc_align
    if chunk is None:
        chunk = rr_view_chunk(n, c_blk, resident=resident,
                              arc_align=arc_align)
    if edge_filter:
        return [pltpu.VMEM((nb + max(nw - 1, 0), cs, LANE), jnp.int8)]
    if rotate and rr_ring_supported(fanout, arc_align, chunk):
        gpc = chunk // arc_align
        hw = nw - 1
        specs = [pltpu.VMEM((nb, cs, LANE), jnp.int8)]
        if hw:
            specs += [
                pltpu.VMEM((gpc + hw, cs, LANE), jnp.bfloat16),
                pltpu.VMEM((hw, cs, LANE), jnp.bfloat16),
            ]
        return specs
    return [
        pltpu.VMEM((nb + max(nw - 1, 1), cs, LANE), jnp.bfloat16),
        pltpu.VMEM((nb, cs, LANE), jnp.int8),
    ]


def rr_align_scratch_bytes(n: int, fanout: int, c_blk: int,
                           arc_align: int, *, chunk: int | None = None,
                           resident: bool = False,
                           rotate: bool = True) -> int:
    """VMEM the aligned-arc window scratch needs — computed FROM the
    allocation specs (:func:`rr_align_scratch_specs`), so formula and
    kernel are one.  ``chunk=None`` derives the kernel's default
    view-build chunk (non-resident — the widest, hence an upper bound on
    the ring's fixed bytes for resident callers)."""
    if arc_align <= 1:
        return 0
    return sum(
        math.prod(s.shape) * jnp.dtype(s.dtype).itemsize
        for s in rr_align_scratch_specs(n, fanout, c_blk, arc_align,
                                        chunk=chunk, resident=resident,
                                        rotate=rotate)
    )


def rr_resident_supported(n: int, fanout: int, c_blk: int,
                          n_cols: int | None = None,
                          arc_align: int = 1, *,
                          block_r: int = _FUSED_BLOCK_R,
                          rotate: bool = True) -> bool:
    """Whether the floor-traffic resident-lanes rr variant fits VMEM.

    With ``arc_align > 1`` the aligned-arc window scratch
    (:func:`rr_align_scratch_bytes`) is counted against the combined
    budget, so config-time validation agrees with the kernel's own
    check."""
    if n_cols is None:
        n_cols = n
    align_bytes = rr_align_scratch_bytes(n, fanout, c_blk, arc_align,
                                         resident=True, rotate=rotate)
    # aligned mode materializes no stripe: resident VMEM is the two
    # parked lanes + the window scratch
    lane_bytes = (2 if arc_align > 1 else 3) * n * c_blk
    # per-row VMEM that scales with N regardless of stripe width: the
    # flags block (compacted where admissible), plus the count
    # accumulator on deep-stripe shapes (int32 at N >= 32,768) —
    # omitting these admitted a resident N=86,016 aligned shape that
    # demanded 165 MB of VMEM
    row_extra = rr_flags_bytes(n, c_blk, block_r=block_r, resident=True,
                               arc_align=arc_align, rotate=rotate)
    if n_cols // c_blk > RR_ACC_STRIPES:
        # lane-compacted int32 count accumulator + the grid-resident
        # compact count OUTPUT block (both [N/LANE, LANE] int32)
        row_extra += n * 8
    return (
        rr_supported(n, fanout, c_blk, n_cols, arc_align,
                     block_r=block_r, rotate=rotate)
        and lane_bytes <= RR_RESIDENT_MAX_BYTES
        and lane_bytes + align_bytes + row_extra
        <= RR_RESIDENT_ALIGN_BUDGET
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "interpret", "suspect",
    ),
)
def stripe_merge_update_blocked(
    view: jax.Array,
    edges: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    interpret: bool = False,
    suspect: int | None = None,
) -> tuple[jax.Array, ...]:
    """Gossip merge + membership update + age advance, stripe-resident.

    Same contract as :func:`fused_merge_update_blocked` (int8 view in the
    ``STRIPE_BLOCK_C`` blocked layout), different memory strategy: instead
    of per-receiver-row DMA gathers (F x N^2 HBM bytes, bound by DMA
    descriptor issue), each view column block is loaded into VMEM once and
    the F-way max reads it with vector loads — HBM view traffic drops F-fold
    and the descriptor count drops from F x N per round to ~nc.

    Returns (hb, age, status, member_cnt, n_det, first_obs): ``member_cnt``
    int32 [nc, cs, LANE] counts, per subject, the live observers whose
    updated list holds the entry (self INCLUDED — callers subtract the
    diagonal); ``n_det``/``first_obs`` carry this round's detection stats
    when ``detect_stats`` (see :func:`_epilogue_and_count`), zeros
    otherwise.
    """
    n, nc, cs, _ = view.shape
    fanout = edges.shape[1]
    if not stripe_supported(n, fanout, nc * cs * LANE):
        raise ValueError(
            f"stripe merge needs lane-aligned N, cs*LANE == {STRIPE_BLOCK_C} "
            f"and N*{STRIPE_BLOCK_C} <= {STRIPE_MAX_BYTES} B of VMEM "
            f"(N={n}, blocked cols={cs * LANE}); use the gather kernel"
        )
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2

    # dead receivers merge nothing: remap their edges to self (their own view
    # row is all -1), as in the gather kernel
    self_idx = jnp.arange(n, dtype=edges.dtype)[:, None]
    edges = jnp.where((alive != 0)[:, None], edges, self_idx)
    # liveness replicated across the lane dim for clean vector broadcast
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))

    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _stripe_kernel(n, fanout, r_blk, member, unknown, age_clamp,
                       failed, detect_stats, suspect=suspect),
        grid=(nc, n // r_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, fanout), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, cs, LANE), view.dtype),
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=_CompilerParams(vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(edges, view, hb, age, status, alive_lanes, shift_a, shift_b)
    return tuple(out)


# rows per in-VMEM window-max chunk (arc kernel): each ping-pong buffer is
# (ARC_CHUNK + F - 1, cs, LANE) bfloat16 — ~8.5 MB at cs=32.  bf16 because
# v5e Mosaic has no narrow-int vector max (arith.maxsi on i8 fails to
# legalize); bf16 max is native and exact for the int8 view range.
ARC_CHUNK = 1024

# Widest per-receiver group-match bitmask the scenario edge_filter can
# pack into one int32 lane (bit 31 stays clear — the sign bit): the
# fanout/arc_align group count must not exceed this.  Shared by the
# kernel validation below, the rr dispatch gate (core/rounds
# _rr_scan_eligible) and the scenario capability check
# (scenarios/tensor._require_arc_scenario) so the three can't drift.
ARC_MATCH_MAX_GROUPS = 31


def _windowmax_inplace(stripe, bufa, bufb, halo, fanout: int, nchunks: int,
                       rows: int = ARC_CHUNK):
    """Windowed row max, in place over the resident stripe.

    W[r] = max over view rows r..r+F-1 (mod N).  Shift-doubling to the
    largest power of two <= F, then one overlapped combine — O(log F)
    passes instead of F, amortized over every receiver reading the stripe.
    ``rows`` is the per-chunk row count (callers shrink it at wide
    stripes, where the bf16 ping-pong buffers would otherwise crowd VMEM).
    """
    halo[...] = stripe[0:fanout - 1]  # pre-overwrite wrap rows
    # largest power of two <= fanout
    p = 1 << (fanout.bit_length() - 1)

    def chunk_body(c, _):
        base = c * rows
        ext = rows + fanout - 1
        bufa[0:rows] = stripe[pl.ds(base, rows)].astype(bufa.dtype)

        @pl.when(c == nchunks - 1)
        def _():
            bufa[rows:ext] = halo[...].astype(bufa.dtype)

        @pl.when(c < nchunks - 1)
        def _():
            bufa[rows:ext] = stripe[
                pl.ds(base + rows, fanout - 1)
            ].astype(bufa.dtype)

        # shift-doubling ping-pong: after the step with shift s,
        # the buffer holds window maxes of length 2s
        src, dst = bufa, bufb
        length = ext
        s = 1
        while s < p:
            dst[0:length - s] = jnp.maximum(
                src[0:length - s], src[pl.ds(s, length - s)]
            )
            src, dst = dst, src
            length -= s
            s *= 2
        # combine two p-windows into the F-window (overlap is fine
        # for max): W[r] = max(D_p[r], D_p[r + F - p])
        if p == fanout:
            w = src[0:rows]
        else:
            w = jnp.maximum(
                src[0:rows],
                src[pl.ds(fanout - p, rows)],
            )
        stripe[pl.ds(base, rows)] = w.astype(stripe.dtype)
        return 0

    lax.fori_loop(0, nchunks, chunk_body, 0, unroll=False)


def _arc_update_kernel(
    n: int, fanout: int, r_blk: int, member: int, unknown: int,
    age_clamp: int, failed: int, detect_stats: bool,
    suspect: int | None = None,
):
    nchunks = n // ARC_CHUNK

    def kernel(
        bases_ref, view_ref, hb_hbm, age_hbm, status_hbm, alive_ref,
        sa_ref, sb_ref,
        hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
        stripe, bufa, bufb, halo, best_scratch,
        hb_vmem, age_vmem, status_vmem, stripe_sem, row_sems,
    ):
        j = pl.program_id(0)
        i = pl.program_id(1)

        # 4-D lane refs with dynamic row-block slices — aliasable layout,
        # deliberately NOT aliased (see the stripe kernel's comment: only
        # the capacity-bound gather kernel trades the ~2 ms/round aliasing
        # cost for the three reclaimed lane buffers)
        rows = pl.ds(i * r_blk, r_blk)
        row_copies = [
            pltpu.make_async_copy(hb_hbm.at[rows, j], hb_vmem, row_sems.at[0]),
            pltpu.make_async_copy(age_hbm.at[rows, j], age_vmem, row_sems.at[1]),
            pltpu.make_async_copy(status_hbm.at[rows, j], status_vmem, row_sems.at[2]),
        ]
        for c in row_copies:
            c.start()

        @pl.when(i == 0)
        def _():
            cp = pltpu.make_async_copy(view_ref.at[:, j], stripe, stripe_sem)
            cp.start()
            cp.wait()
            _windowmax_inplace(stripe, bufa, bufb, halo, fanout, nchunks)

        # Phase 1 — one widened vector load per receiver row (the windowed
        # max did the F-way work once per stripe, O(log F) instead of F)
        def body(r, _):
            best_scratch[r] = stripe[bases_ref[r, 0]].astype(jnp.int32)
            return 0

        lax.fori_loop(0, r_blk, body, 0, unroll=False)
        for c in row_copies:
            c.wait()

        # Phase 2 — block-wide epilogue + per-subject reductions.  The
        # receiver-liveness gate is load-bearing here: arc bases cannot be
        # remapped to a "blank" row (every window-maxed stripe row holds
        # real values), so dead receivers are masked in the epilogue.
        recv = alive_ref[...].reshape(r_blk, 1, LANE) != 0
        _epilogue_and_count(
            best_scratch[...],
            hb_vmem[...].astype(jnp.int32),
            age_vmem[...].astype(jnp.int32),
            status_vmem[...].astype(jnp.int32),
            recv, sa_ref[0][None], sb_ref[0][None],
            hb_out, age_out, status_out, cnt_out, ndet_out, fobs_out,
            i, r_blk, member, unknown, age_clamp, failed, detect_stats, n,
            suspect=suspect,
        )

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "fanout", "member", "unknown", "age_clamp", "failed", "detect_stats",
        "block_r", "interpret", "suspect",
    ),
)
def arc_merge_update_blocked(
    view: jax.Array,
    bases: jax.Array,
    hb: jax.Array,
    age: jax.Array,
    status: jax.Array,
    shift_a: jax.Array,
    shift_b: jax.Array,
    alive: jax.Array,
    *,
    fanout: int,
    member: int,
    unknown: int,
    age_clamp: int,
    failed: int = 2,
    detect_stats: bool = False,
    block_r: int = _FUSED_BLOCK_R,
    interpret: bool = False,
    suspect: int | None = None,
) -> tuple[jax.Array, ...]:
    """Arc merge + membership update + age advance + member count, fused.

    The ``random_arc`` production kernel: combines the O(log F) windowed
    row-max (:func:`_windowmax_inplace` — senders are F consecutive rows)
    with :func:`stripe_merge_update_blocked`'s block-wide epilogue, so the hb/age/status lanes are read and written
    exactly once per round AND the per-receiver merge work is one vector
    load instead of an F-way max — the cheapest per-element round this
    module has.  Same contract as ``stripe_merge_update_blocked`` except
    senders come as arc ``bases`` int32 [N].

    (An in-kernel-tick variant of this kernel was measured and rejected:
    Mosaic's widened elementwise ran ~3x slower than the XLA tick pass it
    replaced — see BASELINE.md's round-profile notes.)
    """
    n, nc, cs, _ = view.shape
    if not stripe_supported(n, fanout, nc * cs * LANE):
        raise ValueError(
            f"arc merge update needs lane-aligned N, cs*LANE == "
            f"{STRIPE_BLOCK_C} and N*{STRIPE_BLOCK_C} <= {STRIPE_MAX_BYTES} B "
            f"(N={n}, blocked cols={cs * LANE}); use the XLA path"
        )
    if n % ARC_CHUNK:
        raise ValueError(f"arc merge update needs N % {ARC_CHUNK} == 0, got {n}")
    if not 1 < fanout <= ARC_CHUNK:
        raise ValueError(f"arc fanout must be in (1, {ARC_CHUNK}], got {fanout}")
    r_blk = max(min(block_r, n), _FUSED_BLOCK_R_MIN)
    while n % r_blk:
        r_blk //= 2
    alive_lanes = jnp.broadcast_to(alive.astype(jnp.int32)[:, None], (n, LANE))
    ext = ARC_CHUNK + fanout - 1
    row_spec = lambda j, i: (i, j, 0, 0)  # noqa: E731
    lane_blk = lambda dt: pl.BlockSpec(  # noqa: E731
        (r_blk, 1, cs, LANE), row_spec, memory_space=pltpu.VMEM
    )
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        _arc_update_kernel(n, fanout, r_blk, member, unknown, age_clamp,
                           failed, detect_stats, suspect=suspect),
        grid=(nc, n // r_blk),
        in_specs=[
            pl.BlockSpec(
                (r_blk, 1), lambda j, i: (i, 0), memory_space=pltpu.SMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (r_blk, LANE), lambda j, i: (i, 0), memory_space=pltpu.VMEM
            ),
            subj_spec,
            subj_spec,
        ],
        out_specs=[
            lane_blk(hb.dtype), lane_blk(age.dtype), lane_blk(status.dtype),
            subj_spec, subj_spec, subj_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, nc, cs, LANE), hb.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), age.dtype),
            jax.ShapeDtypeStruct((n, nc, cs, LANE), status.dtype),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, cs, LANE), view.dtype),
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((fanout - 1, cs, LANE), view.dtype),
            pltpu.VMEM((r_blk, cs, LANE), jnp.int32),
            pltpu.VMEM((r_blk, cs, LANE), hb.dtype),
            pltpu.VMEM((r_blk, cs, LANE), age.dtype),
            pltpu.VMEM((r_blk, cs, LANE), status.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=_CompilerParams(vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(bases.reshape(n, 1), view, hb, age, status, alive_lanes,
      shift_a, shift_b)
    return tuple(out)


# ---------------------------------------------------------------------------
# The resident-round kernel ("rr"): tick + gossip-view build + merge +
# membership update + every per-round reduction in ONE pallas call.
#
# Round 3 measured Mosaic's widened elementwise ~3x behind XLA and kept the
# heartbeat tick in XLA.  Round 4 re-measured and found the 3x was NOT
# Mosaic's VPU: the same epilogue ops cost ~0.75 ms via BlockSpec-pipelined
# blocks vs ~3.5 ms inside the manual-DMA stripe kernel, whose per-step
# waits serialize DMA latency against compute 512 times per round.  With
# lane blocks fetched by Mosaic's own pipeline the whole round fits in one
# kernel at XLA-class elementwise speed, and the separate XLA passes (tick
# fusion, view fusion, member-count reduction — together ~5.6 ms/round at
# N=16k) disappear:
#
#   per stripe j (grid j outer, i inner):
#     i == 0: build the GOSSIP VIEW stripe in VMEM from the raw hb/status/
#             age stripes (chunked double-buffered DMAs), recomputing the
#             heartbeat tick elementwise — the view never exists in HBM
#             (VERDICT r3 task 1: the [N, N] view materialization is gone)
#     every i: gather the F-way max from the resident view stripe, then
#             recompute the tick on the receiver block (BlockSpec-fetched)
#             and run the merge epilogue + reductions, writing each lane
#             exactly once
#
# Per-round HBM traffic drops from ~17 N^2 bytes (tick fusion 6 + view
# fusion 3 + kernel 7 + count pass 1) to ~6 N^2: the kernel's wire is TWO
# byte lanes per entry — hb int8 plus age(6b)|status(2b) PACKED into one
# biased byte (AGE_CLAMP = 63 makes age fit; config rejects deeper
# thresholds) — so the view build reads 2, the receiver sweep reads 2 and
# writes 2.  The round is ambient-bandwidth-bound (the shared chip
# delivers a fraction of its spec sheet), so a byte saved is time saved
# 1:1; the unpack (one add, one shift, one mask) rides the VPU's idle
# lanes.  The tick is recomputed twice per element (view build + receiver
# sweep) — duplicated VPU, two fewer HBM round trips, the same trade
# _round_core_fused makes in XLA (a tick-stub experiment measured the
# duplicated compute at ~0 ms: it hides entirely under the DMA waits).
#
# All arithmetic is WIDENED int32 over the packed int8 lanes, with
# per-subject int32 vectors (sa/sb/g) carrying the rebase state — the
# unclipped formulation the narrow-dtype XLA paths are proven equivalent
# to (core/rounds.py _membership_update / _gossip_view / _tick).
# ---------------------------------------------------------------------------

# RR_CHUNK / VSLOTS (the view-build chunk rows and DMA pipeline depth)
# are defined above the budget helpers, which mirror the chunk geometry.


def pack_age_status(age: jax.Array, status: jax.Array) -> jax.Array:
    """age(6b)|status(2b) into one biased int8: (age << 2 | status) - 128.

    The resident-round kernel's lane format — valid for age <= AGE_CLAMP
    (63) and status in {0, 1, 2}.  Biasing keeps the packed value inside
    signed int8 so the lane shares the hb lanes' dtype and tiling.
    """
    p = (age.astype(jnp.int32) << 2) | status.astype(jnp.int32)
    return (p - 128).astype(jnp.int8)


def unpack_age_status(asl: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Inverse of :func:`pack_age_status`; returns int32 (age, status)."""
    p = asl.astype(jnp.int32) + 128
    return p >> 2, p & 3


# ---------------------------------------------------------------------------
# Packed in-kernel arithmetic (int32 compute over the packed int8 lanes).
#
# Round-5 device traces showed the rr kernel COMPUTE-bound, not
# bandwidth-bound: with every elementwise stage stubbed out the kernel
# streams its lanes at near-spec HBM rate (~2.4 ms/round at N=16k), while
# the widened tick/view/merge stages added ~9 ms.  Probing Mosaic on v5e:
# int8 vectors support only bitwise + equality (no add, no ordered
# compare); int16 adds legalize but ordered compares don't; even bf16
# ordered compares are rejected ("target does not support this
# comparison") — ordered compares exist at i32 width only, so narrow-dtype
# density is off the table.  What remains is doing LESS i32 work:
#   * the age|status byte is never unpacked — for the packed biased byte
#     asl = ((age << 2) | st) - 128,
#       st == X           <=>  (asl & 3) == X
#       st == X & age > t <=>  (asl & 3) == X  &  asl > ((t << 2) | X) - 128
#       age := 0, st kept <=>  asl := (asl & 3) - 128
#       st := 0, age kept <=>  asl := asl & -4       (UNKNOWN == 0)
#       age := age + 1    <=>  asl := asl + 4        (no carry below clamp;
#                              age == clamp <=> asl >= (clamp << 2) - 128)
#     which deletes the unpack (+128, >>2, &3) and repack (<<2, |, -128)
#     from both passes;
#   * every per-subject saturation threshold is precomputed OUTSIDE the
#     kernel (the narrow XLA formulation's thresholds, core/rounds.py
#     _membership_update:584-638) and arrives as one int8 stack, so the
#     merge runs the narrow path's compare/select chain with no per-element
#     threshold math;
#   * resident mode parks the TICKED lanes, so the receiver sweep skips
#     the duplicate tick entirely (the single largest elementwise stage)
#     and reconstructs the detection mask with one compare.
# All arithmetic is i32 with a truncating int8 store, which reproduces the
# narrow XLA path's mod-2^8 wrap semantics exactly (bit-identical; pinned
# by the rr parity tests and the golden fuzz suite).
# ---------------------------------------------------------------------------

# rows of the per-subject int8 threshold stack (built in
# resident_round_blocked, one (cs, LANE) slab per stripe in-kernel)
V_SA_N, V_SA_ALL, V_HI_N, V_THR_G, V_CMP_DEEP, V_D8, V_UP_DEEP, \
    V_KEEP_THR, V_HI_THR, V_HAS_HI, V_SB8 = range(11)
N_VEC = 11


def _rr_tick_packed(hb, asl, act_r, ref_r, eye, thr_g, member, failed,
                    t_fail, t_cooldown, suspect=None, confirm_thr=0,
                    confirm_thr_hi=0, lh_r=None):
    """The heartbeat tick over i32-widened hb + PACKED age|status.

    Mirrors core/rounds.py ``_tick`` (lean crash-only path: small-group
    refresh, sentinel-sticky diagonal bump, detection over the
    POST-refresh age, fresh cooldown stamp, cooldown expiry — order
    matters) on the packed byte: the hb bump wraps on the int8 store
    exactly like the XLA narrow path's ``hb + bump`` (core/rounds.py:415),
    and the grace compare uses the precomputed clipped threshold
    (core/rounds.py:427-434).  Takes/returns i32.

    ``fail`` carries no explicit ``~eye`` term: it is implied.  A bumped
    diagonal has age 0 (< t_fail after the refresh/bump resets); an
    unbumped diagonal fails another conjunct — inactive row -> ``act_r``
    false, non-member -> the member test false, floor sentinel -> ``past``
    false.  (_tick keeps the reference's explicit self-exclusion; dropping
    it here removes an iota-mask AND from the hot pass — measured
    ~0.3 ms/round at N=16k.)

    ``suspect`` (round 11) arms the fused SWIM lifecycle: a stale MEMBER
    enters SUSPECT (status bits 1 -> 3, the AGE LANE keeps running — it IS
    the suspicion clock, ``age - t_fail`` = rounds in SUSPECT), and a
    SUSPECT lane confirms to FAILED once ``age > confirm_thr``
    (= t_fail + t_suspect).  ``fail`` then carries the CONFIRMATIONS, the
    lifecycle's actual failure declarations, exactly as the XLA ``_tick``.
    The confirm compare carries no ``~eye`` term either: the diagonal is
    never SUSPECT (self-suspicion needs ``stale``, which excludes self).

    ``lh_r`` (round 14) arms the fused Lifeguard local-health stretch: a
    per-ROW bool mask of receivers whose own view holds an anomalous
    SUSPECT fraction (derived OUTSIDE the kernel from the carried
    per-receiver suspect counts, riding flags bit 4).  A degraded row's
    confirmation threshold is ``confirm_thr_hi``
    (= t_fail + t_suspect * (1 + lh_multiplier)) instead of
    ``confirm_thr`` — a one-select per-row threshold shift, so the
    rr/SWAR fast path no longer degrades to stripe/XLA for
    lh_multiplier > 0.  ``lh_r=None`` keeps the scalar compare
    bit-identical to round 11.

    Both windows are instances of the contract's ``stale`` /
    ``confirm_window`` threshold formulas (analysis/protocol_spec.py
    THRESHOLDS) — the fused kernel implements the same guards as the
    XLA ``_tick`` and both socket engines, and the spec-* lint rules
    plus tests/test_protocol_spec.py hold all of them to that table.
    """
    st_bits = asl & 3
    st_mem = st_bits == member
    nsent = hb != -128
    if suspect is None:
        refresh = ref_r & st_mem
        refresh_val = st_bits - 128
    else:
        # small-group refreshers revert SUSPECT -> MEMBER with the fresh
        # stamp (detection is disabled below min_group, so suspicion is
        # moot there) — one write: every listed lane becomes (MEMBER, 0)
        refresh = ref_r & (st_mem | (st_bits == suspect))
        refresh_val = member - 128
    if eye is None:
        # caller knows the diagonal does not cross this block: the whole
        # bump chain drops out at trace time
        asl = jnp.where(refresh, refresh_val, asl)
    else:
        bump = eye & act_r & st_mem & nsent
        hb = hb + bump.astype(jnp.int32)
        # the diagonal is never SUSPECT, so the bump write's st_bits is
        # MEMBER — shared select with the refresh stamp either way
        asl = jnp.where(refresh, refresh_val, asl)
        asl = jnp.where(bump, st_bits - 128, asl)
    # refresh/bump writes touch disjoint rows from the detection below
    # (act_r vs ref_r), so st_mem still reads the relevant status here;
    # `past` needs no sentinel re-test (the bump cannot move a lane off
    # -128 — it is gated on nsent)
    past = (hb >= thr_g) & nsent
    stale = (
        act_r & st_mem & past
        & (asl > ((t_fail << 2) | member) - 128)
    )
    if suspect is None:
        fail = stale
        asl = jnp.where(fail, failed - 128, asl)
        elig = st_mem & ~fail
    else:
        st_sus = st_bits == suspect
        thr_b = ((confirm_thr << 2) | suspect) - 128
        if lh_r is not None:
            # Lifeguard stretch: degraded rows confirm at the stretched
            # threshold (one per-row select; both byte constants static)
            thr_b = jnp.where(
                lh_r, ((confirm_thr_hi << 2) | suspect) - 128, thr_b
            )
        confirm = act_r & st_sus & (asl > thr_b)
        # member -> suspect is one status bit (1 -> 3): age bits unchanged
        # (the clock keeps running); both masks derive from the pre-write
        # status, so an entry spends >= 1 round SUSPECT before confirming
        asl = jnp.where(stale, asl | 2, asl)
        asl = jnp.where(confirm, failed - 128, asl)
        fail = confirm
        elig = (st_mem | st_sus) & ~fail
    expire = ((asl & 3) == failed) & (asl > ((t_cooldown << 2) | failed) - 128)
    asl = jnp.where(expire, asl & -4, asl)
    # post-tick membership (gossip eligibility), for free: fail is the
    # only member-removing transition (expire acts on FAILED lanes), and
    # a newly-SUSPECT entry keeps gossiping (still a list entry)
    return hb, asl, fail, elig


def _wrap8(x):
    """int8 wrap of an i32 value in [-384, 383] — the narrow XLA path's
    mod-2^8 semantics for arithmetic whose result is COMPARED (not just
    stored; stores wrap for free on the int8 cast)."""
    return ((x + 128) & 255) - 128


def _rr_merge_packed(hb, asl, best, recv, vec, member, unknown, age_clamp,
                     suspect=None):
    """Merge epilogue (advance / add / rebase / age advance), i32 packed.

    Mirrors core/rounds.py ``_membership_update``'s narrow branch
    (rounds.py:584-638) term for term; every clipped threshold arrives
    precomputed in ``vec`` (widened i8 -> i32 values, so compares are the
    narrow path's sign-extended compares and adds/subs wrap on the final
    int8 store).  Returns (hb', asl', refute) as i32 — ``refute`` the
    SUSPECT -> MEMBER refutation mask (None when ``suspect`` is).

    Suspicion (round 11): a SUSPECT entry is still listed, so it takes
    the advance compare — and an advance on a SUSPECT entry IS SWIM's
    refutation (the update write below lands it back at (MEMBER, age 0),
    the same bits every advance writes).

    ``lhs`` is wrapped explicitly: the reference computes it in int8, and
    in the ``shift_a < -128`` regime (reachable after a rejoin drops the
    per-subject base) the wrap is what keeps the compare meaningful — an
    unwrapped i32 sum made ``advance`` unconditionally true there
    (round-5 review finding).
    """
    st = asl & 3
    any_m = best >= 0
    listed = (st == member) if suspect is None else (
        (st == member) | (st == suspect)
    )
    advance = (
        recv & listed & any_m
        & (best > vec[V_CMP_DEEP]) & (_wrap8(best + vec[V_SA_N]) > hb)
    )
    add = recv & (st == unknown) & any_m
    upd = advance | add
    up_val = jnp.where(best <= vec[V_UP_DEEP], -128, best + vec[V_D8])
    keep_val = jnp.where(
        (vec[V_HAS_HI] != 0) & (hb >= vec[V_HI_THR]),
        127, hb - vec[V_SB8],
    )
    keep_val = jnp.where(hb <= vec[V_KEEP_THR], -128, keep_val)
    new_hb = jnp.where(upd, up_val, keep_val)
    # every update writes (MEMBER, age 0): adds learn the entry, advances
    # refresh it — and refute it if it was SUSPECT.  (Suspicion off this
    # is the same bits as the old add/advance split: advance lanes were
    # already MEMBER.)
    base = jnp.where(upd, member - 128, asl)
    new_asl = jnp.where(base >= (age_clamp << 2) - 128, base, base + 4)
    refute = (advance & (st == suspect)) if suspect is not None else None
    return new_hb, new_asl, refute


# ---------------------------------------------------------------------------
# SWAR variants of the packed-byte stages (config.elementwise="swar").
#
# The widened formulations above give every int8 element its own i32 VPU
# slot — unavoidable for ORDERED compares per the round-5 Mosaic probes
# (i8/i16/bf16 ordered compares don't legalize), but 4x the slots the data
# needs.  The SWAR forms reinterpret the int8 blocks as i32 words of 4
# packed subjects (``pltpu.bitcast`` along the sublane axis — a register
# reinterpret on the TPU's (32, 128) int8 tile, not a shuffle) and run
# the same compares/selects with carry-safe bitwise word arithmetic
# (ops/swar.py): ~2x the ops per word, 1/4 the words — and no
# widen/narrow relayouts at the block edges.  Byte semantics are the
# widened path's mod-2^8 semantics exactly; parity is pinned by the
# swar-vs-lanes rr tests and the golden fuzz suite.  Masks travel as
# hmasks (0x80 per true byte) until a select needs full bytes.
# ---------------------------------------------------------------------------


def _rr_tick_view_swar(hb, asl, act_h, ref_h, vec, member, failed,
                       t_fail, t_cooldown, suspect=None, confirm_thr=0,
                       confirm_thr_hi=0, lh_h=None, send_h=None):
    """SWAR mirror of :func:`_rr_tick_packed` (diagonal-free chunks) plus
    the gossip-view encode, over packed words.

    The caller guarantees the diagonal does not cross this block (the
    in-band chunks run the widened path — the bump chain needs the
    per-byte eye mask and covers at most c_blk of N rows per stripe), so
    the whole bump chain drops out exactly as in the widened eye=None
    branch.  Returns (hb, asl', fail_h, enc) — ``enc`` the encoded view
    words (absent lanes 0xFF = -1), ``fail_h`` an hmask (the
    CONFIRMATIONS when ``suspect`` arms the fused SWIM lifecycle —
    see :func:`_rr_tick_packed`).  ``send_h``: optional per-row
    sends-this-round hmask (scenario slow-sender mute — a muted row's
    view lanes encode absent, its tick is untouched).  ``lh_h``: optional
    per-row degraded hmask (flags bit 4) selecting the Lifeguard-
    stretched ``confirm_thr_hi`` word — see :func:`_rr_tick_packed`.
    """
    st_bits = asl & swar.word(3)
    stm_h = swar.eq(st_bits, swar.word(member))
    nsent_h = swar.ne(hb, swar.H)
    if suspect is None:
        refresh_b = swar.to_bytes(ref_h & stm_h)
        # st_bits | H == word(member - 128) on the refreshed (MEMBER)
        # bytes — kept as the bit-op form (one OR, no select operand)
        asl = swar.sel(refresh_b, st_bits | swar.H, asl)
    else:
        sus_pre_h = swar.eq(st_bits, swar.word(suspect))
        refresh_b = swar.to_bytes(ref_h & (stm_h | sus_pre_h))
        # listed refreshers land at (MEMBER, age 0) — the SUSPECT ->
        # MEMBER small-group revert rides the same constant write
        asl = swar.sel(refresh_b, swar.word(member - 128), asl)
    past_h = swar.ges(hb, vec[V_THR_G]) & nsent_h
    stale_h = (
        act_h & stm_h & past_h
        & swar.gts(asl, swar.word(((t_fail << 2) | member) - 128))
    )
    if suspect is None:
        fail_h = stale_h
        asl = swar.sel(swar.to_bytes(fail_h), swar.word(failed - 128), asl)
        elig_h = stm_h & ~fail_h
    else:
        thr_w = swar.word(((confirm_thr << 2) | suspect) - 128)
        if lh_h is not None:
            # degraded rows take the stretched threshold word (flags are
            # row-uniform, so all 4 bytes of a word agree)
            thr_w = swar.sel(
                swar.to_bytes(lh_h),
                swar.word(((confirm_thr_hi << 2) | suspect) - 128), thr_w,
            )
        confirm_h = act_h & sus_pre_h & swar.gts(asl, thr_w)
        # member -> suspect: set status bit 1, age bits untouched (the
        # age lane IS the suspicion clock)
        asl = asl | (swar.to_bytes(stale_h) & swar.word(2))
        asl = swar.sel(swar.to_bytes(confirm_h), swar.word(failed - 128),
                       asl)
        fail_h = confirm_h
        elig_h = (stm_h | sus_pre_h) & ~fail_h
    expire_h = (
        swar.eq(asl & swar.word(3), swar.word(failed))
        & swar.gts(asl, swar.word(((t_cooldown << 2) | failed) - 128))
    )
    asl = swar.sel(swar.to_bytes(expire_h), asl & swar.word(0xFC), asl)
    goss_h = (
        elig_h & act_h
        & (swar.ges(hb, vec[V_SA_N]) | swar.ne(vec[V_SA_ALL], 0))
        & swar.les(hb, vec[V_HI_N])
        & nsent_h
    )
    if send_h is not None:
        goss_h = goss_h & send_h
    enc = swar.sel(swar.to_bytes(goss_h), swar.sub(hb, vec[V_SA_N]),
                   swar.word(0xFF))
    return hb, asl, fail_h, enc


def _rr_merge_swar(hb, asl, best, recv_b, vec, member, unknown, age_clamp,
                   suspect=None):
    """SWAR mirror of :func:`_rr_merge_packed` over packed words.

    ``recv_b`` is a full-byte receiver mask (uniform across a word's 4
    subjects); ``vec`` holds the per-subject threshold stack as packed
    words.  Byte adds/subs wrap mod 2^8 — the widened path's store-wrap
    (and its explicit ``_wrap8`` on ``lhs``) for free.  Returns
    (hb', asl', refute_b) — ``refute_b`` the full-byte SUSPECT -> MEMBER
    refutation mask (None when ``suspect`` is); the listed test under
    suspicion is one status-bit-0 word test (MEMBER=1 and SUSPECT=3 both
    carry it; the wrapper asserts the encoding).
    """
    st = asl & swar.word(3)
    anym_h = ~best & swar.H  # best >= 0: sign bit clear
    if suspect is None:
        listed_h = swar.eq(st, swar.word(member))
    else:
        listed_h = swar.ne(st & swar.L, 0)  # status bit 0: MEMBER|SUSPECT
    adv_b = recv_b & swar.to_bytes(
        listed_h & anym_h
        & swar.gts(best, vec[V_CMP_DEEP])
        & swar.gts(swar.add(best, vec[V_SA_N]), hb)
    )
    add_b = recv_b & swar.to_bytes(swar.eq(st, swar.word(unknown)) & anym_h)
    upd_b = adv_b | add_b
    up_val = swar.sel(swar.to_bytes(swar.les(best, vec[V_UP_DEEP])),
                      swar.H, swar.add(best, vec[V_D8]))
    keep_val = swar.sel(
        swar.to_bytes(swar.ne(vec[V_HAS_HI], 0) & swar.ges(hb, vec[V_HI_THR])),
        swar.word(127), swar.sub(hb, vec[V_SB8]),
    )
    keep_val = swar.sel(swar.to_bytes(swar.les(hb, vec[V_KEEP_THR])),
                        swar.H, keep_val)
    new_hb = swar.sel(upd_b, up_val, keep_val)
    # every update lands at (MEMBER, age 0) — adds learn, advances
    # refresh/refute (suspicion off: advance lanes are MEMBER already, so
    # the unified select is the same bits as the old add/advance split)
    base = swar.sel(upd_b, swar.word(member - 128), asl)
    new_asl = swar.sel(
        swar.to_bytes(swar.ges(base, swar.word((age_clamp << 2) - 128))),
        base, swar.add(base, swar.word(4)),
    )
    refute_b = (
        adv_b & swar.to_bytes(swar.eq(st, swar.word(suspect)))
        if suspect is not None else None
    )
    return new_hb, new_asl, refute_b


def _rr_kernel(
    n: int, n_fanout: int, r_blk: int, cs: int, chunk: int,
    member: int, unknown: int, failed: int, age_clamp: int,
    window: int, t_fail: int, t_cooldown: int, hb_min: int,
    arc: bool = False, resident: bool = False, unroll: int = 1,
    view_dt=jnp.int8, stub: frozenset = frozenset(),
    arc_rows: int = ARC_CHUNK, vslots: int = VSLOTS, arc_align: int = 1,
    rcnt_acc: bool = False, swar_mode: bool = False, ring: bool = False,
    flags_compact: bool = False, suspect: int | None = None,
    confirm_thr: int = 0, confirm_thr_hi: int = 0, lh_lane: bool = False,
    edge_filter: bool = False, *, nstripes: int,
):
    # swar_mode: run the elementwise stages over packed 4-subject words
    # (see the SWAR section above _rr_tick_view_swar).  The view-build
    # chunks that the diagonal crosses, and the non-resident receiver
    # sweep (whose tick needs the per-byte eye mask), stay on the widened
    # path — both formulations are bit-equal, so mixing is invisible.
    # nstripes is the GRID's stripe count — the local nc under column
    # sharding, where deriving it from the global n would be wrong (the
    # last-stripe count flush would never fire); callers pass it.
    # suspect (round 11): the fused SWIM lifecycle — suspect/confirm in
    # the tick stages, refute-on-advance in the merge stages, plus three
    # per-subject suspicion reductions (entered / refuted / held-SUSPECT)
    # accumulated exactly like ndet.  lh_lane (round 14): the Lifeguard
    # local-health lane — flags bit 4 marks degraded receivers (derived
    # outside from the carried per-receiver suspect counts), the confirm
    # threshold becomes a per-row two-value select (confirm_thr vs
    # confirm_thr_hi), and a per-RECEIVER post-merge SUSPECT count output
    # (scnt, accumulated exactly like the rcnt member counts — both
    # forms) feeds the NEXT round's degraded mask.  edge_filter: the
    # scenario-armed aligned-arc build — group maxes land in a FULL int8
    # T buffer (no W pass, no ring) and the per-receiver gather is an
    # nw-way masked max driven by the (base, group-match-bitmask) pairs
    # in the edges input; a dropped group contributes the absent encoding
    # (-1), the same value "no sender carried it" produces.
    nchunks = n // chunk
    nblocks = n // r_blk
    sus = suspect is not None
    # the "sus" stage stub (tools/stub_bisect.py) skips the suspicion
    # OBSERVABLE reductions (entered/refuted/held masks + their three
    # per-subject sums) while keeping the lifecycle transitions — its
    # delta vs the full run isolates the reduction cost; the full
    # suspicion-on-vs-off A/B (--suspicion) isolates transitions+all
    sus_red = sus and "sus" not in stub
    # aligned-arc mode never reads the view stripe (the gather consumes
    # the window maxes), so it is not materialized; any stub keeps the
    # real stripe so the bisect tool's stubbed paths stay valid
    no_stripe = arc and arc_align > 1 and not stub
    # ring-rotated aligned-arc geometry (see rr_align_scratch_specs):
    # groups per view-build chunk and the halo (window rows that straddle
    # a chunk boundary)
    if arc and arc_align > 1:
        nb_k = n // arc_align
        nw_k = n_fanout // arc_align
        hw_k = nw_k - 1
        gpc_k = chunk // arc_align

    mx = max(chunk, r_blk)
    # post-tick byte that identifies THIS round's MEMBER -> SUSPECT entry
    # (the clock is the age lane, so entry happens at age == t_fail + 1
    # exactly — ages advance by one per unrefreshed round and reset on
    # every refresh, so the value is hit once per episode)
    sus_new_byte = (((t_fail + 1) << 2) | (suspect or 0)) - 128

    def kernel(
        edges_ref, col0_ref, flags_all, vecs_ref, hb_any, as_any,
        hb_out, as_out, cnt_out, ndet_out, fobs_out, rcnt_out,
        nsus_out, nref_out, sus_out, *more,
    ):
        # the local-health lane appends one output (the per-receiver
        # suspect counts) between the fixed outputs and the scratch list
        more = list(more)
        scnt_out = more.pop(0) if lh_lane else None
        stripe, best_scratch, vbuf, vsems, dbuf, flbuf, *rest = more
        # resident mode parks the TICKED lanes in VMEM during the
        # view-build pass, so the receiver sweep touches no HBM at all —
        # the round's wire drops to the 4 N^2 information floor (read
        # once + write once) — and skips the tick recompute entirely: a
        # post-tick (st == FAILED, age == 0) byte can only mean THIS
        # round's detection (stored ages are always >= 1 — the epilogue
        # advances every age before store), so the sweep reconstructs the
        # fail mask with one compare.
        rest = list(rest)
        sacc = rest.pop() if (rcnt_acc and lh_lane) else None
        racc = rest.pop() if rcnt_acc else None
        if resident:
            hb_res, as_res, *arc_scratch = rest
        else:
            rbuf, rsems, *arc_scratch = rest
        # aligned-arc window scratch, by build (rr_align_scratch_specs'
        # layouts): edge-filter — one FULL int8 T (+ wrap halo rows);
        # ring-rotated — W first, then the fixed T ring + the wrap head;
        # full-T fallback — whole-stripe T, then W
        if arc and arc_align > 1:
            if edge_filter:
                tbuf8 = arc_scratch[0]
            elif ring:
                wbuf_a = arc_scratch[0]
                tring = arc_scratch[1] if hw_k else None
                thead = arc_scratch[2] if hw_k else None
            else:
                tbuf_a, wbuf_a = arc_scratch
        # The raw lanes arrive ONCE, in ANY memory space; every VMEM
        # crossing is an explicit software-pipelined DMA — BlockSpec-fetched
        # lane inputs measured ~3 ms/round slower here (Mosaic serializes
        # its own block copies against the kernel's manual DMAs, the same
        # effect the fused gather kernel hit in round 3), and passing the
        # lanes twice (BlockSpec + ANY) made XLA materialize three 0.8 ms
        # defensive copies per round.  The view-build chunks (vbuf) and the
        # receiver blocks (rbuf) ping-pong through SEPARATE buffers so the
        # first receiver block's DMA can be issued before the stripe's view
        # build and hide entirely under it (a shared buffer forced an
        # unpipelined reload after every view build).
        j = pl.program_id(0)
        i = pl.program_id(1)
        # global subject index of this program's first column: 0 single
        # chip; the shard's offset under subject-axis shard_map (rows stay
        # global, so the diagonal lives at row == global column)
        col0 = col0_ref[0, 0]
        # this stripe's per-subject threshold slab, (cs, LANE) rows widened
        # once per grid step — broadcasts against (rows, cs, LANE) blocks
        vec = [vecs_ref[k, 0].astype(jnp.int32) for k in range(N_VEC)]
        if swar_mode:
            # the same slab as packed words (register reinterpret along
            # the sublane axis) for the SWAR stages
            vecw = [pltpu.bitcast(vecs_ref[k, 0], jnp.int32)
                    for k in range(N_VEC)]

        # One-time iota scratch (first grid step): per-element iotas are
        # NOT hoisted by Mosaic out of the chunk loop — recomputing the
        # diagonal mask's two broadcasted iotas per block measured
        # ~1.4 ms/round at N=16k.  dbuf holds row - (local col), so the
        # diagonal test is one load + one compare against a per-block
        # scalar (the fobs reduction reuses it: min-reducing row - col
        # over rows and adding the column back on the reduced shape).
        @pl.when((j == 0) & (i == 0))
        def _():
            r0 = lax.broadcasted_iota(jnp.int32, (mx, cs, LANE), 0)
            cl = (lax.broadcasted_iota(jnp.int32, (mx, cs, LANE), 1) * LANE
                  + lax.broadcasted_iota(jnp.int32, (mx, cs, LANE), 2))
            dbuf[...] = r0 - cl

        def load_flags(start, size):
            # materialize the flag broadcast ONCE through scratch into
            # (size, cs, LANE): Mosaic otherwise re-runs the
            # sublane-broadcast relayout at every use (~1.6 ms/round).
            # Returns the raw int8 block; the widened path casts at the
            # use site, the SWAR path bitcasts to packed words (a word's
            # 4 bytes span the cs axis, where flags are uniform, so flag
            # words are the row's byte replicated — masks fall out of
            # plain word bit-tests)
            if flags_compact:
                # LANE-compacted layout [N/LANE, LANE]: size/LANE compact
                # rows reshape back to per-row bytes (lane -> sublane
                # relayout, the inverse of the count accumulator's) —
                # callers guarantee LANE-divisible start/size (the
                # wrapper's flags_compact gate)
                src = flags_all[pl.ds(start // LANE, size // LANE)].reshape(
                    size, 1, 1)
            else:
                src = flags_all[pl.ds(start, size)].reshape(size, 1, LANE)
            flbuf[pl.ds(0, size)] = jnp.broadcast_to(src, (size, cs, LANE))
            return flbuf[pl.ds(0, size)]

        def issue_into(buf, sems, blk_rows, rows_per, slot):
            rows = pl.ds(blk_rows * rows_per, rows_per)
            for li, lane in enumerate((hb_any, as_any)):
                pltpu.make_async_copy(
                    lane.at[j, rows], buf.at[slot, li], sems.at[slot, li]
                ).start()

        def wait_on(buf, sems, rows_per, slot):
            for li, lane in enumerate((hb_any, as_any)):
                pltpu.make_async_copy(
                    lane.at[j, pl.ds(0, rows_per)], buf.at[slot, li],
                    sems.at[slot, li],
                ).wait()

        issue = functools.partial(issue_into, vbuf, vsems)
        wait = functools.partial(wait_on, vbuf, vsems)
        if not resident:
            rissue = functools.partial(issue_into, rbuf, rsems)
            rwait = functools.partial(wait_on, rbuf, rsems)

        # --- i == 0: build this stripe's gossip view in VMEM ------------
        # chunked DMAs over the raw lanes, pipelined VSLOTS deep: at
        # depth 2 the per-chunk DMA latency (~2 us against a sub-us
        # transfer at narrow stripe widths) stayed exposed and serialized
        # the whole build — measured ~2-3 ms/round at c_blk <= 2048.
        # Chunks stay small (the widened tick temporaries scale with the
        # chunk and are what actually bound VMEM); only the in-flight
        # depth grows.  The tick is recomputed on each chunk so the view
        # reflects post-tick state.
        @pl.when(i == 0)
        def _():
            # this stripe's first receiver block rides under the view build
            if not resident:
                rissue(0, r_blk, 0)
            for c0 in range(min(vslots - 1, nchunks)):
                issue(c0, chunk, c0)

            def body(c, _):
                slot = lax.rem(c, vslots)

                @pl.when(c + vslots - 1 < nchunks)
                def _():
                    issue(c + vslots - 1, chunk,
                          lax.rem(c + vslots - 1, vslots))

                wait(chunk, slot)
                if "vtick" in stub:
                    if resident and "park" not in stub:
                        hb_res[pl.ds(c * chunk, chunk)] = vbuf[slot, 0]
                        as_res[pl.ds(c * chunk, chunk)] = vbuf[slot, 1]
                    stripe[pl.ds(c * chunk, chunk)] = (
                        vbuf[slot, 0].astype(stripe.dtype))
                    return 0

                def tick_view_swar():
                    # packed-word tick + view encode (diagonal-free
                    # chunks only — see _rr_tick_view_swar)
                    hbw = pltpu.bitcast(vbuf[slot, 0], jnp.int32)
                    aslw = pltpu.bitcast(vbuf[slot, 1], jnp.int32)
                    send_h = lh_h = None
                    if "noflags" in stub:
                        act_h = ref_h = jnp.int32(-1)
                    else:
                        flw = pltpu.bitcast(
                            load_flags(c * chunk, chunk), jnp.int32)
                        act_h = swar.ne(flw & swar.word(1), 0)
                        ref_h = swar.ne(flw & swar.word(2), 0)
                        if edge_filter:
                            # scenario mute (flag bit 3): the slow-sender
                            # rows send nothing this round
                            send_h = swar.eq(flw & swar.word(8), 0)
                        if lh_lane:
                            # Lifeguard degraded rows (flag bit 4)
                            lh_h = swar.ne(flw & swar.word(16), 0)
                    hbw, aslw, _fail, enc = _rr_tick_view_swar(
                        hbw, aslw, act_h, ref_h, vecw, member, failed,
                        t_fail, t_cooldown, suspect=suspect,
                        confirm_thr=confirm_thr,
                        confirm_thr_hi=confirm_thr_hi, lh_h=lh_h,
                        send_h=send_h,
                    )
                    if resident and "park" not in stub:
                        hb_res[pl.ds(c * chunk, chunk)] = pltpu.bitcast(
                            hbw, jnp.int8)
                        as_res[pl.ds(c * chunk, chunk)] = pltpu.bitcast(
                            aslw, jnp.int8)
                    if not no_stripe:
                        # enc bytes are the stored-wrapped values; widened
                        # stripes (cs < 32) get the same value the widened
                        # path's _wrap8 + astype produces
                        enc8 = pltpu.bitcast(enc, jnp.int8)
                        stripe[pl.ds(c * chunk, chunk)] = (
                            enc8 if view_dt == jnp.int8
                            else enc8.astype(stripe.dtype))
                    if arc and arc_align > 1 and "wmax" not in stub:
                        # aligned-arc group max on the packed words (byte
                        # max over WRAPPED encodings, as the widened path)
                        gw = enc.reshape(gpc_k, arc_align, cs // 4, LANE)
                        vals = [gw[:, t] for t in range(arc_align)]
                        while len(vals) > 1:
                            nxt = [swar.maxs(vals[m], vals[m + 1])
                                   for m in range(0, len(vals) - 1, 2)]
                            if len(vals) % 2:
                                nxt.append(vals[-1])
                            vals = nxt
                        gm8 = pltpu.bitcast(vals[0], jnp.int8)
                        if edge_filter:
                            # scenario build: group maxes land in the
                            # FULL int8 T — the masked gather reads them
                            # directly (no W precompute: the per-receiver
                            # window is filtered, so it cannot be shared)
                            tbuf8[pl.ds(c * gpc_k, gpc_k)] = gm8
                        elif ring and hw_k:
                            # ring build: this chunk's group maxes land at
                            # the FIXED ring position (rows [hw, hw+gpc));
                            # the W flush after the tick branches consumes
                            # them, so T never scales with N
                            tring[hw_k:hw_k + gpc_k] = gm8.astype(
                                tring.dtype)
                        elif ring:
                            # fanout == align: W[b] IS T[b] — straight to
                            # the gather buffer, no ring at all
                            wbuf_a[pl.ds(c * gpc_k, gpc_k)] = gm8
                        else:
                            tbuf_a[pl.ds(c * gpc_k, gpc_k)] = gm8.astype(
                                tbuf_a.dtype)

                def tick_view(eye):
                    sends = lh_r = None
                    if "noflags" in stub:
                        act_r = ref_r = jnp.bool_(True)
                    else:
                        flb = load_flags(c * chunk, chunk).astype(jnp.int32)
                        act_r = (flb & 1) != 0
                        ref_r = (flb & 2) != 0
                        if edge_filter:
                            sends = (flb & 8) == 0  # scenario mute bit
                        if lh_lane:
                            lh_r = (flb & 16) != 0  # Lifeguard degraded
                    hb = vbuf[slot, 0].astype(jnp.int32)
                    asl = vbuf[slot, 1].astype(jnp.int32)
                    hb, asl, _fail, stm = _rr_tick_packed(
                        hb, asl, act_r, ref_r, eye, vec[V_THR_G],
                        member, failed, t_fail, t_cooldown,
                        suspect=suspect, confirm_thr=confirm_thr,
                        confirm_thr_hi=confirm_thr_hi, lh_r=lh_r,
                    )
                    if resident and "park" not in stub:
                        # park the TICKED lanes: the receiver sweep reads
                        # them back without re-ticking (int8 store wraps —
                        # the narrow XLA path's mod-2^8 semantics)
                        hb_res[pl.ds(c * chunk, chunk)] = hb.astype(jnp.int8)
                        as_res[pl.ds(c * chunk, chunk)] = asl.astype(jnp.int8)
                    # the gossip view: active senders' MEMBER entries
                    # within the rebase window (core/rounds.py
                    # _gossip_view, narrow formulation, rounds.py:536-556);
                    # absent entries -1
                    goss = (
                        stm & act_r
                        & ((hb >= vec[V_SA_N]) | (vec[V_SA_ALL] != 0))
                        & (hb <= vec[V_HI_N])
                        & (hb != -128)
                    )
                    if sends is not None:
                        goss = goss & sends
                    rel = hb - vec[V_SA_N]
                    if view_dt != jnp.int8:
                        # the int8 store wraps for free; a widened stripe
                        # must wrap explicitly or deep-shift (sa_all)
                        # subjects store rel - 256 (round-5 review finding)
                        rel = _wrap8(rel)
                    enc = jnp.where(goss, rel, -1)
                    if not no_stripe:
                        stripe[pl.ds(c * chunk, chunk)] = enc.astype(
                            stripe.dtype)
                    if arc and arc_align > 1 and "wmax" not in stub:
                        # aligned-arc group max rides the view build: the
                        # encoded values are already live in registers, so
                        # the windowed row-max's whole-stripe re-read (and
                        # its O(log F) shift-doubling passes) never happens.
                        # The max must run over the WRAPPED int8 values the
                        # stripe would store (max-then-wrap != wrap-then-max
                        # for deep-shift subjects whose rel straddles the
                        # wrap) — for widened view dtypes rel is wrapped
                        # above.  The gather below reads ONLY the window
                        # maxes, so in aligned mode the stripe itself is
                        # write-only and is not materialized at all
                        # (no_stripe): that frees N x c_blk bytes of VMEM —
                        # the rr row bound drops to the window scratch —
                        # and deletes one full store pass from the view
                        # build
                        encw = _wrap8(enc) if view_dt == jnp.int8 else enc
                        gm = jnp.max(
                            encw.reshape(gpc_k, arc_align, cs, LANE), axis=1
                        )
                        if edge_filter:
                            # scenario build: full int8 T (see the SWAR
                            # branch's comment)
                            tbuf8[pl.ds(c * gpc_k, gpc_k)] = gm.astype(
                                tbuf8.dtype)
                        elif ring and hw_k:
                            # ring build (see the SWAR branch's comment)
                            tring[hw_k:hw_k + gpc_k] = gm.astype(
                                tring.dtype)
                        elif ring:
                            wbuf_a[pl.ds(c * gpc_k, gpc_k)] = gm.astype(
                                wbuf_a.dtype)
                        else:
                            tbuf_a[pl.ds(c * gpc_k, gpc_k)] = gm.astype(
                                tbuf_a.dtype)

                # the diagonal crosses this stripe only in the c_blk-row
                # band at its own columns: every other chunk skips the
                # eye compare and the whole bump chain (fail needs no
                # ~eye — see _rr_tick_packed's docstring)
                dlo = j * cs * LANE + col0
                base_row = c * chunk
                in_band = (base_row + chunk > dlo) & (base_row < dlo
                                                      + cs * LANE)
                if "noeye" in stub:
                    tick_view(None)
                else:
                    @pl.when(in_band)
                    def _():
                        tick_view(dbuf[pl.ds(0, chunk)] == dlo - base_row)

                    @pl.when(~in_band)
                    def _():
                        # the off-band bulk (nchunks - 1 or - 2 of nchunks)
                        # is where the SWAR density pays
                        if swar_mode:
                            tick_view_swar()
                        else:
                            tick_view(None)

                if (arc and arc_align > 1 and ring and hw_k
                        and "wmax" not in stub and "wring" not in stub):
                    # ring-rotated W flush: ring rows [0, hw) hold the
                    # PREVIOUS chunk's trailing group maxes (the carry),
                    # rows [hw, hw+gpc) this chunk's — every window row
                    # whose halo just completed flushes to W NOW, so the
                    # bf16 T data never outlives one chunk + halo.  The
                    # first chunk has no carry: it flushes its gpc - hw
                    # halo-free rows and saves its head for the mod-N
                    # wrap close after the loop.
                    @pl.when(c == 0)
                    def _():
                        thead[...] = tring[hw_k:2 * hw_k]
                        w = tring[pl.ds(hw_k, gpc_k - hw_k)]
                        for gg in range(1, nw_k):
                            w = jnp.maximum(
                                w, tring[pl.ds(hw_k + gg, gpc_k - hw_k)])
                        wbuf_a[pl.ds(0, gpc_k - hw_k)] = w.astype(
                            wbuf_a.dtype)

                    @pl.when(c > 0)
                    def _():
                        w = tring[pl.ds(0, gpc_k)]
                        for gg in range(1, nw_k):
                            w = jnp.maximum(w, tring[pl.ds(gg, gpc_k)])
                        wbuf_a[pl.ds(c * gpc_k - hw_k, gpc_k)] = w.astype(
                            wbuf_a.dtype)

                    # carry: this chunk's trailing hw group rows become
                    # the next chunk's leading halo (disjoint copy —
                    # rr_ring_supported guarantees gpc >= nw > hw)
                    tring[0:hw_k] = tring[pl.ds(gpc_k, hw_k)]
                return 0

            lax.fori_loop(0, nchunks, body, 0, unroll=False)
            if arc and arc_align > 1 and edge_filter and "wmax" not in stub:
                # close the mod-N wrap for the masked gather: the last
                # hw window positions read groups [nb, nb + hw)
                for gg in range(hw_k):
                    tbuf8[pl.ds(nb_k + gg, 1)] = tbuf8[pl.ds(gg, 1)]
            elif arc and arc_align > 1 and ring and "wmax" not in stub:
                if hw_k and "wring" not in stub:
                    # close the mod-N wrap: after the last chunk the ring
                    # carry rows [0, hw) hold T[nb-hw .. nb); appending
                    # the saved head (T[0 .. hw)) completes the final hw
                    # window rows — the only W rows whose windows straddle
                    # the stripe's wrap
                    tring[hw_k:2 * hw_k] = thead[...]
                    w = tring[pl.ds(0, hw_k)]
                    for gg in range(1, nw_k):
                        w = jnp.maximum(w, tring[pl.ds(gg, hw_k)])
                    wbuf_a[pl.ds(nb_k - hw_k, hw_k)] = w.astype(wbuf_a.dtype)
            elif arc and arc_align > 1 and "wmax" not in stub:
                # full-T fallback (chunks narrower than the halo — see
                # rr_ring_supported): the group maxes T are already in
                # tbuf (the view build wrote them).  One pair-max pass
                # over the N/align group rows finishes the F-window:
                # W[b] = max_{g < F/align} T[(b + g) mod nb]
                for g in range(nw_k - 1):
                    tbuf_a[pl.ds(nb_k + g, 1)] = tbuf_a[pl.ds(g, 1)]  # halo

                def wbody(c, _):
                    base = c * w_rows
                    w = tbuf_a[pl.ds(base, w_rows)]
                    for g in range(1, nw_k):
                        w = jnp.maximum(w, tbuf_a[pl.ds(base + g, w_rows)])
                    wbuf_a[pl.ds(base, w_rows)] = w.astype(wbuf_a.dtype)
                    return 0

                w_rows = min(nb_k, 256)
                while nb_k % w_rows:
                    w_rows //= 2
                lax.fori_loop(0, nb_k // w_rows, wbody, 0, unroll=False)
            elif arc and "wmax" not in stub:
                # arc senders are F consecutive rows: replace the stripe
                # with its windowed row-max once, so the per-receiver
                # merge below is ONE vector load instead of an F-way
                # scalar-issued gather (O(log F) vectorized passes,
                # amortized over every receiver)
                bufa, bufb, halo = arc_scratch
                _windowmax_inplace(stripe, bufa, bufb, halo, n_fanout,
                                   n // arc_rows, rows=arc_rows)

        # prefetch the NEXT receiver block while this one is gathered and
        # merged; the last block of a stripe prefetches nothing (the next
        # stripe's i == 0 step issues its own block 0 under the view build)
        if not resident:
            slot = lax.rem(i, 2)

            @pl.when(i + 1 < nblocks)
            def _():
                rissue(i + 1, r_blk, lax.rem(i + 1, 2))

        # --- every i: merge rows from the resident stripe ---------------
        # best accumulates widened (no narrow-int vector max on v5e) but
        # stores int8 — view values fit, and the narrower scratch frees
        # VMEM for bigger row blocks.  The loop handles ``unroll`` rows
        # per iteration with a TREE max per row: at narrow stripe widths
        # (c_blk 1024/2048) a one-row-per-iteration chain of F dependent
        # maxes left the VPU issue-bound — nc x N serial iterations was
        # exactly what sank the round-4 resident-lanes attempt — while
        # unrolled independent rows + log-depth maxes keep the load
        # pipeline full at every stripe width.
        # max in the stripe's own dtype where it is vector-maxable (int32 /
        # bf16 at the narrow tile-aligned widths); int8 widens (no narrow
        # vector max, and no ordered narrow compares either, on v5e)
        cd = jnp.int32 if view_dt == jnp.int8 else view_dt
        if arc and arc_align > 1 and edge_filter:
            shift = arc_align.bit_length() - 1

            def gather(t, _):
                # masked nw-way max over the group maxes: bit k of the
                # receiver's match mask keeps window group k (partition
                # rules at group granularity — the wrapper's caller
                # validated align-closed sides); a dropped group
                # contributes the absent encoding, exactly what "no
                # sender carried the entry" produces
                for k in range(unroll):
                    r = t * unroll + k
                    gidx = edges_ref[r, 0] >> shift
                    msk = edges_ref[r, 1]
                    vals = []
                    for w in range(nw_k):
                        v = tbuf8[gidx + w].astype(jnp.int32)
                        keep = (msk >> w) & 1 != 0
                        vals.append(jnp.where(keep, v, -1))
                    while len(vals) > 1:
                        nxt = [jnp.maximum(vals[m], vals[m + 1])
                               for m in range(0, len(vals) - 1, 2)]
                        if len(vals) % 2:
                            nxt.append(vals[-1])
                        vals = nxt
                    best_scratch[r] = vals[0].astype(best_scratch.dtype)
                return 0
        elif arc and arc_align > 1:
            shift = arc_align.bit_length() - 1
            wb = wbuf_a

            def gather(t, _):
                for k in range(unroll):
                    r = t * unroll + k
                    best_scratch[r] = wb[edges_ref[r, 0] >> shift].astype(
                        best_scratch.dtype)
                return 0
        elif arc:
            def gather(t, _):
                for k in range(unroll):
                    r = t * unroll + k
                    best_scratch[r] = stripe[edges_ref[r, 0]].astype(
                        best_scratch.dtype)
                return 0
        else:
            def gather(t, _):
                for k in range(unroll):
                    r = t * unroll + k
                    vals = [stripe[edges_ref[r, f]].astype(cd)
                            for f in range(n_fanout)]
                    while len(vals) > 1:
                        nxt = [jnp.maximum(vals[m], vals[m + 1])
                               for m in range(0, len(vals) - 1, 2)]
                        if len(vals) % 2:
                            nxt.append(vals[-1])
                        vals = nxt
                    best_scratch[r] = vals[0].astype(best_scratch.dtype)
                return 0

        if "gather" not in stub:
            lax.fori_loop(0, r_blk // unroll, gather, 0, unroll=False)

        # --- tick + merge epilogue on the receiver block ----------------
        flb8 = load_flags(i * r_blk, r_blk)
        if resident:
            rrows = pl.ds(i * r_blk, r_blk)
            raw_hb, raw_as = hb_res[rrows], as_res[rrows]
        else:
            rwait(r_blk, slot)
            raw_hb, raw_as = rbuf[slot, 0], rbuf[slot, 1]
        if "epi" in stub:
            hb_out[0] = raw_hb
            as_out[0] = raw_as
            rcnt_out[...] = jnp.zeros_like(rcnt_out)
            if lh_lane:
                scnt_out[...] = jnp.zeros_like(scnt_out)

            @pl.when(i == 0)
            def _():
                cnt_out[...] = jnp.zeros_like(cnt_out)
                ndet_out[...] = jnp.zeros_like(ndet_out)
                fobs_out[...] = jnp.zeros_like(fobs_out)
                nsus_out[...] = jnp.zeros_like(nsus_out)
                nref_out[...] = jnp.zeros_like(nref_out)
                sus_out[...] = jnp.zeros_like(sus_out)

            return
        if swar_mode and resident:
            # SWAR sweep: the parked lanes reinterpret as packed words, the
            # merge runs 4 subjects per op (_rr_merge_swar), and the
            # reduction masks come back as -1/0 bytes via one bitcast each.
            # (The non-resident sweep re-runs the tick, whose bump chain
            # needs the per-byte eye mask — it stays on the widened path.)
            hbw = pltpu.bitcast(raw_hb, jnp.int32)
            aslw = pltpu.bitcast(raw_as, jnp.int32)
            fail_h = swar.eq(aslw, swar.word(failed - 128))
            flw = pltpu.bitcast(flb8, jnp.int32)
            recv_b = swar.to_bytes(swar.ne(flw & swar.word(4), 0))
            bestw = pltpu.bitcast(best_scratch[...], jnp.int32)
            new_hbw, new_aslw, refute_b = _rr_merge_swar(
                hbw, aslw, bestw, recv_b, vecw, member, unknown, age_clamp,
                suspect=suspect,
            )
            hb_out[0] = pltpu.bitcast(new_hbw, jnp.int8)
            as_out[0] = pltpu.bitcast(new_aslw, jnp.int8)
            recv = (flb8 & 4) != 0  # int8 bit-test (native per the probes)
            if sus:
                listed_new = pltpu.bitcast(
                    swar.to_bytes(swar.ne(new_aslw & swar.L, 0)),
                    jnp.int8) != 0
                if lh_lane:
                    # per-receiver sums reduce over the subject axes, so
                    # the 0/1-word trick (byte lanes < 256) cannot apply
                    # — one byte-space mask, only on lh-armed runs
                    lh_held = pltpu.bitcast(
                        swar.to_bytes(swar.eq(new_aslw & swar.word(3),
                                              swar.word(suspect))),
                        jnp.int8) != 0
                if sus_red:
                    # 0/1-byte counter WORDS (hmask sign bit -> per-byte
                    # one): the suspicion sums below reduce these int32
                    # words directly — 1/4 the elements of the byte-space
                    # bool forms, and no byte-space mask materializes
                    sus_new = (swar.eq(aslw, swar.word(sus_new_byte))
                               >> 7) & swar.L
                    refute = refute_b & swar.L
                    held_sus = (swar.eq(new_aslw & swar.word(3),
                                        swar.word(suspect)) >> 7) & swar.L
            else:
                listed_new = pltpu.bitcast(
                    swar.to_bytes(swar.eq(new_aslw & swar.word(3),
                                          swar.word(member))), jnp.int8) != 0
            fail = pltpu.bitcast(swar.to_bytes(fail_h), jnp.int8) != 0
        else:
            flb = flb8.astype(jnp.int32)
            recv = (flb & 4) != 0
            if resident:
                # parked lanes are already ticked; (FAILED, age 0)
                # identifies this round's detections (see the parking
                # comment above)
                hb = raw_hb.astype(jnp.int32)
                asl = raw_as.astype(jnp.int32)
                fail = asl == failed - 128
            else:
                act_r = (flb & 1) != 0
                ref_r = (flb & 2) != 0
                eye = dbuf[pl.ds(0, r_blk)] == (j * cs * LANE + col0
                                                - i * r_blk)
                hb, asl, fail, _stm = _rr_tick_packed(
                    raw_hb.astype(jnp.int32), raw_as.astype(jnp.int32),
                    act_r, ref_r, eye, vec[V_THR_G],
                    member, failed, t_fail, t_cooldown,
                    suspect=suspect, confirm_thr=confirm_thr,
                    confirm_thr_hi=confirm_thr_hi,
                    lh_r=((flb & 16) != 0) if lh_lane else None,
                )

            best = best_scratch[...].astype(jnp.int32)
            new_hb, new_asl, refute = _rr_merge_packed(
                hb, asl, best, recv, vec, member, unknown, age_clamp,
                suspect=suspect,
            )
            hb_out[0] = new_hb.astype(hb_out.dtype)
            as_out[0] = new_asl.astype(as_out.dtype)
            st_new = new_asl & 3
            if sus:
                listed_new = (st_new == member) | (st_new == suspect)
                if lh_lane:
                    lh_held = st_new == suspect
                if sus_red:
                    # post-tick (SUSPECT, age == t_fail + 1) == entered
                    # THIS round (see sus_new_byte above)
                    sus_new = asl == sus_new_byte
                    held_sus = st_new == suspect
            else:
                listed_new = st_new == member

        # per-subject reductions, accumulated across consecutive i steps.
        # The membership tallies count LISTED entries — under suspicion a
        # SUSPECT entry is still in the list (pending refute/confirm), so
        # both the convergence count (cnt) and the per-receiver group-size
        # count (rc below) must keep it, exactly as the XLA _listed does.
        cnt_part = jnp.sum((recv & listed_new).astype(jnp.int32),
                           axis=0)[None]
        ndet_part = jnp.sum(fail.astype(jnp.int32), axis=0)[None]
        if sus_red:
            # suspicion observables, same accumulation pattern as ndet:
            # entered (post-tick newly-SUSPECT), refuted (merge advance on
            # a SUSPECT lane), held (post-merge SUSPECT anywhere — feeds
            # the first_suspect episode carry; NOT recv-gated: a dead
            # observer's frozen SUSPECT lane holds the episode open,
            # matching the XLA any(status == SUSPECT) reduction).
            # SWAR branch: the masks are 0/1-byte WORDS — summing int32
            # words over <= 128-row slices accumulates each byte lane
            # carry-free (counts <= 128 < 256), and ONE bitcast unpacks
            # the four byte-lane sums back to their subject positions
            # (the same transform the lane outputs use), so the whole
            # reduction touches 1/4 the elements and builds no byte-space
            # mask.  Widened branch: plain bool sums with the widen fused
            # into the reduce.  (The round-11 1.2x suspicion-overhead
            # budget lives or dies on this epilogue.)
            if swar_mode and resident:
                def _wsum(w):
                    part = None
                    for s0 in range(0, r_blk, 128):
                        sw = jnp.sum(w[s0:s0 + 128], axis=0)[None]
                        p = pltpu.bitcast(sw, jnp.int8).astype(
                            jnp.int32) & 255
                        part = p if part is None else part + p
                    return part

                nsus_part = _wsum(sus_new)
                nref_part = _wsum(refute)
                sus_part = _wsum(held_sus)
            else:
                nsus_part = jnp.sum(sus_new, axis=0,
                                    dtype=jnp.int32)[None]
                nref_part = jnp.sum(refute, axis=0,
                                    dtype=jnp.int32)[None]
                sus_part = jnp.sum(held_sus, axis=0,
                                   dtype=jnp.int32)[None]
        # min (row - col) over rows, column added back on the reduced
        # shape (one small iota) — avoids a full-block row iota
        dmin = jnp.min(jnp.where(fail, dbuf[pl.ds(0, r_blk)], n), axis=0)
        col_s = (lax.broadcasted_iota(jnp.int32, (cs, LANE), 0) * LANE
                 + lax.broadcasted_iota(jnp.int32, (cs, LANE), 1))
        fobs_part = jnp.where(
            jnp.any(fail, axis=0), dmin + col_s + i * r_blk, n
        )[None]
        # per-RECEIVER member count (next round's group-size input).
        # Default (rcnt_acc=False): per-stripe partials leave as an
        # [N, nc*LANE] block indexed (j, i) — every block written exactly
        # once, the write fully hidden under the compute-bound kernel
        # (the round-5 A/B that rejected accumulation at headline nc).
        # rcnt_acc=True (deep-stripe shapes, nc > RR_ACC_STRIPES): the
        # partials ACCUMULATE in a LANE-COMPACTED VMEM scratch
        # [N/LANE, LANE] — the (r_blk, 1) per-row sums relayout into
        # lanes (128 receivers per scratch row), so the accumulator is
        # 4 B/receiver instead of the lane-replicated form's 512 B (a
        # 67 MB VMEM hog at N=131,072 that blocked wide stripes) — and
        # the whole compact count block flushes once at the final grid
        # step.  At N=81,920/c_blk=512 (nc=160) the per-stripe form
        # would be a 3.4 GB int16 side output that cannot fit HBM
        # beside the lanes.
        # reductions stay >= 2-D throughout: a rank-1 intermediate here
        # crashes the TPU lowering (layout.h implicit_dim check)
        if "rcnt" in stub:
            rcnt_out[...] = jnp.zeros_like(rcnt_out)
            if lh_lane:
                scnt_out[...] = jnp.zeros_like(scnt_out)
        else:
            rpl = r_blk // LANE
            arows = pl.ds(i * rpl, rpl)

            def recv_count(mask, out_ref, acc_ref):
                """Per-receiver count of ``mask`` entries, in the same
                two output forms as the member counts (the rc block
                below IS this helper applied to listed_new)."""
                c = jnp.sum(mask.astype(jnp.int32), axis=2)
                c = jnp.sum(c, axis=1, keepdims=True)
                if not rcnt_acc:
                    # int16 output: a per-stripe partial <= cs*LANE <= 4096
                    out_ref[...] = jnp.broadcast_to(
                        c, (c.shape[0], LANE)
                    ).astype(out_ref.dtype)
                else:
                    c2 = c.reshape(rpl, LANE)  # sublane -> lane relayout

                    @pl.when(j == 0)
                    def _():
                        acc_ref[arows] = c2

                    @pl.when(j > 0)
                    def _():
                        acc_ref[arows] = acc_ref[arows] + c2

                    @pl.when((j == nstripes - 1) & (i == nblocks - 1))
                    def _():
                        out_ref[...] = acc_ref[...]

            recv_count(listed_new, rcnt_out, racc)
            if lh_lane:
                # the local-health lane's per-receiver suspect counts —
                # next round's degraded mask derives from these outside
                # the kernel (core/rounds._scan_rounds_rr_packed)
                recv_count(lh_held, scnt_out, sacc)

        @pl.when(i == 0)
        def _():
            cnt_out[...] = cnt_part
            ndet_out[...] = ndet_part
            fobs_out[...] = fobs_part
            if sus_red:
                nsus_out[...] = nsus_part
                nref_out[...] = nref_part
                sus_out[...] = sus_part
            else:
                nsus_out[...] = jnp.zeros_like(nsus_out)
                nref_out[...] = jnp.zeros_like(nref_out)
                sus_out[...] = jnp.zeros_like(sus_out)

        @pl.when(i > 0)
        def _():
            cnt_out[...] = cnt_out[...] + cnt_part
            ndet_out[...] = ndet_out[...] + ndet_part
            fobs_out[...] = jnp.minimum(fobs_out[...], fobs_part)
            if sus_red:
                nsus_out[...] = nsus_out[...] + nsus_part
                nref_out[...] = nref_out[...] + nref_part
                sus_out[...] = sus_out[...] + sus_part

    return kernel


def _recv_cnt_spec(n: int, r_blk: int, use_acc: bool) -> "pl.BlockSpec":
    """The per-receiver count output BlockSpec, shared by the member
    counts and the local-health lane's suspect counts (one owner, so the
    two forms cannot drift)."""
    if use_acc:
        return pl.BlockSpec((n // LANE, LANE), lambda j, i: (0, 0),
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec((r_blk, LANE), lambda j, i: (i, j),
                        memory_space=pltpu.VMEM)


@functools.partial(
    jax.jit,
    static_argnames=(
        "fanout", "member", "unknown", "failed", "age_clamp", "window",
        "t_fail", "t_cooldown", "block_r", "chunk", "interpret",
        "resident", "gather_unroll", "arc_align", "rcnt_acc", "elementwise",
        "rotate", "suspect", "t_suspect", "lh_multiplier", "edge_filter",
        "_stub",
    ),
)
def resident_round_blocked(
    edges: jax.Array,
    hb: jax.Array,
    asl: jax.Array,
    flags: jax.Array,
    sa: jax.Array,
    sb: jax.Array,
    g: jax.Array,
    *,
    fanout: int | None = None,
    member: int,
    unknown: int,
    failed: int,
    age_clamp: int,
    window: int,
    t_fail: int,
    t_cooldown: int,
    block_r: int = _FUSED_BLOCK_R,
    chunk: int = RR_CHUNK,
    interpret: bool = False,
    resident: bool = False,
    gather_unroll: int | None = None,
    col_offset: jax.Array | int = 0,
    arc_align: int = 1,
    rcnt_acc: bool | None = None,
    elementwise: str = "lanes",
    rotate: bool = True,
    suspect: int | None = None,
    t_suspect: int = 0,
    lh_multiplier: int = 0,
    edge_filter: bool = False,
    _stub: str = "",
) -> tuple[jax.Array, ...]:
    """One whole gossip round (lean crash-only fault model) in one kernel.

    ``resident=True`` additionally parks the raw lanes in VMEM during the
    view-build read, dropping the receiver sweep's HBM re-read: the round
    then moves exactly the 4 N^2-byte information floor (each packed lane
    read once, written once).  Requires
    :func:`rr_resident_supported` — 3 x N x c_blk bytes of VMEM.
    ``gather_unroll`` overrides the per-iteration row count of the merge
    gather (default: auto by stripe width).  ``elementwise``
    ("lanes" | "swar") picks the widened-i32 or the packed-4-subjects-
    per-word formulation of the tick/view/merge stages (see the SWAR
    section above :func:`_rr_tick_view_swar`).  Bit-identical outputs
    across all knobs (pinned by tests/test_merge_pallas.py).

    Contract (two int8 lanes per entry, STRIPE-MAJOR ``[nc, N, cs, LANE]``
    layout — ``blocked_shape`` transposed so each stripe's rows are
    contiguous — PRE-tick):

    * ``hb`` int8; ``asl`` the :func:`pack_age_status` byte — the kernel's
      whole HBM wire is 2 B/entry, which is what bounds the round on the
      bandwidth-shared chip.
    * ``edges`` int32 [N, F] in-edge sender ids (NOT remapped for dead
      receivers — the epilogue gates on the alive bit instead).  For the
      ``random_arc`` topology pass arc BASES int32 [N] plus ``fanout=F``:
      the kernel then window-maxes the view stripe once (O(log F)
      vectorized passes) and the per-receiver merge is a single load.
    * ``flags`` int8: bit 0 = active sender this round (alive & group >=
      min_group), bit 1 = small-group refresher, bit 2 = alive, bit 3 =
      scenario sender mute (edge_filter runs), bit 4 = Lifeguard-degraded
      receiver (lh_multiplier > 0 runs — derived per round from the
      carried per-receiver suspect counts; the confirm threshold is then
      a per-row select between t_fail + t_suspect and t_fail + t_suspect
      * (1 + lh_multiplier)).  Derived per round from the carried member
      counts.  Two accepted layouts:
      LANE-COMPACTED [N/LANE, LANE] row-major (1 B/row — what capacity
      callers pass) or lane-replicated [N, LANE] (legacy); the wrapper
      converts to whichever layout the blocking admits (compact needs
      LANE-divisible view chunks and receiver blocks —
      :func:`rr_flags_compact_ok`).
    * ``rotate`` (default True) enables the ring-rotated aligned-arc
      view build + the compacted flags layout — the row-budget layouts
      that lift the aligned rr past ~367k rows at c_blk=512.
      ``rotate=False`` restores the round-5 full-T/replicated layouts
      (the on-chip probe fallback, and the A/B baseline for tests).
    * ``sa``/``sb``/``g`` int32 per-subject vectors in the blocked
      [nc, cs, LANE] form: view shift (view_base - hb_base), store shift
      (new_base - hb_base) and grace threshold (hb_grace - hb_base).
    * statics: the protocol constants; ``window`` is the int8 rebase window.

    Returns (hb', asl', member_cnt [nc,cs,LANE], n_det, first_obs,
    recv_cnt — per-receiver member counts, in one of two forms:
    [N, nc*LANE] lane-replicated per-stripe partials (default,
    nc <= RR_ACC_STRIPES; reduce with
    ``recv_cnt.reshape(n, -1).sum(1) // LANE``) or [N/LANE, LANE]
    LANE-COMPACTED stripe-complete counts (deep-stripe shapes,
    accumulated in VMEM at 4 B/receiver; ``recv_cnt.reshape(n)`` IS the
    count vector; ``rcnt_acc`` overrides the choice).  The counts feed
    the NEXT round's active/refresher split (carried by the scan — the
    member-count XLA pass is gone too).

    ``lh_multiplier > 0`` (with ``suspect`` armed) appends ONE more
    output: ``suspect_cnt`` — the per-receiver count of post-merge
    SUSPECT entries, in exactly ``recv_cnt``'s two forms — which the
    scan carries to derive the next round's flags-bit-4 degraded mask
    (the Lifeguard local-health stretch, fully fused since round 14).
    """
    nc, n, cs, _ = hb.shape
    arc = fanout is not None
    if not arc:
        fanout = edges.shape[1]
    elif edges.ndim == 1:
        edges = edges.reshape(n, 1)
    if suspect is not None:
        # the fused lifecycle's bit tricks assume the core/state.py
        # encoding (status bit 0 == listed; member -> suspect is one bit)
        if (member, suspect, unknown, failed) != (1, 3, 0, 2):
            raise ValueError(
                "fused suspicion needs the (UNKNOWN, MEMBER, FAILED, "
                "SUSPECT) == (0, 1, 2, 3) status encoding"
            )
        if not 1 <= t_suspect or t_fail + t_suspect >= age_clamp:
            raise ValueError(
                f"t_suspect must be >= 1 with t_fail + t_suspect < "
                f"age_clamp ({age_clamp}); the age lane is the suspicion "
                f"clock (got t_fail={t_fail}, t_suspect={t_suspect})"
            )
        if lh_multiplier and (
            t_fail + t_suspect * (1 + lh_multiplier) >= age_clamp
        ):
            raise ValueError(
                f"t_fail + t_suspect * (1 + lh_multiplier) must be < "
                f"age_clamp ({age_clamp}); the stretched confirm window "
                f"rides the same age-lane clock (got t_fail={t_fail}, "
                f"t_suspect={t_suspect}, lh_multiplier={lh_multiplier})"
            )
    elif lh_multiplier:
        raise ValueError(
            "lh_multiplier > 0 (the Lifeguard local-health lane) "
            "requires the fused SWIM lifecycle (suspect=...)"
        )
    lh_lane = suspect is not None and lh_multiplier > 0
    if edge_filter:
        if not arc or arc_align <= 1:
            raise ValueError(
                "edge_filter (the scenario-armed masked gather) requires "
                "the aligned-arc topology; explicit-edge scenarios rewrite "
                "the sampled [N, F] edges instead (scenarios/tensor.py)"
            )
        if fanout // arc_align > ARC_MATCH_MAX_GROUPS:
            raise ValueError(
                "edge_filter packs the per-receiver group-match mask into "
                f"an int32: fanout/arc_align must be <= "
                f"{ARC_MATCH_MAX_GROUPS} (got {fanout // arc_align})"
            )
        if edges.shape != (n, 2):
            raise ValueError(
                f"edge_filter expects [N, 2] (base, match-mask) edges, "
                f"got {edges.shape}"
            )
    if hb.dtype != jnp.int8:
        raise ValueError("resident round kernel requires int8 lanes")
    if elementwise not in ("lanes", "swar"):
        raise ValueError(f"unknown elementwise: {elementwise!r}")
    if elementwise == "swar" and cs % 4:
        raise ValueError(
            f"elementwise='swar' packs 4 subjects per word along the "
            f"sublane axis and needs cs % 4 == 0 (got cs={cs})"
        )
    if arc and n % ARC_CHUNK:
        raise ValueError(f"arc resident round needs N % {ARC_CHUNK} == 0")
    if arc_align > 1:
        if not arc:
            raise ValueError("arc_align > 1 requires the arc topology")
        if arc_align & (arc_align - 1) or fanout % arc_align or n % arc_align:
            raise ValueError(
                "arc_align must be a power of two dividing fanout and n "
                f"(align={arc_align}, fanout={fanout}, n={n})"
            )
    if not rr_supported(n, fanout, cs * LANE, nc * cs * LANE,
                        arc_align if (arc and not _stub) else 1,
                        block_r=block_r, rotate=rotate):
        raise ValueError(
            f"resident round kernel needs lane-aligned N, cs*LANE in "
            f"{RR_BLOCK_CS} and its VMEM row cost within "
            f"{STRIPE_MAX_BYTES} B "
            f"(N={n}, blocked cols={cs * LANE}); use the stripe/XLA path"
        )
    # aligned-arc window scratch is counted against the resident budget
    # so near-boundary shapes fail with THIS error, not a late Mosaic
    # VMEM allocation failure; the same math backs rr_resident_supported,
    # so config-time validation agrees
    align_bytes = rr_align_scratch_bytes(
        n, fanout, cs * LANE, arc_align if arc else 1,
        resident=resident, rotate=rotate)
    if resident and not rr_resident_supported(
            n, fanout, cs * LANE, nc * cs * LANE,
            arc_align=arc_align if arc else 1,
            block_r=block_r, rotate=rotate):
        raise ValueError(
            f"resident lanes need 3*N*c_blk <= {RR_RESIDENT_MAX_BYTES} B "
            f"(+ {align_bytes} B aligned-arc scratch within "
            f"{RR_RESIDENT_ALIGN_BUDGET} B total) of VMEM "
            f"(N={n}, c_blk={cs * LANE})"
        )
    # the view-build chunk comes from the SAME derivation the budget
    # helpers use (rr_view_chunk: the resident VMEM cap, n-divisibility
    # halving, whole-groups arc floor) — one definition, no drift
    ch = rr_view_chunk(n, cs * LANE, resident=resident, chunk=chunk,
                       arc_align=arc_align)
    if arc_align > 1 and (ch % arc_align or n % ch):
        raise ValueError(
            f"arc_align={arc_align} incompatible with view-build "
            f"chunk {ch} at n={n}"
        )
    # pipeline depth: deep at narrow chunk DMAs (sub-us transfers whose
    # latency a 2-slot ping-pong left exposed); 2 slots at c_blk=4096,
    # where chunks are ~1 MB and the deep buffers crowd VMEM instead
    vslots = VSLOTS if (resident or cs < 32) else 2
    r_blk = _rr_block_rows(n, block_r)
    # auto gather unroll: one iteration should cover ~a native-tile's worth
    # of sublanes — 4 rows at c_blk=1024, 2 at 2048, 1 at 4096
    u = gather_unroll if gather_unroll else max(1, 4096 // (cs * LANE))
    while r_blk % u:
        u //= 2
    hb_min = int(jnp.iinfo(jnp.int8).min)

    # ring-rotated aligned-arc view build: on whenever rotate and the
    # chunk covers the window halo (every production shape); the full-T
    # build is the fallback — and the rotate=False A/B baseline
    ring = (rotate and arc and arc_align > 1 and not edge_filter
            and rr_ring_supported(fanout, arc_align, ch))
    # flags layout: LANE-compacted whenever every in-kernel slice covers
    # whole compact rows (the same gate the budget math charges by); the
    # wrapper converts from whichever layout the caller passed (both are
    # cheap [N]-scale XLA ops)
    flags_compact = rotate and rr_flags_compact_ok(
        n, cs * LANE, block_r=block_r, resident=resident, chunk=chunk,
        arc_align=arc_align)
    if flags.shape == (n, LANE):
        if flags_compact:
            flags = flags[:, 0].reshape(n // LANE, LANE)
    elif n % LANE == 0 and flags.shape == (n // LANE, LANE):
        if not flags_compact:
            flags = jnp.broadcast_to(flags.reshape(n, 1), (n, LANE))
    else:
        raise ValueError(
            f"flags must be [N, {LANE}] (replicated) or [N/{LANE}, {LANE}] "
            f"(LANE-compacted), got {flags.shape} at N={n}"
        )

    # Tile-aligned view stripe: int8's native tile is (32, 128) sublanes x
    # lanes, so at narrow stripe widths (cs < 32) every per-row gather load
    # straddles a tile and Mosaic lowers it as a slow per-load sublane
    # rotate — the round-4 "scalar-issued gather" that sank narrow-stripe
    # throughput.  Widening the stripe element to the dtype whose native
    # tile height equals cs (int32 at cs=8, bf16 at cs=16 — both have
    # native vector max, and the int8 view range [-1, 126] is exact in
    # either) makes each row exactly one aligned tile.  The widened stripe
    # costs the same VMEM as the c4096 int8 stripe; fall back to int8 when
    # it cannot fit (the N=65,536 capacity frontier, where VMEM is the
    # constraint and the gather penalty is accepted).
    if cs >= 32:
        view_dt, vbytes = jnp.int8, 1
    elif cs == 16:
        view_dt, vbytes = jnp.bfloat16, 2
    else:
        view_dt, vbytes = jnp.int32, 4
    resident_extra = 2 * n * cs * LANE if resident else 0
    if n * cs * LANE * vbytes + resident_extra > RR_RESIDENT_MAX_BYTES:
        view_dt, vbytes = jnp.int8, 1

    # aligned-arc mode materializes no view stripe (matches the kernel
    # factory's decision; any stub keeps the real stripe for the bisect
    # tool)
    no_stripe = arc and arc_align > 1 and not _stub

    # per-receiver count output form: per-stripe partial blocks by default
    # (the write hides under the compute-bound kernel — round-5 A/B), the
    # lane-compacted in-kernel accumulator at deep stripe counts, where
    # the per-stripe side output grows with nc and stops fitting HBM
    # beside the lanes (N=81,920 at c_blk=512: nc=160 -> 3.4 GB int16).
    # compact accumulated counts are full per-receiver counts (<= N):
    # always int32; the per-stripe partials (<= cs*LANE <= 4096) ship int16
    use_acc = rcnt_acc if rcnt_acc is not None else nc > RR_ACC_STRIPES
    cnt_dt = jnp.int32 if use_acc else jnp.int16
    if use_acc and (r_blk % LANE or n % LANE):
        raise ValueError(
            f"accumulated count form needs LANE-divisible block_r and N "
            f"(block_r={r_blk}, N={n})"
        )

    # per-subject int8 threshold stack for the packed in-kernel arithmetic
    # (see the module comment above _rr_tick_packed); the int8 casts wrap
    # mod 2^8 — exactly the narrow XLA formulation's casts
    if unknown != 0 or not (0 <= member <= 3 and 0 <= failed <= 3):
        raise ValueError(
            "packed-int8 rr kernel needs UNKNOWN == 0 and 2-bit statuses"
        )
    i8 = jnp.int8
    sa32 = sa.astype(jnp.int32)
    sb32 = sb.astype(jnp.int32)
    g32 = g.astype(jnp.int32)
    d32 = sa32 - sb32
    vecs = jnp.stack([
        sa32.astype(i8),                                # V_SA_N (wraps)
        (sa32 < -128).astype(i8),                       # V_SA_ALL
        jnp.clip(sa32 + window, -128, 127).astype(i8),  # V_HI_N
        jnp.clip(g32 + 1, -128, 127).astype(i8),        # V_THR_G
        jnp.clip(-129 - sa32, -2, 127).astype(i8),      # V_CMP_DEEP
        d32.astype(i8),                                 # V_D8 (wraps)
        jnp.clip(-129 - d32, -2, 127).astype(i8),       # V_UP_DEEP
        jnp.clip(sb32 - 129, -128, 127).astype(i8),     # V_KEEP_THR
        jnp.clip(sb32 + 128, -128, 127).astype(i8),     # V_HI_THR
        (sb32 < 0).astype(i8),                          # V_HAS_HI
        sb32.astype(i8),                                # V_SB8 (wraps)
    ])

    # stripe-major lane layout [nc, N, cs, LANE]: a stripe's rows are one
    # contiguous region, so every lane DMA block and output block is a
    # single contiguous transfer (the receiver-major layout's 4 KB-strided
    # rows bounded the kernel at ~220 GB/s effective)
    lane_blk = pl.BlockSpec((1, r_blk, cs, LANE), lambda j, i: (j, i, 0, 0),
                            memory_space=pltpu.VMEM)
    subj_spec = pl.BlockSpec(
        (1, cs, LANE), lambda j, i: (j, 0, 0), memory_space=pltpu.VMEM
    )
    ew = (2 if edge_filter else 1) if arc else fanout
    # window-max chunk rows scale down at wide stripes so the bf16
    # ping-pong buffers stay ~2 MB (17 MB at c_blk=4096 otherwise — they
    # crowded out the round-5 iota/flag scratches)
    arc_rows = max(256, ARC_CHUNK * 1024 // (cs * LANE))
    while arc and arc_rows < fanout - 1:
        arc_rows *= 2  # halo rows must fit inside the next chunk
    while n % arc_rows:
        arc_rows //= 2
    ext = arc_rows + fanout - 1
    if arc and arc_align > 1:
        # tile-aligned arc window scratch, allocated from the SAME spec
        # function the budget math sums (rr_align_scratch_specs — the
        # scratch-budget lint reconciles the two): ring-rotated W + fixed
        # T ring + wrap head by default, the full-T + W fallback when the
        # chunk cannot cover the halo.  The chunked view build must emit
        # whole groups per chunk.
        arc_scratch = rr_align_scratch_specs(
            n, fanout, cs * LANE, arc_align, chunk=ch, rotate=ring,
            edge_filter=edge_filter)
    elif arc:
        arc_scratch = [
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((ext, cs, LANE), jnp.bfloat16),
            pltpu.VMEM((fanout - 1, cs, LANE), view_dt),  # stripe-dtype halo
        ]
    else:
        arc_scratch = []
    if resident:
        # parked raw lanes replace the receiver-block ping-pong: the sweep
        # reads VMEM only
        rblock_scratch = [
            pltpu.VMEM((n, cs, LANE), jnp.int8),
            pltpu.VMEM((n, cs, LANE), jnp.int8),
        ]
    else:
        rblock_scratch = [
            pltpu.VMEM((2, 2, r_blk, cs, LANE), jnp.int8),
            pltpu.SemaphoreType.DMA((2, 2)),
        ]
    out = pl.pallas_call(
        _rr_kernel(n, fanout, r_blk, cs, ch, member, unknown, failed,
                   age_clamp, window, t_fail, t_cooldown, hb_min, arc=arc,
                   resident=resident, unroll=u, view_dt=view_dt,
                   stub=frozenset(s for s in _stub.split(",") if s),
                   arc_rows=arc_rows, vslots=vslots, arc_align=arc_align,
                   rcnt_acc=use_acc, swar_mode=elementwise == "swar",
                   ring=ring, flags_compact=flags_compact, suspect=suspect,
                   confirm_thr=t_fail + t_suspect,
                   confirm_thr_hi=t_fail + t_suspect * (1 + lh_multiplier),
                   lh_lane=lh_lane, edge_filter=edge_filter,
                   nstripes=nc),
        grid=(nc, n // r_blk),
        # in-place lane update: safe because every [row-block, stripe]
        # region's reads (the i==0 view-build chunk pass and the one-step-
        # early receiver prefetch) strictly precede its own step's output
        # write, and stripes never overlap.  Kills the defensive copies XLA
        # otherwise inserts for custom-call operands that are also scan
        # carries (~2.5 ms/round) and drops two [N, N] lane buffers from
        # peak HBM
        input_output_aliases={4: 0, 5: 1},
        in_specs=[
            pl.BlockSpec((r_blk, ew), lambda j, i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda j, i: (0, 0),
                         memory_space=pltpu.SMEM),   # global column offset
            pl.BlockSpec((n // LANE, LANE) if flags_compact else (n, LANE),
                         lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),   # flags (resident)
            pl.BlockSpec((N_VEC, 1, cs, LANE), lambda j, i: (0, j, 0, 0),
                         memory_space=pltpu.VMEM),   # threshold stack
            pl.BlockSpec(memory_space=pl.ANY),   # hb       (manual DMAs)
            pl.BlockSpec(memory_space=pl.ANY),   # age|status packed
        ],
        out_specs=[
            lane_blk, lane_blk,
            subj_spec, subj_spec, subj_spec,
            # per-receiver counts: per-stripe partial blocks (default),
            # or — accumulated form — the whole LANE-COMPACTED count
            # block (N/LANE rows: 4 B/receiver, small enough to stay
            # resident for the entire grid), written once at the final
            # step from the compact accumulator
            _recv_cnt_spec(n, r_blk, use_acc),
            # suspicion reductions (round 11): suspects entered, refuted,
            # and held-SUSPECT per subject — zeros when suspicion is off
            subj_spec, subj_spec, subj_spec,
        ] + (
            # the local-health lane's per-receiver suspect counts, in
            # exactly the recv_cnt forms (round 14)
            [_recv_cnt_spec(n, r_blk, use_acc)] if lh_lane else []),
        out_shape=[
            jax.ShapeDtypeStruct((nc, n, cs, LANE), jnp.int8),
            jax.ShapeDtypeStruct((nc, n, cs, LANE), jnp.int8),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct(
                (n // LANE, LANE) if use_acc else (n, nc * LANE), cnt_dt),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
            jax.ShapeDtypeStruct((nc, cs, LANE), jnp.int32),
        ] + ([jax.ShapeDtypeStruct(
            (n // LANE, LANE) if use_acc else (n, nc * LANE), cnt_dt)]
            if lh_lane else []),
        scratch_shapes=[
            # aligned-arc mode never reads the stripe (write-only): a
            # token allocation keeps the kernel signature; the real
            # window data lives in the T/W arc scratch
            pltpu.VMEM((8 if no_stripe else n, cs, LANE), view_dt),
            pltpu.VMEM((r_blk, cs, LANE), jnp.int8),      # best (narrow)
            # view-build chunk pipeline, then the one-time iota scratch
            # (diagonal delta) and the materialized flag broadcast, then
            # either the receiver-block ping-pong (non-resident) or the
            # parked ticked lanes (resident)
            pltpu.VMEM((vslots, 2, ch, cs, LANE), jnp.int8),
            pltpu.SemaphoreType.DMA((vslots, 2)),
            pltpu.VMEM((max(ch, r_blk), cs, LANE), jnp.int32),  # dbuf
            pltpu.VMEM((max(ch, r_blk), cs, LANE), jnp.int8),   # flbuf
        ] + rblock_scratch + arc_scratch + (
            # the accumulated form's LANE-COMPACTED count scratch
            # (persists across the whole grid; flushed at the final step)
            # — doubled when the local-health lane accumulates suspect
            # counts the same way (racc first, then sacc)
            [pltpu.VMEM((n // LANE, LANE), cnt_dt)]
            * ((1 + int(lh_lane)) if use_acc else 0)),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=126 * 1024 * 1024),
        interpret=interpret,
    )(edges, jnp.asarray(col_offset, jnp.int32).reshape(1, 1), flags, vecs,
      hb, asl)
    return tuple(out)


def fanout_max_merge_xla(view: jax.Array, edges: jax.Array) -> jax.Array:
    """Reference XLA formulation of the same op (gather + running max).

    Used on CPU, for unsupported shapes, and as the oracle the kernel is
    tested against.
    """
    def body(f, best):
        k = lax.dynamic_index_in_dim(edges, f, axis=1, keepdims=False)
        return jnp.maximum(best, view[k, :])

    init = jnp.full(view.shape, -1, dtype=view.dtype)
    return lax.fori_loop(0, edges.shape[1], body, init)


def arc_window_max_xla(view: jax.Array, bases: jax.Array, fanout: int) -> jax.Array:
    """XLA formulation of the arc merge: shift-doubling windowed row-max
    plus ONE row gather — F-independent traffic, identical results to
    ``fanout_max_merge_xla`` over the expanded arc edges.

    The workhorse for arc topologies off the TPU fast path (CPU runs, the
    sharded virtual-mesh correctness runs at 100k-class N, where the F-way
    gather's F x N^2 bytes are prohibitive).  Works on 2-D [N, C] and
    blocked [N, nc, cs, LANE] views alike (axis 0 is always the row).
    """
    n = view.shape[0]
    ext = jnp.concatenate([view, view[: fanout - 1]], axis=0)  # row wrap
    p = 1 << (fanout.bit_length() - 1)  # largest power of two <= fanout
    length = n + fanout - 1
    s = 1
    while s < p:
        # after the step with shift s, ext[r] = max over rows r..r+2s-1
        ext = jnp.maximum(ext[: length - s], ext[s:length])
        length -= s
        s *= 2
    if p == fanout:
        w = ext[:n]
    else:
        # two overlapping p-windows cover the F-window exactly (max is
        # idempotent): W[r] = max(D_p[r], D_p[r + F - p])
        w = jnp.maximum(ext[:n], ext[fanout - p:fanout - p + n])
    return w[bases]


def arc_group_window_max_xla(
    view: jax.Array, edges2: jax.Array, fanout: int, align: int
) -> jax.Array:
    """Scenario-filtered aligned-arc merge, XLA formulation (round 11).

    The per-edge drop form the scenario engine needs does not exist for
    arcs (the senders are F consecutive rows merged through a window
    max), but ALIGNED arcs decompose into ``F/align`` whole groups — so a
    partition whose sides are align-group-closed drops senders at GROUP
    granularity, which is exactly per-edge granularity (every edge of a
    group shares the drop verdict).  ``edges2`` int32 [N, 2] carries
    (arc base, match bitmask): bit k keeps window group k
    (scenarios.tensor.arc_match_edges builds it).  A dropped group
    contributes the absent encoding (-1) — the same value "no sender
    carried the entry" produces, so the merge epilogue is unchanged.

    This is the oracle the rr kernel's ``edge_filter`` mode is pinned
    against; per-edge equivalence (group-closed sides) is pinned by the
    explicit-edge cross-check in tests/test_scenarios.py.
    """
    n = view.shape[0]
    nb, nw = n // align, fanout // align
    rest = view.shape[1:]
    gm = jnp.max(view.reshape((nb, align) + rest), axis=1)
    ext = jnp.concatenate([gm, gm[:max(nw - 1, 1)]], axis=0)  # wrap halo
    bases, mask = edges2[:, 0], edges2[:, 1]
    g = bases // align
    absent = jnp.asarray(-1, view.dtype)
    best = None
    for k in range(nw):
        v = ext[g + k]
        keep = (((mask >> k) & 1) != 0).reshape((n,) + (1,) * len(rest))
        v = jnp.where(keep, v, absent)
        best = v if best is None else jnp.maximum(best, v)
    return best
