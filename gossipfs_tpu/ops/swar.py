"""SWAR (SIMD-within-a-register) int8 arithmetic over packed int32 words.

The round's elementwise work — threshold compares, status selects, age
advance — runs over all-int8 lanes, but the v5e VPU exposes ordered
compares only at i32 width (BASELINE.md round-5 Mosaic probes: int8
vectors support bitwise + equality only; int16 adds legalize but ordered
compares don't).  The lanes formulation therefore widens every int8
element to its own i32 slot: one subject per VPU lane, 4x the register
pressure the data needs.  This module implements the same per-byte
semantics on WORDS of four packed int8 subjects using carry-safe bitwise
arithmetic (Hacker's Delight ch. 2/6 style), so each ordered compare,
select, and wrap-around add touches 4 subjects per i32 op.

Conventions:

* A "word" is an int32 carrying 4 independent int8 lanes (bytes,
  little-endian: byte 0 = lowest subject index of the group).
* An "hmask" is a word whose bytes are 0x80 (true) / 0x00 (false) — the
  natural output of the compare primitives.  hmasks compose with
  ``&``/``|``/``~...&H``; expand to a full-byte mask (0xFF/0x00) with
  :func:`to_bytes` only when a select needs it.
* All byte arithmetic WRAPS mod 2^8 — exactly the semantics of the
  narrow (int8-stored) XLA formulation in core/rounds.py, whose adds and
  subs wrap on the int8 store and whose compares read sign-extended
  bytes.  Bit-equality per byte is pinned exhaustively (all 256 x 256
  operand pairs) by tests/test_swar.py.

Two packing layouts share this word math:

* The XLA paths (core/rounds.py) pack along the MINOR (subject) axis via
  :func:`pack` / :func:`unpack` (``lax.bitcast_convert_type`` over
  trailing groups of 4).
* The pallas resident-round kernel packs along the SUBLANE axis via
  ``pltpu.bitcast`` (ops/merge_pallas.py), which matches the TPU's
  physical int8 tile packing so the bitcast is a register reinterpret,
  not a shuffle.  The word math is packing-agnostic: bytes never
  interact across lanes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def word(b: int) -> int:
    """The Python int32 value whose 4 bytes all equal ``b`` (mod 256)."""
    v = (b & 0xFF) * 0x01010101
    return v - (1 << 32) if v >= (1 << 31) else v


H = word(0x80)    # per-byte sign bits
L = word(0x01)    # per-byte ones
B7F = word(0x7F)  # per-byte low-7 mask (~H)

# single-byte select masks, index k = byte k of the word (int32-safe)
BYTE = (0x000000FF, 0x0000FF00, 0x00FF0000, -16777216)


def pack(x: jnp.ndarray) -> jnp.ndarray:
    """int8 [..., 4k] -> int32 words [..., k] (byte i = element 4w+i)."""
    if x.dtype != jnp.int8:
        raise ValueError(f"pack expects int8, got {x.dtype}")
    if x.shape[-1] % 4:
        raise ValueError(f"pack needs a minor axis % 4 == 0, got {x.shape}")
    g = x.reshape(*x.shape[:-1], x.shape[-1] // 4, 4)
    return lax.bitcast_convert_type(g, jnp.int32)


def unpack(w: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack`: int32 words [..., k] -> int8 [..., 4k]."""
    b = lax.bitcast_convert_type(w, jnp.int8)
    return b.reshape(*w.shape[:-1], w.shape[-1] * 4)


def eq(x, y):
    """Per-byte x == y -> hmask.  (Zero-byte detect on x ^ y; the low-7
    add cannot carry across bytes: 0x7F + 0x7F < 0x100.)"""
    z = x ^ y
    return ~(((z & B7F) + B7F) | z) & H


def ne(x, y):
    """Per-byte x != y -> hmask."""
    z = x ^ y
    return (((z & B7F) + B7F) | z) & H


def ges(x, y):
    """Per-byte SIGNED x >= y -> hmask.

    Unsigned compare of the sign-flipped bytes: ``t``'s high bit per byte
    is (low7(x) >= low7(y)) — the per-byte subtraction cannot borrow
    across bytes because every byte of ``x | H`` is >= 0x80 and every
    byte of ``y & B7F`` is <= 0x7F.  The sign-flip folds into the
    high-bit fixup: signed x >= y is (~x & y) | (x ~^ y) & (xl >= yl)
    at the sign bit.
    """
    t = (x | H) - (y & B7F)
    return ((~x & y) | (~(x ^ y) & t)) & H


def gts(x, y):
    """Per-byte SIGNED x > y -> hmask."""
    return ~ges(y, x) & H


def les(x, y):
    """Per-byte SIGNED x <= y -> hmask."""
    return ges(y, x)


def to_bytes(m):
    """hmask -> full-byte mask (0xFF per true byte).  The multiply by 255
    cannot carry: each byte of the 0/1 word contributes < 256."""
    return ((m >> 7) & L) * 0xFF


def sel(m, x, y):
    """Byte-wise select: x where full-byte mask ``m`` else y."""
    return y ^ ((x ^ y) & m)


def add(x, y):
    """Per-byte wrap-around add (no carries cross byte boundaries)."""
    return ((x & B7F) + (y & B7F)) ^ ((x ^ y) & H)


def sub(x, y):
    """Per-byte wrap-around subtract (no borrows cross byte boundaries)."""
    return ((x | H) - (y & B7F)) ^ ((x ^ ~y) & H)


def maxs(x, y):
    """Per-byte SIGNED max."""
    return sel(to_bytes(ges(x, y)), x, y)


def mins(x, y):
    """Per-byte SIGNED min."""
    return sel(to_bytes(les(x, y)), x, y)


def bool_mask(b) -> jnp.ndarray:
    """bool array -> word-shaped select mask (-1/0: every byte set).

    For masks that are uniform across the 4 packed subjects (per-receiver
    row flags, scalar conditions) — the word is all-ones or all-zeros, so
    it serves directly as a full-byte mask and as an hmask operand.
    """
    return jnp.where(b, jnp.int32(-1), jnp.int32(0))
