"""In-process SDFS cluster: client ops + quorum + read-repair + recovery.

This is the data/control plane of the reference (put/get/delete/ls/store,
re-replication, election) with its *transport* replaced: where the reference
moves bytes with sshpass/scp and control with Go net/rpc over TCP
(reference: slave/slave.go:668-928, T1/T2 in SURVEY §2.3), the TPU build moves
bytes between LocalStores directly and takes the membership view from the
failure detector (the sim).  The protocol logic — conflict windows, quorum
counting, stale-replica self-repair, repair planning, election — is preserved
verbatim, so BASELINE config 5 (SDFS co-sim over simulated membership) runs
the same decisions the Go cluster would make.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from gossipfs_tpu.sdfs import election
from gossipfs_tpu.sdfs.master import SDFSMaster
from gossipfs_tpu.sdfs.quorum import read_quorum, write_quorum
from gossipfs_tpu.sdfs.store import LocalStore
from gossipfs_tpu.sdfs.types import WRITE_CONFLICT_WINDOW, ReplicatePlan


class SDFSCluster:
    """All nodes' stores plus the master role, driven by a membership view."""

    def __init__(self, n: int, seed: int = 0, introducer: int = 0):
        self.n = n
        self.seed = seed
        self.stores = {i: LocalStore() for i in range(n)}
        self.master_node = introducer  # initial master = introducer (slave.go:22,99)
        self.master = SDFSMaster(seed=seed)
        self.live: list[int] = list(range(n))      # gossip membership VIEW
        self.reachable: set[int] = set(self.live)  # transport-level reachability
        self.election_pending = False  # master missing, external driver elects
        # repairs a budgeted fail_recover pass planned but deferred (the
        # repair-storm scheduler's backlog signal — see fail_recover)
        self.last_repair_pending = 0
        self.master.update_member(self.live)

    # -- membership seam ---------------------------------------------------
    def update_membership(
        self,
        view: list[int],
        reachable: list[int] | None = None,
        now: int = 0,
        elect: bool = True,
    ) -> None:
        """Feed the detector's membership *view* in (the slave.go:478 seam).

        ``view`` drives placement and the election trigger — it is gossip
        data and may lag ground truth (a dead-but-undetected replica stays
        placeable, exactly like the reference).  ``reachable`` models which
        processes answer RPC/scp at all (a connection to a dead host fails
        immediately even before gossip detects it); it defaults to the view.
        Triggers election when the master is gone from the view
        (updateMemberList, slave.go:452-457); with ``elect=False`` the
        trigger only sets ``election_pending`` and an external driver (the
        shim's distributed Vote/AssignNewMaster path) runs the protocol.
        """
        self.live = sorted(view)
        self.reachable = set(reachable) if reachable is not None else set(self.live)
        self.master.update_member(self.live)
        if self.master_node not in self.live and self.live:
            if elect:
                self._elect(now)
            else:
                self.election_pending = True
        else:
            self.election_pending = False

    def _elect(self, now: int = 0) -> None:
        """Fixed-candidate majority vote + metadata rebuild (slave.go:930-1051),
        computed centrally (the in-process fast path; the gRPC shim's
        distributed mode drives the same outcome through the Vote /
        AssignNewMaster RPC surface instead — shim/service.py).

        Every live node votes for the lowest-ordered member; with all votes
        cast the majority is automatic.  Candidates must actually answer RPC
        (a dead-but-undetected lowest member can't receive votes).  The new
        master rebuilds metadata from surviving local registries.
        """
        candidates = [x for x in self.live if x in self.reachable]
        candidate = election.successor(candidates)
        # majority is counted against the full member list (Receive_vote,
        # slave.go:968-984): with most of the view unreachable, the election
        # stalls rather than letting a minority rebuild (and shrink) metadata
        if candidate is None or not election.tally(set(candidates), len(self.live)):
            return
        registries = {
            i: self.stores[i].listing() for i in self.live if i in self.reachable
        }
        self.install_rebuilt_master(candidate, registries, now)

    def install_rebuilt_master(
        self, winner: int, registries: dict[int, dict[str, int]], now: int
    ) -> None:
        """Make ``winner`` the master with metadata rebuilt from collected
        registries (rebuild_file_meta, slave.go:986-1043) — the commit step
        shared by the central ``_elect`` and the shim's distributed
        Vote/AssignNewMaster election."""
        self.master_node = winner
        # a rebuilt file's true last-write time died with the old master;
        # treat it as not-recent so the conflict window doesn't spuriously
        # reject the first post-election put
        rebuilt = election.rebuild_metadata(
            registries, now=now - WRITE_CONFLICT_WINDOW
        )
        new_master = SDFSMaster(seed=self.seed)
        new_master.files = rebuilt
        new_master.update_member(self.live)
        self.master = new_master

    # -- client ops --------------------------------------------------------
    def put(
        self,
        name: str,
        data: bytes,
        now: int,
        confirm: Callable[[], bool] | None = None,
    ) -> bool:
        """Write path with conflict window + quorum (slave.go:668-778).

        On a write-write conflict (another put within 60 rounds) the master
        asks the requester for confirmation (server.go:74-121); ``confirm``
        models the interactive yes/no (default: reject, the 30 s-timeout
        outcome).
        """
        if self.master.updated_recently(name, now):
            if confirm is None or not confirm():
                return False  # "Write-Write conflicts!" (slave.go:681-686)
        replicas, version = self.master.handle_put(name, now)
        return self._push(name, data, replicas, version)

    def _push(self, name: str, data: bytes, replicas: list[int],
              version: int) -> bool:
        """Replica fan-out + W-ack count — the write path's commit half,
        shared by :meth:`put` and :meth:`put_batch`."""
        if not replicas:
            return False  # no live members to place on
        acks = 0
        for node in replicas:
            if node in self.reachable:  # scp to a dead host fails, no ack
                self.stores[node].put(name, data, version)
                acks += 1
        return acks >= write_quorum(len(replicas))

    def put_batch(
        self,
        items: list[tuple[str, bytes]],
        now: int,
        confirm: Callable[[], bool] | None = None,
    ) -> dict[str, bool]:
        """Many puts in one round: placement for every NEW file happens as
        ONE vectorized draw (``SDFSMaster.handle_put_batch``) instead of a
        per-file RNG walk; conflict checking, version bumps, replica
        pushes and W-ack counting stay per file (bytes still move per
        replica).  The traffic plane's open-loop generator drives this at
        thousands of files per round.
        """
        allowed: list[str] = []
        results: dict[str, bool] = {}
        payload: dict[str, bytes] = {}
        for name, data in items:
            if self.master.updated_recently(name, now) and (
                confirm is None or not confirm()
            ):
                results[name] = False  # conflict window, unconfirmed
                continue
            allowed.append(name)
            payload[name] = data
        placed = self.master.handle_put_batch(allowed, now)
        for name in allowed:
            replicas, version = placed[name]
            results[name] = self._push(name, payload[name], replicas, version)
        return results

    def get(self, name: str) -> bytes | None:
        """Read path with quorum of version reports + read-repair
        (slave.go:780-892)."""
        replicas, version = self.master.file_info(name)
        if not replicas or version < 0:
            return None  # "No File Found" (slave.go:830-834)
        reports = {
            node: self.stores[node].version(name)
            for node in replicas
            if node in self.reachable
        }
        if len(reports) < read_quorum(len(replicas)):
            return None  # can't reach a quorum of replicas
        # stale replicas self-repair by pulling from a fresh one (slave.go:799-813)
        fresh = [node for node, v in reports.items() if v >= version]
        if not fresh:
            return None
        blob = self.stores[fresh[0]].get(name)
        for node, v in reports.items():
            if v < version and blob is not None:
                self.stores[node].put(name, blob, version)
        # the client's copy of the pulled bytes (the reference scp-pulls one
        # replica, slave.go:857-878) — reads move one copy, writes move R
        return None if blob is None else bytes(memoryview(blob))

    def delete(self, name: str) -> bool:
        """Master drops metadata, replicas drop data (slave.go:1057-1091)."""
        old = self.master.delete(name)
        if not old:
            return False
        for node in old:
            self.stores[node].delete(name)
        return True

    def ls(self, name: str) -> list[int]:
        """Replica locations of a file (slave.go:894-917)."""
        replicas, _ = self.master.file_info(name)
        return replicas

    def store_listing(self, node: int) -> dict[str, int]:
        """Files stored on one node (slave.go:919-928)."""
        return self.stores[node].listing()

    def lost_files(self) -> list[str]:
        """Files with NO replica left in the membership view — the
        ``replica_lost`` evidence (plan_repairs silently skips them as
        unrecoverable; the traffic plane wants them observable)."""
        live_set = set(self.live)
        return [
            name
            for name, info in self.master.files.items()
            if not any(nd in live_set for nd in info.node_list)
        ]

    # -- failure recovery (slave.go:1093-1175 + master.go:74-127) ----------
    def fail_recover(self, budget: int | None = None) -> list[ReplicatePlan]:
        """Re-replicate every under-replicated file from its first healthy
        replica (Fail_recover + Re_put).  Called RECOVERY_DELAY rounds after a
        detection in the co-sim driver.

        Metadata commits *after* the copies: a file's node_list only gains
        replicas that actually received the bytes, so a failed copy (target
        dead-but-undetected) leaves the file under-replicated in metadata and
        eligible for retry on the next recovery pass.

        ``budget``: the repair-storm scheduler's per-pass cap — at most this
        many plans EXECUTE (plans arrive most-deficient-first from
        ``plan_repairs``, so the budget drains the files closest to data
        loss first); the remainder stays under-replicated in metadata and
        is re-planned next pass.  ``last_repair_pending`` records how many
        planned repairs the budget deferred, so the co-sim driver knows to
        schedule another pass immediately instead of waiting for the next
        detection.

        Returns only *executed* plans, with ``new_nodes`` narrowed to the
        copies that actually landed — what the event log and the bench's
        repair count should reflect.
        """
        if budget is not None and budget <= 0:
            # a zero budget would defer every plan forever while the
            # driver reschedules a full planning sweep each round
            raise ValueError("repair budget must be positive (None = "
                             "unbounded)")
        plans = self.master.plan_repairs(self.live, reachable=self.reachable)
        executed: list[ReplicatePlan] = []
        self.last_repair_pending = 0
        for i, plan in enumerate(plans):
            if budget is not None and len(executed) >= budget:
                self.last_repair_pending = len(plans) - i
                break
            # a listed survivor can hold no bytes (put acked by quorum while
            # it was unreachable, then it rejoined): fall through the other
            # reachable survivors instead of livelocking on an empty source
            # ... and a survivor can hold *stale* bytes (same rejoin story,
            # one version behind): only a source at the plan's version may
            # seed copies, else old bytes get re-stamped as current
            blob = None
            used_source = plan.source  # == first survivor in reach
            for src in plan.survivors:
                if (
                    src in self.reachable
                    and self.stores[src].version(plan.file) >= plan.version
                ):
                    blob = self.stores[src].get(plan.file)
                    if blob is not None:
                        used_source = src
                        break
            if blob is None:
                continue
            copied = []
            for node in plan.new_nodes:
                if node in self.reachable:
                    self.stores[node].put(plan.file, blob, plan.version)
                    copied.append(node)
            self.master.commit_repair(plan.file, list(plan.survivors) + copied)
            if copied:
                # report the survivor that actually served the bytes, which
                # can differ from plan.source (stale/empty-source fallthrough)
                executed.append(
                    dataclasses.replace(
                        plan, source=used_source, new_nodes=tuple(copied)
                    )
                )
        return executed
