"""In-process SDFS cluster: client ops + quorum + read-repair + recovery.

This is the data/control plane of the reference (put/get/delete/ls/store,
re-replication, election) with its *transport* replaced: where the reference
moves bytes with sshpass/scp and control with Go net/rpc over TCP
(reference: slave/slave.go:668-928, T1/T2 in SURVEY §2.3), the TPU build moves
bytes between LocalStores directly and takes the membership view from the
failure detector (the sim).  The protocol logic — conflict windows, quorum
counting, stale-replica self-repair, repair planning, election — is preserved
verbatim, so BASELINE config 5 (SDFS co-sim over simulated membership) runs
the same decisions the Go cluster would make.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from gossipfs_tpu.erasure import codec
from gossipfs_tpu.sdfs import election
from gossipfs_tpu.sdfs.master import SDFSMaster
from gossipfs_tpu.sdfs.quorum import (
    read_quorum,
    stripe_read_quorum,
    stripe_write_quorum,
    write_quorum,
)
from gossipfs_tpu.sdfs.store import LocalStore
from gossipfs_tpu.sdfs.types import (
    STRIPE_K,
    STRIPE_M,
    STRIPE_WRITE_SLACK,
    WRITE_CONFLICT_WINDOW,
    ReplicatePlan,
    StripeInfo,
    StripeRepairPlan,
)


class SDFSCluster:
    """All nodes' stores plus the master role, driven by a membership view.

    ``redundancy="stripe"`` swaps the 4-full-replica byte plane for the
    erasure plane (``gossipfs_tpu/erasure/``): puts encode the payload
    into k data + m parity fragments (one LocalStore key per fragment,
    ``codec.frag_key``), landed rack-disjointly; gets reconstruct from
    ANY k fresh fragments; repair re-encodes missing fragments from k
    surviving ones — moving ~1/k the bytes a whole-replica copy moves.
    Threshold math stays in ``sdfs/quorum.py``.
    """

    def __init__(self, n: int, seed: int = 0, introducer: int = 0,
                 redundancy: str = "replica", stripe_k: int = STRIPE_K,
                 stripe_m: int = STRIPE_M, rack_size: int | None = None):
        if redundancy not in ("replica", "stripe"):
            raise ValueError(f"unknown redundancy mode: {redundancy!r}")
        self.n = n
        self.seed = seed
        self.redundancy = redundancy
        self.stripe_k = stripe_k
        self.stripe_m = stripe_m
        # node -> rack id; contiguous blocks of rack_size nodes (the
        # scenario engine's correlated-outage grouping), or every node
        # its own rack when no topology is configured
        self.racks = {i: (i // rack_size if rack_size else i)
                      for i in range(n)}
        self.stores = {i: LocalStore() for i in range(n)}
        self.master_node = introducer  # initial master = introducer (slave.go:22,99)
        self.master = self._new_master()
        self.live: list[int] = list(range(n))      # gossip membership VIEW
        self.reachable: set[int] = set(self.live)  # transport-level reachability
        self.election_pending = False  # master missing, external driver elects
        # repairs a budgeted fail_recover pass planned but deferred (the
        # repair-storm scheduler's backlog signal — see fail_recover)
        self.last_repair_pending = 0
        # repair byte accounting, both modes: bytes actually written per
        # landed repair copy (replica: the whole blob; stripe: one row of
        # S/k bytes — framing headers excluded) — the ERASURE_r18
        # repair-bandwidth claim's measurement
        self.repair_bytes_written = 0
        self.repair_copies = 0
        self.master.update_member(self.live)

    def _new_master(self) -> SDFSMaster:
        return SDFSMaster(seed=self.seed, redundancy=self.redundancy,
                          stripe_k=self.stripe_k, stripe_m=self.stripe_m,
                          racks=self.racks)

    # -- membership seam ---------------------------------------------------
    def update_membership(
        self,
        view: list[int],
        reachable: list[int] | None = None,
        now: int = 0,
        elect: bool = True,
    ) -> None:
        """Feed the detector's membership *view* in (the slave.go:478 seam).

        ``view`` drives placement and the election trigger — it is gossip
        data and may lag ground truth (a dead-but-undetected replica stays
        placeable, exactly like the reference).  ``reachable`` models which
        processes answer RPC/scp at all (a connection to a dead host fails
        immediately even before gossip detects it); it defaults to the view.
        Triggers election when the master is gone from the view
        (updateMemberList, slave.go:452-457); with ``elect=False`` the
        trigger only sets ``election_pending`` and an external driver (the
        shim's distributed Vote/AssignNewMaster path) runs the protocol.
        """
        self.live = sorted(view)
        self.reachable = set(reachable) if reachable is not None else set(self.live)
        self.master.update_member(self.live)
        if self.master_node not in self.live and self.live:
            if elect:
                self._elect(now)
            else:
                self.election_pending = True
        else:
            self.election_pending = False

    def _elect(self, now: int = 0) -> None:
        """Fixed-candidate majority vote + metadata rebuild (slave.go:930-1051),
        computed centrally (the in-process fast path; the gRPC shim's
        distributed mode drives the same outcome through the Vote /
        AssignNewMaster RPC surface instead — shim/service.py).

        Every live node votes for the lowest-ordered member; with all votes
        cast the majority is automatic.  Candidates must actually answer RPC
        (a dead-but-undetected lowest member can't receive votes).  The new
        master rebuilds metadata from surviving local registries.
        """
        candidates = [x for x in self.live if x in self.reachable]
        candidate = election.successor(candidates)
        # majority is counted against the full member list (Receive_vote,
        # slave.go:968-984): with most of the view unreachable, the election
        # stalls rather than letting a minority rebuild (and shrink) metadata
        if candidate is None or not election.tally(set(candidates), len(self.live)):
            return
        registries = {
            i: self.stores[i].listing() for i in self.live if i in self.reachable
        }
        self.install_rebuilt_master(candidate, registries, now)

    def install_rebuilt_master(
        self, winner: int, registries: dict[int, dict[str, int]], now: int
    ) -> None:
        """Make ``winner`` the master with metadata rebuilt from collected
        registries (rebuild_file_meta, slave.go:986-1043) — the commit step
        shared by the central ``_elect`` and the shim's distributed
        Vote/AssignNewMaster election."""
        self.master_node = winner
        # a rebuilt file's true last-write time died with the old master;
        # treat it as not-recent so the conflict window doesn't spuriously
        # reject the first post-election put
        new_master = self._new_master()
        if self.redundancy == "stripe":
            new_master.stripes = self._rebuild_stripes(
                registries, now=now - WRITE_CONFLICT_WINDOW
            )
        else:
            new_master.files = election.rebuild_metadata(
                registries, now=now - WRITE_CONFLICT_WINDOW
            )
        new_master.update_member(self.live)
        self.master = new_master

    def _rebuild_stripes(
        self, registries: dict[int, dict[str, int]], now: int
    ) -> dict[str, StripeInfo]:
        """Stripe-mode metadata rebuild: surviving registries list
        fragment keys (``name#s<slot>``), so the new master recovers
        per-slot holders at the highest version seen; the payload length
        comes out of any surviving fragment's self-describing frame."""
        width = self.stripe_k + self.stripe_m
        # file -> slot -> (version, node), highest version per slot wins
        best: dict[str, dict[int, tuple[int, int]]] = {}
        for node, listing in registries.items():
            for key, version in listing.items():
                parsed = codec.parse_frag_key(key)
                if parsed is None:
                    continue
                name, slot = parsed
                if not 0 <= slot < width:
                    continue
                slots = best.setdefault(name, {})
                if slot not in slots or version > slots[slot][0]:
                    slots[slot] = (version, node)
        rebuilt: dict[str, StripeInfo] = {}
        for name, slots in best.items():
            nodes = [-1] * width
            version = max(v for v, _ in slots.values())
            for slot, (_v, node) in slots.items():
                nodes[slot] = node
            length = 0
            for slot, (v, node) in sorted(
                slots.items(), key=lambda kv: -kv[1][0]
            ):
                blob = self.stores[node].get(codec.frag_key(name, slot))
                if blob is not None:
                    length, _ = codec.unpack_fragment(blob)
                    break
            rebuilt[name] = StripeInfo(fragment_nodes=nodes, version=version,
                                       timestamp=now, length=length)
        return rebuilt

    # -- client ops --------------------------------------------------------
    def put(
        self,
        name: str,
        data: bytes,
        now: int,
        confirm: Callable[[], bool] | None = None,
    ) -> bool:
        """Write path with conflict window + quorum (slave.go:668-778).

        On a write-write conflict (another put within 60 rounds) the master
        asks the requester for confirmation (server.go:74-121); ``confirm``
        models the interactive yes/no (default: reject, the 30 s-timeout
        outcome).
        """
        if self.master.updated_recently(name, now):
            if confirm is None or not confirm():
                return False  # "Write-Write conflicts!" (slave.go:681-686)
        if self.redundancy == "stripe":
            slots, version = self.master.handle_stripe_put(name, now)
            return self._push_stripe(name, data, slots, version)
        replicas, version = self.master.handle_put(name, now)
        return self._push(name, data, replicas, version)

    def _push(self, name: str, data: bytes, replicas: list[int],
              version: int) -> bool:
        """Replica fan-out + W-ack count — the write path's commit half,
        shared by :meth:`put` and :meth:`put_batch`."""
        if not replicas:
            return False  # no live members to place on
        acks = 0
        for node in replicas:
            if node in self.reachable:  # scp to a dead host fails, no ack
                self.stores[node].put(name, data, version)
                acks += 1
        return acks >= write_quorum(len(replicas))

    def _push_stripe(self, name: str, data: bytes, slots: list[int],
                     version: int) -> bool:
        """Stripe fan-out: encode the payload into k+m fragments, land each
        on its slot's holder, ack at the stripe write quorum
        (``sdfs/quorum.py`` owns the threshold).  Every put re-encodes and
        rewrites ALL slots, so at most ``STRIPE_WRITE_SLACK`` slots can be
        stale at any acked version — which keeps k fresh fragments live
        without a stripe read-repair path (the repair plane owns fragment
        refresh)."""
        if not slots:
            return False
        k, m = self.stripe_k, self.stripe_m
        fragments = codec.encode_blob(data, k, m)
        self.master.set_stripe_length(name, len(data))
        acks = 0
        for slot, node in enumerate(slots):
            if node >= 0 and node in self.reachable:
                self.stores[node].put(
                    codec.frag_key(name, slot),
                    codec.pack_fragment(fragments[slot], len(data)),
                    version,
                )
                acks += 1
        return acks >= stripe_write_quorum(k, m, STRIPE_WRITE_SLACK)

    def put_batch(
        self,
        items: list[tuple[str, bytes]],
        now: int,
        confirm: Callable[[], bool] | None = None,
    ) -> dict[str, bool]:
        """Many puts in one round: placement for every NEW file happens as
        ONE vectorized draw (``SDFSMaster.handle_put_batch``) instead of a
        per-file RNG walk; conflict checking, version bumps, replica
        pushes and W-ack counting stay per file (bytes still move per
        replica).  The traffic plane's open-loop generator drives this at
        thousands of files per round.
        """
        allowed: list[str] = []
        results: dict[str, bool] = {}
        payload: dict[str, bytes] = {}
        for name, data in items:
            if self.master.updated_recently(name, now) and (
                confirm is None or not confirm()
            ):
                results[name] = False  # conflict window, unconfirmed
                continue
            allowed.append(name)
            payload[name] = data
        if self.redundancy == "stripe":
            # stripe placement stays per file (the rack-disjoint draw has
            # no batched twin yet — BASELINE.md's erasure section notes it)
            for name in allowed:
                slots, version = self.master.handle_stripe_put(name, now)
                results[name] = self._push_stripe(
                    name, payload[name], slots, version
                )
            return results
        placed = self.master.handle_put_batch(allowed, now)
        for name in allowed:
            replicas, version = placed[name]
            results[name] = self._push(name, payload[name], replicas, version)
        return results

    def get(self, name: str) -> bytes | None:
        """Read path with quorum of version reports + read-repair
        (slave.go:780-892)."""
        if self.redundancy == "stripe":
            return self._get_stripe(name)
        replicas, version = self.master.file_info(name)
        if not replicas or version < 0:
            return None  # "No File Found" (slave.go:830-834)
        reports = {
            node: self.stores[node].version(name)
            for node in replicas
            if node in self.reachable
        }
        if len(reports) < read_quorum(len(replicas)):
            return None  # can't reach a quorum of replicas
        # stale replicas self-repair by pulling from a fresh one (slave.go:799-813)
        fresh = [node for node, v in reports.items() if v >= version]
        if not fresh:
            return None
        blob = self.stores[fresh[0]].get(name)
        for node, v in reports.items():
            if v < version and blob is not None:
                self.stores[node].put(name, blob, version)
        # the client's copy of the pulled bytes (the reference scp-pulls one
        # replica, slave.go:857-878) — reads move one copy, writes move R
        return None if blob is None else bytes(memoryview(blob))

    def _get_stripe(self, name: str) -> bytes | None:
        """Stripe read: fresh fragments from any ``stripe_read_quorum``
        slots reconstruct the payload.  No read-repair here — every put
        rewrites all slots and the repair plane refreshes the rest, so
        stale slots are bounded by the write slack (see
        :meth:`_push_stripe`)."""
        k, m = self.stripe_k, self.stripe_m
        slots, version, length = self.master.stripe_file_info(name)
        if not slots or version < 0:
            return None
        rows: dict[int, bytes] = {}
        for slot, node in enumerate(slots):
            if node < 0 or node not in self.reachable:
                continue
            key = codec.frag_key(name, slot)
            if self.stores[node].version(key) < version:
                continue  # stale fragment can't serve this read
            blob = self.stores[node].get(key)
            if blob is None:
                continue
            _, rows[slot] = codec.unpack_fragment(blob)
            if len(rows) == stripe_read_quorum(k, m):
                break
        if len(rows) < stripe_read_quorum(k, m):
            return None
        return codec.decode_blob(rows, k, m, length)

    def delete(self, name: str) -> bool:
        """Master drops metadata, replicas drop data (slave.go:1057-1091)."""
        if self.redundancy == "stripe":
            old_slots = self.master.stripe_delete(name)
            if not old_slots:
                return False
            for slot, node in enumerate(old_slots):
                if node >= 0:
                    self.stores[node].delete(codec.frag_key(name, slot))
            return True
        old = self.master.delete(name)
        if not old:
            return False
        for node in old:
            self.stores[node].delete(name)
        return True

    def ls(self, name: str) -> list[int]:
        """Replica locations of a file (slave.go:894-917); in stripe mode
        the slot-aligned fragment holders (-1 = unplaced slot)."""
        if self.redundancy == "stripe":
            slots, _, _ = self.master.stripe_file_info(name)
            return slots
        replicas, _ = self.master.file_info(name)
        return replicas

    def store_listing(self, node: int) -> dict[str, int]:
        """Files stored on one node (slave.go:919-928)."""
        return self.stores[node].listing()

    def lost_files(self) -> list[str]:
        """Files with NO replica left in the membership view — the
        ``replica_lost`` evidence (plan_repairs silently skips them as
        unrecoverable; the traffic plane wants them observable)."""
        live_set = set(self.live)
        if self.redundancy == "stripe":
            # a stripe is LOST once fewer than k fragments remain in the
            # view — the MDS bound, not total wipeout, is the loss line
            rq = stripe_read_quorum(self.stripe_k, self.stripe_m)
            return [
                name
                for name, info in self.master.stripes.items()
                if sum(1 for nd in info.fragment_nodes if nd in live_set) < rq
            ]
        return [
            name
            for name, info in self.master.files.items()
            if not any(nd in live_set for nd in info.node_list)
        ]

    # -- failure recovery (slave.go:1093-1175 + master.go:74-127) ----------
    def fail_recover(
        self, budget: int | None = None
    ) -> list[ReplicatePlan] | list[StripeRepairPlan]:
        """Re-replicate every under-replicated file from its first healthy
        replica (Fail_recover + Re_put).  Called RECOVERY_DELAY rounds after a
        detection in the co-sim driver.

        Metadata commits *after* the copies: a file's node_list only gains
        replicas that actually received the bytes, so a failed copy (target
        dead-but-undetected) leaves the file under-replicated in metadata and
        eligible for retry on the next recovery pass.

        ``budget``: the repair-storm scheduler's per-pass cap — at most this
        many plans EXECUTE (plans arrive most-deficient-first from
        ``plan_repairs``, so the budget drains the files closest to data
        loss first); the remainder stays under-replicated in metadata and
        is re-planned next pass.  ``last_repair_pending`` records how many
        planned repairs the budget deferred, so the co-sim driver knows to
        schedule another pass immediately instead of waiting for the next
        detection.

        Returns only *executed* plans, with ``new_nodes`` narrowed to the
        copies that actually landed — what the event log and the bench's
        repair count should reflect.
        """
        if budget is not None and budget <= 0:
            # a zero budget would defer every plan forever while the
            # driver reschedules a full planning sweep each round
            raise ValueError("repair budget must be positive (None = "
                             "unbounded)")
        if self.redundancy == "stripe":
            return self._fail_recover_stripe(budget)
        plans = self.master.plan_repairs(self.live, reachable=self.reachable)
        executed: list[ReplicatePlan] = []
        self.last_repair_pending = 0
        for i, plan in enumerate(plans):
            if budget is not None and len(executed) >= budget:
                self.last_repair_pending = len(plans) - i
                break
            # a listed survivor can hold no bytes (put acked by quorum while
            # it was unreachable, then it rejoined): fall through the other
            # reachable survivors instead of livelocking on an empty source
            # ... and a survivor can hold *stale* bytes (same rejoin story,
            # one version behind): only a source at the plan's version may
            # seed copies, else old bytes get re-stamped as current
            blob = None
            used_source = plan.source  # == first survivor in reach
            for src in plan.survivors:
                if (
                    src in self.reachable
                    and self.stores[src].version(plan.file) >= plan.version
                ):
                    blob = self.stores[src].get(plan.file)
                    if blob is not None:
                        used_source = src
                        break
            if blob is None:
                continue
            copied = []
            for node in plan.new_nodes:
                if node in self.reachable:
                    self.stores[node].put(plan.file, blob, plan.version)
                    self.repair_bytes_written += len(blob)
                    self.repair_copies += 1
                    copied.append(node)
            self.master.commit_repair(plan.file, list(plan.survivors) + copied)
            if copied:
                # report the survivor that actually served the bytes, which
                # can differ from plan.source (stale/empty-source fallthrough)
                executed.append(
                    dataclasses.replace(
                        plan, source=used_source, new_nodes=tuple(copied)
                    )
                )
        return executed

    def _fail_recover_stripe(
        self, budget: int | None
    ) -> list[StripeRepairPlan]:
        """Stripe recovery: fetch k surviving fragments, re-encode the
        missing slots, land them on the planned rack-disjoint targets.
        Each landed fragment moves ceil(S/k) bytes where a replica repair
        moves S — the 1/k repair-bandwidth claim's mechanism.  Budget
        counts executed PLANS (stripes), symmetric with replica mode."""
        k, m = self.stripe_k, self.stripe_m
        plans = self.master.plan_stripe_repairs(
            self.live, reachable=self.reachable
        )
        executed: list[StripeRepairPlan] = []
        self.last_repair_pending = 0
        for i, plan in enumerate(plans):
            if budget is not None and len(executed) >= budget:
                self.last_repair_pending = len(plans) - i
                break
            info = self.master.stripes.get(plan.file)
            if info is None:
                continue
            # gather k source fragments at the plan's version — a listed
            # survivor can be empty or stale (acked while unreachable, then
            # rejoined), so fall through the other survivors; short of k
            # sources the stripe is skipped and re-planned next pass
            rows: dict[int, bytes] = {}
            length = info.length
            for slot in plan.survivors:
                node = info.fragment_nodes[slot]
                key = codec.frag_key(plan.file, slot)
                if (
                    node in self.reachable
                    and self.stores[node].version(key) >= plan.version
                ):
                    blob = self.stores[node].get(key)
                    if blob is not None:
                        length, rows[slot] = codec.unpack_fragment(blob)
                if len(rows) == stripe_read_quorum(k, m):
                    break
            if len(rows) < stripe_read_quorum(k, m):
                continue
            rebuilt = codec.repair_fragments(
                rows, list(plan.slots), k, m, length
            )
            landed: dict[int, int] = {}
            for slot, target in zip(plan.slots, plan.new_nodes):
                if target in self.reachable:
                    self.stores[target].put(
                        codec.frag_key(plan.file, slot),
                        codec.pack_fragment(rebuilt[slot], length),
                        plan.version,
                    )
                    # row bytes only: the 4-byte frame is storage framing,
                    # not repair traffic (BASELINE.md's convention)
                    self.repair_bytes_written += len(rebuilt[slot])
                    self.repair_copies += 1
                    landed[slot] = target
            if landed:
                self.master.commit_stripe_repair(plan.file, landed)
                executed.append(
                    dataclasses.replace(
                        plan,
                        slots=tuple(landed),
                        new_nodes=tuple(landed[s] for s in landed),
                    )
                )
        return executed
