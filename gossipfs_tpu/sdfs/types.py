"""SDFS metadata types.

Reference equivalents: ``master.File_info{Node_list, Version, Timestamp}``
(reference: master/master.go:22-31) and the per-node filename->version registry
``sdfs_slave.SDFSSLAVE`` (sdfs_slave/sdfs_slave.go:10-18).  Time is measured in
gossip rounds (1 round == 1 s), like everything in the TPU build.
"""

from __future__ import annotations

import dataclasses

REPLICATION_FACTOR = 4        # 4 replicas, tolerates 3 failures (master.go:104,131)
WRITE_CONFLICT_WINDOW = 60    # write-write conflict window, rounds (master.go:225)
CONFIRM_TIMEOUT = 30          # conflict-confirmation timeout, rounds (server.go:172)
RECOVERY_DELAY = 8            # heartbeats to wait before re-replication (slave.go:1123)

# Erasure mode defaults (redundancy="stripe"): a (4, 2) systematic RS
# stripe stores 6 fragments of S/4 bytes each — 1.5x storage vs the 4x
# of full replication — and survives any 2 fragment losses.  The write
# slack lets the put ack one fragment early (5 of 6 landed) while still
# leaving one parity of post-ack margin; see sdfs/quorum.py.
STRIPE_K = 4                  # data fragments per stripe
STRIPE_M = 2                  # parity fragments per stripe
STRIPE_WRITE_SLACK = 1        # un-landed fragments tolerated at ack time


@dataclasses.dataclass
class FileInfo:
    """Metadata the master keeps per SDFS file (master/master.go:22-31)."""

    node_list: list[int]      # replica node ids
    version: int
    timestamp: int            # round of last successful put


@dataclasses.dataclass
class StripeInfo:
    """Metadata the master keeps per stripe-mode file: one holder node
    per fragment SLOT (index < k is data, >= k is parity), -1 for a slot
    whose fragment is currently unplaced/lost.  The slot order is the
    codec's row order, so repair re-encodes straight into the holes."""

    fragment_nodes: list[int]   # len k+m, slot -> holder node id (-1 = none)
    version: int
    timestamp: int              # round of last successful put
    length: int                 # payload bytes (fragments are padded to S/k)


@dataclasses.dataclass(frozen=True)
class StripeRepairPlan:
    """One stripe's budgeted repair order: re-encode ``slots`` from any k
    surviving fragments and land them on ``new_nodes`` (slot-aligned)."""

    file: str
    version: int
    slots: tuple[int, ...]       # fragment slots to rebuild
    new_nodes: tuple[int, ...]   # target holder per slot (same order)
    survivors: tuple[int, ...]   # slots whose fragments were live at plan time


@dataclasses.dataclass(frozen=True)
class ReplicatePlan:
    """One file's re-replication order (master.Replicate_info, master.go:27-31)."""

    file: str
    source: int               # first reachable healthy replica to copy from
    version: int
    new_nodes: tuple[int, ...]  # nodes that must receive a copy
    survivors: tuple[int, ...] = ()  # replicas that already hold the data
