"""SDFS metadata types.

Reference equivalents: ``master.File_info{Node_list, Version, Timestamp}``
(reference: master/master.go:22-31) and the per-node filename->version registry
``sdfs_slave.SDFSSLAVE`` (sdfs_slave/sdfs_slave.go:10-18).  Time is measured in
gossip rounds (1 round == 1 s), like everything in the TPU build.
"""

from __future__ import annotations

import dataclasses

REPLICATION_FACTOR = 4        # 4 replicas, tolerates 3 failures (master.go:104,131)
WRITE_CONFLICT_WINDOW = 60    # write-write conflict window, rounds (master.go:225)
CONFIRM_TIMEOUT = 30          # conflict-confirmation timeout, rounds (server.go:172)
RECOVERY_DELAY = 8            # heartbeats to wait before re-replication (slave.go:1123)


@dataclasses.dataclass
class FileInfo:
    """Metadata the master keeps per SDFS file (master/master.go:22-31)."""

    node_list: list[int]      # replica node ids
    version: int
    timestamp: int            # round of last successful put


@dataclasses.dataclass(frozen=True)
class ReplicatePlan:
    """One file's re-replication order (master.Replicate_info, master.go:27-31)."""

    file: str
    source: int               # first reachable healthy replica to copy from
    version: int
    new_nodes: tuple[int, ...]  # nodes that must receive a copy
    survivors: tuple[int, ...] = ()  # replicas that already hold the data
