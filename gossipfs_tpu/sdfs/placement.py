"""Replica placement: uniform random without replacement until 4 replicas.

Reference: ``Init_replica`` (master/master.go:129-150) draws random members
until it has 4 distinct ones.  Note the reference's latent bug — it draws with
``rand.Intn(len(members)-1)``, which can never select the *last* member of the
snapshot; we implement the evidently intended uniform choice (documented
deviation, caught by statistical test).

Three implementations with identical semantics:
  * ``place`` — plain Python over a membership list (control-plane path).
  * ``place_batch`` — vectorized JAX placement of many files at once over an
    alive mask, for the 100k-node SDFS co-sim (BASELINE config 5).  Two
    methods behind one call: the exact Gumbel top-k (O(n_files x N) — fine
    to ~8k members) and, at traffic-plane scale, a rejection-free SAMPLED
    draw (O(n_files x m), m = a small static oversample) that never
    materializes an [n_files, N] score matrix.
  * ``place_batch_np`` — the host-side (numpy) batch form the metadata
    master uses for thousands-of-puts-per-round workloads
    (``SDFSMaster.handle_put_batch``).
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR

# place_batch switches from the exact Gumbel top-k to the sampled draw
# above this member count: the Gumbel path's [n_files, N] perturbed-score
# matrix is exact but costs n_files x N floats (1.6 GB at 2048 files over
# 100k members), while the sampled path is O(n_files x OVERSAMPLE)
BATCH_GUMBEL_MAX_N = 8192

# draws per file on the sampled path: first-k-distinct of iid uniform
# draws IS uniform-without-replacement; with k=4 picks the chance of
# fewer than k distinct among 8k draws is ~(k/n_alive)^(m-k) — negligible
# whenever n_alive >> k (the regime the method is selected for), and a
# short row falls back to -1 slots the caller retries
OVERSAMPLE_FACTOR = 8


def place(
    members: list[int], rng: random.Random, k: int = REPLICATION_FACTOR
) -> list[int]:
    """Choose min(k, len(members)) distinct replica nodes, uniformly."""
    if len(members) <= k:
        return list(members)
    return rng.sample(list(members), k)


def first_k_distinct(nodes: jnp.ndarray, k: int) -> jnp.ndarray:
    """[rows, m] draws -> [rows, k] first-k-distinct per row, -1 padded.

    Keeping the FIRST occurrence of each value in draw order is exactly
    sequential rejection sampling, so the result is uniform without
    replacement given iid uniform draws.
    """
    rows, m = nodes.shape
    # dup[i, j, j2] — draw j repeats an EARLIER draw j2 < j of the same row
    dup = (nodes[:, :, None] == nodes[:, None, :]) & (
        jnp.arange(m)[None, :] < jnp.arange(m)[:, None]
    )[None]
    is_new = ~dup.any(axis=2) & (nodes >= 0)
    rank = jnp.cumsum(is_new, axis=1) - 1
    take = is_new & (rank < k)
    out = jnp.full((rows, k), -1, dtype=jnp.int32)
    row_idx = jnp.broadcast_to(jnp.arange(rows)[:, None], (rows, m))
    return out.at[row_idx, jnp.where(take, rank, k)].set(
        jnp.where(take, nodes.astype(jnp.int32), -1), mode="drop"
    )


def sample_members(key: jax.Array, mask: jax.Array, rows: int,
                   m: int) -> jnp.ndarray:
    """[rows, m] node ids drawn iid-uniformly over ``mask``'s true set.

    Rank-to-index via searchsorted on the mask's cumsum — no [rows, N]
    intermediate, no dynamic-shape nonzero.  Rows are -1 when the mask is
    empty.
    """
    n_set = jnp.sum(mask)
    cum = jnp.cumsum(mask.astype(jnp.int32))
    ranks = jax.random.randint(key, (rows, m), 0, jnp.maximum(n_set, 1))
    nodes = jnp.searchsorted(cum, ranks + 1).astype(jnp.int32)
    return jnp.where(n_set > 0, nodes, -1)


def place_batch(
    key: jax.Array,
    alive: jax.Array,
    n_files: int,
    k: int = REPLICATION_FACTOR,
    method: str = "auto",
) -> jax.Array:
    """int32 [n_files, k] — independent uniform placements over live nodes.

    ``method="gumbel"``: samples without replacement per file via Gumbel
    top-k over the alive mask (one fused sort; exact — if fewer than k
    nodes are alive, dead slots are filled with -1).  ``method="sampled"``:
    rejection-free oversampled draw (``sample_members`` + first-k-distinct)
    that scales to 100k+ members; a row may carry -1 slots when the draw
    collides (vanishingly rare at n_alive >> k) or n_alive < k — callers
    treat -1 as an unplaced slot and retry.  ``"auto"`` picks gumbel at or
    below ``BATCH_GUMBEL_MAX_N`` members, sampled above.
    """
    n = alive.shape[0]
    if method == "auto":
        method = "gumbel" if n <= BATCH_GUMBEL_MAX_N else "sampled"
    if method == "gumbel":
        g = jax.random.gumbel(key, (n_files, n))
        scores = jnp.where(alive[None, :], g, -jnp.inf)
        _, idx = jax.lax.top_k(scores, k)
        enough = jnp.sum(alive) >= jnp.arange(1, k + 1)[None, :]
        return jnp.where(enough, idx.astype(jnp.int32), -1)
    if method != "sampled":
        raise ValueError(f"unknown placement method: {method!r}")
    nodes = sample_members(key, alive, n_files, OVERSAMPLE_FACTOR * k)
    return first_k_distinct(nodes, k)


def place_batch_np(
    rng: np.random.Generator,
    members: np.ndarray,
    n_files: int,
    k: int = REPLICATION_FACTOR,
) -> np.ndarray:
    """Host-side batch placement: int64 [n_files, k] over a member array.

    The metadata master's thousands-of-new-files-per-round path
    (``SDFSMaster.handle_put_batch``): one Gumbel top-k over the member
    list per call — same uniform-without-replacement semantics as
    ``place``, different (still uniform) draws, numpy only so the
    control plane stays host-side.  Fewer than k members: every file
    gets the whole list (``place``'s small-cluster rule).
    """
    members = np.asarray(members, dtype=np.int64)
    n_m = len(members)
    if n_m <= k:
        return np.tile(members, (n_files, 1)) if n_m else np.empty(
            (n_files, 0), dtype=np.int64
        )
    g = rng.gumbel(size=(n_files, n_m))
    idx = np.argpartition(-g, k - 1, axis=1)[:, :k]
    return members[idx]
