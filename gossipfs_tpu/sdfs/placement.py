"""Replica placement: uniform random without replacement until 4 replicas.

Reference: ``Init_replica`` (master/master.go:129-150) draws random members
until it has 4 distinct ones.  Note the reference's latent bug — it draws with
``rand.Intn(len(members)-1)``, which can never select the *last* member of the
snapshot; we implement the evidently intended uniform choice (documented
deviation, caught by statistical test).

Two implementations with identical semantics:
  * ``place`` — plain Python over a membership list (control-plane path).
  * ``place_batch`` — vectorized JAX placement of many files at once over an
    alive mask, for the 100k-node SDFS co-sim (BASELINE config 5).
"""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp

from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR


def place(
    members: list[int], rng: random.Random, k: int = REPLICATION_FACTOR
) -> list[int]:
    """Choose min(k, len(members)) distinct replica nodes, uniformly."""
    if len(members) <= k:
        return list(members)
    return rng.sample(list(members), k)


def place_batch(
    key: jax.Array, alive: jax.Array, n_files: int, k: int = REPLICATION_FACTOR
) -> jax.Array:
    """int32 [n_files, k] — independent uniform placements over live nodes.

    Samples without replacement per file via Gumbel top-k over the alive mask
    (one fused sort instead of a per-file rejection loop).  Files get the k
    live nodes with the largest perturbed scores; if fewer than k nodes are
    alive, dead slots are filled with -1.
    """
    n = alive.shape[0]
    g = jax.random.gumbel(key, (n_files, n))
    scores = jnp.where(alive[None, :], g, -jnp.inf)
    _, idx = jax.lax.top_k(scores, k)
    enough = jnp.sum(alive) >= jnp.arange(1, k + 1)[None, :]
    return jnp.where(enough, idx.astype(jnp.int32), -1)
