"""Master election + metadata rebuild, as pure functions.

Reference: when the master vanishes from the member list, everyone votes for
``MemberList[0]``; the candidate becomes master on a strict majority of
distinct voters, then reconstructs file metadata from every surviving node's
local registry (reference: slave/slave.go:930-1051).  The report calls this a
"bully algorithm"; in fact it is fixed-candidate majority voting — the
lowest-ordered member always wins (SURVEY §2.2 E1).  We keep the real
semantics and the name ``successor``.
"""

from __future__ import annotations

from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR, FileInfo


def successor(members: list[int]) -> int | None:
    """Who everyone votes for: the first member of the list (slave.go:936-947)."""
    return min(members) if members else None


def tally(votes: set[int], n_members: int) -> bool:
    """Strict majority of distinct voters elects the candidate
    (Receive_vote, slave.go:968-984)."""
    return len(votes) > n_members // 2


def rebuild_metadata(
    registries: dict[int, dict[str, int]], now: int
) -> dict[str, FileInfo]:
    """Reconstruct the file->replica map from surviving local registries.

    For each file: holders sorted by their local version, keep the top 4 as
    the replica set, version = max seen (rebuild_file_meta + sortByValue,
    slave/slave.go:986-1043, 120-143).  Recovery-by-reconstruction — the
    reference has no checkpointing (SURVEY §5).
    """
    holders: dict[str, list[tuple[int, int]]] = {}
    for node, registry in registries.items():
        for name, version in registry.items():
            holders.setdefault(name, []).append((node, version))
    meta: dict[str, FileInfo] = {}
    for name, pairs in holders.items():
        # highest version first; node id breaks ties deterministically
        pairs.sort(key=lambda p: (-p[1], p[0]))
        top = pairs[:REPLICATION_FACTOR]
        meta[name] = FileInfo(
            node_list=[node for node, _ in top],
            version=max(v for _, v in pairs),
            timestamp=now,
        )
    return meta
