"""SDFS metadata authority: the master's pure decision logic.

Everything ``master.SDFSMaster`` does (reference: master/master.go:22-259),
re-cast as a deterministic state machine over a membership snapshot — no
RPC, no clocks, no goroutines.  The membership snapshot arrives from the
failure detector exactly through the reference's seam
(``Update_member``, master.go:46-48 fed from slave.go:478): the placement
logic does not care whether the view came from 10 UDP processes or from a row
of the TPU sim tensor.
"""

from __future__ import annotations

import random

from gossipfs_tpu.sdfs import placement
from gossipfs_tpu.sdfs.types import (
    REPLICATION_FACTOR,
    WRITE_CONFLICT_WINDOW,
    FileInfo,
    ReplicatePlan,
)


class SDFSMaster:
    """File->replica metadata plus placement/repair planning."""

    def __init__(self, seed: int = 0):
        self.files: dict[str, FileInfo] = {}
        self.members: list[int] = []
        self._seed = seed
        self._rng = random.Random(seed)

    # -- membership seam (master.go:46-48) --------------------------------
    def update_member(self, members: list[int]) -> None:
        self.members = sorted(members)

    # -- put path (master.go:152-247) -------------------------------------
    def updated_recently(self, name: str, now: int) -> bool:
        """Write-write conflict: a put within the last 60 rounds
        (If_file_updated_recent, master.go:214-229)."""
        info = self.files.get(name)
        return info is not None and now - info.timestamp < WRITE_CONFLICT_WINDOW

    def handle_put(self, name: str, now: int) -> tuple[list[int], int]:
        """Allocate replicas (first put) and bump the version.

        Mirrors Update_timestamp + Init_replica + Handle_put_request
        (master.go:129-175): placement happens once per file lifetime; later
        puts reuse the node list and only bump version/timestamp.
        """
        info = self.files.get(name)
        if info is None:
            nodes = placement.place(self.members, self._rng)
            info = FileInfo(node_list=nodes, version=0, timestamp=now)
            self.files[name] = info
        info.version += 1
        info.timestamp = now
        return list(info.node_list), info.version

    # -- read path (master.go:177-212) ------------------------------------
    def file_info(self, name: str) -> tuple[list[int], int]:
        """Replica list + version; ([], -1) when absent (Get_file_info)."""
        info = self.files.get(name)
        if info is None:
            return [], -1
        return list(info.node_list), info.version

    # -- delete (master.go:249-259) ---------------------------------------
    def delete(self, name: str) -> list[int]:
        """Drop metadata, return the old replica set for data deletion."""
        info = self.files.pop(name, None)
        return list(info.node_list) if info else []

    # -- repair planning (Update_metadata, master.go:74-127) ---------------
    def plan_repairs(
        self, live: list[int], reachable: set[int] | None = None
    ) -> list[ReplicatePlan]:
        """Diff every file's replica set against the live membership.

        For each file with fewer than 4 live replicas: re-place over live
        members, keep surviving replicas, and order copies from the first
        *reachable* healthy source to each newcomer.  (The reference
        re-creates its plan map inside the per-file loop, so only the last
        deficient file ever got repaired — master.go:118 — and it blindly
        uses working[0] as source even when that node no longer answers RPC.
        Fixed here: all deficient files are planned, the source must be
        reachable, and the caller commits the new node_list only for copies
        that succeeded — see ``commit_repair``.  Divergences documented and
        covered by tests.)
        """
        live_set = set(live)
        reach = live_set if reachable is None else (set(reachable) & live_set)
        # pure w.r.t. master state: membership updates flow only through
        # update_member (the slave.go:478 seam), and placement draws come
        # from a membership-keyed derived RNG rather than the shared one —
        # so a planning call with a stale snapshot (shim GetUpdateMeta)
        # neither redirects later placement nor perturbs its determinism
        members = sorted(live_set)
        rng = random.Random(f"{self._seed}:{members}")
        plans: list[ReplicatePlan] = []
        for name, info in self.files.items():
            working = [x for x in info.node_list if x in live_set]
            if len(working) >= min(REPLICATION_FACTOR, len(live_set)) or not working:
                # fully replicated — or every replica lost (file unrecoverable)
                continue
            sources = [x for x in working if x in reach]
            if not sources:
                # no reachable healthy copy right now: leave metadata as-is
                # so the file stays under-replicated and is retried later
                continue
            need = REPLICATION_FACTOR - len(working)
            # candidates must be reachable: a copy to an unreachable node
            # can't land, and with the derived (deterministic) RNG an
            # unreachable pick would be re-picked forever for an unchanged
            # view — reachable-only placement keeps retries progressing
            candidates = [x for x in reach if x not in set(working)]
            new_nodes = placement.place(candidates, rng, k=need)
            if new_nodes:
                plans.append(
                    ReplicatePlan(
                        file=name,
                        source=sources[0],
                        version=info.version,
                        new_nodes=tuple(new_nodes),
                        survivors=tuple(working),
                    )
                )
        return plans

    def commit_repair(self, name: str, node_list: list[int]) -> None:
        """Record the post-repair replica set (survivors + successful copies)."""
        info = self.files.get(name)
        if info is not None:
            info.node_list = list(node_list)
