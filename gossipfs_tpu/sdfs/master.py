"""SDFS metadata authority: the master's pure decision logic.

Everything ``master.SDFSMaster`` does (reference: master/master.go:22-259),
re-cast as a deterministic state machine over a membership snapshot — no
RPC, no clocks, no goroutines.  The membership snapshot arrives from the
failure detector exactly through the reference's seam
(``Update_member``, master.go:46-48 fed from slave.go:478): the placement
logic does not care whether the view came from 10 UDP processes or from a row
of the TPU sim tensor.
"""

from __future__ import annotations

import random

import numpy as np

from gossipfs_tpu.sdfs import placement
from gossipfs_tpu.sdfs.quorum import stripe_read_quorum
from gossipfs_tpu.sdfs.types import (
    REPLICATION_FACTOR,
    STRIPE_K,
    STRIPE_M,
    WRITE_CONFLICT_WINDOW,
    FileInfo,
    ReplicatePlan,
    StripeInfo,
    StripeRepairPlan,
)

# files at or above this count plan repairs through the vectorized array
# diff instead of the per-file Python loop (identical decisions, different
# — still uniform — random placement draws)
BATCH_PLAN_THRESHOLD = 64


class SDFSMaster:
    """File->replica metadata plus placement/repair planning."""

    def __init__(self, seed: int = 0, redundancy: str = "replica",
                 stripe_k: int = STRIPE_K, stripe_m: int = STRIPE_M,
                 racks: dict[int, int] | None = None):
        """``redundancy="stripe"`` keeps per-file :class:`StripeInfo`
        (one holder per fragment slot) instead of replica lists, placed
        rack-disjointly against ``racks`` (node -> rack id; None = every
        node its own rack, i.e. plain distinct placement)."""
        if redundancy not in ("replica", "stripe"):
            raise ValueError(f"unknown redundancy mode: {redundancy!r}")
        self.files: dict[str, FileInfo] = {}
        self.stripes: dict[str, StripeInfo] = {}
        self.redundancy = redundancy
        self.stripe_k = stripe_k
        self.stripe_m = stripe_m
        self.racks = racks
        self.members: list[int] = []
        self._seed = seed
        self._rng = random.Random(seed)

    def _rack_map(self) -> dict[int, int]:
        """Node -> rack id over the current view (identity when no rack
        topology was configured — rack-disjoint degrades to distinct)."""
        if self.racks is not None:
            return self.racks
        return {x: x for x in self.members}

    # -- membership seam (master.go:46-48) --------------------------------
    def update_member(self, members: list[int]) -> None:
        self.members = sorted(members)

    # -- put path (master.go:152-247) -------------------------------------
    def updated_recently(self, name: str, now: int) -> bool:
        """Write-write conflict: a put within the last 60 rounds
        (If_file_updated_recent, master.go:214-229)."""
        info = (self.stripes if self.redundancy == "stripe"
                else self.files).get(name)
        return info is not None and now - info.timestamp < WRITE_CONFLICT_WINDOW

    def handle_put(self, name: str, now: int) -> tuple[list[int], int]:
        """Allocate replicas (first put) and bump the version.

        Mirrors Update_timestamp + Init_replica + Handle_put_request
        (master.go:129-175): placement happens once per file lifetime; later
        puts reuse the node list and only bump version/timestamp.
        """
        info = self.files.get(name)
        if info is None:
            nodes = placement.place(self.members, self._rng)
            info = FileInfo(node_list=nodes, version=0, timestamp=now)
            self.files[name] = info
        info.version += 1
        info.timestamp = now
        return list(info.node_list), info.version

    def handle_put_batch(
        self, names: list[str], now: int
    ) -> dict[str, tuple[list[int], int]]:
        """Batch put path for the traffic plane: one vectorized placement
        draw covers every NEW file in the batch (``placement.place_batch_np``
        — thousands of files per round cost one Gumbel top-k instead of
        n_files sequential ``rng.sample`` calls), then the per-file version
        bump reuses :meth:`handle_put` (which finds the placement already
        recorded).  Same uniform-without-replacement semantics; only the
        random draws differ from the sequential path (both uniform), and
        they come from a membership+batch-keyed derived RNG so batch
        placement neither consumes nor perturbs the sequential RNG stream.
        """
        new = [nm for nm in names if nm not in self.files]
        if len(new) >= BATCH_PLAN_THRESHOLD and len(self.members) > (
            REPLICATION_FACTOR
        ):
            import hashlib

            digest = hashlib.sha256(
                f"{self._seed}:{self.members}:{len(self.files)}:{new[0]}"
                .encode()
            ).digest()
            rng = np.random.default_rng(int.from_bytes(digest[:16], "little"))
            rows = placement.place_batch_np(
                rng, np.asarray(self.members), len(new)
            )
            for nm, nodes in zip(new, rows):
                self.files[nm] = FileInfo(
                    node_list=[int(x) for x in nodes], version=0,
                    timestamp=now,
                )
        return {nm: self.handle_put(nm, now) for nm in names}

    # -- read path (master.go:177-212) ------------------------------------
    def file_info(self, name: str) -> tuple[list[int], int]:
        """Replica list + version; ([], -1) when absent (Get_file_info)."""
        info = self.files.get(name)
        if info is None:
            return [], -1
        return list(info.node_list), info.version

    # -- delete (master.go:249-259) ---------------------------------------
    def delete(self, name: str) -> list[int]:
        """Drop metadata, return the old replica set for data deletion."""
        info = self.files.pop(name, None)
        return list(info.node_list) if info else []

    # -- repair planning (Update_metadata, master.go:74-127) ---------------
    def plan_repairs(
        self, live: list[int], reachable: set[int] | None = None
    ) -> list[ReplicatePlan]:
        """Diff every file's replica set against the live membership.

        For each file with fewer than 4 live replicas: re-place over live
        members, keep surviving replicas, and order copies from the first
        *reachable* healthy source to each newcomer.  (The reference
        re-creates its plan map inside the per-file loop, so only the last
        deficient file ever got repaired — master.go:118 — and it blindly
        uses working[0] as source even when that node no longer answers RPC.
        Fixed here: all deficient files are planned, the source must be
        reachable, and the caller commits the new node_list only for copies
        that succeeded — see ``commit_repair``.  Divergences documented and
        covered by tests.)

        Plans come back MOST-DEFICIENT-FIRST (fewest surviving replicas at
        the front, ties in file-iteration order): the repair-storm
        scheduler (``SDFSCluster.fail_recover(budget=...)``) executes a
        per-round budget off this ordering, so a mass failure spends its
        budget on the files closest to data loss first.
        """
        live_set = set(live)
        reach = live_set if reachable is None else (set(reachable) & live_set)
        if len(self.files) >= BATCH_PLAN_THRESHOLD:
            # at co-sim scale (BASELINE config 5: thousands of files over
            # 100k-class membership) the per-file Python loop is the
            # bottleneck; the array-diff planner makes the same decisions
            return self._plan_repairs_batch(live_set, reach)
        # pure w.r.t. master state: membership updates flow only through
        # update_member (the slave.go:478 seam), and placement draws come
        # from a membership-keyed derived RNG rather than the shared one —
        # so a planning call with a stale snapshot (shim GetUpdateMeta)
        # neither redirects later placement nor perturbs its determinism
        members = sorted(live_set)
        rng = random.Random(f"{self._seed}:{members}")
        plans: list[ReplicatePlan] = []
        for name, info in self.files.items():
            working = [x for x in info.node_list if x in live_set]
            if len(working) >= min(REPLICATION_FACTOR, len(live_set)) or not working:
                # fully replicated — or every replica lost (file unrecoverable)
                continue
            sources = [x for x in working if x in reach]
            if not sources:
                # no reachable healthy copy right now: leave metadata as-is
                # so the file stays under-replicated and is retried later
                continue
            need = REPLICATION_FACTOR - len(working)
            # candidates must be reachable: a copy to an unreachable node
            # can't land, and with the derived (deterministic) RNG an
            # unreachable pick would be re-picked forever for an unchanged
            # view — reachable-only placement keeps retries progressing
            candidates = [x for x in reach if x not in set(working)]
            new_nodes = placement.place(candidates, rng, k=need)
            if new_nodes:
                plans.append(
                    ReplicatePlan(
                        file=name,
                        source=sources[0],
                        version=info.version,
                        new_nodes=tuple(new_nodes),
                        survivors=tuple(working),
                    )
                )
        plans.sort(key=lambda p: len(p.survivors))  # most-deficient-first
        return plans

    def _plan_repairs_batch(
        self, live_set: set[int], reach: set[int]
    ) -> list[ReplicatePlan]:
        """Vectorized repair planner — the array-diff formulation of
        ``plan_repairs`` for config-5 scale (VERDICT round-1 weak #4).

        Same decision rules as the loop path: per file, surviving replicas
        = node_list ∩ live; deficient files with a reachable source get
        REPLICATION_FACTOR - |working| fresh reachable non-replica nodes,
        drawn uniformly without replacement (Gumbel top-k over the
        candidate mask — ``placement.place_batch``'s construction, here in
        numpy since the control plane is host-side).  Only the random
        draws differ from the loop path (both are uniform); determinism is
        preserved via a membership-keyed seed.
        """
        names = list(self.files)
        n_files = len(names)
        r = REPLICATION_FACTOR
        node_list = np.full((n_files, r), -1, dtype=np.int64)
        versions = np.empty(n_files, dtype=np.int64)
        for i, name in enumerate(names):
            nl = self.files[name].node_list[:r]
            node_list[i, : len(nl)] = nl
            versions[i] = self.files[name].version
        live_arr = np.fromiter(live_set, dtype=np.int64, count=len(live_set))
        reach_arr = np.fromiter(reach, dtype=np.int64, count=len(reach))

        valid = node_list >= 0
        working = valid & np.isin(node_list, live_arr)
        w_count = working.sum(axis=1)
        target = min(r, len(live_set))
        sourced = working & np.isin(node_list, reach_arr)
        deficient = (w_count < target) & (w_count > 0) & sourced.any(axis=1)
        if not deficient.any() or len(reach) == 0:
            return []

        # first reachable working replica per file (the plan's source)
        src_slot = np.argmax(sourced, axis=1)
        sources = node_list[np.arange(n_files), src_slot]

        # uniform without-replacement draws over reachable non-replica
        # candidates: Gumbel perturbation + top-k, masked per file
        members = sorted(live_set)
        # membership-keyed like the loop path: hash the FULL seed string so
        # distinct views genuinely reseed (a truncated prefix would collide
        # for most same-epoch views and freeze the placement draws)
        import hashlib

        digest = hashlib.sha256(f"{self._seed}:{members}".encode()).digest()
        rng = np.random.default_rng(int.from_bytes(digest[:16], "little"))
        dead_rows = np.nonzero(deficient)[0]
        # most-deficient-first, stable on file index — the same ordering
        # contract as the loop path (repair-budget scheduling depends on it)
        dead_rows = dead_rows[np.argsort(w_count[dead_rows], kind="stable")]
        reach_sorted = np.sort(reach_arr)
        n_reach = len(reach_sorted)

        if n_reach <= 4 * r:
            # few candidates: exact Gumbel top-k over the full mask
            scores = rng.gumbel(size=(len(dead_rows), n_reach))
            for j, row in enumerate(dead_rows):
                scores[j, np.isin(reach_sorted, node_list[row][valid[row]])] = -np.inf
            order = np.argsort(-scores, axis=1)

            def picks_for(j: int, row: int, need: int) -> list[int]:
                return [
                    int(reach_sorted[k])
                    for k in order[j, :need]
                    if np.isfinite(scores[j, k])
                ]
        else:
            # many candidates: draw a small oversample per file and keep the
            # first `need` distinct non-replica picks — at config-5 scale
            # (thousands of reachable nodes, <= 4 replicas each) a redraw is
            # ever needed with probability ~(r/n_reach)^oversample ~ 0
            oversample = 4 * r
            draws = rng.integers(0, n_reach, size=(len(dead_rows), oversample))
            drawn = reach_sorted[draws]

            def picks_for(j: int, row: int, need: int) -> list[int]:
                taken: list[int] = []
                replicas = set(int(x) for x in node_list[row][valid[row]])
                for cand in drawn[j]:
                    c = int(cand)
                    if c in replicas or c in taken:
                        continue
                    taken.append(c)
                    if len(taken) == need:
                        break
                return taken

        plans: list[ReplicatePlan] = []
        for j, row in enumerate(dead_rows):
            need = int(r - w_count[row])
            picks = picks_for(j, int(row), need)
            if not picks:
                continue
            survivors = tuple(int(x) for x in node_list[row][working[row]])
            plans.append(
                ReplicatePlan(
                    file=names[row],
                    source=int(sources[row]),
                    version=int(versions[row]),
                    new_nodes=tuple(picks),
                    survivors=survivors,
                )
            )
        return plans

    def commit_repair(self, name: str, node_list: list[int]) -> None:
        """Record the post-repair replica set (survivors + successful copies)."""
        info = self.files.get(name)
        if info is not None:
            info.node_list = list(node_list)

    # -- stripe mode (gossipfs_tpu/erasure/) -------------------------------
    def handle_stripe_put(self, name: str, now: int) -> tuple[list[int], int]:
        """Stripe-mode :meth:`handle_put`: allocate k+m rack-disjoint
        fragment holders once per file lifetime (``erasure.planner.
        place_stripe``), bump the version on every put.  Slots beyond
        what the membership can hold distinctly stay -1 (unplaced)."""
        from gossipfs_tpu.erasure.planner import place_stripe

        width = self.stripe_k + self.stripe_m
        info = self.stripes.get(name)
        if info is None:
            nodes = place_stripe(self.members, self._rack_map(), self._rng,
                                 self.stripe_k, self.stripe_m)
            nodes = list(nodes) + [-1] * (width - len(nodes))
            info = StripeInfo(fragment_nodes=nodes, version=0,
                              timestamp=now, length=0)
            self.stripes[name] = info
        info.version += 1
        info.timestamp = now
        return list(info.fragment_nodes), info.version

    def stripe_file_info(self, name: str) -> tuple[list[int], int, int]:
        """Fragment holders + version + payload length; ([], -1, 0) when
        absent (the stripe twin of :meth:`file_info`)."""
        info = self.stripes.get(name)
        if info is None:
            return [], -1, 0
        return list(info.fragment_nodes), info.version, info.length

    def stripe_delete(self, name: str) -> list[int]:
        """Drop stripe metadata; returns the old holder-by-slot list."""
        info = self.stripes.pop(name, None)
        return list(info.fragment_nodes) if info else []

    def plan_stripe_repairs(
        self, live: list[int], reachable: set[int] | None = None
    ) -> list[StripeRepairPlan]:
        """Diff every stripe's fragment holders against the live view —
        the stripe twin of :meth:`plan_repairs`, same contracts: plans
        come back MOST-ENDANGERED-FIRST (fewest live fragments at the
        front — a stripe at k live fragments is one loss from data
        death), sources must be reachable (re-encoding needs k live
        fragments to read), candidates are reachable non-holders with
        repair picks filling the least-loaded racks first,
        and the caller commits only the fragments that actually landed
        (``commit_stripe_repair``).  A stripe below k live fragments is
        data loss — skipped as unrecoverable, like the replica path's
        zero-survivor files."""
        k, m = self.stripe_k, self.stripe_m
        width = k + m
        live_set = set(live)
        reach = live_set if reachable is None else (set(reachable) & live_set)
        members = sorted(live_set)
        rng = random.Random(f"{self._seed}:stripe:{members}")
        racks = self.racks if self.racks is not None else {
            x: x for x in live_set
        }
        from gossipfs_tpu.erasure.planner import pick_repair_targets

        plans: list[StripeRepairPlan] = []
        for name, info in self.stripes.items():
            nodes = info.fragment_nodes
            live_slots = [s for s, nd in enumerate(nodes) if nd in live_set]
            w = len(live_slots)
            target = min(width, len(live_set))
            if w >= target or w < stripe_read_quorum(k, m):
                # full strength — or already below k (data loss, not a plan)
                continue
            reach_slots = [s for s in live_slots if nodes[s] in reach]
            if len(reach_slots) < stripe_read_quorum(k, m):
                # can't read k fragments right now: retried next pass
                continue
            holders = {nd for nd in nodes if nd >= 0}
            candidates = [x for x in reach if x not in holders]
            holes = [s for s in range(width) if s not in set(live_slots)]
            need = min(len(holes), target - w)
            rack_load: dict[int, int] = {}
            for s in live_slots:
                r = racks.get(nodes[s], nodes[s])
                rack_load[r] = rack_load.get(r, 0) + 1
            picks = pick_repair_targets(candidates, racks, rack_load,
                                        need, rng)
            if picks:
                plans.append(StripeRepairPlan(
                    file=name, version=info.version,
                    slots=tuple(holes[: len(picks)]),
                    new_nodes=tuple(picks),
                    survivors=tuple(live_slots),
                ))
        plans.sort(key=lambda p: len(p.survivors))  # most-endangered-first
        return plans

    def commit_stripe_repair(self, name: str,
                             assignments: dict[int, int]) -> None:
        """Record landed repairs: slot -> new holder (only fragments
        that actually received bytes — the stripe :meth:`commit_repair`)."""
        info = self.stripes.get(name)
        if info is not None:
            for slot, node in assignments.items():
                info.fragment_nodes[slot] = node

    def set_stripe_length(self, name: str, length: int) -> None:
        """The byte plane reports the payload length at put time (the
        master never sees bytes; decode needs the unpadded length)."""
        info = self.stripes.get(name)
        if info is not None:
            info.length = length
