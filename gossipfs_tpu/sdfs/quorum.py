"""Quorum arithmetic for replicated reads/writes — the ONE owner.

Reference: ``cal_quorum_num`` computes ``Ceil((len+1)/2)`` with *integer*
division, so the Ceil is a no-op and the quorum is ``floor((n+1)/2)`` — 2 of 4
replicas for BOTH writes and reads (slave/slave.go:717-722).  The report
claims "ACK by 3 replicas" for writes — ``ceil((n+1)/2)`` = 3 of 4, the
W=3/R=2 pair whose ``W + R > n`` inequality is what actually guarantees a
read quorum intersects the last acked write.  The code disagrees with the
report, and we reproduce the CODE's behavior (the actually-deployed
semantics, BASELINE.md "Protocol constants"); ``claimed_write_quorum``
exposes the report's intended value so the discrepancy stays checkable.

Single-ownership rule (pinned by a lint test in tests/test_traffic.py):
every consumer — ``sdfs/cluster.py``'s ack counting, the traffic plane's
planner/harness (``gossipfs_tpu/traffic/``) — imports these functions.
No re-derived ``(n + 1) // 2`` exists anywhere else in the tree.

The stripe thresholds below extend the same ownership to the erasure
plane (``gossipfs_tpu/erasure/``): a (k, m) stripe reads at k-of-(k+m)
and acks a write at (k+m-f)-of-(k+m).  gossipfs-lint's
stripe-quorum-ownership rule flags any re-derived ``k + m - f``
threshold comparison outside this module.
"""

from __future__ import annotations


def quorum(n_replicas: int) -> int:
    """The deployed quorum: floor((n+1)/2) — 2 of 4 for writes AND reads."""
    return (n_replicas + 1) // 2


def write_quorum(n_replicas: int) -> int:
    """W — acks required before a put commits (slave.go:717-722 deployed
    arithmetic; the report claims ``claimed_write_quorum``)."""
    return quorum(n_replicas)


def read_quorum(n_replicas: int) -> int:
    """R — replica version reports required before a get proceeds."""
    return quorum(n_replicas)


def claimed_write_quorum(n_replicas: int) -> int:
    """The report's claimed W: the Ceil ``cal_quorum_num`` INTENDED —
    ceil((n+1)/2), i.e. 3 of 4 — which with R=2 satisfies W + R > n.
    Documented-discrepancy accessor only; nothing executes this policy."""
    return n_replicas // 2 + 1


def stripe_read_quorum(k: int, m: int) -> int:
    """R for a (k, m) stripe: ANY k of the k+m fragments reconstruct the
    payload (the MDS property of the systematic RS code in
    ``gossipfs_tpu/erasure/codec.py``), so reads proceed at exactly k."""
    if k < 1 or m < 1:
        raise ValueError(f"stripe shape needs k >= 1 and m >= 1, got ({k}, {m})")
    return k


def stripe_write_quorum(k: int, m: int, slack: int) -> int:
    """W for a (k, m) stripe: (k + m - slack) fragment acks commit a put.

    ``slack`` is the number of fragment landings a writer may still be
    waiting on at ack time.  It must stay <= m - 1 so an acked write
    retains at least one parity fragment of durability margin (losing
    every un-acked fragment still leaves >= k live, and the read quorum
    k intersects the acked set: W + R = 2k + m - slack > k + m)."""
    if k < 1 or m < 1:
        raise ValueError(f"stripe shape needs k >= 1 and m >= 1, got ({k}, {m})")
    if not 0 <= slack <= m - 1:
        raise ValueError(f"write slack must be in [0, m-1], got {slack} for m={m}")
    return k + m - slack
