"""Quorum arithmetic for replicated reads/writes.

Reference: ``cal_quorum_num`` computes ``Ceil((len+1)/2)`` with *integer*
division, so the Ceil is a no-op and the quorum is ``floor((n+1)/2)`` — 2 of 4
replicas (slave/slave.go:717-722; the report claims "ACK by 3 replicas" but the
code disagrees, BASELINE.md).  We reproduce the code's behavior, which is the
actually-deployed semantics.
"""

from __future__ import annotations


def quorum(n_replicas: int) -> int:
    """Acks required before a put/get completes: floor((n+1)/2)."""
    return (n_replicas + 1) // 2
