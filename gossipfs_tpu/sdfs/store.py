"""Per-node local SDFS store: filename -> version registry plus blob storage.

Reference: ``sdfs_slave.SDFSSLAVE`` keeps a ``map[string]int`` of local file
versions and reads/writes files under a hardcoded home directory
(sdfs_slave/sdfs_slave.go:10-96; note its ``get_file`` reads only a 4096-byte
buffer — a latent truncation bug the reference sidesteps by moving real data
over scp).  Here the registry and the bytes live together; transfers are
byte-complete.
"""

from __future__ import annotations

import pathlib


class LocalStore:
    """One node's SDFS-local registry + content."""

    def __init__(self, root: str | pathlib.Path | None = None):
        """In-memory by default; pass ``root`` to persist blobs on disk
        (the CLI's equivalent of the reference's sdfs/ directory)."""
        self.versions: dict[str, int] = {}
        self.root = pathlib.Path(root) if root is not None else None
        self._blobs: dict[str, bytes] = {}
        if self.root is not None:
            self.root.mkdir(parents=True, exist_ok=True)

    # -- registry (Update_file_version, sdfs_slave.go:20-25) ---------------
    def set_version(self, name: str, version: int) -> None:
        self.versions[name] = version

    def version(self, name: str) -> int:
        """-1 when the file isn't stored locally (Ls_file returns ok=false)."""
        return self.versions.get(name, -1)

    # -- data (Put_file / get_file / Delete_file_data) ---------------------
    def put(self, name: str, data: bytes, version: int) -> None:
        if self.root is not None:
            (self.root / name).write_bytes(data)
        else:
            # defensive byte copy: each replica owns its content, like the
            # reference's per-replica scp (and a caller-held bytearray can't
            # mutate the store later); also what makes bench/sdfs_ops.py's
            # latency-vs-size curves measure an actual per-replica transfer
            self._blobs[name] = bytes(memoryview(data))
        self.versions[name] = version

    def get(self, name: str) -> bytes | None:
        if name not in self.versions:
            return None
        if self.root is not None:
            path = self.root / name
            return path.read_bytes() if path.exists() else None
        return self._blobs.get(name)

    def delete(self, name: str) -> bool:
        existed = name in self.versions
        self.versions.pop(name, None)
        self._blobs.pop(name, None)
        if self.root is not None:
            (self.root / name).unlink(missing_ok=True)
        return existed

    def listing(self) -> dict[str, int]:
        """filename -> version for every locally stored file (Ls_localfile)."""
        return dict(self.versions)
