"""Latency/throughput/durability harness: the SDFS plane under load.

Four production-shaped runs over the interactive CoSim (full fidelity:
real byte movement, quorum acks, detection-driven repair, elections),
every op and control-plane reaction flight-recorded so the durability
facts are independently re-derivable from events alone
(``traffic/audit.py``; ``verify_claims.py traffic_durability``):

  * **steady state** — the open-loop mix against a healthy cohort;
  * **churn** — tracked crashes mid-run; acked writes must survive
    detection -> delayed re-replication;
  * **partition race** — writes keep arriving while a timed partition
    confines quorum reachability to the master's side (PR-2 scenario
    engine); minority-starved puts REJECT (never ack-then-lose), and
    after heal every acked write is still readable;
  * **repair storm** — a rack-sized correlated group dies at once; the
    budgeted repair scheduler (``CoSim(repair_budget=...)``) drains the
    deficit most-endangered-first at budget/pass.

The harness keeps a durability LEDGER (file -> last acked version +
payload digest, deletes retired) and audits it against the cluster's
stores at the end: ``lost`` counts acked writes no live replica can
serve at the acked-or-newer version.  One honest boundary: these runs
are CPU-pinned and small-N (each CoSim tick is an interactive XLA
round); the 100k-member lane runs the tensorized planner instead
(``traffic/planner.py``, ``bench/traffic_bench.py --scale``).
"""

from __future__ import annotations

import dataclasses

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.obs.monitor import MonitorParams, MonitorRecorder
from gossipfs_tpu.sdfs.types import RECOVERY_DELAY
from gossipfs_tpu.traffic import audit
from gossipfs_tpu.traffic.workload import (
    Workload,
    WorkloadSpec,
    drive_cosim,
    payload_digest,
)


def traffic_config(n: int, t_cooldown: int = 12) -> SimConfig:
    """The harness's protocol profile: the north-star gossip-only mode
    (required by the scenario engine's partition filter) on the XLA
    merge — the interactive lane's kernel."""
    return SimConfig(
        n=n, topology="random", fanout=SimConfig.log_fanout(n),
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=t_cooldown,
        merge_kernel="xla",
    )


class TrafficHarness:
    """One CoSim + one workload + one durability ledger."""

    def __init__(self, n: int, spec: WorkloadSpec, seed: int = 0,
                 trace: str | None = None, repair_budget: int | None = None,
                 t_cooldown: int = 12):
        # rack_size=8 groups nodes into the same contiguous blocks the
        # repair-storm scenario kills, so stripe placement's rack
        # balancing is exercised against the actual failure domain
        self.sim = CoSim(traffic_config(n, t_cooldown=t_cooldown),
                         seed=seed, repair_budget=repair_budget,
                         redundancy=spec.redundancy,
                         stripe_k=spec.stripe_k, stripe_m=spec.stripe_m,
                         rack_size=8)
        self.wl = Workload(spec)
        # round 13: the recorder carries the streaming invariant monitor
        # inline (obs/monitor.py) — the acked-write durability ledger is
        # checked AS EVENTS STREAM, a third accounting beside the
        # harness ledger and the post-hoc replay.  The FPR-storm row is
        # off: partition/outage runs legitimately storm mid-fault (the
        # far side is confirmed while alive); durability is the
        # invariant these runs must hold.
        self.recorder = MonitorRecorder(
            trace, source="traffic", n=n,
            params=MonitorParams(fpr_threshold=None),
            workload=dataclasses.asdict(spec),
            repair_budget=repair_budget,
        )
        self.sim.attach_recorder(self.recorder)
        self.acked: dict[str, tuple[int, str]] = {}  # file -> (version, digest)

    # -- driving ----------------------------------------------------------
    def warmup(self, rounds: int = 3) -> None:
        """Advance past the initial hb<=1 detection grace before loading."""
        self.sim.tick(rounds)

    def run(self, rounds: int) -> dict:
        """Drive ``rounds`` of open-loop load (one window summary back)."""
        return drive_cosim(
            self.sim, self.wl, rounds, recorder=self.recorder,
            on_ack=lambda f, v, d: self.acked.__setitem__(f, (v, d)),
            on_delete=lambda f: self.acked.pop(f, None),
        )

    def drain(self, rounds: int) -> None:
        """Quiesce: let detection/recovery passes finish without new load."""
        self.sim.tick(rounds)

    def preload(self, count: int, size: int = 4096) -> int:
        """Seed ``count`` files through the BATCH put path (one vectorized
        placement draw — the ``SDFSMaster.handle_put_batch`` seam);
        returns how many acked."""
        rnd = self.sim.round
        items = []
        for i in range(count):
            name = f"pre{i}.txt"
            items.append((name, self.wl.payload(name, rnd, size)))
        results = self.sim.put_batch(items, confirm=lambda: True)
        meta = (self.sim.cluster.master.stripes
                if self.sim.cluster.redundancy == "stripe"
                else self.sim.cluster.master.files)
        for name, data in items:
            if results.get(name):
                info = meta[name]
                self.acked[name] = (info.version, payload_digest(data))
        return sum(bool(v) for v in results.values())

    # -- durability -------------------------------------------------------
    def audit_stores(self) -> dict:
        """Harness-side durability: every acked write must have at least
        one LIVE listed replica holding the acked-or-newer version
        (stores are read directly — no read-repair side effects).  In
        stripe mode an acked write survives while >= k slots have their
        CURRENT assigned holder live and fresh — the same
        current-metadata semantics as the replica branch."""
        from gossipfs_tpu.erasure import codec
        from gossipfs_tpu.sdfs.quorum import stripe_read_quorum

        cluster = self.sim.cluster
        live = set(cluster.live)
        stripe = cluster.redundancy == "stripe"
        rq = (stripe_read_quorum(cluster.stripe_k, cluster.stripe_m)
              if stripe else None)
        lost = []
        for name, (version, _digest) in sorted(self.acked.items()):
            if stripe:
                sinfo = cluster.master.stripes.get(name)
                nodes = sinfo.fragment_nodes if sinfo is not None else ()
                slots_ok = sum(
                    1
                    for slot, nd in enumerate(nodes)
                    if nd in live
                    and cluster.stores[nd].version(
                        codec.frag_key(name, slot)) >= version
                )
                ok = slots_ok >= rq
            else:
                info = cluster.master.files.get(name)
                fnodes = info.node_list if info is not None else ()
                ok = any(
                    nd in live
                    and cluster.stores[nd].version(name) >= version
                    for nd in fnodes
                )
            if not ok:
                lost.append(name)
        return {
            "files_acked": len(self.acked),
            "lost": len(lost),
            "lost_files": lost,
        }

    def durability(self) -> dict:
        """All three accountings + the exact-match verdicts the claim
        checks: the harness's cluster-state ledger, the post-hoc event
        replay, and (round 13) the STREAMING monitor's incremental
        ledger — same facts from the online path, plus its invariant
        verdict (zero ``no_acked_write_lost`` violations)."""
        harness = self.audit_stores()
        harness["acked_writes"] = sum(
            1 for e in self.recorder.events
            if e.kind in ("replica_put", "stripe_put")
        )
        harness["repair_events"] = self.sim.repairs_done
        from_events = audit.durability_from_events([
            e for e in self.recorder.events
            if e.kind != "invariant_violation"
        ])
        match = all(
            harness[k] == from_events[k]
            for k in ("acked_writes", "files_acked", "lost")
        ) and harness["repair_events"] == from_events["repair_events"]
        self.recorder.finish()
        mon = self.recorder.monitor
        streaming = mon.summary().get("durability") or {}
        return {
            "harness": harness,
            "events": from_events,
            "match": bool(match),
            "monitor": {
                **mon.verdict(),
                "facts": streaming,
                "match_events": streaming == from_events,
            },
        }

    def close(self) -> None:
        self.recorder.close()


# ---------------------------------------------------------------------------
# the four scenario runs (bench/traffic_bench.py's cosim lane)
# ---------------------------------------------------------------------------


def steady_state(n: int, rounds: int, spec: WorkloadSpec, seed: int = 0,
                 trace: str | None = None) -> dict:
    h = TrafficHarness(n, spec, seed=seed, trace=trace)
    h.warmup()
    window = h.run(rounds)
    h.drain(RECOVERY_DELAY + 2)
    out = {"scenario": "steady", "n": n, **window,
           "repair_bytes_written": h.sim.cluster.repair_bytes_written,
           "repair_copies": h.sim.cluster.repair_copies,
           "durability": h.durability(),
           "traffic_vitals": h.sim.traffic_status()}
    h.close()
    return out


def churn(n: int, rounds: int, spec: WorkloadSpec, crashes: int = 4,
          seed: int = 0, trace: str | None = None) -> dict:
    """Tracked crashes land mid-window while the load keeps arriving."""
    h = TrafficHarness(n, spec, seed=seed, trace=trace)
    h.warmup()
    first = h.run(rounds // 2)
    victims = _victims(h.sim, crashes)
    for v in victims:
        h.sim.detector.crash(v)
    second = h.run(rounds - rounds // 2)
    h.drain(h.sim.config.t_fail + RECOVERY_DELAY + 6)
    out = {
        "scenario": "churn", "n": n, "crashed": victims,
        "before": first, "after_crash": second,
        "repair_bytes_written": h.sim.cluster.repair_bytes_written,
        "repair_copies": h.sim.cluster.repair_copies,
        "durability": h.durability(),
        "traffic_vitals": h.sim.traffic_status(),
    }
    h.close()
    return out


def partition_race(n: int, spec: WorkloadSpec, seed: int = 0,
                   trace: str | None = None, split_rounds: int = 24,
                   rounds_each: int = 8) -> dict:
    """Writes racing a timed partition: load before, DURING, and after a
    half/half split that confines quorum reachability to the master's
    side (cosim._reachable).  The split window exceeds t_fail +
    RECOVERY_DELAY so far-side replicas are detected and repaired onto
    the near side mid-split; post-heal, the ledger must be fully
    durable and some mid-split ops must have been quorum-REJECTED (the
    race's observable)."""
    from gossipfs_tpu.scenarios import split_halves

    h = TrafficHarness(n, spec, seed=seed, trace=trace)
    h.warmup()
    before = h.run(rounds_each)
    start = h.sim.round
    h.sim.load_scenario(
        split_halves(n, start=1, end=1 + split_rounds)
    )
    h.sim.tick(2)  # the split takes effect; reachability confines
    during = h.run(rounds_each)
    # ride out the rest of the split + heal + reconvergence + repairs
    h.drain(max(0, (start + 1 + split_rounds) - h.sim.round) + 2)
    h.sim.clear_scenario()
    after = h.run(rounds_each)
    h.drain(h.sim.config.t_fail + RECOVERY_DELAY + 8)
    # PUTS only: gets on never-written keys miss benignly in every
    # window, so the race's observable must count quorum-starved writes,
    # not read misses (the traffic_durability claim checks this > 0)
    rejected_during = (during["by_op"]["put"]["issued"]
                       - during["by_op"]["put"]["acked"])
    out = {
        "scenario": "partition_race", "n": n,
        "split_rounds": split_rounds,
        "before": before, "during_split": during, "after_heal": after,
        "rejected_during_split": rejected_during,
        "repair_bytes_written": h.sim.cluster.repair_bytes_written,
        "repair_copies": h.sim.cluster.repair_copies,
        "durability": h.durability(),
        "traffic_vitals": h.sim.traffic_status(),
    }
    h.close()
    return out


def repair_storm(n: int, spec: WorkloadSpec, files: int = 128,
                 rack: tuple[int, int] = (8, 8), repair_budget: int = 8,
                 seed: int = 0, trace: str | None = None) -> dict:
    """Kill a correlated rack-sized group at once; the budgeted scheduler
    drains the deficit at ``repair_budget`` repairs per pass.  ``rack``
    = (first node, size).  Returns the per-pass drain curve (from the
    repair events) and the storm's completion round."""
    h = TrafficHarness(n, spec, seed=seed, trace=trace,
                       repair_budget=repair_budget)
    h.warmup()
    assert h.preload(files) == files
    light = dataclasses.replace(spec, rate=max(1.0, spec.rate / 4))
    h.wl = Workload(light)
    h.run(4)
    lo, size = rack
    victims = [x for x in range(lo, lo + size)
               if x != h.sim.config.introducer
               and x != h.sim.cluster.master_node]
    crash_round = h.sim.round
    for v in victims:
        h.sim.detector.crash(v)
    # detection + delayed recovery, then budget-paced drain passes
    deficit_rounds = h.sim.config.t_fail + RECOVERY_DELAY
    drain_horizon = deficit_rounds + (files * 2) // repair_budget + 12
    h.drain(drain_horizon)
    repair_rounds = sorted(
        e.round for e in h.recorder.events
        if e.kind in ("replica_repair", "stripe_repair")
        and e.round > crash_round
    )
    per_round: dict[int, int] = {}
    for r in repair_rounds:
        per_round[r] = per_round.get(r, 0) + 1
    out = {
        "scenario": "repair_storm", "n": n, "files": files,
        "rack_killed": len(victims), "repair_budget": repair_budget,
        "crash_round": crash_round,
        "repairs_total": len(repair_rounds),
        "max_repairs_per_round": max(per_round.values()) if per_round else 0,
        "repair_complete_round": repair_rounds[-1] if repair_rounds else None,
        "storm_drain_rounds": (repair_rounds[-1] - crash_round)
        if repair_rounds else None,
        "repairs_per_round": {str(k): v for k, v in sorted(per_round.items())},
        "repair_bytes_written": h.sim.cluster.repair_bytes_written,
        "repair_copies": h.sim.cluster.repair_copies,
        "durability": h.durability(),
        "traffic_vitals": h.sim.traffic_status(),
    }
    h.close()
    return out


def _victims(sim: CoSim, count: int) -> list[int]:
    """Crash candidates sparing the introducer and the current master."""
    n = sim.config.n
    out = []
    step = max(n // (count + 1), 1)
    x = step
    while len(out) < count and x < n:
        if x not in (sim.config.introducer, sim.cluster.master_node):
            out.append(x)
        x += step
    return out
