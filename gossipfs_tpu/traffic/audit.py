"""Durability facts derived from flight-recorder events ALONE.

The traffic harness reports durability from cluster state (it can read
every store).  This module re-derives the same facts by replaying the
event stream — ``replica_put`` (acked version + acking nodes), ``crash``
/ ``join`` (ground-truth liveness), ``replica_repair`` (landed copies),
``replica_delete`` — with no access to the run.  ``tools/timeline.py``
attaches this to its analysis whenever a stream carries traffic events,
and ``tools/verify_claims.py``'s ``traffic_durability`` claim requires
the two accountings to agree EXACTLY (the observability subsystem's
standing-oracle pattern, applied to the data plane).

The erasure plane rides the same machine: ``stripe_put`` (slot-aligned
acking fragment holders + the stripe's k) and ``stripe_repair`` (landed
slot/target pairs) maintain a PER-SLOT ledger, and a stripe counts lost
when fewer than k distinct slots retain a live fresh holder — the MDS
bound, audited from events alone.

Conservative by construction: read-repair refills (a stale replica
pulling fresh bytes during a get) emit no event, so the event-side
replica sets can only UNDER-count copies — an event-side "zero lost"
verdict is therefore at least as strong as the harness's.

Round 13: the replay is a CLASS (:class:`DurabilityReplay`) so the
streaming monitor (``obs/monitor.py``) maintains the same ledger
incrementally, one event at a time; :func:`durability_from_events` is
the post-hoc wrapper over it — one state machine, two consumption
modes, so the two accountings cannot drift.

Pure python + stdlib only (the obs package convention), so the deploy
lane's jax-free tooling can import it too.
"""

from __future__ import annotations


class DurabilityReplay:
    """The event-replay durability state machine, one event at a time.

    ``observe`` consumes events in stream order.  Within one round the
    canonical ordering puts ground-truth liveness verbs (crash/join)
    before data-plane rows — the recorder streams emit them that way
    (the detector ticks before the control plane reacts), and the
    post-hoc wrapper enforces it with an explicit sort, so the
    incremental and sorted replays walk identical sequences on any
    round-ordered stream.
    """

    def __init__(self) -> None:
        self.dead: set[int] = set()
        # file -> {node: version} as far as events can know it
        self.holders: dict[str, dict[int, int]] = {}
        # stripe mode: file -> {slot: {node: version}} — PER SLOT, because
        # loss is counted in distinct recoverable slots: a rejoined stale
        # holder and its repair replacement can both hold the SAME slot,
        # and flattening them to nodes would double-count that fragment
        self.stripe_slots: dict[str, dict[int, dict[int, int]]] = {}
        self.stripe_k: dict[str, int] = {}
        self.acked_version: dict[str, int] = {}
        self.acked_writes = 0
        self.repair_events = 0
        self.repair_complete_round: int | None = None

    def observe(self, e) -> None:
        d = e.detail
        if e.kind == "crash":
            self.dead.add(e.subject)
        elif e.kind == "join":
            self.dead.discard(e.subject)
        elif e.kind == "replica_put":
            self.acked_writes += 1
            name, version = d.get("file"), int(d.get("version", 0))
            self.acked_version[name] = version
            h = self.holders.setdefault(name, {})
            for nd in d.get("replicas", []):
                h[int(nd)] = version
        elif e.kind == "replica_repair":
            self.repair_events += 1
            self.repair_complete_round = e.round
            name, version = d.get("file"), int(d.get("version", 0))
            h = self.holders.setdefault(name, {})
            for nd in d.get("targets", []):
                h[int(nd)] = version
        elif e.kind == "stripe_put":
            self.acked_writes += 1
            name, version = d.get("file"), int(d.get("version", 0))
            self.acked_version[name] = version
            self.stripe_k[name] = int(d.get("k", 0))
            slots = self.stripe_slots.setdefault(name, {})
            for slot, nd in enumerate(d.get("fragments", [])):
                if int(nd) >= 0:
                    slots.setdefault(slot, {})[int(nd)] = version
        elif e.kind == "stripe_repair":
            self.repair_events += 1
            self.repair_complete_round = e.round
            name, version = d.get("file"), int(d.get("version", 0))
            slots = self.stripe_slots.setdefault(name, {})
            for slot, nd in zip(d.get("slots", []), d.get("targets", [])):
                slots.setdefault(int(slot), {})[int(nd)] = version
        elif e.kind == "replica_delete":
            # mode-neutral delete verb: drops replica and stripe state
            self.acked_version.pop(d.get("file"), None)
            self.holders.pop(d.get("file"), None)
            self.stripe_slots.pop(d.get("file"), None)
            self.stripe_k.pop(d.get("file"), None)

    def _slots_alive(self, name: str, version: int) -> int:
        """Distinct slots with >= 1 event-known live holder at the acked
        version (stripe files only)."""
        return sum(
            1
            for nodes in self.stripe_slots.get(name, {}).values()
            if any(nd not in self.dead and v >= version
                   for nd, v in nodes.items())
        )

    def lost_files(self) -> list[str]:
        """Files whose last-acked version survives on NO event-known
        live replica right now (end-of-stream: the durability verdict).
        Stripe files are lost below k live fresh SLOTS — the MDS
        reconstruction bound, counted per slot."""
        out = []
        for name, version in self.acked_version.items():
            if name in self.stripe_k:
                if self._slots_alive(name, version) < self.stripe_k[name]:
                    out.append(name)
            elif not any(
                nd not in self.dead and v >= version
                for nd, v in self.holders.get(name, {}).items()
            ):
                out.append(name)
        return sorted(out)

    def facts(self) -> dict:
        lost_files = self.lost_files()
        return {
            "acked_writes": self.acked_writes,
            "files_acked": len(self.acked_version),
            "repair_events": self.repair_events,
            "repair_complete_round": self.repair_complete_round,
            "lost": len(lost_files),
            "lost_files": lost_files,
        }


def durability_from_events(events) -> dict:
    """Replay a (round-ordered) event stream into durability facts.

    Returns the comparable fact set: ``acked_writes`` (replica_put event
    count), ``files_acked`` (distinct files with an undeleted acked
    write), ``repair_events``, ``lost`` + ``lost_files`` (files whose
    last-acked version survives on NO event-known live replica at end of
    stream), and ``repair_complete_round`` (the last repair's round — the
    repair-storm completion mark).
    """
    replay = DurabilityReplay()
    for e in sorted(
        events, key=lambda e: (e.round, 0 if e.kind in ("crash", "join")
                               else 1)
    ):
        replay.observe(e)
    return replay.facts()
