"""Durability facts derived from flight-recorder events ALONE.

The traffic harness reports durability from cluster state (it can read
every store).  This module re-derives the same facts by replaying the
event stream — ``replica_put`` (acked version + acking nodes), ``crash``
/ ``join`` (ground-truth liveness), ``replica_repair`` (landed copies),
``replica_delete`` — with no access to the run.  ``tools/timeline.py``
attaches this to its analysis whenever a stream carries traffic events,
and ``tools/verify_claims.py``'s ``traffic_durability`` claim requires
the two accountings to agree EXACTLY (the observability subsystem's
standing-oracle pattern, applied to the data plane).

Conservative by construction: read-repair refills (a stale replica
pulling fresh bytes during a get) emit no event, so the event-side
replica sets can only UNDER-count copies — an event-side "zero lost"
verdict is therefore at least as strong as the harness's.

Round 13: the replay is a CLASS (:class:`DurabilityReplay`) so the
streaming monitor (``obs/monitor.py``) maintains the same ledger
incrementally, one event at a time; :func:`durability_from_events` is
the post-hoc wrapper over it — one state machine, two consumption
modes, so the two accountings cannot drift.

Pure python + stdlib only (the obs package convention), so the deploy
lane's jax-free tooling can import it too.
"""

from __future__ import annotations


class DurabilityReplay:
    """The event-replay durability state machine, one event at a time.

    ``observe`` consumes events in stream order.  Within one round the
    canonical ordering puts ground-truth liveness verbs (crash/join)
    before data-plane rows — the recorder streams emit them that way
    (the detector ticks before the control plane reacts), and the
    post-hoc wrapper enforces it with an explicit sort, so the
    incremental and sorted replays walk identical sequences on any
    round-ordered stream.
    """

    def __init__(self) -> None:
        self.dead: set[int] = set()
        # file -> {node: version} as far as events can know it
        self.holders: dict[str, dict[int, int]] = {}
        self.acked_version: dict[str, int] = {}
        self.acked_writes = 0
        self.repair_events = 0
        self.repair_complete_round: int | None = None

    def observe(self, e) -> None:
        d = e.detail
        if e.kind == "crash":
            self.dead.add(e.subject)
        elif e.kind == "join":
            self.dead.discard(e.subject)
        elif e.kind == "replica_put":
            self.acked_writes += 1
            name, version = d.get("file"), int(d.get("version", 0))
            self.acked_version[name] = version
            h = self.holders.setdefault(name, {})
            for nd in d.get("replicas", []):
                h[int(nd)] = version
        elif e.kind == "replica_repair":
            self.repair_events += 1
            self.repair_complete_round = e.round
            name, version = d.get("file"), int(d.get("version", 0))
            h = self.holders.setdefault(name, {})
            for nd in d.get("targets", []):
                h[int(nd)] = version
        elif e.kind == "replica_delete":
            self.acked_version.pop(d.get("file"), None)
            self.holders.pop(d.get("file"), None)

    def lost_files(self) -> list[str]:
        """Files whose last-acked version survives on NO event-known
        live replica right now (end-of-stream: the durability verdict)."""
        return sorted(
            name
            for name, version in self.acked_version.items()
            if not any(
                nd not in self.dead and v >= version
                for nd, v in self.holders.get(name, {}).items()
            )
        )

    def facts(self) -> dict:
        lost_files = self.lost_files()
        return {
            "acked_writes": self.acked_writes,
            "files_acked": len(self.acked_version),
            "repair_events": self.repair_events,
            "repair_complete_round": self.repair_complete_round,
            "lost": len(lost_files),
            "lost_files": lost_files,
        }


def durability_from_events(events) -> dict:
    """Replay a (round-ordered) event stream into durability facts.

    Returns the comparable fact set: ``acked_writes`` (replica_put event
    count), ``files_acked`` (distinct files with an undeleted acked
    write), ``repair_events``, ``lost`` + ``lost_files`` (files whose
    last-acked version survives on NO event-known live replica at end of
    stream), and ``repair_complete_round`` (the last repair's round — the
    repair-storm completion mark).
    """
    replay = DurabilityReplay()
    for e in sorted(
        events, key=lambda e: (e.round, 0 if e.kind in ("crash", "join")
                               else 1)
    ):
        replay.observe(e)
    return replay.facts()
