"""Durability facts derived from flight-recorder events ALONE.

The traffic harness reports durability from cluster state (it can read
every store).  This module re-derives the same facts by replaying the
event stream — ``replica_put`` (acked version + acking nodes), ``crash``
/ ``join`` (ground-truth liveness), ``replica_repair`` (landed copies),
``replica_delete`` — with no access to the run.  ``tools/timeline.py``
attaches this to its analysis whenever a stream carries traffic events,
and ``tools/verify_claims.py``'s ``traffic_durability`` claim requires
the two accountings to agree EXACTLY (the observability subsystem's
standing-oracle pattern, applied to the data plane).

Conservative by construction: read-repair refills (a stale replica
pulling fresh bytes during a get) emit no event, so the event-side
replica sets can only UNDER-count copies — an event-side "zero lost"
verdict is therefore at least as strong as the harness's.

Pure python + stdlib only (the obs package convention), so the deploy
lane's jax-free tooling can import it too.
"""

from __future__ import annotations


def durability_from_events(events) -> dict:
    """Replay a (round-ordered) event stream into durability facts.

    Returns the comparable fact set: ``acked_writes`` (replica_put event
    count), ``files_acked`` (distinct files with an undeleted acked
    write), ``repair_events``, ``lost`` + ``lost_files`` (files whose
    last-acked version survives on NO event-known live replica at end of
    stream), and ``repair_complete_round`` (the last repair's round — the
    repair-storm completion mark).
    """
    events = sorted(
        events, key=lambda e: (e.round, 0 if e.kind in ("crash", "join")
                               else 1)
    )
    dead: set[int] = set()
    # file -> {node: version} as far as events can know it
    holders: dict[str, dict[int, int]] = {}
    acked_version: dict[str, int] = {}
    acked_writes = 0
    repair_events = 0
    repair_complete_round = None
    for e in events:
        d = e.detail
        if e.kind == "crash":
            dead.add(e.subject)
        elif e.kind == "join":
            dead.discard(e.subject)
        elif e.kind == "replica_put":
            acked_writes += 1
            name, version = d.get("file"), int(d.get("version", 0))
            acked_version[name] = version
            h = holders.setdefault(name, {})
            for nd in d.get("replicas", []):
                h[int(nd)] = version
        elif e.kind == "replica_repair":
            repair_events += 1
            repair_complete_round = e.round
            name, version = d.get("file"), int(d.get("version", 0))
            h = holders.setdefault(name, {})
            for nd in d.get("targets", []):
                h[int(nd)] = version
        elif e.kind == "replica_delete":
            acked_version.pop(d.get("file"), None)
            holders.pop(d.get("file"), None)
    lost_files = sorted(
        name
        for name, version in acked_version.items()
        if not any(
            nd not in dead and v >= version
            for nd, v in holders.get(name, {}).items()
        )
    )
    return {
        "acked_writes": acked_writes,
        "files_acked": len(acked_version),
        "repair_events": repair_events,
        "repair_complete_round": repair_complete_round,
        "lost": len(lost_files),
        "lost_files": lost_files,
    }
