"""Tensorized placement + repair planning over the live [N] alive mask.

The per-file Python repair loop (``SDFSMaster.plan_repairs``) is the right
shape at CLI scale; at the north-star scale — 100k+ members, tens of
thousands of files, thousands of arrivals per round — placement and repair
planning must be ARRAY programs against the same [N] masks the gossip
layer already produces.  This module is that program:

  * **placement** — ``sdfs/placement.py::place_batch`` (extended round 12
    with the rejection-free sampled method) places thousands of files per
    round without an [n_files, N] intermediate;
  * **repair planning** — the whole replicas-lost x under-replicated-files
    diff is ONE masked computation: per-file surviving-replica counts from
    ``alive[replicas]``, deficiency scores, and a single ``top_k`` picking
    the ``budget`` most-deficient repairable files (the repair-storm
    scheduler: a rack-kill's thousand deficient files drain at
    budget/round, most-endangered first, instead of serializing);
  * **commit** — survivors compact to the row front, fresh reachable
    non-replica picks fill the tail, all in-array.

Quorum arithmetic is IMPORTED from ``sdfs/quorum.py`` (``write_quorum`` /
``read_quorum``) — never re-derived here; a lint test enforces it.

``ReplicaTable`` is the host-side wrapper the scale bench drives
(``bench/traffic_bench.py --scale``): it holds the replica table on
device and exposes place / plan+commit / ack-accounting steps.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.sdfs.placement import (
    OVERSAMPLE_FACTOR,
    first_k_distinct,
    place_batch,
    sample_members,
)
from gossipfs_tpu.sdfs.quorum import read_quorum, write_quorum
from gossipfs_tpu.sdfs.types import REPLICATION_FACTOR


class RepairPlan(NamedTuple):
    """One budgeted planning pass over the whole table (device arrays).

    ``idx``/``valid`` — the up-to-``budget`` chosen file rows (invalid
    slots are budget headroom beyond the deficient count); ``source`` —
    first reachable surviving replica per chosen file; ``need`` — copies
    required; ``picks`` — [budget, k] fresh reachable non-replica nodes
    (-1 past ``need``); ``deficient`` — total repairable-deficient files
    BEFORE the budget cut (the backlog gauge); ``lost`` — files whose
    replicas are all gone (unrecoverable this pass).
    """

    idx: jax.Array
    valid: jax.Array
    source: jax.Array
    need: jax.Array
    picks: jax.Array
    deficient: jax.Array
    lost: jax.Array


def _working(replicas: jax.Array, mask: jax.Array) -> jax.Array:
    """[F, k] — replica slot holds a node currently in ``mask``."""
    return (replicas >= 0) & mask[jnp.clip(replicas, 0)]


@functools.partial(jax.jit, static_argnames=("budget", "k"))
def plan_repairs_tensor(
    key: jax.Array,
    replicas: jax.Array,
    n_files: jax.Array,
    alive: jax.Array,
    reach: jax.Array,
    budget: int,
    k: int = REPLICATION_FACTOR,
) -> RepairPlan:
    """The masked-top-k repair planner (semantics of
    ``SDFSMaster.plan_repairs``, vectorized): deficient = fewer than
    min(k, n_alive) surviving replicas, at least one survivor reachable
    (the copy source); the ``budget`` most-deficient files get
    ``k - survivors`` fresh picks drawn uniformly without replacement
    from reachable non-replica nodes.  Deterministic under ``key``.
    """
    cap = replicas.shape[0]
    used = jnp.arange(cap) < n_files
    working = _working(replicas, alive) & used[:, None]
    w = working.sum(axis=1)
    target = jnp.minimum(k, alive.sum())
    sourced = working & reach[jnp.clip(replicas, 0)]
    placed = used & (replicas >= 0).any(axis=1)
    lost = placed & (w == 0)
    deficient = placed & (w < target) & (w > 0) & sourced.any(axis=1)

    score = jnp.where(deficient, (k - w).astype(jnp.int32), 0)
    top, idx = jax.lax.top_k(score, min(budget, cap))
    valid = top > 0

    src_slot = jnp.argmax(sourced[idx], axis=1)
    source = jnp.where(
        valid, replicas[idx, src_slot], -1
    )
    need = jnp.where(valid, k - w[idx], 0)

    # fresh picks: oversampled reachable draws, banned = the file's own
    # current replicas (dead ones included — a dead-but-listed node must
    # not be re-picked; it may still hold stale bytes and rejoin)
    draws = sample_members(key, reach, idx.shape[0], OVERSAMPLE_FACTOR * k)
    forb = replicas[idx]
    banned = (
        (draws[:, :, None] == forb[:, None, :]) & (forb >= 0)[:, None, :]
    ).any(axis=2)
    picks_full = first_k_distinct(jnp.where(banned, -1, draws), k)
    picks = jnp.where(
        jnp.arange(k)[None, :] < need[:, None], picks_full, -1
    )
    return RepairPlan(
        idx=idx, valid=valid, source=source, need=need, picks=picks,
        deficient=deficient.sum(), lost=lost,
    )


@functools.partial(jax.jit, static_argnames=("k",))
def commit_repairs(
    replicas: jax.Array,
    idx: jax.Array,
    valid: jax.Array,
    picks: jax.Array,
    alive: jax.Array,
    k: int = REPLICATION_FACTOR,
) -> jax.Array:
    """Apply a :class:`RepairPlan` in-array: each chosen row becomes
    survivors (compacted to the front) + the landed picks — exactly
    ``commit_repair``'s survivors-plus-successful-copies rule."""
    rows = replicas[idx]
    working = _working(rows, alive)
    order = jnp.argsort(~working, axis=1, stable=True)
    compacted = jnp.take_along_axis(rows, order, axis=1)
    w = working.sum(axis=1)
    pos = jnp.arange(k)[None, :]
    pick_idx = pos - w[:, None]
    shifted = jnp.take_along_axis(picks, jnp.clip(pick_idx, 0, k - 1), 1)
    newrow = jnp.where(
        pos < w[:, None],
        compacted,
        jnp.where((pick_idx >= 0) & (shifted >= 0), shifted, -1),
    )
    newrow = jnp.where(valid[:, None], newrow, rows)
    return replicas.at[idx].set(newrow)


@functools.partial(jax.jit, static_argnames=("k",))
def replication_stats(
    replicas: jax.Array,
    n_files: jax.Array,
    alive: jax.Array,
    reach: jax.Array,
    k: int = REPLICATION_FACTOR,
) -> jax.Array:
    """[k + 3] summary vector: histogram of surviving-replica counts
    (0..k live replicas — slot 0 is the lost-file count) plus acked-write
    reachability: files whose reachable replicas meet the WRITE quorum,
    and files meeting the READ quorum (``sdfs/quorum.py`` — the single
    owner of both thresholds)."""
    cap = replicas.shape[0]
    used = jnp.arange(cap) < n_files
    placed = used & (replicas >= 0).any(axis=1)
    w = (_working(replicas, alive) & placed[:, None]).sum(axis=1)
    hist = jnp.zeros((k + 1,), dtype=jnp.int32).at[
        jnp.where(placed, w, k + 0)
    ].add(placed.astype(jnp.int32), mode="drop")
    r = (_working(replicas, reach) & placed[:, None]).sum(axis=1)
    w_ok = (placed & (r >= write_quorum(k))).sum()
    r_ok = (placed & (r >= read_quorum(k))).sum()
    return jnp.concatenate([hist, w_ok[None], r_ok[None]])


class ReplicaTable:
    """Device-resident file->replica table: the 100k-member traffic lane.

    The byte plane is out of scope here (BASELINE.md documents the honest
    CPU-pinned boundary); what this models EXACTLY is the metadata
    plane's placement and repair decisions against live membership masks
    — the part that was per-file Python and is now O(1) array steps per
    round at any N the masks support.
    """

    def __init__(self, capacity: int, n: int,
                 k: int = REPLICATION_FACTOR, seed: int = 0):
        self.capacity = capacity
        self.n = n
        self.k = k
        self.replicas = jnp.full((capacity, k), -1, dtype=jnp.int32)
        self.n_files = 0
        self._key = jax.random.PRNGKey(seed)
        self._ctr = 0

    def _next_key(self) -> jax.Array:
        self._ctr += 1
        return jax.random.fold_in(self._key, self._ctr)

    def place(self, alive: jax.Array, count: int,
              method: str = "auto") -> jax.Array:
        """Place ``count`` new files over ``alive``; returns their rows."""
        if self.n_files + count > self.capacity:
            raise ValueError("ReplicaTable capacity exceeded")
        rows = place_batch(self._next_key(), alive, count, self.k,
                           method=method)
        self.replicas = jax.lax.dynamic_update_slice(
            self.replicas, rows, (self.n_files, 0)
        )
        self.n_files += count
        return rows

    def plan_and_commit(self, alive: jax.Array, reach: jax.Array,
                        budget: int) -> dict:
        """One budgeted repair pass; commits landed picks in-array and
        returns the pass's host-side counters."""
        plan = plan_repairs_tensor(
            self._next_key(), self.replicas, jnp.int32(self.n_files),
            alive, reach, budget, self.k,
        )
        self.replicas = commit_repairs(
            self.replicas, plan.idx, plan.valid, plan.picks, alive, self.k
        )
        executed = int(plan.valid.sum())
        return {
            "repairs_executed": executed,
            "repairs_pending": max(int(plan.deficient) - executed, 0),
            "copies_ordered": int((plan.picks >= 0).sum()),
            "files_lost": int(plan.lost.sum()),
        }

    def stats(self, alive: jax.Array, reach: jax.Array) -> dict:
        v = np.asarray(replication_stats(
            self.replicas, jnp.int32(self.n_files), alive, reach, self.k
        ))
        return {
            "files": self.n_files,
            "replica_histogram": v[: self.k + 1].tolist(),
            "write_quorum_reachable": int(v[self.k + 1]),
            "read_quorum_reachable": int(v[self.k + 2]),
        }
