"""Open-loop SDFS load generation: deterministic arrivals, skewed keys.

OPEN-LOOP means arrivals are a function of TIME, not of completions: the
generator emits ``rate`` operations every round regardless of how the
previous round's ops fared, so a saturated or partitioned system shows up
as rejected/failed ops and growing repair backlog instead of silently
slowing the generator down (the classic closed-loop coordination bug in
load testing).  Determinism is per-(seed, round): the op list for round r
never depends on how many times or in what order rounds were generated.

Workload shape mirrors the reference's benchmark workload: the repo's
checked-in Wikipedia-dump shards are ~3-4 MB (file1..10.txt; BASELINE.md
"Published claims"), so the default size distribution spans 64 KB to
4 MB with most mass at the shard magnitudes.  Key popularity is Zipf by
default (a few hot files take most writes — what makes the 60-round
write-write conflict window actually bind) or uniform.

Two drivers ship here: ``drive_cosim`` (the interactive CoSim — in-process
byte movement, flight-recordable) and ``drive_shim`` (the gRPC shim —
base64-framed protobuf over a real HTTP/2 socket, the process-boundary
path).  Both consume the same op stream, so their throughput rows are
comparable.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import random
import time

from gossipfs_tpu.sdfs.types import STRIPE_K, STRIPE_M

# the reference shards' magnitudes: 64 KB / 1 MB / 3.2 MB / 4 MB
# (file10.txt is 3.2 MB, file5.txt 4.0 MB — BASELINE.md "wire_ops")
REFERENCE_SIZES = (65_536, 1_048_576, 3_276_800, 4_194_304)
REFERENCE_SIZE_WEIGHTS = (1.0, 2.0, 3.0, 3.0)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """The declarative workload knob set (JSON-friendly field types).

    ``rate`` — mean operations per round (open-loop; fractional rates
    accumulate, e.g. 0.5 issues one op every other round).
    ``put_frac``/``delete_frac`` — operation mix; the remainder is gets.
    ``n_keys`` — keyspace size (names ``f<k>.txt``).
    ``popularity`` — "zipf" (exponent ``zipf_s``) or "uniform".
    ``sizes``/``size_weights`` — logical file-size distribution.
    ``payload_cap`` — cap on bytes ACTUALLY materialized per op: big runs
    keep the logical size for the record while moving capped payloads
    (the honest CPU-pinned boundary is documented in BASELINE.md; 0/None
    = move the full logical size).
    ``redundancy`` — "replica" (4 full copies) or "stripe" (the erasure
    plane: ``stripe_k`` data + ``stripe_m`` parity Reed-Solomon
    fragments per file — gossipfs_tpu/erasure/).
    """

    rate: float = 16.0
    put_frac: float = 0.3
    delete_frac: float = 0.02
    n_keys: int = 128
    popularity: str = "zipf"
    zipf_s: float = 1.1
    sizes: tuple[int, ...] = REFERENCE_SIZES
    size_weights: tuple[float, ...] = REFERENCE_SIZE_WEIGHTS
    payload_cap: int | None = 65_536
    seed: int = 0
    redundancy: str = "replica"
    stripe_k: int = STRIPE_K
    stripe_m: int = STRIPE_M

    def __post_init__(self):
        if not 0 <= self.put_frac + self.delete_frac <= 1:
            raise ValueError("put_frac + delete_frac must be within [0, 1]")
        if self.popularity not in ("zipf", "uniform"):
            raise ValueError(f"unknown popularity: {self.popularity!r}")
        if len(self.sizes) != len(self.size_weights):
            raise ValueError("sizes and size_weights lengths differ")
        if self.rate <= 0 or self.n_keys <= 0:
            raise ValueError("rate and n_keys must be positive")
        if self.redundancy not in ("replica", "stripe"):
            raise ValueError(f"unknown redundancy: {self.redundancy!r}")
        if self.stripe_k < 1 or self.stripe_m < 1:
            raise ValueError("stripe_k and stripe_m must be >= 1")


@dataclasses.dataclass(frozen=True)
class Op:
    """One arrival: ``kind`` in {"put", "get", "delete"}; ``size`` is the
    LOGICAL byte size (puts only; the driver may cap materialized bytes)."""

    kind: str
    key: str
    size: int = 0


class Workload:
    """Deterministic open-loop op stream over a :class:`WorkloadSpec`."""

    def __init__(self, spec: WorkloadSpec):
        self.spec = spec
        # Zipf CDF over key RANKS; a seed-keyed permutation maps rank ->
        # key id so "which keys are hot" varies with the seed, not just
        # how hot hotness is
        weights = (
            [1.0 / (r + 1) ** spec.zipf_s for r in range(spec.n_keys)]
            if spec.popularity == "zipf"
            else [1.0] * spec.n_keys
        )
        total = sum(weights)
        acc, cdf = 0.0, []
        for w in weights:
            acc += w
            cdf.append(acc / total)
        self._cdf = cdf
        perm = list(range(spec.n_keys))
        random.Random(f"wl-perm:{spec.seed}").shuffle(perm)
        self._rank_to_key = perm
        sacc, scdf = 0.0, []
        stot = sum(spec.size_weights)
        for w in spec.size_weights:
            sacc += w
            scdf.append(sacc / stot)
        self._size_cdf = scdf

    def _pick(self, cdf: list[float], u: float) -> int:
        lo, hi = 0, len(cdf) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if u <= cdf[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def arrivals(self, rnd: int) -> int:
        """Open-loop arrival count for round ``rnd``: the deterministic
        rate accumulator floor(rate*(r+1)) - floor(rate*r) — constant
        long-run rate, no completion feedback."""
        rate = self.spec.rate
        return int(rate * (rnd + 1)) - int(rate * rnd)

    def ops(self, rnd: int) -> list[Op]:
        """The round's op list — a pure function of (spec.seed, rnd)."""
        rng = random.Random(f"wl:{self.spec.seed}:{rnd}")
        out: list[Op] = []
        for _ in range(self.arrivals(rnd)):
            key = f"f{self._rank_to_key[self._pick(self._cdf, rng.random())]}.txt"
            u = rng.random()
            if u < self.spec.put_frac:
                size = self.spec.sizes[self._pick(self._size_cdf, rng.random())]
                out.append(Op("put", key, size))
            elif u < self.spec.put_frac + self.spec.delete_frac:
                out.append(Op("delete", key))
            else:
                out.append(Op("get", key))
        return out

    def payload(self, key: str, rnd: int, size: int) -> bytes:
        """Deterministic content for (key, round): verifiable after the
        fact (``payload_digest``) and capped at ``payload_cap`` actually
        materialized bytes — the logical ``size`` rides the op record."""
        cap = self.spec.payload_cap
        n = size if not cap else min(size, cap)
        token = f"{self.spec.seed}:{key}:{rnd}:{size}|".encode()
        return (token * (n // len(token) + 1))[:n]


def payload_digest(data: bytes) -> str:
    """Short content digest for durability bookkeeping (not security)."""
    return hashlib.sha256(data).hexdigest()[:16]


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def drive_cosim(sim, wl: Workload, rounds: int, *, recorder=None,
                on_ack=None, on_delete=None) -> dict:
    """Issue each round's arrivals against a CoSim, then tick one round.

    Write-write conflicts are auto-confirmed (the programmatic-client
    convention every bench uses; rejected-anyway puts count as issued,
    not acked).  ``on_ack(key, version, digest)`` / ``on_delete(key)``
    feed the harness's durability ledger; ``recorder`` (a FlightRecorder)
    gets one ``client_op`` latency row per op.  Returns the counter/latency
    summary for the window.
    """
    from gossipfs_tpu.obs.schema import Event

    lat = {"put": [], "get": [], "delete": []}
    counts = {"put": [0, 0], "get": [0, 0], "delete": [0, 0]}  # issued, acked
    confirm = lambda: True  # noqa: E731
    for _ in range(rounds):
        rnd = sim.round
        for op in wl.ops(rnd):
            t0 = time.perf_counter()
            if op.kind == "put":
                data = wl.payload(op.key, rnd, op.size)
                ok = sim.put(op.key, data, confirm=confirm)
                if ok and on_ack is not None:
                    meta = (sim.cluster.master.stripes
                            if sim.cluster.redundancy == "stripe"
                            else sim.cluster.master.files)
                    version = meta[op.key].version
                    on_ack(op.key, version, payload_digest(data))
            elif op.kind == "get":
                ok = sim.get(op.key) is not None
            else:
                ok = sim.delete(op.key)
                if ok and on_delete is not None:
                    on_delete(op.key)
            ms = (time.perf_counter() - t0) * 1e3
            counts[op.kind][0] += 1
            counts[op.kind][1] += bool(ok)
            lat[op.kind].append(ms)
            if recorder is not None:
                recorder.emit(Event(
                    round=rnd, observer=-1, subject=-1, kind="client_op",
                    detail={"op": op.kind, "file": op.key, "bytes": op.size,
                            "ms": round(ms, 4), "ok": bool(ok)},
                ))
        sim.tick(1)
    return summarize_window(counts, lat, rounds)


def drive_shim(client, wl: Workload, rounds: int, *, start_round: int = 0,
               recorder=None) -> dict:
    """The same op stream through the gRPC shim (process boundary): Put/
    Get/Delete RPCs with auto-confirm, one Advance per round.  ``client``
    is a ``shim.client.ShimClient`` dialed at a live ``ShimServer``."""
    from gossipfs_tpu.obs.schema import Event

    lat = {"put": [], "get": [], "delete": []}
    counts = {"put": [0, 0], "get": [0, 0], "delete": [0, 0]}
    rnd = start_round
    for _ in range(rounds):
        for op in wl.ops(rnd):
            t0 = time.perf_counter()
            if op.kind == "put":
                data = wl.payload(op.key, rnd, op.size)
                reply = client.call(
                    "Put", file=op.key,
                    data_b64=base64.b64encode(data).decode(), confirm=True,
                )
                ok = bool(reply.get("ok"))
            elif op.kind == "get":
                ok = bool(client.call("Get", file=op.key).get("found"))
            else:
                ok = bool(client.call("Delete", file=op.key).get("ok"))
            ms = (time.perf_counter() - t0) * 1e3
            counts[op.kind][0] += 1
            counts[op.kind][1] += ok
            lat[op.kind].append(ms)
            if recorder is not None:
                recorder.emit(Event(
                    round=rnd, observer=-1, subject=-1, kind="client_op",
                    detail={"op": op.kind, "file": op.key, "bytes": op.size,
                            "ms": round(ms, 4), "ok": ok},
                ))
        rnd = int(client.call("Advance", rounds=1)["round"])
    return summarize_window(counts, lat, rounds)


def quantiles(vals: list[float]) -> dict:
    """Nearest-rank p50/p95/max rollup for latency lists — the ONE
    convention every client_op consumer uses (the drivers' window
    summaries here, tools/timeline.py's stream rollup)."""
    if not vals:
        return {"p50_ms": None, "p95_ms": None, "max_ms": None}
    s = sorted(vals)
    return {
        "p50_ms": round(s[len(s) // 2], 4),
        "p95_ms": round(s[min(len(s) - 1, int(len(s) * 0.95))], 4),
        "max_ms": round(s[-1], 4),
    }


def summarize_window(counts: dict, lat: dict, rounds: int) -> dict:
    """One driver window's throughput/latency row set."""
    issued = sum(c[0] for c in counts.values())
    acked = sum(c[1] for c in counts.values())
    return {
        "rounds": rounds,
        "ops_issued": issued,
        "ops_acked": acked,
        "ops_per_round": round(issued / rounds, 3) if rounds else 0.0,
        "by_op": {
            kind: {"issued": counts[kind][0], "acked": counts[kind][1],
                   **quantiles(lat[kind])}
            for kind in counts
        },
    }
