"""SDFS traffic plane: open-loop load + tensorized placement/repair planning.

The reference is a *file system* (PAPER.md §0), yet until this subsystem
the data plane was only benched by a handful of sequential ops at 4-8
nodes.  ``traffic/`` closes ROADMAP's "SDFS under production traffic"
item with three pieces:

  * ``workload.py`` — a deterministic OPEN-LOOP generator (arrivals keep
    coming whether or not the system keeps up): put/get/delete mixes at a
    controlled per-round rate, Zipf or uniform key popularity, file-size
    distribution mirroring the reference's ~3-4 MB Wikipedia shards;
    drivers for the interactive CoSim and the gRPC shim.
  * ``planner.py`` — placement and repair planning TENSORIZED against the
    live [N] alive mask: thousands of placements per round and the whole
    repair set (replicas-lost x under-replicated-files) as one masked
    top-k, with a per-round repair budget (the repair-storm scheduler).
    Quorum arithmetic is imported from ``sdfs/quorum.py`` — never
    re-derived here (lint-tested).
  * ``harness.py`` + ``audit.py`` — latency/throughput/durability runs
    (steady state, churn, a write burst racing a timed partition, a
    rack-kill repair storm), every op and repair flight-recorded so
    ``tools/timeline.py`` re-derives the durability facts from events
    alone (``verify_claims.py traffic_durability``).

Committed artifact: ``TRAFFIC_r12.json`` (``bench/traffic_bench.py``).
"""

from gossipfs_tpu.traffic.workload import Workload, WorkloadSpec  # noqa: F401
