"""Scenario engine: declarative network-partition & link-fault injection.

One :class:`~gossipfs_tpu.scenarios.schedule.FaultScenario` file drives
all three transport engines — the tensor sim (edge filters on the
sampled topology), the asyncio UDP engine (send-hook drop rule), and
the per-process deployment (the rule table pushed over the control
plane).  See ``scenarios/schedule.py`` for the schema and semantics.

The tensor backend's exports resolve LAZILY (module ``__getattr__``):
``schedule``/``runtime`` are pure-Python, and the deploy daemons — a
documented jax-free path that must start in milliseconds — import them
via this package from their ``ScenarioLoad`` RPC.  An eager
``tensor`` import here would pull jax into every daemon the moment a
scenario arms.
"""

from gossipfs_tpu.scenarios.runtime import ScenarioRuntime
from gossipfs_tpu.scenarios.schedule import (
    CorrelatedOutage,
    FaultScenario,
    Flapping,
    LinkFault,
    Partition,
    SlowNode,
    expand_selector,
    split_halves,
)

_TENSOR_EXPORTS = (
    "TensorScenario",
    "compile_tensor",
    "filter_edges",
    "require_scenario_config",
    "xla_fallback_config",
)

__all__ = [
    "CorrelatedOutage",
    "FaultScenario",
    "Flapping",
    "LinkFault",
    "Partition",
    "ScenarioRuntime",
    "SlowNode",
    "expand_selector",
    "split_halves",
    *_TENSOR_EXPORTS,
]


def __getattr__(name: str):
    if name in _TENSOR_EXPORTS:
        from gossipfs_tpu.scenarios import tensor

        return getattr(tensor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
