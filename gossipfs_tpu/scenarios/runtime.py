"""Per-message scenario evaluation for the socket engines.

The reference implementation of the drop semantics in
``scenarios.schedule``: the asyncio UDP cluster and the per-process
deployment both consult :meth:`ScenarioRuntime.drops` from their
datagram send hook (``detector/udp.py`` ``UdpNode._send``), so a
datagram either leaves the sender or it does not — receivers never know
the scenario exists, exactly like a real netsplit.

Bernoulli loss draws come from one ``random.Random`` stream per runtime
(seeded from the scenario's ``seed``); socket engines are real-time and
not bit-reproducible anyway, so per-message stream position is fine.
The tensor engine uses counter-based draws instead
(``scenarios.tensor.filter_edges``) to stay scan/jit-deterministic.
"""

from __future__ import annotations

import random

from gossipfs_tpu.scenarios.schedule import FaultScenario


class ScenarioRuntime:
    """Evaluates one armed scenario: ``drops(src, dst, rnd)`` per message.

    ``rnd`` is the engine's round counter minus the arming round (the
    caller owns the clock: the in-process UDP cluster counts periods,
    the deployment divides wall time since ``ScenarioLoad`` by the
    gossip period).
    """

    def __init__(self, scenario: FaultScenario):
        self.scenario = scenario
        self._rng = random.Random(scenario.seed)
        # frozen-set membership per rule: the hook runs per datagram
        sc = scenario
        self._parts = [(p.start, p.end, p.pid(sc.n)) for p in sc.partitions]
        self._losses = [
            (f.start, f.end, f.rate, frozenset(f.src), frozenset(f.dst))
            for f in sc.link_faults
        ]
        self._slows = [
            (s.start, s.end, s.stride, frozenset(s.nodes))
            for s in sc.slow_nodes
        ]
        self._flaps = [
            (f, frozenset(f.nodes)) for f in sc.flapping
        ]
        self._outs = [
            (o.start, o.end, frozenset(o.nodes)) for o in sc.outages
        ]

    def drops(self, src: int, dst: int, rnd: int) -> bool:
        """Whether the src -> dst message at round ``rnd`` is dropped."""
        for start, end, pid in self._parts:
            if start <= rnd < end and pid[src] != pid[dst]:
                return True
        for start, end, stride, nodes in self._slows:
            if start <= rnd < end and src in nodes and rnd % stride != 0:
                return True
        for rule, nodes in self._flaps:
            # dark-phase flappers: every outgoing datagram drops (the
            # node keeps ticking — gray failure, not crash)
            if src in nodes and rule.down_at(rnd):
                return True
        for start, end, nodes in self._outs:
            # correlated blackout: the group talks to NO ONE, itself
            # included (the shared switch died)
            if start <= rnd < end and (src in nodes or dst in nodes):
                return True
        for start, end, rate, src_set, dst_set in self._losses:
            if (start <= rnd < end and src in src_set and dst in dst_set
                    and self._rng.random() < rate):
                return True
        return False

    def status(self, rnd: int) -> dict:
        """One status document (the ``scenario status`` verb / RPC)."""
        return self.scenario.status(rnd)
