"""Tensor-sim backend of the scenario engine: edge filters on the
sampled topology.

The round kernel is receiver-centric — ``edges[i, f]`` is the f-th
sender whose row receiver *i* max-merges this round (core/topology.py).
A dropped message is therefore an EDGE REWRITE: the filtered edge points
at the receiver itself, and a self-edge merge is a provable no-op (the
gossip view is built from the same ticked state the receiver holds, so
the strict ``advance`` compare rejects every value — the argument
aligned arcs already rely on, core/topology.random_arc_bases_aligned).
Nothing else about the round changes: nodes keep ticking, bumping and
detecting; only which rows reach which receivers does.

Engine coverage / capability matrix (round 11 — the fast-path
unification retired the forced-XLA fork; see also config.py's
merge_kernel notes):

  * every merge path consumes filtered edges: the XLA/stripe paths take
    the rewritten [N, F] edges natively, and the resident-round scan
    applies the SAME rewrite to the edges it samples per round
    (core/rounds.py ``_scan_rounds_rr_packed``) before the in-kernel
    gather — a self-edge gathers the receiver's own view row, which the
    strict advance compare rejects, so the fast kernels needed no new
    merge semantics;
  * ``random_arc`` with ``arc_align > 1``: partitions and the
    sender-global rules (slow senders, round-13 flapping) compose at
    GROUP granularity (an aligned arc is F/align whole groups, so
    align-group-closed partition sides give exactly per-edge semantics
    — :func:`arc_match_edges` builds the per-receiver group match
    masks, :func:`sends_mask` the slow/flap sender mute).  Round 14:
    correlated outages compose EXACTLY on aligned arcs with no
    group-closure requirement at all — the rule is separable into a
    sender-global mute (src in group: rides :func:`sends_mask`, a muted
    row's view lanes encode absent to every receiver) and a
    receiver-global mute (dst in group: the receiver's match mask goes
    to ZERO, dropping every window group at once), whose union is
    ``grp[src] | grp[dst]``, the per-edge rule verbatim.  Only
    Bernoulli loss draws remain irreducibly per-edge and stay a
    ``random``-topology (or ring) capability —
    :func:`require_scenario_config` enforces the matrix per scenario;
  * ``remove_broadcast`` must be off: the broadcast is modeled as an
    instantaneous tensor column-OR, not as transport messages, so a
    partition could not filter it — gossip-only dissemination is the
    transport-faithful mode (it also needs ``fresh_cooldown``, as ever).

Scenario round numbers are relative to ARMING: :class:`TensorScenario`
carries ``round0`` (the absolute sim round at arming) and the filter
subtracts it, so a scenario loaded mid-run keeps its schedule.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.scenarios.schedule import FaultScenario


class TensorScenario(NamedTuple):
    """The compiled (device-array) form ``filter_edges`` consumes.

    Rule counts are static (array shapes); empty rule kinds compile to
    zero-length leading axes and vanish from the traced program.  All
    leaves are loop-invariant over a scan.
    """

    round0: jax.Array      # int32 scalar — absolute round the scenario armed
    part_start: jax.Array  # int32 [P]
    part_end: jax.Array    # int32 [P]
    part_pid: jax.Array    # int32 [P, N]
    loss_start: jax.Array  # int32 [L]
    loss_end: jax.Array    # int32 [L]
    loss_rate: jax.Array   # float32 [L]
    loss_src: jax.Array    # bool [L, N]
    loss_dst: jax.Array    # bool [L, N]
    slow_start: jax.Array  # int32 [S]
    slow_end: jax.Array    # int32 [S]
    slow_stride: jax.Array # int32 [S]
    slow_nodes: jax.Array  # bool [S, N]
    flap_start: jax.Array  # int32 [K]
    flap_end: jax.Array    # int32 [K]
    flap_up: jax.Array     # int32 [K]
    flap_period: jax.Array # int32 [K]  (up + down)
    flap_nodes: jax.Array  # bool [K, N]
    out_start: jax.Array   # int32 [O]
    out_end: jax.Array     # int32 [O]
    out_nodes: jax.Array   # bool [O, N]


def compile_tensor(scenario: FaultScenario, round0: int = 0) -> TensorScenario:
    """Compile a declarative scenario to the device-array rule table."""
    n = scenario.n

    def mask(nodes) -> np.ndarray:
        m = np.zeros((n,), dtype=bool)
        m[list(nodes)] = True
        return m

    parts = scenario.partitions
    losses = scenario.link_faults
    slows = scenario.slow_nodes
    flaps = scenario.flapping
    outs = scenario.outages
    return TensorScenario(
        round0=jnp.int32(round0),
        part_start=jnp.asarray([p.start for p in parts], jnp.int32),
        part_end=jnp.asarray([p.end for p in parts], jnp.int32),
        part_pid=jnp.asarray(
            np.stack([p.pid(n) for p in parts], axis=0)
            if parts else np.zeros((0, n), np.int32)
        ),
        loss_start=jnp.asarray([f.start for f in losses], jnp.int32),
        loss_end=jnp.asarray([f.end for f in losses], jnp.int32),
        loss_rate=jnp.asarray([f.rate for f in losses], jnp.float32),
        loss_src=jnp.asarray(
            np.stack([mask(f.src) for f in losses], axis=0)
            if losses else np.zeros((0, n), bool)
        ),
        loss_dst=jnp.asarray(
            np.stack([mask(f.dst) for f in losses], axis=0)
            if losses else np.zeros((0, n), bool)
        ),
        slow_start=jnp.asarray([s.start for s in slows], jnp.int32),
        slow_end=jnp.asarray([s.end for s in slows], jnp.int32),
        slow_stride=jnp.asarray([max(s.stride, 1) for s in slows], jnp.int32),
        slow_nodes=jnp.asarray(
            np.stack([mask(s.nodes) for s in slows], axis=0)
            if slows else np.zeros((0, n), bool)
        ),
        flap_start=jnp.asarray([f.start for f in flaps], jnp.int32),
        flap_end=jnp.asarray([f.end for f in flaps], jnp.int32),
        flap_up=jnp.asarray([f.up for f in flaps], jnp.int32),
        flap_period=jnp.asarray([f.up + f.down for f in flaps], jnp.int32),
        flap_nodes=jnp.asarray(
            np.stack([mask(f.nodes) for f in flaps], axis=0)
            if flaps else np.zeros((0, n), bool)
        ),
        out_start=jnp.asarray([o.start for o in outs], jnp.int32),
        out_end=jnp.asarray([o.end for o in outs], jnp.int32),
        out_nodes=jnp.asarray(
            np.stack([mask(o.nodes) for o in outs], axis=0)
            if outs else np.zeros((0, n), bool)
        ),
    )


def _flap_dark(tsc: TensorScenario, k: int, rel: jax.Array) -> jax.Array:
    """Scalar bool: flap rule k is in its dark phase at relative round
    ``rel`` (schedule.Flapping.down_at, traced form)."""
    return (
        (rel >= tsc.flap_start[k]) & (rel < tsc.flap_end[k])
        & ((rel - tsc.flap_start[k]) % tsc.flap_period[k] >= tsc.flap_up[k])
    )


def filter_edges(
    tsc: TensorScenario, edges: jax.Array, rnd: jax.Array, key: jax.Array
) -> jax.Array:
    """Apply the rule table to one round's explicit in-edges.

    ``edges`` int32 [N, F] (sender ids per receiver; ring mode's [N, 3]
    included), ``rnd`` the absolute round scalar, ``key`` a per-round
    PRNG key (the loss draws fold the rule index in, so multiple loss
    rules draw independently).  Returns edges with every dropped
    message's edge rewritten to the receiver (a no-op merge).
    """
    n, _f = edges.shape
    rel = rnd - tsc.round0
    recv = jnp.arange(n, dtype=jnp.int32)[:, None]
    drop = jnp.zeros(edges.shape, dtype=bool)
    p_count = tsc.part_start.shape[0]
    for p in range(p_count):
        active = (rel >= tsc.part_start[p]) & (rel < tsc.part_end[p])
        pid = tsc.part_pid[p]
        drop |= active & (pid[edges] != pid[recv])
    for s in range(tsc.slow_start.shape[0]):
        active = (
            (rel >= tsc.slow_start[s]) & (rel < tsc.slow_end[s])
            & (rel % tsc.slow_stride[s] != 0)
        )
        drop |= active & tsc.slow_nodes[s][edges]
    for k in range(tsc.flap_start.shape[0]):
        drop |= _flap_dark(tsc, k, rel) & tsc.flap_nodes[k][edges]
    for o in range(tsc.out_start.shape[0]):
        active = (rel >= tsc.out_start[o]) & (rel < tsc.out_end[o])
        grp = tsc.out_nodes[o]
        # blackout: src in group OR dst in group (rack-wide, both ways)
        drop |= active & (grp[edges] | grp[recv])
    for l in range(tsc.loss_start.shape[0]):  # noqa: E741
        active = (rel >= tsc.loss_start[l]) & (rel < tsc.loss_end[l])
        u = jax.random.uniform(jax.random.fold_in(key, l), edges.shape)
        drop |= (
            active
            & tsc.loss_src[l][edges]
            & tsc.loss_dst[l][recv]
            & (u < tsc.loss_rate[l])
        )
    return jnp.where(drop, recv, edges)


def sends_mask(tsc: TensorScenario, n: int, rnd: jax.Array) -> jax.Array:
    """bool [N]: which nodes get their datagrams out this round.

    The SENDER-side rules (slow nodes off their stride) as a node mask —
    for merge forms with no per-edge rewrite (aligned arcs): a muted
    node's gossip-view row encodes absent everywhere, which drops every
    out-edge at once while its own tick (bump/detect) runs untouched —
    exactly the per-edge rewrite's effect for sender-global rules.
    Correlated outages (round 14) contribute their src-side half here;
    the dst-side half rides :func:`arc_match_edges`'s receiver zero-mask
    — together the per-edge ``grp[src] | grp[dst]`` rule exactly.
    """
    rel = rnd - tsc.round0
    send = jnp.ones((n,), bool)
    for s in range(tsc.slow_start.shape[0]):
        active = (
            (rel >= tsc.slow_start[s]) & (rel < tsc.slow_end[s])
            & (rel % tsc.slow_stride[s] != 0)
        )
        send &= ~(active & tsc.slow_nodes[s])
    for k in range(tsc.flap_start.shape[0]):
        # flapping is sender-global exactly like the slow-sender rule,
        # so the aligned-arc forms inherit it through the same mute
        send &= ~(_flap_dark(tsc, k, rel) & tsc.flap_nodes[k])
    for o in range(tsc.out_start.shape[0]):
        active = (rel >= tsc.out_start[o]) & (rel < tsc.out_end[o])
        send &= ~(active & tsc.out_nodes[o])
    return send


def arc_match_edges(
    tsc: TensorScenario, bases: jax.Array, rnd: jax.Array,
    fanout: int, align: int,
) -> jax.Array:
    """Aligned-arc partition filter as (base, group-match bitmask) pairs.

    int32 [N, 2]: row i carries its arc base and a bitmask whose bit k
    keeps window group k (the ``align`` senders at rows
    ``(base + k*align) .. + align``) — kept iff NO active partition rule
    separates the group from receiver i.  Valid when every partition
    side is align-group-closed (``require_scenario_config`` checks), so
    one representative node decides for the whole group and group
    granularity IS per-edge granularity.  Correlated outages (round 14)
    add a RECEIVER-global term needing no closure at all: a receiver
    inside an active outage zeroes its whole mask (every in-edge drops
    at once — the dst-side half of ``grp[src] | grp[dst]``; the
    src-side half rides :func:`sends_mask`).  Consumed by the rr
    kernel's ``edge_filter`` masked gather and by
    ``ops.merge_pallas.arc_group_window_max_xla`` (the XLA oracle).
    """
    n = bases.shape[0]
    nb, nw = n // align, fanout // align
    rel = rnd - tsc.round0
    g = bases // align
    recv = jnp.arange(n, dtype=jnp.int32)
    mask = jnp.zeros((n,), jnp.int32)
    for k in range(nw):
        rep = ((g + k) % nb) * align  # group representative node
        ok = jnp.ones((n,), bool)
        for p in range(tsc.part_start.shape[0]):
            active = (rel >= tsc.part_start[p]) & (rel < tsc.part_end[p])
            pid = tsc.part_pid[p]
            ok &= ~active | (pid[rep] == pid[recv])
        mask |= jnp.where(ok, jnp.int32(1 << k), 0)
    for o in range(tsc.out_start.shape[0]):
        active = (rel >= tsc.out_start[o]) & (rel < tsc.out_end[o])
        mask = jnp.where(active & tsc.out_nodes[o], 0, mask)
    return jnp.stack([bases.astype(jnp.int32), mask], axis=1)


def require_scenario_config(config: SimConfig, scenario=None) -> None:
    """Reject protocol/scenario combinations no transport form can honor.

    * ``remove_broadcast`` is an instantaneous column-OR over the whole
      matrix, not a set of messages — a partition could not filter it
      (the UDP/deploy engines DO filter their real REMOVE datagrams);
      gossip-only dissemination is the transport-faithful mode.
    * ``random_arc``: aligned arcs (arc_align > 1) take partitions with
      align-group-closed sides, slow/flapping senders, and (round 14)
      correlated outages — the outage rule is separable into sender-
      global + receiver-global mutes, so it needs no group closure (see
      :func:`arc_match_edges` / :func:`sends_mask`); Bernoulli loss
      draws are irreducibly per-edge and need ``random`` (or ring).
      Unaligned arcs (arc_align == 1) have no group form at all — use
      ``random``.

    ``scenario``: the concrete :class:`TensorScenario` (or the
    declarative ``FaultScenario``) when available — arc-capability
    checks need the rule tables; with ``None`` only the config-level
    requirements are checked.
    """
    if config.remove_broadcast:
        raise ValueError(
            "scenario runs require remove_broadcast=False: the sim's REMOVE "
            "broadcast is an instantaneous tensor reduction, not transport "
            "messages, so partitions/link faults cannot filter it "
            "(use gossip-only dissemination + fresh_cooldown)"
        )
    if not config.fresh_cooldown:
        raise ValueError(
            "scenario runs require fresh_cooldown=True: in gossip-only "
            "dissemination the faithful stale-timestamp fail list gives "
            "removed entries a ~zero cooldown and zombie re-add cycles "
            "(config.py fresh_cooldown notes) — a partitioned run would "
            "then never reconverge after heal, misattributing the "
            "protocol pathology to the injected fault"
        )
    if config.topology == "random_arc":
        if config.arc_align <= 1:
            raise ValueError(
                "scenario runs on random_arc need arc_align > 1 (whole "
                "sender groups are the drop unit); unaligned arcs have no "
                "per-edge form — use topology='random'"
            )
        if scenario is not None:
            _require_arc_scenario(scenario, config)


def _require_arc_scenario(scenario, config: SimConfig) -> None:
    """Concrete aligned-arc capability checks (rule tables in hand)."""
    align = config.arc_align
    if isinstance(scenario, TensorScenario):
        n_loss = int(scenario.loss_start.shape[0])
        pids = np.asarray(scenario.part_pid)
    else:  # declarative FaultScenario
        n_loss = len(scenario.link_faults)
        pids = (
            np.stack([p.pid(config.n) for p in scenario.partitions])
            if scenario.partitions else np.zeros((0, config.n), np.int32)
        )
    if n_loss:
        raise ValueError(
            "Bernoulli loss rules draw per (sender, receiver) edge and "
            "have no group form: run loss scenarios on topology='random' "
            "(or ring); aligned arcs take partitions, slow/flapping "
            "senders and correlated outages"
        )
    from gossipfs_tpu.ops.merge_pallas import ARC_MATCH_MAX_GROUPS

    if config.fanout // align > ARC_MATCH_MAX_GROUPS:
        raise ValueError(
            "aligned-arc scenarios pack the group-match mask into an "
            f"int32: fanout/arc_align must be <= {ARC_MATCH_MAX_GROUPS} "
            f"(got {config.fanout // align})"
        )
    if pids.size:
        grouped = pids.reshape(pids.shape[0], -1, align)
        if (grouped != grouped[:, :, :1]).any():
            raise ValueError(
                "aligned-arc scenarios need align-group-closed partition "
                f"sides: every group of {align} consecutive nodes must "
                "sit on one side (then group-granular filtering IS "
                "per-edge filtering); regroup the partition or use "
                "topology='random'"
            )


def xla_fallback_config(config: SimConfig) -> SimConfig:
    """Deprecated alias: the XLA-oracle form of ``config``.

    Round 11 retired the forced substitution — every merge path consumes
    filtered edges now, so scenario runs keep their configured kernel.
    This name survives for callers that explicitly want the oracle path
    (parity tests, A/B bisection); the substitution semantics have ONE
    owner, ``config.fallback_config``.
    """
    from gossipfs_tpu.config import fallback_config

    require_scenario_config(config)
    return fallback_config(config)
