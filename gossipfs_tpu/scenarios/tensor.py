"""Tensor-sim backend of the scenario engine: edge filters on the
sampled topology.

The round kernel is receiver-centric — ``edges[i, f]`` is the f-th
sender whose row receiver *i* max-merges this round (core/topology.py).
A dropped message is therefore an EDGE REWRITE: the filtered edge points
at the receiver itself, and a self-edge merge is a provable no-op (the
gossip view is built from the same ticked state the receiver holds, so
the strict ``advance`` compare rejects every value — the argument
aligned arcs already rely on, core/topology.random_arc_bases_aligned).
Nothing else about the round changes: nodes keep ticking, bumping and
detecting; only which rows reach which receivers does.

Engine coverage / gating (see also config.py's merge_kernel notes):

  * the XLA merge paths (2-D state) take filtered edges natively —
    scenario runs therefore FORCE ``merge_kernel="xla"`` via
    :func:`xla_fallback_config` (the rr/pallas fast paths run the round
    in-kernel over unfiltered gathers and stay reserved for
    fault-free transport);
  * ``remove_broadcast`` must be off: the broadcast is modeled as an
    instantaneous tensor column-OR, not as transport messages, so a
    partition could not filter it — gossip-only dissemination is the
    transport-faithful mode (it also needs ``fresh_cooldown``, as ever);
  * ``random_arc`` has no per-edge form (arc bases gather through a
    windowed row-max) — use ``random``, whose detection behavior the
    arc mode matches by construction (bench/curves.py parity rows).

Scenario round numbers are relative to ARMING: :class:`TensorScenario`
carries ``round0`` (the absolute sim round at arming) and the filter
subtracts it, so a scenario loaded mid-run keeps its schedule.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.scenarios.schedule import FaultScenario


class TensorScenario(NamedTuple):
    """The compiled (device-array) form ``filter_edges`` consumes.

    Rule counts are static (array shapes); empty rule kinds compile to
    zero-length leading axes and vanish from the traced program.  All
    leaves are loop-invariant over a scan.
    """

    round0: jax.Array      # int32 scalar — absolute round the scenario armed
    part_start: jax.Array  # int32 [P]
    part_end: jax.Array    # int32 [P]
    part_pid: jax.Array    # int32 [P, N]
    loss_start: jax.Array  # int32 [L]
    loss_end: jax.Array    # int32 [L]
    loss_rate: jax.Array   # float32 [L]
    loss_src: jax.Array    # bool [L, N]
    loss_dst: jax.Array    # bool [L, N]
    slow_start: jax.Array  # int32 [S]
    slow_end: jax.Array    # int32 [S]
    slow_stride: jax.Array # int32 [S]
    slow_nodes: jax.Array  # bool [S, N]


def compile_tensor(scenario: FaultScenario, round0: int = 0) -> TensorScenario:
    """Compile a declarative scenario to the device-array rule table."""
    n = scenario.n

    def mask(nodes) -> np.ndarray:
        m = np.zeros((n,), dtype=bool)
        m[list(nodes)] = True
        return m

    parts = scenario.partitions
    losses = scenario.link_faults
    slows = scenario.slow_nodes
    return TensorScenario(
        round0=jnp.int32(round0),
        part_start=jnp.asarray([p.start for p in parts], jnp.int32),
        part_end=jnp.asarray([p.end for p in parts], jnp.int32),
        part_pid=jnp.asarray(
            np.stack([p.pid(n) for p in parts], axis=0)
            if parts else np.zeros((0, n), np.int32)
        ),
        loss_start=jnp.asarray([f.start for f in losses], jnp.int32),
        loss_end=jnp.asarray([f.end for f in losses], jnp.int32),
        loss_rate=jnp.asarray([f.rate for f in losses], jnp.float32),
        loss_src=jnp.asarray(
            np.stack([mask(f.src) for f in losses], axis=0)
            if losses else np.zeros((0, n), bool)
        ),
        loss_dst=jnp.asarray(
            np.stack([mask(f.dst) for f in losses], axis=0)
            if losses else np.zeros((0, n), bool)
        ),
        slow_start=jnp.asarray([s.start for s in slows], jnp.int32),
        slow_end=jnp.asarray([s.end for s in slows], jnp.int32),
        slow_stride=jnp.asarray([max(s.stride, 1) for s in slows], jnp.int32),
        slow_nodes=jnp.asarray(
            np.stack([mask(s.nodes) for s in slows], axis=0)
            if slows else np.zeros((0, n), bool)
        ),
    )


def filter_edges(
    tsc: TensorScenario, edges: jax.Array, rnd: jax.Array, key: jax.Array
) -> jax.Array:
    """Apply the rule table to one round's explicit in-edges.

    ``edges`` int32 [N, F] (sender ids per receiver; ring mode's [N, 3]
    included), ``rnd`` the absolute round scalar, ``key`` a per-round
    PRNG key (the loss draws fold the rule index in, so multiple loss
    rules draw independently).  Returns edges with every dropped
    message's edge rewritten to the receiver (a no-op merge).
    """
    n, _f = edges.shape
    rel = rnd - tsc.round0
    recv = jnp.arange(n, dtype=jnp.int32)[:, None]
    drop = jnp.zeros(edges.shape, dtype=bool)
    p_count = tsc.part_start.shape[0]
    for p in range(p_count):
        active = (rel >= tsc.part_start[p]) & (rel < tsc.part_end[p])
        pid = tsc.part_pid[p]
        drop |= active & (pid[edges] != pid[recv])
    for s in range(tsc.slow_start.shape[0]):
        active = (
            (rel >= tsc.slow_start[s]) & (rel < tsc.slow_end[s])
            & (rel % tsc.slow_stride[s] != 0)
        )
        drop |= active & tsc.slow_nodes[s][edges]
    for l in range(tsc.loss_start.shape[0]):  # noqa: E741
        active = (rel >= tsc.loss_start[l]) & (rel < tsc.loss_end[l])
        u = jax.random.uniform(jax.random.fold_in(key, l), edges.shape)
        drop |= (
            active
            & tsc.loss_src[l][edges]
            & tsc.loss_dst[l][recv]
            & (u < tsc.loss_rate[l])
        )
    return jnp.where(drop, recv, edges)


def require_scenario_config(config: SimConfig) -> None:
    """Reject protocol modes the transport-level fault model cannot honor.

    * ``remove_broadcast`` is an instantaneous column-OR over the whole
      matrix, not a set of messages — a partition could not filter it
      (the UDP/deploy engines DO filter their real REMOVE datagrams);
      gossip-only dissemination is the transport-faithful mode.
    * ``random_arc`` gathers through a windowed row-max over arc bases
      and has no per-edge rewrite; use ``random``.
    """
    if config.remove_broadcast:
        raise ValueError(
            "scenario runs require remove_broadcast=False: the sim's REMOVE "
            "broadcast is an instantaneous tensor reduction, not transport "
            "messages, so partitions/link faults cannot filter it "
            "(use gossip-only dissemination + fresh_cooldown)"
        )
    if not config.fresh_cooldown:
        raise ValueError(
            "scenario runs require fresh_cooldown=True: in gossip-only "
            "dissemination the faithful stale-timestamp fail list gives "
            "removed entries a ~zero cooldown and zombie re-add cycles "
            "(config.py fresh_cooldown notes) — a partitioned run would "
            "then never reconverge after heal, misattributing the "
            "protocol pathology to the injected fault"
        )
    if config.topology == "random_arc":
        raise ValueError(
            "scenario runs support topology 'ring' or 'random': random_arc "
            "merges through a windowed row-max over arc bases, which has no "
            "per-edge drop form"
        )


def xla_fallback_config(config: SimConfig) -> SimConfig:
    """The config a scenario run actually executes: same protocol, XLA merge.

    The pallas/rr kernels fuse the gather, epilogue and per-round
    reductions in-kernel over unfiltered edge semantics; under active
    link faults the run falls back to the XLA merge path (documented in
    config.py's ``merge_kernel`` notes), which consumes the filtered
    edges natively.  Everything protocol-level (dtypes, thresholds,
    dissemination mode, elementwise formulation) is preserved.
    """
    require_scenario_config(config)
    if config.merge_kernel == "xla":
        return config
    return dataclasses.replace(config, merge_kernel="xla")
