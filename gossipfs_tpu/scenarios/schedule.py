"""Declarative fault scenarios: timed partitions, link faults, slow nodes.

The reference's fault model is crash-stop (CTRL+C) plus voluntary
leave/join — the ``RoundEvents`` masks.  Real gossip deployments die from
*partial* failures instead: netsplits, lossy links, asymmetric
reachability, nodes that lag.  A :class:`FaultScenario` is a typed,
JSON-loadable schedule of such faults that compiles onto all three
transport engines from ONE file:

  * the tensor sim — edge filters on the sampled [N, F] in-edge set
    (``scenarios.tensor.filter_edges``, applied inside the round scan);
  * the asyncio UDP engine — a drop rule at the datagram send hook
    (``detector/udp.py`` ``UdpNode._send``);
  * the per-process deployment — the same rule table pushed to every
    node daemon over the control plane (``ScenarioLoad`` RPC).

Semantics, identical everywhere (``scenarios.runtime.ScenarioRuntime``
is the reference implementation): a message from ``src`` to ``dst`` at
round ``r`` (rounds counted from the moment the scenario is ARMED on
that engine) is dropped iff any active rule says so —

  * :class:`Partition`  — active and src/dst fall in different groups;
  * :class:`LinkFault`  — active, src in ``src_set``, dst in
    ``dst_set``: Bernoulli drop with probability ``rate`` (``rate=1.0``
    in one direction only models an asymmetric link);
  * :class:`SlowNode`   — active, src is slow, and the round is not a
    multiple of ``stride``: the node's messages only get out every
    ``stride``-th round (it lags, synchronous-round style);
  * :class:`Flapping`   — active, src flaps, and the duty cycle is in
    its dark phase: the node's outgoing datagrams all drop for ``down``
    consecutive rounds out of every ``up + down`` (Lifeguard's gray
    failure — the node looks dead long enough to be suspected, then
    comes back and looks like a false positive);
  * :class:`CorrelatedOutage` — active and src OR dst sits in the
    group: a rack/zone-sized blackout (the top-of-rack switch died —
    members cannot even reach each other), the correlated-failure
    class that makes per-node-independent repair placement lose whole
    replica sets at once.

Faults affect TRANSPORT only — nodes keep ticking, bumping their own
heartbeats and detecting; what changes is which datagrams arrive.  Heal
events are just the ``end`` round of each rule window.

Node selectors in JSON: an int list ``[0, 3, 7]``, a half-open range
``{"range": [0, 512]}``, or ``"all"``.  Example::

    {"name": "halves", "n": 1024, "seed": 0,
     "partitions": [{"start": 5, "end": 40,
                     "groups": [{"range": [0, 512]}]}],
     "link_faults": [{"start": 0, "end": 5, "rate": 0.3,
                      "src": "all", "dst": [7]}]}

Nodes left out of every partition group form one implicit "rest" group.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Sequence

import numpy as np

Selector = object  # "all" | list[int] | {"range": [lo, hi)}


def expand_selector(sel: Selector, n: int) -> tuple[int, ...]:
    """Normalize a JSON node selector to a sorted id tuple (see module doc)."""
    if sel == "all":
        return tuple(range(n))
    if isinstance(sel, dict):
        lo, hi = sel["range"]
        if not 0 <= lo < hi <= n:
            raise ValueError(f"range {sel['range']} outside [0, {n})")
        return tuple(range(int(lo), int(hi)))
    nodes = tuple(sorted(int(x) for x in sel))
    for x in nodes:
        if not 0 <= x < n:
            raise ValueError(f"node id {x} out of range [0, {n})")
    if len(set(nodes)) != len(nodes):
        raise ValueError(f"duplicate node ids in selector: {sel}")
    return nodes


@dataclasses.dataclass(frozen=True)
class Partition:
    """Cluster split over rounds [start, end): cross-group messages drop.

    ``groups`` are disjoint; nodes in none of them form the implicit
    rest group.  ``end`` is the heal round (the first round messages
    flow again).
    """

    start: int
    end: int
    groups: tuple[tuple[int, ...], ...]

    def pid(self, n: int) -> np.ndarray:
        """int32 [N] partition id: group k -> k+1, the rest -> 0."""
        pid = np.zeros((n,), dtype=np.int32)
        for k, g in enumerate(self.groups):
            pid[list(g)] = k + 1
        return pid


@dataclasses.dataclass(frozen=True)
class LinkFault:
    """Bernoulli loss on the directed src -> dst links over [start, end).

    ``rate=1.0`` is a total directional blackout — one such rule without
    its reverse models asymmetric reachability.
    """

    start: int
    end: int
    rate: float
    src: tuple[int, ...]
    dst: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class SlowNode:
    """Lagging senders: over [start, end) their messages only get out on
    rounds that are multiples of ``stride``."""

    start: int
    end: int
    stride: int
    nodes: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Flapping:
    """Flapping senders: over [start, end) the nodes cycle ``up`` rounds
    healthy then ``down`` rounds DARK (every outgoing datagram drops),
    repeating.  The node itself keeps ticking — bumping its own
    heartbeat, detecting — so each recovery re-announces a counter that
    advanced through the dark phase: the gray-failure shape that storms
    a raw short t_fail with false positives and that SWIM suspicion
    exists to absorb (a ``down`` within the suspect window refutes; a
    ``down`` past it confirms a live node FAILED).
    """

    start: int
    end: int
    up: int
    down: int
    nodes: tuple[int, ...]

    def down_at(self, rnd: int) -> bool:
        """Whether the rule's nodes are in the dark phase at ``rnd``."""
        if not self.start <= rnd < self.end:
            return False
        return (rnd - self.start) % (self.up + self.down) >= self.up


@dataclasses.dataclass(frozen=True)
class CorrelatedOutage:
    """Correlated-failure group: over [start, end) every message with
    src OR dst in the group drops — a rack/zone blackout (the shared
    switch died; group members cannot even reach each other).  Unlike a
    :class:`Partition` group (which keeps internal connectivity) the
    whole group goes dark at once, and unlike crash events the nodes
    keep running: at ``end`` they resurface with views frozen at the
    outage start."""

    start: int
    end: int
    nodes: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class FaultScenario:
    """One declarative fault schedule (see module docstring).

    Round numbers are RELATIVE to when the scenario is armed on an
    engine (``load_scenario`` / construction), so the same file drives
    a sim started at round 0 and a socket cluster armed mid-run.
    """

    name: str
    n: int
    partitions: tuple[Partition, ...] = ()
    link_faults: tuple[LinkFault, ...] = ()
    slow_nodes: tuple[SlowNode, ...] = ()
    seed: int = 0  # Bernoulli-loss stream id (each engine derives its own)
    # round-13 gray-failure primitives (after ``seed`` so positional
    # construction of the round-7 fields stays valid)
    flapping: tuple[Flapping, ...] = ()
    outages: tuple[CorrelatedOutage, ...] = ()

    def __post_init__(self) -> None:
        if self.n < 2:
            raise ValueError(f"n must be >= 2, got {self.n}")
        for p in self.partitions:
            self._check_window(p.start, p.end, "partition")
            seen: set[int] = set()
            for g in p.groups:
                if not g:
                    raise ValueError("empty partition group")
                overlap = seen & set(g)
                if overlap:
                    raise ValueError(
                        f"partition groups overlap on nodes {sorted(overlap)}"
                    )
                seen |= set(g)
                for x in g:
                    self._check_node(x)
        for lf in self.link_faults:
            self._check_window(lf.start, lf.end, "link_fault")
            if not 0.0 < lf.rate <= 1.0:
                raise ValueError(f"link fault rate must be in (0, 1], got {lf.rate}")
            for x in (*lf.src, *lf.dst):
                self._check_node(x)
        for s in self.slow_nodes:
            self._check_window(s.start, s.end, "slow_node")
            if s.stride < 2:
                raise ValueError(f"slow stride must be >= 2, got {s.stride}")
            for x in s.nodes:
                self._check_node(x)
        for fl in self.flapping:
            self._check_window(fl.start, fl.end, "flapping")
            if fl.up < 1 or fl.down < 1:
                raise ValueError(
                    f"flapping needs up >= 1 and down >= 1, got "
                    f"up={fl.up} down={fl.down}")
            if not fl.nodes:
                raise ValueError("empty flapping node set")
            for x in fl.nodes:
                self._check_node(x)
        for o in self.outages:
            self._check_window(o.start, o.end, "outage")
            if not o.nodes:
                raise ValueError("empty outage group")
            for x in o.nodes:
                self._check_node(x)

    def _check_window(self, start: int, end: int, kind: str) -> None:
        if start < 0 or end <= start:
            raise ValueError(f"{kind} window must have 0 <= start < end, "
                             f"got [{start}, {end})")

    def _check_node(self, x: int) -> None:
        if not 0 <= x < self.n:
            raise ValueError(f"node id {x} out of range [0, {self.n})")

    # -- queries ------------------------------------------------------------
    def _rules(self):
        return (*self.partitions, *self.link_faults, *self.slow_nodes,
                *self.flapping, *self.outages)

    @property
    def horizon(self) -> int:
        """First round past every rule window (all links healthy after)."""
        return max((r.end for r in self._rules()), default=0)

    def active_at(self, rnd: int) -> bool:
        """Any rule active at (armed-relative) round ``rnd``."""
        return any(r.start <= rnd < r.end for r in self._rules())

    def unreachable_at(self, rnd: int) -> set[int]:
        """Nodes no datagram can LEAVE at round ``rnd`` — outage-group
        members and flapping nodes in their dark phase.  The control
        plane's reachability model (cosim._reachable) subtracts these:
        an scp to a blacked-out rack fails like one to a dead VM."""
        out: set[int] = set()
        for o in self.outages:
            if o.start <= rnd < o.end:
                out |= set(o.nodes)
        for fl in self.flapping:
            if fl.down_at(rnd):
                out |= set(fl.nodes)
        return out

    def pid_at(self, rnd: int) -> np.ndarray | None:
        """Combined int32 [N] partition id at round ``rnd``, None if no
        partition is active.  Multiple active partitions compose by
        refinement: src/dst communicate iff NO active rule separates
        them — exactly the per-rule OR the engines apply.
        """
        pid = None
        for p in self.partitions:
            if p.start <= rnd < p.end:
                rule = p.pid(self.n)
                pid = rule if pid is None else pid * (len(p.groups) + 1) + rule
        return pid

    def status(self, rnd: int) -> dict:
        """THE status document every engine surface serves (CLI
        ``scenario status``, the deploy ``ScenarioStatus`` RPC, detector
        ``scenario_status``) — one producer, so the fields cannot drift
        between engines."""
        return {
            "name": self.name,
            "round": int(rnd),
            "active": self.active_at(rnd),
            "horizon": self.horizon,
            "rules": self.active_rules(rnd),
        }

    def active_rules(self, rnd: int) -> list[str]:
        """Human-readable descriptions of the rules active at ``rnd``."""
        out = []
        for p in self.partitions:
            if p.start <= rnd < p.end:
                sizes = [len(g) for g in p.groups]
                rest = self.n - sum(sizes)
                out.append(f"partition[{p.start},{p.end}) groups={sizes}"
                           + (f"+rest({rest})" if rest else ""))
        for lf in self.link_faults:
            if lf.start <= rnd < lf.end:
                out.append(f"link_loss[{lf.start},{lf.end}) rate={lf.rate} "
                           f"{len(lf.src)}->{len(lf.dst)} nodes")
        for s in self.slow_nodes:
            if s.start <= rnd < s.end:
                out.append(f"slow[{s.start},{s.end}) stride={s.stride} "
                           f"nodes={len(s.nodes)}")
        for fl in self.flapping:
            if fl.start <= rnd < fl.end:
                out.append(f"flap[{fl.start},{fl.end}) up={fl.up} "
                           f"down={fl.down} nodes={len(fl.nodes)}"
                           f"{' DARK' if fl.down_at(rnd) else ''}")
        for o in self.outages:
            if o.start <= rnd < o.end:
                out.append(f"outage[{o.start},{o.end}) "
                           f"nodes={len(o.nodes)}")
        return out

    # -- JSON codec ---------------------------------------------------------
    def to_json(self) -> str:
        def sel(nodes: Sequence[int]) -> object:
            nodes = list(nodes)
            if len(nodes) == self.n:
                return "all"
            if nodes and nodes == list(range(nodes[0], nodes[-1] + 1)):
                return {"range": [nodes[0], nodes[-1] + 1]}
            return nodes

        doc = {
            "name": self.name,
            "n": self.n,
            "seed": self.seed,
            "partitions": [
                {"start": p.start, "end": p.end,
                 "groups": [sel(g) for g in p.groups]}
                for p in self.partitions
            ],
            "link_faults": [
                {"start": f.start, "end": f.end, "rate": f.rate,
                 "src": sel(f.src), "dst": sel(f.dst)}
                for f in self.link_faults
            ],
            "slow_nodes": [
                {"start": s.start, "end": s.end, "stride": s.stride,
                 "nodes": sel(s.nodes)}
                for s in self.slow_nodes
            ],
            "flapping": [
                {"start": f.start, "end": f.end, "up": f.up,
                 "down": f.down, "nodes": sel(f.nodes)}
                for f in self.flapping
            ],
            "outages": [
                {"start": o.start, "end": o.end, "nodes": sel(o.nodes)}
                for o in self.outages
            ],
        }
        return json.dumps(doc, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultScenario":
        doc = json.loads(text)
        n = int(doc["n"])
        return cls(
            name=str(doc.get("name", "scenario")),
            n=n,
            seed=int(doc.get("seed", 0)),
            partitions=tuple(
                Partition(
                    start=int(p["start"]), end=int(p["end"]),
                    groups=tuple(expand_selector(g, n) for g in p["groups"]),
                )
                for p in doc.get("partitions", [])
            ),
            link_faults=tuple(
                LinkFault(
                    start=int(f["start"]), end=int(f["end"]),
                    rate=float(f["rate"]),
                    src=expand_selector(f.get("src", "all"), n),
                    dst=expand_selector(f.get("dst", "all"), n),
                )
                for f in doc.get("link_faults", [])
            ),
            slow_nodes=tuple(
                SlowNode(
                    start=int(s["start"]), end=int(s["end"]),
                    stride=int(s["stride"]),
                    nodes=expand_selector(s["nodes"], n),
                )
                for s in doc.get("slow_nodes", [])
            ),
            flapping=tuple(
                Flapping(
                    start=int(f["start"]), end=int(f["end"]),
                    up=int(f["up"]), down=int(f["down"]),
                    nodes=expand_selector(f["nodes"], n),
                )
                for f in doc.get("flapping", [])
            ),
            outages=tuple(
                CorrelatedOutage(
                    start=int(o["start"]), end=int(o["end"]),
                    nodes=expand_selector(o["nodes"], n),
                )
                for o in doc.get("outages", [])
            ),
        )

    @classmethod
    def from_file(cls, path: str) -> "FaultScenario":
        with open(path) as f:
            return cls.from_json(f.read())


def split_halves(n: int, start: int, end: int,
                 name: str = "halves", seed: int = 0) -> FaultScenario:
    """The canonical netsplit: nodes [0, n/2) vs the rest over [start, end)."""
    return FaultScenario(
        name=name, n=n, seed=seed,
        partitions=(Partition(start=start, end=end,
                              groups=(tuple(range(n // 2)),)),),
    )
