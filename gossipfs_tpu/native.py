"""ctypes bindings for the native (C++) gossip runtime.

The reference's runtime is Go: a blocking UDP receive goroutine plus a 1 s
heartbeat driver per process (reference: slave/slave.go:207-248, main.go:27-33).
The TPU build's native equivalent lives in ``native/``: an epoll-driven C++
engine running all N protocol nodes over real localhost UDP sockets, speaking
the reference wire format (``<#ENTRY#>``/``<#INFO#>``/``<CMD>`` framing,
slave.go:365-385).  This module builds it on demand (``make`` in ``native/``)
and wraps it in the same ``FailureDetector`` interface as the TPU sim and the
Python asyncio parity path — three interchangeable engines, one seam.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

from gossipfs_tpu.detector.api import DetectionEvent

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libgossipfs_native.so"
_build_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    """The shared library could not be built (no toolchain, compile error)."""


def _build() -> None:
    # -B: we only get here when _stale() already decided a rebuild is
    # due, and make's own mtime compare disagrees on ties (the
    # fresh-checkout case) and ignores the Makefile-only edit case —
    # an unforced `make` would exit 0 WITHOUT recompiling and the stale
    # binary would run anyway
    try:
        proc = subprocess.run(
            ["make", "-B", "-C", str(_NATIVE_DIR)],
            capture_output=True, text=True
        )
    except FileNotFoundError as e:  # no make on PATH
        raise NativeBuildError(f"native build needs make: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}"
        )


def _sources() -> list[pathlib.Path]:
    return [_NATIVE_DIR / "codec.cc", _NATIVE_DIR / "engine.cc",
            _NATIVE_DIR / "codec.h", _NATIVE_DIR / "tsa.h",
            _NATIVE_DIR / "Makefile"]


def _stale() -> bool:
    """Whether the .so must be (re)built.  ``>=`` on purpose: a fresh
    checkout stamps sources and a stray .so with the SAME mtime, and the
    old ``>`` compare let tests run silently against a binary built from
    DIFFERENT sources.  An mtime tie costs one cheap rebuild."""
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    return any(s.stat().st_mtime >= lib_mtime for s in _sources())


def ensure_fresh() -> pathlib.Path:
    """Rebuild the .so if any source is at-or-newer than it; raise
    NativeBuildError on failure.  tests/test_native.py calls this at
    collection so a stale binary can never pass silently against old
    engine/codec sources — and a broken rebuild is a loud failure, not
    a skip.

    Once the library is LOADED in this process a rebuild cannot take
    effect (the CDLL handle keeps serving the old mapping, and
    overwriting a dlopen'd .so risks corrupting in-flight native
    calls) — that situation raises instead of claiming freshness."""
    with _build_lock:
        if _stale():
            if _lib is not None:
                raise NativeBuildError(
                    "native sources changed AFTER the library was loaded "
                    "in this process; restart to pick up the rebuild "
                    "(refusing to overwrite a mapped .so)"
                )
            _build()
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    """Build (if needed) and load the native runtime, caching the handle."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _stale():
            _build()
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.gfs_cluster_create.restype = ctypes.c_void_p
        lib.gfs_cluster_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gfs_cluster_start.argtypes = [ctypes.c_void_p]
        lib.gfs_cluster_start.restype = ctypes.c_int
        lib.gfs_cluster_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.gfs_crash, lib.gfs_leave, lib.gfs_join):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.gfs_advance.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.gfs_round.argtypes = [ctypes.c_void_p]
        lib.gfs_round.restype = ctypes.c_int
        for fn in (lib.gfs_membership, lib.gfs_suspects):
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ]
            fn.restype = ctypes.c_int
        lib.gfs_incarnation.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int
        ]
        lib.gfs_incarnation.restype = ctypes.c_longlong
        for fn in (lib.gfs_alive, lib.gfs_drain_events):
            fn.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int
            ]
            fn.restype = ctypes.c_int
        for fn in (lib.gfs_codec_encode, lib.gfs_codec_decode):
            fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
            fn.restype = ctypes.c_int
        # round-16 observability + campaign surface
        lib.gfs_configure.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.gfs_configure.restype = ctypes.c_int
        lib.gfs_obs_enable.argtypes = [ctypes.c_void_p]
        lib.gfs_obs_enable.restype = ctypes.c_int
        for fn in (lib.gfs_obs_drain, lib.gfs_vitals):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
            fn.restype = ctypes.c_int
        lib.gfs_scenario_load.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
        ]
        lib.gfs_scenario_load.restype = ctypes.c_int
        lib.gfs_scenario_clear.argtypes = [ctypes.c_void_p]
        lib.gfs_stop.argtypes = [ctypes.c_void_p]
        lib.gfs_seed_full.argtypes = [ctypes.c_void_p]
        lib.gfs_warm.argtypes = [ctypes.c_void_p]
        lib.gfs_warm.restype = ctypes.c_int
        _lib = lib
        return _lib


# -- codec (parity-testable against detector/udp.py's Python codec) ---------

def _call_sized(fn, data: bytes, initial_cap: int) -> bytes:
    """Call a snprintf-style C function, growing the buffer on truncation
    (the function returns the full required length)."""
    cap = initial_cap
    while True:
        out = ctypes.create_string_buffer(cap)
        need = fn(data, out, cap)
        if need < cap:
            return out.raw[:need]
        cap = need + 1


def codec_encode(entries: list[tuple[str, int, float]]) -> str:
    """Members -> reference wire string, through the C++ codec."""
    lib = load_library()
    lines = "\n".join(f"{a} {hb} {ts}" for a, hb, ts in entries).encode()
    return _call_sized(lib.gfs_codec_encode, lines, 3 * len(lines) + 64).decode()


def codec_decode(wire: str) -> list[tuple[str, int, float]]:
    """Reference wire string -> members, through the C++ codec."""
    lib = load_library()
    raw = _call_sized(
        lib.gfs_codec_decode, wire.encode(), 2 * len(wire) + 64
    ).decode()
    entries = []
    for line in raw.splitlines():
        addr, hb, ts = line.split(" ")
        entries.append((addr, int(hb), float(ts)))
    return entries


# -- observability plane (obs/) ---------------------------------------------

# detail values the C++ engine emits as 0/1 ints but the schema carries
# as booleans (the json the other recorders write)
_BOOL_DETAIL = frozenset({"false_positive", "scheduled"})


def _parse_obs_lines(text: str):
    """``gfs_obs_drain`` text -> schema Events.

    Line form (one writer, ``Cluster::ObsEmit`` in native/engine.cc):
    ``kind round observer subject k=v k=v ...`` — kinds are
    ``obs/schema.py`` EVENT_KINDS members (the native-obs-kinds lint
    rule enforces it), so the rendered stream is a plain
    ``gossipfs-obs/v1`` stream and ``obs.recorder.load_stream`` stays
    the one reader.
    """
    from gossipfs_tpu.obs.schema import Event

    events = []
    for line in text.splitlines():
        parts = line.split(" ")
        if len(parts) < 4:
            continue
        detail = {}
        for kv in parts[4:]:
            k, _, v = kv.partition("=")
            if k in _BOOL_DETAIL:
                detail[k] = v not in ("0", "")
            else:
                try:
                    detail[k] = int(v)
                except ValueError:
                    try:
                        detail[k] = float(v)
                    except ValueError:
                        detail[k] = v
        events.append(Event(round=int(parts[1]), observer=int(parts[2]),
                            subject=int(parts[3]), kind=parts[0],
                            detail=detail))
    return events


# log-spaced tick_ms histogram buckets (upper bounds, ms); the last
# bucket is open-ended
_HIST_EDGES_MS = (0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0, 300.0)


def latency_histogram(events) -> dict:
    """Per-round wall-clock latency histogram from a native stream's
    ``round_tick.tick_ms`` samples (the epoll tick pass's cost — the
    real-time engine's 'did we fall behind the period' evidence).

    Returns ``{"count", "p50_ms", "max_ms", "buckets": {"<=0.1": k, ...,
    ">300.0": k}}``; zero samples -> count 0 and no quantiles (absent,
    not 0 — the n/a rule).
    """
    import bisect
    import statistics

    samples = sorted(
        e.detail["tick_ms"] for e in events
        if e.kind == "round_tick" and "tick_ms" in e.detail)
    doc: dict = {"count": len(samples)}
    if not samples:
        return doc
    doc["p50_ms"] = round(statistics.median(samples), 3)
    doc["max_ms"] = round(samples[-1], 3)
    buckets: dict[str, int] = {}
    lo = 0
    for edge in _HIST_EDGES_MS:
        hi = bisect.bisect_right(samples, edge)
        buckets[f"<={edge}"] = hi - lo
        lo = hi
    buckets[f">{_HIST_EDGES_MS[-1]}"] = len(samples) - lo
    doc["buckets"] = buckets
    return doc


def compile_native_scenario(scenario) -> str:
    """``scenarios.FaultScenario`` -> the native engine's fault-gate
    table (the text ``gfs_scenario_load`` parses).

    Covers the gate primitives the committed campaign cases use —
    flapping duty-cycle blackout, correlated-outage rack darkness,
    timed partition, lagging senders — with ``ScenarioRuntime.drops``
    semantics applied at ``Node::Send``.  Bernoulli link loss is
    rejected (an RNG-stream parity question the gate table deliberately
    does not take on; run those cases on the udp engine).
    """
    if scenario.link_faults:
        raise ValueError(
            "Bernoulli link loss is not expressible on the native gate "
            "table — the drop draw would need an RNG-stream parity "
            "decision; drive loss cases through the udp engine")
    lines = [f"name {scenario.name.replace(' ', '_')}"]
    for f in scenario.flapping:
        ids = " ".join(str(i) for i in f.nodes)
        lines.append(f"flap {f.start} {f.end} {f.up} {f.down} {ids}")
    for o in scenario.outages:
        ids = " ".join(str(i) for i in o.nodes)
        lines.append(f"outage {o.start} {o.end} {ids}")
    for p in scenario.partitions:
        pid = " ".join(str(int(x)) for x in p.pid(scenario.n))
        lines.append(f"partition {p.start} {p.end} {pid}")
    for s in scenario.slow_nodes:
        ids = " ".join(str(i) for i in s.nodes)
        lines.append(f"slow {s.start} {s.end} {s.stride} {ids}")
    return "\n".join(lines) + "\n"


# -- the engine behind the FailureDetector seam -----------------------------

class NativeUdpDetector:
    """FailureDetector over the C++ epoll engine (real localhost datagrams).

    Same verbs and views as ``detector.sim.SimDetector`` and
    ``detector.udp.UdpDetector`` — the config-1 parity path at native speed.
    ``advance(r)`` blocks for r heartbeat periods of wall time (the native
    engine, like the reference, runs in real time).

    Round 16 — the obs-plane + campaign surface (mirroring UdpCluster's
    round-14 knobs): ``push="random"``/``fanout``/``remove_broadcast``
    select the campaign protocol profile, ``suspicion`` arms the SWIM
    lifecycle (+ Lifeguard local health) inside the engine, and
    ``attach_recorder`` turns on structured event buffering that
    ``pump_obs`` drains through the ONE schema (``obs/schema.py``) into
    the attached ``FlightRecorder`` — so a native trace is a plain
    ``gossipfs-obs/v1`` stream every existing reader ingests unchanged.

    Round 20: ``delta=True`` turns membership pushes into delta-piggyback
    frames (changed-first + round-robin tail, capped at ``delta_entries``,
    full anti-entropy push every ``anti_entropy_every`` rounds — must stay
    below ``t_fail``), and ``loops=k`` stripes the receive path across k
    epoll loops with node i owned by stripe ``i % k``.
    """

    def __init__(
        self,
        n: int,
        base_port: int = 19000,
        period: float = 0.05,
        t_fail: int = 5,
        t_cooldown: int = 5,
        min_group: int = 4,
        fresh_cooldown: bool = False,
        introducer: int = 0,
        push: str = "ring",
        fanout: int | None = None,
        remove_broadcast: bool = True,
        suspicion=None,
        delta: bool = False,
        delta_entries: int = 16,
        anti_entropy_every: int = 4,
        loops: int = 1,
    ):
        self._lib = load_library()
        self.n = n
        self.base_port = base_port
        self.period = period
        self.suspicion = suspicion
        self._recorder = None
        self._obs_round0 = 0
        self._h = self._lib.gfs_cluster_create(
            n, base_port, period, t_fail, t_cooldown, min_group,
            int(fresh_cooldown), introducer,
        )
        knobs = []
        if push != "ring":
            knobs.append(f"push={push}")
        if fanout is not None:
            knobs.append(f"fanout={fanout}")
        if not remove_broadcast:
            knobs.append("remove_broadcast=0")
        if suspicion is not None:
            knobs.append(f"t_suspect={suspicion.t_suspect}")
            knobs.append(f"lh_multiplier={suspicion.lh_multiplier}")
            knobs.append(f"lh_frac={suspicion.lh_frac!r}")
        if delta:
            # delta piggybacking (protocol_spec.DELTA_GOSSIP); the engine
            # rejects anti_entropy_every >= t_fail like UdpCluster does
            knobs.append("delta=1")
            knobs.append(f"delta_entries={delta_entries}")
            knobs.append(f"anti_entropy_every={anti_entropy_every}")
        if loops != 1:
            knobs.append(f"loops={loops}")
        if knobs and self._lib.gfs_configure(
                self._h, " ".join(knobs).encode()) != 0:
            self._lib.gfs_cluster_destroy(self._h)
            self._h = None
            raise ValueError(f"native engine rejected knobs: {knobs}")
        if self._lib.gfs_cluster_start(self._h) != 0:
            self._lib.gfs_cluster_destroy(self._h)
            self._h = None
            raise RuntimeError(
                f"native cluster failed to start (ports {base_port}..{base_port + n - 1} busy?)"
            )

    # -- FailureDetector protocol ------------------------------------------
    def join(self, node: int) -> None:
        self._lib.gfs_join(self._h, node)

    def leave(self, node: int) -> None:
        self._lib.gfs_leave(self._h, node)

    def crash(self, node: int) -> None:
        self._lib.gfs_crash(self._h, node)

    def advance(self, rounds: int = 1) -> None:
        self._lib.gfs_advance(self._h, rounds)

    @property
    def round(self) -> int:
        return self._lib.gfs_round(self._h)

    def membership(self, observer: int) -> list[int]:
        buf = (ctypes.c_int * self.n)()
        count = self._lib.gfs_membership(self._h, observer, buf, self.n)
        return list(buf[:count])

    def alive_nodes(self) -> list[int]:
        buf = (ctypes.c_int * self.n)()
        count = self._lib.gfs_alive(self._h, buf, self.n)
        return list(buf[:count])

    # -- conformance-harness read seams (round 19) -------------------------
    def suspects(self, observer: int) -> list[int]:
        """Node indices the observer currently holds under suspicion."""
        buf = (ctypes.c_int * self.n)()
        count = self._lib.gfs_suspects(self._h, observer, buf, self.n)
        return list(buf[:count])

    def incarnation(self, observer: int, subject: int) -> int:
        """The subject's heartbeat counter in the observer's view
        (the per-entry incarnation surface); -1 when absent."""
        return int(self._lib.gfs_incarnation(self._h, observer, subject))

    def wire_addr(self, node: int) -> str:
        """The wire address datagrams name this node by."""
        return f"127.0.0.1:{self.base_port + node}"

    # -- obs plane (round 16) ----------------------------------------------
    def attach_recorder(self, recorder) -> int:
        """Arm an ``obs.FlightRecorder`` (or MonitorRecorder) and enable
        event buffering in the engine.  Returns the ABSOLUTE engine
        round the recorded stream's round 0 maps to (the rebased,
        arming-relative frame the udp campaign streams use)."""
        self._recorder = recorder
        self._obs_round0 = self._lib.gfs_obs_enable(self._h)
        return self._obs_round0

    def pump_obs(self) -> int:
        """Drain buffered engine events into the attached recorder;
        returns the event count.  Call after (or periodically during)
        ``advance`` — the engine buffers until drained."""
        if self._recorder is None:
            return 0
        total = 0
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            got = self._lib.gfs_obs_drain(self._h, buf, cap)
            if got == -1:
                cap *= 2
                continue
            if got == 0:
                return total
            events = _parse_obs_lines(buf.raw[:got].decode())
            self._recorder.extend(events)
            total += len(events)

    def vitals(self) -> dict:
        """The uniform counter set (obs.schema.VITALS_FIELDS).  This
        engine knows ground-truth aliveness (in-process), so
        ``false_positives`` is live; suspicion counters appear only when
        the lifecycle is armed, and ``fp_suppressed`` stays absent — the
        per-refute ground truth only the sim has (rendered n/a)."""
        raw = _call_sized(self._lib.gfs_vitals, self._h, 512).decode()
        doc: dict = {"engine": "native"}
        for kv in raw.split():
            k, _, v = kv.partition("=")
            doc[k] = int(v)
        mon = getattr(self._recorder, "monitor", None)
        if mon is not None:
            doc["invariant_violations"] = len(mon.violations)
        return doc

    # -- campaign surface (round 16) ---------------------------------------
    def seed_full_membership(self) -> None:
        """Start from the fully-joined steady state (the udp engine's
        ``seed_full_membership``): every node lists everyone at hb 0
        with a fresh local stamp — inside the hb<=1 detection grace."""
        self._lib.gfs_seed_full(self._h)

    def warm(self) -> bool:
        """Whether every live view is full with every counter past the
        hb<=1 detection grace (the campaign runners' readiness gate)."""
        return bool(self._lib.gfs_warm(self._h))

    def load_scenario(self, scenario, round0: int | None = None) -> None:
        """Arm a ``scenarios.FaultScenario`` as the engine's send-gate
        table.  Windows are anchored at absolute engine round
        ``round0`` (default: the current round) — pass the round
        ``attach_recorder`` returned so the gate windows and the
        recorded stream share one relative clock."""
        if scenario.n != self.n:
            raise ValueError(
                f"scenario is for n={scenario.n}, cluster has n={self.n}")
        table = compile_native_scenario(scenario)
        if round0 is None:
            round0 = self.round
        if self._lib.gfs_scenario_load(
                self._h, table.encode(), int(round0)) != 0:
            raise ValueError(
                f"native engine rejected the gate table for {scenario.name}")

    def clear_scenario(self) -> None:
        self._lib.gfs_scenario_clear(self._h)

    def stop(self) -> None:
        """Halt the epoll loop + sockets, keeping state drainable: call
        before a big ``pump_obs`` — on a 1-core host a long drain parse
        starves a still-running loop (rounds lag, entries look stale)
        and manufactures an FP cascade in the stream's tail."""
        self._lib.gfs_stop(self._h)

    def drain_events(self) -> list[DetectionEvent]:
        cap = 4096 * 4
        buf = (ctypes.c_int * cap)()
        events = []
        while True:
            count = self._lib.gfs_drain_events(self._h, buf, cap)
            for i in range(count):
                events.append(
                    DetectionEvent(
                        round=buf[i * 4 + 0],
                        observer=buf[i * 4 + 1],
                        subject=buf[i * 4 + 2],
                        false_positive=bool(buf[i * 4 + 3]),
                    )
                )
            if count < cap // 4:
                return events

    def close(self) -> None:
        if self._h is not None:
            self._lib.gfs_cluster_destroy(self._h)
            self._h = None

    def __enter__(self) -> "NativeUdpDetector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
