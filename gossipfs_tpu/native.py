"""ctypes bindings for the native (C++) gossip runtime.

The reference's runtime is Go: a blocking UDP receive goroutine plus a 1 s
heartbeat driver per process (reference: slave/slave.go:207-248, main.go:27-33).
The TPU build's native equivalent lives in ``native/``: an epoll-driven C++
engine running all N protocol nodes over real localhost UDP sockets, speaking
the reference wire format (``<#ENTRY#>``/``<#INFO#>``/``<CMD>`` framing,
slave.go:365-385).  This module builds it on demand (``make`` in ``native/``)
and wraps it in the same ``FailureDetector`` interface as the TPU sim and the
Python asyncio parity path — three interchangeable engines, one seam.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

from gossipfs_tpu.detector.api import DetectionEvent

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libgossipfs_native.so"
_build_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    """The shared library could not be built (no toolchain, compile error)."""


def _build() -> None:
    # -B: we only get here when _stale() already decided a rebuild is
    # due, and make's own mtime compare disagrees on ties (the
    # fresh-checkout case) and ignores the Makefile-only edit case —
    # an unforced `make` would exit 0 WITHOUT recompiling and the stale
    # binary would run anyway
    try:
        proc = subprocess.run(
            ["make", "-B", "-C", str(_NATIVE_DIR)],
            capture_output=True, text=True
        )
    except FileNotFoundError as e:  # no make on PATH
        raise NativeBuildError(f"native build needs make: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed:\n{proc.stdout}\n{proc.stderr}"
        )


def _sources() -> list[pathlib.Path]:
    return [_NATIVE_DIR / "codec.cc", _NATIVE_DIR / "engine.cc",
            _NATIVE_DIR / "codec.h", _NATIVE_DIR / "Makefile"]


def _stale() -> bool:
    """Whether the .so must be (re)built.  ``>=`` on purpose: a fresh
    checkout stamps sources and a stray .so with the SAME mtime, and the
    old ``>`` compare let tests run silently against a binary built from
    DIFFERENT sources.  An mtime tie costs one cheap rebuild."""
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    return any(s.stat().st_mtime >= lib_mtime for s in _sources())


def ensure_fresh() -> pathlib.Path:
    """Rebuild the .so if any source is at-or-newer than it; raise
    NativeBuildError on failure.  tests/test_native.py calls this at
    collection so a stale binary can never pass silently against old
    engine/codec sources — and a broken rebuild is a loud failure, not
    a skip.

    Once the library is LOADED in this process a rebuild cannot take
    effect (the CDLL handle keeps serving the old mapping, and
    overwriting a dlopen'd .so risks corrupting in-flight native
    calls) — that situation raises instead of claiming freshness."""
    with _build_lock:
        if _stale():
            if _lib is not None:
                raise NativeBuildError(
                    "native sources changed AFTER the library was loaded "
                    "in this process; restart to pick up the rebuild "
                    "(refusing to overwrite a mapped .so)"
                )
            _build()
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    """Build (if needed) and load the native runtime, caching the handle."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _stale():
            _build()
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.gfs_cluster_create.restype = ctypes.c_void_p
        lib.gfs_cluster_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_double, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gfs_cluster_start.argtypes = [ctypes.c_void_p]
        lib.gfs_cluster_start.restype = ctypes.c_int
        lib.gfs_cluster_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.gfs_crash, lib.gfs_leave, lib.gfs_join):
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.gfs_advance.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.gfs_round.argtypes = [ctypes.c_void_p]
        lib.gfs_round.restype = ctypes.c_int
        for fn in (lib.gfs_membership,):
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ]
            fn.restype = ctypes.c_int
        for fn in (lib.gfs_alive, lib.gfs_drain_events):
            fn.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int
            ]
            fn.restype = ctypes.c_int
        for fn in (lib.gfs_codec_encode, lib.gfs_codec_decode):
            fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
            fn.restype = ctypes.c_int
        _lib = lib
        return _lib


# -- codec (parity-testable against detector/udp.py's Python codec) ---------

def _call_sized(fn, data: bytes, initial_cap: int) -> bytes:
    """Call a snprintf-style C function, growing the buffer on truncation
    (the function returns the full required length)."""
    cap = initial_cap
    while True:
        out = ctypes.create_string_buffer(cap)
        need = fn(data, out, cap)
        if need < cap:
            return out.raw[:need]
        cap = need + 1


def codec_encode(entries: list[tuple[str, int, float]]) -> str:
    """Members -> reference wire string, through the C++ codec."""
    lib = load_library()
    lines = "\n".join(f"{a} {hb} {ts}" for a, hb, ts in entries).encode()
    return _call_sized(lib.gfs_codec_encode, lines, 3 * len(lines) + 64).decode()


def codec_decode(wire: str) -> list[tuple[str, int, float]]:
    """Reference wire string -> members, through the C++ codec."""
    lib = load_library()
    raw = _call_sized(
        lib.gfs_codec_decode, wire.encode(), 2 * len(wire) + 64
    ).decode()
    entries = []
    for line in raw.splitlines():
        addr, hb, ts = line.split(" ")
        entries.append((addr, int(hb), float(ts)))
    return entries


# -- the engine behind the FailureDetector seam -----------------------------

class NativeUdpDetector:
    """FailureDetector over the C++ epoll engine (real localhost datagrams).

    Same verbs and views as ``detector.sim.SimDetector`` and
    ``detector.udp.UdpDetector`` — the config-1 parity path at native speed.
    ``advance(r)`` blocks for r heartbeat periods of wall time (the native
    engine, like the reference, runs in real time).
    """

    def __init__(
        self,
        n: int,
        base_port: int = 19000,
        period: float = 0.05,
        t_fail: int = 5,
        t_cooldown: int = 5,
        min_group: int = 4,
        fresh_cooldown: bool = False,
        introducer: int = 0,
    ):
        self._lib = load_library()
        self.n = n
        self._h = self._lib.gfs_cluster_create(
            n, base_port, period, t_fail, t_cooldown, min_group,
            int(fresh_cooldown), introducer,
        )
        if self._lib.gfs_cluster_start(self._h) != 0:
            self._lib.gfs_cluster_destroy(self._h)
            self._h = None
            raise RuntimeError(
                f"native cluster failed to start (ports {base_port}..{base_port + n - 1} busy?)"
            )

    # -- FailureDetector protocol ------------------------------------------
    def join(self, node: int) -> None:
        self._lib.gfs_join(self._h, node)

    def leave(self, node: int) -> None:
        self._lib.gfs_leave(self._h, node)

    def crash(self, node: int) -> None:
        self._lib.gfs_crash(self._h, node)

    def advance(self, rounds: int = 1) -> None:
        self._lib.gfs_advance(self._h, rounds)

    @property
    def round(self) -> int:
        return self._lib.gfs_round(self._h)

    def membership(self, observer: int) -> list[int]:
        buf = (ctypes.c_int * self.n)()
        count = self._lib.gfs_membership(self._h, observer, buf, self.n)
        return list(buf[:count])

    def alive_nodes(self) -> list[int]:
        buf = (ctypes.c_int * self.n)()
        count = self._lib.gfs_alive(self._h, buf, self.n)
        return list(buf[:count])

    def drain_events(self) -> list[DetectionEvent]:
        cap = 4096 * 4
        buf = (ctypes.c_int * cap)()
        events = []
        while True:
            count = self._lib.gfs_drain_events(self._h, buf, cap)
            for i in range(count):
                events.append(
                    DetectionEvent(
                        round=buf[i * 4 + 0],
                        observer=buf[i * 4 + 1],
                        subject=buf[i * 4 + 2],
                        false_positive=bool(buf[i * 4 + 3]),
                    )
                )
            if count < cap // 4:
                return events

    def close(self) -> None:
        if self._h is not None:
            self._lib.gfs_cluster_destroy(self._h)
            self._h = None

    def __enter__(self) -> "NativeUdpDetector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
