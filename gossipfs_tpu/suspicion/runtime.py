"""Per-node suspicion runtime for the socket engines.

The reference implementation of the suspect/refute lifecycle in
``suspicion/params.py``: one :class:`SuspicionRuntime` per gossip node
tracks which peers that node currently suspects, applies the
local-health stretch to its confirmation window, and counts
refutations/confirmations.  The asyncio UDP engine (``detector/udp.py``
``UdpNode``) and the per-process deploy daemons (``deploy/node.py``,
which arm it via the ``SuspicionLoad`` RPC) both drive it from their
heartbeat tick; the tensor engine implements the same state machine as
fused array transitions (``core/rounds.py``) and is pinned against the
per-node model by the golden suspicion tests.

Clock convention: the caller owns time (the UDP engines pass
``time.monotonic()`` seconds and scale windows by their period), this
class only compares "now - suspect_start" against the window it is
handed.  Keys are whatever the engine uses to name peers (addresses for
the socket engines).
"""

from __future__ import annotations

from gossipfs_tpu.suspicion.params import SuspicionParams


class SuspicionRuntime:
    """One node's suspect table + refute/confirm accounting.

    The verb surface here (suspect/adopt/expired/refute/confirm/drop/
    degraded/t_suspect_window) IS the contract's per-node lifecycle API
    (analysis/protocol_spec.py); the spec-runtime-protocol rule pins it,
    including the lh_frac-driven ``degraded`` signal and the
    lh-multiplied confirmation window.
    """

    def __init__(self, params: SuspicionParams):
        self.params = params
        self.suspects: dict[object, float] = {}  # key -> suspect-start time
        self.entered = 0
        self.refutations = 0
        self.confirms = 0

    # -- lifecycle -----------------------------------------------------------
    def suspect(self, key, now: float) -> bool:
        """Mark ``key`` SUSPECT on local evidence (a stale entry); no-op
        if already suspected.  True when newly marked."""
        if key in self.suspects:
            return False
        self.suspects[key] = now
        self.entered += 1
        return True

    def adopt(self, key, now: float) -> None:
        """Adopt a peer-disseminated suspicion (a SUSPECT broadcast):
        starts the timer but does NOT count toward ``entered`` — the
        vitals count entries newly suspected on local evidence (the
        tensor engine's semantics), and an adoption of a locally-fresh
        entry is discarded uncounted at the next tick anyway."""
        self.suspects.setdefault(key, now)

    def expired(self, key, now: float, t_suspect_window: float) -> bool:
        """Whether ``key``'s suspicion outlived the confirmation window."""
        start = self.suspects.get(key)
        return start is not None and now - start > t_suspect_window

    def refute(self, key) -> bool:
        """Fresh evidence of life (a heartbeat/incarnation advance): clear
        the suspicion.  True when a pending suspicion was refuted."""
        if self.suspects.pop(key, None) is None:
            return False
        self.refutations += 1
        return True

    def confirm(self, key) -> None:
        """SUSPECT -> FAILED: the caller removes the member; we count it."""
        self.suspects.pop(key, None)
        self.confirms += 1

    def drop(self, key) -> None:
        """Member removed for a non-detector reason (LEAVE, a peer's
        REMOVE): forget any pending suspicion without counting."""
        self.suspects.pop(key, None)

    # -- local health (Lifeguard) --------------------------------------------
    def degraded(self, n_listed: int) -> bool:
        """Evidence of self-degradation: an anomalous fraction of the
        node's listed peers simultaneously SUSPECT (params.lh_frac)."""
        p = self.params
        return p.lh_multiplier > 0 and len(self.suspects) > p.lh_frac * n_listed

    def t_suspect_window(self, unit: float, n_listed: int) -> float:
        """The SUSPECT->FAILED window in the caller's clock: ``t_suspect``
        rounds of ``unit`` seconds each, stretched by the local-health
        multiplier while degraded."""
        mult = 1 + (self.params.lh_multiplier if self.degraded(n_listed) else 0)
        return self.params.t_suspect * mult * unit

    # -- status --------------------------------------------------------------
    def status(self) -> dict:
        """THE per-node suspicion vitals document (CLI ``suspicion
        status``, the deploy ``ScenarioStatus`` ride-along) — one
        producer, so the fields cannot drift between engines."""
        return {
            "t_suspect": self.params.t_suspect,
            "lh_multiplier": self.params.lh_multiplier,
            "suspects": sorted(str(k) for k in self.suspects),
            "suspects_entered": self.entered,
            "refutations": self.refutations,
            "confirms": self.confirms,
        }
