"""Suspicion subsystem schema: SWIM suspect/refute lifecycle parameters.

The reference detector is pure crash-on-timeout (slave/slave.go:24,470):
``t_fail`` rounds of silence and the entry is declared FAILED.  The
BASELINE detection-quality curves show the limit of that single knob —
FPR grows monotonically with N, and ``--t-fail-sweep`` shows t_fail=3
collapsing into a false-positive storm, so faster detection is
unreachable by turning it.  SWIM (Das et al., DSN 2002) interposes an
intermediate SUSPECT state: a silent member is *suspected* first, and
only confirmed FAILED after ``t_suspect`` further rounds of silence; any
fresher heartbeat (an incarnation bump, in SWIM's terms) observed in the
meantime *refutes* the suspicion and the entry rejoins the membership
unharmed.  Lifeguard (Dadgar et al., 2018) adds local health awareness:
a node that sees evidence it is itself degraded — here, an anomalous
fraction of its entries simultaneously SUSPECT, the signal a starved or
cut-off receiver produces — stretches its own confirmation timeout
instead of storming.

:class:`SuspicionParams` is the one typed schema all three transport
engines consume (mirroring ``scenarios/schedule.py``):

  * tensor sim — the suspect/confirm/refute transitions fused into the
    XLA round (``core/rounds.py``; ``SimConfig.suspicion``, which gates
    the run onto the XLA merge path exactly like scenario runs);
  * asyncio UDP — real ``SUSPECT``/``REFUTE`` wire verbs with an
    incarnation (heartbeat) bump (``detector/udp.py``);
  * per-process deploy — the same params pushed over the control plane
    (``SuspicionLoad`` RPC, like ``ScenarioLoad``).

Timer semantics, identical everywhere (``suspicion/runtime.py`` is the
per-message reference implementation): the suspicion clock runs on entry
*staleness* — an entry is suspected once it has been silent more than
``t_fail`` rounds, and confirmed once silent more than
``t_fail + t_suspect * (1 + lh)`` rounds, where ``lh`` is the local
health multiplier (0 unless the observer is degraded).  In the tensor
engine the per-entry ``age`` lane carries the suspect-start timestamp
implicitly (``age - t_fail`` = rounds in SUSPECT), so no new [N, N]
lane is needed.  A refutation is any heartbeat advance observed while
SUSPECT; the UDP engine additionally carries SWIM's *active* refutation
— a suspected node that learns of its suspicion bumps its own counter
and broadcasts a REFUTE.

Jax-free on purpose: the deploy daemons (a documented jax-free path)
load this module from their ``SuspicionLoad`` RPC.
"""

from __future__ import annotations

import dataclasses
import json


@dataclasses.dataclass(frozen=True)
class SuspicionParams:
    """One suspicion policy (frozen + hashable: it rides ``SimConfig``).

    ``t_suspect``: rounds an entry stays SUSPECT before confirmation —
    total silence before FAILED is ``t_fail + t_suspect`` (times the
    local-health stretch, below).

    ``lh_multiplier`` (optional Lifeguard local health, 0 = off): when a
    node's *own* view holds more than ``lh_frac`` of its listed peers
    simultaneously SUSPECT — evidence the node itself is degraded (a
    healthy node never legitimately suspects a quarter of the cluster at
    once) — its confirmation window stretches to
    ``t_fail + t_suspect * (1 + lh_multiplier)``.  The signal is
    memoryless (recomputed each round from the live suspect fraction),
    which keeps it a cheap [N]-vector compare in the tensor engine.

    ``lh_frac``: the degradation threshold as a fraction of the node's
    listed (MEMBER + SUSPECT) peers.  Use exact binary fractions (0.25,
    0.125) so the float compare agrees bit-for-bit between the tensor
    engine (float32) and the per-node reference model (float64).
    """

    t_suspect: int = 2
    lh_multiplier: int = 0
    lh_frac: float = 0.25

    def __post_init__(self) -> None:
        if self.t_suspect < 1:
            raise ValueError(f"t_suspect must be >= 1, got {self.t_suspect}")
        if self.lh_multiplier < 0:
            raise ValueError(
                f"lh_multiplier must be >= 0, got {self.lh_multiplier}"
            )
        if not 0.0 < self.lh_frac < 1.0:
            raise ValueError(f"lh_frac must be in (0, 1), got {self.lh_frac}")

    # -- derived thresholds --------------------------------------------------
    def confirm_after(self, t_fail: int, degraded: bool = False) -> int:
        """Rounds of total silence before SUSPECT confirms to FAILED."""
        mult = 1 + (self.lh_multiplier if degraded else 0)
        return t_fail + self.t_suspect * mult

    def max_confirm_after(self, t_fail: int) -> int:
        """The worst-case confirmation age (full local-health stretch) —
        what the age lane's saturation clamp must stay above."""
        return self.confirm_after(t_fail, degraded=True)

    # -- JSON codec (the control-plane wire form, like FaultScenario's) ------
    def to_json(self) -> str:
        return json.dumps({
            "t_suspect": self.t_suspect,
            "lh_multiplier": self.lh_multiplier,
            "lh_frac": self.lh_frac,
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "SuspicionParams":
        doc = json.loads(text)
        return cls(
            t_suspect=int(doc["t_suspect"]),
            lh_multiplier=int(doc.get("lh_multiplier", 0)),
            lh_frac=float(doc.get("lh_frac", 0.25)),
        )

    @classmethod
    def from_file(cls, path: str) -> "SuspicionParams":
        with open(path) as f:
            return cls.from_json(f.read())
