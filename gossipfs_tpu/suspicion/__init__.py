"""Suspicion subsystem: SWIM-style suspect/refute lifecycle + Lifeguard
adaptive timeouts.

One :class:`~gossipfs_tpu.suspicion.params.SuspicionParams` policy
drives all three transport engines — the tensor sim (the ALIVE ->
SUSPECT -> FAILED transitions fused into the XLA round,
``SimConfig.suspicion``), the asyncio UDP engine (real SUSPECT/REFUTE
wire verbs with incarnation-bump refutation), and the per-process
deployment (params pushed over the control plane via the
``SuspicionLoad`` RPC).  See ``suspicion/params.py`` for the schema and
timer semantics; ``suspicion/runtime.py`` is the per-node reference
implementation the socket engines share.

The tensor gating helpers resolve LAZILY (module ``__getattr__``), same
pattern as ``scenarios/``: ``params``/``runtime`` are pure-Python and
the deploy daemons — a documented jax-free path that must start in
milliseconds — import them via this package from their ``SuspicionLoad``
RPC.  An eager ``tensor`` import here would pull the config module (and
with it the jax-adjacent stack) into every daemon the moment suspicion
arms.
"""

from gossipfs_tpu.suspicion.params import SuspicionParams
from gossipfs_tpu.suspicion.runtime import SuspicionRuntime

_TENSOR_EXPORTS = (
    "require_suspicion_config",
    "with_suspicion",
)

__all__ = [
    "SuspicionParams",
    "SuspicionRuntime",
    *_TENSOR_EXPORTS,
]


def __getattr__(name: str):
    if name in _TENSOR_EXPORTS:
        from gossipfs_tpu.suspicion import tensor

        return getattr(tensor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
