"""Tensor-sim gating for the suspicion subsystem.

Suspicion rides the config, not a side table: ``SimConfig.suspicion``
holds a :class:`~gossipfs_tpu.suspicion.params.SuspicionParams` and the
round kernel (core/rounds.py) branches on it at trace time.  What this
module owns is the ENGINE GATING — the same rules the scenario engine
established (scenarios/tensor.py), because the fast kernels fuse the
protocol over semantics suspicion changes:

  * the rr/pallas merge kernels run the MEMBER-only tick/epilogue
    in-kernel — they know nothing of the SUSPECT lane value, the
    widened view eligibility, or the refute-on-advance status write.
    Suspicion runs therefore execute the XLA merge path
    (``merge_kernel="xla"``); rr/pallas stays the suspicion-free fast
    path (documented in config.py's ``merge_kernel`` notes);
  * the SWAR packed-word elementwise formulation (ops/swar.py) encodes
    the 3-state status machine in its word constants — suspicion runs
    use ``elementwise="lanes"``;
  * ``remove_broadcast`` must be off: an instantaneous cluster-wide
    REMOVE would bypass the per-observer SUSPECT window entirely
    (gossip-only dissemination is the mode the lifecycle is defined
    for, and it needs ``fresh_cooldown`` as ever).

``SimConfig.__post_init__`` enforces all of this at construction, so a
fast-kernel config with suspicion is unconstructible; :func:`with_suspicion`
is the convenience that maps any gossip-only config onto its suspicion-run
form — the ``xla_fallback_config`` analog for this subsystem.
"""

from __future__ import annotations

import dataclasses

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.suspicion.params import SuspicionParams


def require_suspicion_config(config: SimConfig) -> None:
    """Reject protocol modes the SWIM lifecycle cannot compose with."""
    if config.remove_broadcast:
        raise ValueError(
            "suspicion requires remove_broadcast=False: the sim's REMOVE "
            "broadcast is an instantaneous tensor column-OR that would "
            "confirm a failure cluster-wide before any observer's SUSPECT "
            "window could refute it (use gossip-only dissemination + "
            "fresh_cooldown, the north-star mode)"
        )
    if not config.fresh_cooldown:
        raise ValueError(
            "suspicion requires fresh_cooldown=True: gossip-only "
            "dissemination with the faithful stale-timestamp fail list "
            "gives confirmed removals a ~zero cooldown and zombie re-add "
            "cycles (config.py fresh_cooldown notes), which would "
            "re-suspect the same corpse forever"
        )


def with_suspicion(config: SimConfig, params: SuspicionParams) -> SimConfig:
    """The config a suspicion run actually executes: same protocol
    thresholds/dtypes/topology, suspicion armed, XLA merge + lanes
    elementwise substituted (the scenario engine's fallback pattern —
    fault-free transport stays on the fast kernels)."""
    require_suspicion_config(config)
    rep: dict = {"suspicion": params}
    if config.merge_kernel != "xla":
        rep["merge_kernel"] = "xla"
    if config.elementwise != "lanes":
        rep["elementwise"] = "lanes"
    return dataclasses.replace(config, **rep)
