"""Tensor-sim requirements for the suspicion subsystem.

Suspicion rides the config, not a side table: ``SimConfig.suspicion``
holds a :class:`~gossipfs_tpu.suspicion.params.SuspicionParams` and the
round kernels branch on it at trace time.  Round 11 (fast-path
unification) FUSED the lifecycle into every merge path — the XLA
tick/epilogues (lanes AND the SWAR packed-word forms), the stripe/arc
pallas kernels' in-kernel epilogue, and the resident-round kernel's
packed tick/merge stages (ops/merge_pallas.py) — so the old
``merge_kernel="xla"`` / ``elementwise="lanes"`` construction gates are
GONE: a capacity-ladder rr/SWAR config with suspicion constructs and
runs, bit-equal to the XLA oracle (pinned by the oracle grid, the golden
fuzz suite, and ``verify_claims.py fastpath_parity``).

What this module still owns is the PROTOCOL-MODE requirement
(:func:`require_suspicion_config`): gossip-only dissemination.  Round 14
removed the last capability note: the Lifeguard local-health stretch
(``lh_multiplier > 0``) is fused into the rr/SWAR fast path too — the
scan carries the per-receiver SUSPECT counts (a kernel side output,
like the member counts), derives each receiver's degraded bit outside
the kernel, and the kernel applies the stretched confirmation threshold
as a per-row select (flags bit 4; ops/merge_pallas.py) — so every
suspicion knob, local health included, runs on every merge path,
oracle-pinned bit-exact against ``suspicion/runtime.py`` semantics by
the lh parity tests and the golden fuzz suite.

:func:`with_suspicion` survives as a deprecated alias of
``config.fallback_config`` — the one owner of oracle-path substitution —
for callers that explicitly want the XLA+lanes oracle form.
"""

from __future__ import annotations

from gossipfs_tpu.config import SimConfig, fallback_config
from gossipfs_tpu.suspicion.params import SuspicionParams


def require_suspicion_config(config: SimConfig) -> None:
    """Reject protocol modes the SWIM lifecycle cannot compose with."""
    if config.remove_broadcast:
        raise ValueError(
            "suspicion requires remove_broadcast=False: the sim's REMOVE "
            "broadcast is an instantaneous tensor column-OR that would "
            "confirm a failure cluster-wide before any observer's SUSPECT "
            "window could refute it (use gossip-only dissemination + "
            "fresh_cooldown, the north-star mode)"
        )
    if not config.fresh_cooldown:
        raise ValueError(
            "suspicion requires fresh_cooldown=True: gossip-only "
            "dissemination with the faithful stale-timestamp fail list "
            "gives confirmed removals a ~zero cooldown and zombie re-add "
            "cycles (config.py fresh_cooldown notes), which would "
            "re-suspect the same corpse forever"
        )


def with_suspicion(config: SimConfig, params: SuspicionParams) -> SimConfig:
    """Deprecated alias: arm suspicion on the XLA-ORACLE form of config.

    Round 11 fused the lifecycle into the fast kernels, so arming
    suspicion no longer requires any substitution —
    ``dataclasses.replace(cfg, suspicion=params)`` keeps the configured
    kernel.  This name survives for callers that explicitly want the
    oracle path (parity baselines, the curves A/B's reference rows); the
    substitution semantics have ONE owner, ``config.fallback_config``.
    """
    return fallback_config(config, suspicion=params)
