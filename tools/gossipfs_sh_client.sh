#!/usr/bin/env bash
# Non-Python gossipfs client: drives the shim's gRPC surface with nothing
# but protoc and curl (HTTP/2 prior knowledge) — the proof that
# gossipfs.proto is a codegen-able contract any non-Python consumer can
# program against (the reference's Go CLI shape; north star "the Go CLI
# keeps consuming the membership view through a thin gRPC shim").
#
# Usage:
#   tools/gossipfs_sh_client.sh HOST:PORT METHOD REQ_TYPE RESP_TYPE <<< 'textproto'
#
# Examples:
#   tools/gossipfs_sh_client.sh 127.0.0.1:9000 Join NodeRequest OkReply <<< 'node: 3'
#   tools/gossipfs_sh_client.sh 127.0.0.1:9000 Advance AdvanceRequest AdvanceReply <<< 'rounds: 5'
#   tools/gossipfs_sh_client.sh 127.0.0.1:9000 Lsm LsmRequest LsmReply <<< 'observer: 0'
#
# The request is read as protobuf text format on stdin, encoded with
# protoc --encode, framed per the gRPC HTTP/2 wire spec (1-byte compressed
# flag + 4-byte big-endian length + message), POSTed with curl over h2c,
# and the response frame is decoded back to text format.

set -euo pipefail

ADDR=${1:?usage: $0 HOST:PORT METHOD REQ_TYPE RESP_TYPE}
METHOD=${2:?method name, e.g. Join}
REQ_TYPE=${3:?request message type, e.g. NodeRequest}
RESP_TYPE=${4:?response message type, e.g. OkReply}

HERE=$(cd "$(dirname "$0")" && pwd)
PROTO_DIR=${GOSSIPFS_PROTO_DIR:-"$HERE/../gossipfs_tpu/shim"}
PROTO=gossipfs.proto

tmp=$(mktemp -d)
trap 'rm -rf "$tmp"' EXIT

# request: textproto (stdin) -> binary message -> gRPC length-prefixed frame
protoc --encode="gossipfs.$REQ_TYPE" -I "$PROTO_DIR" "$PROTO" > "$tmp/msg.bin"
len=$(stat -c%s "$tmp/msg.bin")
printf '\x00' > "$tmp/frame.bin"
for b in $(printf '%08x' "$len" | sed 's/../& /g'); do
  printf "\\x$b"
done >> "$tmp/frame.bin"
cat "$tmp/msg.bin" >> "$tmp/frame.bin"

curl -s --fail --http2-prior-knowledge \
  -H 'content-type: application/grpc+proto' \
  -H 'te: trailers' \
  --data-binary @"$tmp/frame.bin" \
  "http://$ADDR/gossipfs.Shim/$METHOD" \
  -o "$tmp/resp.bin"

# response: strip the 5-byte frame header, decode to text format
tail -c +6 "$tmp/resp.bin" | protoc --decode="gossipfs.$RESP_TYPE" -I "$PROTO_DIR" "$PROTO"
