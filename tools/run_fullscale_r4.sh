#!/bin/bash
# Round-4 full-scale evidence runs (VERDICT r3 task 3): the exact sharded
# BASELINE-config-4 program on the 8-way virtual CPU mesh, at sizes the
# committed FULLSCALE artifact has never shown.  Sequential — one host core —
# and nice'd so interactive work keeps priority.  Every run folds into the
# ONE canonical FULLSCALE.json as soon as it completes (newest run becomes
# "current", the previous current moves into the "history" array — see
# bench/full_scale.py main()).
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/fullscale_r4
# the axon site hook imports jax at interpreter startup, so the platform
# must be pinned in the environment BEFORE python launches —
# full_scale._force_cpu_mesh alone is too late under this site config
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
unset PALLAS_AXON_POOL_IPS PALLAS_AXON_REMOTE_COMPILE PALLAS_AXON_TPU_GEN
echo "[$(date -u +%FT%TZ)] start N=65536" >> /tmp/fullscale_r4/progress.log
nice -n 19 python -m gossipfs_tpu.bench.full_scale \
  --n 65536 --rounds 16 --out FULLSCALE.json \
  > /tmp/fullscale_r4/n65536.out 2>&1
echo "[$(date -u +%FT%TZ)] done N=65536 rc=$?" >> /tmp/fullscale_r4/progress.log
echo "[$(date -u +%FT%TZ)] start N=98304" >> /tmp/fullscale_r4/progress.log
nice -n 19 python -m gossipfs_tpu.bench.full_scale \
  --n 98304 --rounds 12 --out FULLSCALE.json \
  > /tmp/fullscale_r4/n98304.out 2>&1
echo "[$(date -u +%FT%TZ)] done N=98304 rc=$?" >> /tmp/fullscale_r4/progress.log
