#!/usr/bin/env python3
"""gossipfs-lint CLI — run the repo-wide invariant analyzer.

Usage::

    python tools/lint.py                 # all AST rules, exit 1 on findings
    python tools/lint.py --list          # rule table
    python tools/lint.py --rule NAME     # a subset (repeatable)
    python tools/lint.py --probe         # include probe rules (imports jax)
    python tools/lint.py --json          # machine-readable findings
    python tools/lint.py --overlay gossipfs_tpu/x.py=tests/fixtures/lint/y.py
                                         # mount a file over the scanned
                                         # tree (fixture/exit-code testing)

Exit codes: 0 clean, 1 findings, 2 usage/internal error.  The rule
registry lives in ``gossipfs_tpu/analysis/`` — see its module docstring
and BASELINE.md's "Static analysis" section.  The spec-* rules diff all
three engines against the machine-readable protocol contract
(``gossipfs_tpu/analysis/protocol_spec.py``; BASELINE.md "Protocol
contract"); ``make lint`` chains this CLI with the clang Thread Safety
Analysis and clang-tidy legs, and ``tools/spec_verify.py`` re-proves
every spec rule red (on its fixture) + green (on the repo).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossipfs_tpu.analysis import REGISTRY, RepoIndex, run_rules  # noqa: E402


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gossipfs-lint",
        description="repo-wide invariant analyzer "
                    "(gossipfs_tpu/analysis/)")
    ap.add_argument("--list", action="store_true",
                    help="list registered rules and exit")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--probe", action="store_true",
                    help="include probe rules (import jax; slower)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--overlay", action="append", default=[],
                    metavar="VIRTUAL=REAL",
                    help="mount REAL file at repo-relative VIRTUAL path")
    args = ap.parse_args(argv)

    if args.list:
        for name, r in sorted(REGISTRY.items()):
            print(f"{name} [{r.kind}]: {r.description}")
        return 0

    if args.rule:
        unknown = set(args.rule) - set(REGISTRY)
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)} "
                  f"(see --list)", file=sys.stderr)
            return 2

    overlay = {}
    for spec in args.overlay:
        if "=" not in spec:
            print(f"bad --overlay (want VIRTUAL=REAL): {spec}",
                  file=sys.stderr)
            return 2
        virt, real = spec.split("=", 1)
        overlay[virt] = real

    kinds = ("ast", "probe") if args.probe else ("ast",)
    try:
        # internal errors must land on the documented exit-code contract
        # (2), never on a traceback that exits 1 — a CI hook keying on
        # "1 = findings" would report findings that do not exist.
        # ImportError: a probe rule's heavy dependency is missing
        # (naming one with --rule is explicit consent to try);
        # SyntaxError/OSError: an unparseable or unreadable file (a
        # broken --overlay path, or a syntactically invalid source)
        findings = run_rules(RepoIndex(overlay=overlay), names=args.rule,
                             kinds=kinds)
    except (ImportError, SyntaxError, OSError) as e:
        print(f"lint internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f)
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
