"""Stage-cost bisection of the resident-round kernel (headline shapes).

Times the raw kernel (100-round scan, carried lanes) with stages stubbed
out, isolating each stage's cost in the CURRENT build:

    vtick  - view-build tick + view encode replaced by a raw copy (also
             skips the aligned group max and the ring flush that ride it)
    wmax   - the arc window work skipped entirely (group max + ring
             flush / full-T pass)
    wring  - aligned arcs only: the group max still rides the view build,
             but the ring-rotated W flush (per-chunk pair-max + carry +
             wrap close) is skipped — isolates the rotated build's own
             pass, the stage the round-9 redesign added
    gather - the per-receiver row gather skipped
    epi    - merge epilogue + every reduction replaced by a passthrough
    rcnt   - the per-receiver member-count side output zeroed
    sus    - suspicion runs only (--suspicion): the suspicion OBSERVABLE
             reductions (entered/refuted/held masks + the packed-field
             sum) skipped while the fused lifecycle transitions keep
             running — the (full)-minus-sus delta is the reduction cost,
             and the --suspicion-vs-not (full) delta is the whole fused
             lifecycle

    JAX_PLATFORMS=axon python tools/stub_bisect.py
    JAX_PLATFORMS=axon python tools/stub_bisect.py --arc-align 8
    JAX_PLATFORMS=axon python tools/stub_bisect.py --elementwise swar
    JAX_PLATFORMS=axon python tools/stub_bisect.py --arc-align 8 \
        --elementwise swar --suspicion            # round-11 fused path
    JAX_PLATFORMS=axon python tools/stub_bisect.py --arc-align 8 \
        --elementwise swar --suspicion --scenario # + edge_filter build
    JAX_PLATFORMS=cpu  python tools/stub_bisect.py --interpret --n 1024 \
        --block-c 512 --block-r 128 --rounds 2 --reps 1

``--elementwise swar`` times the packed-word SWAR stages
(config.elementwise, ops/swar.py) against the widened default — the
"(full)" row's delta between the two runs is the recovered elementwise
time.  ``--suspicion``/``--scenario`` (round 11) A/B the fused fast
path: suspicion arms the in-kernel SWIM lifecycle (t_fail=3,
t_suspect=2 — the SUSPECT_r08 fast knob), scenario switches the
aligned-arc build to the edge_filter masked gather over (base,
group-match-bitmask) pairs with a mid-partition mask (half the window
groups dropped) and the sender-mute flag bit armed on 1/16 of rows.
``--interpret`` runs the interpreter-mode kernel so the tool works
end-to-end off-TPU (stage attribution is then about interpreter op
counts, not VPU time — use it to validate the tool and the stub paths,
not to quote performance).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import functools
import json
import time

import jax
import jax.numpy as jnp
from jax import lax

from gossipfs_tpu.ops import merge_pallas
from gossipfs_tpu.config import AGE_CLAMP
from gossipfs_tpu.core.state import FAILED, MEMBER, SUSPECT, UNKNOWN

LANE = merge_pallas.LANE


def build_inputs(n, c_blk, fanout, key, arc_align=1, scenario=False):
    nc, cs = n // c_blk, c_blk // LANE
    ks = jax.random.split(key, 5)
    hb = jax.random.randint(ks[0], (nc, n, cs, LANE), -128, 127, jnp.int8)
    age = jax.random.randint(ks[1], (nc, n, cs, LANE), 0, 40, jnp.int32)
    st = jax.random.randint(ks[2], (nc, n, cs, LANE), 0, 3, jnp.int32)
    asl = merge_pallas.pack_age_status(age, st)
    # active + alive, LANE-compacted (the round-9 production layout; the
    # wrapper expands it for blockings that need the replicated form).
    # Scenario runs arm the sender-mute bit (8) on 1/16 of the rows — a
    # representative slow-sender round
    flags = jnp.broadcast_to(
        jnp.int8(1 + 4), (n // LANE, LANE)).astype(jnp.int8)
    if scenario:
        muted = (jax.random.uniform(ks[4], (n // LANE, LANE))
                 < 1.0 / 16.0)
        flags = (flags + jnp.where(muted, 8, 0)).astype(jnp.int8)
    sa = jnp.zeros((nc, cs, LANE), jnp.int32)
    sb = jnp.zeros((nc, cs, LANE), jnp.int32)
    g = jnp.full((nc, cs, LANE), -120, jnp.int32)
    if arc_align > 1:
        # aligned-arc bases are multiples of arc_align (core/topology.py
        # random_arc_bases_aligned) — unaligned bases would read gather
        # windows the aligned group-max never produced (ADVICE r5 #1)
        bases = jax.random.randint(
            ks[3], (n, 1), 0, n // arc_align, jnp.int32) * arc_align
    else:
        bases = jax.random.randint(ks[3], (n, 1), 0, n, jnp.int32)
    if scenario:
        # edge_filter form: (base, group-match bitmask) pairs — a
        # mid-partition round where ~half of each receiver's window
        # groups sit across the split (scenarios.tensor.arc_match_edges
        # builds the real masks from a rule table)
        nw = fanout // arc_align
        mask = jax.random.randint(ks[4], (n, 1), 0, 1 << nw, jnp.int32)
        return hb, asl, flags, sa, sb, g, jnp.concatenate(
            [bases, mask], axis=1)
    return hb, asl, flags, sa, sb, g, bases


def time_stub(n, c_blk, block_r, fanout, stub, rounds, reps,
              arc_align=1, elementwise="lanes", interpret=False,
              rotate=True, suspicion=False, scenario=False):
    hb, asl, flags, sa, sb, g, bases = build_inputs(
        n, c_blk, fanout, jax.random.PRNGKey(0), arc_align=arc_align,
        scenario=scenario)

    kern = functools.partial(
        merge_pallas.resident_round_blocked,
        fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
        failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
        t_fail=3 if suspicion else 5, t_cooldown=12, block_r=block_r,
        resident=True,
        arc_align=arc_align, elementwise=elementwise, interpret=interpret,
        rotate=rotate, _stub=stub,
        suspect=int(SUSPECT) if suspicion else None,
        t_suspect=2 if suspicion else 0,
        edge_filter=scenario,
    )

    @jax.jit
    def run(hb, asl):
        def step(carry, _):
            hb, asl = carry
            out = kern(bases, hb, asl, flags, sa, sb, g)
            return (out[0], out[1]), out[3].sum()
        (hb, asl), s = lax.scan(step, (hb, asl), None, length=rounds)
        return hb, asl, s

    out = run(hb, asl)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(hb, asl)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
        time.sleep(1.0)
    return best


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--block-c", type=int, default=2_048)
    p.add_argument("--block-r", type=int, default=512)
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--arc-align", type=int, default=1)
    p.add_argument("--elementwise", choices=("lanes", "swar"),
                   default="lanes")
    p.add_argument("--interpret", action="store_true",
                   help="interpreter-mode kernel (off-TPU tool validation)")
    p.add_argument("--rr-rotate", choices=("auto", "off"), default="auto",
                   help="A/B the round-9 ring-rotated build + compacted "
                        "flags (auto) against the round-5 full-T/"
                        "replicated layouts (off) — same bits")
    p.add_argument("--suspicion", action="store_true",
                   help="arm the fused SWIM lifecycle (round 11) — run "
                        "with and without to isolate the whole fused "
                        "suspicion cost; adds the 'sus' reduction stub")
    p.add_argument("--scenario", action="store_true",
                   help="run the edge_filter (scenario-armed aligned-arc)"
                        " build: masked gather over (base, match-mask) "
                        "pairs + sender-mute flags (requires --arc-align "
                        "> 1); A/B vs a run without it isolates the "
                        "filtered build's cost")
    p.add_argument("--stubs", nargs="*", default=None)
    args = p.parse_args()
    if args.scenario and args.arc_align <= 1:
        p.error("--scenario (the edge_filter build) requires --arc-align "
                "> 1; explicit-edge scenarios rewrite edges outside the "
                "kernel and cost nothing in it")
    if args.stubs is None:
        args.stubs = [
            "", "rcnt", "gather", "wmax,gather", "epi", "epi,rcnt",
            "vtick", "vtick,wmax,gather,epi,rcnt",
        ]
        if (args.arc_align > 1 and args.rr_rotate != "off"
                and not args.scenario):
            # the rotated-build stage stub only exists on aligned arcs
            # running the ring build — under --rr-rotate off (or the
            # edge_filter build, which replaces the ring with a full-T
            # masked-gather layout) it would be a no-op row mislabelled
            # as a stage cost
            args.stubs.insert(3, "wring")
        if args.suspicion:
            # isolate the suspicion observable reductions from the fused
            # lifecycle transitions (see the 'sus' stub doc above)
            args.stubs.insert(1, "sus")
    # self-describing header row (obs.schema.ROUNDPROF_SCHEMA) — same
    # convention as bench/roundprof.py, so stub-bisect JSONL artifacts
    # carry their schema/shape/knobs and the analyzer can ingest them
    from gossipfs_tpu.obs import schema as obs_schema

    print(json.dumps({
        "schema": obs_schema.ROUNDPROF_SCHEMA, "tool": "stub_bisect",
        "n": args.n, "block_c": args.block_c, "block_r": args.block_r,
        "arc_align": args.arc_align, "elementwise": args.elementwise,
        "rr_rotate": args.rr_rotate, "suspicion": args.suspicion,
        "scenario": args.scenario,
        "backend": ("interpret/" if args.interpret else "")
        + jax.default_backend(),
    }), flush=True)
    fanout = max(1, args.n.bit_length() - 1)
    if args.arc_align > 1:
        # round fanout UP to an arc_align multiple, as the production
        # entry points do (bench/curves.py, bench/frontier.py) — the raw
        # log2-ish fanout (14 at the default N) is not a multiple of 8
        # and resident_round_blocked rejects it (ADVICE r5 #1)
        fanout = -(-fanout // args.arc_align) * args.arc_align
    for stub in args.stubs:
        el = time_stub(args.n, args.block_c, args.block_r, fanout,
                       stub, args.rounds, args.reps,
                       arc_align=args.arc_align,
                       elementwise=args.elementwise,
                       interpret=args.interpret,
                       rotate=args.rr_rotate != "off",
                       suspicion=args.suspicion,
                       scenario=args.scenario)
        print(json.dumps({
            "stub": stub or "(full)",
            "ms_per_round": round(el / args.rounds * 1e3, 3),
            "elementwise": args.elementwise,
            "rr_rotate": args.rr_rotate,
            "suspicion": args.suspicion, "scenario": args.scenario,
            "backend": ("interpret/" if args.interpret else "")
            + jax.default_backend(),
        }), flush=True)


if __name__ == "__main__":
    main()
