"""Adversarial campaign CLI — sweep/bisect scenario space with the
streaming monitor as the oracle (gossipfs_tpu/campaigns/).

    # grid-sweep a severity axis, ledger every verdict
    JAX_PLATFORMS=cpu python tools/campaign.py --family flap --n 256 \
        --t-fail 3 --values 2 3 4 5 6 --ledger CAMPAIGN.jsonl

    # bisect to the exact breaking point, commit it as a regression case
    JAX_PLATFORMS=cpu python tools/campaign.py --family flap --n 256 \
        --t-fail 3 --bisect 1 10 --ledger CAMPAIGN.jsonl \
        --commit regressions/flap_storm_n256.json

    # replay a committed case (the tier-1 smoke's command form)
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --case regressions/flap_storm_n256.json

    # the SAME case over a REAL-SOCKET engine, verdict required to agree
    # with the tensor replay (campaigns/engines.py; --scale-n re-makes
    # campaign-family cases at a socket-budget cohort).  The native
    # C++ epoll engine is the COHORT-EXACT lane: committed n=256 cases
    # run at their committed n (the asyncio loop melts past n~64)
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --case regressions/outage_storm_n256.json --engine udp
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --case regressions/outage_storm_n256.json --engine native
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --case regressions/flap_storm_n256.json --engine deploy --scale-n 8

    # a case pair over one engine (the verify_claims `native_cohort`
    # claim: the committed storm + its absorption twin must reproduce
    # their pre/post-fix verdicts over the native transport)
    JAX_PLATFORMS=cpu python tools/campaign.py --engine native \
        --pair regressions/outage_storm_n256.json \
               regressions/outage_absorbed_n256.json

    # the three-engine verdict matrix over every committed case
    # (NATIVECAMPAIGN_r16.json is the committed artifact of this)
    JAX_PLATFORMS=cpu python tools/campaign.py --matrix \
        --out NATIVECAMPAIGN_r16.json

    # map the Lifeguard local-health knob surface vs correlated outages
    # (LOCALHEALTH_r14.json is the committed artifact of this command)
    JAX_PLATFORMS=cpu python tools/campaign.py --surface --n 256 \
        --t-fail 2 --t-suspect 3 --sizes 2 8 16 \
        --lh-point 4:0.015625 --lh-point 8:0.015625 \
        --crash-at 10 12 20 --out LOCALHEALTH_r14.json

    # re-verify a committed surface's chosen absorption point
    # (the verify_claims.py `outage_absorption` claim's command)
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --absorption LOCALHEALTH_r14.json

Families and their severity axes: ``campaigns.FAMILIES`` (flap/down,
loss/rate_pct, partition/split_len, outage/size).  Extra fixed knobs
ride ``--knob k=v``; the Lifeguard local-health knobs ride
``--lh-multiplier`` / ``--lh-frac`` (campaign axes since round 14).
The ledger is a ``gossipfs-obs/v1`` stream (header + ``campaign_verdict``
rows) — ``tools/timeline.py`` ingests it unchanged.  Prints ONE JSON
document; exit 0 iff the requested action succeeded (a sweep/bisect
that found breaking points still exits 0 — finding them is the job;
--case exits nonzero when NOT reproduced, --absorption when NOT
absorbed).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json


def _parse_lh_points(specs):
    pts = []
    for s in specs:
        m, _, f = s.partition(":")
        pts.append((int(m), float(f)))
    return pts


def _surface(args) -> dict:
    from gossipfs_tpu import campaigns

    pts = _parse_lh_points(args.lh_point or ["4:0.015625"])
    sizes = args.sizes or [2, 8, 16]
    probe_models = {}
    for ca in (args.crash_at or [10]):
        probe_models[str(ca)] = campaigns.knob_surface(
            args.n, sizes, pts, t_fail=args.t_fail,
            t_suspect=args.t_suspect, seed=args.seed, track=args.track,
            crash_at=ca,
        )
    # auto-pick the committed point: the smallest absorbed rack, least
    # stretch, coarsest threshold — tie-broken toward the earliest
    # probe model (the hardest one the point still absorbs under)
    chosen = None
    for ca in sorted(probe_models, key=int):
        for r in probe_models[ca]["rows"]:
            if not r["absorbed"]:
                continue
            key = (r["size"], r["lh_multiplier"], -r["lh_frac"], int(ca))
            if chosen is None or key < chosen[0]:
                chosen = (key, ca, r)
    doc = {
        "schema": "gossipfs-localhealth/v1",
        "n": args.n, "t_fail": args.t_fail, "t_suspect": args.t_suspect,
        "sizes": sizes,
        "lh_points": [{"lh_multiplier": m, "lh_frac": f}
                      for (m, f) in pts],
        "probe_models": probe_models,
        "chosen": None if chosen is None else {
            "crash_at": int(chosen[1]),
            **{k: chosen[2][k] for k in
               ("size", "lh_multiplier", "lh_frac", "outage", "quiet",
                "ttd_growth_outage", "ttd_growth_quiet", "absorbed")},
        },
        "command": ("python tools/campaign.py --surface --n %d "
                    "--t-fail %d --t-suspect %d --seed %d --track %d "
                    "--sizes %s %s --crash-at %s" % (
                        args.n, args.t_fail, args.t_suspect, args.seed,
                        args.track,
                        " ".join(str(s) for s in sizes),
                        " ".join(f"--lh-point {m}:{f}" for m, f in pts),
                        " ".join(str(c) for c in (args.crash_at or [10])),
                    )),
    }
    return doc


def _absorption(path) -> dict:
    """Re-run a committed surface's CHOSEN point (baselines included)
    and re-evaluate the absorption predicate from fresh runs — the
    ``outage_absorption`` claim."""
    from gossipfs_tpu import campaigns

    art = json.loads(open(path).read())
    ch = art.get("chosen")
    if not ch:
        return {"absorbed": False, "error": f"{path} has no chosen point"}
    # re-run with the COMMITTED point's full run knobs — the chosen
    # probe model records seed/track/rounds, and defaulting them here
    # would re-verify different experiments than the artifact's
    probe = art["probe_models"][str(ch["crash_at"])]
    fresh = campaigns.knob_surface(
        art["n"], [ch["size"]],
        [(ch["lh_multiplier"], ch["lh_frac"])],
        t_fail=art["t_fail"], t_suspect=art["t_suspect"],
        crash_at=ch["crash_at"], seed=probe.get("seed", 0),
        track=probe.get("track", 4), rounds=probe.get("rounds", 35),
        length=probe["outage"]["length"], start=probe["outage"]["start"],
    )
    row = fresh["rows"][0]
    return {
        "claim": "outage_absorption",
        "artifact": path,
        "absorbed": bool(row["absorbed"]),
        "chosen": {k: ch[k] for k in ("size", "lh_multiplier", "lh_frac",
                                      "crash_at")},
        "outage": row["outage"],
        "quiet": row["quiet"],
        "ttd_growth_outage": row["ttd_growth_outage"],
        "ttd_growth_quiet": row["ttd_growth_quiet"],
        "fpr_floor": fresh["fpr_floor"],
        "baseline_t5_outage": fresh["baselines"]["t5_outage"][
            str(ch["size"])],
    }


def _engine_cell(out: dict) -> dict:
    """One verdict-matrix cell from a run_case_engine result."""
    cell = {
        "n": out["n"],
        "scaled_from": out.get("scaled_from"),
        "verdict": out["engine_verdict"],
        # the tensor replay this row's agreement was judged against —
        # for a rescaled row that is the SCALED doc's replay, not the
        # committed-cohort one in the case's `tensor` column
        "tensor_reference_verdict": out["tensor_verdict"],
        "reproduced": out["reproduced"],
        "agreement": out["agreement"],
    }
    row = out.get("engine_row") or {}
    if "period" in row:
        cell["period"] = row["period"]
    if "tick_ms" in row:
        cell["tick_ms"] = row["tick_ms"]
    return cell


def _pair(args) -> dict:
    """Two committed cases through ONE engine — the storm/absorption
    pre/post-fix pair the `native_cohort` claim re-runs: both must
    reproduce their committed verdicts AND agree with the tensor
    replay per invariant."""
    from gossipfs_tpu.campaigns.engines import run_case_engine

    cases = {}
    ok = True
    for path in args.pair:
        out = run_case_engine(path, engine=args.engine,
                              scale_n=args.scale_n, period=args.period)
        cases[os.path.basename(path)] = {
            "expect": out["expect"],
            "tensor_verdict": out["tensor_verdict"],
            "engine": _engine_cell(out),
        }
        ok = ok and out["reproduced"]
    return {"claim": "case_pair", "engine": args.engine,
            "reproduced": ok, "cases": cases}


def _case_subprocess(path, engine: str, scale_n: int | None,
                     period: float | None) -> dict:
    """One engine row in a FRESH interpreter.  The real-time lanes are
    load-sensitive by physics (wall-clock staleness), and a matrix run
    accumulates in-process state — jax arrays from the tensor replays,
    GC pressure, event-loop residue — that measurably starves a
    subsequent socket run (observed: the committed udp twin flipping
    violated inside a long matrix process, passing standalone).
    Subprocess isolation makes every cell the same experiment the
    standalone `--case` command runs."""
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__), "--case", str(path),
           "--engine", engine]
    if scale_n is not None:
        cmd += ["--scale-n", str(scale_n)]
    if period is not None:
        cmd += ["--period", str(period)]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=1800)
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"no JSON from {cmd}: {out.stdout[-300:]}\n"
                       f"{out.stderr[-300:]}")


def _matrix(args) -> dict:
    """The three-engine verdict matrix over every committed regression
    case (the NATIVECAMPAIGN_r16.json artifact): tensor at the
    committed n (the reference), native COHORT-EXACT at the committed
    n, udp at the committed n when it fits the asyncio cohort budget
    and scale_case-rescaled otherwise.  Every engine cell runs in its
    own subprocess (see _case_subprocess).  Agreement is required per
    invariant both engines checked; `all_agree` summarizes the matrix.
    """
    import pathlib

    from gossipfs_tpu import campaigns

    repo = pathlib.Path(__file__).resolve().parents[1]
    paths = sorted((repo / "regressions").glob("*.json"))
    cases = {}
    all_agree = True
    native_cohort_max = 0
    rescale_boundaries = []
    for path in paths:
        doc = campaigns.load_case(path)
        n = int(doc["config"]["n"])
        nat = _case_subprocess(path, "native", None, args.period)
        scale = None if n <= args.udp_budget else args.udp_budget
        udp = _case_subprocess(path, "udp", scale, args.period)
        row = {
            "n": n,
            "family": doc.get("family"),
            "expect": doc["expect"],
            # the tensor replay of the committed doc (deterministic —
            # the native lane runs it cohort-exact)
            "tensor": {"verdict": nat["tensor_verdict"],
                       "reproduced": nat["tensor_verdict"]
                       == doc["expect"]["verdict"]},
            "native": _engine_cell(nat),
            "udp": _engine_cell(udp),
        }
        cases[path.name] = row
        udp_ok = udp["reproduced"]
        if not udp_ok and udp.get("scaled_from") is not None:
            # rescale boundaries, caught in-matrix — the reason the
            # cohort-exact native lane exists.  Two known classes:
            # (a) scaled_reference_flips (the round-14 knife-edge): the
            #     SCALED tensor replay flips its verdict while the
            #     socket engine still shows the committed-cohort
            #     behavior ("the absorption knife-edge is cohort-sized
            #     — the case does not simply rescale"; the committed
            #     engine-calibrated twin outage_absorbed_udp_n64.json
            #     exists for exactly this);
            # (b) knee_at_boundary: a BISECTED breaking point rescaled
            #     onto a jittered real-time transport sits at the
            #     boundary by construction (the knee is the MINIMUM
            #     violating severity on synchronous tensor rounds;
            #     receipt-stamping slack is ~one FP per window —
            #     measured worst windows 0.7-1.3x threshold across
            #     runs of the scaled flap knee).
            # Both are recorded findings, not matrix failures: the
            # binding all-invariant agreement for these cases is their
            # COHORT-EXACT native row.  Anything else (e.g. a scaled
            # mild case storming) still fails the matrix.
            reason = None
            if udp["engine_verdict"] == doc["expect"]["verdict"]:
                reason = "scaled_reference_flips"
            elif (doc.get("axis_value") is not None
                  and doc["expect"]["verdict"] == "violated"
                  and set(udp["agreement"]["mismatched"])
                  <= set(doc["expect"].get("invariants", []))):
                reason = "knee_at_boundary"
            if reason is not None:
                rescale_boundaries.append({
                    "case": path.name,
                    "reason": reason,
                    "scaled_to": udp["n"],
                    "mismatched": udp["agreement"]["mismatched"],
                    "engine_verdict": udp["engine_verdict"],
                    "scaled_tensor_verdict": udp["tensor_verdict"],
                    "committed_expect": doc["expect"]["verdict"],
                })
                udp_ok = True
        all_agree = all_agree and nat["reproduced"] and udp_ok
        if nat["reproduced"]:
            native_cohort_max = max(native_cohort_max, n)
    return {
        "schema": "gossipfs-nativecampaign/v1",
        "metric": "three-engine (tensor/udp/native) campaign verdict "
                  "matrix over every committed regression case; native "
                  "runs are cohort-exact at the committed n, udp rows "
                  "above the asyncio budget are scale_case-rescaled "
                  "(agreement judged vs the scaled tensor replay; "
                  "known rescale-boundary classes — a scaled reference "
                  "that itself flips verdict, a bisected knee sitting "
                  "at the threshold on a jittered transport — land in "
                  "rescale_boundaries with the cohort-exact native row "
                  "as the binding agreement)",
        "engines": ["tensor", "udp", "native"],
        "udp_budget": args.udp_budget,
        "native_cohort_max_n": native_cohort_max,
        "all_agree": all_agree,
        "rescale_boundaries": rescale_boundaries,
        "cases": cases,
        "command": "python tools/campaign.py --matrix --udp-budget %d"
                   % args.udp_budget,
    }


def _ab_cell_subprocess(n: int, delta: int, loops: int, rounds: int,
                        period: float | None) -> dict:
    """One A/B cell in a FRESH interpreter (same isolation rationale
    as _case_subprocess — the cells are real-time measurements)."""
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__),
           "--ab-cell", str(n), "--ab-delta", str(delta),
           "--ab-cell-loops", str(loops), "--ab-rounds", str(rounds)]
    if period is not None:
        cmd += ["--period", str(period)]
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=1800)
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"no JSON from {cmd}: {out.stdout[-300:]}\n"
                       f"{out.stderr[-300:]}")


def _ab(args) -> dict:
    """The delta-piggyback A/B grid (full-list vs delta x k epoll
    loops x cohort sizes) on the native engine: bytes/round and the
    per-round tick latency, quiet cluster, both arms at the same
    fanout.  ``ok`` requires (a) the delta arm's payload reduction at
    the LARGEST n to reach --ab-target on some loop count, (b) every
    delta cell's p50 tick inside the lane's period budget
    (native_period(n)), and (c) zero false positives in every cell —
    the honesty check that the bytes saved did not come out of
    correctness."""
    from gossipfs_tpu.campaigns.engines import native_period

    cells = []
    for n in args.ab_ns:
        for loops in args.ab_loop_grid:
            for delta in (0, 1):
                cells.append(_ab_cell_subprocess(
                    n, delta, loops, args.ab_rounds, args.period))
    by = {(c["n"], c["loops"], bool(c["delta"])): c for c in cells}
    reduction = {}
    p50_tick_ms = {}
    for n in args.ab_ns:
        for k in args.ab_loop_grid:
            full, dl = by[(n, k, False)], by[(n, k, True)]
            key = f"n{n}_k{k}"
            reduction[key] = (full["wire"]["bytes_per_round"]
                              / dl["wire"]["bytes_per_round"])
            p50_tick_ms[key] = {"full": full["tick_ms"]["p50_ms"],
                                "delta": dl["tick_ms"]["p50_ms"]}
    n_max = max(args.ab_ns)
    headline = max(reduction[f"n{n_max}_k{k}"]
                   for k in args.ab_loop_grid)
    budget_ms = {n: native_period(n) * 1000.0 for n in args.ab_ns}
    p50_ok = all(
        by[(n, k, True)]["tick_ms"]["p50_ms"] <= budget_ms[n]
        for n in args.ab_ns for k in args.ab_loop_grid)
    fp_ok = all(c["false_positives"] == 0 for c in cells)
    doc = {
        "schema": "gossipfs-delta-ab/v1",
        "metric": "full-list vs delta-piggyback wire payload and tick "
                  "latency on the native engine, k epoll loops, quiet "
                  "cluster, both arms at identical fanout",
        "ns": args.ab_ns, "loop_grid": args.ab_loop_grid,
        "rounds": args.ab_rounds,
        "cells": cells,
        "bytes_reduction": reduction,
        "p50_tick_ms": p50_tick_ms,
        "p50_budget_ms": {str(n): budget_ms[n] for n in args.ab_ns},
        "headline_reduction": headline,
        "target_reduction": args.ab_target,
        "zero_false_positives": fp_ok,
        "p50_within_budget": p50_ok,
        "ok": headline >= args.ab_target and p50_ok and fp_ok,
    }
    if args.ab_udp_case:
        u = _case_subprocess(args.ab_udp_case, "udp", None, args.period)
        wire = (u.get("engine_row") or {}).get("wire")
        doc["udp_slice"] = {
            "case": os.path.basename(args.ab_udp_case),
            "reproduced": u["reproduced"],
            "verdict": u["engine_verdict"],
            "agreement": u["agreement"],
            "wire": wire,
        }
        doc["ok"] = doc["ok"] and u["reproduced"] \
            and bool(wire and wire["frames_delta"] > 0)
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", choices=None, default=None,
                   help="scenario family (campaigns.FAMILIES)")
    p.add_argument("--n", type=int, default=256)
    p.add_argument("--t-fail", type=int, default=5)
    p.add_argument("--t-suspect", type=int, default=0,
                   help="arm the SWIM lifecycle at this suspect window "
                        "(0 = raw)")
    p.add_argument("--lh-multiplier", type=int, default=0,
                   help="Lifeguard local-health stretch multiplier "
                        "(needs --t-suspect; a campaign axis since "
                        "round 14)")
    p.add_argument("--lh-frac", type=float, default=0.25,
                   help="local-health degradation threshold (fraction "
                        "of listed peers simultaneously SUSPECT; use "
                        "exact binary fractions)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--track", type=int, default=4,
                   help="tracked crashes per run (TTD/reconvergence "
                        "probes)")
    p.add_argument("--fault-rounds", type=int, default=24,
                   help="how long the family's fault window stays armed")
    p.add_argument("--values", type=int, nargs="+", default=None,
                   help="grid-sweep these severity-axis values")
    p.add_argument("--bisect", type=int, nargs=2, metavar=("LO", "HI"),
                   default=None,
                   help="bisect the severity axis over [LO, HI] to the "
                        "smallest violating value")
    p.add_argument("--knob", action="append", default=[],
                   metavar="K=V", help="fix a family knob (repeatable)")
    p.add_argument("--ledger", type=str, default=None,
                   help="write the campaign ledger JSONL here")
    p.add_argument("--commit", type=str, default=None,
                   help="commit the confirmed breaking point as a "
                        "regression case file at this path")
    p.add_argument("--case", type=str, default=None,
                   help="replay a committed regression case instead of "
                        "running a campaign")
    p.add_argument("--engine", choices=("tensor", "udp", "deploy",
                                        "native"),
                   default="tensor",
                   help="engine for --case/--pair replays: tensor "
                        "(default), udp (asyncio real sockets), deploy "
                        "(one OS process per node), native (C++ epoll — "
                        "the cohort-exact lane) — socket verdicts must "
                        "agree with the tensor replay")
    p.add_argument("--pair", type=str, nargs=2, default=None,
                   metavar=("CASE_A", "CASE_B"),
                   help="replay TWO committed cases through --engine "
                        "(the storm/absorption pre/post-fix pair; exit "
                        "0 iff both reproduce)")
    p.add_argument("--matrix", action="store_true",
                   help="run every committed regressions/ case through "
                        "tensor+udp+native and emit the verdict-matrix "
                        "artifact (NATIVECAMPAIGN_r16.json)")
    p.add_argument("--udp-budget", type=int, default=64,
                   help="--matrix: cohort budget for the asyncio lane — "
                        "bigger committed cases are scale_case-rescaled "
                        "to it (the native lane always runs cohort-"
                        "exact)")
    p.add_argument("--scale-n", type=int, default=None,
                   help="re-make a campaign-family case at this cohort "
                        "size before replaying (the deploy lane's "
                        "process budget; campaigns/engines.scale_case)")
    p.add_argument("--period", type=float, default=None,
                   help="socket-engine heartbeat period in seconds")
    p.add_argument("--trace", type=str, default=None,
                   help="keep the socket engine's recorded obs stream "
                        "at this path")
    p.add_argument("--surface", action="store_true",
                   help="map the local-health knob surface vs "
                        "correlated outages (campaigns.knob_surface)")
    p.add_argument("--sizes", type=int, nargs="+", default=None,
                   help="--surface: outage sizes")
    p.add_argument("--lh-point", action="append", default=None,
                   metavar="M:FRAC",
                   help="--surface: a (lh_multiplier, lh_frac) point "
                        "(repeatable)")
    p.add_argument("--crash-at", type=int, nargs="+", default=None,
                   help="--surface: tracked-probe crash rounds to map "
                        "(the probe model is a load-bearing axis — see "
                        "campaigns.knob_surface on the heal race)")
    p.add_argument("--out", type=str, default=None,
                   help="--surface/--matrix: write the artifact here too")
    p.add_argument("--absorption", type=str, default=None, metavar="ART",
                   help="re-verify a committed surface artifact's "
                        "chosen point (the outage_absorption claim)")
    p.add_argument("--ab", action="store_true",
                   help="delta-piggyback A/B grid on the native engine "
                        "(full vs delta x --ab-loop-grid x --ab-ns); "
                        "with --matrix, both land in one cohort "
                        "artifact (COHORT_r20.json)")
    p.add_argument("--ab-ns", type=int, nargs="+",
                   default=[256, 512, 1024],
                   help="--ab: cohort sizes")
    p.add_argument("--ab-loop-grid", type=int, nargs="+", default=[1, 4],
                   help="--ab: epoll loop counts (gfs_configure loops=k)")
    p.add_argument("--ab-rounds", type=int, default=24,
                   help="--ab: measured steady-state rounds per cell")
    p.add_argument("--ab-target", type=float, default=4.0,
                   help="--ab: required bytes/round reduction at the "
                        "largest --ab-ns")
    p.add_argument("--ab-udp-case", type=str, default=None,
                   help="--ab: also replay this committed delta case "
                        "on the udp engine (the delta_cohort claim's "
                        "verdict-agreement slice)")
    p.add_argument("--ab-cell", type=int, default=None, metavar="N",
                   help=argparse.SUPPRESS)
    p.add_argument("--ab-delta", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--ab-cell-loops", type=int, default=1,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    from gossipfs_tpu import campaigns

    if args.absorption:
        out = _absorption(args.absorption)
        print(json.dumps(out))
        return 0 if out["absorbed"] else 1

    if args.surface:
        out = _surface(args)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
                f.write("\n")
        print(json.dumps(out))
        return 0

    if args.ab_cell:
        from gossipfs_tpu.campaigns.engines import run_ab_cell

        out = run_ab_cell(args.ab_cell, delta=bool(args.ab_delta),
                          loops=args.ab_cell_loops,
                          rounds=args.ab_rounds, period=args.period)
        print(json.dumps(out))
        return 0

    if args.ab and args.matrix:
        # the round-20 cohort artifact: the three-engine verdict matrix
        # (n=1024 cohort-exact included) + the delta A/B perf grid
        matrix = _matrix(args)
        ab = _ab(args)
        out = {
            "schema": "gossipfs-cohort/v1",
            "matrix": matrix,
            "ab": ab,
            "all_agree": matrix["all_agree"],
            "native_cohort_max_n": matrix["native_cohort_max_n"],
            "headline_reduction": ab["headline_reduction"],
            "ok": matrix["all_agree"] and ab["ok"],
            "command": ("python tools/campaign.py --matrix --ab "
                        "--ab-ns %s --out COHORT_r20.json"
                        % " ".join(str(n) for n in args.ab_ns)),
        }
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
                f.write("\n")
        print(json.dumps({k: out[k] for k in
                          ("schema", "all_agree", "native_cohort_max_n",
                           "headline_reduction", "ok")}))
        return 0 if out["ok"] else 1

    if args.ab:
        out = _ab(args)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
                f.write("\n")
        print(json.dumps(out))
        return 0 if out["ok"] else 1

    if args.matrix:
        out = _matrix(args)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
                f.write("\n")
        print(json.dumps(out))
        return 0 if out["all_agree"] else 1

    if args.pair:
        if args.engine == "tensor":
            p.error("--pair compares a SOCKET engine against the tensor "
                    "replay; pick --engine udp|deploy|native")
        out = _pair(args)
        print(json.dumps(out))
        return 0 if out["reproduced"] else 1

    if args.case:
        if args.engine == "tensor":
            if args.scale_n:
                p.error("--scale-n applies to socket engines "
                        "(--engine udp|deploy)")
            out = campaigns.run_case(args.case)
        else:
            out = campaigns.run_case_engine(
                args.case, engine=args.engine, scale_n=args.scale_n,
                period=args.period, trace=args.trace,
            )
        print(json.dumps(out))
        return 0 if out["reproduced"] else 1

    if not args.family:
        p.error("--family (or --case / --pair / --matrix / --surface / "
                "--absorption) is required")
    if args.family not in campaigns.FAMILIES:
        p.error(f"unknown family {args.family!r}; pick from "
                f"{sorted(campaigns.FAMILIES)}")
    if (args.values is None) == (args.bisect is None):
        p.error("pick exactly one of --values / --bisect")
    knobs = {}
    for kv in args.knob:
        k, _, v = kv.partition("=")
        knobs[k] = int(v)

    axis = campaigns.FAMILIES[args.family]["axis"]
    if axis in knobs:
        p.error(f"--knob {axis}=... fixes the {args.family} family's "
                "swept severity axis; give it via --values / --bisect")
    ledger = None
    if args.ledger:
        ledger = campaigns.CampaignLedger(
            args.ledger, family=args.family, n=args.n, axis=axis,
            t_fail=args.t_fail, t_suspect=args.t_suspect,
            lh_multiplier=args.lh_multiplier, lh_frac=args.lh_frac,
            seed=args.seed)
    common = dict(fault_rounds=args.fault_rounds, t_fail=args.t_fail,
                  t_suspect=args.t_suspect,
                  lh_multiplier=args.lh_multiplier, lh_frac=args.lh_frac,
                  seed=args.seed, track=args.track, ledger=ledger,
                  **knobs)
    if args.values is not None:
        out = campaigns.sweep_axis(args.family, args.n, args.values,
                                   **common)
        breaking = min(out["breaking"], default=None)
    else:
        lo, hi = args.bisect
        out = campaigns.bisect_axis(args.family, args.n, lo, hi, **common)
        breaking = out["breaking_point"]
    if ledger is not None:
        ledger.close()
        out["ledger"] = args.ledger

    if args.commit and breaking is not None:
        # re-derive the committed point's scenario (same avoid set as
        # the runs) and stamp the case with the observed verdict
        row = next(r for r in out["rows"]
                   if r["axis_value"] == breaking)
        from gossipfs_tpu.bench.run import tracked_crash_events
        from gossipfs_tpu.obs.monitor import MonitorParams

        cfg = campaigns.driver.campaign_config(
            args.n, t_fail=args.t_fail, t_suspect=args.t_suspect,
            lh_multiplier=args.lh_multiplier, lh_frac=args.lh_frac)
        _, crash_rounds, _ = tracked_crash_events(
            cfg, args.fault_rounds + 1, args.track, 10)
        sc = campaigns.make_scenario(
            args.family, args.n, args.fault_rounds,
            avoid=set(crash_rounds) | {cfg.introducer},
            **{axis: breaking}, **knobs)
        campaigns.write_case(
            args.commit, sc, t_fail=args.t_fail,
            t_suspect=args.t_suspect,
            lh_multiplier=args.lh_multiplier, lh_frac=args.lh_frac,
            seed=args.seed, track=args.track,
            params=MonitorParams.from_dict(row["monitor_params"]),
            expect={"verdict": "violated",
                    "invariants": sorted(
                        row["monitor"]["by_invariant"])},
            family=args.family, axis=axis, axis_value=breaking,
        )
        out["committed"] = args.commit
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
