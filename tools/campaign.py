"""Adversarial campaign CLI — sweep/bisect scenario space with the
streaming monitor as the oracle (gossipfs_tpu/campaigns/).

    # grid-sweep a severity axis, ledger every verdict
    JAX_PLATFORMS=cpu python tools/campaign.py --family flap --n 256 \
        --t-fail 3 --values 2 3 4 5 6 --ledger CAMPAIGN.jsonl

    # bisect to the exact breaking point, commit it as a regression case
    JAX_PLATFORMS=cpu python tools/campaign.py --family flap --n 256 \
        --t-fail 3 --bisect 1 10 --ledger CAMPAIGN.jsonl \
        --commit regressions/flap_storm_n256.json

    # replay a committed case (the tier-1 smoke's command form)
    JAX_PLATFORMS=cpu python tools/campaign.py \
        --case regressions/flap_storm_n256.json

Families and their severity axes: ``campaigns.FAMILIES`` (flap/down,
loss/rate_pct, partition/split_len, outage/size).  Extra fixed knobs
ride ``--knob k=v``.  The ledger is a ``gossipfs-obs/v1`` stream
(header + ``campaign_verdict`` rows) — ``tools/timeline.py`` ingests it
unchanged.  Prints ONE JSON document; exit 0 iff the requested action
succeeded (a sweep/bisect that found breaking points still exits 0 —
finding them is the job; --case exits nonzero when NOT reproduced).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", choices=None, default=None,
                   help="scenario family (campaigns.FAMILIES)")
    p.add_argument("--n", type=int, default=256)
    p.add_argument("--t-fail", type=int, default=5)
    p.add_argument("--t-suspect", type=int, default=0,
                   help="arm the SWIM lifecycle at this suspect window "
                        "(0 = raw)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--track", type=int, default=4,
                   help="tracked crashes per run (TTD/reconvergence "
                        "probes)")
    p.add_argument("--fault-rounds", type=int, default=24,
                   help="how long the family's fault window stays armed")
    p.add_argument("--values", type=int, nargs="+", default=None,
                   help="grid-sweep these severity-axis values")
    p.add_argument("--bisect", type=int, nargs=2, metavar=("LO", "HI"),
                   default=None,
                   help="bisect the severity axis over [LO, HI] to the "
                        "smallest violating value")
    p.add_argument("--knob", action="append", default=[],
                   metavar="K=V", help="fix a family knob (repeatable)")
    p.add_argument("--ledger", type=str, default=None,
                   help="write the campaign ledger JSONL here")
    p.add_argument("--commit", type=str, default=None,
                   help="commit the confirmed breaking point as a "
                        "regression case file at this path")
    p.add_argument("--case", type=str, default=None,
                   help="replay a committed regression case instead of "
                        "running a campaign")
    args = p.parse_args(argv)

    from gossipfs_tpu import campaigns

    if args.case:
        out = campaigns.run_case(args.case)
        print(json.dumps(out))
        return 0 if out["reproduced"] else 1

    if not args.family:
        p.error("--family (or --case) is required")
    if args.family not in campaigns.FAMILIES:
        p.error(f"unknown family {args.family!r}; pick from "
                f"{sorted(campaigns.FAMILIES)}")
    if (args.values is None) == (args.bisect is None):
        p.error("pick exactly one of --values / --bisect")
    knobs = {}
    for kv in args.knob:
        k, _, v = kv.partition("=")
        knobs[k] = int(v)

    axis = campaigns.FAMILIES[args.family]["axis"]
    if axis in knobs:
        p.error(f"--knob {axis}=... fixes the {args.family} family's "
                "swept severity axis; give it via --values / --bisect")
    ledger = None
    if args.ledger:
        ledger = campaigns.CampaignLedger(
            args.ledger, family=args.family, n=args.n, axis=axis,
            t_fail=args.t_fail, t_suspect=args.t_suspect, seed=args.seed)
    common = dict(fault_rounds=args.fault_rounds, t_fail=args.t_fail,
                  t_suspect=args.t_suspect, seed=args.seed,
                  track=args.track, ledger=ledger, **knobs)
    if args.values is not None:
        out = campaigns.sweep_axis(args.family, args.n, args.values,
                                   **common)
        breaking = min(out["breaking"], default=None)
    else:
        lo, hi = args.bisect
        out = campaigns.bisect_axis(args.family, args.n, lo, hi, **common)
        breaking = out["breaking_point"]
    if ledger is not None:
        ledger.close()
        out["ledger"] = args.ledger

    if args.commit and breaking is not None:
        # re-derive the committed point's scenario (same avoid set as
        # the runs) and stamp the case with the observed verdict
        row = next(r for r in out["rows"]
                   if r["axis_value"] == breaking)
        from gossipfs_tpu.bench.run import tracked_crash_events
        from gossipfs_tpu.obs.monitor import MonitorParams

        cfg = campaigns.driver.campaign_config(
            args.n, t_fail=args.t_fail, t_suspect=args.t_suspect)
        _, crash_rounds, _ = tracked_crash_events(
            cfg, args.fault_rounds + 1, args.track, 10)
        sc = campaigns.make_scenario(
            args.family, args.n, args.fault_rounds,
            avoid=set(crash_rounds) | {cfg.introducer},
            **{axis: breaking}, **knobs)
        campaigns.write_case(
            args.commit, sc, t_fail=args.t_fail,
            t_suspect=args.t_suspect, seed=args.seed, track=args.track,
            params=MonitorParams.from_dict(row["monitor_params"]),
            expect={"verdict": "violated",
                    "invariants": sorted(
                        row["monitor"]["by_invariant"])},
            family=args.family, axis=axis, axis_value=breaking,
        )
        out["committed"] = args.commit
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
