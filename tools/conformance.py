"""Conformance-fuzzing CLI: the corpus x engine matrix, one command.

    JAX_PLATFORMS=cpu python tools/conformance.py --matrix \
        --out CONFORMANCE_r19.json          # full corpus, every engine
    JAX_PLATFORMS=cpu python tools/conformance.py --slice
                                            # pinned fast subset (the
                                            # verify_claims.py
                                            # spec_conformance claim)
    JAX_PLATFORMS=cpu python tools/conformance.py --replay \
        regressions/conformance_malformed_udp.json
                                            # re-run a committed repro

Every mode prints one final JSON line and exits nonzero when any
verdict row flips — CI-shaped, like tools/spec_verify.py.

``--matrix --evidence <red_row.json>`` embeds a captured PRE-FIX
verdict row in the artifact and re-runs the same (family, seed, engine)
cell now for the green twin — the SPEC_r17 red->green evidence pattern.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json

from gossipfs_tpu.conformance import harness, schedules, verdict

#: the CPU claim slice: the oracle selfcheck sweeps every family, the
#: tensor column runs every family it can, and the udp column is pinned
#: to the two shortest wire-verb families (8 + 12 rounds) so the claim
#: stays seconds, not minutes.  The native column is the slow lane's
#: (tests/test_conformance.py native smoke + --matrix).
SLICE_UDP_FAMILIES = ("leave_broadcast", "suspect_flood")


def _summary(matrix: dict) -> dict:
    return {
        "ok": matrix["all_agree"],
        "cases": matrix["cases"],
        "rows": len(matrix["rows"]),
        "engines_run": matrix["engines_run"],
        "coverage_complete": matrix["coverage"]["complete"],
        "disagreements": matrix["disagreements"],
    }


def _emit(summary: dict) -> int:
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1


def run_slice() -> dict:
    """The pinned claim subset (CPU, no native toolchain needed)."""
    rows = []
    for fam, spec in schedules.FAMILIES.items():
        case = schedules.generate(fam, seed=0)
        ref = harness.run_case_reference(case)
        rows.append(verdict.oracle_selfcheck(case, ref))
        if "tensor" in spec["engines"]:
            rows.append(verdict.compare(
                case, ref, harness.run_case_tensor(case)))
        if fam in SLICE_UDP_FAMILIES and "udp" in spec["engines"]:
            rows.append(verdict.compare(
                case, ref, harness.run_case_udp(case)))
    failing = [r for r in rows if not r["ok"]]
    return {
        "ok": not failing,
        "cases": len(schedules.FAMILIES),
        "rows": len(rows),
        "engines_run": sorted({r["engine"] for r in rows}),
        "coverage_complete": schedules.coverage()["complete"],
        "disagreements": [
            {"family": r["family"], "seed": r["seed"], "engine": r["engine"],
             "failed_checks": sorted(k for k, c in r["checks"].items()
                                     if not c["ok"])}
            for r in failing
        ],
    }


def _green_twin(red_row: dict) -> dict:
    """Re-run the red row's exact (family, seed, engine) cell on the
    current tree — the post-fix half of the evidence pair."""
    case = schedules.generate(red_row["family"], seed=red_row["seed"])
    ref = harness.run_case_reference(case)
    bundle = harness.RUNNERS[red_row["engine"]](case)
    return verdict.compare(case, ref, bundle)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--matrix", action="store_true",
                      help="full corpus x engine matrix")
    mode.add_argument("--slice", action="store_true",
                      help="pinned fast subset (the spec_conformance claim)")
    mode.add_argument("--replay", metavar="CASE_JSON",
                      help="re-run one committed case doc")
    p.add_argument("--engines", nargs="*", default=None,
                   help="restrict engine columns (reference always runs)")
    p.add_argument("--seeds", nargs="*", type=int, default=[0])
    p.add_argument("--out", default=None,
                   help="write the full matrix doc here (--matrix only)")
    p.add_argument("--evidence", default=None,
                   help="captured pre-fix red verdict row to embed "
                        "red->green in --out (--matrix only)")
    args = p.parse_args(argv)

    if args.slice:
        return _emit(run_slice())

    if args.replay:
        with open(args.replay, encoding="utf-8") as f:
            case = schedules.parse(f.read())
        rows = verdict.run_case(case, engines=args.engines)
        failing = [r for r in rows if not r["ok"]]
        return _emit({
            "ok": not failing,
            "cases": 1,
            "rows": len(rows),
            "engines_run": sorted({r["engine"] for r in rows}),
            "coverage_complete": schedules.coverage()["complete"],
            "disagreements": [
                {"family": r["family"], "seed": r["seed"],
                 "engine": r["engine"],
                 "failed_checks": sorted(k for k, c in r["checks"].items()
                                         if not c["ok"])}
                for r in failing
            ],
        })

    corpus = schedules.generate_corpus(seeds=tuple(args.seeds))
    matrix = verdict.run_matrix(corpus, engines=args.engines)
    if args.out:
        doc = {"schema": "gossipfs-conformance-evidence/v1",
               "matrix": matrix}
        if args.evidence:
            with open(args.evidence, encoding="utf-8") as f:
                red = json.load(f)
            doc["divergence"] = {
                "finding": (
                    "detector/udp.py _decode parsed hb with a bare "
                    "int(float(...)): one malformed chunk raised and "
                    "aborted the WHOLE datagram, losing every valid "
                    "entry sharing it (the native codec skips bad "
                    "entries).  The malformed_codec family's "
                    "mixed_refresh payload — a refuting incarnation "
                    "advance riding with a truncated entry — made the "
                    "asymmetry observable: the udp engine confirmed a "
                    "live node dead.  Fixed by per-entry skip; minimal "
                    "repro committed (shrink.py, signature-pinned)."),
                "red": red,
                "green": _green_twin(red),
                "minimized": "regressions/conformance_malformed_udp.json",
            }
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    return _emit(_summary(matrix))


if __name__ == "__main__":
    sys.exit(main())
