"""On-chip parity soak: the COMPILED rr kernel vs the XLA path.

The test suite pins kernel parity in interpreter mode on CPU; this tool
runs the actual Mosaic-compiled kernel on the TPU against the XLA
formulation over a long crash-churn horizon and asserts bit-equality of
every state lane and metric — hardware-level evidence the interpret
tests cannot give.

    JAX_PLATFORMS=axon python tools/parity_soak.py --rounds 300
    JAX_PLATFORMS=axon python tools/parity_soak.py --suspicion --scenario
    JAX_PLATFORMS=cpu  python tools/parity_soak.py --interpret --n 2048 \
        --block-c 1024 --rounds 16 --elementwise swar --suspicion --scenario

Round-5 artifact (2026-07-31): 300 rounds, N=16,384, aligned-arc
headline config, 0.5% churn -> all lanes + metrics bit-equal, 118.6M
detection events exercised.

Round 11: ``--suspicion`` / ``--scenario`` soak the fused fast path
(SWIM lifecycle in the packed tick/merge, partition + slow-sender
filtering via the edge_filter masked gather) against the XLA oracle;
``--interpret`` is the CPU form — what ``verify_claims.py
fastpath_parity`` re-runs.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import dataclasses
import json

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--rounds", type=int, default=300)
    p.add_argument("--crash-rate", type=float, default=0.005)
    p.add_argument("--block-c", type=int, default=2_048)
    p.add_argument("--block-r", type=int, default=512)
    p.add_argument("--arc-align", type=int, default=8)
    p.add_argument("--fanout", type=int, default=16)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--elementwise", choices=("lanes", "swar"),
                   default="lanes",
                   help="elementwise formulation for BOTH paths (swar = "
                        "packed 4-subject words, ops/swar.py) — run once "
                        "per value to certify the compiled SWAR kernel "
                        "on-chip before bench.py's probe trusts it")
    p.add_argument("--suspicion", action="store_true",
                   help="arm the SWIM lifecycle at the fast knob "
                        "(t_fail=3, t_suspect=2) on BOTH paths — the "
                        "round-11 fused suspect/confirm/refute stages vs "
                        "the XLA lifecycle, bit-equality incl. the "
                        "suspicion counters")
    p.add_argument("--scenario", action="store_true",
                   help="arm a timed half/half partition + slow-sender "
                        "scenario on BOTH paths — the round-11 "
                        "edge_filter masked gather vs the XLA group form")
    p.add_argument("--interpret", action="store_true",
                   help="interpreter-mode rr kernel: the CPU form of this "
                        "soak (verify_claims.py fastpath_parity); without "
                        "it the compiled Mosaic kernel runs on-chip")
    args = p.parse_args(argv)

    import jax

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state

    kw = {}
    if args.suspicion:
        from gossipfs_tpu.suspicion.params import SuspicionParams

        kw.update(t_fail=3, suspicion=SuspicionParams(t_suspect=2))
    base = SimConfig(
        n=args.n, topology="random_arc", fanout=args.fanout,
        arc_align=args.arc_align,
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
        merge_kernel="pallas_rr_interpret" if args.interpret
        else "pallas_rr",
        merge_block_r=args.block_r,
        view_dtype="int8", merge_block_c=args.block_c, rr_resident="auto",
        hb_dtype="int8", elementwise=args.elementwise, **kw,
    )
    run_kw = {}
    if args.scenario:
        from gossipfs_tpu.scenarios import FaultScenario, Partition, SlowNode
        from gossipfs_tpu.scenarios.tensor import compile_tensor

        n = args.n
        run_kw["scenario"] = compile_tensor(FaultScenario(
            name="soak-split", n=n,
            partitions=(Partition(start=3, end=max(args.rounds // 2, 8),
                                  groups=(tuple(range(n // 2)),)),),
            slow_nodes=(SlowNode(start=0, end=args.rounds, stride=3,
                                 nodes=tuple(range(min(n // 16, 256)))),),
        ))
        run_kw["crash_only_events"] = True
    key = jax.random.PRNGKey(args.seed)
    out = {}
    rr_kernel = base.merge_kernel
    for kernel in (rr_kernel, "xla"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        st, mc, pr = run_rounds(
            init_state(cfg), cfg, args.rounds, key,
            crash_rate=args.crash_rate, **run_kw,
        )
        out[kernel] = (jax.device_get(st), jax.device_get(mc),
                       jax.device_get(pr))
    (sr, mr, prr) = out[rr_kernel]
    (sx, mx, prx) = out["xla"]
    checks = {
        "hb": np.array_equal(sr.hb, sx.hb),
        "age": np.array_equal(sr.age, sx.age),
        "status": np.array_equal(sr.status, sx.status),
        "alive": np.array_equal(sr.alive, sx.alive),
        "hb_base": np.array_equal(sr.hb_base, sx.hb_base),
        "first_detect": np.array_equal(mr.first_detect, mx.first_detect),
        "converged": np.array_equal(mr.converged, mx.converged),
        "true_detections": np.array_equal(
            prr.true_detections, prx.true_detections),
        "false_positives": np.array_equal(
            prr.false_positives, prx.false_positives),
    }
    if args.suspicion:
        checks.update({
            "first_suspect": np.array_equal(
                mr.first_suspect, mx.first_suspect),
            "suspects_entered": np.array_equal(
                prr.suspects_entered, prx.suspects_entered),
            "refutations": np.array_equal(
                prr.refutations, prx.refutations),
            "fp_suppressed": np.array_equal(
                prr.fp_suppressed, prx.fp_suppressed),
        })
    doc = {
        "n": args.n, "rounds": args.rounds, "arc_align": args.arc_align,
        "elementwise": args.elementwise, "kernel": rr_kernel,
        "suspicion": bool(args.suspicion), "scenario": bool(args.scenario),
        **checks,
        "all_equal": all(checks.values()),
        "total_detections": int(prr.true_detections.sum()),
    }
    if args.suspicion:
        doc["total_suspects"] = int(prr.suspects_entered.sum())
        doc["total_refutations"] = int(prr.refutations.sum())
    print(json.dumps(doc))
    return 0 if doc["all_equal"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
