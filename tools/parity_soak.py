"""On-chip parity soak: the COMPILED rr kernel vs the XLA path.

The test suite pins kernel parity in interpreter mode on CPU; this tool
runs the actual Mosaic-compiled kernel on the TPU against the XLA
formulation over a long crash-churn horizon and asserts bit-equality of
every state lane and metric — hardware-level evidence the interpret
tests cannot give.

    JAX_PLATFORMS=axon python tools/parity_soak.py --rounds 300

Round-5 artifact (2026-07-31): 300 rounds, N=16,384, aligned-arc
headline config, 0.5% churn -> all lanes + metrics bit-equal, 118.6M
detection events exercised.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import dataclasses
import json

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--rounds", type=int, default=300)
    p.add_argument("--crash-rate", type=float, default=0.005)
    p.add_argument("--block-c", type=int, default=2_048)
    p.add_argument("--block-r", type=int, default=512)
    p.add_argument("--arc-align", type=int, default=8)
    p.add_argument("--fanout", type=int, default=16)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--elementwise", choices=("lanes", "swar"),
                   default="lanes",
                   help="elementwise formulation for BOTH paths (swar = "
                        "packed 4-subject words, ops/swar.py) — run once "
                        "per value to certify the compiled SWAR kernel "
                        "on-chip before bench.py's probe trusts it")
    args = p.parse_args(argv)

    import jax

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state

    base = SimConfig(
        n=args.n, topology="random_arc", fanout=args.fanout,
        arc_align=args.arc_align,
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
        merge_kernel="pallas_rr", merge_block_r=args.block_r,
        view_dtype="int8", merge_block_c=args.block_c, rr_resident="auto",
        hb_dtype="int8", elementwise=args.elementwise,
    )
    key = jax.random.PRNGKey(args.seed)
    out = {}
    for kernel in ("pallas_rr", "xla"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        st, mc, pr = run_rounds(
            init_state(cfg), cfg, args.rounds, key,
            crash_rate=args.crash_rate,
        )
        out[kernel] = (jax.device_get(st), jax.device_get(mc),
                       jax.device_get(pr))
    (sr, mr, prr) = out["pallas_rr"]
    (sx, mx, prx) = out["xla"]
    checks = {
        "hb": np.array_equal(sr.hb, sx.hb),
        "age": np.array_equal(sr.age, sx.age),
        "status": np.array_equal(sr.status, sx.status),
        "alive": np.array_equal(sr.alive, sx.alive),
        "hb_base": np.array_equal(sr.hb_base, sx.hb_base),
        "first_detect": np.array_equal(mr.first_detect, mx.first_detect),
        "converged": np.array_equal(mr.converged, mx.converged),
        "true_detections": np.array_equal(
            prr.true_detections, prx.true_detections),
        "false_positives": np.array_equal(
            prr.false_positives, prx.false_positives),
    }
    doc = {
        "n": args.n, "rounds": args.rounds, "arc_align": args.arc_align,
        "elementwise": args.elementwise,
        **checks,
        "all_equal": all(checks.values()),
        "total_detections": int(prr.true_detections.sum()),
    }
    print(json.dumps(doc))
    return 0 if doc["all_equal"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
