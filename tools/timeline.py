"""Detection-timeline analyzer over flight-recorder event streams.

Merges one or more JSONL event streams (``obs/schema.py`` records —
bench ``--trace`` artifacts, deploy ``node<i>.log`` files, anything a
``FlightRecorder`` wrote), reconstructs per-subject
crash -> SUSPECT -> confirm -> REMOVE -> repair timelines, and
re-derives the detection metrics (TTD first/converged/suspect, FPR,
suppression totals) FROM EVENTS ALONE — a second, independent
accounting of the same run that must agree with
``metrics/detection.summarize``'s array reductions (the standing
correctness oracle; ``--selfcheck`` runs both on one fresh run and
diffs them, and ``tools/verify_claims.py``'s ``trace_invariants`` claim
pins it in CI).

    python tools/timeline.py TRACE.jsonl                  # timelines + metrics
    python tools/timeline.py /tmp/cluster/node*.log       # deploy logs merge
    python tools/timeline.py TRACE.jsonl --subject 777    # one node's story
    python tools/timeline.py TRACE.jsonl --json           # machine-readable
    python tools/timeline.py TRACE.jsonl --monitor        # + streaming-monitor
                                                          #   verdict & parity
    JAX_PLATFORMS=cpu python tools/timeline.py --selfcheck --n 1024
    JAX_PLATFORMS=cpu python tools/timeline.py --selfcheck --monitor --n 1024

Also ingests ``ROUNDPROF_*.jsonl`` profile artifacts (their round-9+
header row names the schema): prints a per-config summary instead of a
timeline.  Streams carrying traffic-plane rows (``replica_put`` /
``client_op`` — bench/traffic_bench.py and bench/sdfs_ops.py ``--trace``
artifacts) additionally get the event-replayed durability accounting
(``traffic/audit.py``: no acked write lost, repair completion round) and
a client-op latency rollup attached to the analysis.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import statistics

from gossipfs_tpu.obs import schema
from gossipfs_tpu.obs.schema import Event


def load_stream(path: str) -> tuple[dict | None, list[Event]]:
    """One JSONL stream -> (header row or None, schema events).

    Delegates to ``obs.recorder.load_stream`` — ONE reader of the line
    format, shared with the streaming monitor's ``feed_jsonl``, so the
    post-hoc and online derivations can never parse a stream
    differently.
    """
    from gossipfs_tpu.obs.recorder import load_stream as _load

    return _load(path)


def merge(paths: list[str]) -> tuple[list[dict], list[Event]]:
    """Merge per-node streams into one round-ordered event sequence."""
    headers, events = [], []
    for p in paths:
        h, evs = load_stream(p)
        if h is not None:
            headers.append(h)
        events.extend(evs)
    events.sort(key=lambda e: (e.round, e.subject, e.observer))
    return headers, events


def kind_sequence(events: list[Event], subject: int,
                  dedupe: bool = True) -> list[str]:
    """The subject's lifecycle-kind sequence, in round order.

    ``dedupe=True`` keeps each kind's FIRST occurrence only — the form
    the three-engine parity test compares (the socket engines emit
    per-observer suspect/remove rows; the scan emits any-observer
    singletons).  Ties within one round break by canonical lifecycle
    order, so engines that emit a round's events in different internal
    order still compare equal."""
    seq = [e.kind for e in sorted(
        (e for e in events
         if e.subject == subject and e.kind in schema.LIFECYCLE_KINDS),
        key=lambda e: (e.round, schema.LIFECYCLE_KINDS.index(e.kind)))]
    if not dedupe:
        return seq
    out: list[str] = []
    for k in seq:
        if k not in out:
            out.append(k)
    return out


def analyze(headers: list[dict], events: list[Event]) -> dict:
    """Event-derived run metrics + per-subject timelines.

    Totals and the FPR come from the ``round_tick`` counter rows (the
    per-round accounting); per-crash latencies come from the lifecycle
    rows (crash/suspect/confirm/remove) — mirroring exactly what
    ``summarize`` computes from the arrays, but from the stream alone.
    """
    n = next((h.get("n") for h in headers if h.get("n")), None)
    n_eff = next((h.get("n_effective") for h in headers
                  if h.get("n_effective")), None) or n

    # header-declared fault schedule (bench traces) + ground-truth rows
    crash_rounds: dict[int, int] = {}
    for h in headers:
        for k, v in (h.get("crash_rounds") or {}).items():
            crash_rounds[int(k)] = int(v)
    for e in events:
        if e.kind == "crash" and e.subject >= 0:
            crash_rounds.setdefault(e.subject, e.round)

    firsts: dict[str, dict[int, int]] = {}
    confirm_fp: dict[int, bool] = {}
    for e in events:
        if e.subject < 0 or e.kind not in ("suspect", "confirm", "remove"):
            continue
        slot = firsts.setdefault(e.kind, {})
        if e.subject not in slot:
            slot[e.subject] = e.round
            if e.kind == "confirm" and "false_positive" in e.detail:
                confirm_fp[e.subject] = bool(e.detail["false_positive"])

    ttd_first, ttd_conv, ttd_sus, sus2conf = {}, {}, {}, {}
    for node, r0 in crash_rounds.items():
        c = firsts.get("confirm", {}).get(node)
        ttd_first[node] = (c - r0) if c is not None else -1
        rm = firsts.get("remove", {}).get(node)
        ttd_conv[node] = (rm - r0) if rm is not None else -1
        s = firsts.get("suspect", {}).get(node)
        if s is not None:
            ttd_sus[node] = s - r0
            if c is not None:
                sus2conf[node] = c - s

    ticks = sorted((e for e in events if e.kind == "round_tick"),
                   key=lambda e: e.round)
    tp = sum(e.detail.get("true_detections", 0) for e in ticks)
    fp = sum(e.detail.get("false_positives", 0) for e in ticks)
    alive_sum = sum(e.detail.get("n_alive", 0) for e in ticks)
    suspicion = any("suspects_entered" in e.detail for e in ticks)
    # the same opportunity model summarize uses: alive observers x (n-1)
    # subjects per round
    opportunities = float(alive_sum) * max((n_eff or 1) - 1, 1)
    fpr = (fp / opportunities) if opportunities else 0.0

    ttd_vals = [v for v in ttd_first.values() if v >= 0]
    doc = {
        "schema": schema.SCHEMA,
        "n": n,
        "rounds": len(ticks),
        "events": len(events),
        # invariant_violation rows a live monitor stamped into the
        # stream (obs/monitor.py) — surfaced, not re-derived; run the
        # stream through a fresh StreamMonitor (--monitor) to re-check
        "invariant_violations": sum(
            1 for e in events if e.kind == "invariant_violation"),
        "tracked_crashes": len(crash_rounds),
        "detected": len(ttd_vals),
        "ttd_first": ttd_first,
        "ttd_converged": ttd_conv,
        "ttd_first_median": statistics.median(ttd_vals) if ttd_vals else None,
        "true_detections": tp,
        "false_positives": fp,
        "false_positive_rate": fpr,
        "suspicion": suspicion,
    }
    if suspicion:
        doc.update(
            suspects_entered=sum(e.detail.get("suspects_entered", 0)
                                 for e in ticks),
            refutations=sum(e.detail.get("refutations", 0) for e in ticks),
            fp_suppressed=sum(e.detail.get("fp_suppressed", 0)
                              for e in ticks),
            ttd_suspect=ttd_sus,
            suspect_to_confirm=sus2conf,
            # the lifecycle invariant: with suspicion on, NO subject
            # confirms FAILED without a preceding SUSPECT
            suspect_before_confirm=all(
                subj in firsts.get("suspect", {})
                and firsts["suspect"][subj] <= r
                for subj, r in firsts.get("confirm", {}).items()
            ),
        )
    if confirm_fp:
        doc["confirm_false_positives"] = sum(confirm_fp.values())

    # traffic-plane streams (traffic/harness.py --trace artifacts) carry
    # replica_put/repair/delete rows: re-derive the durability facts from
    # the events alone (traffic/audit.py — the same function the harness
    # diffs itself against) plus the client_op latency rollup
    if any(e.kind in ("replica_put", "stripe_put", "client_op")
           for e in events):
        from gossipfs_tpu.traffic.audit import durability_from_events
        from gossipfs_tpu.traffic.workload import quantiles

        doc["durability"] = durability_from_events(events)
        ops = [e for e in events if e.kind == "client_op"]
        if ops:
            doc["client_ops"] = {
                "issued": len(ops),
                "acked": sum(bool(e.detail.get("ok")) for e in ops),
                **quantiles([e.detail.get("ms", 0.0) for e in ops]),
            }
    return doc


def render_timeline(events: list[Event], subject: int) -> list[str]:
    rows = sorted((e for e in events if e.subject == subject),
                  key=lambda e: e.round)
    out = []
    for e in rows:
        who = "*" if e.observer < 0 else str(e.observer)
        extra = f" {e.detail}" if e.detail else ""
        out.append(f"  r{e.round:>6} {e.kind:<16} obs={who}{extra}")
    return out


# ---------------------------------------------------------------------------
# roundprof artifact ingestion (ROUNDPROF_*.jsonl)
# ---------------------------------------------------------------------------


def summarize_roundprof(path: str) -> dict:
    rows = []
    header = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if schema.is_header(rec):
                header = rec
            elif "ms_per_round" in rec:
                rows.append(rec)
    best = min(rows, key=lambda r: r["ms_per_round"]) if rows else None
    return {"schema": (header or {}).get("schema"), "rows": len(rows),
            "header": header, "fastest": best}


# ---------------------------------------------------------------------------
# selfcheck: events-vs-summarize cross-check on one fresh run
# ---------------------------------------------------------------------------


def selfcheck(n: int = 1024, rounds: int = 60, seed: int = 0,
              trace_path: str | None = None, monitor: bool = False) -> dict:
    """Record a churn run, then prove the two accountings agree.

    Runs the N-node gossip-only churn scenario WITH the SWIM suspicion
    lifecycle (8 tracked crashes + 1% churn, the curves.py shape),
    decodes the scan into a trace via the flight recorder, re-reads it
    through this analyzer, and asserts:

      * event-derived per-crash TTD (and its median) == ``summarize``'s,
        exactly;
      * event-derived FPR == ``summarize``'s, exactly (same integers,
        same opportunity model — any drift is a real accounting bug);
      * the lifecycle invariant: no confirm without a preceding suspect.

    ``monitor=True`` additionally tails the SAME trace file through the
    streaming invariant monitor (obs/monitor.py) and requires its
    incremental estimators to equal this analyzer's post-hoc derivation
    field for field (``estimator_parity`` — the ``monitor_parity``
    claim), with zero invariant violations on the healthy run.

    Also times the decode: the recorder runs after the scan returns, on
    arrays ``summarize`` reads anyway, so the overhead is host-side and
    reported here for the BASELINE table.
    """
    import tempfile
    import time

    import jax

    from gossipfs_tpu.bench.run import tracked_crash_events
    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state
    from gossipfs_tpu.metrics.detection import summarize
    from gossipfs_tpu.obs.recorder import write_trace
    from gossipfs_tpu.suspicion import SuspicionParams, with_suspicion

    # the FAST knob (t_fail=3 + t_suspect=2, the SUSPECT_r08 headline):
    # under 1% churn this regime actually exercises the lifecycle —
    # thousands of refutations, nonzero fp_suppressed — so the exactness
    # checks below have teeth instead of comparing zeros
    cfg = with_suspicion(
        SimConfig(n=n, topology="random", fanout=SimConfig.log_fanout(n),
                  remove_broadcast=False, fresh_cooldown=True, t_fail=3,
                  t_cooldown=12, merge_kernel="xla"),
        SuspicionParams(t_suspect=2),
    )
    events, crash_rounds, churn_ok = tracked_crash_events(cfg, rounds, 8, 10)
    final, carry, per_round = run_rounds(
        init_state(cfg), cfg, rounds, jax.random.PRNGKey(seed),
        events=events, crash_rate=0.01, churn_ok=churn_ok,
        crash_only_events=True,
    )
    jax.block_until_ready(carry)
    report = summarize(carry, per_round, crash_rounds)

    own_file = trace_path is None
    if own_file:
        fd, trace_path = tempfile.mkstemp(suffix=".jsonl", prefix="obs_")
        os.close(fd)
    t0 = time.perf_counter()
    n_events = write_trace(
        trace_path, per_round, carry, n=n, source="timeline-selfcheck",
        crash_rounds=crash_rounds, alive=final.alive, suspicion=True,
    )
    decode_ms = (time.perf_counter() - t0) * 1e3
    headers, evs = merge([trace_path])
    doc = analyze(headers, evs)
    parity = None
    if monitor:
        # the streaming path end-to-end: tail the written file itself
        # through a fresh monitor, then diff against the post-hoc doc
        from gossipfs_tpu.obs.monitor import StreamMonitor, estimator_parity

        t1 = time.perf_counter()
        mon = StreamMonitor()
        mon.feed_jsonl(trace_path)
        mon.finish()
        monitor_ms = (time.perf_counter() - t1) * 1e3
        parity = estimator_parity(doc, mon.summary())
    if own_file:
        os.unlink(trace_path)

    ttd_events = {k: doc["ttd_first"][k] for k in crash_rounds}
    ttd_sum = dict(report.ttd_first)
    med_sum = [v for v in ttd_sum.values() if v >= 0]
    med_sum = statistics.median(med_sum) if med_sum else None
    out = {
        "n": n,
        "rounds": rounds,
        "events": n_events,
        "decode_ms": round(decode_ms, 2),
        "ttd_match": ttd_events == ttd_sum,
        "ttd_median_events": doc["ttd_first_median"],
        "ttd_median_summarize": med_sum,
        "fpr_events": doc["false_positive_rate"],
        "fpr_summarize": report.false_positive_rate,
        "fpr_match": doc["false_positive_rate"]
        == report.false_positive_rate,
        "detections_match": doc["true_detections"]
        == report.true_detections
        and doc["false_positives"] == report.false_positives,
        "suppression_match": doc["fp_suppressed"] == report.fp_suppressed
        and doc["refutations"] == report.refutations,
        "fp_suppressed": report.fp_suppressed,
        "suspect_before_confirm": bool(doc.get("suspect_before_confirm")),
    }
    if parity is not None:
        out["monitor_parity"] = parity["ok"]
        out["monitor_mismatches"] = parity["mismatches"]
        out["monitor_ms"] = round(monitor_ms, 2)
        out["monitor_violations"] = len(mon.violations)
    out["ok"] = (out["ttd_match"]
                 and out["ttd_median_events"] == out["ttd_median_summarize"]
                 and out["fpr_match"] and out["detections_match"]
                 and out["suppression_match"]
                 # non-triviality: the fast knob must have exercised the
                 # lifecycle, or the exact-match checks compared nothing
                 and out["fp_suppressed"] > 0
                 and out["suspect_before_confirm"]
                 # monitor parity (when requested): streaming estimators
                 # exactly equal this post-hoc derivation, zero
                 # violations on the healthy run
                 and (parity is None or (parity["ok"]
                                         and not mon.violations)))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="*", help="event-stream JSONL files "
                   "(bench --trace artifacts, deploy node logs)")
    p.add_argument("--subject", type=int, default=None,
                   help="render one subject's full timeline")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output only")
    p.add_argument("--selfcheck", action="store_true",
                   help="record a fresh CPU churn run and diff the "
                        "event-derived metrics against summarize's")
    p.add_argument("--monitor", action="store_true",
                   help="additionally run the stream(s) through the "
                        "streaming invariant monitor (obs/monitor.py) "
                        "and report its verdict + the monitor_parity "
                        "diff against this analyzer's post-hoc doc")
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--rounds", type=int, default=60)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.selfcheck:
        out = selfcheck(n=args.n, rounds=args.rounds, seed=args.seed,
                        monitor=args.monitor)
        print(json.dumps(out))
        return 0 if out["ok"] else 1

    if not args.paths:
        p.error("give at least one stream path (or --selfcheck)")

    # roundprof artifacts get their own summary path
    first_head, _ = load_stream(args.paths[0])
    if first_head and first_head.get("schema") == schema.ROUNDPROF_SCHEMA:
        for path in args.paths:
            print(json.dumps({"path": path, **summarize_roundprof(path)}))
        return 0

    headers, events = merge(args.paths)
    doc = analyze(headers, events)
    if args.monitor:
        from gossipfs_tpu.obs.monitor import StreamMonitor, estimator_parity

        mon = StreamMonitor()
        for h in headers:
            mon.observe_header(h)
        mon.feed(events)
        mon.finish()
        doc["monitor"] = mon.verdict()
        doc["monitor_parity"] = estimator_parity(doc, mon.summary())
    if args.json:
        print(json.dumps(doc))
        return 0
    print(f"{len(events)} events from {len(args.paths)} stream(s); "
          f"n={doc['n']} rounds={doc['rounds']}")
    subjects = ([args.subject] if args.subject is not None
                else sorted(doc["ttd_first"]))
    for s in subjects:
        print(f"node {s}: {' -> '.join(kind_sequence(events, s)) or '(no events)'}")
        for line in render_timeline(events, s):
            print(line)
    drop = ("ttd_first", "ttd_converged", "ttd_suspect",
            "suspect_to_confirm")
    print(json.dumps({k: v for k, v in doc.items() if k not in drop}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
