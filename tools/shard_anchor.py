"""Measure ONE chip's share of the sharded capacity-class rr round, for real.

The v5e-8 config-4 projection (BASELINE.md) rests on the sharded
resident-round program: each chip runs the SAME rr kernel over
[N global rows x N/shards local columns], and the only cross-chip traffic
is an [N]-vector psum (< 2 MB/round).  This tool runs exactly that
per-chip program — full-N-row stripes, a shard's column count, the
shard's global column offset — on the single real chip and times it,
replacing the compute-scaling extrapolation with a measured per-chip
anchor.  Since round 9 the ring-rotated view build + LANE-compacted
flags bound the row budget (only the int8 W gather buffer scales with
rows), which is what admits the >= 512k-row shapes at c_blk=512 and the
wider stripes at every anchor.

    JAX_PLATFORMS=axon python tools/shard_anchor.py \
        --n 131072 --shards 8 --block-c 512

    # the whole capacity ladder in ONE invocation (one JSON object out;
    # rows are measured on a TPU, budget-verified otherwise):
    JAX_PLATFORMS=axon python tools/shard_anchor.py --ladder
    JAX_PLATFORMS=cpu  python tools/shard_anchor.py --ladder --budget-only

Round-5 artifact: ANCHORS_r05.json; round-9: ANCHORS_r09.json.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import functools
import json
import time

# The capacity ladder --ladder sweeps in one invocation (previously
# hand-run per-N): (n, shards, block_c, block_r, fanout).  The top rows
# exist only since the round-9 rotated layouts; the widened-stripe
# variants of existing anchors come first so a contended TPU window still
# re-anchors the known shapes before attempting the frontier.
LADDER = [
    (65_536, 8, 1024, 512, 16),
    (98_304, 8, 2048, 512, 24),
    (131_072, 8, 1024, 512, 24),
    (196_608, 16, 1024, 512, 24),
    (262_144, 16, 2048, 512, 24),   # wider stripe the rotated build admits
    (327_680, 16, 1024, 512, 24),   # ditto (c512 was the r05 edge)
    (393_216, 16, 512, 512, 24),    # past the old ~367k row ceiling
    (524_288, 16, 512, 512, 24),    # the round-9 row-budget target
    (786_432, 16, 512, 512, 24),    # headroom: budget admits ~1.5M rows
]


def measure(n: int, shards: int, block_c: int, block_r: int, fanout: int,
            arc_align: int, rounds: int, reps: int, shard: int = 0) -> dict:
    """Time one shard's rr program on the local chip; returns the row."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    lane = mp.LANE
    nloc = n // shards
    nc, cs = nloc // block_c, block_c // lane
    if not mp.rr_supported(n, fanout, block_c, nloc, arc_align=arc_align,
                           block_r=block_r):
        raise SystemExit(f"shape not rr-admissible: n={n}, nloc={nloc}, "
                         f"c_blk={block_c}")
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)

    # build both lanes stripe by stripe under jit: eager full-array RNG
    # materializes a 4 B/element bits buffer (17 GB at the 16-way
    # N=262,144 shape) and int32 intermediates of the same size
    @jax.jit
    def mk_hb(k):
        return jax.random.randint(k, (n, cs, lane), -128, 127, jnp.int8)

    @jax.jit
    def mk_asl(k):
        k1, k2 = jax.random.split(k)
        age = jax.random.randint(k1, (n, cs, lane), 1, 40, jnp.int32)
        st = jax.random.randint(k2, (n, cs, lane), 0, 3, jnp.int32)
        return mp.pack_age_status(age, st)

    # assemble with donated in-place writes: a stack() keeps pieces AND
    # the stacked copy live, which together with the other lane exceeds
    # HBM at the biggest anchor shapes
    @functools.partial(jax.jit, donate_argnums=0)
    def put(buf, piece, j):
        return lax.dynamic_update_index_in_dim(buf, piece, j, 0)

    hb = jnp.zeros((nc, n, cs, lane), jnp.int8)
    for j in range(nc):
        hb = put(hb, mk_hb(jax.random.fold_in(ks[0], j)), j)
    asl = jnp.zeros((nc, n, cs, lane), jnp.int8)
    for j in range(nc):
        asl = put(asl, mk_asl(jax.random.fold_in(ks[1], j)), j)
    # LANE-compacted flags (1 B/row — the round-9 layout the kernel runs)
    flags = jnp.broadcast_to(jnp.int8(1 + 4), (n // lane, lane)
                             ).astype(jnp.int8)
    sa = jnp.zeros((nc, cs, lane), jnp.int32)
    sb = jnp.zeros((nc, cs, lane), jnp.int32)
    g = jnp.full((nc, cs, lane), -120, jnp.int32)
    bases = (jax.random.randint(ks[3], (n,), 0, n // arc_align,
                                jnp.int32) * arc_align).reshape(n, 1)

    kern = functools.partial(
        mp.resident_round_blocked,
        fanout=fanout, member=int(MEMBER), unknown=int(UNKNOWN),
        failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
        t_fail=5, t_cooldown=12, block_r=block_r,
        arc_align=arc_align, col_offset=shard * nloc,
    )

    # donate the lanes (matching the real sharded runner): without
    # donation XLA holds input + output lane copies, which alone exceed
    # HBM at the 16-way N=262,144 shape (2 x 8.6 GB)
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(hb, asl):
        def step(carry, _):
            hb, asl = carry
            out = kern(bases, hb, asl, flags, sa, sb, g)
            return (out[0], out[1]), out[3].sum()
        (hb, asl), s = lax.scan(step, (hb, asl), None, length=rounds)
        return hb, asl, s

    hb, asl, s = run(hb, asl)
    jax.block_until_ready(asl)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        hb, asl, s = run(hb, asl)
        jax.block_until_ready(asl)
        best = min(best, time.perf_counter() - t0)
        time.sleep(2.0)
    ms = best / rounds * 1e3
    return {
        "n_global": n, "shards": shards, "local_cols": nloc,
        "entries_per_chip": n * nloc, "merge_block_c": block_c,
        "fanout": fanout, "arc_align": arc_align,
        "ms_per_round_per_chip": round(ms, 2),
        "implied_rounds_per_sec_v5e8": round(1e3 / ms, 2),
        "note": "per-chip share of the sharded rr round, measured on one "
                "real chip; the sharded program's only cross-chip traffic "
                "is an [N]-vector psum (< 2 MB/round over ICI)",
    }


def run_ladder(args) -> dict:
    """The full capacity ladder in one invocation: every shape's
    row-budget verdict (ring-rotated + compacted-flags layouts), plus
    measured per-chip timings when a TPU is reachable."""
    import jax

    from gossipfs_tpu.parallel.mesh import rr_shard_admissible

    on_tpu = jax.default_backend() == "tpu"
    rows = []
    for n, shards, block_c, block_r, fanout in LADDER:
        row = rr_shard_admissible(n, shards, block_c, fanout,
                                  arc_align=args.arc_align, block_r=block_r)
        row["merge_block_r"] = block_r
        if row["admissible"] and on_tpu and not args.budget_only:
            try:
                row.update(measure(n, shards, block_c, block_r, fanout,
                                   args.arc_align, args.rounds, args.reps))
                row["measured"] = True
            except Exception as e:  # noqa: BLE001 — keep laddering
                row["measured"] = False
                row["error"] = str(e)[:200]
        else:
            row["measured"] = False
        rows.append(row)
    return {
        "metric": "sharded rr capacity ladder (ring-rotated view build + "
                  "LANE-compacted flags row budget; measured per-chip "
                  "where a TPU is reachable, budget-verified otherwise)",
        "backend": jax.default_backend(),
        "ladder": rows,
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=131_072)
    p.add_argument("--shards", type=int, default=8)
    p.add_argument("--block-c", type=int, default=512)
    p.add_argument("--block-r", type=int, default=512)
    p.add_argument("--arc-align", type=int, default=8)
    p.add_argument("--fanout", type=int, default=24)
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--shard", type=int, default=0,
                   help="which shard's column offset to run")
    p.add_argument("--ladder", action="store_true",
                   help="emit the full capacity-ladder JSON in one "
                        "invocation instead of one hand-run row per N")
    p.add_argument("--budget-only", action="store_true",
                   help="with --ladder: admissibility + budget bytes only "
                        "(no device timing; implied off-TPU)")
    args = p.parse_args(argv)

    if args.ladder:
        print(json.dumps(run_ladder(args)))
        return

    print(json.dumps(measure(args.n, args.shards, args.block_c,
                             args.block_r, args.fanout, args.arc_align,
                             args.rounds, args.reps, shard=args.shard)))


if __name__ == "__main__":
    sys.exit(main())
