"""Measure ONE chip's share of the sharded N=131,072 rr round, for real.

The v5e-8 config-4 projection (BASELINE.md) rests on the sharded
resident-round program: each chip runs the SAME rr kernel over
[N global rows x N/8 local columns], and the only cross-chip traffic is
an [N]-vector psum (< 2 MB/round).  This tool runs exactly that
per-chip program — full-N-row stripes, a shard's column count, the
shard's global column offset — on the single real chip and times it,
replacing the compute-scaling extrapolation with a measured per-chip
anchor.  The 512-wide stripe (round 5) is what admits N=131,072 rows:
N x c_blk = 67 MB fits the 72 MB VMEM stripe budget.

    JAX_PLATFORMS=axon python tools/shard_anchor.py \
        --n 131072 --shards 8 --block-c 512

Round-5 artifact: see BASELINE.md's projection section.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import functools
import json
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=131_072)
    p.add_argument("--shards", type=int, default=8)
    p.add_argument("--block-c", type=int, default=512)
    p.add_argument("--block-r", type=int, default=512)
    p.add_argument("--arc-align", type=int, default=8)
    p.add_argument("--fanout", type=int, default=24)
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--shard", type=int, default=0,
                   help="which shard's column offset to run")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax

    from gossipfs_tpu.config import AGE_CLAMP
    from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN
    from gossipfs_tpu.ops import merge_pallas as mp

    n, lane = args.n, mp.LANE
    nloc = n // args.shards
    nc, cs = nloc // args.block_c, args.block_c // lane
    if not mp.rr_supported(n, args.fanout, args.block_c, nloc,
                       arc_align=args.arc_align):
        raise SystemExit(f"shape not rr-admissible: n={n}, nloc={nloc}, "
                         f"c_blk={args.block_c}")
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)

    # build both lanes stripe by stripe under jit: eager full-array RNG
    # materializes a 4 B/element bits buffer (17 GB at the 16-way
    # N=262,144 shape) and int32 intermediates of the same size
    @jax.jit
    def mk_hb(k):
        return jax.random.randint(k, (n, cs, lane), -128, 127, jnp.int8)

    @jax.jit
    def mk_asl(k):
        k1, k2 = jax.random.split(k)
        age = jax.random.randint(k1, (n, cs, lane), 1, 40, jnp.int32)
        st = jax.random.randint(k2, (n, cs, lane), 0, 3, jnp.int32)
        return mp.pack_age_status(age, st)

    # assemble with donated in-place writes: a stack() keeps pieces AND
    # the stacked copy live, which together with the other lane exceeds
    # HBM at the biggest anchor shapes
    @functools.partial(jax.jit, donate_argnums=0)
    def put(buf, piece, j):
        return lax.dynamic_update_index_in_dim(buf, piece, j, 0)

    hb = jnp.zeros((nc, n, cs, lane), jnp.int8)
    for j in range(nc):
        hb = put(hb, mk_hb(jax.random.fold_in(ks[0], j)), j)
    asl = jnp.zeros((nc, n, cs, lane), jnp.int8)
    for j in range(nc):
        asl = put(asl, mk_asl(jax.random.fold_in(ks[1], j)), j)
    flags = jnp.broadcast_to(jnp.int8(1 + 4), (n, lane)).astype(jnp.int8)
    sa = jnp.zeros((nc, cs, lane), jnp.int32)
    sb = jnp.zeros((nc, cs, lane), jnp.int32)
    g = jnp.full((nc, cs, lane), -120, jnp.int32)
    bases = (jax.random.randint(ks[3], (n,), 0, n // args.arc_align,
                                jnp.int32) * args.arc_align).reshape(n, 1)

    kern = functools.partial(
        mp.resident_round_blocked,
        fanout=args.fanout, member=int(MEMBER), unknown=int(UNKNOWN),
        failed=int(FAILED), age_clamp=AGE_CLAMP, window=126,
        t_fail=5, t_cooldown=12, block_r=args.block_r,
        arc_align=args.arc_align, col_offset=args.shard * nloc,
    )

    # donate the lanes (matching the real sharded runner): without
    # donation XLA holds input + output lane copies, which alone exceed
    # HBM at the 16-way N=262,144 shape (2 x 8.6 GB)
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(hb, asl):
        def step(carry, _):
            hb, asl = carry
            out = kern(bases, hb, asl, flags, sa, sb, g)
            return (out[0], out[1]), out[3].sum()
        (hb, asl), s = lax.scan(step, (hb, asl), None, length=args.rounds)
        return hb, asl, s

    hb, asl, s = run(hb, asl)
    jax.block_until_ready(asl)
    best = float("inf")
    for _ in range(args.reps):
        t0 = time.perf_counter()
        hb, asl, s = run(hb, asl)
        jax.block_until_ready(asl)
        best = min(best, time.perf_counter() - t0)
        time.sleep(2.0)
    ms = best / args.rounds * 1e3
    print(json.dumps({
        "n_global": n, "shards": args.shards, "local_cols": nloc,
        "entries_per_chip": n * nloc, "merge_block_c": args.block_c,
        "fanout": args.fanout, "arc_align": args.arc_align,
        "ms_per_round_per_chip": round(ms, 2),
        "implied_rounds_per_sec_v5e8": round(1e3 / ms, 2),
        "note": "per-chip share of the sharded rr round, measured on one "
                "real chip; the sharded program's only cross-chip traffic "
                "is an [N]-vector psum (< 2 MB/round over ICI)",
    }))


if __name__ == "__main__":
    sys.exit(main())
