"""Sweep rr-kernel tuning knobs for the headline config (N=16,384).

Times the EXACT bench.py program (run_rounds, tile-aligned random_arc
fanout=16 arc_align=8, resident rr, 1% crash churn) across
merge_block_c x merge_block_r, printing one JSON line per point.  Best-of-k timing per point to shrug off ambient chip
contention between points (the same hygiene bench.py uses).

    JAX_PLATFORMS=axon python tools/sweep_rr.py --rounds 100 --reps 4
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import itertools
import json
import time

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.ops import merge_pallas


def time_point(n, block_c, block_r, rounds, reps, arc_align=8, fanout=16):
    cfg = SimConfig(
        n=n, topology="random_arc", fanout=fanout, arc_align=arc_align,
        remove_broadcast=False, fresh_cooldown=True, t_cooldown=12,
        merge_kernel="pallas_rr", merge_block_r=block_r,
        view_dtype="int8", merge_block_c=block_c,
        rr_resident="on", hb_dtype="int8",
    )
    key = jax.random.PRNGKey(0)
    state = init_state(cfg)
    st, mc, pr = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
    jax.block_until_ready(st)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        st, mc, pr = run_rounds(state, cfg, rounds, key, crash_rate=0.01)
        jax.block_until_ready(st)
        best = min(best, time.perf_counter() - t0)
        time.sleep(1.0)
    return best


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=16_384)
    p.add_argument("--rounds", type=int, default=100)
    p.add_argument("--reps", type=int, default=4)
    p.add_argument("--block-c", nargs="*", type=int,
                   default=[1024, 2048])
    p.add_argument("--block-r", nargs="*", type=int,
                   default=[128, 256, 512])
    p.add_argument("--arc-align", type=int, default=8)
    p.add_argument("--fanout", type=int, default=16)
    args = p.parse_args()

    for bc, br in itertools.product(args.block_c, args.block_r):
        if not merge_pallas.rr_resident_supported(
                args.n, args.fanout, bc, arc_align=args.arc_align):
            print(json.dumps({"block_c": bc, "block_r": br,
                              "skipped": "no resident VMEM fit"}))
            continue
        el = time_point(args.n, bc, br, args.rounds, args.reps,
                        arc_align=args.arc_align, fanout=args.fanout)
        print(json.dumps({
            "block_c": bc, "block_r": br,
            "ms_per_round": round(el / args.rounds * 1e3, 3),
            "rounds_per_sec": round(args.rounds / el, 2),
        }), flush=True)


if __name__ == "__main__":
    main()
