"""Re-verify every round-5 headline claim end-to-end, one command.

Runs the actual surfaces (not cached artifacts) and emits one JSON line
per claim with PASS/FAIL against a tolerance, then a summary line.
Rates are compared against CLAIM * (1 - tol) — the axon chip is
bandwidth-shared, so a contended window can legitimately miss by more;
rerun in a quieter window before reading a rate FAIL as a regression.

    JAX_PLATFORMS=axon python tools/verify_claims.py            # all
    JAX_PLATFORMS=axon python tools/verify_claims.py --only headline soak
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_json(cmd, timeout=1800):
    out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                         timeout=timeout)
    for line in reversed(out.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(f"no JSON from {cmd}: {out.stdout[-500:]}\n"
                       f"{out.stderr[-500:]}")


def _suspicion_ok(d: dict) -> float:
    """suspicion_fpr predicate over the SUSPECT artifact rows.

    Per N: (a) churn — suspicion-on at the fast knob keeps median
    TTD-first <= t_fail + t_suspect (the t_fail=5-class latency) with
    FPR within 10x of the t_fail=5 baseline (floor 1e-6 ~ 60 FP events,
    so a zero-FP baseline window can't fail a handful of events) instead
    of the raw-t3 storm; (b) loss — suspicion-on FPR strictly below
    suspicion-off at the same t_fail, with refutations actually doing
    the suppressing (fp_suppressed > 0).
    """
    by = {(r["n"], r["fault"], r["mode"]): r for r in d["rows"]}
    for n in sorted({r["n"] for r in d["rows"]}):
        base = by[(n, "churn", "baseline-t5")]
        on = by[(n, "churn", "suspect-t3")]
        raw = by[(n, "churn", "raw-t3")]
        bound = on["t_fail"] + on["t_suspect"]
        if on["ttd_first_median"] is None or on["ttd_first_median"] > bound:
            return 0.0
        if on["false_positive_rate"] > max(
            10 * base["false_positive_rate"], 1e-6
        ):
            return 0.0
        if not on["false_positive_rate"] < raw["false_positive_rate"]:
            return 0.0
        loss_on = by[(n, "loss", "suspect-t3")]
        loss_raw = by[(n, "loss", "raw-t3")]
        if not loss_on["false_positive_rate"] < loss_raw["false_positive_rate"]:
            return 0.0
        if loss_on["fp_suppressed"] <= 0 or on["fp_suppressed"] <= 0:
            return 0.0
    return 1.0


CLAIMS = {
    # name: (cmd, extractor, claimed value, relative tolerance)
    # headline: d["value"] is the MEDIAN attempt since round 6 (bench.py
    # also reports "best"); the 94.0 was calibrated on the old best-of
    # protocol, so the tolerance is widened 0.25 -> 0.3 until a
    # median-convention on-chip number recalibrates it.  bench.py may
    # additionally spend up to 600 s in probe_swar() before sampling —
    # covered by run_json's 1800 s default.
    "headline": (
        [sys.executable, "bench.py"],
        lambda d: d["value"], 94.0, 0.3),
    "frontier_65536": (
        [sys.executable, "-m", "gossipfs_tpu.bench.frontier", "--n", "65536",
         "--rounds", "60", "--block-c", "2048", "--block-r", "512",
         "--topology", "random_arc", "--arc-align", "8"],
        lambda d: d["rounds_per_sec"] if d["detected"] == 8 else 0.0,
        6.71, 0.3),
    "ceiling_86016": (
        [sys.executable, "-m", "gossipfs_tpu.bench.frontier", "--n", "86016",
         "--rounds", "60", "--block-c", "1024", "--block-r", "512",
         "--topology", "random_arc", "--arc-align", "8"],
        lambda d: d["rounds_per_sec"] if d["detected"] == 8 else 0.0,
        3.55, 0.3),
    "soak": (
        [sys.executable, "tools/parity_soak.py", "--n", "16384",
         "--rounds", "100"],
        lambda d: 1.0 if d["all_equal"] else 0.0, 1.0, 0.0),
    "anchor_98304": (
        [sys.executable, "tools/shard_anchor.py", "--n", "98304",
         "--shards", "8", "--block-c", "2048", "--fanout", "24",
         "--rounds", "40", "--reps", "3"],
        lambda d: d["implied_rounds_per_sec_v5e8"], 23.5, 0.3),
    # scenario engine (PARTITION_r07.json is the committed artifact of
    # the same command): during a netsplit ZERO cross-partition heartbeat
    # propagation (cross_hb_advances == 0) and, after heal, cross views
    # reconverge within t_fail + gossip diameter rounds
    # (reconverge_rounds <= reconverge_bound).  CPU-feasible — pinned to
    # the cpu backend so a contended axon window can't skew it.
    "partition_reconv": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "-m",
         "gossipfs_tpu.bench.curves", "--partition", "--ns", "1024"],
        lambda d: 1.0 if all(
            r["cross_hb_advances"] == 0
            and 0 <= r["reconverge_rounds"] <= r["reconverge_bound"]
            for r in d["rows"]
        ) else 0.0,
        1.0, 0.0),
    # suspicion subsystem (SUSPECT_r08.json is the committed artifact of
    # the same command): SWIM suspect/refute at the fast knob (t_fail=3 +
    # t_suspect=2) keeps the t_fail=5-class detection latency WITHOUT the
    # raw-t3 FP storm (within 10x of the t_fail=5 baseline FPR), and
    # under a Bernoulli-loss scenario suspicion-on FPR is strictly below
    # suspicion-off at equal-or-better median TTD.  CPU-pinned.
    "suspicion_fpr": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "-m",
         "gossipfs_tpu.bench.curves", "--suspicion", "--ns", "1024"],
        _suspicion_ok, 1.0, 0.0),
    # round-9 row-budget claim (CPU-pinned; the scratch-budget lint test
    # reconciles the same math against the kernel's real allocations):
    # the ring-rotated view build + LANE-compacted flags admit the whole
    # capacity ladder — including >= 512k rows at c_blk=512, past the
    # round-5 ~367k ceiling — within the 112 MB aligned row budget
    "rr_row_budget": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable,
         "tools/shard_anchor.py", "--ladder", "--budget-only"],
        lambda d: 1.0 if (
            all(r["admissible"] for r in d["ladder"])
            and any(r["n_global"] >= 524_288
                    and r["merge_block_c"] == 512 for r in d["ladder"])
            and all(r["row_budget_bytes"] <= r["budget_limit_bytes"]
                    for r in d["ladder"])
        ) else 0.0,
        1.0, 0.0),
    # round-11 fast-path unification: a partition + slow-sender scenario
    # WITH the SWIM lifecycle armed runs on the rr/SWAR kernel config
    # (no construction gate, no substitution) bit-equal to the XLA
    # oracle — every state lane, the carry (first_suspect included) and
    # the per-round suspicion counters.  CPU-pinned (interpret kernel);
    # the on-chip form is the same command without --interpret, gated
    # behind bench.py probe_rr_suspicion.
    "fastpath_parity": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable,
         "tools/parity_soak.py", "--interpret", "--n", "2048",
         "--block-c", "1024", "--block-r", "128", "--rounds", "16",
         "--crash-rate", "0.02", "--elementwise", "swar",
         "--suspicion", "--scenario"],
        lambda d: 1.0 if (d["all_equal"] and d["total_suspects"] > 0
                          and d["total_refutations"] > 0) else 0.0,
        1.0, 0.0),
    # observability (obs/): the flight-recorder <-> summarize oracle.
    # timeline.py --selfcheck records a fresh N=1024 churn run at the
    # fast suspicion knob, decodes the scan into a trace, re-derives
    # TTD/FPR from events alone, and requires (a) event-derived per-crash
    # TTD and FPR == summarize's EXACTLY (nonzero — the knob guarantees
    # live suppression counts), and (b) no subject confirms FAILED
    # without a preceding SUSPECT.  CPU-pinned.
    "trace_invariants": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "tools/timeline.py",
         "--selfcheck", "--n", "1024"],
        lambda d: 1.0 if d["ok"] else 0.0, 1.0, 0.0),
    # online health plane (obs/monitor.py): the STREAMING monitor is a
    # second, incremental derivation of the same estimators — the claim
    # runs the N=1024 churn selfcheck stream through it and requires
    # estimator-for-estimator equality with timeline.py's post-hoc
    # analysis (monitor_parity == exact match on every PARITY_FIELDS
    # row) plus zero invariant violations on the healthy run.  CPU.
    "monitor_parity": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "tools/timeline.py",
         "--selfcheck", "--monitor", "--n", "1024"],
        lambda d: 1.0 if (d["ok"] and d["monitor_parity"]
                          and d["monitor_violations"] == 0) else 0.0,
        1.0, 0.0),
    # round-14 correlated-failure absorption (LOCALHEALTH_r14.json is
    # the committed knob surface): re-runs the surface's CHOSEN point —
    # baselines included — on the tensor engine (CPU) and requires the
    # absorption predicate to hold from FRESH runs: the outage run's
    # FPR within the t_fail=5-class floor (max(10x the deterministic
    # quiet baseline, 1e-6) — the same floor suspicion_fpr uses), every
    # monitor invariant passing, and tracked-crash median TTD at most
    # +1 round over the lh-off quiet baseline on both the outage and
    # the quiet run.  The udp-engine verdict evidence for the same
    # family point is UDPCAMPAIGN_r14.json (tools/campaign.py --case
    # ... --engine udp; slow-lane test).
    "outage_absorption": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "tools/campaign.py",
         "--absorption", "LOCALHEALTH_r14.json"],
        lambda d: 1.0 if d["absorbed"] else 0.0, 1.0, 0.0),
    # round-16 native cohort campaigns (NATIVECAMPAIGN_r16.json is the
    # committed matrix): the storm/absorption pre/post-fix pair re-runs
    # COHORT-EXACT at n=256 over the native C++ epoll engine — the
    # committed 2-node outage storms (fpr_storm) and the LOCALHEALTH_r14
    # chosen-knob twin absorbs (verdict pass, all four invariants), each
    # agreeing with the tensor replay per invariant.  Needs the native
    # toolchain (g++/make); wall-clock ~2 min on a 1-core host.
    "native_cohort": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "tools/campaign.py",
         "--engine", "native",
         "--pair", "regressions/outage_storm_n256.json",
         "regressions/outage_absorbed_n256.json"],
        lambda d: 1.0 if d["reproduced"] else 0.0, 1.0, 0.0),
    # round-20 delta-piggyback dissemination (COHORT_r20.json is the
    # committed full artifact): the n=256 delta-vs-full A/B on the
    # native engine — >= 2x bytes/round reduction at identical fanout,
    # delta p50 tick inside native_period(256), zero false positives in
    # both arms — plus the committed delta udp case replayed with its
    # verdict agreeing with the tensor replay and delta frames actually
    # on the wire.  The >= 4x n=1024 headline is the slow lane's
    # (tools/campaign.py --matrix --ab).  ~3 min on a 1-core host.
    "delta_cohort": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "tools/campaign.py",
         "--ab", "--ab-ns", "256", "--ab-loop-grid", "1",
         "--ab-rounds", "16", "--ab-target", "2.0",
         "--ab-udp-case", "regressions/outage_mild_delta_udp_n24.json"],
        lambda d: 1.0 if d["ok"] else 0.0, 1.0, 0.0),
    # traffic plane (TRAFFIC_r12.json is the committed artifact of the
    # full-bench form of this command): writes race a timed partition
    # that confines quorum reachability to the master's side; the claim
    # requires (a) minority-starved mid-split puts actually REJECTED
    # (the race's observable — never ack-then-lose), (b) ZERO acked
    # writes lost across the heal under BOTH accountings — the harness's
    # cluster-state ledger AND the event-replayed durability facts
    # (traffic/audit.py, the same replay tools/timeline.py attaches to
    # traffic streams) — and (c) the two accountings agreeing EXACTLY
    # (acked writes, files, repairs, losses).  CPU-pinned.
    # round-17 protocol contract (SPEC_r17.json is the committed
    # red→green evidence): gossipfs-lint — the protocol-spec extractors
    # included — exits 0 on the repo, and every spec rule exits nonzero
    # on its committed seeded-drift fixture (tools/spec_verify.py).
    # Pure static analysis; no accelerator, ~30 s.
    "spec_clean": (
        [sys.executable, "tools/spec_verify.py"],
        lambda d: 1.0 if d["ok"] else 0.0, 1.0, 0.0),
    # round-19 dynamic conformance (CONFORMANCE_r19.json is the
    # committed full matrix): the protocol contract EXECUTED — the
    # pinned CPU slice of the adversarial-schedule corpus (oracle
    # selfcheck over every family, the tensor column in full, the two
    # shortest wire-verb families on the asyncio udp engine) must agree
    # with the reference oracle row-for-row, with every protocol_spec
    # wire verb + injection covered by the corpus.  ~40 s; the native
    # column is the slow lane's (tools/conformance.py --matrix).
    "spec_conformance": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable,
         "tools/conformance.py", "--slice"],
        lambda d: 1.0 if (d["ok"] and d["coverage_complete"]) else 0.0,
        1.0, 0.0),
    # round-18 erasure plane (ERASURE_r18.json is the committed artifact
    # of the same command): the whole gray-failure cosim matrix (steady /
    # churn / partition-race / rack-kill storm) in redundancy="stripe"
    # mode at (k=4, m=2) — zero acked-write losses across ALL four
    # scenarios with the cluster-state ledger, the post-hoc event replay
    # AND the streaming monitor's incremental ledger in exact agreement,
    # plus the bandwidth headline: the rack-kill storm's measured repair
    # bytes PER UNIT OF LOST REDUNDANCY <= 1/k of the replica-mode twin
    # at the SAME failure schedule (n=64 / rack_size=8 gives 8 racks, so
    # (4,2) stripes place fully rack-disjoint and a lost fragment
    # re-encodes ceil(S/k) row bytes where a lost replica re-copies all
    # S).  The per-unit form is the honest one: TOTAL traffic scales by
    # (k+m)/(R*k) = 0.375 at (4,2) vs R=4 — the wider stripe exposes
    # more units to the same rack kill — and the artifact reports that
    # total_ratio next to the claimed per_unit_ratio.  CPU.
    "erasure_durability": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "-m",
         "gossipfs_tpu.bench.traffic_bench", "--erasure-matrix",
         "--n", "64"],
        lambda d: 1.0 if (
            d["erasure_matrix"]["losses_total"] == 0
            and d["erasure_matrix"]["matches_all"]
            and d["erasure_matrix"]["repair_bandwidth"]["per_unit_ratio"]
            is not None
            and d["erasure_matrix"]["repair_bandwidth"]["per_unit_ratio"]
            <= d["erasure_matrix"]["repair_bandwidth"]["bound_1_over_k"]
        ) else 0.0,
        1.0, 0.0),
    "traffic_durability": (
        ["env", "JAX_PLATFORMS=cpu", sys.executable, "-m",
         "gossipfs_tpu.bench.traffic_bench", "--partition-race",
         "--n", "64"],
        lambda d: 1.0 if (
            d["partition_race"]["durability"]["match"]
            and d["partition_race"]["durability"]["harness"]["lost"] == 0
            and d["partition_race"]["durability"]["events"]["lost"] == 0
            and d["partition_race"]["durability"]["harness"]["files_acked"]
            > 0
            and d["partition_race"]["rejected_during_split"] > 0
            # round 13: the STREAMING monitor rides the harness recorder
            # (obs/monitor.py) — zero no_acked_write_lost violations and
            # its incremental ledger exactly equal to the post-hoc replay
            and d["partition_race"]["durability"]["monitor"]["ok"]
            and d["partition_race"]["durability"]["monitor"]["match_events"]
        ) else 0.0,
        1.0, 0.0),
}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--only", nargs="*", default=None,
                   help=f"subset of {sorted(CLAIMS)}")
    args = p.parse_args(argv)
    names = args.only or list(CLAIMS)
    ok = True
    for name in names:
        cmd, extract, want, tol = CLAIMS[name]
        try:
            got = extract(run_json(cmd))
            passed = got >= want * (1.0 - tol)
        except Exception as e:  # noqa: BLE001 — report, keep verifying
            got, passed = f"ERROR: {e}", False
        ok &= bool(passed)
        print(json.dumps({"claim": name, "claimed": want, "measured": got,
                          "tolerance": tol,
                          "result": "PASS" if passed else "FAIL"}),
              flush=True)
    print(json.dumps({"all_pass": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
