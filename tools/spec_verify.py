"""spec_clean: the gossipfs-spec analyzer verified both ways, one JSON line.

Green half: ``tools/lint.py`` (every registered rule, the protocol-spec
extractors included) must exit 0 on the repo itself.  Red half: each
spec rule must exit NONZERO when its committed seeded-drift fixture is
overlay-mounted at the rule's extraction point — a rule that cannot
fire on its own fixture is a dead check, and a repo that fails clean
has drifted from the contract.  The committed red→green evidence for
the round-17 ENTRY-broadcast fix is SPEC_r17.json.

    python tools/spec_verify.py          # one JSON object line, exit 0 iff ok

Consumed by tools/verify_claims.py as the ``spec_clean`` claim.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossipfs_tpu.analysis import REGISTRY  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "fixtures", "lint")

# The cross-language protocol-contract rules (rules_spec.py): the spec-
# prefixed extractors plus the scan-carry seam rule that rides with them.
SPEC_RULES = sorted(
    name for name in REGISTRY
    if name.startswith("spec-") or name == "scan-carry-arity"
)


def _lint(*args: str) -> int:
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "lint.py"), *args],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    return out.returncode


def main() -> int:
    repo_clean = _lint() == 0
    fixtures = []
    for name in SPEC_RULES:
        r = REGISTRY[name]
        overlay = f"{r.fixture_at}={os.path.join(FIXTURES, r.fixture)}"
        rc = _lint("--rule", name, "--overlay", overlay)
        fixtures.append({"rule": name, "fixture": r.fixture,
                         "mounted_at": r.fixture_at, "exit_code": rc,
                         "fired": rc == 1})
    red = sum(1 for f in fixtures if f["fired"])
    ok = repo_clean and red == len(fixtures) and fixtures
    print(json.dumps({
        "claim": "spec_clean",
        "repo_clean": repo_clean,
        "fixtures_total": len(fixtures),
        "fixtures_red": red,
        "ok": bool(ok),
        "fixtures": fixtures,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
