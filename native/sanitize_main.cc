// Sanitizer campaign driver for the native epoll engine.
//
// Drives the C ABI (the exact surface ctypes uses — see
// gossipfs_tpu/native.py) through the committed campaign case while a
// second thread hammers the control/observation verbs concurrently with
// the engine's epoll loop thread: converge, crash two nodes mid-poll,
// detect, cooldown, rejoin, graceful leave, then a codec sweep over
// malformed wire input.  Built by `make tsan` / `make asan`
// (tests/test_native_sanitizers.py runs both and fails on any report);
// protocol outcomes are asserted here so a sanitizer build that
// silently breaks semantics also fails, not just one that races.
//
// Usage: sanitize_{tsan,asan} [base_port] [period_s]

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* gfs_cluster_create(int n, int base_port, double period_s, int t_fail,
                         int t_cooldown, int min_group, int fresh_cooldown,
                         int introducer);
int gfs_cluster_start(void* h);
void gfs_cluster_destroy(void* h);
void gfs_crash(void* h, int i);
void gfs_leave(void* h, int i);
void gfs_join(void* h, int i);
void gfs_advance(void* h, int rounds);
int gfs_round(void* h);
int gfs_membership(void* h, int observer, int* out, int cap);
int gfs_alive(void* h, int* out, int cap);
int gfs_drain_events(void* h, int* out, int cap);
int gfs_codec_encode(const char* lines, char* out, int cap);
int gfs_codec_decode(const char* wire, char* out, int cap);
}

namespace {

constexpr int kN = 12;
constexpr int kTFail = 5;
constexpr int kTCooldown = 5;

bool Contains(const int* buf, int count, int idx) {
  return std::find(buf, buf + count, idx) != buf + count;
}

int Fail(const char* what) {
  std::fprintf(stderr, "SANITIZE_CAMPAIGN_FAIL: %s\n", what);
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  int base_port = argc > 1 ? std::atoi(argv[1]) : 21500;
  double period = argc > 2 ? std::atof(argv[2]) : 0.05;

  void* h = gfs_cluster_create(kN, base_port, period, kTFail, kTCooldown,
                               /*min_group=*/4, /*fresh_cooldown=*/1,
                               /*introducer=*/0);
  if (gfs_cluster_start(h) != 0) {
    gfs_cluster_destroy(h);
    return Fail("cluster failed to start (ports busy?)");
  }

  // warm convergence: everyone joined through the introducer and every
  // counter is past the hb<=1 detection grace
  gfs_advance(h, 6);
  int buf[4 * kN];
  if (gfs_alive(h, buf, kN) != kN) {
    gfs_cluster_destroy(h);
    return Fail("cohort did not converge to n alive");
  }

  // concurrent observation hammering: the race surface TSan exists for
  // is the control/observation verbs (Python-thread side) against the
  // epoll loop thread holding the protocol state
  std::atomic<bool> stop{false};
  std::thread poller([&] {
    int pbuf[4 * kN];
    while (!stop.load()) {
      gfs_alive(h, pbuf, kN);
      gfs_membership(h, 0, pbuf, kN);
      gfs_round(h);
      gfs_drain_events(h, pbuf, 4 * kN);
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  });

  // the campaign: crash two nodes mid-poll, detect, rejoin one
  gfs_crash(h, 5);
  gfs_crash(h, 9);
  gfs_advance(h, kTFail + 7);  // t_fail periods + dissemination slack
  stop.store(true);
  poller.join();

  int rc = 0;
  int alive = gfs_alive(h, buf, kN);
  if (Contains(buf, alive, 5) || Contains(buf, alive, 9))
    rc = Fail("crashed nodes still alive after t_fail + slack");

  // rejoin 5 after the cooldown window; the poller already drained some
  // events, which is fine — the membership views are the outcome checked
  gfs_advance(h, kTCooldown + 3);
  gfs_join(h, 5);
  gfs_advance(h, 8);
  alive = gfs_alive(h, buf, kN);
  if (!Contains(buf, alive, 5)) rc = Fail("rejoined node 5 not alive");
  int members = gfs_membership(h, 0, buf, kN);
  if (!Contains(buf, members, 5))
    rc = Fail("introducer view missing rejoined node 5");

  // graceful leave disseminates without a detection
  gfs_leave(h, 3);
  gfs_advance(h, 4);
  members = gfs_membership(h, 0, buf, kN);
  if (Contains(buf, members, 3)) rc = Fail("LEAVE did not disseminate");

  gfs_cluster_destroy(h);

  // codec sweep: round-trip plus the malformed chunks DecodeMembers must
  // skip (strtoll/strtod edge input — the UBSan half of the build)
  {
    char wire[4096], back[4096];
    int wn = gfs_codec_encode(
        "10.0.0.1:8000 42 1785344960.123456\n10.0.0.2:8000 7 1.5\n", wire,
        sizeof wire);
    if (wn <= 0 || wn >= static_cast<int>(sizeof wire))
      rc = Fail("codec encode sizing");
    if (gfs_codec_decode(wire, back, sizeof back) <= 0)
      rc = Fail("codec decode of own encoding");
    static const char* kMalformed[] = {
        "", "<#ENTRY#>", "bad-no-fields", "x<#INFO#>NaNish",
        "a<#INFO#>99999999999999999999999999<#INFO#>1e999",
        "ok<#INFO#>5<#INFO#>1.0<#ENTRY#>trunc<#INFO#>",
    };
    for (const char* m : kMalformed) gfs_codec_decode(m, back, sizeof back);
    // snprintf-style truncation path: tiny caps must stay in bounds
    char tiny[4];
    gfs_codec_decode(wire, tiny, sizeof tiny);
    gfs_codec_encode("10.0.0.1:8000 1 2.0\n", tiny, sizeof tiny);
  }

  if (rc == 0) std::printf("SANITIZE_CAMPAIGN_OK\n");
  return rc;
}
