// Sanitizer campaign driver for the native epoll engine.
//
// Drives the C ABI (the exact surface ctypes uses — see
// gossipfs_tpu/native.py) through the committed campaign case while a
// second thread hammers the control/observation verbs concurrently with
// the engine's epoll loop thread: configure the suspicion + campaign
// knobs, seed + warm, arm a fault-gate table, converge, crash two nodes
// mid-poll, detect, cooldown, rejoin, graceful leave, then a codec +
// gate-table sweep over malformed input.  The round-16 observation
// surface (gfs_obs_drain / gfs_vitals) is hammered CONCURRENTLY with
// the epoll loop — the new buffers get the same TSan/ASan certification
// as the rest of the ABI.  Built by `make tsan` / `make asan`
// (tests/test_native_sanitizers.py runs both and fails on any report);
// protocol outcomes are asserted here so a sanitizer build that
// silently breaks semantics also fails, not just one that races.
//
// Usage: sanitize_{tsan,asan} [base_port] [period_s]

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* gfs_cluster_create(int n, int base_port, double period_s, int t_fail,
                         int t_cooldown, int min_group, int fresh_cooldown,
                         int introducer);
int gfs_cluster_start(void* h);
void gfs_cluster_destroy(void* h);
void gfs_crash(void* h, int i);
void gfs_leave(void* h, int i);
void gfs_join(void* h, int i);
void gfs_advance(void* h, int rounds);
int gfs_round(void* h);
int gfs_membership(void* h, int observer, int* out, int cap);
int gfs_alive(void* h, int* out, int cap);
int gfs_drain_events(void* h, int* out, int cap);
int gfs_codec_encode(const char* lines, char* out, int cap);
int gfs_codec_decode(const char* wire, char* out, int cap);
// round-16 observability + campaign surface
int gfs_configure(void* h, const char* kv);
int gfs_obs_enable(void* h);
int gfs_obs_drain(void* h, char* out, int cap);
int gfs_vitals(void* h, char* out, int cap);
int gfs_scenario_load(void* h, const char* table, int round0);
void gfs_scenario_clear(void* h);
void gfs_seed_full(void* h);
int gfs_warm(void* h);
void gfs_stop(void* h);
}

namespace {

constexpr int kN = 12;
constexpr int kTFail = 5;
constexpr int kTSuspect = 2;  // armed via gfs_configure below
constexpr int kTCooldown = 5;

bool Contains(const int* buf, int count, int idx) {
  return std::find(buf, buf + count, idx) != buf + count;
}

int Fail(const char* what) {
  std::fprintf(stderr, "SANITIZE_CAMPAIGN_FAIL: %s\n", what);
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  int base_port = argc > 1 ? std::atoi(argv[1]) : 21500;
  double period = argc > 2 ? std::atof(argv[2]) : 0.05;

  void* h = gfs_cluster_create(kN, base_port, period, kTFail, kTCooldown,
                               /*min_group=*/4, /*fresh_cooldown=*/1,
                               /*introducer=*/0);
  // round-16 knob table: the campaign protocol profile + an armed SWIM
  // lifecycle, so the suspicion paths run under the sanitizers too
  if (gfs_configure(h, "push=random fanout=4 remove_broadcast=0 "
                       "t_suspect=2 lh_multiplier=2 lh_frac=0.25") != 0) {
    gfs_cluster_destroy(h);
    return Fail("gfs_configure rejected a valid knob table");
  }
  if (gfs_configure(h, "nonsense=1") == 0 ||
      gfs_configure(h, "lh_frac=2.0") == 0) {
    gfs_cluster_destroy(h);
    return Fail("gfs_configure accepted a malformed knob table");
  }
  if (gfs_cluster_start(h) != 0) {
    gfs_cluster_destroy(h);
    return Fail("cluster failed to start (ports busy?)");
  }
  if (gfs_configure(h, "fanout=3") == 0) {
    gfs_cluster_destroy(h);
    return Fail("gfs_configure accepted knobs after start");
  }

  // seeded steady-state start (the campaign runners' boot), then warm
  gfs_seed_full(h);
  for (int i = 0; i < 100 && !gfs_warm(h); ++i)
    std::this_thread::sleep_for(
        std::chrono::duration<double>(period / 2));
  int buf[4 * kN];
  if (gfs_alive(h, buf, kN) != kN) {
    gfs_cluster_destroy(h);
    return Fail("cohort did not converge to n alive");
  }

  // arm the obs plane + a fault-gate table (flap node 7 dark 2-of-3
  // rounds for a stretch); malformed tables must be rejected whole
  int r0 = gfs_obs_enable(h);
  if (gfs_scenario_load(h, "flap 1 9 1 2 7\noutage 2 4 3\n", r0) != 0) {
    gfs_cluster_destroy(h);
    return Fail("gfs_scenario_load rejected a valid gate table");
  }
  if (gfs_scenario_load(h, "flap 1 9 0 0 7\n", r0) == 0 ||
      gfs_scenario_load(h, "partition 1 4 0 1\n", r0) == 0 ||
      gfs_scenario_load(h, "wat 1 2 3\n", r0) == 0) {
    gfs_cluster_destroy(h);
    return Fail("gfs_scenario_load accepted a malformed gate table");
  }

  // concurrent observation hammering: the race surface TSan exists for
  // is the control/observation verbs (Python-thread side) against the
  // epoll loop thread holding the protocol state — the round-16 obs
  // drain + vitals buffers included
  std::atomic<bool> stop{false};
  std::atomic<long> obs_bytes{0};
  std::thread poller([&] {
    int pbuf[4 * kN];
    char obs[8192];
    char vit[512];
    while (!stop.load()) {
      gfs_alive(h, pbuf, kN);
      gfs_membership(h, 0, pbuf, kN);
      gfs_round(h);
      gfs_drain_events(h, pbuf, 4 * kN);
      int got = gfs_obs_drain(h, obs, sizeof obs);
      if (got > 0) obs_bytes += got;
      gfs_vitals(h, vit, sizeof vit);
      // tiny-cap calls exercise the line-boundary / snprintf sizing
      gfs_obs_drain(h, obs, 8);
      gfs_vitals(h, vit, 4);
      std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
  });

  // the campaign: crash two nodes mid-poll, detect (t_fail + t_suspect
  // with the lifecycle armed), rejoin one
  gfs_crash(h, 5);
  gfs_crash(h, 9);
  gfs_advance(h, kTFail + kTSuspect + 7);
  stop.store(true);
  poller.join();
  if (obs_bytes.load() <= 0) {
    gfs_cluster_destroy(h);
    return Fail("obs drain never produced event lines");
  }

  int rc = 0;
  int alive = gfs_alive(h, buf, kN);
  if (Contains(buf, alive, 5) || Contains(buf, alive, 9))
    rc = Fail("crashed nodes still alive after t_fail + slack");

  // rejoin 5 after the cooldown window; the poller already drained some
  // events, which is fine — the membership views are the outcome checked
  gfs_advance(h, kTCooldown + 3);
  gfs_join(h, 5);
  gfs_advance(h, 8);
  alive = gfs_alive(h, buf, kN);
  if (!Contains(buf, alive, 5)) rc = Fail("rejoined node 5 not alive");
  int members = gfs_membership(h, 0, buf, kN);
  if (!Contains(buf, members, 5))
    rc = Fail("introducer view missing rejoined node 5");

  // graceful leave disseminates without a detection
  gfs_leave(h, 3);
  gfs_advance(h, 4);
  members = gfs_membership(h, 0, buf, kN);
  if (Contains(buf, members, 3)) rc = Fail("LEAVE did not disseminate");

  // stop-then-drain: the loop halts, the buffered events stay readable
  // (the campaign runners' shutdown order), and the stream carries the
  // lifecycle the campaign just ran
  gfs_scenario_clear(h);
  gfs_stop(h);
  {
    std::string all;
    char obs[8192];
    int got;
    while ((got = gfs_obs_drain(h, obs, sizeof obs)) > 0)
      all.append(obs, static_cast<size_t>(got));
    if (all.find("round_tick") == std::string::npos)
      rc = Fail("post-stop drain carried no round_tick rows");
  }

  gfs_cluster_destroy(h);

  // round-20 delta + k-loop phase: a second cluster running the delta
  // dissemination profile with the receive path striped across 4 epoll
  // loops, under the same concurrent observation hammering — the
  // per-peer cursor maps, the ver-ordered change index, the address
  // ring, and the striped socket ownership get their own TSan/ASan
  // certification.  The cadence constraint (anti_entropy_every must
  // stay strictly below t_fail in delta mode, or a lost anti-entropy
  // push can manufacture staleness past the detection window) is
  // exercised as a reject first.
  {
    void* h2 = gfs_cluster_create(kN, base_port + 64, period, kTFail,
                                  kTCooldown, /*min_group=*/4,
                                  /*fresh_cooldown=*/1, /*introducer=*/0);
    if (gfs_configure(h2, "delta=1 anti_entropy_every=5") == 0) {
      gfs_cluster_destroy(h2);
      return Fail("configure accepted anti_entropy_every >= t_fail "
                  "with delta on");
    }
    if (gfs_configure(h2, "loops=0") == 0 ||
        gfs_configure(h2, "loops=65") == 0 ||
        gfs_configure(h2, "delta_entries=0") == 0) {
      gfs_cluster_destroy(h2);
      return Fail("configure accepted an out-of-range delta/loops knob");
    }
    if (gfs_configure(h2, "push=random fanout=4 remove_broadcast=0 "
                          "t_suspect=2 delta=1 delta_entries=8 "
                          "anti_entropy_every=3 loops=4") != 0) {
      gfs_cluster_destroy(h2);
      return Fail("configure rejected a valid delta + loops knob table");
    }
    if (gfs_cluster_start(h2) != 0) {
      gfs_cluster_destroy(h2);
      return Fail("delta cluster failed to start (ports busy?)");
    }
    gfs_seed_full(h2);
    for (int i = 0; i < 100 && !gfs_warm(h2); ++i)
      std::this_thread::sleep_for(
          std::chrono::duration<double>(period / 2));
    gfs_obs_enable(h2);
    std::atomic<bool> stop2{false};
    std::thread poller2([&] {
      int pbuf[4 * kN];
      char obs[8192];
      char vit[512];
      while (!stop2.load()) {
        gfs_alive(h2, pbuf, kN);
        gfs_membership(h2, 1, pbuf, kN);
        gfs_drain_events(h2, pbuf, 4 * kN);
        gfs_obs_drain(h2, obs, sizeof obs);
        gfs_vitals(h2, vit, sizeof vit);
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
      }
    });
    // crash one node mid-poll: detection must cross stripe boundaries
    // (the crashed node's entries live in every other stripe's views)
    gfs_crash(h2, 4);
    gfs_advance(h2, kTFail + kTSuspect + 7);
    stop2.store(true);
    poller2.join();
    int alive2 = gfs_alive(h2, buf, kN);
    if (Contains(buf, alive2, 4))
      rc = Fail("delta cluster: crashed node still alive after slack");
    // the wire actually ran in delta mode: frames_delta must be nonzero
    char vit[512];
    if (gfs_vitals(h2, vit, sizeof vit) <= 0) {
      rc = Fail("delta cluster: vitals unreadable");
    } else {
      const char* p = std::strstr(vit, "frames_delta=");
      if (p == nullptr || std::atoll(p + std::strlen("frames_delta=")) <= 0)
        rc = Fail("delta cluster: no delta frames on the wire");
    }
    gfs_stop(h2);
    gfs_cluster_destroy(h2);
  }

  // codec sweep: round-trip plus the malformed chunks DecodeMembers must
  // skip (strtoll/strtod edge input — the UBSan half of the build)
  {
    char wire[4096], back[4096];
    int wn = gfs_codec_encode(
        "10.0.0.1:8000 42 1785344960.123456\n10.0.0.2:8000 7 1.5\n", wire,
        sizeof wire);
    if (wn <= 0 || wn >= static_cast<int>(sizeof wire))
      rc = Fail("codec encode sizing");
    if (gfs_codec_decode(wire, back, sizeof back) <= 0)
      rc = Fail("codec decode of own encoding");
    static const char* kMalformed[] = {
        "", "<#ENTRY#>", "bad-no-fields", "x<#INFO#>NaNish",
        "a<#INFO#>99999999999999999999999999<#INFO#>1e999",
        "ok<#INFO#>5<#INFO#>1.0<#ENTRY#>trunc<#INFO#>",
    };
    for (const char* m : kMalformed) gfs_codec_decode(m, back, sizeof back);
    // snprintf-style truncation path: tiny caps must stay in bounds
    char tiny[4];
    gfs_codec_decode(wire, tiny, sizeof tiny);
    gfs_codec_encode("10.0.0.1:8000 1 2.0\n", tiny, sizeof tiny);
  }

  if (rc == 0) std::printf("SANITIZE_CAMPAIGN_OK\n");
  return rc;
}
