// Clang Thread Safety Analysis surface for the native engine
// (`make tsa` = clang++ -Wthread-safety -Werror): the compile-time
// counterpart of the tsan/asan evidence lanes.  Under any non-clang
// compiler every macro expands empty and Mutex/MutexLock degrade to a
// plain std::mutex + lock_guard, so the g++ production build is
// byte-for-byte unaffected.
//
// The engine's locking discipline the analysis enforces:
//   - ONE capability, Cluster::mu_, guards all protocol state — every
//     Node field the epoll thread and the C-ABI control verbs both
//     touch is GFS_GUARDED_BY(cluster_->mu_), every Node method that
//     touches them is GFS_REQUIRES(cluster_->mu_).
//   - TSA compares capability expressions syntactically after
//     this-substitution, so at a Cluster call site `node->Tick()` the
//     requirement reads `node->cluster_->mu_` — an alias of the held
//     `this->mu_` the analysis cannot prove.  Node::AssertLockHeld()
//     (a GFS_ASSERT_CAPABILITY no-op) is called once per node at every
//     Cluster -> Node crossing to state exactly that aliasing fact;
//     it asserts, never acquires, so a crossing OUTSIDE the lock still
//     fails the analysis at the first guarded access.

#ifndef GOSSIPFS_NATIVE_TSA_H_
#define GOSSIPFS_NATIVE_TSA_H_

#include <mutex>

#if defined(__clang__)
#define GFS_THREAD_ANNOTATION(x) __attribute__((x))
#else
#define GFS_THREAD_ANNOTATION(x)
#endif

#define GFS_CAPABILITY(x) GFS_THREAD_ANNOTATION(capability(x))
#define GFS_SCOPED_CAPABILITY GFS_THREAD_ANNOTATION(scoped_lockable)
#define GFS_GUARDED_BY(x) GFS_THREAD_ANNOTATION(guarded_by(x))
#define GFS_REQUIRES(...) \
  GFS_THREAD_ANNOTATION(requires_capability(__VA_ARGS__))
#define GFS_ACQUIRE(...) \
  GFS_THREAD_ANNOTATION(acquire_capability(__VA_ARGS__))
#define GFS_RELEASE(...) \
  GFS_THREAD_ANNOTATION(release_capability(__VA_ARGS__))
#define GFS_ASSERT_CAPABILITY(x) GFS_THREAD_ANNOTATION(assert_capability(x))
#define GFS_NO_TSA GFS_THREAD_ANNOTATION(no_thread_safety_analysis)

namespace gossipfs {

// std::mutex carries no TSA annotations under libstdc++, so the engine
// locks through this annotated wrapper instead.
class GFS_CAPABILITY("mutex") Mutex {
 public:
  void lock() GFS_ACQUIRE() { mu_.lock(); }
  void unlock() GFS_RELEASE() { mu_.unlock(); }

 private:
  std::mutex mu_;
};

// Scoped holder (the lock_guard shape the engine already used).
class GFS_SCOPED_CAPABILITY MutexLock {
 public:
  explicit MutexLock(Mutex& mu) GFS_ACQUIRE(mu) : mu_(mu) { mu_.lock(); }
  ~MutexLock() GFS_RELEASE() { mu_.unlock(); }
  MutexLock(const MutexLock&) = delete;
  MutexLock& operator=(const MutexLock&) = delete;

 private:
  Mutex& mu_;
};

}  // namespace gossipfs

#endif  // GOSSIPFS_NATIVE_TSA_H_
