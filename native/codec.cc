#include "codec.h"

#include <cmath>
#include <cstdlib>
#include <iomanip>
#include <sstream>

namespace gossipfs {

std::string EncodeMembers(const std::vector<MemberEntry>& members) {
  std::ostringstream out;
  // full round-trip precision for the timestamp: receivers re-stamp locally
  // (slave.go:426) so only addr/hb matter semantically, but a lossy default
  // 6-significant-digit print would corrupt any uptime > ~1 day
  out << std::setprecision(17);
  bool first = true;
  for (const auto& m : members) {
    if (!first) out << kEntrySep;
    first = false;
    out << m.addr << kFieldSep << m.hb << kFieldSep << m.ts;
  }
  return out.str();
}

std::vector<MemberEntry> DecodeMembers(const std::string& payload) {
  // allocation-free scan (round 16): the campaign-cohort merge path
  // decodes fanout*N lists of N entries per round — the old
  // Split-into-strings walk allocated ~6 strings per entry and was the
  // n=256 engine's hottest loop by far.  strtod reads directly into the
  // payload and stops at the next separator's '<'; the NUL terminating
  // the std::string bounds the final field.
  std::vector<MemberEntry> out;
  if (payload.empty()) return out;
  constexpr size_t esz = sizeof(kEntrySep) - 1;
  constexpr size_t fsz = sizeof(kFieldSep) - 1;
  const char* base = payload.c_str();
  size_t pos = 0;
  for (;;) {
    size_t end = payload.find(kEntrySep, pos);
    if (end == std::string::npos) end = payload.size();
    size_t f1 = payload.find(kFieldSep, pos);
    if (f1 != std::string::npos && f1 < end && f1 > pos) {
      size_t hb_off = f1 + fsz;
      char* endp = nullptr;
      double hb = std::strtod(base + hb_off, &endp);
      // skip non-numeric hb; NaN/inf (and counters past the long long
      // range) would make the cast UB — same silent-skip semantics as
      // the reference's parse
      if (endp != base + hb_off && std::isfinite(hb) &&
          std::fabs(hb) < 9.0e18) {
        MemberEntry m;
        m.addr.assign(payload, pos, f1 - pos);
        m.hb = static_cast<long long>(hb);
        size_t f2 = payload.find(kFieldSep, hb_off);
        if (f2 != std::string::npos && f2 < end)
          m.ts = std::strtod(base + f2 + fsz, nullptr);
        out.push_back(std::move(m));
      }
    }
    if (end >= payload.size()) break;
    pos = end + esz;
  }
  return out;
}

std::string EncodeDelta(const std::vector<MemberEntry>& members) {
  return std::string(kDeltaMark) + EncodeMembers(members);
}

bool IsDelta(const std::string& payload) {
  return payload.compare(0, sizeof(kDeltaMark) - 1, kDeltaMark) == 0;
}

std::vector<MemberEntry> DecodeDelta(const std::string& payload) {
  if (!IsDelta(payload)) return {};
  return DecodeMembers(payload.substr(sizeof(kDeltaMark) - 1));
}

std::string EncodeControl(const std::string& addr, const std::string& verb) {
  return addr + kCmdSep + verb;
}

std::optional<ControlMsg> DecodeControl(const std::string& payload) {
  size_t pos = payload.find(kCmdSep);
  if (pos == std::string::npos) return std::nullopt;
  ControlMsg msg;
  msg.arg = payload.substr(0, pos);
  msg.verb = payload.substr(pos + sizeof(kCmdSep) - 1);
  return msg;
}

}  // namespace gossipfs
