#include "codec.h"

#include <cmath>
#include <cstdlib>
#include <iomanip>
#include <sstream>

namespace gossipfs {
namespace {

std::vector<std::string> Split(const std::string& s, const std::string& sep) {
  std::vector<std::string> out;
  size_t start = 0;
  while (true) {
    size_t pos = s.find(sep, start);
    if (pos == std::string::npos) {
      out.push_back(s.substr(start));
      return out;
    }
    out.push_back(s.substr(start, pos - start));
    start = pos + sep.size();
  }
}

}  // namespace

std::string EncodeMembers(const std::vector<MemberEntry>& members) {
  std::ostringstream out;
  // full round-trip precision for the timestamp: receivers re-stamp locally
  // (slave.go:426) so only addr/hb matter semantically, but a lossy default
  // 6-significant-digit print would corrupt any uptime > ~1 day
  out << std::setprecision(17);
  bool first = true;
  for (const auto& m : members) {
    if (!first) out << kEntrySep;
    first = false;
    out << m.addr << kFieldSep << m.hb << kFieldSep << m.ts;
  }
  return out.str();
}

std::vector<MemberEntry> DecodeMembers(const std::string& payload) {
  std::vector<MemberEntry> out;
  if (payload.empty()) return out;
  for (const auto& chunk : Split(payload, kEntrySep)) {
    auto fields = Split(chunk, kFieldSep);
    if (fields.size() < 2 || fields[0].empty()) continue;
    char* end = nullptr;
    double hb = std::strtod(fields[1].c_str(), &end);
    // skip non-numeric hb; NaN/inf would make the long long cast UB
    if (end == fields[1].c_str() || !std::isfinite(hb)) continue;
    MemberEntry m;
    m.addr = fields[0];
    m.hb = static_cast<long long>(hb);
    m.ts = fields.size() >= 3 ? std::strtod(fields[2].c_str(), nullptr) : 0.0;
    out.push_back(std::move(m));
  }
  return out;
}

std::string EncodeControl(const std::string& addr, const std::string& verb) {
  return addr + kCmdSep + verb;
}

std::optional<ControlMsg> DecodeControl(const std::string& payload) {
  size_t pos = payload.find(kCmdSep);
  if (pos == std::string::npos) return std::nullopt;
  ControlMsg msg;
  msg.arg = payload.substr(0, pos);
  msg.verb = payload.substr(pos + sizeof(kCmdSep) - 1);
  return msg;
}

}  // namespace gossipfs
