// Native gossip runtime: N protocol nodes over real localhost UDP sockets,
// driven by k striped epoll loops (gfs_configure(loops=k), default 1) —
// the C++ equivalent of the reference's Go runtime (goroutine heartbeat
// driver main.go:27-33, blocking UDP receive loop slave/slave.go:207-248),
// for the BASELINE config-1 parity path.
//
// Striping mirrors parallel/mesh.py's row sharding: node i belongs to
// stripe i % k, each stripe owns one epoll fd + one mutex guarding its
// nodes' protocol state, and the per-round tick is a barrier — every
// stripe ticks its own nodes once the period elapses, the last arriver
// publishes round_tick and only then advances the shared round counter.
// Cross-stripe reads (fp attribution, vitals, warm gate) take stripe
// mutexes one at a time, never nested.
//
// Protocol semantics mirror the reference exactly (and the Python asyncio
// twin, gossipfs_tpu/detector/udp.py):
//   - join through the introducer, which appends and pushes its full list to
//     every member (addNewMember, slave.go:250-274)
//   - per-period tick: refresh-only below min_group (slave.go:504-509), bump
//     own heartbeat, detect members with hb > 1 silent past t_fail periods
//     (slave.go:460-476), REMOVE broadcast (slave.go:338-363), fail-list
//     cooldown expiry (slave.go:484-497), then push to ring neighbours at
//     sorted positions self-1, self+1, self+2 (slave.go:515-542) — a full
//     list every anti_entropy_every rounds when delta mode is on, else a
//     capped changed-first + round-robin-tail delta frame
//     (protocol_spec.DELTA_GOSSIP)
//   - merge: shared members take max heartbeat + LOCAL timestamp; unknown
//     members are added unless on the fail list (slave.go:414-440); delta
//     frames merge identically — the mark only changes wire accounting
//
// Exposed through a C ABI (extern "C") for ctypes — see gossipfs_tpu/native.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iomanip>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "codec.h"
#include "tsa.h"

namespace gossipfs {
namespace {

double MonotonicNow() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Uniform-vitals field writer: every field name is a string literal at
// its call site, and gossipfs-lint's native-obs-kinds rule requires
// each to appear in obs/schema.py VITALS_FIELDS — single ownership of
// the counter names across the language boundary (the n/a-not-0 rule:
// a field this engine cannot know is simply never appended, so the
// Python surface renders it n/a, never a fabricated 0).
void AppendVital(std::ostringstream& os, const char* key, long long v) {
  if (os.tellp() > 0) os << ' ';
  os << key << '=' << v;
}

struct Member {
  long long hb = 0;
  double ts = 0.0;
  // monotone change version (delta gossip): stamped from the owning
  // node's ver_clock_ whenever hb advances or the entry is (re)added,
  // so EncodeDeltaFor can select "changed since this peer's cursor"
  long long ver = 0;
};

struct DetectionEvent {
  int round;
  int observer;
  int subject;
  int false_positive;
};

struct Config {
  int n = 10;
  int base_port = 19000;
  double period = 0.05;  // seconds per heartbeat round
  int t_fail = 5;        // periods of silence before declaring failure
  int t_cooldown = 5;    // fail-list suppression periods
  int min_group = 4;     // below this size: refresh-only
  bool fresh_cooldown = false;  // stamp fail-list entries at removal time
  int introducer = 0;
  // campaign protocol profile (gfs_configure, round 16) — the same knobs
  // the asyncio engine grew in round 14 (detector/udp.py UdpCluster):
  // push_random = fanout random listed peers per tick instead of the
  // reference's ring positions; remove_broadcast=false = removal by
  // local timeout only (the north-star gossip-only dissemination).
  bool push_random = false;
  int fanout = 3;
  bool remove_broadcast = true;
  // SWIM suspicion + Lifeguard local health (suspicion/params.py is the
  // schema; suspicion/runtime.py the per-node reference semantics the
  // Tick/Merge paths below mirror).  t_suspect == 0 disarms.
  int t_suspect = 0;
  int lh_multiplier = 0;
  double lh_frac = 0.25;
  // delta-piggyback dissemination (protocol_spec.DELTA_GOSSIP, round
  // 20): per-round refresh pushes carry a bounded per-peer delta frame
  // (recently-changed entries first, round-robin refresh of the stable
  // tail, capped at delta_entries) instead of the full list; every
  // anti_entropy_every-th cluster round still pushes the FULL list so a
  // lost delta can never wedge convergence.  The cadence must stay
  // strictly inside the detection window (anti_entropy_every < t_fail):
  // a receiver's freshest view of a live entry is then at most
  // anti_entropy_every rounds old, so delta mode cannot manufacture
  // staleness (Configure rejects the inversion, like UdpCluster does).
  bool delta = false;
  int delta_entries = 16;
  int anti_entropy_every = 4;
  // receive-path shards: k epoll loops, each with its own socket set +
  // striped node ownership (node i -> stripe i % loops), the way
  // parallel/mesh.py shards rows across devices
  int loops = 1;
};

// Wire-frame class for send accounting (the delta A/B surface): the
// caller names the kind at the send site, so the counters never pay a
// payload scan.
enum class FrameKind { kControl, kFull, kDelta };

// -- fault gates (scenarios/schedule.py primitives, compiled to a text
// table by gossipfs_tpu/native.py::compile_native_scenario and pushed
// over gfs_scenario_load).  Semantics mirror ScenarioRuntime.drops:
// a src -> dst datagram at armed-relative round r is dropped iff any
// active rule says so.  Bernoulli link loss is deliberately NOT in the
// table (it needs an RNG-stream parity decision; the Python compiler
// rejects it, like the aligned-arc tensor path does).
struct GateFlap {
  int start, end, up, down;
  std::vector<char> mask;  // [n] sender membership
};
struct GateOutage {
  int start, end;
  std::vector<char> mask;  // [n] group membership (src OR dst drops)
};
struct GatePartition {
  int start, end;
  std::vector<int> pid;  // [n] group id; cross-pid drops
};
struct GateSlow {
  int start, end, stride;
  std::vector<char> mask;  // [n] lagging senders
};

struct GateTable {
  std::vector<GateFlap> flaps;
  std::vector<GateOutage> outages;
  std::vector<GatePartition> partitions;
  std::vector<GateSlow> slows;
  std::string name;
  int horizon = 0;
};

// Cluster is defined BEFORE Node so Node's thread-safety attributes can
// name the capability they are guarded by (`stripe_->mu_` must resolve
// against a complete Cluster::Stripe).  The members Node needs (ctor,
// dtor, RecordDetection) are declared here and defined out-of-line
// after Node.
class Node;

class Cluster {
 public:
  explicit Cluster(const Config& cfg);
  ~Cluster();  // out-of-line: unique_ptr<Node> needs Node complete

  bool Start();
  void Stop();

  // Control verbs (thread-safe; callable from Python while the loop runs).
  void Crash(int i);
  void Leave(int i);
  void Join(int i);

  // Blocks for `rounds` heartbeat periods of wall time (real-time runtime).
  void Advance(int rounds);

  int Round() { return round_.load(); }
  int Membership(int observer, int* out, int cap);
  int Suspects(int observer, int* out, int cap);
  long long Incarnation(int observer, int subject);  // hb, -1 if absent
  int AliveNodes(int* out, int cap);
  int DrainEvents(int* out, int cap);  // quadruples per event

  // -- round-16 control/observation surface (all thread-safe)
  int Configure(const std::string& kv);  // pre-Start knob table
  int ObsEnable();                       // arm event buffering; returns base round
  int ObsDrain(char* out, int cap);      // whole-line sized drain
  std::string VitalsText();              // uniform k=v counter text
  int ScenarioLoad(const std::string& table, int round0);
  void ScenarioClear();
  void SeedFull();  // fully-joined steady state (udp seed_full_membership)
  int Warm();       // 1 iff every alive view is full with every hb > 1

  // -- the receive-path shard (round 20): nodes i with i % loops == s
  // are OWNED by stripe s — its epoll fd drains their sockets, its
  // thread ticks them, and its mutex guards ALL their protocol state.
  // Datagram "delivery" between nodes is real UDP, so a stripe thread
  // only ever mutates its OWN nodes; the cross-stripe reads that remain
  // (ground-truth aliveness in RecordDetection, the shared round clock,
  // the cumulative counters) are atomics, and the shared planes — the
  // detection-event queue, the obs buffer, the armed fault gates — sit
  // behind their own leaf mutexes.  Lock order: stripe mutexes (index
  // order when more than one) before any leaf; leaves never nest.
  struct Stripe {
    Mutex mu_;
    int epoll_fd_ = -1;
    std::thread thread_;
    std::vector<int> node_ids_;  // immutable after Configure/Start
    // the round this stripe has already ticked (its own thread only)
    int done_round_ = 0;
  };

  const Config& cfg() const { return cfg_; }
  void RecordDetection(int observer, const std::string& subject_addr);
  int IdxOf(const std::string& addr) const {
    auto it = addr_to_idx_.find(addr);
    return it == addr_to_idx_.end() ? -1 : it->second;
  }
  // obs emission (the event lines the Python side renders through
  // obs.recorder.FlightRecorder so the stream's reader stays
  // obs.recorder.load_stream).  Kind strings are literals at every call
  // site: gossipfs-lint's native-obs-kinds rule requires each to appear
  // in obs/schema.py EVENT_KINDS (single ownership across the language
  // boundary), and rules_spec's spec-native-annotations rule requires
  // every LIFECYCLE kind to be dominated by a matching `// @gfs:`
  // contract annotation.
  void ObsEmit(const char* kind, int observer, int subject,
               const std::string& detail);
  void ObsEmit(const char* kind, int observer,
               const std::string& subject_addr, const std::string& detail);
  bool ScenarioDrops(int src, const std::string& dst_addr) const;
  void CountSend(size_t bytes, FrameKind kind) {
    sends_total_.fetch_add(1, std::memory_order_relaxed);
    bytes_total_.fetch_add(static_cast<long long>(bytes),
                           std::memory_order_relaxed);
    if (kind == FrameKind::kFull)
      frames_full_.fetch_add(1, std::memory_order_relaxed);
    else if (kind == FrameKind::kDelta)
      frames_delta_.fetch_add(1, std::memory_order_relaxed);
  }
  std::atomic<int> round_{0};  // Node::Tick reads it for the anti-entropy
                               // cadence; published by the barrier winner

 private:
  void RebuildStripes(int loops);  // pre-Start only (Configure)
  Stripe* StripeOf(int i) {
    return stripes_[static_cast<size_t>(i) % stripes_.size()].get();
  }
  void StripeBody(Stripe* s);
  void EmitRoundTick(double tick_ms);
  void ObsEmitLocked(const char* kind, int observer, int subject,
                     const std::string& detail) GFS_REQUIRES(obs_mu_);

  // Immutable after construction / Start (no lock needed): cfg_ (knob
  // writes only before the loop threads exist), nodes_, addr_to_idx_,
  // stripes_ layout (RebuildStripes runs pre-Start), running_ (atomic).
  Config cfg_;
  std::vector<std::unique_ptr<Node>> nodes_;
  std::map<std::string, int> addr_to_idx_;
  std::vector<std::unique_ptr<Stripe>> stripes_;
  std::atomic<bool> running_{false};
  Mutex ctl_mu_;  // serializes Configure vs Start (both pre-loop)
  // -- round clock + tick barrier (all stripe threads).  A stripe ticks
  // its nodes when now >= next_tick_ and it has not ticked this round_;
  // the FIRST starter stamps tick_t0_, the LAST arriver emits the
  // round_tick, advances next_tick_, resets the counters, and ONLY THEN
  // publishes round_+1 — the ordering that makes a double-tick
  // impossible (no stripe can re-enter until the new round is visible).
  std::atomic<double> next_tick_{0.0};
  std::atomic<int> tick_starters_{0};
  std::atomic<int> tick_arrivals_{0};
  std::atomic<double> tick_t0_{0.0};
  // -- cumulative counters (vitals; events_ drains, so the `metrics`
  // surface needs its own accounting — the udp engine's convention).
  // Atomics: bumped under different stripe locks.
  std::atomic<long long> det_total_{0};
  std::atomic<long long> fp_total_{0};
  std::atomic<long long> sends_total_{0};
  // wire accounting (the delta A/B surface): payload bytes handed to
  // sendto + the full-list vs delta frame split (FrameKind at the send
  // site — no payload scan)
  std::atomic<long long> bytes_total_{0};
  std::atomic<long long> frames_full_{0};
  std::atomic<long long> frames_delta_{0};
  // -- detection-event queue (leaf lock: any stripe appends, the C ABI
  // drains)
  Mutex events_mu_;
  std::vector<DetectionEvent> events_ GFS_GUARDED_BY(events_mu_);
  // -- obs plane: rendered event lines awaiting ObsDrain.  OFF until
  // gfs_obs_enable so detectors without a recorder never grow the
  // buffer; enabling rebases the stamped round clock to 0 (the
  // arming-relative frame the udp campaign streams use).  The armed
  // bit is an atomic fast path; the buffer + baselines are a leaf lock.
  std::atomic<bool> obs_enabled_{false};
  Mutex obs_mu_;
  int obs_round0_ GFS_GUARDED_BY(obs_mu_) = 0;
  std::string obs_buf_ GFS_GUARDED_BY(obs_mu_);
  long long obs_det0_ GFS_GUARDED_BY(obs_mu_) = 0;
  long long obs_fp0_ GFS_GUARDED_BY(obs_mu_) = 0;
  long long obs_sends0_ GFS_GUARDED_BY(obs_mu_) = 0;
  long long obs_sus_entered0_ GFS_GUARDED_BY(obs_mu_) = 0;
  long long obs_refut0_ GFS_GUARDED_BY(obs_mu_) = 0;
  // -- armed fault gates (ScenarioLoad); windows are round0-relative.
  // Armed bit atomic (the per-send fast path); table behind a leaf lock.
  std::atomic<bool> gates_armed_{false};
  mutable Mutex gates_mu_;
  GateTable gates_ GFS_GUARDED_BY(gates_mu_);
  int scn_round0_ GFS_GUARDED_BY(gates_mu_) = 0;

  friend class Node;
};

class Node {
 public:
  Node(Cluster* cluster, int idx, int port);
  ~Node() { Close(); }

  bool Open();   // bind the UDP socket
  void Close();

  void HandleDatagram(const std::string& payload)
      GFS_REQUIRES(stripe_->mu_);
  void Tick(double now) GFS_REQUIRES(stripe_->mu_);
  void StopGraceful() GFS_REQUIRES(stripe_->mu_);  // LEAVE broadcast, die
  void StopCrash() { alive_.store(false); }        // silent death (CTRL+C)
  void ResetState() GFS_REQUIRES(stripe_->mu_);    // fresh state for rejoin
  void SeedMembers(const std::vector<std::string>& addrs, double now)
      GFS_REQUIRES(stripe_->mu_);

  int fd() const { return fd_; }
  int idx() const { return idx_; }
  // ground-truth aliveness is lock-free: RecordDetection reads it for a
  // subject owned by a DIFFERENT stripe, and it only toggles at the
  // C-ABI crash/leave/join seams
  bool alive() const { return alive_.load(); }
  const std::string& addr() const { return addr_; }
  std::vector<std::string> MemberAddrs() const GFS_REQUIRES(stripe_->mu_);
  std::vector<std::string> SuspectAddrs() const GFS_REQUIRES(stripe_->mu_);
  // per-entry heartbeat counter (the incarnation surface the conformance
  // harness reads); -1 when the addr is not in this node's view
  long long HbOf(const std::string& addr) const GFS_REQUIRES(stripe_->mu_);

  // TSA compares capability expressions syntactically, so at a Cluster
  // call site `node->Tick()` requires `node->stripe_->mu_` — an alias
  // of the held stripe mutex the analysis cannot prove.  This
  // assert-only no-op states the aliasing fact; Cluster calls it once
  // per node at every crossing made with the owning stripe's lock held.
  void AssertLockHeld() const GFS_ASSERT_CAPABILITY(stripe_->mu_) {}

 private:
  void Send(const std::string& peer_addr, const std::string& msg,
            FrameKind kind) GFS_REQUIRES(stripe_->mu_);
  void AddMember(const std::string& addr, double now)
      GFS_REQUIRES(stripe_->mu_);  // introducer path
  void RemoveMember(const std::string& addr, double now)
      GFS_REQUIRES(stripe_->mu_);
  void Merge(const std::vector<MemberEntry>& remote, double now)
      GFS_REQUIRES(stripe_->mu_);
  void OnSuspect(const std::string& addr, double now)
      GFS_REQUIRES(stripe_->mu_);
  void OnRefute(const std::string& arg, double now)
      GFS_REQUIRES(stripe_->mu_);
  // Lifeguard local health (runtime.py::degraded)
  bool Degraded() const GFS_REQUIRES(stripe_->mu_);
  std::string EncodeSelf() const GFS_REQUIRES(stripe_->mu_);
  // delta gossip (protocol_spec.DELTA_GOSSIP; udp.py _encode_delta is
  // the twin): advance the change clock / build one bounded per-peer
  // delta frame / send one refresh push picking full vs delta
  long long Bump() GFS_REQUIRES(stripe_->mu_) { return ++ver_clock_; }
  // stamp an entry's change version and re-index it in changed_log_
  void Stamp(Member& m, const std::string& addr)
      GFS_REQUIRES(stripe_->mu_) {
    if (m.ver > 0) changed_log_.erase(m.ver);
    m.ver = Bump();
    changed_log_[m.ver] = addr;
  }
  void RingInsert(const std::string& addr) GFS_REQUIRES(stripe_->mu_) {
    auto it = std::lower_bound(addr_ring_.begin(), addr_ring_.end(), addr);
    if (it == addr_ring_.end() || *it != addr) addr_ring_.insert(it, addr);
  }
  void RingErase(const std::string& addr) GFS_REQUIRES(stripe_->mu_) {
    auto it = std::lower_bound(addr_ring_.begin(), addr_ring_.end(), addr);
    if (it != addr_ring_.end() && *it == addr) addr_ring_.erase(it);
  }
  std::string EncodeDeltaFor(const std::string& peer, FrameKind* kind)
      GFS_REQUIRES(stripe_->mu_);
  void PushRefresh(const std::string& peer, bool anti_entropy,
                   std::string& full_msg) GFS_REQUIRES(stripe_->mu_);
  // per-node stream for the random-push draw
  uint32_t NextRand() GFS_REQUIRES(stripe_->mu_);

  Cluster* const cluster_;
  const int idx_;
  const int port_;
  std::string addr_;
  int fd_ = -1;  // epoll registration is pre-thread; Close post-join
  // the owning receive-path stripe (assigned by Cluster::RebuildStripes
  // pre-Start); its mutex is THE capability guarding this node's state
  Cluster::Stripe* stripe_ = nullptr;
  std::atomic<bool> alive_{false};
  // sorted: ring order by address
  std::map<std::string, Member> members_ GFS_GUARDED_BY(stripe_->mu_);
  // addr -> cooldown-start ts
  std::map<std::string, double> fail_list_ GFS_GUARDED_BY(stripe_->mu_);
  // suspicion (armed iff cfg.t_suspect > 0): addr -> suspect-start ts,
  // plus cumulative lifecycle counters (the vitals/round_tick surface)
  std::map<std::string, double> suspects_ GFS_GUARDED_BY(stripe_->mu_);
  long long sus_entered_ GFS_GUARDED_BY(stripe_->mu_) = 0;
  long long sus_refutations_ GFS_GUARDED_BY(stripe_->mu_) = 0;
  long long sus_confirms_ GFS_GUARDED_BY(stripe_->mu_) = 0;
  // rate-limits REFUTE broadcasts
  double last_refute_t_ GFS_GUARDED_BY(stripe_->mu_) = -1e18;
  uint32_t rng_state_ GFS_GUARDED_BY(stripe_->mu_);
  // delta gossip state (protocol_spec DELTA_GOSSIP): the node's change
  // clock, the per-peer "entries up to this version already sent"
  // cursors, and the round-robin tail-refresh position
  long long ver_clock_ GFS_GUARDED_BY(stripe_->mu_) = 0;
  std::map<std::string, long long> sent_ver_ GFS_GUARDED_BY(stripe_->mu_);
  size_t refresh_pos_ GFS_GUARDED_BY(stripe_->mu_) = 0;
  // ver-ordered change index (ver -> addr, one entry per member at its
  // LATEST ver): EncodeDeltaFor walks it top-down, so the per-peer
  // changed-first selection costs O(cap log N) instead of an O(N)
  // scan + sort PER PEER — the scan made delta-mode ticks ~5x slower
  // than full-list at n=256 (fanout encodes per round vs one)
  std::map<long long, std::string> changed_log_ GFS_GUARDED_BY(stripe_->mu_);
  // sorted address ring: O(1)-indexed round-robin tail refresh
  std::vector<std::string> addr_ring_ GFS_GUARDED_BY(stripe_->mu_);

  friend class Cluster;
};

// -- Cluster members that need a complete Node --------------------------------

Cluster::Cluster(const Config& cfg) : cfg_(cfg) {
  nodes_.reserve(cfg.n);
  for (int i = 0; i < cfg.n; ++i) {
    nodes_.emplace_back(new Node(this, i, cfg.base_port + i));
    addr_to_idx_[nodes_.back()->addr()] = i;
  }
  RebuildStripes(cfg_.loops);
}

Cluster::~Cluster() { Stop(); }

void Cluster::RebuildStripes(int loops) {
  // pre-Start only: no stripe threads exist, so the layout swap is
  // single-threaded by construction (Configure rejects a started
  // cluster before it ever reaches the loops knob)
  stripes_.clear();
  for (int s = 0; s < loops; ++s)
    stripes_.emplace_back(new Stripe);
  for (int i = 0; i < static_cast<int>(nodes_.size()); ++i) {
    Stripe* s = stripes_[static_cast<size_t>(i % loops)].get();
    s->node_ids_.push_back(i);
    nodes_[i]->stripe_ = s;
  }
}

void Cluster::RecordDetection(int observer, const std::string& subject_addr) {
  auto it = addr_to_idx_.find(subject_addr);
  if (it == addr_to_idx_.end()) return;
  // the subject may be owned by a DIFFERENT stripe than the calling
  // observer's: ground-truth aliveness is an atomic read, the queue and
  // counters are the shared leaf planes
  int fp = nodes_[it->second]->alive() ? 1 : 0;
  {
    MutexLock lk(events_mu_);
    events_.push_back(DetectionEvent{round_.load(), observer, it->second, fp});
  }
  det_total_.fetch_add(1, std::memory_order_relaxed);
  fp_total_.fetch_add(fp, std::memory_order_relaxed);
  // the one emission point every failure declaration funnels through —
  // the suspicion path after the (lh-stretched) window expires, and the
  // direct stale confirm when suspicion is disarmed (t_suspect == 0)
  // @gfs:transition SUSPECT->FAILED guard=confirm_window
  // @gfs:transition MEMBER->FAILED guard=stale
  ObsEmit("confirm", observer, it->second,
          fp ? "false_positive=1" : "false_positive=0");
}

// ---------------------------------------------------------------------------
// Node

Node::Node(Cluster* cluster, int idx, int port)
    : cluster_(cluster), idx_(idx), port_(port),
      rng_state_(0x5EEDu ^ (static_cast<uint32_t>(idx) * 2654435761u)) {
  addr_ = "127.0.0.1:" + std::to_string(port);
}

uint32_t Node::NextRand() {
  // xorshift32 — a per-node stream for the random-push draw (no parity
  // contract with the Python engines' streams; real-socket runs are
  // verdict-compared, never bit-compared)
  uint32_t x = rng_state_;
  x ^= x << 13;
  x ^= x >> 17;
  x ^= x << 5;
  rng_state_ = x ? x : 0x5EEDu;
  return rng_state_;
}

bool Node::Open() {
  fd_ = ::socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port_));
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return false;
  }
  return true;
}

void Node::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Node::ResetState() {
  members_.clear();
  fail_list_.clear();
  // a fresh process forgets its suspicions with the rest of its state;
  // the cumulative lifecycle counters survive (vitals are per-run)
  suspects_.clear();
  // delta gossip: a fresh process restarts its change clock and forgets
  // its per-peer cursors — the next push to any peer is a full list
  // (udp.py UdpNode reset does the same)
  ver_clock_ = 0;
  sent_ver_.clear();
  refresh_pos_ = 0;
  changed_log_.clear();
  // a fresh process knows only itself (InitMembership, slave.go:161-167)
  members_[addr_] = Member{0, MonotonicNow()};
  addr_ring_.assign(1, addr_);
  alive_.store(true);
}

void Node::SeedMembers(const std::vector<std::string>& addrs, double now) {
  // the fully-joined steady state the tensor engine's init_state models
  // (udp.py seed_full_membership): everyone listed at hb 0 with a fresh
  // local stamp — inside the hb<=1 detection grace.  Entries seed at
  // ver 0 (nothing "recently changed"), like the udp twin.
  members_.clear();
  for (const auto& a : addrs) members_[a] = Member{0, now};
  changed_log_.clear();
  addr_ring_.assign(addrs.begin(), addrs.end());
  std::sort(addr_ring_.begin(), addr_ring_.end());
}

void Node::Send(const std::string& peer_addr, const std::string& msg,
                FrameKind kind) {
  if (fd_ < 0) return;
  // fault-gate hook (the UdpNode._send seam): an armed scenario rule —
  // flapping dark phase, rack outage, partition, lagging sender —
  // drops the datagram HERE, so heartbeat pushes, control verbs and
  // SUSPECT/REFUTE broadcasts are all affected alike
  if (cluster_->ScenarioDrops(idx_, peer_addr)) return;
  size_t colon = peer_addr.rfind(':');
  if (colon == std::string::npos) return;
  // wire-derived addresses are untrusted: validate the port and IP parses
  // and skip bad entries (like DecodeMembers does for hb) — an exception
  // here would terminate the host process from the epoll thread
  const std::string port_text = peer_addr.substr(colon + 1);
  char* end = nullptr;
  long port = std::strtol(port_text.c_str(), &end, 10);
  if (end == port_text.c_str() || *end != '\0' || port <= 0 || port > 65535)
    return;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, peer_addr.substr(0, colon).c_str(),
                  &sa.sin_addr) != 1)
    return;
  ::sendto(fd_, msg.data(), msg.size(), 0, reinterpret_cast<sockaddr*>(&sa),
           sizeof(sa));
  cluster_->CountSend(msg.size(), kind);
}

std::string Node::EncodeSelf() const {
  std::vector<MemberEntry> entries;
  entries.reserve(members_.size());
  for (const auto& [addr, m] : members_)
    entries.push_back(MemberEntry{addr, m.hb, m.ts});
  return EncodeMembers(entries);
}

std::string Node::EncodeDeltaFor(const std::string& peer, FrameKind* kind) {
  // One bounded delta frame for `peer` — the protocol_spec DELTA_GOSSIP
  // entry-selection rule (udp.py _encode_delta is the line-for-line
  // twin): entries whose version advanced past the per-peer cursor,
  // most recently changed first, then round-robin refresh of the stable
  // tail in any leftover capacity, capped at delta_entries.  A peer
  // with no cursor yet (first contact) gets the full list instead.
  auto cur = sent_ver_.find(peer);
  long long cursor = cur == sent_ver_.end() ? -1 : cur->second;
  sent_ver_[peer] = ver_clock_;
  if (cursor < 0) {
    *kind = FrameKind::kFull;
    return EncodeSelf();
  }
  *kind = FrameKind::kDelta;
  size_t cap = static_cast<size_t>(cluster_->cfg().delta_entries);
  std::vector<MemberEntry> picks;
  picks.reserve(cap);
  // changed entries most-recent-first: walk the ver-ordered change
  // index from the top until the cursor or the cap — O(cap log N) per
  // peer where the members_ scan + sort was O(N log N) PER PEER (the
  // full-list arm encodes once per round; this path runs fanout times)
  for (auto it = changed_log_.rbegin();
       it != changed_log_.rend() && it->first > cursor
       && picks.size() < cap; ++it) {
    auto mi = members_.find(it->second);
    if (mi == members_.end()) continue;
    picks.push_back(MemberEntry{mi->first, mi->second.hb, mi->second.ts});
  }
  if (picks.size() < cap && addr_ring_.size() > picks.size()) {
    // round-robin refresh of the stable tail (ring order by address)
    size_t nall = addr_ring_.size();
    size_t taken = 0;
    for (size_t k = 0; k < nall && picks.size() < cap; ++k) {
      const std::string& a = addr_ring_[(refresh_pos_ + k) % nall];
      bool dup = false;
      for (const auto& p : picks)
        if (p.addr == a) {
          dup = true;
          break;
        }
      if (!dup) {
        auto mi = members_.find(a);
        if (mi != members_.end())
          picks.push_back(MemberEntry{a, mi->second.hb, mi->second.ts});
      }
      taken = k + 1;
    }
    refresh_pos_ = (refresh_pos_ + taken) % nall;
  }
  return EncodeDelta(picks);
}

void Node::PushRefresh(const std::string& peer, bool anti_entropy,
                       std::string& full_msg) {
  if (anti_entropy) {
    if (cluster_->cfg().delta) {
      // a full list covers everything: advance this peer's cursor
      sent_ver_[peer] = ver_clock_;
    }
    Send(peer, full_msg, FrameKind::kFull);
    return;
  }
  if (sent_ver_.find(peer) == sent_ver_.end()) {
    // first contact gets the full list; encode it lazily ONCE per tick
    // and reuse across all cursor-less peers this round — with fanout
    // peers drawn per round, first contacts dominate the early rounds
    // and a per-peer EncodeSelf is an O(N) tax the full-list arm
    // never pays
    if (full_msg.empty()) full_msg = EncodeSelf();
    sent_ver_[peer] = ver_clock_;
    Send(peer, full_msg, FrameKind::kFull);
    return;
  }
  FrameKind kind = FrameKind::kDelta;
  std::string msg = EncodeDeltaFor(peer, &kind);
  Send(peer, msg, kind);
}

void Node::HandleDatagram(const std::string& payload) {
  if (!alive()) return;
  double now = MonotonicNow();
  if (auto ctrl = DecodeControl(payload)) {
    // @gfs:verb JOIN
    if (ctrl->verb == "JOIN") {
      AddMember(ctrl->arg, now);
      // @gfs:verb LEAVE
      // @gfs:verb REMOVE
    } else if (ctrl->verb == "LEAVE" || ctrl->verb == "REMOVE") {
      RemoveMember(ctrl->arg, now);
      // @gfs:verb SUSPECT
    } else if (ctrl->verb == "SUSPECT") {
      OnSuspect(ctrl->arg, now);
      // @gfs:verb REFUTE
    } else if (ctrl->verb == "REFUTE") {
      OnRefute(ctrl->arg, now);
    }
    return;
  }
  if (IsDelta(payload)) {
    // delta frame: strip the marker and run the SAME hardened per-entry
    // max-merge — a truncated or replayed delta degrades to a smaller
    // merge, never a protocol error (udp.py handle() mirrors this
    // dispatch order: control verb, then delta mark, then full list)
    Merge(DecodeDelta(payload), now);
    return;
  }
  Merge(DecodeMembers(payload), now);
}

// -- suspicion wire verbs (SWIM suspect/refute; the same protocol the
// asyncio engine speaks — detector/udp.py _on_suspect/_on_refute) ------------

bool Node::Degraded() const {
  const Config& cfg = cluster_->cfg();
  return cfg.lh_multiplier > 0 &&
         static_cast<double>(suspects_.size()) >
             cfg.lh_frac * static_cast<double>(members_.size());
}

void Node::OnSuspect(const std::string& addr, double now) {
  const Config& cfg = cluster_->cfg();
  if (cfg.t_suspect <= 0) return;
  if (addr == addr_) {
    // the suspect is ME: refute by INCARNATION BUMP — advance my own
    // counter past whatever the suspicion was based on and broadcast a
    // REFUTE carrying it.  One bump + one broadcast per period answers
    // the whole episode (k suspectors each broadcast to everyone, so
    // k*(N-1) copies land here).
    auto me = members_.find(addr_);
    if (me == members_.end()) return;
    // @gfs:rate_limit refute_broadcast
    if (now - last_refute_t_ < cfg.period) return;
    last_refute_t_ = now;
    me->second.hb += 1;
    me->second.ts = now;
    Stamp(me->second, addr_);
    std::string msg = EncodeControl(
        addr_ + kFieldSep + std::to_string(me->second.hb), "REFUTE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg, FrameKind::kControl);
  } else if (members_.find(addr) != members_.end()) {
    // adopt a peer-disseminated suspicion: start the timer, uncounted
    // (runtime.py::adopt — local freshness discards it at the next tick)
    suspects_.emplace(addr, now);
  }
}

void Node::OnRefute(const std::string& arg, double now) {
  // "addr<#INFO#>hb<CMD>REFUTE": the suspect's alive message.  Adopt the
  // bumped incarnation, stamp fresh, cancel any pending suspicion; a
  // fail-listed entry is NOT resurrected (cooldown suppression wins).
  size_t pos = arg.find(kFieldSep);
  std::string addr = pos == std::string::npos ? arg : arg.substr(0, pos);
  long long hb = 0;
  if (pos != std::string::npos) {
    const std::string hb_text = arg.substr(pos + sizeof(kFieldSep) - 1);
    char* end = nullptr;
    hb = std::strtoll(hb_text.c_str(), &end, 10);
    if (end == hb_text.c_str()) hb = 0;
  }
  auto it = members_.find(addr);
  if (it == members_.end()) return;
  if (hb > it->second.hb) {
    it->second.hb = hb;
    Stamp(it->second, addr);
  }
  it->second.ts = now;
  if (suspects_.erase(addr)) {
    sus_refutations_ += 1;
    // @gfs:transition SUSPECT->MEMBER guard=refute_evidence
    cluster_->ObsEmit("refute", idx_, addr, "");
  }
}

void Node::AddMember(const std::string& addr, double now) {
  // introducer path: append at hb=0, push the full list to every member
  // (addNewMember, slave.go:250-274)
  // @gfs:transition UNKNOWN->MEMBER guard=join_or_merge_add
  if (members_.find(addr) == members_.end()) {
    Member& m = members_[addr] = Member{0, now};
    Stamp(m, addr);
    RingInsert(addr);
  }
  std::string msg = EncodeSelf();
  for (const auto& [peer, m] : members_)
    if (peer != addr_) Send(peer, msg, FrameKind::kFull);
}

void Node::RemoveMember(const std::string& addr, double now) {
  auto it = members_.find(addr);
  if (it == members_.end()) return;
  if (fail_list_.find(addr) == fail_list_.end()) {
    // faithful mode keeps the entry's (stale) timestamp on the fail list
    // (removeMember appends the live struct, slave.go:276-286);
    // fresh_cooldown stamps removal time for a real suppression window
    fail_list_[addr] = cluster_->cfg().fresh_cooldown ? now : it->second.ts;
    // @gfs:transition MEMBER->FAILED guard=leave_or_remove
    cluster_->ObsEmit("remove", idx_, addr, "");
  }
  if (it->second.ver > 0) changed_log_.erase(it->second.ver);
  RingErase(addr);
  members_.erase(it);
  // removed for any reason (LEAVE, a peer's REMOVE, a confirm): forget
  // the pending suspicion uncounted (runtime.py::drop)
  suspects_.erase(addr);
}

void Node::Merge(const std::vector<MemberEntry>& remote, double now) {
  // anti-entropy max-merge with LOCAL re-stamping (slave.go:414-440)
  for (const auto& entry : remote) {
    auto it = members_.find(entry.addr);
    if (it != members_.end()) {
      if (entry.hb > it->second.hb) {
        it->second.hb = entry.hb;
        it->second.ts = now;
        Stamp(it->second, entry.addr);
        if (suspects_.erase(entry.addr)) {
          // refute-by-advance: a fresher counter observed while SUSPECT
          // cancels the pending failure (runtime.py::refute)
          sus_refutations_ += 1;
          // @gfs:transition SUSPECT->MEMBER guard=refute_evidence
          cluster_->ObsEmit("refute", idx_, entry.addr, "");
        }
      } else if (cluster_->cfg().delta && entry.hb == it->second.hb &&
                 entry.ts > it->second.ts) {
        // delta mode only: freshness rides the wire on EQUAL counters.
        // Bounded frames break the full-list assumption that every
        // round max-merges 16 fresh draws — after a synchronized
        // anti-entropy round most nodes hold the SAME hb for an entry,
        // so the next full push carries no advance and the local-stamp
        // rule leaves ts aging toward t_fail on a QUIET cluster (the
        // n=1024 staleness storm).  Max-merging the wire ts on equal
        // hb closes it without breaking crash detection: a live node
        // keeps stamping fresh ts into its own pushes, while a crashed
        // node's copies converge to a constant max and staleness still
        // grows globally.  Clamped to now so a forged future ts cannot
        // suppress detection; full-list mode stays bit-identical.
        it->second.ts = std::min(entry.ts, now);
      }
      // @gfs:transition UNKNOWN->MEMBER guard=join_or_merge_add
    } else if (fail_list_.find(entry.addr) == fail_list_.end()) {
      Member& m = members_[entry.addr] = Member{entry.hb, now};
      Stamp(m, entry.addr);
      RingInsert(entry.addr);
    }
  }
}

void Node::Tick(double now) {
  if (!alive()) return;
  const Config& cfg = cluster_->cfg();
  if (static_cast<int>(members_.size()) < cfg.min_group) {
    for (auto& [addr, m] : members_) m.ts = now;  // refresh-only
    return;
  }
  auto self = members_.find(addr_);
  if (self != members_.end()) {
    self->second.hb += 1;
    self->second.ts = now;
    Stamp(self->second, addr_);
  }
  // failure detection (slave.go:460-482).  With suspicion armed
  // (cfg.t_suspect > 0) a stale member passes through SUSPECT first:
  // the first stale tick broadcasts SUSPECT (so the subject can
  // actively refute by incarnation bump — OnSuspect), and only the
  // SUSPECT->FAILED window — t_suspect periods, stretched by the
  // Lifeguard local-health multiplier while this observer is degraded —
  // confirms the removal.  Mirrors detector/udp.py UdpNode.tick /
  // suspicion/runtime.py exactly.
  double t_fail = cfg.t_fail * cfg.period;
  bool sus = cfg.t_suspect > 0;
  std::vector<std::string> newly_suspect;
  std::vector<std::string> failed;
  for (const auto& [addr, m] : members_) {
    if (addr == addr_) continue;
    bool stale = m.hb > 1 && m.ts < now - t_fail;
    if (!stale) {
      // a genuinely-refuted suspicion was already popped (and counted)
      // by Merge/OnRefute when the fresh evidence arrived; anything
      // left here is a peer-disseminated adoption for an entry that
      // was never stale locally — clear it WITHOUT counting
      if (sus) suspects_.erase(addr);
      continue;
    }
    if (sus) {
      auto it = suspects_.find(addr);
      if (it == suspects_.end()) {
        suspects_[addr] = now;
        sus_entered_ += 1;
        newly_suspect.push_back(addr);
        continue;
      }
      // the stretched window is recomputed PER MEMBER, like the udp
      // engine's rt.t_suspect_window call: suspicions entered earlier
      // in this same tick count toward this member's degraded bit, so
      // a mass-suspicion tick stretches the window for the members
      // examined after the lh_frac crossing
      int mult = 1 + (Degraded() ? cfg.lh_multiplier : 0);
      double window = cfg.t_suspect * mult * cfg.period;
      if (!(now - it->second > window)) {
        // periodic re-notification (SWIM re-gossips suspicion): the
        // original SUSPECT may have been sent into a fault window — a
        // rack outage drops it, so the subject never learns and the
        // post-heal refute wave would ride passive list gossip alone,
        // leaking a heal-race FP tail (~100 FPs at n=256, measured).
        // One subject-only datagram per suspect per tick triggers the
        // active incarnation-bump refute the moment the subject is
        // reachable again; the REFUTE broadcast is rate-limited on the
        // subject's side, so k re-notifiers cost one bump per period.
        Send(addr, EncodeControl(addr, "SUSPECT"), FrameKind::kControl);
        continue;
      }
      suspects_.erase(it);
      sus_confirms_ += 1;
    }
    failed.push_back(addr);
  }
  for (const auto& addr : newly_suspect) {
    // @gfs:transition MEMBER->SUSPECT guard=stale
    cluster_->ObsEmit("suspect", idx_, addr, "");
    std::string msg = EncodeControl(addr, "SUSPECT");
    // @gfs:dissemination new_suspect profile=campaign bound=subject+fanout
    if (cfg.push_random) {
      // campaign profile: bounded dissemination — the SUBJECT always
      // hears (its active incarnation-bump refute is the point) plus
      // fanout random peers, O(fanout) per new suspicion like every
      // other push in this mode.  The reference-faithful all-peers
      // broadcast below is O(suspects x N) per round: at n=256 a rack
      // outage makes ~250 observers suspect 8 nodes in ONE tick —
      // ~500k synchronous sendtos that stall the epoll thread for
      // seconds, go-stale everything, and storm the cluster by
      // ENGINE physics, not protocol (measured: 26 s tick, 73k FPs).
      Send(addr, msg, FrameKind::kControl);
      std::vector<const std::string*> peers;
      peers.reserve(members_.size());
      for (const auto& [peer, m] : members_)
        if (peer != addr_ && peer != addr) peers.push_back(&peer);
      int k = std::min<int>(cfg.fanout, static_cast<int>(peers.size()));
      for (int i = 0; i < k; ++i) {
        int j = i + static_cast<int>(NextRand() % (peers.size() - i));
        std::swap(peers[i], peers[j]);
        Send(*peers[i], msg, FrameKind::kControl);
      }
    } else {
      // ring mode: the asyncio engine's wire behavior verbatim (the
      // small-n udp-parity lane compares event sequences)
      // @gfs:dissemination new_suspect profile=reference bound=all_peers
      for (const auto& [peer, m] : members_)
        if (peer != addr_) Send(peer, msg, FrameKind::kControl);
    }
  }
  for (const auto& addr : failed) {
    // detection first, then the removal it causes — the same
    // confirm -> remove causal order every engine's events carry
    cluster_->RecordDetection(idx_, addr);
    RemoveMember(addr, now);
    if (cfg.remove_broadcast) {
      std::string msg = EncodeControl(addr, "REMOVE");
      for (const auto& [peer, m] : members_)
        if (peer != addr_) Send(peer, msg, FrameKind::kControl);
    }
  }
  // fail-list cooldown expiry (slave.go:484-497)
  // @gfs:transition FAILED->UNKNOWN guard=cooldown_expiry
  double t_cool = cfg.t_cooldown * cfg.period;
  for (auto it = fail_list_.begin(); it != fail_list_.end();) {
    if (it->second < now - t_cool)
      it = fail_list_.erase(it);
    else
      ++it;
  }
  if (members_.find(addr_) == members_.end()) return;  // removed-self
  // membership refresh push.  Delta mode (protocol_spec
  // membership_refresh/delta, round 20): every anti_entropy_every-th
  // cluster round — all stripes tick on the same round clock — pushes
  // the FULL list so a lost delta can never wedge convergence (Pittel's
  // bound stays the reconvergence oracle); every other round sends a
  // bounded per-peer delta frame (EncodeDeltaFor: changed-first, rr
  // tail, capped).
  // @gfs:dissemination membership_refresh profile=delta bound=changed+rr_tail+capped
  bool anti_entropy =
      !cfg.delta || (cluster_->round_.load() % cfg.anti_entropy_every == 0);
  std::string msg = anti_entropy ? EncodeSelf() : std::string();
  if (cfg.push_random) {
    // campaign/north-star push topology: fanout random listed peers per
    // tick (the tensor engine's topology='random' — event propagation
    // in O(log N) rounds instead of the ring's O(N) position walk)
    std::vector<const std::string*> peers;
    peers.reserve(members_.size());
    for (const auto& [addr, m] : members_)
      if (addr != addr_) peers.push_back(&addr);
    int k = std::min<int>(cfg.fanout, static_cast<int>(peers.size()));
    // partial Fisher-Yates: first k entries are a uniform sample
    for (int i = 0; i < k; ++i) {
      int j = i + static_cast<int>(NextRand() % (peers.size() - i));
      std::swap(peers[i], peers[j]);
      PushRefresh(*peers[i], anti_entropy, msg);
    }
    return;
  }
  // ring push to sorted list positions self-1, self+1, self+2
  // (slave.go:515-542); std::map iteration order == sorted addresses
  std::vector<const std::string*> ordered;
  ordered.reserve(members_.size());
  for (const auto& [addr, m] : members_) ordered.push_back(&addr);
  int n = static_cast<int>(ordered.size());
  int self_i = 0;
  for (int i = 0; i < n; ++i)
    if (*ordered[i] == addr_) self_i = i;
  for (int off : {-1, 1, 2}) {
    const std::string& peer = *ordered[((self_i + off) % n + n) % n];
    if (peer != addr_) PushRefresh(peer, anti_entropy, msg);
  }
}

void Node::StopGraceful() {
  if (alive()) {
    std::string msg = EncodeControl(addr_, "LEAVE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg, FrameKind::kControl);
  }
  alive_.store(false);
}

std::vector<std::string> Node::MemberAddrs() const {
  std::vector<std::string> out;
  out.reserve(members_.size());
  for (const auto& [addr, m] : members_) out.push_back(addr);
  return out;
}

std::vector<std::string> Node::SuspectAddrs() const {
  std::vector<std::string> out;
  out.reserve(suspects_.size());
  for (const auto& [addr, t] : suspects_) out.push_back(addr);
  return out;
}

long long Node::HbOf(const std::string& addr) const {
  auto it = members_.find(addr);
  return it == members_.end() ? -1 : it->second.hb;
}

// ---------------------------------------------------------------------------
// Cluster

bool Cluster::Start() {
  MutexLock ctl(ctl_mu_);
  if (running_.load()) return false;
  for (auto& s : stripes_) {
    s->epoll_fd_ = ::epoll_create1(0);
    if (s->epoll_fd_ < 0) return false;
  }
  for (auto& node : nodes_) {
    if (!node->Open()) return false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = static_cast<uint32_t>(node->idx());
    ::epoll_ctl(StripeOf(node->idx())->epoll_fd_, EPOLL_CTL_ADD, node->fd(),
                &ev);
  }
  // everyone joins through the introducer (slave.go:288-308); the JOIN
  // datagrams sit in socket buffers until the stripe threads start
  const std::string intro_addr = nodes_[cfg_.introducer]->addr();
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      nodes_[id]->AssertLockHeld();
      nodes_[id]->ResetState();
    }
  }
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      if (node->idx() != cfg_.introducer)
        node->Send(intro_addr, EncodeControl(node->addr(), "JOIN"),
                   FrameKind::kControl);
    }
  }
  round_.store(0);
  tick_starters_.store(0);
  tick_arrivals_.store(0);
  for (auto& s : stripes_) s->done_round_ = 0;
  next_tick_.store(MonotonicNow() + cfg_.period);
  running_ = true;
  for (auto& s : stripes_) {
    Stripe* sp = s.get();
    s->thread_ = std::thread([this, sp] {
      while (running_) StripeBody(sp);
    });
  }
  return true;
}

void Cluster::StripeBody(Stripe* s) {
  epoll_event events[64];
  double now = MonotonicNow();
  double wait_s = next_tick_.load() - now;
  int timeout_ms = wait_s > 0 ? static_cast<int>(wait_s * 1000) + 1 : 0;
  if (s->done_round_ != round_.load()) {
    // this stripe already ticked the current round and is waiting for
    // the barrier winner to publish the next one: keep draining
    // datagrams on a short poll instead of busy-spinning
    timeout_ms = 1;
  }
  int nfds = ::epoll_wait(s->epoll_fd_, events, 64, std::min(timeout_ms, 50));
  bool ticked = false;
  {
    MutexLock lk(s->mu_);
    char buf[65536];
    for (int e = 0; e < nfds; ++e) {
      Node* node = nodes_[events[e].data.u32].get();
      node->AssertLockHeld();
      while (true) {
        ssize_t len = ::recv(node->fd(), buf, sizeof(buf), 0);
        if (len <= 0) break;
        node->HandleDatagram(std::string(buf, static_cast<size_t>(len)));
      }
    }
    now = MonotonicNow();
    if (now >= next_tick_.load() && s->done_round_ == round_.load()) {
      if (tick_starters_.fetch_add(1) == 0) tick_t0_.store(now);
      for (int id : s->node_ids_) {
        Node* node = nodes_[id].get();
        node->AssertLockHeld();
        node->Tick(now);
      }
      s->done_round_ = round_.load() + 1;
      ticked = true;
    }
  }
  if (!ticked) return;
  // tick barrier: the LAST stripe to arrive owns the round roll-over.
  // It emits the round_tick (locking stripes one at a time — no stripe
  // lock is ever held while taking another, so the order is deadlock-
  // free by construction), advances the shared deadline, resets the
  // barrier counters, and publishes round_+1 LAST — no stripe can
  // re-enter its tick until the new round is visible, so a double-tick
  // is impossible.
  if (tick_arrivals_.fetch_add(1) + 1 != static_cast<int>(stripes_.size()))
    return;
  double tick_ms = (MonotonicNow() - tick_t0_.load()) * 1000.0;
  if (obs_enabled_.load()) EmitRoundTick(tick_ms);
  double nt = next_tick_.load() + cfg_.period;
  double now2 = MonotonicNow();
  if (nt < now2) nt = now2 + cfg_.period;  // fell behind
  next_tick_.store(nt);
  tick_starters_.store(0);
  tick_arrivals_.store(0);
  round_.fetch_add(1);
}

void Cluster::EmitRoundTick(double tick_ms) {
  // one round_tick per completed protocol round — the ground truth this
  // in-process engine KNOWS (nodes_[i]->alive()): n_alive plus the
  // round's detection/false-positive deltas, so a recorded native
  // stream feeds the streaming monitor's rolling-FPR invariant exactly
  // like a tensor or udp trace.  Native extras ride the same detail:
  // members_listed (sum of live view sizes), sends (datagrams that
  // left a socket this round) and tick_ms (wall-clock cost of the tick
  // pass — the per-round latency histogram's sample).  The suspicion
  // counters appear only when armed (the n/a-not-0 inference rule);
  // fp_suppressed stays absent (per-refute ground truth is sim-only).
  int n_alive = 0;
  long long members_listed = 0;
  long long sus_entered = 0, sus_refut = 0, sus_now = 0;
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      if (node->alive()) {
        n_alive += 1;
        members_listed += static_cast<long long>(node->members_.size());
        sus_now += static_cast<long long>(node->suspects_.size());
      }
      sus_entered += node->sus_entered_;
      sus_refut += node->sus_refutations_;
    }
  }
  long long det = det_total_.load();
  long long fp = fp_total_.load();
  long long sends = sends_total_.load();
  MutexLock ob(obs_mu_);
  long long det_d = det - obs_det0_;
  long long fp_d = fp - obs_fp0_;
  std::ostringstream d;
  d << "n_alive=" << n_alive << " true_detections=" << (det_d - fp_d)
    << " false_positives=" << fp_d << " members_listed=" << members_listed
    << " sends=" << (sends - obs_sends0_) << " tick_ms="
    << std::fixed << std::setprecision(3) << tick_ms;
  if (cfg_.t_suspect > 0) {
    d << " suspects_entered=" << (sus_entered - obs_sus_entered0_)
      << " refutations=" << (sus_refut - obs_refut0_)
      << " suspects_now=" << sus_now;
  }
  obs_det0_ = det;
  obs_fp0_ = fp;
  obs_sends0_ = sends;
  obs_sus_entered0_ = sus_entered;
  obs_refut0_ = sus_refut;
  ObsEmitLocked("round_tick", -1, -1, d.str());
}

void Cluster::Stop() {
  running_.store(false);
  for (auto& s : stripes_) {
    if (s->thread_.joinable()) s->thread_.join();
    if (s->epoll_fd_ >= 0) {
      ::close(s->epoll_fd_);
      s->epoll_fd_ = -1;
    }
  }
  for (auto& node : nodes_) node->Close();
}

void Cluster::Crash(int i) {
  MutexLock lk(StripeOf(i)->mu_);
  nodes_[i]->AssertLockHeld();
  nodes_[i]->StopCrash();
  // ground truth stamped at the injection seam: a dead process bumps
  // nothing, so the hb_freeze rides along (the tensor decode's pairing)
  // @gfs:inject crash
  ObsEmit("crash", -1, i, "scheduled=1");
  // @gfs:inject hb_freeze
  ObsEmit("hb_freeze", -1, i, "");
}

void Cluster::Leave(int i) {
  MutexLock lk(StripeOf(i)->mu_);
  nodes_[i]->AssertLockHeld();
  nodes_[i]->StopGraceful();
  // @gfs:inject leave
  ObsEmit("leave", -1, i, "");
}

void Cluster::Join(int i) {
  MutexLock lk(StripeOf(i)->mu_);
  Node* node = nodes_[i].get();
  node->AssertLockHeld();
  if (!node->alive()) node->ResetState();
  // JOIN to the introducer; lost if the introducer is down (SPOF kept,
  // slave.go:22)
  node->Send(nodes_[cfg_.introducer]->addr(),
             EncodeControl(node->addr(), "JOIN"), FrameKind::kControl);
  // @gfs:inject join
  ObsEmit("join", -1, i, "");
}

void Cluster::Advance(int rounds) {
  int target = round_.load() + rounds;
  while (running_) {
    if (round_.load() >= target) return;
    std::this_thread::sleep_for(
        std::chrono::duration<double>(cfg_.period / 4));
  }
}

int Cluster::Membership(int observer, int* out, int cap) {
  MutexLock lk(StripeOf(observer)->mu_);
  std::vector<int> ids;
  nodes_[observer]->AssertLockHeld();
  for (const auto& addr : nodes_[observer]->MemberAddrs()) {
    int idx = IdxOf(addr);
    if (idx >= 0) ids.push_back(idx);
  }
  std::sort(ids.begin(), ids.end());
  int n = std::min(static_cast<int>(ids.size()), cap);
  std::copy(ids.begin(), ids.begin() + n, out);
  return n;
}

int Cluster::Suspects(int observer, int* out, int cap) {
  MutexLock lk(StripeOf(observer)->mu_);
  std::vector<int> ids;
  nodes_[observer]->AssertLockHeld();
  for (const auto& addr : nodes_[observer]->SuspectAddrs()) {
    int idx = IdxOf(addr);
    if (idx >= 0) ids.push_back(idx);
  }
  std::sort(ids.begin(), ids.end());
  int n = std::min(static_cast<int>(ids.size()), cap);
  std::copy(ids.begin(), ids.begin() + n, out);
  return n;
}

long long Cluster::Incarnation(int observer, int subject) {
  MutexLock lk(StripeOf(observer)->mu_);
  nodes_[observer]->AssertLockHeld();
  return nodes_[observer]->HbOf(nodes_[subject]->addr());
}

int Cluster::AliveNodes(int* out, int cap) {
  // ground-truth aliveness is atomic per node: no locks needed
  int count = 0;
  for (const auto& node : nodes_) {
    if (node->alive() && count < cap) out[count++] = node->idx();
  }
  return count;
}

int Cluster::DrainEvents(int* out, int cap) {
  MutexLock lk(events_mu_);
  int n = std::min(static_cast<int>(events_.size()), cap / 4);
  for (int i = 0; i < n; ++i) {
    out[i * 4 + 0] = events_[i].round;
    out[i * 4 + 1] = events_[i].observer;
    out[i * 4 + 2] = events_[i].subject;
    out[i * 4 + 3] = events_[i].false_positive;
  }
  events_.erase(events_.begin(), events_.begin() + n);
  return n;
}

// ---------------------------------------------------------------------------
// round-16 control/observation surface

int Cluster::Configure(const std::string& kv) {
  MutexLock lk(ctl_mu_);
  if (running_.load()) return -1;  // knobs are fixed once the loops run
  std::istringstream in(kv);
  std::string tok;
  while (in >> tok) {
    size_t eq = tok.find('=');
    if (eq == std::string::npos) return -1;
    const std::string key = tok.substr(0, eq);
    const std::string val = tok.substr(eq + 1);
    char* end = nullptr;
    if (key == "push") {
      if (val != "ring" && val != "random") return -1;
      cfg_.push_random = (val == "random");
    } else if (key == "fanout") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 1) return -1;
      cfg_.fanout = static_cast<int>(v);
    } else if (key == "remove_broadcast") {
      cfg_.remove_broadcast = val != "0";
    } else if (key == "t_suspect") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 0) return -1;
      cfg_.t_suspect = static_cast<int>(v);
    } else if (key == "lh_multiplier") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 0) return -1;
      cfg_.lh_multiplier = static_cast<int>(v);
    } else if (key == "lh_frac") {
      double v = std::strtod(val.c_str(), &end);
      if (end == val.c_str() || *end != '\0' || !(v > 0.0 && v < 1.0))
        return -1;
      cfg_.lh_frac = v;
    } else if (key == "delta") {
      cfg_.delta = val != "0";
    } else if (key == "delta_entries") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 1) return -1;
      cfg_.delta_entries = static_cast<int>(v);
    } else if (key == "anti_entropy_every") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 1) return -1;
      cfg_.anti_entropy_every = static_cast<int>(v);
    } else if (key == "loops") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 1 || v > 64) return -1;
      cfg_.loops = static_cast<int>(v);
      RebuildStripes(cfg_.loops);
    } else {
      return -1;
    }
  }
  // the DELTA_GOSSIP cadence constraint (see Config): an anti-entropy
  // gap at or past the detection window could manufacture staleness —
  // reject it, exactly like UdpCluster's ValueError
  if (cfg_.delta && cfg_.anti_entropy_every >= cfg_.t_fail) return -1;
  return 0;
}

void Cluster::ObsEmitLocked(const char* kind, int observer, int subject,
                            const std::string& detail) {
  std::ostringstream line;
  line << kind << ' ' << (round_.load() - obs_round0_) << ' ' << observer
       << ' ' << subject;
  if (!detail.empty()) line << ' ' << detail;
  line << '\n';
  obs_buf_ += line.str();
}

void Cluster::ObsEmit(const char* kind, int observer, int subject,
                      const std::string& detail) {
  if (!obs_enabled_.load(std::memory_order_acquire)) return;
  MutexLock lk(obs_mu_);
  ObsEmitLocked(kind, observer, subject, detail);
}

void Cluster::ObsEmit(const char* kind, int observer,
                      const std::string& subject_addr,
                      const std::string& detail) {
  if (!obs_enabled_.load(std::memory_order_acquire)) return;
  ObsEmit(kind, observer, IdxOf(subject_addr), detail);
}

int Cluster::ObsEnable() {
  // gather the suspicion baselines stripe by stripe (stripe locks come
  // before the obs leaf in the lock order)
  long long e = 0, r = 0;
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      e += node->sus_entered_;
      r += node->sus_refutations_;
    }
  }
  int round = round_.load();
  MutexLock ob(obs_mu_);
  // rebase the stamped round clock to 0 and zero the per-round deltas:
  // the recorded stream lives in the arming-relative frame the udp
  // campaign runner's streams use (its cluster clock starts at 0)
  obs_round0_ = round;
  obs_det0_ = det_total_.load();
  obs_fp0_ = fp_total_.load();
  obs_sends0_ = sends_total_.load();
  obs_sus_entered0_ = e;
  obs_refut0_ = r;
  obs_enabled_.store(true, std::memory_order_release);
  return round;
}

int Cluster::ObsDrain(char* out, int cap) {
  MutexLock lk(obs_mu_);
  if (obs_buf_.empty() || cap <= 1) return 0;
  size_t take = obs_buf_.size();
  if (take > static_cast<size_t>(cap - 1)) {
    // drain whole lines only: find the last newline that fits
    size_t nl = obs_buf_.rfind('\n', static_cast<size_t>(cap - 2));
    if (nl == std::string::npos) return -1;  // one line > cap: grow buffer
    take = nl + 1;
  }
  std::memcpy(out, obs_buf_.data(), take);
  out[take] = '\0';
  obs_buf_.erase(0, take);
  return static_cast<int>(take);
}

std::string Cluster::VitalsText() {
  int n_alive = 0;
  long long sus_now = 0, entered = 0, refut = 0, confirms = 0;
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      if (node->alive()) {
        n_alive += 1;
        sus_now += static_cast<long long>(node->suspects_.size());
      }
      entered += node->sus_entered_;
      refut += node->sus_refutations_;
      confirms += node->sus_confirms_;
    }
  }
  std::ostringstream os;
  AppendVital(os, "round", round_.load());
  AppendVital(os, "n_alive", n_alive);
  AppendVital(os, "detections", det_total_.load());
  AppendVital(os, "false_positives", fp_total_.load());
  AppendVital(os, "bytes_sent", bytes_total_.load());
  AppendVital(os, "frames_full", frames_full_.load());
  AppendVital(os, "frames_delta", frames_delta_.load());
  if (cfg_.t_suspect > 0) {
    AppendVital(os, "suspects_now", sus_now);
    AppendVital(os, "suspects_entered", entered);
    AppendVital(os, "refutations", refut);
    AppendVital(os, "confirms", confirms);
  }
  return os.str();
}

int Cluster::ScenarioLoad(const std::string& table, int round0) {
  GateTable g;
  std::istringstream in(table);
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    std::istringstream ls(line);
    std::string kind;
    ls >> kind;
    if (kind == "name") {
      ls >> g.name;
      continue;
    }
    int start = 0, end = 0;
    if (!(ls >> start >> end) || start < 0 || end <= start) return -1;
    g.horizon = std::max(g.horizon, end);
    auto read_mask = [&](std::vector<char>& mask) -> bool {
      mask.assign(cfg_.n, 0);
      int id = 0;
      bool any = false;
      while (ls >> id) {
        if (id < 0 || id >= cfg_.n) return false;
        mask[id] = 1;
        any = true;
      }
      return any;
    };
    if (kind == "flap") {
      GateFlap f;
      f.start = start;
      f.end = end;
      if (!(ls >> f.up >> f.down) || f.up < 1 || f.down < 1) return -1;
      if (!read_mask(f.mask)) return -1;
      g.flaps.push_back(std::move(f));
    } else if (kind == "outage") {
      GateOutage o;
      o.start = start;
      o.end = end;
      if (!read_mask(o.mask)) return -1;
      g.outages.push_back(std::move(o));
    } else if (kind == "slow") {
      GateSlow s;
      s.start = start;
      s.end = end;
      if (!(ls >> s.stride) || s.stride < 2) return -1;
      if (!read_mask(s.mask)) return -1;
      g.slows.push_back(std::move(s));
    } else if (kind == "partition") {
      GatePartition p;
      p.start = start;
      p.end = end;
      p.pid.reserve(cfg_.n);
      int pid = 0;
      while (ls >> pid) p.pid.push_back(pid);
      if (static_cast<int>(p.pid.size()) != cfg_.n) return -1;
      g.partitions.push_back(std::move(p));
    } else {
      return -1;
    }
  }
  const std::string name = g.name.empty() ? std::string("scenario") : g.name;
  const int horizon = g.horizon;
  {
    MutexLock lk(gates_mu_);
    gates_ = std::move(g);
    scn_round0_ = round0;
    gates_armed_.store(true, std::memory_order_release);
  }
  ObsEmit("scenario_arm", -1, -1,
          "name=" + name + " horizon=" + std::to_string(horizon));
  return 0;
}

void Cluster::ScenarioClear() {
  if (gates_armed_.exchange(false)) ObsEmit("scenario_clear", -1, -1, "");
}

bool Cluster::ScenarioDrops(int src, const std::string& dst_addr) const {
  // ScenarioRuntime.drops, minus Bernoulli loss (rejected at compile
  // time by native.py): called from Node::Send with the sender's stripe
  // lock held — the gate table is its own leaf, armed bit the fast path
  if (!gates_armed_.load(std::memory_order_acquire)) return false;
  MutexLock lk(gates_mu_);
  int r = round_.load() - scn_round0_;
  for (const auto& f : gates_.flaps) {
    if (f.mask[src] && f.start <= r && r < f.end &&
        (r - f.start) % (f.up + f.down) >= f.up)
      return true;
  }
  auto dst_it = addr_to_idx_.find(dst_addr);
  int dst = dst_it == addr_to_idx_.end() ? -1 : dst_it->second;
  for (const auto& o : gates_.outages) {
    if (o.start <= r && r < o.end &&
        (o.mask[src] || (dst >= 0 && o.mask[dst])))
      return true;
  }
  for (const auto& p : gates_.partitions) {
    if (p.start <= r && r < p.end && dst >= 0 && p.pid[src] != p.pid[dst])
      return true;
  }
  for (const auto& s : gates_.slows) {
    if (s.mask[src] && s.start <= r && r < s.end && r % s.stride != 0)
      return true;
  }
  return false;
}

void Cluster::SeedFull() {
  double now = MonotonicNow();
  std::vector<std::string> addrs;
  addrs.reserve(nodes_.size());
  for (const auto& node : nodes_) addrs.push_back(node->addr());
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      if (node->alive()) node->SeedMembers(addrs, now);
    }
  }
}

int Cluster::Warm() {
  for (auto& s : stripes_) {
    MutexLock lk(s->mu_);
    for (int id : s->node_ids_) {
      Node* node = nodes_[id].get();
      node->AssertLockHeld();
      if (!node->alive()) continue;
      // full view with every counter past the hb<=1 grace — and NO churn
      // residue: a pending suspicion means some entry is already past
      // t_fail silent (it would confirm right after the caller starts
      // its run — observed as a warm-gate FP burst in the stream's first
      // rounds), and a non-empty fail list means a detection fired within
      // the cooldown window (the view only LOOKS full because the entry
      // was just re-added at a stale-prone counter)
      if (static_cast<int>(node->members_.size()) != cfg_.n) return 0;
      if (!node->suspects_.empty() || !node->fail_list_.empty()) return 0;
      for (const auto& [addr, m] : node->members_)
        if (m.hb <= 1) return 0;
    }
  }
  return 1;
}

}  // namespace
}  // namespace gossipfs

// ---------------------------------------------------------------------------
// C ABI for ctypes (gossipfs_tpu/native.py)

extern "C" {

void* gfs_cluster_create(int n, int base_port, double period_s, int t_fail,
                         int t_cooldown, int min_group, int fresh_cooldown,
                         int introducer) {
  gossipfs::Config cfg;
  cfg.n = n;
  cfg.base_port = base_port;
  cfg.period = period_s;
  cfg.t_fail = t_fail;
  cfg.t_cooldown = t_cooldown;
  cfg.min_group = min_group;
  cfg.fresh_cooldown = fresh_cooldown != 0;
  cfg.introducer = introducer;
  return new gossipfs::Cluster(cfg);
}

int gfs_cluster_start(void* h) {
  return static_cast<gossipfs::Cluster*>(h)->Start() ? 0 : -1;
}

void gfs_cluster_destroy(void* h) {
  delete static_cast<gossipfs::Cluster*>(h);
}

void gfs_crash(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Crash(i); }
void gfs_leave(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Leave(i); }
void gfs_join(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Join(i); }

void gfs_advance(void* h, int rounds) {
  static_cast<gossipfs::Cluster*>(h)->Advance(rounds);
}

int gfs_round(void* h) { return static_cast<gossipfs::Cluster*>(h)->Round(); }

int gfs_membership(void* h, int observer, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->Membership(observer, out, cap);
}

// Conformance-harness read seams (round 19): the observer's current
// suspect set and its per-entry heartbeat counter for one subject —
// the same observable surface verdict.py reads off the udp engine's
// node.rt.suspects / members[addr].hb.
int gfs_suspects(void* h, int observer, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->Suspects(observer, out, cap);
}

long long gfs_incarnation(void* h, int observer, int subject) {
  return static_cast<gossipfs::Cluster*>(h)->Incarnation(observer, subject);
}

int gfs_alive(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->AliveNodes(out, cap);
}

int gfs_drain_events(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->DrainEvents(out, cap);
}

// -- round-16 observability + campaign surface ------------------------------

// Pre-start protocol knobs ("k=v k=v ..."): push=ring|random, fanout,
// remove_broadcast, t_suspect, lh_multiplier, lh_frac, delta,
// delta_entries, anti_entropy_every, loops.  0 ok, -1 on a bad table, a
// started cluster, or delta with anti_entropy_every >= t_fail (the same
// constraint UdpCluster rejects with ValueError).
int gfs_configure(void* h, const char* kv) {
  return static_cast<gossipfs::Cluster*>(h)->Configure(kv ? kv : "");
}

// Arm event buffering and rebase the stamped round clock; returns the
// absolute engine round the stream's round 0 maps to.
int gfs_obs_enable(void* h) {
  return static_cast<gossipfs::Cluster*>(h)->ObsEnable();
}

// Drain buffered event lines ("kind round observer subject k=v ...").
// Returns bytes written (whole lines only, NUL-terminated), 0 when the
// buffer is empty, -1 when a single line exceeds cap (grow and retry).
int gfs_obs_drain(void* h, char* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->ObsDrain(out, cap);
}

// Load the fault-gate table (text form; see Cluster::ScenarioLoad),
// windows anchored at absolute round `round0`.  0 ok, -1 on parse error.
int gfs_scenario_load(void* h, const char* table, int round0) {
  return static_cast<gossipfs::Cluster*>(h)->ScenarioLoad(table ? table : "",
                                                          round0);
}

void gfs_scenario_clear(void* h) {
  static_cast<gossipfs::Cluster*>(h)->ScenarioClear();
}

void gfs_seed_full(void* h) {
  static_cast<gossipfs::Cluster*>(h)->SeedFull();
}

// Halt the epoll loop + close sockets WITHOUT destroying state: the
// buffered obs events stay drainable.  On a 1-core host a big
// gfs_obs_drain parse while the loop still runs starves the protocol
// (rounds lag -> wall-clock staleness -> a manufactured FP cascade in
// the stream's tail — observed at n=256); runners stop first, then
// drain at leisure.
void gfs_stop(void* h) { static_cast<gossipfs::Cluster*>(h)->Stop(); }

int gfs_warm(void* h) { return static_cast<gossipfs::Cluster*>(h)->Warm(); }

// Codec surface for parity tests: input lines "addr hb ts\n", output the
// wire string (and the reverse).  snprintf semantics: writes at most cap-1
// bytes + NUL and returns the FULL required length, so callers can detect
// truncation and retry with a bigger buffer.
static int CopyOut(const std::string& text, char* out, int cap) {
  int n = std::min(static_cast<int>(text.size()), cap - 1);
  if (n > 0) std::memcpy(out, text.data(), static_cast<size_t>(n));
  if (cap > 0) out[n] = '\0';
  return static_cast<int>(text.size());
}

// Uniform vitals ("k=v k=v ..." — obs.schema.VITALS_FIELDS names only;
// unknowable fields are ABSENT, rendered n/a by the Python surface).
// snprintf sizing semantics, like the codec calls below.
int gfs_vitals(void* h, char* out, int cap) {
  return CopyOut(static_cast<gossipfs::Cluster*>(h)->VitalsText(), out, cap);
}

int gfs_codec_encode(const char* lines, char* out, int cap) {
  std::vector<gossipfs::MemberEntry> entries;
  std::istringstream in(lines);
  std::string addr;
  long long hb;
  double ts;
  while (in >> addr >> hb >> ts)
    entries.push_back(gossipfs::MemberEntry{addr, hb, ts});
  return CopyOut(gossipfs::EncodeMembers(entries), out, cap);
}

int gfs_codec_decode(const char* wire, char* out, int cap) {
  auto entries = gossipfs::DecodeMembers(wire);
  std::ostringstream os;
  os << std::setprecision(17);
  for (const auto& e : entries) os << e.addr << ' ' << e.hb << ' ' << e.ts << '\n';
  return CopyOut(os.str(), out, cap);
}

}  // extern "C"
