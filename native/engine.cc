// Native gossip runtime: N protocol nodes over real localhost UDP sockets,
// driven by one epoll loop — the C++ equivalent of the reference's Go
// runtime (goroutine heartbeat driver main.go:27-33, blocking UDP receive
// loop slave/slave.go:207-248), for the BASELINE config-1 parity path.
//
// Protocol semantics mirror the reference exactly (and the Python asyncio
// twin, gossipfs_tpu/detector/udp.py):
//   - join through the introducer, which appends and pushes its full list to
//     every member (addNewMember, slave.go:250-274)
//   - per-period tick: refresh-only below min_group (slave.go:504-509), bump
//     own heartbeat, detect members with hb > 1 silent past t_fail periods
//     (slave.go:460-476), REMOVE broadcast (slave.go:338-363), fail-list
//     cooldown expiry (slave.go:484-497), then full-list push to ring
//     neighbours at sorted positions self-1, self+1, self+2 (slave.go:515-542)
//   - merge: shared members take max heartbeat + LOCAL timestamp; unknown
//     members are added unless on the fail list (slave.go:414-440)
//
// Exposed through a C ABI (extern "C") for ctypes — see gossipfs_tpu/native.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstring>
#include <iomanip>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "codec.h"

namespace gossipfs {
namespace {

double MonotonicNow() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Member {
  long long hb = 0;
  double ts = 0.0;
};

struct DetectionEvent {
  int round;
  int observer;
  int subject;
  int false_positive;
};

struct Config {
  int n = 10;
  int base_port = 19000;
  double period = 0.05;  // seconds per heartbeat round
  int t_fail = 5;        // periods of silence before declaring failure
  int t_cooldown = 5;    // fail-list suppression periods
  int min_group = 4;     // below this size: refresh-only
  bool fresh_cooldown = false;  // stamp fail-list entries at removal time
  int introducer = 0;
};

class Cluster;

class Node {
 public:
  Node(Cluster* cluster, int idx, int port);
  ~Node() { Close(); }

  bool Open();   // bind the UDP socket
  void Close();

  void HandleDatagram(const std::string& payload);
  void Tick(double now);
  void StopGraceful();  // LEAVE broadcast then die
  void StopCrash();     // silent death (CTRL+C)
  void ResetState();    // fresh process state for a rejoin

  int fd() const { return fd_; }
  int idx() const { return idx_; }
  bool alive() const { return alive_; }
  const std::string& addr() const { return addr_; }
  std::vector<std::string> MemberAddrs() const;

 private:
  void Send(const std::string& peer_addr, const std::string& msg);
  void AddMember(const std::string& addr, double now);   // introducer path
  void RemoveMember(const std::string& addr, double now);
  void Merge(const std::vector<MemberEntry>& remote, double now);
  std::string EncodeSelf() const;

  Cluster* cluster_;
  int idx_;
  int port_;
  std::string addr_;
  int fd_ = -1;
  bool alive_ = false;
  std::map<std::string, Member> members_;     // sorted: ring order by address
  std::map<std::string, double> fail_list_;   // addr -> cooldown-start ts

  friend class Cluster;
};

class Cluster {
 public:
  explicit Cluster(const Config& cfg) : cfg_(cfg) {
    nodes_.reserve(cfg.n);
    for (int i = 0; i < cfg.n; ++i) {
      nodes_.emplace_back(new Node(this, i, cfg.base_port + i));
      addr_to_idx_[nodes_.back()->addr()] = i;
    }
  }
  ~Cluster() { Stop(); }

  bool Start();
  void Stop();

  // Control verbs (thread-safe; callable from Python while the loop runs).
  void Crash(int i);
  void Leave(int i);
  void Join(int i);

  // Blocks for `rounds` heartbeat periods of wall time (real-time runtime).
  void Advance(int rounds);

  int Round() {
    std::lock_guard<std::mutex> lk(mu_);
    return round_;
  }
  int Membership(int observer, int* out, int cap);
  int AliveNodes(int* out, int cap);
  int DrainEvents(int* out, int cap);  // quadruples per event

  const Config& cfg() const { return cfg_; }
  void RecordDetection(int observer, const std::string& subject_addr) {
    auto it = addr_to_idx_.find(subject_addr);
    if (it == addr_to_idx_.end()) return;
    events_.push_back(DetectionEvent{round_, observer, it->second,
                                     nodes_[it->second]->alive() ? 1 : 0});
  }
  int IdxOf(const std::string& addr) const {
    auto it = addr_to_idx_.find(addr);
    return it == addr_to_idx_.end() ? -1 : it->second;
  }

 private:
  void LoopBody();

  Config cfg_;
  std::vector<std::unique_ptr<Node>> nodes_;
  std::map<std::string, int> addr_to_idx_;
  std::vector<DetectionEvent> events_;
  std::mutex mu_;  // guards all protocol state; the loop thread holds it
                   // while processing one batch of datagrams / one tick
  std::thread loop_;
  std::atomic<bool> running_{false};
  int epoll_fd_ = -1;
  int round_ = 0;
  double next_tick_ = 0.0;
};

// ---------------------------------------------------------------------------
// Node

Node::Node(Cluster* cluster, int idx, int port)
    : cluster_(cluster), idx_(idx), port_(port) {
  addr_ = "127.0.0.1:" + std::to_string(port);
}

bool Node::Open() {
  fd_ = ::socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port_));
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return false;
  }
  return true;
}

void Node::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Node::ResetState() {
  members_.clear();
  fail_list_.clear();
  // a fresh process knows only itself (InitMembership, slave.go:161-167)
  members_[addr_] = Member{0, MonotonicNow()};
  alive_ = true;
}

void Node::Send(const std::string& peer_addr, const std::string& msg) {
  if (fd_ < 0) return;
  size_t colon = peer_addr.rfind(':');
  if (colon == std::string::npos) return;
  // wire-derived addresses are untrusted: validate the port and IP parses
  // and skip bad entries (like DecodeMembers does for hb) — an exception
  // here would terminate the host process from the epoll thread
  const std::string port_text = peer_addr.substr(colon + 1);
  char* end = nullptr;
  long port = std::strtol(port_text.c_str(), &end, 10);
  if (end == port_text.c_str() || *end != '\0' || port <= 0 || port > 65535)
    return;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, peer_addr.substr(0, colon).c_str(),
                  &sa.sin_addr) != 1)
    return;
  ::sendto(fd_, msg.data(), msg.size(), 0, reinterpret_cast<sockaddr*>(&sa),
           sizeof(sa));
}

std::string Node::EncodeSelf() const {
  std::vector<MemberEntry> entries;
  entries.reserve(members_.size());
  for (const auto& [addr, m] : members_)
    entries.push_back(MemberEntry{addr, m.hb, m.ts});
  return EncodeMembers(entries);
}

void Node::HandleDatagram(const std::string& payload) {
  if (!alive_) return;
  double now = MonotonicNow();
  if (auto ctrl = DecodeControl(payload)) {
    if (ctrl->verb == "JOIN") {
      AddMember(ctrl->arg, now);
    } else if (ctrl->verb == "LEAVE" || ctrl->verb == "REMOVE") {
      RemoveMember(ctrl->arg, now);
    }
    return;
  }
  Merge(DecodeMembers(payload), now);
}

void Node::AddMember(const std::string& addr, double now) {
  // introducer path: append at hb=0, push the full list to every member
  // (addNewMember, slave.go:250-274)
  if (members_.find(addr) == members_.end()) members_[addr] = Member{0, now};
  std::string msg = EncodeSelf();
  for (const auto& [peer, m] : members_)
    if (peer != addr_) Send(peer, msg);
}

void Node::RemoveMember(const std::string& addr, double now) {
  auto it = members_.find(addr);
  if (it == members_.end()) return;
  if (fail_list_.find(addr) == fail_list_.end()) {
    // faithful mode keeps the entry's (stale) timestamp on the fail list
    // (removeMember appends the live struct, slave.go:276-286);
    // fresh_cooldown stamps removal time for a real suppression window
    fail_list_[addr] = cluster_->cfg().fresh_cooldown ? now : it->second.ts;
  }
  members_.erase(it);
}

void Node::Merge(const std::vector<MemberEntry>& remote, double now) {
  // anti-entropy max-merge with LOCAL re-stamping (slave.go:414-440)
  for (const auto& entry : remote) {
    auto it = members_.find(entry.addr);
    if (it != members_.end()) {
      if (entry.hb > it->second.hb) {
        it->second.hb = entry.hb;
        it->second.ts = now;
      }
    } else if (fail_list_.find(entry.addr) == fail_list_.end()) {
      members_[entry.addr] = Member{entry.hb, now};
    }
  }
}

void Node::Tick(double now) {
  if (!alive_) return;
  const Config& cfg = cluster_->cfg();
  if (static_cast<int>(members_.size()) < cfg.min_group) {
    for (auto& [addr, m] : members_) m.ts = now;  // refresh-only
    return;
  }
  auto self = members_.find(addr_);
  if (self != members_.end()) {
    self->second.hb += 1;
    self->second.ts = now;
  }
  // failure detection (slave.go:460-476)
  double t_fail = cfg.t_fail * cfg.period;
  std::vector<std::string> failed;
  for (const auto& [addr, m] : members_) {
    if (addr == addr_) continue;
    if (m.hb > 1 && m.ts < now - t_fail) failed.push_back(addr);
  }
  for (const auto& addr : failed) {
    RemoveMember(addr, now);
    cluster_->RecordDetection(idx_, addr);
    std::string msg = EncodeControl(addr, "REMOVE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg);
  }
  // fail-list cooldown expiry (slave.go:484-497)
  double t_cool = cfg.t_cooldown * cfg.period;
  for (auto it = fail_list_.begin(); it != fail_list_.end();) {
    if (it->second < now - t_cool)
      it = fail_list_.erase(it);
    else
      ++it;
  }
  // ring push to sorted list positions self-1, self+1, self+2
  // (slave.go:515-542); std::map iteration order == sorted addresses
  if (members_.find(addr_) == members_.end()) return;  // removed-self
  std::vector<const std::string*> ordered;
  ordered.reserve(members_.size());
  for (const auto& [addr, m] : members_) ordered.push_back(&addr);
  int n = static_cast<int>(ordered.size());
  int self_i = 0;
  for (int i = 0; i < n; ++i)
    if (*ordered[i] == addr_) self_i = i;
  std::string msg = EncodeSelf();
  for (int off : {-1, 1, 2}) {
    const std::string& peer = *ordered[((self_i + off) % n + n) % n];
    if (peer != addr_) Send(peer, msg);
  }
}

void Node::StopGraceful() {
  if (alive_) {
    std::string msg = EncodeControl(addr_, "LEAVE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg);
  }
  alive_ = false;
}

void Node::StopCrash() { alive_ = false; }

std::vector<std::string> Node::MemberAddrs() const {
  std::vector<std::string> out;
  out.reserve(members_.size());
  for (const auto& [addr, m] : members_) out.push_back(addr);
  return out;
}

// ---------------------------------------------------------------------------
// Cluster

bool Cluster::Start() {
  epoll_fd_ = ::epoll_create1(0);
  if (epoll_fd_ < 0) return false;
  for (auto& node : nodes_) {
    if (!node->Open()) return false;
    node->ResetState();
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = static_cast<uint32_t>(node->idx());
    ::epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, node->fd(), &ev);
  }
  // everyone joins through the introducer (slave.go:288-308)
  {
    std::lock_guard<std::mutex> lk(mu_);
    Node* intro = nodes_[cfg_.introducer].get();
    for (auto& node : nodes_)
      if (node->idx() != cfg_.introducer)
        node->Send(intro->addr(), EncodeControl(node->addr(), "JOIN"));
    next_tick_ = MonotonicNow() + cfg_.period;
  }
  running_ = true;
  loop_ = std::thread([this] {
    while (running_) LoopBody();
  });
  return true;
}

void Cluster::LoopBody() {
  epoll_event events[64];
  double now = MonotonicNow();
  double wait_s = next_tick_ - now;
  int timeout_ms = wait_s > 0 ? static_cast<int>(wait_s * 1000) + 1 : 0;
  int nfds = ::epoll_wait(epoll_fd_, events, 64, std::min(timeout_ms, 50));
  std::lock_guard<std::mutex> lk(mu_);
  char buf[65536];
  for (int e = 0; e < nfds; ++e) {
    Node* node = nodes_[events[e].data.u32].get();
    while (true) {
      ssize_t len = ::recv(node->fd(), buf, sizeof(buf), 0);
      if (len <= 0) break;
      node->HandleDatagram(std::string(buf, static_cast<size_t>(len)));
    }
  }
  now = MonotonicNow();
  if (now >= next_tick_) {
    for (auto& node : nodes_) node->Tick(now);
    round_ += 1;
    next_tick_ += cfg_.period;
    if (next_tick_ < now) next_tick_ = now + cfg_.period;  // fell behind
  }
}

void Cluster::Stop() {
  if (running_.exchange(false)) loop_.join();
  if (epoll_fd_ >= 0) {
    ::close(epoll_fd_);
    epoll_fd_ = -1;
  }
  for (auto& node : nodes_) node->Close();
}

void Cluster::Crash(int i) {
  std::lock_guard<std::mutex> lk(mu_);
  nodes_[i]->StopCrash();
}

void Cluster::Leave(int i) {
  std::lock_guard<std::mutex> lk(mu_);
  nodes_[i]->StopGraceful();
}

void Cluster::Join(int i) {
  std::lock_guard<std::mutex> lk(mu_);
  Node* node = nodes_[i].get();
  if (!node->alive()) node->ResetState();
  // JOIN to the introducer; lost if the introducer is down (SPOF kept,
  // slave.go:22)
  node->Send(nodes_[cfg_.introducer]->addr(),
             EncodeControl(node->addr(), "JOIN"));
}

void Cluster::Advance(int rounds) {
  int target;
  {
    std::lock_guard<std::mutex> lk(mu_);
    target = round_ + rounds;
  }
  while (running_) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (round_ >= target) return;
    }
    std::this_thread::sleep_for(
        std::chrono::duration<double>(cfg_.period / 4));
  }
}

int Cluster::Membership(int observer, int* out, int cap) {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<int> ids;
  for (const auto& addr : nodes_[observer]->MemberAddrs()) {
    int idx = IdxOf(addr);
    if (idx >= 0) ids.push_back(idx);
  }
  std::sort(ids.begin(), ids.end());
  int n = std::min(static_cast<int>(ids.size()), cap);
  std::copy(ids.begin(), ids.begin() + n, out);
  return n;
}

int Cluster::AliveNodes(int* out, int cap) {
  std::lock_guard<std::mutex> lk(mu_);
  int count = 0;
  for (const auto& node : nodes_)
    if (node->alive() && count < cap) out[count++] = node->idx();
  return count;
}

int Cluster::DrainEvents(int* out, int cap) {
  std::lock_guard<std::mutex> lk(mu_);
  int n = std::min(static_cast<int>(events_.size()), cap / 4);
  for (int i = 0; i < n; ++i) {
    out[i * 4 + 0] = events_[i].round;
    out[i * 4 + 1] = events_[i].observer;
    out[i * 4 + 2] = events_[i].subject;
    out[i * 4 + 3] = events_[i].false_positive;
  }
  events_.erase(events_.begin(), events_.begin() + n);
  return n;
}

}  // namespace
}  // namespace gossipfs

// ---------------------------------------------------------------------------
// C ABI for ctypes (gossipfs_tpu/native.py)

extern "C" {

void* gfs_cluster_create(int n, int base_port, double period_s, int t_fail,
                         int t_cooldown, int min_group, int fresh_cooldown,
                         int introducer) {
  gossipfs::Config cfg;
  cfg.n = n;
  cfg.base_port = base_port;
  cfg.period = period_s;
  cfg.t_fail = t_fail;
  cfg.t_cooldown = t_cooldown;
  cfg.min_group = min_group;
  cfg.fresh_cooldown = fresh_cooldown != 0;
  cfg.introducer = introducer;
  return new gossipfs::Cluster(cfg);
}

int gfs_cluster_start(void* h) {
  return static_cast<gossipfs::Cluster*>(h)->Start() ? 0 : -1;
}

void gfs_cluster_destroy(void* h) {
  delete static_cast<gossipfs::Cluster*>(h);
}

void gfs_crash(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Crash(i); }
void gfs_leave(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Leave(i); }
void gfs_join(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Join(i); }

void gfs_advance(void* h, int rounds) {
  static_cast<gossipfs::Cluster*>(h)->Advance(rounds);
}

int gfs_round(void* h) { return static_cast<gossipfs::Cluster*>(h)->Round(); }

int gfs_membership(void* h, int observer, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->Membership(observer, out, cap);
}

int gfs_alive(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->AliveNodes(out, cap);
}

int gfs_drain_events(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->DrainEvents(out, cap);
}

// Codec surface for parity tests: input lines "addr hb ts\n", output the
// wire string (and the reverse).  snprintf semantics: writes at most cap-1
// bytes + NUL and returns the FULL required length, so callers can detect
// truncation and retry with a bigger buffer.
static int CopyOut(const std::string& text, char* out, int cap) {
  int n = std::min(static_cast<int>(text.size()), cap - 1);
  if (n > 0) std::memcpy(out, text.data(), static_cast<size_t>(n));
  if (cap > 0) out[n] = '\0';
  return static_cast<int>(text.size());
}

int gfs_codec_encode(const char* lines, char* out, int cap) {
  std::vector<gossipfs::MemberEntry> entries;
  std::istringstream in(lines);
  std::string addr;
  long long hb;
  double ts;
  while (in >> addr >> hb >> ts)
    entries.push_back(gossipfs::MemberEntry{addr, hb, ts});
  return CopyOut(gossipfs::EncodeMembers(entries), out, cap);
}

int gfs_codec_decode(const char* wire, char* out, int cap) {
  auto entries = gossipfs::DecodeMembers(wire);
  std::ostringstream os;
  os << std::setprecision(17);
  for (const auto& e : entries) os << e.addr << ' ' << e.hb << ' ' << e.ts << '\n';
  return CopyOut(os.str(), out, cap);
}

}  // extern "C"
