// Native gossip runtime: N protocol nodes over real localhost UDP sockets,
// driven by one epoll loop — the C++ equivalent of the reference's Go
// runtime (goroutine heartbeat driver main.go:27-33, blocking UDP receive
// loop slave/slave.go:207-248), for the BASELINE config-1 parity path.
//
// Protocol semantics mirror the reference exactly (and the Python asyncio
// twin, gossipfs_tpu/detector/udp.py):
//   - join through the introducer, which appends and pushes its full list to
//     every member (addNewMember, slave.go:250-274)
//   - per-period tick: refresh-only below min_group (slave.go:504-509), bump
//     own heartbeat, detect members with hb > 1 silent past t_fail periods
//     (slave.go:460-476), REMOVE broadcast (slave.go:338-363), fail-list
//     cooldown expiry (slave.go:484-497), then full-list push to ring
//     neighbours at sorted positions self-1, self+1, self+2 (slave.go:515-542)
//   - merge: shared members take max heartbeat + LOCAL timestamp; unknown
//     members are added unless on the fail list (slave.go:414-440)
//
// Exposed through a C ABI (extern "C") for ctypes — see gossipfs_tpu/native.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <iomanip>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "codec.h"
#include "tsa.h"

namespace gossipfs {
namespace {

double MonotonicNow() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Uniform-vitals field writer: every field name is a string literal at
// its call site, and gossipfs-lint's native-obs-kinds rule requires
// each to appear in obs/schema.py VITALS_FIELDS — single ownership of
// the counter names across the language boundary (the n/a-not-0 rule:
// a field this engine cannot know is simply never appended, so the
// Python surface renders it n/a, never a fabricated 0).
void AppendVital(std::ostringstream& os, const char* key, long long v) {
  if (os.tellp() > 0) os << ' ';
  os << key << '=' << v;
}

struct Member {
  long long hb = 0;
  double ts = 0.0;
};

struct DetectionEvent {
  int round;
  int observer;
  int subject;
  int false_positive;
};

struct Config {
  int n = 10;
  int base_port = 19000;
  double period = 0.05;  // seconds per heartbeat round
  int t_fail = 5;        // periods of silence before declaring failure
  int t_cooldown = 5;    // fail-list suppression periods
  int min_group = 4;     // below this size: refresh-only
  bool fresh_cooldown = false;  // stamp fail-list entries at removal time
  int introducer = 0;
  // campaign protocol profile (gfs_configure, round 16) — the same knobs
  // the asyncio engine grew in round 14 (detector/udp.py UdpCluster):
  // push_random = fanout random listed peers per tick instead of the
  // reference's ring positions; remove_broadcast=false = removal by
  // local timeout only (the north-star gossip-only dissemination).
  bool push_random = false;
  int fanout = 3;
  bool remove_broadcast = true;
  // SWIM suspicion + Lifeguard local health (suspicion/params.py is the
  // schema; suspicion/runtime.py the per-node reference semantics the
  // Tick/Merge paths below mirror).  t_suspect == 0 disarms.
  int t_suspect = 0;
  int lh_multiplier = 0;
  double lh_frac = 0.25;
};

// -- fault gates (scenarios/schedule.py primitives, compiled to a text
// table by gossipfs_tpu/native.py::compile_native_scenario and pushed
// over gfs_scenario_load).  Semantics mirror ScenarioRuntime.drops:
// a src -> dst datagram at armed-relative round r is dropped iff any
// active rule says so.  Bernoulli link loss is deliberately NOT in the
// table (it needs an RNG-stream parity decision; the Python compiler
// rejects it, like the aligned-arc tensor path does).
struct GateFlap {
  int start, end, up, down;
  std::vector<char> mask;  // [n] sender membership
};
struct GateOutage {
  int start, end;
  std::vector<char> mask;  // [n] group membership (src OR dst drops)
};
struct GatePartition {
  int start, end;
  std::vector<int> pid;  // [n] group id; cross-pid drops
};
struct GateSlow {
  int start, end, stride;
  std::vector<char> mask;  // [n] lagging senders
};

struct GateTable {
  std::vector<GateFlap> flaps;
  std::vector<GateOutage> outages;
  std::vector<GatePartition> partitions;
  std::vector<GateSlow> slows;
  std::string name;
  int horizon = 0;
};

// Cluster is defined BEFORE Node so Node's thread-safety attributes can
// name the capability they are guarded by (`cluster_->mu_` must resolve
// against a complete Cluster).  The members Node needs (ctor, dtor,
// RecordDetection) are declared here and defined out-of-line after Node.
class Node;

class Cluster {
 public:
  explicit Cluster(const Config& cfg);
  ~Cluster();  // out-of-line: unique_ptr<Node> needs Node complete

  bool Start();
  void Stop();

  // Control verbs (thread-safe; callable from Python while the loop runs).
  void Crash(int i);
  void Leave(int i);
  void Join(int i);

  // Blocks for `rounds` heartbeat periods of wall time (real-time runtime).
  void Advance(int rounds);

  int Round() {
    MutexLock lk(mu_);
    return round_;
  }
  int Membership(int observer, int* out, int cap);
  int Suspects(int observer, int* out, int cap);
  long long Incarnation(int observer, int subject);  // hb, -1 if absent
  int AliveNodes(int* out, int cap);
  int DrainEvents(int* out, int cap);  // quadruples per event

  // -- round-16 control/observation surface (all thread-safe)
  int Configure(const std::string& kv);  // pre-Start knob table
  int ObsEnable();                       // arm event buffering; returns base round
  int ObsDrain(char* out, int cap);      // whole-line sized drain
  std::string VitalsText();              // uniform k=v counter text
  int ScenarioLoad(const std::string& table, int round0);
  void ScenarioClear();
  void SeedFull();  // fully-joined steady state (udp seed_full_membership)
  int Warm();       // 1 iff every alive view is full with every hb > 1

  const Config& cfg() const { return cfg_; }
  void RecordDetection(int observer, const std::string& subject_addr)
      GFS_REQUIRES(mu_);
  int IdxOf(const std::string& addr) const {
    auto it = addr_to_idx_.find(addr);
    return it == addr_to_idx_.end() ? -1 : it->second;
  }
  // obs emission (single writer of the event lines; the Python side
  // renders them through obs.recorder.FlightRecorder so the stream's
  // reader stays obs.recorder.load_stream).  Kind strings are literals
  // at every call site: gossipfs-lint's native-obs-kinds rule requires
  // each to appear in obs/schema.py EVENT_KINDS (single ownership
  // across the language boundary), and rules_spec's
  // spec-native-annotations rule requires every LIFECYCLE kind to be
  // dominated by a matching `// @gfs:` contract annotation.
  void ObsEmit(const char* kind, int observer, int subject,
               const std::string& detail) GFS_REQUIRES(mu_);
  void ObsEmit(const char* kind, int observer,
               const std::string& subject_addr, const std::string& detail)
      GFS_REQUIRES(mu_);
  bool ScenarioDrops(int src, const std::string& dst_addr) const
      GFS_REQUIRES(mu_);
  void CountSend() GFS_REQUIRES(mu_) { sends_total_ += 1; }

 private:
  void LoopBody();
  void EmitRoundTick(double tick_ms) GFS_REQUIRES(mu_);

  // Immutable after construction / Start (no lock needed): cfg_ (knob
  // writes only before the loop thread exists), nodes_, addr_to_idx_,
  // epoll_fd_, loop_, running_ (atomic).
  Config cfg_;
  std::vector<std::unique_ptr<Node>> nodes_;
  std::map<std::string, int> addr_to_idx_;
  std::thread loop_;
  std::atomic<bool> running_{false};
  int epoll_fd_ = -1;
  // mu_ guards all protocol state — every Node field below plus these —
  // against the epoll loop thread vs the C-ABI control verbs.  The loop
  // thread holds it while processing one batch of datagrams / one tick.
  Mutex mu_;
  std::vector<DetectionEvent> events_ GFS_GUARDED_BY(mu_);
  int round_ GFS_GUARDED_BY(mu_) = 0;
  double next_tick_ GFS_GUARDED_BY(mu_) = 0.0;
  // -- cumulative counters (vitals; events_ drains, so the `metrics`
  // surface needs its own accounting — the udp engine's convention)
  long long det_total_ GFS_GUARDED_BY(mu_) = 0;
  long long fp_total_ GFS_GUARDED_BY(mu_) = 0;
  long long sends_total_ GFS_GUARDED_BY(mu_) = 0;
  // -- obs plane: rendered event lines awaiting ObsDrain.  OFF until
  // gfs_obs_enable so detectors without a recorder never grow the
  // buffer; enabling rebases the stamped round clock to 0 (the
  // arming-relative frame the udp campaign streams use).
  bool obs_enabled_ GFS_GUARDED_BY(mu_) = false;
  int obs_round0_ GFS_GUARDED_BY(mu_) = 0;
  std::string obs_buf_ GFS_GUARDED_BY(mu_);
  long long obs_det0_ GFS_GUARDED_BY(mu_) = 0;
  long long obs_fp0_ GFS_GUARDED_BY(mu_) = 0;
  long long obs_sends0_ GFS_GUARDED_BY(mu_) = 0;
  long long obs_sus_entered0_ GFS_GUARDED_BY(mu_) = 0;
  long long obs_refut0_ GFS_GUARDED_BY(mu_) = 0;
  // -- armed fault gates (ScenarioLoad); windows are round0-relative
  GateTable gates_ GFS_GUARDED_BY(mu_);
  bool gates_armed_ GFS_GUARDED_BY(mu_) = false;
  int scn_round0_ GFS_GUARDED_BY(mu_) = 0;

  friend class Node;
};

class Node {
 public:
  Node(Cluster* cluster, int idx, int port);
  ~Node() { Close(); }

  bool Open();   // bind the UDP socket
  void Close();

  void HandleDatagram(const std::string& payload)
      GFS_REQUIRES(cluster_->mu_);
  void Tick(double now) GFS_REQUIRES(cluster_->mu_);
  void StopGraceful() GFS_REQUIRES(cluster_->mu_);  // LEAVE broadcast, die
  void StopCrash() GFS_REQUIRES(cluster_->mu_);     // silent death (CTRL+C)
  void ResetState() GFS_REQUIRES(cluster_->mu_);    // fresh state for rejoin
  void SeedMembers(const std::vector<std::string>& addrs, double now)
      GFS_REQUIRES(cluster_->mu_);

  int fd() const { return fd_; }
  int idx() const { return idx_; }
  bool alive() const GFS_REQUIRES(cluster_->mu_) { return alive_; }
  const std::string& addr() const { return addr_; }
  std::vector<std::string> MemberAddrs() const GFS_REQUIRES(cluster_->mu_);
  std::vector<std::string> SuspectAddrs() const GFS_REQUIRES(cluster_->mu_);
  // per-entry heartbeat counter (the incarnation surface the conformance
  // harness reads); -1 when the addr is not in this node's view
  long long HbOf(const std::string& addr) const GFS_REQUIRES(cluster_->mu_);

  // TSA compares capability expressions syntactically, so at a Cluster
  // call site `node->Tick()` requires `node->cluster_->mu_` — an alias
  // of the held `this->mu_` the analysis cannot prove.  This assert-only
  // no-op states the aliasing fact; Cluster calls it once per node at
  // every crossing made with mu_ held.
  void AssertLockHeld() const GFS_ASSERT_CAPABILITY(cluster_->mu_) {}

 private:
  void Send(const std::string& peer_addr, const std::string& msg)
      GFS_REQUIRES(cluster_->mu_);
  void AddMember(const std::string& addr, double now)
      GFS_REQUIRES(cluster_->mu_);  // introducer path
  void RemoveMember(const std::string& addr, double now)
      GFS_REQUIRES(cluster_->mu_);
  void Merge(const std::vector<MemberEntry>& remote, double now)
      GFS_REQUIRES(cluster_->mu_);
  void OnSuspect(const std::string& addr, double now)
      GFS_REQUIRES(cluster_->mu_);
  void OnRefute(const std::string& arg, double now)
      GFS_REQUIRES(cluster_->mu_);
  // Lifeguard local health (runtime.py::degraded)
  bool Degraded() const GFS_REQUIRES(cluster_->mu_);
  std::string EncodeSelf() const GFS_REQUIRES(cluster_->mu_);
  // per-node stream for the random-push draw
  uint32_t NextRand() GFS_REQUIRES(cluster_->mu_);

  Cluster* const cluster_;
  const int idx_;
  const int port_;
  std::string addr_;
  int fd_ = -1;  // epoll registration is pre-thread; Close post-join
  bool alive_ GFS_GUARDED_BY(cluster_->mu_) = false;
  // sorted: ring order by address
  std::map<std::string, Member> members_ GFS_GUARDED_BY(cluster_->mu_);
  // addr -> cooldown-start ts
  std::map<std::string, double> fail_list_ GFS_GUARDED_BY(cluster_->mu_);
  // suspicion (armed iff cfg.t_suspect > 0): addr -> suspect-start ts,
  // plus cumulative lifecycle counters (the vitals/round_tick surface)
  std::map<std::string, double> suspects_ GFS_GUARDED_BY(cluster_->mu_);
  long long sus_entered_ GFS_GUARDED_BY(cluster_->mu_) = 0;
  long long sus_refutations_ GFS_GUARDED_BY(cluster_->mu_) = 0;
  long long sus_confirms_ GFS_GUARDED_BY(cluster_->mu_) = 0;
  // rate-limits REFUTE broadcasts
  double last_refute_t_ GFS_GUARDED_BY(cluster_->mu_) = -1e18;
  uint32_t rng_state_ GFS_GUARDED_BY(cluster_->mu_);

  friend class Cluster;
};

// -- Cluster members that need a complete Node --------------------------------

Cluster::Cluster(const Config& cfg) : cfg_(cfg) {
  nodes_.reserve(cfg.n);
  for (int i = 0; i < cfg.n; ++i) {
    nodes_.emplace_back(new Node(this, i, cfg.base_port + i));
    addr_to_idx_[nodes_.back()->addr()] = i;
  }
}

Cluster::~Cluster() { Stop(); }

void Cluster::RecordDetection(int observer, const std::string& subject_addr) {
  auto it = addr_to_idx_.find(subject_addr);
  if (it == addr_to_idx_.end()) return;
  Node* subject = nodes_[it->second].get();
  subject->AssertLockHeld();
  int fp = subject->alive() ? 1 : 0;
  events_.push_back(DetectionEvent{round_, observer, it->second, fp});
  det_total_ += 1;
  fp_total_ += fp;
  // the one emission point every failure declaration funnels through —
  // the suspicion path after the (lh-stretched) window expires, and the
  // direct stale confirm when suspicion is disarmed (t_suspect == 0)
  // @gfs:transition SUSPECT->FAILED guard=confirm_window
  // @gfs:transition MEMBER->FAILED guard=stale
  ObsEmit("confirm", observer, it->second,
          fp ? "false_positive=1" : "false_positive=0");
}

// ---------------------------------------------------------------------------
// Node

Node::Node(Cluster* cluster, int idx, int port)
    : cluster_(cluster), idx_(idx), port_(port),
      rng_state_(0x5EEDu ^ (static_cast<uint32_t>(idx) * 2654435761u)) {
  addr_ = "127.0.0.1:" + std::to_string(port);
}

uint32_t Node::NextRand() {
  // xorshift32 — a per-node stream for the random-push draw (no parity
  // contract with the Python engines' streams; real-socket runs are
  // verdict-compared, never bit-compared)
  uint32_t x = rng_state_;
  x ^= x << 13;
  x ^= x >> 17;
  x ^= x << 5;
  rng_state_ = x ? x : 0x5EEDu;
  return rng_state_;
}

bool Node::Open() {
  fd_ = ::socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (fd_ < 0) return false;
  int one = 1;
  ::setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port_));
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (::bind(fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    ::close(fd_);
    fd_ = -1;
    return false;
  }
  return true;
}

void Node::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Node::ResetState() {
  members_.clear();
  fail_list_.clear();
  // a fresh process forgets its suspicions with the rest of its state;
  // the cumulative lifecycle counters survive (vitals are per-run)
  suspects_.clear();
  // a fresh process knows only itself (InitMembership, slave.go:161-167)
  members_[addr_] = Member{0, MonotonicNow()};
  alive_ = true;
}

void Node::SeedMembers(const std::vector<std::string>& addrs, double now) {
  // the fully-joined steady state the tensor engine's init_state models
  // (udp.py seed_full_membership): everyone listed at hb 0 with a fresh
  // local stamp — inside the hb<=1 detection grace
  members_.clear();
  for (const auto& a : addrs) members_[a] = Member{0, now};
}

void Node::Send(const std::string& peer_addr, const std::string& msg) {
  if (fd_ < 0) return;
  // fault-gate hook (the UdpNode._send seam): an armed scenario rule —
  // flapping dark phase, rack outage, partition, lagging sender —
  // drops the datagram HERE, so heartbeat pushes, control verbs and
  // SUSPECT/REFUTE broadcasts are all affected alike
  if (cluster_->ScenarioDrops(idx_, peer_addr)) return;
  size_t colon = peer_addr.rfind(':');
  if (colon == std::string::npos) return;
  // wire-derived addresses are untrusted: validate the port and IP parses
  // and skip bad entries (like DecodeMembers does for hb) — an exception
  // here would terminate the host process from the epoll thread
  const std::string port_text = peer_addr.substr(colon + 1);
  char* end = nullptr;
  long port = std::strtol(port_text.c_str(), &end, 10);
  if (end == port_text.c_str() || *end != '\0' || port <= 0 || port > 65535)
    return;
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, peer_addr.substr(0, colon).c_str(),
                  &sa.sin_addr) != 1)
    return;
  ::sendto(fd_, msg.data(), msg.size(), 0, reinterpret_cast<sockaddr*>(&sa),
           sizeof(sa));
  cluster_->CountSend();
}

std::string Node::EncodeSelf() const {
  std::vector<MemberEntry> entries;
  entries.reserve(members_.size());
  for (const auto& [addr, m] : members_)
    entries.push_back(MemberEntry{addr, m.hb, m.ts});
  return EncodeMembers(entries);
}

void Node::HandleDatagram(const std::string& payload) {
  if (!alive_) return;
  double now = MonotonicNow();
  if (auto ctrl = DecodeControl(payload)) {
    // @gfs:verb JOIN
    if (ctrl->verb == "JOIN") {
      AddMember(ctrl->arg, now);
      // @gfs:verb LEAVE
      // @gfs:verb REMOVE
    } else if (ctrl->verb == "LEAVE" || ctrl->verb == "REMOVE") {
      RemoveMember(ctrl->arg, now);
      // @gfs:verb SUSPECT
    } else if (ctrl->verb == "SUSPECT") {
      OnSuspect(ctrl->arg, now);
      // @gfs:verb REFUTE
    } else if (ctrl->verb == "REFUTE") {
      OnRefute(ctrl->arg, now);
    }
    return;
  }
  Merge(DecodeMembers(payload), now);
}

// -- suspicion wire verbs (SWIM suspect/refute; the same protocol the
// asyncio engine speaks — detector/udp.py _on_suspect/_on_refute) ------------

bool Node::Degraded() const {
  const Config& cfg = cluster_->cfg();
  return cfg.lh_multiplier > 0 &&
         static_cast<double>(suspects_.size()) >
             cfg.lh_frac * static_cast<double>(members_.size());
}

void Node::OnSuspect(const std::string& addr, double now) {
  const Config& cfg = cluster_->cfg();
  if (cfg.t_suspect <= 0) return;
  if (addr == addr_) {
    // the suspect is ME: refute by INCARNATION BUMP — advance my own
    // counter past whatever the suspicion was based on and broadcast a
    // REFUTE carrying it.  One bump + one broadcast per period answers
    // the whole episode (k suspectors each broadcast to everyone, so
    // k*(N-1) copies land here).
    auto me = members_.find(addr_);
    if (me == members_.end()) return;
    // @gfs:rate_limit refute_broadcast
    if (now - last_refute_t_ < cfg.period) return;
    last_refute_t_ = now;
    me->second.hb += 1;
    me->second.ts = now;
    std::string msg = EncodeControl(
        addr_ + kFieldSep + std::to_string(me->second.hb), "REFUTE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg);
  } else if (members_.find(addr) != members_.end()) {
    // adopt a peer-disseminated suspicion: start the timer, uncounted
    // (runtime.py::adopt — local freshness discards it at the next tick)
    suspects_.emplace(addr, now);
  }
}

void Node::OnRefute(const std::string& arg, double now) {
  // "addr<#INFO#>hb<CMD>REFUTE": the suspect's alive message.  Adopt the
  // bumped incarnation, stamp fresh, cancel any pending suspicion; a
  // fail-listed entry is NOT resurrected (cooldown suppression wins).
  size_t pos = arg.find(kFieldSep);
  std::string addr = pos == std::string::npos ? arg : arg.substr(0, pos);
  long long hb = 0;
  if (pos != std::string::npos) {
    const std::string hb_text = arg.substr(pos + sizeof(kFieldSep) - 1);
    char* end = nullptr;
    hb = std::strtoll(hb_text.c_str(), &end, 10);
    if (end == hb_text.c_str()) hb = 0;
  }
  auto it = members_.find(addr);
  if (it == members_.end()) return;
  if (hb > it->second.hb) it->second.hb = hb;
  it->second.ts = now;
  if (suspects_.erase(addr)) {
    sus_refutations_ += 1;
    // @gfs:transition SUSPECT->MEMBER guard=refute_evidence
    cluster_->ObsEmit("refute", idx_, addr, "");
  }
}

void Node::AddMember(const std::string& addr, double now) {
  // introducer path: append at hb=0, push the full list to every member
  // (addNewMember, slave.go:250-274)
  // @gfs:transition UNKNOWN->MEMBER guard=join_or_merge_add
  if (members_.find(addr) == members_.end()) members_[addr] = Member{0, now};
  std::string msg = EncodeSelf();
  for (const auto& [peer, m] : members_)
    if (peer != addr_) Send(peer, msg);
}

void Node::RemoveMember(const std::string& addr, double now) {
  auto it = members_.find(addr);
  if (it == members_.end()) return;
  if (fail_list_.find(addr) == fail_list_.end()) {
    // faithful mode keeps the entry's (stale) timestamp on the fail list
    // (removeMember appends the live struct, slave.go:276-286);
    // fresh_cooldown stamps removal time for a real suppression window
    fail_list_[addr] = cluster_->cfg().fresh_cooldown ? now : it->second.ts;
    // @gfs:transition MEMBER->FAILED guard=leave_or_remove
    cluster_->ObsEmit("remove", idx_, addr, "");
  }
  members_.erase(it);
  // removed for any reason (LEAVE, a peer's REMOVE, a confirm): forget
  // the pending suspicion uncounted (runtime.py::drop)
  suspects_.erase(addr);
}

void Node::Merge(const std::vector<MemberEntry>& remote, double now) {
  // anti-entropy max-merge with LOCAL re-stamping (slave.go:414-440)
  for (const auto& entry : remote) {
    auto it = members_.find(entry.addr);
    if (it != members_.end()) {
      if (entry.hb > it->second.hb) {
        it->second.hb = entry.hb;
        it->second.ts = now;
        if (suspects_.erase(entry.addr)) {
          // refute-by-advance: a fresher counter observed while SUSPECT
          // cancels the pending failure (runtime.py::refute)
          sus_refutations_ += 1;
          // @gfs:transition SUSPECT->MEMBER guard=refute_evidence
          cluster_->ObsEmit("refute", idx_, entry.addr, "");
        }
      }
      // @gfs:transition UNKNOWN->MEMBER guard=join_or_merge_add
    } else if (fail_list_.find(entry.addr) == fail_list_.end()) {
      members_[entry.addr] = Member{entry.hb, now};
    }
  }
}

void Node::Tick(double now) {
  if (!alive_) return;
  const Config& cfg = cluster_->cfg();
  if (static_cast<int>(members_.size()) < cfg.min_group) {
    for (auto& [addr, m] : members_) m.ts = now;  // refresh-only
    return;
  }
  auto self = members_.find(addr_);
  if (self != members_.end()) {
    self->second.hb += 1;
    self->second.ts = now;
  }
  // failure detection (slave.go:460-482).  With suspicion armed
  // (cfg.t_suspect > 0) a stale member passes through SUSPECT first:
  // the first stale tick broadcasts SUSPECT (so the subject can
  // actively refute by incarnation bump — OnSuspect), and only the
  // SUSPECT->FAILED window — t_suspect periods, stretched by the
  // Lifeguard local-health multiplier while this observer is degraded —
  // confirms the removal.  Mirrors detector/udp.py UdpNode.tick /
  // suspicion/runtime.py exactly.
  double t_fail = cfg.t_fail * cfg.period;
  bool sus = cfg.t_suspect > 0;
  std::vector<std::string> newly_suspect;
  std::vector<std::string> failed;
  for (const auto& [addr, m] : members_) {
    if (addr == addr_) continue;
    bool stale = m.hb > 1 && m.ts < now - t_fail;
    if (!stale) {
      // a genuinely-refuted suspicion was already popped (and counted)
      // by Merge/OnRefute when the fresh evidence arrived; anything
      // left here is a peer-disseminated adoption for an entry that
      // was never stale locally — clear it WITHOUT counting
      if (sus) suspects_.erase(addr);
      continue;
    }
    if (sus) {
      auto it = suspects_.find(addr);
      if (it == suspects_.end()) {
        suspects_[addr] = now;
        sus_entered_ += 1;
        newly_suspect.push_back(addr);
        continue;
      }
      // the stretched window is recomputed PER MEMBER, like the udp
      // engine's rt.t_suspect_window call: suspicions entered earlier
      // in this same tick count toward this member's degraded bit, so
      // a mass-suspicion tick stretches the window for the members
      // examined after the lh_frac crossing
      int mult = 1 + (Degraded() ? cfg.lh_multiplier : 0);
      double window = cfg.t_suspect * mult * cfg.period;
      if (!(now - it->second > window)) {
        // periodic re-notification (SWIM re-gossips suspicion): the
        // original SUSPECT may have been sent into a fault window — a
        // rack outage drops it, so the subject never learns and the
        // post-heal refute wave would ride passive list gossip alone,
        // leaking a heal-race FP tail (~100 FPs at n=256, measured).
        // One subject-only datagram per suspect per tick triggers the
        // active incarnation-bump refute the moment the subject is
        // reachable again; the REFUTE broadcast is rate-limited on the
        // subject's side, so k re-notifiers cost one bump per period.
        Send(addr, EncodeControl(addr, "SUSPECT"));
        continue;
      }
      suspects_.erase(it);
      sus_confirms_ += 1;
    }
    failed.push_back(addr);
  }
  for (const auto& addr : newly_suspect) {
    // @gfs:transition MEMBER->SUSPECT guard=stale
    cluster_->ObsEmit("suspect", idx_, addr, "");
    std::string msg = EncodeControl(addr, "SUSPECT");
    // @gfs:dissemination new_suspect profile=campaign bound=subject+fanout
    if (cfg.push_random) {
      // campaign profile: bounded dissemination — the SUBJECT always
      // hears (its active incarnation-bump refute is the point) plus
      // fanout random peers, O(fanout) per new suspicion like every
      // other push in this mode.  The reference-faithful all-peers
      // broadcast below is O(suspects x N) per round: at n=256 a rack
      // outage makes ~250 observers suspect 8 nodes in ONE tick —
      // ~500k synchronous sendtos that stall the epoll thread for
      // seconds, go-stale everything, and storm the cluster by
      // ENGINE physics, not protocol (measured: 26 s tick, 73k FPs).
      Send(addr, msg);
      std::vector<const std::string*> peers;
      peers.reserve(members_.size());
      for (const auto& [peer, m] : members_)
        if (peer != addr_ && peer != addr) peers.push_back(&peer);
      int k = std::min<int>(cfg.fanout, static_cast<int>(peers.size()));
      for (int i = 0; i < k; ++i) {
        int j = i + static_cast<int>(NextRand() % (peers.size() - i));
        std::swap(peers[i], peers[j]);
        Send(*peers[i], msg);
      }
    } else {
      // ring mode: the asyncio engine's wire behavior verbatim (the
      // small-n udp-parity lane compares event sequences)
      // @gfs:dissemination new_suspect profile=reference bound=all_peers
      for (const auto& [peer, m] : members_)
        if (peer != addr_) Send(peer, msg);
    }
  }
  for (const auto& addr : failed) {
    // detection first, then the removal it causes — the same
    // confirm -> remove causal order every engine's events carry
    cluster_->RecordDetection(idx_, addr);
    RemoveMember(addr, now);
    if (cfg.remove_broadcast) {
      std::string msg = EncodeControl(addr, "REMOVE");
      for (const auto& [peer, m] : members_)
        if (peer != addr_) Send(peer, msg);
    }
  }
  // fail-list cooldown expiry (slave.go:484-497)
  // @gfs:transition FAILED->UNKNOWN guard=cooldown_expiry
  double t_cool = cfg.t_cooldown * cfg.period;
  for (auto it = fail_list_.begin(); it != fail_list_.end();) {
    if (it->second < now - t_cool)
      it = fail_list_.erase(it);
    else
      ++it;
  }
  if (members_.find(addr_) == members_.end()) return;  // removed-self
  std::string msg = EncodeSelf();
  if (cfg.push_random) {
    // campaign/north-star push topology: fanout random listed peers per
    // tick (the tensor engine's topology='random' — event propagation
    // in O(log N) rounds instead of the ring's O(N) position walk)
    std::vector<const std::string*> peers;
    peers.reserve(members_.size());
    for (const auto& [addr, m] : members_)
      if (addr != addr_) peers.push_back(&addr);
    int k = std::min<int>(cfg.fanout, static_cast<int>(peers.size()));
    // partial Fisher-Yates: first k entries are a uniform sample
    for (int i = 0; i < k; ++i) {
      int j = i + static_cast<int>(NextRand() % (peers.size() - i));
      std::swap(peers[i], peers[j]);
      Send(*peers[i], msg);
    }
    return;
  }
  // ring push to sorted list positions self-1, self+1, self+2
  // (slave.go:515-542); std::map iteration order == sorted addresses
  std::vector<const std::string*> ordered;
  ordered.reserve(members_.size());
  for (const auto& [addr, m] : members_) ordered.push_back(&addr);
  int n = static_cast<int>(ordered.size());
  int self_i = 0;
  for (int i = 0; i < n; ++i)
    if (*ordered[i] == addr_) self_i = i;
  for (int off : {-1, 1, 2}) {
    const std::string& peer = *ordered[((self_i + off) % n + n) % n];
    if (peer != addr_) Send(peer, msg);
  }
}

void Node::StopGraceful() {
  if (alive_) {
    std::string msg = EncodeControl(addr_, "LEAVE");
    for (const auto& [peer, m] : members_)
      if (peer != addr_) Send(peer, msg);
  }
  alive_ = false;
}

void Node::StopCrash() { alive_ = false; }

std::vector<std::string> Node::MemberAddrs() const {
  std::vector<std::string> out;
  out.reserve(members_.size());
  for (const auto& [addr, m] : members_) out.push_back(addr);
  return out;
}

std::vector<std::string> Node::SuspectAddrs() const {
  std::vector<std::string> out;
  out.reserve(suspects_.size());
  for (const auto& [addr, t] : suspects_) out.push_back(addr);
  return out;
}

long long Node::HbOf(const std::string& addr) const {
  auto it = members_.find(addr);
  return it == members_.end() ? -1 : it->second.hb;
}

// ---------------------------------------------------------------------------
// Cluster

bool Cluster::Start() {
  epoll_fd_ = ::epoll_create1(0);
  if (epoll_fd_ < 0) return false;
  for (auto& node : nodes_) {
    if (!node->Open()) return false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = static_cast<uint32_t>(node->idx());
    ::epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, node->fd(), &ev);
  }
  // everyone joins through the introducer (slave.go:288-308)
  {
    MutexLock lk(mu_);
    Node* intro = nodes_[cfg_.introducer].get();
    for (auto& node : nodes_) {
      node->AssertLockHeld();
      node->ResetState();
    }
    for (auto& node : nodes_) {
      node->AssertLockHeld();
      if (node->idx() != cfg_.introducer)
        node->Send(intro->addr(), EncodeControl(node->addr(), "JOIN"));
    }
    next_tick_ = MonotonicNow() + cfg_.period;
  }
  running_ = true;
  loop_ = std::thread([this] {
    while (running_) LoopBody();
  });
  return true;
}

void Cluster::LoopBody() {
  epoll_event events[64];
  double deadline;
  {
    MutexLock lk(mu_);
    deadline = next_tick_;
  }
  double now = MonotonicNow();
  double wait_s = deadline - now;
  int timeout_ms = wait_s > 0 ? static_cast<int>(wait_s * 1000) + 1 : 0;
  int nfds = ::epoll_wait(epoll_fd_, events, 64, std::min(timeout_ms, 50));
  MutexLock lk(mu_);
  char buf[65536];
  for (int e = 0; e < nfds; ++e) {
    Node* node = nodes_[events[e].data.u32].get();
    node->AssertLockHeld();
    while (true) {
      ssize_t len = ::recv(node->fd(), buf, sizeof(buf), 0);
      if (len <= 0) break;
      node->HandleDatagram(std::string(buf, static_cast<size_t>(len)));
    }
  }
  now = MonotonicNow();
  if (now >= next_tick_) {
    double t0 = MonotonicNow();
    for (auto& node : nodes_) {
      node->AssertLockHeld();
      node->Tick(now);
    }
    double tick_ms = (MonotonicNow() - t0) * 1000.0;
    if (obs_enabled_) EmitRoundTick(tick_ms);
    round_ += 1;
    next_tick_ += cfg_.period;
    if (next_tick_ < now) next_tick_ = now + cfg_.period;  // fell behind
  }
}

void Cluster::EmitRoundTick(double tick_ms) {
  // one round_tick per completed protocol round — the ground truth this
  // in-process engine KNOWS (nodes_[i]->alive()): n_alive plus the
  // round's detection/false-positive deltas, so a recorded native
  // stream feeds the streaming monitor's rolling-FPR invariant exactly
  // like a tensor or udp trace.  Native extras ride the same detail:
  // members_listed (sum of live view sizes), sends (datagrams that
  // left a socket this round) and tick_ms (wall-clock cost of the tick
  // pass — the per-round latency histogram's sample).  The suspicion
  // counters appear only when armed (the n/a-not-0 inference rule);
  // fp_suppressed stays absent (per-refute ground truth is sim-only).
  int n_alive = 0;
  long long members_listed = 0;
  long long sus_entered = 0, sus_refut = 0, sus_now = 0;
  for (const auto& node : nodes_) {
    node->AssertLockHeld();
    if (node->alive()) {
      n_alive += 1;
      members_listed += static_cast<long long>(node->members_.size());
      sus_now += static_cast<long long>(node->suspects_.size());
    }
    sus_entered += node->sus_entered_;
    sus_refut += node->sus_refutations_;
  }
  long long det_d = det_total_ - obs_det0_;
  long long fp_d = fp_total_ - obs_fp0_;
  std::ostringstream d;
  d << "n_alive=" << n_alive << " true_detections=" << (det_d - fp_d)
    << " false_positives=" << fp_d << " members_listed=" << members_listed
    << " sends=" << (sends_total_ - obs_sends0_) << " tick_ms="
    << std::fixed << std::setprecision(3) << tick_ms;
  if (cfg_.t_suspect > 0) {
    d << " suspects_entered=" << (sus_entered - obs_sus_entered0_)
      << " refutations=" << (sus_refut - obs_refut0_)
      << " suspects_now=" << sus_now;
  }
  obs_det0_ = det_total_;
  obs_fp0_ = fp_total_;
  obs_sends0_ = sends_total_;
  obs_sus_entered0_ = sus_entered;
  obs_refut0_ = sus_refut;
  ObsEmit("round_tick", -1, -1, d.str());
}

void Cluster::Stop() {
  if (running_.exchange(false)) loop_.join();
  if (epoll_fd_ >= 0) {
    ::close(epoll_fd_);
    epoll_fd_ = -1;
  }
  for (auto& node : nodes_) node->Close();
}

void Cluster::Crash(int i) {
  MutexLock lk(mu_);
  nodes_[i]->AssertLockHeld();
  nodes_[i]->StopCrash();
  // ground truth stamped at the injection seam: a dead process bumps
  // nothing, so the hb_freeze rides along (the tensor decode's pairing)
  // @gfs:inject crash
  ObsEmit("crash", -1, i, "scheduled=1");
  // @gfs:inject hb_freeze
  ObsEmit("hb_freeze", -1, i, "");
}

void Cluster::Leave(int i) {
  MutexLock lk(mu_);
  nodes_[i]->AssertLockHeld();
  nodes_[i]->StopGraceful();
  // @gfs:inject leave
  ObsEmit("leave", -1, i, "");
}

void Cluster::Join(int i) {
  MutexLock lk(mu_);
  Node* node = nodes_[i].get();
  node->AssertLockHeld();
  if (!node->alive()) node->ResetState();
  // JOIN to the introducer; lost if the introducer is down (SPOF kept,
  // slave.go:22)
  node->Send(nodes_[cfg_.introducer]->addr(),
             EncodeControl(node->addr(), "JOIN"));
  // @gfs:inject join
  ObsEmit("join", -1, i, "");
}

void Cluster::Advance(int rounds) {
  int target;
  {
    MutexLock lk(mu_);
    target = round_ + rounds;
  }
  while (running_) {
    {
      MutexLock lk(mu_);
      if (round_ >= target) return;
    }
    std::this_thread::sleep_for(
        std::chrono::duration<double>(cfg_.period / 4));
  }
}

int Cluster::Membership(int observer, int* out, int cap) {
  MutexLock lk(mu_);
  std::vector<int> ids;
  nodes_[observer]->AssertLockHeld();
  for (const auto& addr : nodes_[observer]->MemberAddrs()) {
    int idx = IdxOf(addr);
    if (idx >= 0) ids.push_back(idx);
  }
  std::sort(ids.begin(), ids.end());
  int n = std::min(static_cast<int>(ids.size()), cap);
  std::copy(ids.begin(), ids.begin() + n, out);
  return n;
}

int Cluster::Suspects(int observer, int* out, int cap) {
  MutexLock lk(mu_);
  std::vector<int> ids;
  nodes_[observer]->AssertLockHeld();
  for (const auto& addr : nodes_[observer]->SuspectAddrs()) {
    int idx = IdxOf(addr);
    if (idx >= 0) ids.push_back(idx);
  }
  std::sort(ids.begin(), ids.end());
  int n = std::min(static_cast<int>(ids.size()), cap);
  std::copy(ids.begin(), ids.begin() + n, out);
  return n;
}

long long Cluster::Incarnation(int observer, int subject) {
  MutexLock lk(mu_);
  nodes_[observer]->AssertLockHeld();
  return nodes_[observer]->HbOf(nodes_[subject]->addr());
}

int Cluster::AliveNodes(int* out, int cap) {
  MutexLock lk(mu_);
  int count = 0;
  for (const auto& node : nodes_) {
    node->AssertLockHeld();
    if (node->alive() && count < cap) out[count++] = node->idx();
  }
  return count;
}

int Cluster::DrainEvents(int* out, int cap) {
  MutexLock lk(mu_);
  int n = std::min(static_cast<int>(events_.size()), cap / 4);
  for (int i = 0; i < n; ++i) {
    out[i * 4 + 0] = events_[i].round;
    out[i * 4 + 1] = events_[i].observer;
    out[i * 4 + 2] = events_[i].subject;
    out[i * 4 + 3] = events_[i].false_positive;
  }
  events_.erase(events_.begin(), events_.begin() + n);
  return n;
}

// ---------------------------------------------------------------------------
// round-16 control/observation surface

int Cluster::Configure(const std::string& kv) {
  MutexLock lk(mu_);
  if (running_) return -1;  // protocol knobs are fixed once the loop runs
  std::istringstream in(kv);
  std::string tok;
  while (in >> tok) {
    size_t eq = tok.find('=');
    if (eq == std::string::npos) return -1;
    const std::string key = tok.substr(0, eq);
    const std::string val = tok.substr(eq + 1);
    char* end = nullptr;
    if (key == "push") {
      if (val != "ring" && val != "random") return -1;
      cfg_.push_random = (val == "random");
    } else if (key == "fanout") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 1) return -1;
      cfg_.fanout = static_cast<int>(v);
    } else if (key == "remove_broadcast") {
      cfg_.remove_broadcast = val != "0";
    } else if (key == "t_suspect") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 0) return -1;
      cfg_.t_suspect = static_cast<int>(v);
    } else if (key == "lh_multiplier") {
      long v = std::strtol(val.c_str(), &end, 10);
      if (end == val.c_str() || *end != '\0' || v < 0) return -1;
      cfg_.lh_multiplier = static_cast<int>(v);
    } else if (key == "lh_frac") {
      double v = std::strtod(val.c_str(), &end);
      if (end == val.c_str() || *end != '\0' || !(v > 0.0 && v < 1.0))
        return -1;
      cfg_.lh_frac = v;
    } else {
      return -1;
    }
  }
  return 0;
}

void Cluster::ObsEmit(const char* kind, int observer, int subject,
                      const std::string& detail) {
  if (!obs_enabled_) return;
  std::ostringstream line;
  line << kind << ' ' << (round_ - obs_round0_) << ' ' << observer << ' '
       << subject;
  if (!detail.empty()) line << ' ' << detail;
  line << '\n';
  obs_buf_ += line.str();
}

void Cluster::ObsEmit(const char* kind, int observer,
                      const std::string& subject_addr,
                      const std::string& detail) {
  if (!obs_enabled_) return;
  ObsEmit(kind, observer, IdxOf(subject_addr), detail);
}

int Cluster::ObsEnable() {
  MutexLock lk(mu_);
  obs_enabled_ = true;
  // rebase the stamped round clock to 0 and zero the per-round deltas:
  // the recorded stream lives in the arming-relative frame the udp
  // campaign runner's streams use (its cluster clock starts at 0)
  obs_round0_ = round_;
  obs_det0_ = det_total_;
  obs_fp0_ = fp_total_;
  obs_sends0_ = sends_total_;
  long long e = 0, r = 0;
  for (const auto& node : nodes_) {
    node->AssertLockHeld();
    e += node->sus_entered_;
    r += node->sus_refutations_;
  }
  obs_sus_entered0_ = e;
  obs_refut0_ = r;
  return round_;
}

int Cluster::ObsDrain(char* out, int cap) {
  MutexLock lk(mu_);
  if (obs_buf_.empty() || cap <= 1) return 0;
  size_t take = obs_buf_.size();
  if (take > static_cast<size_t>(cap - 1)) {
    // drain whole lines only: find the last newline that fits
    size_t nl = obs_buf_.rfind('\n', static_cast<size_t>(cap - 2));
    if (nl == std::string::npos) return -1;  // one line > cap: grow buffer
    take = nl + 1;
  }
  std::memcpy(out, obs_buf_.data(), take);
  out[take] = '\0';
  obs_buf_.erase(0, take);
  return static_cast<int>(take);
}

std::string Cluster::VitalsText() {
  MutexLock lk(mu_);
  int n_alive = 0;
  long long sus_now = 0, entered = 0, refut = 0, confirms = 0;
  for (const auto& node : nodes_) {
    node->AssertLockHeld();
    if (node->alive()) {
      n_alive += 1;
      sus_now += static_cast<long long>(node->suspects_.size());
    }
    entered += node->sus_entered_;
    refut += node->sus_refutations_;
    confirms += node->sus_confirms_;
  }
  std::ostringstream os;
  AppendVital(os, "round", round_);
  AppendVital(os, "n_alive", n_alive);
  AppendVital(os, "detections", det_total_);
  AppendVital(os, "false_positives", fp_total_);
  if (cfg_.t_suspect > 0) {
    AppendVital(os, "suspects_now", sus_now);
    AppendVital(os, "suspects_entered", entered);
    AppendVital(os, "refutations", refut);
    AppendVital(os, "confirms", confirms);
  }
  return os.str();
}

int Cluster::ScenarioLoad(const std::string& table, int round0) {
  GateTable g;
  std::istringstream in(table);
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    std::istringstream ls(line);
    std::string kind;
    ls >> kind;
    if (kind == "name") {
      ls >> g.name;
      continue;
    }
    int start = 0, end = 0;
    if (!(ls >> start >> end) || start < 0 || end <= start) return -1;
    g.horizon = std::max(g.horizon, end);
    auto read_mask = [&](std::vector<char>& mask) -> bool {
      mask.assign(cfg_.n, 0);
      int id = 0;
      bool any = false;
      while (ls >> id) {
        if (id < 0 || id >= cfg_.n) return false;
        mask[id] = 1;
        any = true;
      }
      return any;
    };
    if (kind == "flap") {
      GateFlap f;
      f.start = start;
      f.end = end;
      if (!(ls >> f.up >> f.down) || f.up < 1 || f.down < 1) return -1;
      if (!read_mask(f.mask)) return -1;
      g.flaps.push_back(std::move(f));
    } else if (kind == "outage") {
      GateOutage o;
      o.start = start;
      o.end = end;
      if (!read_mask(o.mask)) return -1;
      g.outages.push_back(std::move(o));
    } else if (kind == "slow") {
      GateSlow s;
      s.start = start;
      s.end = end;
      if (!(ls >> s.stride) || s.stride < 2) return -1;
      if (!read_mask(s.mask)) return -1;
      g.slows.push_back(std::move(s));
    } else if (kind == "partition") {
      GatePartition p;
      p.start = start;
      p.end = end;
      p.pid.reserve(cfg_.n);
      int pid = 0;
      while (ls >> pid) p.pid.push_back(pid);
      if (static_cast<int>(p.pid.size()) != cfg_.n) return -1;
      g.partitions.push_back(std::move(p));
    } else {
      return -1;
    }
  }
  MutexLock lk(mu_);
  gates_ = std::move(g);
  gates_armed_ = true;
  scn_round0_ = round0;
  ObsEmit("scenario_arm", -1, -1,
          "name=" + (gates_.name.empty() ? std::string("scenario")
                                         : gates_.name) +
              " horizon=" + std::to_string(gates_.horizon));
  return 0;
}

void Cluster::ScenarioClear() {
  MutexLock lk(mu_);
  if (gates_armed_) ObsEmit("scenario_clear", -1, -1, "");
  gates_armed_ = false;
}

bool Cluster::ScenarioDrops(int src, const std::string& dst_addr) const {
  // ScenarioRuntime.drops, minus Bernoulli loss (rejected at compile
  // time by native.py): called from Node::Send with mu_ held
  if (!gates_armed_) return false;
  int r = round_ - scn_round0_;
  for (const auto& f : gates_.flaps) {
    if (f.mask[src] && f.start <= r && r < f.end &&
        (r - f.start) % (f.up + f.down) >= f.up)
      return true;
  }
  auto dst_it = addr_to_idx_.find(dst_addr);
  int dst = dst_it == addr_to_idx_.end() ? -1 : dst_it->second;
  for (const auto& o : gates_.outages) {
    if (o.start <= r && r < o.end &&
        (o.mask[src] || (dst >= 0 && o.mask[dst])))
      return true;
  }
  for (const auto& p : gates_.partitions) {
    if (p.start <= r && r < p.end && dst >= 0 && p.pid[src] != p.pid[dst])
      return true;
  }
  for (const auto& s : gates_.slows) {
    if (s.mask[src] && s.start <= r && r < s.end && r % s.stride != 0)
      return true;
  }
  return false;
}

void Cluster::SeedFull() {
  MutexLock lk(mu_);
  double now = MonotonicNow();
  std::vector<std::string> addrs;
  addrs.reserve(nodes_.size());
  for (const auto& node : nodes_) addrs.push_back(node->addr());
  for (auto& node : nodes_) {
    node->AssertLockHeld();
    if (node->alive()) node->SeedMembers(addrs, now);
  }
}

int Cluster::Warm() {
  MutexLock lk(mu_);
  for (const auto& node : nodes_) {
    node->AssertLockHeld();
    if (!node->alive()) continue;
    // full view with every counter past the hb<=1 grace — and NO churn
    // residue: a pending suspicion means some entry is already past
    // t_fail silent (it would confirm right after the caller starts
    // its run — observed as a warm-gate FP burst in the stream's first
    // rounds), and a non-empty fail list means a detection fired within
    // the cooldown window (the view only LOOKS full because the entry
    // was just re-added at a stale-prone counter)
    if (static_cast<int>(node->members_.size()) != cfg_.n) return 0;
    if (!node->suspects_.empty() || !node->fail_list_.empty()) return 0;
    for (const auto& [addr, m] : node->members_)
      if (m.hb <= 1) return 0;
  }
  return 1;
}

}  // namespace
}  // namespace gossipfs

// ---------------------------------------------------------------------------
// C ABI for ctypes (gossipfs_tpu/native.py)

extern "C" {

void* gfs_cluster_create(int n, int base_port, double period_s, int t_fail,
                         int t_cooldown, int min_group, int fresh_cooldown,
                         int introducer) {
  gossipfs::Config cfg;
  cfg.n = n;
  cfg.base_port = base_port;
  cfg.period = period_s;
  cfg.t_fail = t_fail;
  cfg.t_cooldown = t_cooldown;
  cfg.min_group = min_group;
  cfg.fresh_cooldown = fresh_cooldown != 0;
  cfg.introducer = introducer;
  return new gossipfs::Cluster(cfg);
}

int gfs_cluster_start(void* h) {
  return static_cast<gossipfs::Cluster*>(h)->Start() ? 0 : -1;
}

void gfs_cluster_destroy(void* h) {
  delete static_cast<gossipfs::Cluster*>(h);
}

void gfs_crash(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Crash(i); }
void gfs_leave(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Leave(i); }
void gfs_join(void* h, int i) { static_cast<gossipfs::Cluster*>(h)->Join(i); }

void gfs_advance(void* h, int rounds) {
  static_cast<gossipfs::Cluster*>(h)->Advance(rounds);
}

int gfs_round(void* h) { return static_cast<gossipfs::Cluster*>(h)->Round(); }

int gfs_membership(void* h, int observer, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->Membership(observer, out, cap);
}

// Conformance-harness read seams (round 19): the observer's current
// suspect set and its per-entry heartbeat counter for one subject —
// the same observable surface verdict.py reads off the udp engine's
// node.rt.suspects / members[addr].hb.
int gfs_suspects(void* h, int observer, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->Suspects(observer, out, cap);
}

long long gfs_incarnation(void* h, int observer, int subject) {
  return static_cast<gossipfs::Cluster*>(h)->Incarnation(observer, subject);
}

int gfs_alive(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->AliveNodes(out, cap);
}

int gfs_drain_events(void* h, int* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->DrainEvents(out, cap);
}

// -- round-16 observability + campaign surface ------------------------------

// Pre-start protocol knobs ("k=v k=v ..."): push=ring|random, fanout,
// remove_broadcast, t_suspect, lh_multiplier, lh_frac.  0 ok, -1 on a
// bad table or a started cluster.
int gfs_configure(void* h, const char* kv) {
  return static_cast<gossipfs::Cluster*>(h)->Configure(kv ? kv : "");
}

// Arm event buffering and rebase the stamped round clock; returns the
// absolute engine round the stream's round 0 maps to.
int gfs_obs_enable(void* h) {
  return static_cast<gossipfs::Cluster*>(h)->ObsEnable();
}

// Drain buffered event lines ("kind round observer subject k=v ...").
// Returns bytes written (whole lines only, NUL-terminated), 0 when the
// buffer is empty, -1 when a single line exceeds cap (grow and retry).
int gfs_obs_drain(void* h, char* out, int cap) {
  return static_cast<gossipfs::Cluster*>(h)->ObsDrain(out, cap);
}

// Load the fault-gate table (text form; see Cluster::ScenarioLoad),
// windows anchored at absolute round `round0`.  0 ok, -1 on parse error.
int gfs_scenario_load(void* h, const char* table, int round0) {
  return static_cast<gossipfs::Cluster*>(h)->ScenarioLoad(table ? table : "",
                                                          round0);
}

void gfs_scenario_clear(void* h) {
  static_cast<gossipfs::Cluster*>(h)->ScenarioClear();
}

void gfs_seed_full(void* h) {
  static_cast<gossipfs::Cluster*>(h)->SeedFull();
}

// Halt the epoll loop + close sockets WITHOUT destroying state: the
// buffered obs events stay drainable.  On a 1-core host a big
// gfs_obs_drain parse while the loop still runs starves the protocol
// (rounds lag -> wall-clock staleness -> a manufactured FP cascade in
// the stream's tail — observed at n=256); runners stop first, then
// drain at leisure.
void gfs_stop(void* h) { static_cast<gossipfs::Cluster*>(h)->Stop(); }

int gfs_warm(void* h) { return static_cast<gossipfs::Cluster*>(h)->Warm(); }

// Codec surface for parity tests: input lines "addr hb ts\n", output the
// wire string (and the reverse).  snprintf semantics: writes at most cap-1
// bytes + NUL and returns the FULL required length, so callers can detect
// truncation and retry with a bigger buffer.
static int CopyOut(const std::string& text, char* out, int cap) {
  int n = std::min(static_cast<int>(text.size()), cap - 1);
  if (n > 0) std::memcpy(out, text.data(), static_cast<size_t>(n));
  if (cap > 0) out[n] = '\0';
  return static_cast<int>(text.size());
}

// Uniform vitals ("k=v k=v ..." — obs.schema.VITALS_FIELDS names only;
// unknowable fields are ABSENT, rendered n/a by the Python surface).
// snprintf sizing semantics, like the codec calls below.
int gfs_vitals(void* h, char* out, int cap) {
  return CopyOut(static_cast<gossipfs::Cluster*>(h)->VitalsText(), out, cap);
}

int gfs_codec_encode(const char* lines, char* out, int cap) {
  std::vector<gossipfs::MemberEntry> entries;
  std::istringstream in(lines);
  std::string addr;
  long long hb;
  double ts;
  while (in >> addr >> hb >> ts)
    entries.push_back(gossipfs::MemberEntry{addr, hb, ts});
  return CopyOut(gossipfs::EncodeMembers(entries), out, cap);
}

int gfs_codec_decode(const char* wire, char* out, int cap) {
  auto entries = gossipfs::DecodeMembers(wire);
  std::ostringstream os;
  os << std::setprecision(17);
  for (const auto& e : entries) os << e.addr << ' ' << e.hb << ' ' << e.ts << '\n';
  return CopyOut(os.str(), out, cap);
}

}  // extern "C"
