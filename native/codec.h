// Wire codec for the gossip membership protocol.
//
// Byte-for-byte the reference's framing (reference: slave/slave.go:365-385):
// membership lists are entries joined by "<#ENTRY#>" with fields joined by
// "<#INFO#>" (address, heartbeat count, timestamp); control datagrams are
// "addr<CMD>VERB" with VERB in {JOIN, LEAVE, REMOVE} (slave.go:293, 218).
// This is the native (C++) half of the framework's runtime: the same frames
// the Python asyncio parity path (gossipfs_tpu/detector/udp.py) speaks.

#pragma once

#include <optional>
#include <string>
#include <vector>

namespace gossipfs {

inline constexpr char kEntrySep[] = "<#ENTRY#>";
inline constexpr char kFieldSep[] = "<#INFO#>";
inline constexpr char kCmdSep[] = "<CMD>";
// Delta-piggyback frames (protocol_spec.DELTA_GOSSIP): a membership list
// prefixed with this mark carries only the sender's SELECTED entries
// (recently-changed first, round-robin tail refresh, capped) instead of
// the full table.  Receivers max-merge it exactly like a full list; the
// mark only exists so anti-entropy full pushes stay distinguishable for
// wire accounting and conformance fuzzing.
inline constexpr char kDeltaMark[] = "<#DELTA#>";

struct MemberEntry {
  std::string addr;
  long long hb = 0;
  double ts = 0.0;  // sender-local timestamp; receivers re-stamp locally
};

struct ControlMsg {
  std::string arg;   // the address the verb applies to
  std::string verb;  // JOIN | LEAVE | REMOVE
};

// Membership list -> wire string (encode, slave.go:365-373).
std::string EncodeMembers(const std::vector<MemberEntry>& members);

// Wire string -> entries (decode, slave.go:375-385).  Malformed chunks
// (fewer than 2 fields, non-numeric hb) are skipped, like the reference's
// silent parse behavior.
std::vector<MemberEntry> DecodeMembers(const std::string& payload);

// Delta frame: kDeltaMark + EncodeMembers(selected entries).
std::string EncodeDelta(const std::vector<MemberEntry>& members);

// True iff the payload starts with kDeltaMark.
bool IsDelta(const std::string& payload);

// Entries of a delta frame; empty when the payload is not a delta frame.
std::vector<MemberEntry> DecodeDelta(const std::string& payload);

// Control framing: "addr<CMD>VERB".
std::string EncodeControl(const std::string& addr, const std::string& verb);

// Returns the control message if the payload contains "<CMD>", else nullopt
// (in which case the payload is a membership list — GetMsg's dispatch rule,
// slave.go:207-248).
std::optional<ControlMsg> DecodeControl(const std::string& payload);

}  // namespace gossipfs
