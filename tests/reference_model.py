"""Naive per-node Python model of the gossip protocol — the golden oracle.

Deliberately written object-style (one dict per node, explicit message loops),
mirroring how the reference Go code manipulates per-node ``[]Member`` slices
(reference: slave/slave.go:414-544), so that it shares *no code shape* with the
vectorized kernel.  Tests compare the tensor sim against this model
entry-for-entry every round on small N ("golden-trace equivalence", SURVEY §4).

Synchronous-rounds semantics identical to gossipfs_tpu.core.rounds:
events -> tick (refresh/bump/detect/remove-broadcast/cooldown) -> merge -> age+1.
Only rows of *alive* nodes are meaningful (dead processes don't run).

One deliberate supersession of the reference is modeled here too: gossip
carries only entries within ``config.rebase_window`` of the subject's own
(post-bump) counter.  In-window this is invisible — same-incarnation
copies lag by O(t_fail) hops — but copies of an OLD incarnation more than
a window ahead are excluded instead of dominating the reference's
incarnation-free max-merge (slave.go:419-424), which is what lets the
narrow-dtype rebased storage resolve zombie-rejoin instead of inheriting
the ambiguity (core/rounds.py `_pre_tick`/`_merge`).
"""

from __future__ import annotations

import dataclasses

from gossipfs_tpu.config import AGE_CLAMP

UNKNOWN, MEMBER, FAILED, SUSPECT = 0, 1, 2, 3


@dataclasses.dataclass
class Entry:
    hb: int = 0
    age: int = 0
    status: int = UNKNOWN


class NaiveSim:
    def __init__(self, config, member_mask=None):
        self.cfg = config
        n = config.n
        members = list(range(n)) if member_mask is None else [
            j for j in range(n) if member_mask[j]
        ]
        self.alive = [j in set(members) for j in range(n)]
        self.tables = []
        for i in range(n):
            row = [Entry() for _ in range(n)]
            if self.alive[i]:
                for j in members:
                    row[j] = Entry(hb=0, age=0, status=MEMBER)
            self.tables.append(row)
        self.round = 0
        self.fail_events = []  # list of (round, observer, subject)

    # -- helpers -----------------------------------------------------------
    def _listed(self, e):
        """In the membership list: MEMBER, or (suspicion armed) SUSPECT —
        a suspect is still a member pending refute/confirm."""
        if getattr(self.cfg, "suspicion", None) is None:
            return e.status == MEMBER
        return e.status in (MEMBER, SUSPECT)

    def _member_count(self, i):
        return sum(1 for e in self.tables[i] if self._listed(e))

    def _ring_in_edges(self, i):
        """Receiver-side ring inversion over i's own table, cyclic id order.

        Suspicion: SUSPECT entries stay ring push targets (still list
        positions — core/topology.ring_edges_from_status agrees);
        excluding them would make ring suspicion self-reinforcing.
        """
        n = self.cfg.n
        members = [
            j for j in range(n)
            if j != i and self._listed(self.tables[i][j])
        ]
        if not members:
            return [i, i, i]
        next1 = min(members, key=lambda j: (j - i) % n)
        prev1 = min(members, key=lambda j: (i - j) % n)
        rest = [j for j in members if j != prev1]
        prev2 = min(rest, key=lambda j: (i - j) % n) if rest else i
        return [next1, prev1, prev2]

    # -- one synchronous round --------------------------------------------
    def step(self, edges=None, crash=(), leave=(), join=()):
        cfg, n = self.cfg, self.cfg.n

        # events: leave broadcast, crash, join via introducer
        for j in leave:
            if not self.alive[j]:
                continue
            for i in range(n):
                if self.alive[i] and self._listed(self.tables[i][j]):
                    # faithful mode: fail-list entry keeps its stale timestamp
                    self.tables[i][j].status = FAILED
                    if self.cfg.fresh_cooldown:
                        self.tables[i][j].age = 0
            self.alive[j] = False
        for j in crash:
            self.alive[j] = False
        joiners = [j for j in join if not self.alive[j] and self.alive[cfg.introducer]]
        for j in joiners:  # introducer appends unconditionally
            if j != cfg.introducer:
                self.tables[cfg.introducer][j] = Entry(0, 0, MEMBER)
        for j in joiners:  # push to every previously-alive member: add if unknown
            for i in range(n):
                if self.alive[i] and self.tables[i][j].status == UNKNOWN:
                    self.tables[i][j] = Entry(0, 0, MEMBER)
        for j in joiners:  # joiner adopts the introducer's pushed list
            row = []
            for k in range(n):
                e = self.tables[cfg.introducer][k]
                row.append(
                    Entry(e.hb, 0, MEMBER) if e.status == MEMBER else Entry()
                )
            row[j] = Entry(0, 0, MEMBER)
            self.tables[j] = row
            self.alive[j] = True

        # the gossip window anchors on each subject's own pre-tick counter
        # + 1 (== post-bump when the subject bumps); captured post-events so
        # a join's row reset takes effect immediately
        prediag = [self.tables[j][j].hb for j in range(n)]

        # tick
        sus = getattr(cfg, "suspicion", None)
        active = [False] * n
        fails = []
        for i in range(n):
            if not self.alive[i]:
                continue
            if self._member_count(i) < cfg.min_group:
                # below min_group detection is disabled, so suspicion is
                # moot: refresh the stamp and revert SUSPECT -> MEMBER
                for e in self.tables[i]:
                    if self._listed(e):
                        e.age = 0
                        if e.status == SUSPECT:
                            e.status = MEMBER
                continue
            active[i] = True
            # Lifeguard local health (pre-tick counts, like the tensor's
            # status0 anchor): an anomalous SUSPECT fraction in MY OWN
            # view stretches MY confirmation window
            confirm_thr = None
            if sus is not None:
                listed_cnt = self._member_count(i)
                sus_cnt = sum(
                    1 for e in self.tables[i] if e.status == SUSPECT
                )
                degraded = (sus.lh_multiplier > 0
                            and sus_cnt > sus.lh_frac * listed_cnt)
                confirm_thr = sus.confirm_after(cfg.t_fail, degraded)
            me = self.tables[i][i]
            if me.status == MEMBER:  # no self entry -> no bump (slave.go:443-448)
                me.hb += 1
                me.age = 0
            for j in range(n):
                if j == i:
                    continue
                e = self.tables[i][j]
                if sus is None:
                    if (
                        e.status == MEMBER
                        and e.hb > cfg.hb_grace
                        and e.age > cfg.t_fail
                    ):
                        e.status = FAILED
                        if cfg.fresh_cooldown:
                            e.age = 0
                        fails.append((i, j))
                else:
                    # SWIM lifecycle: MEMBER -> SUSPECT at t_fail silent
                    # rounds; SUSPECT -> FAILED (the detection event) at
                    # confirm_thr; both judged on the entry's pre-write
                    # status, so an entry spends >= 1 round SUSPECT
                    if e.status == SUSPECT and e.age > confirm_thr:
                        e.status = FAILED
                        if cfg.fresh_cooldown:
                            e.age = 0
                        fails.append((i, j))
                    elif (
                        e.status == MEMBER
                        and e.hb > cfg.hb_grace
                        and e.age > cfg.t_fail
                    ):
                        e.status = SUSPECT
        self.fail_events.extend((self.round, i, j) for i, j in fails)
        if cfg.remove_broadcast:
            removed = {j for _, j in fails}
            for j in removed:
                for i in range(n):
                    if self.alive[i] and self.tables[i][j].status == MEMBER:
                        self.tables[i][j].status = FAILED
                        if cfg.fresh_cooldown:
                            self.tables[i][j].age = 0
        for i in range(n):
            if not self.alive[i]:
                continue
            for e in self.tables[i]:
                if e.status == FAILED and e.age > cfg.t_cooldown:
                    e.status = UNKNOWN

        # merge: receivers gather active senders' tables, elementwise max
        snapshot = [[dataclasses.replace(e) for e in row] for row in self.tables]
        for i in range(n):
            if not self.alive[i]:
                continue
            row_edges = (
                self._ring_in_edges(i)
                if self.cfg.topology == "ring"
                else [int(e) for e in edges[i]]
            )
            for k in row_edges:
                if not active[k]:
                    continue
                for j in range(n):
                    se = snapshot[k][j]
                    if not self._listed(se):
                        # suspicion: a sender's SUSPECT entries keep
                        # gossiping (still list entries; the receiver's
                        # strict max-merge makes stale copies harmless)
                        continue
                    # window rule: gossip carries values in
                    # [view_base, view_base + window], the view's exact
                    # representable range (zombie exclusion only once the
                    # base has lifted off zero)
                    vb = max(prediag[j] + 1 - cfg.rebase_window, 0)
                    if se.hb < vb or se.hb > vb + cfg.rebase_window:
                        continue
                    e = self.tables[i][j]
                    if self._listed(e) and se.hb > e.hb:
                        # REFUTATION: a fresher counter observed while
                        # SUSPECT cancels the pending failure
                        e.hb = se.hb
                        e.age = 0
                        e.status = MEMBER
                    elif e.status == UNKNOWN:
                        self.tables[i][j] = Entry(se.hb, 0, MEMBER)

        for i in range(n):
            if self.alive[i]:
                for e in self.tables[i]:
                    # saturate like the sim's age lane (state.py: every
                    # protocol comparison is against a small threshold, so
                    # the clamp is part of the contract, not an artifact)
                    e.age = min(e.age + 1, AGE_CLAMP)
        self.round += 1
