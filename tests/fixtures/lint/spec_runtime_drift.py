"""Seeded drift for spec-runtime-protocol: SuspicionRuntime lost the
`refute` lifecycle verb (the SUSPECT->MEMBER contract edge) and its
degraded() formula no longer references lh_frac (mounted over
gossipfs_tpu/suspicion/runtime.py)."""


class SuspicionRuntime:
    def __init__(self, params):
        self.params = params
        self.pending = {}

    def suspect(self, addr, now):
        if addr in self.pending:
            return False
        self.pending[addr] = now
        return True

    def adopt(self, addr, now):
        self.pending.setdefault(addr, now)

    def expired(self, addr, now, window):
        t0 = self.pending.get(addr)
        return t0 is not None and now - t0 > window

    # DRIFT: no refute() — refuting evidence can no longer cancel a
    # pending failure through the runtime

    def confirm(self, addr):
        self.pending.pop(addr, None)

    def drop(self, addr):
        self.pending.pop(addr, None)

    def degraded(self, n_listed):
        # DRIFT: hardwired threshold instead of the lh_frac formula
        return len(self.pending) > 4

    def t_suspect_window(self, unit, n_listed):
        mult = 1 + (self.params.lh_multiplier
                    if self.degraded(n_listed) else 0)
        return self.params.t_suspect * mult * unit
