"""Trigger fixture for the jit-hygiene rule: a host clock call in a
traced module, plus a sync call and a Python branch on a traced value
inside a lax.scan body.  Mounted under core/ by tests/test_analysis.py
only — never imported."""

import time

import jax.numpy as jnp
from jax import lax


def bad_scan(xs):
    def step(carry, x):
        stamp = time.time()  # host clock: freezes at trace time
        if carry > 0:  # Python branch on a traced value
            x = x + 1
        host = x.item()  # device sync, once per scan step
        return carry + x, host + stamp

    return lax.scan(step, jnp.int32(0), xs)
