"""Trigger fixture for the asyncio-hygiene rule: a blocking sleep inside
a coroutine and a dropped create_task handle.  Mounted under detector/
by tests/test_analysis.py only — never imported."""

import asyncio
import time


async def bad_loop():
    asyncio.create_task(asyncio.sleep(1))  # handle dropped: GC can kill it
    time.sleep(0.1)  # blocks every node's heartbeat task
