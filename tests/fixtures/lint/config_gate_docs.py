"""Trigger fixture for the config-gate-docs rule: a stand-in for
config.py whose SimConfig grew a capability gate on a field BASELINE.md
documents nowhere.  Mounted (shadowing config.py) by
tests/test_analysis.py only — never imported."""

import dataclasses


@dataclasses.dataclass(frozen=True)
class SimConfig:
    frobnicate_level: int = 0  # no BASELINE.md config-gate matrix row

    def __post_init__(self) -> None:
        if self.frobnicate_level > 3:
            raise ValueError("frobnicate_level must be <= 3")
