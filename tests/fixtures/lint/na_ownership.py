"""Trigger fixture for the na-render-ownership rule: re-derives the
absent-not-zero "n/a" rendering instead of calling obs.schema.na.
Mounted by tests/test_analysis.py only."""


def bad_render(value):
    return "n/a" if value is None else str(value)
