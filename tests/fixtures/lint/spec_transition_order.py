"""Seeded drift for spec-transition-order: the SUSPECT status write
hoisted ABOVE the confirm-mask computation, so a same-round entry can
satisfy the confirm compare and skip its suspect window entirely
(mounted over gossipfs_tpu/core/rounds.py)."""

import jax.numpy as jnp

SUSPECT = 2
FAILED = 3


def _tick(status, age, stale, suspect_new, degraded, config, sus):
    confirm_age = (
        config.t_fail
        + sus.t_suspect * (1 + jnp.where(degraded, sus.lh_multiplier, 0))
    )
    # DRIFT: SUSPECT written FIRST — the mask below sees post-write
    # status, collapsing the MEMBER->SUSPECT->FAILED two-round floor
    status = jnp.where(suspect_new, SUSPECT, status)
    confirm = (status == SUSPECT) & (age > confirm_age)
    status = jnp.where(confirm, FAILED, status)
    return status
