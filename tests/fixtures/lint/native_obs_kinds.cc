// Trigger fixture for the native-obs-kinds rule: a stand-in engine.cc
// that mints an event kind the schema does not own and serves a vitals
// field outside VITALS_FIELDS.  Mounted over native/engine.cc via the
// RepoIndex overlay by tests/test_analysis.py — never compiled.

void Fixture() {
  // a schema-owned kind: fine
  ObsEmit("round_tick", -1, -1, "n_alive=4");
  // a kind EVENT_KINDS does not know: load_stream would drop the rows
  ObsEmit("bogus_native_kind", -1, 3, "");
  // a schema-owned vitals field: fine
  AppendVital(os, "round", 7);
  // a field outside VITALS_FIELDS: the uniform surface would drift
  AppendVital(os, "not_a_vitals_field", 1);
}
