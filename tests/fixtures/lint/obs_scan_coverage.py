"""Trigger fixture for the obs-scan-coverage rule: a stand-in for
core/rounds.py whose RoundMetrics grew a field that is neither mapped
to a schema kind nor explicitly unexported.  Mounted (shadowing
core/rounds.py) by tests/test_analysis.py only — never imported."""

from typing import NamedTuple


class RoundMetrics(NamedTuple):
    true_detections: object
    unmapped_new_metric: object  # no SCAN_FIELD_MAP / SCAN_UNEXPORTED row


class MetricsCarry(NamedTuple):
    first_detect: object
