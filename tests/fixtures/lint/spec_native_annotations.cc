// Seeded drift for spec-native-annotations (mounted over
// native/engine.cc): an annotation matching no contract row, a
// lifecycle emission with no dominating annotation, and a native
// surface missing most of the contract's required annotations.

// @gfs:transition FAILED->MEMBER guard=zombie_resurrection
void Node::Tick(double now) {
  for (const auto& addr : newly_suspect) {
    cluster_->ObsEmit("suspect", idx_, addr, "");
  }
}
