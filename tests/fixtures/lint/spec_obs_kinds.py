"""Seeded drift for spec-obs-kind-coverage (mounted over
gossipfs_tpu/obs/schema.py): LIFECYCLE_KINDS dropped `refute` and grew
a `resurrect` kind no contract transition emits."""

EVENT_KINDS = {
    "crash": "ground truth: process death injected",
    "hb_freeze": "ground truth: heartbeat counter frozen",
    "leave": "ground truth: graceful departure injected",
    "join": "ground truth: (re)join injected",
    "suspect": "observer entered a suspicion window for subject",
    "refute": "pending suspicion cancelled by evidence of life",
    "confirm": "observer declared subject failed",
    "remove": "observer dropped subject from its membership list",
    "resurrect": "DRIFT: a lifecycle kind with no contract row",
}

LIFECYCLE_KINDS = (
    "crash",
    "hb_freeze",
    "leave",
    "join",
    "suspect",
    "confirm",
    "remove",
    "resurrect",
)
