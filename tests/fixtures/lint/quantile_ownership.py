"""Trigger fixture for the quantile-ownership rule: builds the
p50/p95 rollup keys by hand instead of calling
traffic.workload.quantiles.  Mounted by tests/test_analysis.py only."""


def bad_rollup(vals):
    s = sorted(vals)
    return {"p50_ms": s[len(s) // 2], "p95_ms": s[-1]}
