"""Trigger fixture for the backoff-ownership rule: a retry loop with a
geometrically-growing sleep — the bounded-backoff schedule re-derived
outside shim/retry.py.  Mounted by tests/test_analysis.py only."""

import time


def bad_retry(fn):
    delay = 0.1
    while True:
        try:
            return fn()
        except Exception:
            time.sleep(delay)
            delay *= 2  # the exponential schedule, re-derived
