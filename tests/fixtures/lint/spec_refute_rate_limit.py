"""Seeded drift for spec-refute-rate-limit: the once-per-period REFUTE
rate limit dropped from _on_suspect — every received SUSPECT copy now
triggers a full broadcast, amplifying one episode to O(k x N) datagrams
(mounted over gossipfs_tpu/detector/udp.py)."""

CMD_SEP = "<CMD>"
FIELD_SEP = "<#INFO#>"


class UdpNode:
    def _on_suspect(self, addr):
        now = self._now()
        if addr == self.addr:
            me = self.members.get(self.addr)
            if me is None:
                return
            # DRIFT: no compare against self._last_refute_t, no stamp —
            # the incarnation bump + broadcast runs per received copy
            me.hb += 1
            me.ts = now
            msg = f"{self.addr}{FIELD_SEP}{me.hb}{CMD_SEP}REFUTE"
            for peer in list(self.members):
                if peer != self.addr:
                    self._send(peer, msg)
        elif addr in self.members:
            rt = self._suspicion()
            if rt is not None:
                rt.adopt(addr, now)
