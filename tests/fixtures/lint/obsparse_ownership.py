"""Trigger fixture for the obsparse-ownership rule: hand-parses an obs
event line (json.loads + the "kind" key in one function) instead of
going through obs.schema.Event.from_record.  Mounted by
tests/test_analysis.py only."""

import json


def bad_parse(line: str) -> bool:
    rec = json.loads(line)
    return rec.get("kind") == "confirm"  # schema knowledge, re-derived
