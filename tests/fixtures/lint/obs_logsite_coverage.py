"""Trigger fixture for the obs-logsite-coverage rule: a stand-in for
cosim.py with a kind="..." log site the schema maps don't know.
Mounted (shadowing cosim.py) by tests/test_analysis.py only."""


def emit(log) -> None:
    log.append(round=0, kind="totally_new_kind")  # bypasses the schema
