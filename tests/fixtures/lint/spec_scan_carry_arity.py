"""Seeded drift for scan-carry-arity (mounted over
gossipfs_tpu/parallel/mesh.py): the MetricsCarry out_spec lost a field
— three shard specs against core.rounds' four NamedTuple slots, so
every spec after the dropped one binds to the wrong carry field."""

from jax.sharding import PartitionSpec as P

from gossipfs_tpu.core import rounds

AXIS = "nodes"


def _out_specs():
    rep = P()
    return (
        # DRIFT: first_suspect's spec dropped — 3 specs, 4 fields
        rounds.MetricsCarry(P(AXIS), P(AXIS), P(AXIS)),
        rounds.RoundMetrics(rep, rep, rep, rep, rep, rep),
    )
