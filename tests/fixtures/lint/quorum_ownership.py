"""Trigger fixture for the quorum-ownership rule: re-derives the
W=floor((n+1)/2) arithmetic instead of importing sdfs/quorum.py.
Mounted over gossipfs_tpu/traffic/ by tests/test_analysis.py only —
never imported."""


def bad_write_quorum(n: int) -> int:
    return (n + 1) // 2  # the owned expression, re-derived


def bad_claimed_quorum(n: int) -> int:
    return n // 2 + 1  # the ceil form, re-derived
