"""Seeded drift for conformance-verb-coverage: a FAMILIES table whose
union covers neither the REFUTE wire verb nor the hb_freeze injection —
the corpus silently fell behind the contract.  Mounted at
gossipfs_tpu/conformance/schedules.py by the fixture test."""

FAMILIES = {
    "confirm_expiry": {
        "doc": "unrefuted suspicion confirms",
        "verbs": ["JOIN", "LEAVE", "REMOVE", "SUSPECT"],
        "injections": ["crash", "leave", "join"],
        "probes": ["SUSPECT->FAILED:confirm_window"],
        "engines": ["reference", "tensor", "udp", "native"],
    },
}
