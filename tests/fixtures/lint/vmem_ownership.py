"""Trigger fixture for the vmem-scratch-ownership rule: allocates VMEM
scratch outside ops/merge_pallas.py, where the scratch-budget
reconciliation cannot see it.  Mounted by tests/test_analysis.py only —
never imported (the import below is AST surface, not runtime)."""

from jax.experimental.pallas import tpu as pltpu


def bad_scratch():
    return pltpu.VMEM((8, 128), "int8")  # unbudgeted allocation
