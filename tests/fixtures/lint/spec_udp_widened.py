"""Seeded drift for spec-dissemination: the new-suspicion SUSPECT push
widened back to an unconditional all-peers broadcast — the exact
ENTRY-broadcast asymmetry this rule flagged at head (mounted over
gossipfs_tpu/detector/udp.py)."""

CMD_SEP = "<CMD>"


class UdpNode:
    def tick(self, now):
        c = self.cluster
        rt = self._suspicion()
        for addr in list(self.members):
            if addr == self.addr:
                continue
            if rt is not None:
                if rt.suspect(addr, now):
                    self._obs("suspect", addr)
                    msg = f"{addr}{CMD_SEP}SUSPECT"
                    # DRIFT: no campaign-profile gate — every new
                    # suspicion goes to every peer regardless of c.push
                    for peer in list(self.members):
                        if peer != self.addr:
                            self._send(peer, msg)
                    continue
