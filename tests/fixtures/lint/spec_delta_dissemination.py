"""Seeded drift for spec-delta-dissemination: the delta entry
selection rewritten oldest-first with NO round-robin stable-tail
refresh — a stable entry is never re-pushed between anti-entropy
rounds, so its refresh gap silently grows toward the detection window
(mounted over gossipfs_tpu/detector/udp.py)."""

DELTA_MARK = "<#DELTA#>"
ENTRY_SEP = "<#ENTRY#>"
FIELD_SEP = "<#INFO#>"


class UdpNode:
    def _encode_delta(self, peer):
        c = self.cluster
        cursor = self._sent_ver.get(peer)
        self._sent_ver[peer] = self._ver
        if cursor is None:
            return self._encode()
        cap = c.delta_entries
        # DRIFT: oldest change first, truncated at the cap, and the
        # stable tail is never refreshed in leftover capacity
        changed = [(a, m) for a, m in self.members.items()
                   if m.ver > cursor]
        changed.sort(key=lambda am: am[1].ver)
        picks = changed[:cap]
        return DELTA_MARK + ENTRY_SEP.join(
            f"{a}{FIELD_SEP}{m.hb}{FIELD_SEP}{m.ts}" for a, m in picks)

    def tick(self, now):
        c = self.cluster
        anti_entropy = (not c.delta
                        or self.rounds % c.anti_entropy_every == 0)
        return anti_entropy


class UdpCluster:
    def __init__(self, n, t_fail=5, delta=False, delta_entries=16,
                 anti_entropy_every=4):
        if delta and anti_entropy_every >= t_fail:
            raise ValueError("anti_entropy_every must stay below t_fail")
        self.delta = delta
        self.delta_entries = delta_entries
        self.anti_entropy_every = anti_entropy_every
