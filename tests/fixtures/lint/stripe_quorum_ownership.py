"""Trigger fixture for the stripe-quorum-ownership rule: re-derives the
(k+m-f)-of-(k+m) stripe write threshold instead of importing
sdfs/quorum.py.  Mounted over gossipfs_tpu/erasure/ by
tests/test_analysis.py only — never imported."""


def bad_stripe_write_quorum(acks: int, k: int, m: int, f: int) -> bool:
    return acks >= k + m - f  # the owned threshold shape, re-derived


def bad_stripe_width_check(live: int, stripe_k: int, stripe_m: int) -> bool:
    # subtracting slack from the stripe width inside a comparison
    return live > stripe_k + stripe_m - 1
