"""Trigger fixture for the rr-scratch-budget probe rule: the drift it
exists to catch is a kernel allocation the budget list stops charging.
The probe cannot be triggered by mounting a source file (it reconciles
RUNTIME allocations), so this fixture carries the injection knob:
tests/test_analysis.py calls ``probes._reconcile(spec_drop=SPEC_DROP)``,
simulating a budget list missing the kernel's last spec, and asserts
the byte-sum reconciliation fires."""

SPEC_DROP = 1
