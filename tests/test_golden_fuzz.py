"""Randomized golden-parity fuzz (VERDICT round-1 #6).

Seeded random event schedules — crash/leave/join storms, introducer kill,
rejoin-while-cooling races — swept across {ring, random, random_arc}
topologies and {int32, int16} heartbeat storage x {int16, int8} view
dtypes, checked entry-for-entry against the naive per-node oracle every
round.  This is exactly the corner territory of the narrow-dtype rebase
logic that the hand-picked golden schedules miss.
"""

from __future__ import annotations

import random as pyrandom

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import gossip_round
from gossipfs_tpu.core.rounds import run_rounds as gossip_run_rounds
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.core import topology
from gossipfs_tpu.suspicion import SuspicionParams
from reference_model import NaiveSim

# randomized 24-config x 200-round sweep with O(N^2) Python comparisons (~16 min); test_golden_parity covers the same oracle deterministically in the fast lane
pytestmark = pytest.mark.slow


def random_schedule(rng: pyrandom.Random, n: int, rounds: int,
                    kill_introducer: bool) -> dict[int, dict]:
    """Seeded event schedule: sparse storms of every event type.

    Joins target recently-dead nodes with bias, so rejoin-while-cooling
    (the zombie corner) is exercised constantly.
    """
    schedule: dict[int, dict] = {}
    recently_dead: list[int] = []
    for r in range(3, rounds):
        ev = {"crash": [], "leave": [], "join": []}
        if rng.random() < 0.10:
            ev["crash"] = rng.sample(range(1, n), k=rng.randint(1, 3))
            recently_dead.extend(ev["crash"])
        if rng.random() < 0.06:
            ev["leave"] = [rng.randrange(1, n)]
            recently_dead.append(ev["leave"][0])
        if rng.random() < 0.12 and recently_dead:
            # bias toward the most recent corpse: rejoin while others are
            # still cooling on its old entry
            pick = recently_dead[-1] if rng.random() < 0.5 else rng.choice(recently_dead)
            ev["join"] = [pick]
        if kill_introducer and r == rounds // 2:
            ev["crash"] = sorted(set(ev["crash"]) | {0})
        if any(ev.values()):
            schedule[r] = ev
    return schedule


def to_events(n: int, ev: dict) -> RoundEvents:
    def m(idx):
        a = np.zeros(n, dtype=bool)
        if idx:
            a[list(idx)] = True
        return jnp.asarray(a)

    return RoundEvents(crash=m(ev.get("crash", [])), leave=m(ev.get("leave", [])),
                       join=m(ev.get("join", [])))


def compare(state, naive, where: str) -> None:
    n = state.n
    assert np.array(state.alive).tolist() == naive.alive, f"alive @ {where}"
    hb = np.array(state.hb_true())  # absolute counters whatever the storage
    age = np.array(state.age)
    status = np.array(state.status)
    for i in range(n):
        if not naive.alive[i]:
            continue  # dead processes don't run; their rows are unspecified
        row = naive.tables[i]
        for j in range(n):
            e = row[j]
            assert status[i][j] == e.status, f"status[{i},{j}] @ {where}"
            if e.status != 0:
                # old-incarnation zombie lanes (above the subject's own
                # counter — only reachable after a rejoin) saturate at the
                # narrow storage's ceiling by design; they are excluded
                # from gossip on both sides, so only status/age carry
                # protocol meaning for them
                zombie = e.hb > naive.tables[j][j].hb
                if not zombie:
                    assert hb[i][j] == e.hb, f"hb[{i},{j}] @ {where}"
                assert age[i][j] == e.age, f"age[{i},{j}] @ {where}"


CONFIGS = [
    # (name, cfg kwargs, kill_introducer)
    ("ring-i32", dict(n=24), False),
    ("ring-i32-introkill", dict(n=24), True),
    ("rand-i32-v16", dict(n=32, topology="random", fanout=5), False),
    ("rand-i32-v8", dict(n=32, topology="random", fanout=5,
                         view_dtype="int8"), False),
    ("rand-i16-v16", dict(n=32, topology="random", fanout=5,
                          hb_dtype="int16"), False),
    ("rand-i16-v8", dict(n=48, topology="random", fanout=6,
                         hb_dtype="int16", view_dtype="int8"), False),
    ("rand-i16-v8-introkill", dict(n=32, topology="random", fanout=5,
                                   hb_dtype="int16", view_dtype="int8"), True),
    ("arc-i32-v16", dict(n=32, topology="random_arc", fanout=5), False),
    ("arc-i16-v8", dict(n=64, topology="random_arc", fanout=6,
                        hb_dtype="int16", view_dtype="int8"), False),
    ("nobcast-i16-v8", dict(n=32, topology="random", fanout=5,
                            remove_broadcast=False, fresh_cooldown=True,
                            hb_dtype="int16", view_dtype="int8"), False),
    ("rand-i8-v8", dict(n=32, topology="random", fanout=5,
                        hb_dtype="int8", view_dtype="int8"), False),
    ("arc-i8-v8-introkill", dict(n=64, topology="random_arc", fanout=6,
                                 remove_broadcast=False, fresh_cooldown=True,
                                 hb_dtype="int8", view_dtype="int8"), True),
    # the SWAR packed-word elementwise path (config.elementwise="swar",
    # ops/swar.py) against the same per-node oracle: crash/leave/join
    # storms drive the swar tick (remove-broadcast OR-reduce included)
    # and the swar membership epilogue through the rebase/zombie corners
    ("rand-i8-v8-swar", dict(n=32, topology="random", fanout=5,
                             hb_dtype="int8", view_dtype="int8",
                             elementwise="swar"), False),
    ("arc-i8-v8-swar-introkill", dict(n=64, topology="random_arc", fanout=6,
                                      remove_broadcast=False,
                                      fresh_cooldown=True,
                                      hb_dtype="int8", view_dtype="int8",
                                      elementwise="swar"), True),
    # the suspicion subsystem's XLA lifecycle (SimConfig.suspicion,
    # suspicion/) against the same per-node oracle: crash/leave/join
    # storms drive the SUSPECT/confirm/refute transitions — including
    # rejoin-while-SUSPECT (the old incarnation's copy must confirm and
    # cool down, never refute off the fresh incarnation's counter) and
    # the Lifeguard local-health stretch under mass suspicion
    ("sus-ring-i32", dict(n=24, remove_broadcast=False, fresh_cooldown=True,
                          suspicion=SuspicionParams(t_suspect=2)), False),
    ("sus-rand-i16-v8-introkill", dict(n=32, topology="random", fanout=5,
                                       remove_broadcast=False,
                                       fresh_cooldown=True,
                                       hb_dtype="int16", view_dtype="int8",
                                       suspicion=SuspicionParams(
                                           t_suspect=3, lh_multiplier=2,
                                           lh_frac=0.25)), True),
]


def test_fuzz_rr_rotated_scan_matches_oracle():
    """Golden fuzz on the round-9 rr path: the ring-rotated aligned-arc
    view build + LANE-compacted flags (merge_kernel='pallas_rr_interpret',
    resident lanes), driven by a seeded crash-storm schedule through
    ``run_rounds`` in segments and checked entry-for-entry against the
    per-node oracle at every segment boundary.

    The CONFIGS sweep above drives ``gossip_round``, which never reaches
    the rr kernel (it needs lane-aligned N >= the stripe width and the
    lean crash-only scan), so this is the one fuzz config the new path
    gets — crash-only by construction (the rr fault model; scheduled
    leaves would mean silent death, identical to crash on both sides).
    Edge replication mirrors core.rounds._scan_rounds_rr's per-round key
    derivation so the oracle gossips over the same sampled arcs."""
    cfg = SimConfig(n=1024, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_cooldown=12, view_dtype="int8", hb_dtype="int8",
                    merge_kernel="pallas_rr_interpret", merge_block_c=512,
                    merge_block_r=128, rr_resident="on")
    n, rounds, seg = cfg.n, 40, 5
    rng = pyrandom.Random(909)
    schedule: dict[int, list[int]] = {}
    for r in range(2, rounds):
        if rng.random() < 0.12:
            schedule[r] = rng.sample(range(1, n), k=rng.randint(1, 3))
    state = init_state(cfg)
    naive = NaiveSim(cfg)
    key = jax.random.PRNGKey(11)
    for r0 in range(0, rounds, seg):
        crash = np.zeros((seg, n), dtype=bool)
        for r in range(r0, r0 + seg):
            for idx in schedule.get(r, []):
                crash[r - r0, idx] = True
        z = jnp.zeros((seg, n), dtype=bool)
        ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
        state, _, _ = gossip_run_rounds(state, cfg, seg, key, events=ev,
                                        crash_only_events=True)
        for r in range(r0, r0 + seg):
            # the rr scan's per-round edge key (core/rounds.py
            # _scan_rounds_rr_packed.step): fold_in(key, round), split
            k_edge, _ = jax.random.split(jax.random.fold_in(key, r))
            bases = topology.in_edges(cfg, k_edge, None)
            naive.step(np.array(topology.arc_edges(bases, cfg.fanout)),
                       crash=schedule.get(r, []))
        compare(state, naive, where=f"rr-rotated round {r0 + seg}")


@pytest.mark.parametrize("with_scenario", [False, True],
                         ids=["suspicion", "partition+suspicion"])
def test_fuzz_rr_suspicion_partition_matches_oracle(with_scenario):
    """Round-11 golden fuzz: the fused fast path — SWIM suspicion (fused
    SUSPECT/confirm in the packed tick, refute-on-advance in the merge)
    on the ring-rotated + LANE-compacted + SWAR resident-round kernel —
    driven by a seeded crash storm against the per-node oracle, with and
    without a timed half/half partition + slow-sender scenario armed.

    The scenario variant runs the kernel's ``edge_filter`` masked-gather
    build (group-match masks over align-closed partition sides, sender
    mute riding the flags); the scenario-free variant keeps the
    ring-rotated build, so BOTH round-11 kernel forms meet the oracle.
    Oracle edges mirror the rr scan's per-round sampling, expanded to
    explicit [N, F] form and put through the SAME rule table via
    ``scenarios.tensor.filter_edges`` (per-edge == group-granular for
    align-group-closed sides — the equivalence scenarios/tensor.py
    argues; no Bernoulli rules, so the filter key is inert)."""
    from gossipfs_tpu.scenarios import FaultScenario, Partition, SlowNode
    from gossipfs_tpu.scenarios.tensor import compile_tensor, filter_edges

    cfg = SimConfig(n=512, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_fail=3, t_cooldown=12, view_dtype="int8",
                    hb_dtype="int8", merge_kernel="pallas_rr_interpret",
                    merge_block_c=512, merge_block_r=128, rr_resident="on",
                    elementwise="swar",
                    suspicion=SuspicionParams(t_suspect=2))
    n, rounds, seg = cfg.n, 40, 5
    tsc = None
    if with_scenario:
        sc = FaultScenario(
            name="fuzz-split", n=n,
            # halves are align-group-closed (512 % 8 == 0); the split
            # spans enough rounds for cross-side entries to walk the full
            # MEMBER -> SUSPECT -> FAILED -> cooldown -> re-add lifecycle
            partitions=(Partition(start=6, end=24,
                                  groups=(tuple(range(n // 2)),)),),
            slow_nodes=(SlowNode(start=2, end=32, stride=3,
                                 nodes=tuple(range(32))),),
        )
        tsc = compile_tensor(sc)
    rng = pyrandom.Random(909)
    schedule: dict[int, list[int]] = {}
    for r in range(2, rounds):
        if rng.random() < 0.12:
            schedule[r] = rng.sample(range(1, n), k=rng.randint(1, 3))
    state = init_state(cfg)
    naive = NaiveSim(cfg)
    key = jax.random.PRNGKey(11)
    for r0 in range(0, rounds, seg):
        crash = np.zeros((seg, n), dtype=bool)
        for r in range(r0, r0 + seg):
            for idx in schedule.get(r, []):
                crash[r - r0, idx] = True
        z = jnp.zeros((seg, n), dtype=bool)
        ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
        state, _, _ = gossip_run_rounds(state, cfg, seg, key, events=ev,
                                        crash_only_events=True,
                                        scenario=tsc)
        for r in range(r0, r0 + seg):
            k = jax.random.fold_in(key, r)
            k_edge, _ = jax.random.split(k)
            bases = topology.in_edges(cfg, k_edge, None)
            edges = topology.arc_edges(bases, cfg.fanout)
            if tsc is not None:
                edges = filter_edges(tsc, edges.astype(jnp.int32),
                                     jnp.int32(r), k)
            naive.step(np.array(edges), crash=schedule.get(r, []))
        compare(state, naive,
                where=f"rr-sus{'-scn' if with_scenario else ''} "
                      f"round {r0 + seg}")


@pytest.mark.parametrize("name,kwargs,introkill", CONFIGS,
                         ids=[c[0] for c in CONFIGS])
@pytest.mark.parametrize("seed", [1, 2])
def test_fuzz_matches_oracle(name, kwargs, introkill, seed):
    cfg = SimConfig(**kwargs)
    rounds = 200
    rng = pyrandom.Random(1000 * seed + len(name))
    schedule = random_schedule(rng, cfg.n, rounds, introkill)
    state = init_state(cfg)
    naive = NaiveSim(cfg)
    key = jax.random.PRNGKey(seed)
    for r in range(rounds):
        ev = schedule.get(r, {})
        events = to_events(cfg.n, ev)
        k = jax.random.fold_in(key, r)
        if cfg.topology == "ring":
            edges = None
            oracle_edges = None
        else:
            edges = topology.in_edges(cfg, k, None)
            oracle_edges = (
                np.array(topology.arc_edges(edges, cfg.fanout))
                if cfg.topology == "random_arc"
                else np.array(edges)
            )
        state, _, _, _ = gossip_round(state, events, edges, cfg)
        naive.step(oracle_edges, crash=ev.get("crash", []),
                   leave=ev.get("leave", []), join=ev.get("join", []))
        # compare every 5 rounds (and right after event rounds) — full
        # entry-for-entry comparison is O(N^2) Python per round
        if r % 5 == 0 or r in schedule or (r - 1) in schedule:
            compare(state, naive, where=f"{name} seed={seed} round {r}")
    compare(state, naive, where=f"{name} seed={seed} final")


@pytest.mark.scenario
@pytest.mark.campaign
def test_fuzz_gray_failure_matches_oracle():
    """Round-13 golden fuzz: the gray-failure primitives — flapping duty
    cycles + a correlated rack outage — armed over a seeded crash storm
    WITH the SWIM lifecycle, checked entry-for-entry against the
    per-node oracle.  The scenario path runs the interactive
    ``gossip_round_scenario`` (the same per-edge ``filter_edges`` the
    bulk scan applies); oracle edges are the identical sampled [N, F]
    set put through the same rule table, so a flapping node's dark
    phases and the outage window's total blackout must produce the
    exact same SUSPECT/refute/confirm/cooldown walk in both."""
    from gossipfs_tpu.core.rounds import gossip_round_scenario
    from gossipfs_tpu.scenarios import (
        CorrelatedOutage,
        FaultScenario,
        Flapping,
    )
    from gossipfs_tpu.scenarios.tensor import compile_tensor, filter_edges

    n, rounds = 48, 60
    cfg = SimConfig(n=n, topology="random", fanout=6,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_fail=3, t_cooldown=8, hb_dtype="int16",
                    view_dtype="int8",
                    suspicion=SuspicionParams(t_suspect=2))
    sc = FaultScenario(
        name="fuzz-gray", n=n,
        # two flappers whose dark span brackets the suspect window (one
        # refutes inside it, one confirms past it) + a 5-node rack
        # blackout long enough to walk MEMBER -> SUSPECT -> FAILED ->
        # cooldown on both sides of the outage boundary
        flapping=(Flapping(start=4, end=44, up=3, down=4, nodes=(5, 6)),
                  Flapping(start=8, end=40, up=2, down=7, nodes=(11,)),),
        outages=(CorrelatedOutage(start=14, end=30,
                                  nodes=(20, 21, 22, 23, 24)),),
    )
    tsc = compile_tensor(sc)
    rng = pyrandom.Random(1313)
    schedule: dict[int, list[int]] = {}
    for r in range(3, rounds):
        if rng.random() < 0.10:
            schedule[r] = rng.sample(
                [x for x in range(1, n)], k=rng.randint(1, 2))
    state = init_state(cfg)
    naive = NaiveSim(cfg)
    key = jax.random.PRNGKey(7)
    for r in range(rounds):
        crash = schedule.get(r, [])
        ev = to_events(n, {"crash": crash})
        k = jax.random.fold_in(key, r)
        edges = topology.in_edges(cfg, k, None)
        k_scn = jax.random.fold_in(k, 0x5CE)
        state, _, _, _ = gossip_round_scenario(state, ev, edges, cfg,
                                               tsc, k_scn)
        oracle_edges = filter_edges(tsc, edges.astype(jnp.int32),
                                    jnp.int32(r), k_scn)
        naive.step(np.array(oracle_edges), crash=crash)
        if r % 4 == 0 or r in schedule:
            compare(state, naive, where=f"gray round {r}")
    compare(state, naive, where="gray final")


def test_fuzz_rr_lh_outage_matches_oracle():
    """Round-14 golden fuzz: the Lifeguard local-health lane fused into
    the rr/SWAR resident-round kernel (flags bit 4 + carried
    per-receiver suspect counts) AND the aligned-arc correlated-outage
    form (sends_mask sender mute + zero receiver match mask), driven by
    a rack blackout + crash storms against the per-node oracle.

    The schedule makes the stretch fire on BOTH sides of the lh_frac
    compare: a 40-node rack blackout (rack members see ~95% of their
    view SUSPECT -> degraded; cluster observers see ~4% -> not) and a
    ~20% mass crash storm (every survivor crosses lh_frac=0.125 ->
    degraded, confirms at the stretched threshold).  Oracle edges
    mirror the rr scan's per-round sampling, expanded to explicit
    [N, F] form through ``filter_edges`` — whose per-edge outage rule
    the group form must equal exactly (the round-14 equivalence
    scenarios/tensor.py argues).  n=1024: the aligned-arc rr scan
    requires N % ARC_CHUNK == 0, so smaller fuzz shapes silently fall
    back to the stripe dispatch (the gate this test asserts)."""
    from gossipfs_tpu.core.rounds import _use_rr
    from gossipfs_tpu.scenarios import CorrelatedOutage, FaultScenario, SlowNode
    from gossipfs_tpu.scenarios.tensor import compile_tensor, filter_edges

    cfg = SimConfig(n=1024, topology="random_arc", fanout=16, arc_align=8,
                    remove_broadcast=False, fresh_cooldown=True,
                    t_fail=3, t_cooldown=12, view_dtype="int8",
                    hb_dtype="int8", merge_kernel="pallas_rr_interpret",
                    merge_block_c=512, merge_block_r=128, rr_resident="on",
                    elementwise="swar",
                    suspicion=SuspicionParams(t_suspect=2, lh_multiplier=3,
                                              lh_frac=0.125))
    n, rounds, seg = cfg.n, 40, 5
    assert _use_rr(cfg, n, n), "the lh config must take the rr scan"
    sc = FaultScenario(
        name="fuzz-rack", n=n,
        outages=(CorrelatedOutage(start=4, end=16,
                                  nodes=tuple(range(32, 72))),),
        slow_nodes=(SlowNode(start=2, end=30, stride=3,
                             nodes=tuple(range(16))),),
    )
    tsc = compile_tensor(sc)
    rng = pyrandom.Random(1414)
    schedule: dict[int, list[int]] = {}
    for r in range(2, rounds):
        if rng.random() < 0.12:
            schedule[r] = rng.sample(range(1, n), k=rng.randint(1, 3))
    # the mass storm: ~20% simultaneous crashes crosses lh_frac
    schedule[18] = sorted(
        set(rng.sample(range(1, n), k=200)) - set(schedule.get(18, [])))
    state = init_state(cfg)
    naive = NaiveSim(cfg)
    key = jax.random.PRNGKey(23)
    for r0 in range(0, rounds, seg):
        crash = np.zeros((seg, n), dtype=bool)
        for r in range(r0, r0 + seg):
            for idx in schedule.get(r, []):
                crash[r - r0, idx] = True
        z = jnp.zeros((seg, n), dtype=bool)
        ev = RoundEvents(crash=jnp.asarray(crash), leave=z, join=z)
        state, _, _ = gossip_run_rounds(state, cfg, seg, key, events=ev,
                                        crash_only_events=True,
                                        scenario=tsc)
        for r in range(r0, r0 + seg):
            k = jax.random.fold_in(key, r)
            k_edge, _ = jax.random.split(k)
            bases = topology.in_edges(cfg, k_edge, None)
            edges = filter_edges(
                tsc, topology.arc_edges(bases, cfg.fanout).astype(jnp.int32),
                jnp.int32(r), k)
            naive.step(np.array(edges), crash=schedule.get(r, []))
        compare(state, naive, where=f"rr-lh-outage round {r0 + seg}")
