"""Regression tests for review findings (round 1 code review)."""

import io

import jax
import jax.numpy as jnp
import numpy as np

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import RoundEvents, init_state
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.detector.api import FailureDetector
from gossipfs_tpu.detector.sim import SimDetector
from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.shim.cli import dispatch

KEY = jax.random.PRNGKey(0)


class TestDetectorValidation:
    def test_out_of_range_id_rejected_immediately(self):
        det = SimDetector(SimConfig(n=8))
        for verb in (det.crash, det.leave, det.join):
            try:
                verb(999)
                assert False, "expected ValueError"
            except ValueError:
                pass
            try:
                verb(-1)
                assert False, "expected ValueError"
            except ValueError:
                pass
        det.advance(2)  # detector still usable

    def test_cli_survives_bad_node_id_and_bad_regex(self):
        sim = CoSim(SimConfig(n=8))
        out = io.StringIO()
        assert dispatch(sim, "crash 999", out=out)
        assert dispatch(sim, "advance 2", out=out)  # not bricked
        assert dispatch(sim, "grep (", out=out)
        text = out.getvalue()
        assert "error:" in text and "round=2" in text


class TestControlPlaneFidelity:
    def test_election_waits_for_detection_not_crash(self):
        # the control plane consumes the gossip VIEW: master death must not
        # trigger election until the detector actually removes it
        sim = CoSim(SimConfig(n=10))
        sim.tick(3)
        old_master = sim.cluster.master_node
        sim.detector.crash(old_master)
        sim.tick(3)  # well inside the t_fail window
        assert sim.cluster.master_node == old_master  # still undetected
        sim.tick(10)  # past detection
        assert sim.cluster.master_node != old_master

    def test_put_works_right_after_election(self):
        # rebuilt metadata must not spuriously trip the 60-round conflict
        # window (rebuild stamps now - WRITE_CONFLICT_WINDOW)
        sim = CoSim(SimConfig(n=10))
        sim.tick(3)
        assert sim.put("a.txt", b"v1")
        sim.tick(70)  # leave the original conflict window
        victim = sim.cluster.master_node
        sim.detector.crash(victim)
        sim.tick(12)  # detection + election
        assert sim.cluster.master_node != victim
        assert sim.put("a.txt", b"v2", confirm=None)
        assert sim.get("a.txt") == b"v2"

    def test_undetected_dead_replica_still_placeable(self):
        # gossip view lags ground truth: a put right after a crash may place
        # on the dead node (and then misses its ack) — reference behavior
        c = SDFSCluster(n=8, seed=0)
        c.update_membership(view=list(range(8)), reachable=list(range(7)))
        placed_on_dead = False
        for i in range(20):
            assert c.put(f"f{i}.txt", b"x", now=1000 * i)
            if 7 in c.ls(f"f{i}.txt"):
                placed_on_dead = True
                assert c.stores[7].get(f"f{i}.txt") is None  # no ack from dead
        assert placed_on_dead


class TestMetricsCarryJoins:
    def test_ineffective_join_does_not_reset_metrics(self):
        # joins while the introducer is dead are lost (slave.go:22 SPOF) and
        # must not erase the victim's detection/convergence record
        cfg = SimConfig(n=10)
        n = cfg.n
        crash = np.zeros((40, n), dtype=bool)
        join = np.zeros((40, n), dtype=bool)
        crash[5, 0] = True   # introducer dies (undetectable? no — detectable)
        crash[10, 4] = True  # victim
        join[30, 4] = True   # rejoin attempt fails: introducer is down
        ev = RoundEvents(
            crash=jnp.asarray(crash),
            leave=jnp.zeros((40, n), dtype=bool),
            join=jnp.asarray(join),
        )
        state, mc, _ = run_rounds(init_state(cfg), cfg, 40, KEY, events=ev)
        assert not bool(state.alive[4])
        assert int(mc.first_detect[4]) > 0  # record survived the lost join


class TestUdpDetectorProtocol:
    def test_satisfies_failure_detector_and_rejoins(self):
        from gossipfs_tpu.detector.udp import UdpDetector

        det = UdpDetector(n=10, base_port=19600, period=0.05, fresh_cooldown=True)
        try:
            assert isinstance(det, FailureDetector)
            det.advance(10)
            assert det.membership(0) == list(range(10))
            det.crash(4)
            det.advance(20)
            assert any(e.subject == 4 for e in det.drain_events())
            det.join(4)
            det.advance(15)
            assert 4 in det.alive_nodes()
            assert 4 in det.membership(0)
        finally:
            det.close()
