"""Bounded-backoff discipline (shim/retry.py) — the round-14 hardening
of the deploy/shim control plane.

The property under test is the one campaigns/engines.py leans on when it
calls a deploy campaign surviving a correlated outage "evidence of
graceful degradation": a control-plane call's TOTAL retry time is
hard-bounded no matter how transient failures interleave — injected
RPC failures below.
"""

from __future__ import annotations

import pytest

from gossipfs_tpu.shim import retry


class _Clock:
    """Deterministic time stand-in: sleep() advances monotonic() and
    records every delay, so the tests assert exact schedules."""

    def __init__(self):
        self.now = 0.0
        self.sleeps: list[float] = []

    def monotonic(self) -> float:
        return self.now

    def sleep(self, s: float) -> None:
        self.sleeps.append(s)
        self.now += s


class _Transient(Exception):
    pass


class _Fatal(Exception):
    pass


def _is_transient(e: BaseException) -> bool:
    return isinstance(e, _Transient)


@pytest.fixture
def clock(monkeypatch):
    c = _Clock()
    monkeypatch.setattr(retry, "time", c)
    return c


class TestCallWithBackoff:
    def test_transient_failures_then_success(self, clock):
        calls = []

        def fn():
            calls.append(clock.now)
            if len(calls) < 4:
                raise _Transient(f"flake {len(calls)}")
            return "ok"

        out = retry.call_with_backoff(
            fn, retryable=_is_transient, attempts=6,
            base_delay=0.05, max_delay=1.0, total_deadline=10.0)
        assert out == "ok"
        assert len(calls) == 4
        # exponential schedule, exactly: 50 ms, 100 ms, 200 ms
        assert clock.sleeps == [0.05, 0.1, 0.2]

    def test_permanent_failure_total_time_bounded(self, clock):
        def fn():
            raise _Transient("down")

        with pytest.raises(_Transient):
            retry.call_with_backoff(
                fn, retryable=_is_transient, attempts=6,
                base_delay=0.05, max_delay=1.0, total_deadline=10.0)
        # attempts respected; total sleep == the capped geometric sum
        # (0.05 + 0.1 + 0.2 + 0.4 + 0.8) and <= the hard deadline
        assert len(clock.sleeps) == 5
        assert sum(clock.sleeps) == pytest.approx(1.55)
        assert sum(clock.sleeps) <= 10.0

    def test_total_deadline_clips_and_stops(self, clock):
        """Injected failures against a tight budget: each sleep is
        clipped to the REMAINING budget and an exhausted budget stops
        retrying — total wall time spent sleeping never exceeds
        total_deadline even when attempts would allow more."""
        attempts_made = []

        def fn():
            attempts_made.append(clock.now)
            raise _Transient("down")

        with pytest.raises(_Transient):
            retry.call_with_backoff(
                fn, retryable=_is_transient, attempts=50,
                base_delay=4.0, max_delay=8.0, total_deadline=10.0)
        assert sum(clock.sleeps) <= 10.0
        # 4 + 6(clip) = 10 -> budget gone -> stop: 3 attempts, not 50
        assert clock.sleeps == [4.0, 6.0]
        assert len(attempts_made) == 3

    def test_max_delay_caps_the_doubling(self, clock):
        def fn():
            raise _Transient("down")

        with pytest.raises(_Transient):
            retry.call_with_backoff(
                fn, retryable=_is_transient, attempts=5,
                base_delay=0.3, max_delay=0.5, total_deadline=60.0)
        assert clock.sleeps == [0.3, 0.5, 0.5, 0.5]

    def test_non_retryable_raises_immediately(self, clock):
        calls = []

        def fn():
            calls.append(1)
            raise _Fatal("real bug")

        with pytest.raises(_Fatal):
            retry.call_with_backoff(
                fn, retryable=_is_transient, attempts=6,
                base_delay=0.05, total_deadline=10.0)
        assert len(calls) == 1 and clock.sleeps == []

    def test_first_try_success_sleeps_nothing(self, clock):
        assert retry.call_with_backoff(
            lambda: 7, retryable=_is_transient) == 7
        assert clock.sleeps == []


class TestGrpcPredicates:
    """The two call-site policies classify grpc codes as documented."""

    @staticmethod
    def _rpc_error(code_name: str):
        import grpc

        class _Err(grpc.RpcError):
            def code(self):
                return getattr(grpc.StatusCode, code_name)

        return _Err()

    def test_backpressure_only_resource_exhausted(self):
        assert retry.grpc_backpressure(self._rpc_error("RESOURCE_EXHAUSTED"))
        assert not retry.grpc_backpressure(self._rpc_error("UNAVAILABLE"))
        assert not retry.grpc_backpressure(ValueError("x"))

    def test_transient_covers_control_plane_codes(self):
        for code in ("RESOURCE_EXHAUSTED", "UNAVAILABLE",
                     "DEADLINE_EXCEEDED"):
            assert retry.grpc_transient(self._rpc_error(code))
        assert not retry.grpc_transient(self._rpc_error("NOT_FOUND"))
        assert not retry.grpc_transient(RuntimeError("x"))


class TestLauncherControlPlane:
    """The launcher's fan-outs ride the shared discipline: a node that
    flakes transiently still acks; total retry time stays bounded."""

    def test_load_scenario_retries_transient_node(self, clock, monkeypatch,
                                                  tmp_path):
        from gossipfs_tpu.deploy import launcher
        from gossipfs_tpu.scenarios.schedule import FaultScenario

        cluster = launcher.Cluster(2, root=str(tmp_path))

        class _Proc:
            def poll(self):
                return None

        class _FlakyClient:
            def __init__(self):
                self.calls = 0

            def call(self, method, timeout=None, retries=True, **request):
                assert timeout == cluster.ctrl_timeout
                # the launcher owns the one retry layer — the client's
                # inner backpressure loop must be OFF (nesting the two
                # would multiply the advertised time bound)
                assert retries is False
                self.calls += 1
                if self.calls < 3:
                    raise TestGrpcPredicates._rpc_error("UNAVAILABLE")
                return {"ok": True}

        flaky = _FlakyClient()
        cluster.procs = {0: _Proc(), 1: _Proc()}
        monkeypatch.setattr(cluster, "client", lambda idx: flaky)
        sc = FaultScenario(name="noop", n=2)
        assert cluster.load_scenario(sc) == [0, 1]
        # node 0 flaked twice then acked (2 sleeps); node 1 acked cold
        assert clock.sleeps == [0.1, 0.2]

    def test_dead_node_fails_fast_within_budget(self, clock, monkeypatch,
                                                tmp_path):
        from gossipfs_tpu.deploy import launcher

        cluster = launcher.Cluster(1, root=str(tmp_path))

        class _Proc:
            def poll(self):
                return None

        class _DeadClient:
            def call(self, method, timeout=None, retries=True, **request):
                raise TestGrpcPredicates._rpc_error("UNAVAILABLE")

        cluster.procs = {0: _Proc()}
        monkeypatch.setattr(cluster, "client", lambda idx: _DeadClient())
        assert cluster.vitals() == []
        # bounded: 4 attempts, 3 backoffs, total sleep well under the
        # 3 s control-plane retry budget
        assert len(clock.sleeps) == 3
        assert sum(clock.sleeps) <= 3.0
