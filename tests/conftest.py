"""Test harness: force CPU JAX with an 8-device virtual mesh.

Real benches run on TPU; tests exercise the identical sharded code paths on a
virtual 8-device CPU mesh (the sim's stand-in for a v5e-8), so multi-chip
sharding is validated without multi-chip hardware.

This image's sitecustomize registers the 'axon' TPU-tunnel PJRT plugin in
every interpreter and pins jax to it, so setting JAX_PLATFORMS=cpu here is too
late — we additionally deregister the axon backend factory before any backend
is initialized.  Otherwise the first jax.devices() call dials the (single,
possibly busy) TPU chip from every test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent XLA compilation cache: the fast lane is dominated by compile
# time (measured ~2x on test_sharding: 57 s cold -> 26 s warm), and the
# same programs recompile on every pytest invocation without it.  The
# cache lives at the repo root (.jax_cache/, gitignored — note `git clean
# -dfx` deletes it, costing one ~6 min cold repopulation); override with
# JAX_COMPILATION_CACHE_DIR.
_cache = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: deep/redundant coverage (fuzz sweeps, interpret-mode e2e, "
        "multi-process runs).  The default CI lane is `pytest -m 'not "
        "slow'` (< 5 min, every component covered at least once); run the "
        "full suite before shipping protocol-arithmetic changes.",
    )
    config.addinivalue_line(
        "markers",
        "scenario: scenario-engine coverage (gossipfs_tpu/scenarios/ — "
        "partitions, link faults, slow nodes across the three transport "
        "engines).  Fast-lane cases ride tier-1; the deploy variant is "
        "additionally marked slow.  `pytest -m scenario` runs just this "
        "subsystem.",
    )
    config.addinivalue_line(
        "markers",
        "suspicion: suspicion-subsystem coverage (gossipfs_tpu/suspicion/ "
        "— SWIM suspect/refute lifecycle + Lifeguard adaptive timeouts "
        "across the three transport engines).  Fast-lane cases ride "
        "tier-1; the deploy variant is additionally marked slow.  "
        "`pytest -m suspicion` runs just this subsystem.",
    )
    config.addinivalue_line(
        "markers",
        "campaign: online-health-plane coverage (gossipfs_tpu/obs/"
        "monitor.py + gossipfs_tpu/campaigns/ — the streaming invariant "
        "monitor, the gray-failure scenario primitives, and the "
        "campaign driver with its committed regression cases).  "
        "Fast-lane cases ride tier-1, including the regression-case "
        "replay smoke.  `pytest -m campaign` runs just this subsystem.",
    )
    config.addinivalue_line(
        "markers",
        "traffic: traffic-plane coverage (gossipfs_tpu/traffic/ — the "
        "open-loop SDFS load generator, tensorized placement/repair "
        "planning, and the durability harness).  Fast-lane cases ride "
        "tier-1, including the small-N put/get/churn smoke asserting no "
        "acked-write loss.  `pytest -m traffic` runs just this "
        "subsystem.",
    )
    config.addinivalue_line(
        "markers",
        "conformance: conformance-fuzzing coverage (gossipfs_tpu/"
        "conformance/ — the spec-driven adversarial-schedule generator, "
        "the per-engine injection harness with its reference oracle, "
        "the verdict matrix and the shrinker).  Fast-lane cases ride "
        "tier-1, including one short schedule through reference + "
        "tensor + udp with verdict agreement and the committed "
        "malformed-datagram repro replay; the native variant is "
        "additionally marked slow.  `pytest -m conformance` runs just "
        "this subsystem.",
    )
    config.addinivalue_line(
        "markers",
        "erasure: erasure-plane coverage (gossipfs_tpu/erasure/ — the "
        "GF(256) Reed-Solomon codec, stripe placement/repair planning, "
        "and the redundancy=\"stripe\" byte plane through cluster/cosim/"
        "harness).  Fast-lane cases ride tier-1, including the n=32 "
        "put/get/rack-kill/repair smoke asserting no acked-write loss "
        "and the committed stripe rack-kill regression-case replay.  "
        "`pytest -m erasure` runs just this subsystem.",
    )
