"""Non-Python client proof (VERDICT #8): protoc + curl drive the shim.

tools/gossipfs_sh_client.sh speaks the gRPC wire protocol with no Python
and no gRPC runtime at all — protoc encodes/decodes gossipfs.proto
messages and curl POSTs the length-prefixed frames over HTTP/2 prior
knowledge.  If a shell script can do Join/Advance/Lsm from the .proto
alone, any language's generated client can.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.shim.service import ShimServer

SCRIPT = pathlib.Path(__file__).resolve().parents[1] / "tools" / "gossipfs_sh_client.sh"

needs_tools = pytest.mark.skipif(
    shutil.which("protoc") is None or shutil.which("curl") is None,
    reason="protoc + curl required",
)


def sh_call(address: str, method: str, req_type: str, resp_type: str,
            textproto: str) -> str:
    out = subprocess.run(
        [str(SCRIPT), address, method, req_type, resp_type],
        input=textproto.encode(),
        capture_output=True,
        timeout=60,
    )
    assert out.returncode == 0, out.stderr.decode()
    return out.stdout.decode()


@needs_tools
def test_shell_client_join_advance_lsm():
    sim = CoSim(SimConfig(n=8), seed=1)
    server = ShimServer(sim, port=0).start()
    try:
        # Advance the simulated clock 5 rounds
        reply = sh_call(server.address, "Advance", "AdvanceRequest",
                        "AdvanceReply", "rounds: 5")
        assert "round: 5" in reply
        # Crash a node, advance past detection, and read node 0's view
        sh_call(server.address, "Crash", "NodeRequest", "OkReply", "node: 6")
        sh_call(server.address, "Advance", "AdvanceRequest", "AdvanceReply",
                "rounds: 10")
        lsm = sh_call(server.address, "Lsm", "LsmRequest", "LsmReply",
                      "observer: 0")
        members = [int(x.split(":")[1]) for x in lsm.splitlines()
                   if x.startswith("members:")]
        assert 6 not in members
        assert 0 in members
        # Join it back through the introducer and let gossip re-add it
        sh_call(server.address, "Join", "NodeRequest", "OkReply", "node: 6")
        sh_call(server.address, "Advance", "AdvanceRequest", "AdvanceReply",
                "rounds: 3")
        lsm = sh_call(server.address, "Lsm", "LsmRequest", "LsmReply",
                      "observer: 0")
        members = [int(x.split(":")[1]) for x in lsm.splitlines()
                   if x.startswith("members:")]
        assert 6 in members
    finally:
        server.stop()
