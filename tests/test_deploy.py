"""One-process-per-node deployment: detection, repair, and election across
real OS process boundaries (deploy/node.py + deploy/launcher.py).

The embedded shim hosts the whole cluster in one process; these tests spawn
one ``gossipfs_tpu.deploy.node`` process per member (the reference's real
topology, main.go:14-35) and kill -9 them mid-flight.  Slow lane: each case
boots a real cluster (multi-second convergence on this 1-core host).
"""

import os
import time

import pytest

from gossipfs_tpu.deploy.launcher import Cluster

pytestmark = pytest.mark.slow

N = 5
PERIOD = 0.1


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(N, period=PERIOD, root=str(tmp_path))
    c.start(timeout=60.0)
    yield c
    c.stop()


def test_kill9_detection_repair_and_get(cluster):
    data = os.urandom(64 * 1024)
    assert cluster.client(1).put("wiki.txt", data)
    holders = cluster.client(1).ls("wiki.txt")
    assert len(holders) == 4

    victim = next(h for h in holders if h != 0)
    observer = next(i for i in range(N) if i not in (victim, 0))
    cluster.kill9(victim)

    detect_s = cluster.wait_detected(victim, observer, timeout=30.0)
    # ~t_fail periods of gossip timeout, with generous jitter headroom on
    # a loaded 1-core CI box
    assert detect_s < 20.0

    repair_s = cluster.wait_repaired("wiki.txt", observer, 4, timeout=60.0)
    assert repair_s < 40.0
    healed = set(cluster.client(observer).ls("wiki.txt"))
    assert victim not in healed and len(healed) == 4

    # the healed copy is byte-identical, served by the surviving processes
    assert cluster.client(observer).get("wiki.txt") == data

    # the repair crossed process boundaries: the master logged the plan,
    # the source logged the push — each in its own per-process log file
    hits = []
    for i in range(N):
        if i == victim:
            continue
        hits += cluster.client(i).call(
            "Grep", pattern="re_replicate|reput"
        ).get("lines") or []
    assert hits


def test_master_kill9_election_and_writes_resume(cluster):
    data = b"survives the master" * 100
    assert cluster.client(2).put("meta.txt", data)

    cluster.kill9(0)  # the master AND the introducer
    # wait_new_master is SYNCHRONIZATION only (its generous timeout
    # absorbs 1-core CI starvation); the latency ASSERTION below is in
    # protocol rounds read off the winner's own event log instead of a
    # widenable wall-clock window
    cluster.wait_new_master(2, 0, timeout=120.0)

    # the new master rebuilt metadata from per-node store listings:
    # the pre-election file is still readable through it
    assert cluster.client(2).get("meta.txt") == data

    # exactly one survivor logged the win (the lowest live node)
    winners = []
    for i in range(1, N):
        winners += cluster.client(i).call(
            "Grep", pattern="became master"
        ).get("lines") or []
    assert len({w["node"] for w in winners}) == 1

    # election latency in PROTOCOL ROUNDS: every deploy log entry carries
    # the node's own heartbeat-tick counter (deploy/node.py log()), which
    # stalls with the process under host load instead of widening like
    # wall time.  From the round the winner's own detector dropped the
    # dead master to the round it logged the win: its view must go
    # masterless (~immediately after its own detection), then one control
    # tick campaigns and the Vote fan-out completes — a handful of rounds
    # of protocol work, NOT a function of absolute host speed.
    # the winner itself may have dropped the master via a peer's REMOVE
    # broadcast (no local detect entry), so take the earliest detect of
    # node 0 across survivors — their tick counters align to within a
    # couple of rounds (all booted inside the same convergence window)
    winner = int(next(iter(winners))["node"])
    win_lines = cluster.client(winner).call(
        "Grep", pattern="became master"
    ).get("lines") or []
    detect_lines = []
    for i in range(1, N):
        detect_lines += [
            ln for ln in (cluster.client(i).call(
                "Grep", pattern="detected failure of node 0"
            ).get("lines") or [])
            if ln.get("subject") == 0
        ]
    assert win_lines and detect_lines
    elected_round = min(ln["round"] for ln in win_lines)
    detect_round = min(ln["round"] for ln in detect_lines)
    latency_rounds = elected_round - detect_round
    # lower bound -3, not 0: elected/detect rounds may come from two
    # different nodes' tick counters (boot skew of a couple of ticks)
    assert -3 <= latency_rounds <= 30, (
        f"election took {latency_rounds} protocol rounds after first "
        f"detection (elected@{elected_round}, detected@{detect_round})"
    )


def test_write_conflict_confirmation_crosses_processes(cluster):
    assert cluster.client(1).put("c.txt", b"first")
    # second write inside the 60 s window from a DIFFERENT node: the master
    # calls AskForConfirmation back on the requester's own server
    # (auto-confirm default answers yes)
    assert cluster.client(3).put("c.txt", b"second")
    time.sleep(PERIOD * 2)
    got = cluster.client(2).get("c.txt")
    assert got == b"second"


def test_reference_10node_workflow():
    """The reference's real README workflow (README.md:8-30, the report's
    file5/file10 measurement workload) across 10 OS processes: put /
    update / get of 5 MB and 10 MB files, ls/store listings, kill -9 of a
    replica holder mid-workload, quorum read through the failure window,
    and a byte-identical post-repair get.  bench/ref_workflow.py is the
    measured artifact (REFWORKFLOW.json); this pins the workflow in CI."""
    from gossipfs_tpu.bench.ref_workflow import run

    import grpc

    try:
        out = run(n=10, mb5=5, mb10=10, period=0.5, timeout=180.0)
    except (RuntimeError, TimeoutError, grpc.RpcError):
        # one retry, for INFRA failures only (boot/convergence/RPC
        # deadline): booting ten processes while earlier cases' clusters
        # tear down can starve the gossip loops on this 1-core host.
        # Correctness failures (AssertionError — wrong bytes, bad quorum)
        # are never retried: an intermittent data bug must fail the run
        time.sleep(5.0)
        out = run(n=10, mb5=5, mb10=10, period=0.5, timeout=180.0)
    assert out["ok"] and out["post_repair_byte_identical"]
    # correctness only: the latency-ordering claims (read < insert,
    # latency grows with size) are REFWORKFLOW.json's to show — asserting
    # them here flakes whenever the loaded 1-core host stalls one RPC
    for k in ("insert5_s", "insert10_s", "update5_s", "read5_s",
              "read10_s", "detect_s", "repair_s"):
        assert out[k] >= 0
