"""End-to-end co-simulation: detector drives SDFS recovery and election.

This is the sim-level version of the reference's demo workflow: put files,
CTRL+C a node, watch detection -> delayed re-replication -> reads still serve
(SURVEY §3.5), and master death -> election -> metadata rebuild (§2.2 E1).
"""

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.sdfs.types import RECOVERY_DELAY


def make_sim(n=10, seed=0):
    # n=10 == the reference's deployment scale; beyond ~12 the ring topology
    # develops real false positives after a crash (freshness diameter exceeds
    # t_fail), which makes deterministic assertions impossible — that regime
    # is exercised statistically in test_rounds.py instead.
    return CoSim(SimConfig(n=n), seed=seed)


class TestCoSim:
    def test_put_crash_recover_get(self):
        sim = make_sim()
        sim.tick(3)
        assert sim.put("file5.txt", b"payload")
        victim = sim.cluster.ls("file5.txt")[0]
        if victim == sim.cluster.master_node:
            victim = sim.cluster.ls("file5.txt")[1]
        sim.detector.crash(victim)
        # detection ~t_fail+1 rounds, recovery RECOVERY_DELAY after that
        sim.tick(6 + RECOVERY_DELAY + 3)
        assert any(e.subject == victim for e in sim.events)
        replicas = sim.cluster.ls("file5.txt")
        assert victim not in replicas
        assert len(replicas) == 4
        assert sim.get("file5.txt") == b"payload"
        # observability: the same events the Go cluster logs are grep-able
        assert sim.log.grep("Failure Detected")
        assert sim.log.grep("Re-replicated file5.txt")

    def test_master_crash_elects_lowest_live_node(self):
        sim = make_sim()
        sim.tick(3)
        assert sim.put("a.txt", b"abc")
        old_master = sim.cluster.master_node
        sim.detector.crash(old_master)
        sim.tick(10)
        assert sim.cluster.master_node != old_master
        assert sim.cluster.master_node == min(sim.detector.alive_nodes())
        assert sim.get("a.txt") == b"abc"

    def test_write_conflict_rejected_within_window(self):
        sim = make_sim()
        sim.tick(2)
        assert sim.put("a.txt", b"v1")
        sim.tick(10)  # still inside the 60-round window
        assert not sim.put("a.txt", b"v2")
        assert sim.get("a.txt") == b"v1"

    def test_leave_is_not_a_detection(self):
        sim = make_sim()
        sim.tick(2)
        sim.detector.leave(7)
        sim.tick(10)
        assert 7 not in sim.detector.alive_nodes()
        assert not any(e.subject == 7 for e in sim.events)


class TestRecoveryCadence:
    def test_repair_waits_exactly_recovery_delay(self):
        """The reference sleeps 8 heartbeats between detection and
        re-replication (Fail_recover, slave.go:1123): repairs must land in
        the round scheduled RECOVERY_DELAY after detection, never earlier."""
        sim = make_sim()
        sim.tick(3)
        assert sim.put("file5.txt", b"payload")
        victim = sim.cluster.ls("file5.txt")[0]
        if victim == sim.cluster.master_node:
            victim = sim.cluster.ls("file5.txt")[1]
        sim.detector.crash(victim)
        sim.tick(20)
        detect_round = min(
            e.round for e in sim.events if e.subject == victim
        )
        repair_rounds = [
            entry["round"] for entry in sim.log.grep("Re-replicated file5.txt")
        ]
        assert repair_rounds, "no repair happened"
        # events are stamped with the round index the heartbeat started
        # from; the recovery timer counts from the heartbeat that fired
        # (detect_round + 1), matching Fail_recover's sleep-from-detection
        assert min(repair_rounds) == detect_round + 1 + RECOVERY_DELAY
