"""Real-socket parity path: 10 nodes gossiping over localhost UDP.

BASELINE config 1.  Runs at 20x real-time (50 ms period).  Timing assertions
are deliberately tolerant — this validates protocol behavior over real
sockets, not exact round counts (that's the golden-parity suite's job).
"""

import asyncio

import pytest

from gossipfs_tpu.detector.udp import UdpCluster


def run_async(coro):
    return asyncio.run(coro)


class TestUdpCluster:
    def test_join_converges_to_full_membership(self):
        async def scenario():
            c = UdpCluster(n=10, base_port=19000, period=0.05)
            try:
                await c.start_all()
                await c.run(12)
                return [c.membership(i) for i in range(10)]
            finally:
                c.stop_all()

        views = run_async(scenario())
        for view in views:
            assert view == list(range(10))

    def test_crash_detection_and_remove_broadcast(self):
        async def scenario():
            # fresh_cooldown: under the faithful stale-timestamp fail list,
            # event-loop jitter comparable to the period sustains an endemic
            # re-add/re-detect limit cycle (see test below) — the reference
            # escapes it only because LAN latency << its 1 s period
            c = UdpCluster(n=10, base_port=19100, period=0.1, fresh_cooldown=True)
            try:
                await c.start_all()
                await c.run(10)
                c.crash(4)
                for _ in range(10):
                    await c.run(c.t_fail + 5)
                    views = [c.membership(i) for i in c.alive_nodes()]
                    if all(4 not in v for v in views):
                        break
                return c.drain_events(), views
            finally:
                c.stop_all()

        events, views = run_async(scenario())
        assert any(e.subject == 4 and not e.false_positive for e in events)
        for view in views:
            assert 4 not in view

    def test_faithful_cooldown_detection_fires(self):
        # Faithful stale-timestamp fail list over real sockets.  Detection
        # must fire; whether the dead node then zombie-cycles (re-add ->
        # re-detect) depends on event-loop jitter relative to the period —
        # both outcomes are legitimate protocol behavior, so only the
        # detection itself is asserted (the cycling is deterministically
        # reproduced in the tensor sim:
        # test_rounds.py::test_stale_cooldown_zombies_cycle_without_broadcast).
        async def scenario():
            c = UdpCluster(n=10, base_port=19400, period=0.05)
            try:
                await c.start_all()
                await c.run(10)
                c.crash(4)
                await c.run(30)
                return c.drain_events()
            finally:
                c.stop_all()

        events = run_async(scenario())
        assert any(e.subject == 4 and not e.false_positive for e in events)

    def test_leave_removes_without_detection_event(self):
        async def scenario():
            c = UdpCluster(n=10, base_port=19200, period=0.05)
            try:
                await c.start_all()
                await c.run(10)
                c.leave(7)
                await c.run(4)
                return c.drain_events(), [c.membership(i) for i in c.alive_nodes()]
            finally:
                c.stop_all()

        events, views = run_async(scenario())
        assert not any(e.subject == 7 for e in events)
        for view in views:
            assert 7 not in view

    def test_heartbeats_advance(self):
        async def scenario():
            c = UdpCluster(n=10, base_port=19300, period=0.05)
            try:
                await c.start_all()
                await c.run(15)
                node = c.nodes[3]
                return {a: m.hb for a, m in node.members.items()}, node.addr
            finally:
                c.stop_all()

        hbs, self_addr = run_async(scenario())
        assert hbs[self_addr] >= 10
        # gossip carried everyone's counters forward too
        others = [v for a, v in hbs.items() if a != self_addr]
        assert all(v >= 5 for v in others)
