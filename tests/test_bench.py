"""Bench runners (gossipfs_tpu/bench/run.py) on shrunken BASELINE scenarios."""

import json

import pytest

from gossipfs_tpu.bench import run as bench_run
from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.models import presets


def test_presets_cover_all_five_baseline_configs():
    assert set(presets.ALL) == {
        "parity-10",
        "sim-1k",
        "sim-10k-crash",
        "sim-100k",
        "sim-100k-sdfs",
    }


def test_tracked_crash_events_spread_and_skip_introducer():
    cfg = SimConfig(n=64)
    events, crash_rounds, churn_ok = bench_run.tracked_crash_events(
        cfg, rounds=30, track=4, at=10
    )
    assert events.crash.shape == (30, 64)
    assert set(crash_rounds.values()) == {10}
    assert cfg.introducer not in crash_rounds
    assert len(crash_rounds) == 4
    # tracked victims are excluded from random churn (TTD measurement
    # guard), and so is the introducer (its death severs every rejoin —
    # slave.go:22 SPOF — which would collapse churny scenarios to nothing)
    import numpy as np

    ok = np.asarray(churn_ok)
    assert not ok[list(crash_rounds)].any()
    assert not ok[cfg.introducer]
    assert ok.sum() == 64 - 4 - 1


def test_run_scenario_parity_10_detects_tracked_crashes():
    result = bench_run.run_scenario("parity-10", rounds_override=60, track=2)
    assert result["n"] == 10 and result["topology"] == "ring"
    assert result["rounds_per_sec"] > 0
    det = result["detection"]
    # every tracked crash detected within t_fail + propagation slack
    for node, ttd in det["ttd_first"].items():
        assert 0 < ttd <= 15, (node, ttd)
    for node, ttd in det["ttd_converged"].items():
        assert 0 < ttd <= 25, (node, ttd)


def test_run_scenario_shrunken_churn_config_runs_and_reports():
    result = bench_run.run_scenario(
        "sim-10k-crash", n_override=256, rounds_override=40, track=3
    )
    assert result["n"] == 256
    assert result["fanout"] == SimConfig.log_fanout(256)
    assert result["detection"]["true_detections"] > 0
    json.dumps(result)  # report must be JSON-serializable


def test_run_scenario_cosim_keeps_files_readable():
    sc = presets.ALL["sim-100k-sdfs"]
    import dataclasses

    small = dataclasses.replace(sc, n_files=20, crash_rate=0.01, rejoin_rate=0.02)
    result = bench_run.run_scenario(
        small, n_override=128, rounds_override=32, track=2
    )
    co = result["cosim"]
    assert co["files"] == 20
    # 4-way replication + re-replication keeps a large majority readable
    # under 1% crash churn over a short horizon
    assert co["files_readable"] >= 15
    assert co["final_alive"] > 0
    json.dumps(result)


def test_cli_main_prints_json(capsys, tmp_path):
    out = tmp_path / "r.json"
    bench_run.main(
        ["--scenario", "parity-10", "--rounds", "20", "--track", "1", "--out", str(out)]
    )
    doc = json.loads(capsys.readouterr().out)
    assert doc["scenario"] == "parity-10"
    assert json.loads(out.read_text())["scenario"] == "parity-10"


def test_sdfs_ops_reproduces_reference_claims():
    """The report's qualitative perf claims (BASELINE.md "Published
    claims") on the TPU build's SDFS plane.

    Only the structurally deterministic claims gate CI: writes move R
    replica copies vs the read's single pull, and latency grows with file
    size.  The third claim (4-node vs 8-node equivalence) compares two
    wall-clock medians whose ratio stays noisy under host load however the
    benchmark interleaves/warms/min-reduces — it is still computed and
    reported by bench/sdfs_ops.py for BASELINE.md, just not asserted here.
    """
    from gossipfs_tpu.bench.sdfs_ops import run

    # large enough payloads that byte-copy time dominates scheduler noise
    out = run(sizes=(65_536, 2_097_152), reps=5)
    claims = out["reference_claims_reproduced"]
    assert claims["write_exceeds_read_at_large_files"], out
    assert claims["latency_grows_with_size"], out


def test_curves_sweep_smoke():
    """The TTD/FPR curve runner (bench/curves.py) produces a row per N with
    every tracked crash detected at ~t_fail rounds."""
    from gossipfs_tpu.bench.curves import sweep

    out = sweep(ns=(256,), rounds=30)
    (row,) = out["rows"]
    assert row["detected"] == row["tracked_crashes"]
    assert row["ttd_first_median"] == 5
    assert row["false_positive_rate"] < 1e-4


def test_wire_ops_real_payload_shape(tmp_path):
    """bench/wire_ops drives Put/Get + crash-repair over a live gRPC server
    and verifies byte identity; CI runs it with small payloads (the
    recorded benchmark uses the reference's 4 MB shards)."""
    from gossipfs_tpu.bench.wire_ops import run

    a = tmp_path / "a.bin"
    b = tmp_path / "b.bin"
    a.write_bytes(b"A" * 200_000)
    b.write_bytes(b"B" * 100_000)
    out = run(files=(str(a), str(b)), n=8, reps=2)
    assert {r["file"] for r in out["rows"]} == {"a.bin", "b.bin"}
    assert out["repair"]["healed"]
    assert out["repair"]["bytes_identical_after_repair"]
