"""Sanitizer lane for the native epoll engine (slow lane).

Builds the committed campaign driver (native/sanitize_main.cc — the C
ABI surface ctypes uses, driven through converge / concurrent
crash+poll hammering / detect / cooldown / rejoin / graceful leave /
codec malformed-input sweep) under ThreadSanitizer and
ASan+UBSan, runs it, and fails on ANY report line — the acceptance is
zero reports with zero suppressions.  `make lint-native` (clang-tidy)
is exercised too, skipping gracefully when the toolchain is absent.

The 578-line engine runs all protocol state on one epoll loop thread
with control verbs arriving from Python threads; TSan is the only
check that sees that interleaving.  Slow lane: each sanitizer run is
~2-4 s of real-time protocol plus the instrumented build.
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess

import pytest

pytestmark = pytest.mark.slow

if shutil.which("g++") is None or shutil.which("make") is None:
    pytest.skip("no native toolchain", allow_module_level=True)

NATIVE = pathlib.Path(__file__).resolve().parents[1] / "native"

# Disjoint from every other native/udp test's range so the slow lane can
# coexist with a parallel fast-lane run.
_PORTS = {"tsan": 21500, "asan": 21600}

_REPORT_MARKERS = (
    "WARNING: ThreadSanitizer",
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",  # UBSan
    "SANITIZE_CAMPAIGN_FAIL",
)


# A minimal toolchain legitimately lacks the sanitizer RUNTIMES; only
# those failures may skip.  Anything else (a compile error in engine.cc,
# an ABI drift against sanitize_main.cc's extern "C" block) must FAIL —
# a skip there would silently green the zero-report acceptance.
_MISSING_RUNTIME_MARKERS = (
    "cannot find -ltsan", "cannot find -lasan", "cannot find -lubsan",
    "libtsan", "libasan", "libubsan",
    "unrecognized command line option", "unrecognized command-line option",
    "unsupported option",
)


def _build(target: str) -> None:
    proc = subprocess.run(["make", "-C", str(NATIVE), target],
                          capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        err = proc.stderr
        if any(m in err for m in _MISSING_RUNTIME_MARKERS):
            pytest.skip(f"sanitizer runtime unavailable: {target}\n"
                        f"{err[-500:]}")
        pytest.fail(f"sanitizer build broke (not a missing runtime): "
                    f"{target}\n{proc.stdout[-500:]}\n{err[-1500:]}")


def _run_campaign(binary: str, port: int, env: dict) -> None:
    proc = subprocess.run(
        [str(NATIVE / binary), str(port), "0.05"],
        capture_output=True, text=True, timeout=240, env=env)
    text = proc.stdout + proc.stderr
    for marker in _REPORT_MARKERS:
        assert marker not in text, f"{binary}: {marker}\n{text[-2000:]}"
    assert proc.returncode == 0, f"{binary} rc={proc.returncode}\n{text[-2000:]}"
    assert "SANITIZE_CAMPAIGN_OK" in text


def test_tsan_campaign_zero_reports():
    import os

    _build("tsan")
    env = dict(os.environ, TSAN_OPTIONS="halt_on_error=1")
    _run_campaign("sanitize_tsan", _PORTS["tsan"], env)


def test_asan_ubsan_campaign_zero_reports():
    import os

    _build("asan")
    env = dict(os.environ,
               ASAN_OPTIONS="detect_stack_use_after_return=1",
               UBSAN_OPTIONS="print_stacktrace=1")
    _run_campaign("sanitize_asan", _PORTS["asan"], env)


def test_lint_native_target_runs():
    """`make lint-native` must succeed: clang-tidy clean when the tool
    exists, a graceful skip message when it does not — never an error."""
    proc = subprocess.run(["make", "-C", str(NATIVE), "lint-native"],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
