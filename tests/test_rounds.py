"""Behavioral tests of the round kernel against protocol semantics.

Constants under test come straight from the reference (BASELINE.md):
1 round = 1 s heartbeat, t_fail=5, t_cooldown=5, min_group=4, ring fanout 3.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import gossip_round, run_rounds
from gossipfs_tpu.core.state import FAILED, MEMBER, UNKNOWN, RoundEvents, SimState, init_state


def schedule(num_rounds, n, crash=(), leave=(), join=()):
    """Build stacked RoundEvents from {round: [nodes]} dicts."""
    def mask(spec):
        m = np.zeros((num_rounds, n), dtype=bool)
        for r, nodes in dict(spec).items():
            m[r, list(nodes)] = True
        return jnp.asarray(m)

    return RoundEvents(crash=mask(crash), leave=mask(leave), join=mask(join))


KEY = jax.random.PRNGKey(0)


class TestSteadyState:
    def test_no_false_positives_and_full_membership(self):
        cfg = SimConfig(n=16)
        state = init_state(cfg)
        state, mc, per_round = run_rounds(state, cfg, 30, KEY)
        assert int(per_round.false_positives.sum()) == 0
        assert int(per_round.true_detections.sum()) == 0
        assert bool(jnp.all(state.status == MEMBER))
        # own heartbeat bumps once per round (slave.go:443-448)
        assert bool(jnp.all(jnp.diag(state.hb) == 30))
        # everyone converged: no detect/converge events fired
        assert bool(jnp.all(mc.first_detect == -1))

    def test_heartbeats_propagate_on_ring(self):
        cfg = SimConfig(n=16)
        state = init_state(cfg)
        state, _, _ = run_rounds(state, cfg, 30, KEY)
        # every view is at most (ring diameter) behind the subject's own count
        lag = jnp.diag(state.hb)[None, :] - state.hb
        assert bool(jnp.all(lag >= 0))
        assert int(lag.max()) <= cfg.n  # loose bound; ring diameter ~ n/3


class TestCrashDetection:
    def test_detection_time_matches_protocol(self):
        # n=10 == the reference's actual deployment scale (10 VMs)
        cfg = SimConfig(n=10)
        crash_round, victim = 10, 5
        state = init_state(cfg)
        ev = schedule(30, cfg.n, crash={crash_round: [victim]})
        state, mc, per_round = run_rounds(state, cfg, 30, KEY, events=ev)
        # victim's last bump+push was round crash_round-1; neighbours' entries
        # stop refreshing, so age exceeds t_fail exactly t_fail+1 rounds later
        first = int(mc.first_detect[victim])
        assert first == crash_round - 1 + cfg.t_fail + 1
        # REMOVE broadcast clears the victim everywhere the same round
        assert int(mc.converged[victim]) == first
        assert int(per_round.false_positives.sum()) == 0
        # detector-removed fail-list entries carry an already-stale timestamp,
        # so they expire to UNKNOWN immediately (slave.go:276-286, 484-497),
        # and the REMOVE broadcast left nobody to gossip the victim back
        col = state.status[:, victim]
        others = jnp.arange(cfg.n) != victim
        assert bool(jnp.all(col[others & np.array(state.alive)] == UNKNOWN))

    def test_emergent_false_positives_beyond_reference_scale(self):
        # At n=16 the ring's freshness diameter exceeds t_fail: when a relay
        # node dies, some live node's entries go stale before updates arrive
        # the long way round, and the protocol false-positively removes it.
        # The reference never saw this (it ran <= 10 VMs, diameter < 5) —
        # measuring exactly this FPR-vs-N behavior is what the simulator is
        # for (BASELINE.md curves).
        cfg = SimConfig(n=16)
        state = init_state(cfg)
        ev = schedule(30, cfg.n, crash={10: [5]})
        state, mc, per_round = run_rounds(state, cfg, 30, KEY, events=ev)
        assert int(per_round.false_positives.sum()) > 0

    def test_no_broadcast_converges_with_fresh_cooldown(self):
        # gossip-only dissemination needs a real suppression window that
        # outlasts the detection spread, else zombies cycle (see next test)
        cfg = SimConfig(
            n=16, remove_broadcast=False, fresh_cooldown=True, t_cooldown=10
        )
        state = init_state(cfg)
        ev = schedule(40, cfg.n, crash={10: [5]})
        state, mc, _ = run_rounds(state, cfg, 40, KEY, events=ev)
        assert int(mc.first_detect[5]) >= 15
        assert int(mc.converged[5]) != -1
        # without broadcast, observers detect independently as their own
        # entries age out — convergence is later or equal, never earlier
        assert int(mc.converged[5]) >= int(mc.first_detect[5])

    def test_stale_cooldown_zombies_cycle_without_broadcast(self):
        # Emergent protocol bug surfaced by the sim: the reference's fail-list
        # entries keep their stale timestamps (slave.go:276-286), so detector
        # removals expire instantly; without the REMOVE broadcast masking it,
        # laggard gossip re-adds the dead member and detection cycles forever.
        cfg = SimConfig(n=16, remove_broadcast=False)  # faithful cooldown
        state = init_state(cfg)
        ev = schedule(60, cfg.n, crash={10: [5]})
        state, mc, per_round = run_rounds(state, cfg, 60, KEY, events=ev)
        assert int(mc.converged[5]) == -1
        # the same dead node keeps getting re-detected, round after round
        assert int(per_round.true_detections.sum()) > cfg.n

    def test_hb_grace_never_detects_silent_newborn(self):
        # reference quirk kept: entries with hb <= 1 are exempt from detection
        # (slave/slave.go:468-469) — a node that crashes before its counter
        # passes 1 is never detected.
        cfg = SimConfig(n=16)
        state = init_state(cfg)
        ev = schedule(30, cfg.n, crash={0: [5]})  # dies before any bump
        state, mc, _ = run_rounds(state, cfg, 30, KEY, events=ev)
        assert int(mc.first_detect[5]) == -1
        assert bool(jnp.all(state.status[:, 5][np.array(state.alive)] == MEMBER))


class TestSmallGroup:
    def test_below_min_group_never_detects(self):
        # groups smaller than 4 only refresh timestamps (slave.go:504-509)
        cfg = SimConfig(n=8)
        mask = jnp.arange(8) < 3
        state = init_state(cfg, member_mask=mask)
        ev = schedule(30, cfg.n, crash={5: [2]})
        state, mc, per_round = run_rounds(state, cfg, 30, KEY, events=ev)
        assert int(mc.first_detect[2]) == -1
        assert int(per_round.true_detections.sum()) == 0
        # survivors still list the dead node as MEMBER forever
        assert int(state.status[0, 2]) == MEMBER

    def test_exactly_min_group_detects(self):
        cfg = SimConfig(n=8)
        mask = jnp.arange(8) < 4
        state = init_state(cfg, member_mask=mask)
        ev = schedule(40, cfg.n, crash={10: [2]})
        state, mc, _ = run_rounds(state, cfg, 40, KEY, events=ev)
        assert int(mc.first_detect[2]) != -1


class TestLeaveJoin:
    def test_leave_removes_immediately_without_detection(self):
        cfg = SimConfig(n=16)
        state = init_state(cfg)
        ev = schedule(20, cfg.n, leave={10: [7]})
        state, mc, per_round = run_rounds(state, cfg, 20, KEY, events=ev)
        # LEAVE broadcast removes at the leave round; the detector never fires
        assert int(mc.first_detect[7]) == -1
        assert int(mc.converged[7]) == 10
        assert int(per_round.true_detections.sum()) == 0
        assert not bool(state.alive[7])

    def test_join_spreads_to_everyone(self):
        cfg = SimConfig(n=16)
        mask = jnp.arange(16) < 12
        state = init_state(cfg, member_mask=mask)
        ev = schedule(20, cfg.n, join={5: [13]})
        state, _, _ = run_rounds(state, cfg, 20, KEY, events=ev)
        assert bool(state.alive[13])
        alive = np.array(state.alive)
        assert bool(jnp.all(state.status[alive, 13] == MEMBER))
        # the joiner learned the whole cohort from the introducer's push
        assert int(jnp.sum(state.status[13] == MEMBER)) == 13

    def test_join_fails_when_introducer_down(self):
        # the hardcoded introducer is a SPOF in the reference (slave.go:22);
        # semantics kept: a join while it is down is lost
        cfg = SimConfig(n=16)
        mask = jnp.arange(16) < 12
        state = init_state(cfg, member_mask=mask)
        ev = schedule(20, cfg.n, crash={3: [0]}, join={5: [13]})
        state, _, _ = run_rounds(state, cfg, 20, KEY, events=ev)
        assert not bool(state.alive[13])


class TestRandomTopology:
    def test_random_fanout_detects_and_converges(self):
        cfg = SimConfig(n=64, topology="random", fanout=SimConfig.log_fanout(64))
        state = init_state(cfg)
        ev = schedule(40, cfg.n, crash={10: [17]})
        state, mc, per_round = run_rounds(state, cfg, 40, KEY, events=ev)
        assert int(mc.first_detect[17]) != -1
        assert int(mc.converged[17]) != -1
        assert int(per_round.false_positives.sum()) == 0

    def test_churn_run_is_stable(self):
        cfg = SimConfig(n=64, topology="random", fanout=6, remove_broadcast=True)
        state = init_state(cfg)
        state, mc, per_round = run_rounds(
            state, cfg, 60, KEY, crash_rate=0.01, rejoin_rate=0.05
        )
        assert int(per_round.n_alive[-1]) > 0
        # crashes are being noticed
        assert int(per_round.true_detections.sum()) > 0


class TestHeartbeatRebasing:
    def test_column_shift_invariance(self):
        """The int16 gossip-view rebasing (core/rounds.py _merge) must make
        round semantics invariant to a uniform shift of heartbeat counters:
        shifting every hb by a constant far beyond REBASE_WINDOW and running
        the same rounds yields the same state, shifted back."""
        shift = 1_000_000
        cfg = SimConfig(n=64, topology="random", fanout=6)
        state = init_state(cfg)
        # settle so every live entry is past the hb grace in both runs
        ev = schedule(10, cfg.n)
        state, _, _ = run_rounds(state, cfg, 10, KEY, events=ev)

        shifted = state._replace(hb=state.hb + shift)
        ev = schedule(25, cfg.n, crash={3: [7], 12: [40]}, leave={5: [2]})
        out_a, mc_a, pr_a = run_rounds(state, cfg, 25, KEY, events=ev)
        out_b, mc_b, pr_b = run_rounds(shifted, cfg, 25, KEY, events=ev)

        assert jnp.array_equal(out_b.hb, out_a.hb + shift)
        assert jnp.array_equal(out_b.age, out_a.age)
        assert jnp.array_equal(out_b.status, out_a.status)
        assert jnp.array_equal(mc_b.first_detect, mc_a.first_detect)
        assert jnp.array_equal(pr_b.true_detections, pr_a.true_detections)

    def test_age_saturates_without_overflow(self):
        from gossipfs_tpu.config import AGE_CLAMP

        cfg = SimConfig(n=8)  # below min_group=4? n=8 fine
        state = init_state(cfg)
        state, _, _ = run_rounds(state, cfg, AGE_CLAMP + 40, KEY)
        assert state.age.dtype == jnp.int8
        assert int(state.age.max()) <= AGE_CLAMP
        assert int(state.age.min()) >= 0

    def test_int8_view_matches_int16(self):
        """view_dtype='int8' (the bench headline) must be semantically
        identical to int16 whenever gossip lag stays inside the 126-round
        int8 window — which is every random-fanout steady state.  The hb
        shift pushes colmax past 126 so the int8 run actively rebases
        (base > 0) while the int16 run does not: equality here proves the
        narrow view changes bytes, not protocol behavior."""
        import dataclasses

        cfg16 = SimConfig(n=64, topology="random", fanout=6, view_dtype="int16")
        cfg8 = dataclasses.replace(cfg16, view_dtype="int8")
        state = init_state(cfg16)
        state, _, _ = run_rounds(state, cfg16, 10, KEY)
        state = state._replace(hb=state.hb + 200)

        ev = schedule(
            40, cfg16.n, crash={3: [7], 20: [40]}, leave={5: [2]}, join={25: [7]}
        )
        out_a, mc_a, pr_a = run_rounds(state, cfg16, 40, KEY, events=ev)
        out_b, mc_b, pr_b = run_rounds(state, cfg8, 40, KEY, events=ev)
        assert jnp.array_equal(out_b.hb, out_a.hb)
        assert jnp.array_equal(out_b.age, out_a.age)
        assert jnp.array_equal(out_b.status, out_a.status)
        assert jnp.array_equal(mc_b.first_detect, mc_a.first_detect)
        assert jnp.array_equal(mc_b.converged, mc_a.converged)
        assert jnp.array_equal(pr_b.true_detections, pr_a.true_detections)
        assert jnp.array_equal(pr_b.false_positives, pr_a.false_positives)

    def test_int8_view_rejected_for_ring(self):
        with pytest.raises(ValueError, match="int8"):
            SimConfig(n=64, topology="ring", fanout=3, view_dtype="int8")

    @pytest.mark.parametrize("kernel", [
        "xla",
        # interpreter-mode pallas: deep but slow; the fast lane pins the
        # rebasing arithmetic through the xla param
        pytest.param("pallas_interpret", marks=pytest.mark.slow),
    ])
    def test_int16_hb_mode_matches_int32(self, kernel):
        """hb_dtype='int16' stores counters relative to hb_base, renormalized
        every round by the merge write.  Protocol behavior (status, age,
        detection/convergence metrics) and the reconstructed true counters
        on live MEMBER lanes must match the int32 mode exactly; dead rows
        and FAILED/UNKNOWN lanes are don't-care storage.  The run is long
        enough (and hb-shifted) that store_base > 0, so the relative
        encoding is actually exercised."""
        import dataclasses

        n = 256 if kernel == "pallas_interpret" else 64
        fo = 8 if kernel == "pallas_interpret" else 6
        cfg32 = SimConfig(
            n=n, topology="random", fanout=fo, merge_kernel=kernel,
            view_dtype="int8", hb_dtype="int32",
        )
        cfg16 = dataclasses.replace(cfg32, hb_dtype="int16")

        def run(cfg):
            state = init_state(cfg)
            state, _, _ = run_rounds(state, cfg, 10, KEY)
            # push counters past the int8 view window so rebasing is active
            state = state._replace(hb=(state.hb + 300).astype(state.hb.dtype))
            ev = schedule(
                50, cfg.n, crash={3: [7], 20: [40]}, leave={5: [2]},
                join={25: [7]},
            )
            return run_rounds(state, cfg, 50, KEY, events=ev)

        out_a, mc_a, pr_a = run(cfg32)
        out_b, mc_b, pr_b = run(cfg16)
        assert out_b.hb.dtype == jnp.int16
        assert jnp.array_equal(out_b.status, out_a.status)
        assert jnp.array_equal(out_b.age, out_a.age)
        assert jnp.array_equal(out_b.alive, out_a.alive)
        assert jnp.array_equal(mc_b.first_detect, mc_a.first_detect)
        assert jnp.array_equal(mc_b.converged, mc_a.converged)
        assert jnp.array_equal(pr_b.true_detections, pr_a.true_detections)
        assert jnp.array_equal(pr_b.false_positives, pr_a.false_positives)
        # true counters agree wherever they are semantically live
        live_member = out_a.alive[:, None] & (out_a.status == MEMBER)
        ha = jnp.where(live_member, out_a.hb_true(), -1)
        hbb = jnp.where(live_member, out_b.hb_true(), -1)
        assert jnp.array_equal(ha, hbb)

    def test_int16_hb_rejected_for_ring(self):
        with pytest.raises(ValueError, match="int16"):
            SimConfig(n=64, topology="ring", fanout=3, hb_dtype="int16")

    def test_run_rounds_donate_matches(self):
        """The buffer-donating variant (used for memory-bound large-N runs)
        is the same program; only the input state's buffers are consumed."""
        from gossipfs_tpu.core.rounds import run_rounds_donate

        cfg = SimConfig(n=64, topology="random", fanout=6)
        ev = schedule(20, cfg.n, crash={3: [7]})
        base = run_rounds(init_state(cfg), cfg, 20, KEY, events=ev)
        got = run_rounds_donate(init_state(cfg), cfg, 20, KEY, events=ev)
        for a, b in zip(jax.tree.leaves(base), jax.tree.leaves(got)):
            assert jnp.array_equal(a, b)

    def test_int8_view_rejected_when_lag_bound_exceeds_window(self):
        """t_fail x diameter must fit the 126-round window: tiny fanout on a
        large graph (many hops) or a huge t_fail both blow it."""
        with pytest.raises(ValueError, match="rebase window"):
            SimConfig(n=4096, topology="random", fanout=1, view_dtype="int8")
        with pytest.raises(ValueError, match="rebase window"):
            SimConfig(
                n=1024, topology="random", fanout=10, t_fail=40,
                view_dtype="int8",
            )
        # the bench headline config must remain admissible
        SimConfig(
            n=16_384, topology="random", fanout=SimConfig.log_fanout(16_384),
            view_dtype="int8",
        )

    def test_rejoin_after_long_run_not_masked_by_stale_lanes(self):
        """The rebase base must come from gossip-eligible copies only.
        Frozen hb lanes of expired (UNKNOWN) entries keep crash-time
        counters; if they anchored the base, a node rejoining once the run
        is > REBASE_WINDOW rounds old would have its fresh hb=0 entries
        masked out of the int16 view, age out at every peer, and be
        false-positive detected forever."""
        from gossipfs_tpu.config import REBASE_WINDOW

        cfg = SimConfig(n=32, topology="random", fanout=5)
        state = init_state(cfg)
        state, _, _ = run_rounds(state, cfg, 5, KEY)
        # simulate a REBASE_WINDOW+ old cluster (uniform shift is behavior-
        # preserving, test_column_shift_invariance)
        state = state._replace(hb=state.hb + REBASE_WINDOW + 1000)

        j = 7
        ev = schedule(cfg.t_fail + cfg.t_cooldown + 4, cfg.n, crash={0: [j]})
        state, _, _ = run_rounds(state, cfg, ev.crash.shape[0], KEY, events=ev)
        # j's entries have expired to UNKNOWN, hb lanes frozen high
        assert int((state.status[:, j] == MEMBER).sum()) <= 1

        ev = schedule(25, cfg.n, join={0: [j]})
        state, _, per_round = run_rounds(state, cfg, 25, KEY, events=ev)
        assert bool(state.alive[j])
        assert int(per_round.false_positives.sum()) == 0
        # every live peer carries j as a fresh MEMBER again
        live = state.alive & (jnp.arange(cfg.n) != j)
        assert bool(jnp.all(state.status[live, j] == MEMBER))
        assert int(state.age[live, j].max()) <= cfg.t_fail


class TestInteractiveHostTraffic:
    def test_eventful_advance_pulls_vectors_not_matrices(self, monkeypatch):
        """Interactive advance's per-eventful-round host transfer is O(N):
        the per-subject detection vectors, never the [N, N] fail matrix
        (measured by tallying every device->host conversion the driver
        makes while a crash is detected)."""
        import numpy as np

        from gossipfs_tpu.detector import sim as sim_mod
        from gossipfs_tpu.detector.sim import SimDetector

        cfg = SimConfig(n=256, topology="random", fanout=8)
        det = SimDetector(cfg)
        det.advance(2)
        det.crash(7)

        pulled: list[int] = []
        real_asarray = np.asarray

        def tally(x, *a, **k):
            out = real_asarray(x, *a, **k)
            pulled.append(out.nbytes)
            return out

        monkeypatch.setattr(sim_mod.np, "asarray", tally)
        det.advance(cfg.t_fail + 3)  # crosses the detection round
        events = det.drain_events()
        assert any(e.subject == 7 for e in events)
        # every host pull is vector-sized: O(N) with small constants, an
        # order of magnitude under the N*N fail matrix
        assert pulled and max(pulled) <= 8 * cfg.n
        assert max(pulled) < cfg.n * cfg.n


class TestPackedDetector:
    """Interactive FailureDetector over the rr packed state (the
    capacity-frontier CLI path, detector/sim.PackedDetector)."""

    def _cfg(self):
        from gossipfs_tpu.config import SimConfig

        return SimConfig.packed_rr(1024, interpret=True, fanout=8)

    def test_crash_detected_at_t_fail_with_first_observer(self):
        from gossipfs_tpu.detector.sim import PackedDetector, SimDetector

        cfg = self._cfg()
        d = PackedDetector(cfg, seed=3)
        d.advance(3)
        d.crash(5)
        d.advance(8)
        ev = [e for e in d.drain_events() if e.subject == 5]
        assert len(ev) == 1 and ev[0].round == 8  # crash@3 + t_fail 5
        assert not ev[0].false_positive
        assert 5 not in d.alive_nodes()
        # the scan-path detector agrees on the FIRST detection round (its
        # interactive path additionally re-reports the subject on later
        # rounds as more observers fire; the packed path matches the bulk
        # path's first-detection-only stream)
        s = SimDetector(cfg, seed=3)
        s.advance(3)
        s.crash(5)
        s.advance(8)
        sv = [e for e in s.drain_events() if e.subject == 5]
        assert sv and sv[0].round == 8

    @pytest.mark.slow  # interpret-mode rr rounds; the fast lane keeps
    # crash-detection and the join-vs-matrix oracle (the two strongest
    # PackedDetector checks); these variations rerun the same machinery
    def test_leave_is_silent_death(self):
        from gossipfs_tpu.detector.sim import PackedDetector

        d = PackedDetector(self._cfg())
        d.advance(3)
        d.leave(7)
        d.advance(8)
        assert any(e.subject == 7 for e in d.drain_events())

    def test_join_matches_matrix_scan_bit_for_bit(self):
        """Round-5: PackedDetector.join — an O(N) column/row rewrite on
        the packed lanes between donated scans — must reproduce the
        matrix path's join semantics exactly.  Same key schedule, same
        crash/rejoin timeline: final hb/age/status/alive bit-identical
        to run_rounds with scheduled matrix events."""
        import dataclasses

        import jax.numpy as jnp

        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents, init_state
        from gossipfs_tpu.detector.sim import PackedDetector
        from gossipfs_tpu.ops import merge_pallas

        cfg = self._cfg()
        rounds = 20
        d = PackedDetector(cfg, seed=3)
        d.advance(2)
        d.crash(7)
        d.advance(10)          # detection (t_fail 5) + cooldown expiry
        d.join(7)
        d.advance(rounds - 12)
        hb4, as4, alive, hb_base, rnd, _ = d._carry
        age_w, st_w = merge_pallas.unpack_age_status(as4)
        tr = lambda a: a.transpose(1, 0, 2, 3)  # noqa: E731

        ev = np.zeros((rounds, cfg.n), dtype=bool)
        ev[2, 7] = True
        join = np.zeros((rounds, cfg.n), dtype=bool)
        join[12, 7] = True
        z = jnp.zeros((rounds, cfg.n), dtype=bool)
        events = RoundEvents(crash=jnp.asarray(ev), leave=z,
                             join=jnp.asarray(join))
        mcfg = dataclasses.replace(cfg, merge_kernel="xla")
        final, carry, _ = run_rounds(
            init_state(mcfg), mcfg, rounds, jax.random.PRNGKey(3),
            events=events,
        )
        assert 7 in d.alive_nodes()
        assert jnp.array_equal(final.hb.reshape(cfg.n, -1),
                               tr(hb4).reshape(cfg.n, -1))
        assert jnp.array_equal(final.status.reshape(cfg.n, -1),
                               tr(st_w.astype(jnp.int8)).reshape(cfg.n, -1))
        assert jnp.array_equal(final.age.reshape(cfg.n, -1),
                               tr(age_w.astype(jnp.int8)).reshape(cfg.n, -1))
        assert jnp.array_equal(final.alive, alive)
        assert jnp.array_equal(final.hb_base, hb_base)
        # rejoin resets the subject's detection clock in the carry
        assert int(d._mcarry.first_detect[7]) == -1

    @pytest.mark.slow  # interpret-mode rr rounds; the fast lane keeps
    # crash-detection and the join-vs-matrix oracle (the two strongest
    # PackedDetector checks); these variations rerun the same machinery
    def test_same_round_crash_and_join_leaves_node_alive(self):
        """Matrix ordering: crashes land before joins, so crash(j)+join(j)
        queued into the same advance ends with j ALIVE (fresh incarnation)
        — the packed path must clear the honored crash bit, not kill the
        joiner it just revived."""
        import dataclasses

        import jax.numpy as jnp

        from gossipfs_tpu.core.rounds import run_rounds
        from gossipfs_tpu.core.state import RoundEvents, init_state
        from gossipfs_tpu.detector.sim import PackedDetector
        from gossipfs_tpu.ops import merge_pallas

        cfg = self._cfg()
        d = PackedDetector(cfg, seed=3)
        d.advance(2)
        d.crash(7)
        d.join(7)
        d.advance(3)
        assert 7 in d.alive_nodes()
        hb4, _, alive, _, _, _ = d._carry
        tr = lambda a: a.transpose(1, 0, 2, 3)  # noqa: E731

        rounds = 5
        ev = np.zeros((rounds, cfg.n), dtype=bool)
        ev[2, 7] = True
        join = np.zeros((rounds, cfg.n), dtype=bool)
        join[2, 7] = True
        z = jnp.zeros((rounds, cfg.n), dtype=bool)
        events = RoundEvents(crash=jnp.asarray(ev), leave=z,
                             join=jnp.asarray(join))
        mcfg = dataclasses.replace(cfg, merge_kernel="xla")
        final, _, _ = run_rounds(
            init_state(mcfg), mcfg, rounds, jax.random.PRNGKey(3),
            events=events,
        )
        assert jnp.array_equal(final.alive, alive)
        assert jnp.array_equal(final.hb.reshape(cfg.n, -1),
                               tr(hb4).reshape(cfg.n, -1))

    @pytest.mark.slow  # interpret-mode rr rounds; the fast lane keeps
    # crash-detection and the join-vs-matrix oracle (the two strongest
    # PackedDetector checks); these variations rerun the same machinery
    def test_rejoin_within_cooldown_is_suppressed(self):
        """Zombie suppression: a rejoin while receivers still hold the
        FAILED (fail-list) entry must not be re-added by them — only the
        introducer appends — matching the reference's RecentFailList gate
        (slave.go:430-439)."""
        from gossipfs_tpu.detector.sim import PackedDetector

        cfg = self._cfg()
        d = PackedDetector(cfg, seed=3)
        d.advance(2)
        d.crash(7)
        d.advance(7)   # detected (crash@2 + t_fail 5 -> round 7), within
                       # the t_cooldown=12 suppression window
        d.join(7)
        d.advance(1)
        # joiner is alive and self-listed; a non-introducer receiver that
        # holds the cooldown entry has NOT re-added it yet
        assert 7 in d.alive_nodes()
        assert 7 in d.membership(7)
        others = [m for m in (1, 2, 3) if m != cfg.introducer]
        assert any(7 not in d.membership(m) for m in others)
        # gossip re-spreads the fresh incarnation once cooldown expires
        d.advance(30)
        assert 7 in d.membership(others[0])

    @pytest.mark.slow  # interpret-mode rr rounds; the fast lane keeps
    # crash-detection and the join-vs-matrix oracle (the two strongest
    # PackedDetector checks); these variations rerun the same machinery
    def test_membership_drops_after_convergence(self):
        from gossipfs_tpu.detector.sim import PackedDetector

        d = PackedDetector(self._cfg())
        d.advance(3)
        d.crash(9)
        d.advance(16)  # detection + gossip diameter
        assert 9 not in d.membership(0)
        assert len(d.membership(0)) == 1023
