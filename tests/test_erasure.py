"""Erasure plane (gossipfs_tpu/erasure/ + the redundancy="stripe" path).

Four layers, fast lane throughout:

  * codec — exhaustive GF(256) arithmetic vs a bitwise reference loop,
    every <= m-erasure pattern at (4, 2) and (8, 3) decoded bit-exact,
    and the tensor/numpy twins pinned equal (the BASELINE.md parity
    contract);
  * planner — rack-disjoint tensor placement, the masked-top-k stripe
    repair plan (most-endangered-first ordering asserted), and the
    host twins' rack-balance bounds;
  * cluster/cosim — the n=32 put/get/rack-kill/repair smoke with zero
    acked-write loss, stale-slot boundedness, election rebuild from
    fragment frame headers, and the event-replay durability ledger;
  * tooling — the committed stripe rack-kill regression case replays
    (campaigns.run_case), and the stripe vitals obey the n/a-never-0
    rule both ways in `traffic status`.
"""

import io
import itertools
import random

import numpy as np
import pytest

from gossipfs_tpu.erasure import codec, planner
from gossipfs_tpu.sdfs.quorum import stripe_read_quorum, stripe_write_quorum
from gossipfs_tpu.sdfs.types import STRIPE_K, STRIPE_M

pytestmark = pytest.mark.erasure


def _ref_gf_mul(a: int, b: int) -> int:
    """Bitwise carry-less multiply mod 0x11d — the schoolbook reference
    the table path must agree with everywhere."""
    out = 0
    while b:
        if b & 1:
            out ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
    return out


# ---------------------------------------------------------------------------
# GF(256) arithmetic — exhaustive vs the reference loop
# ---------------------------------------------------------------------------


class TestField:
    def test_mul_exhaustive_vs_reference(self):
        for a in range(256):
            for b in range(256):
                assert codec.gf_mul(a, b) == _ref_gf_mul(a, b), (a, b)

    def test_inverse_exhaustive(self):
        for a in range(1, 256):
            inv = codec.gf_inv(a)
            assert codec.gf_mul(a, inv) == 1, a
        with pytest.raises(ZeroDivisionError):
            codec.gf_inv(0)

    def test_div_exhaustive(self):
        for a in range(256):
            for b in range(1, 256):
                assert codec.gf_mul(codec.gf_div(a, b), b) == a, (a, b)
        with pytest.raises(ZeroDivisionError):
            codec.gf_div(3, 0)

    def test_matinv_roundtrip_and_singular(self):
        rng = np.random.default_rng(7)
        eye = np.eye(4, dtype=np.uint8)
        for _ in range(8):
            # random k x k submatrix of a generator — nonsingular by MDS
            rows = tuple(sorted(rng.choice(6, size=4, replace=False)))
            a = codec.generator_rows(4, 2)[list(rows)]
            assert (codec.gf_matmul_np(codec.gf_matinv(a), a) == eye).all()
        with pytest.raises(np.linalg.LinAlgError):
            codec.gf_matinv(np.zeros((3, 3), dtype=np.uint8))


# ---------------------------------------------------------------------------
# codec — every <= m erasure pattern decodes bit-exact; twins pinned
# ---------------------------------------------------------------------------


class TestCodec:
    @pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
    def test_all_erasure_patterns_bit_exact(self, k, m):
        rng = random.Random(f"erasure:{k}:{m}")
        data = bytes(rng.randrange(256) for _ in range(k * 37 + 5))
        fragments = codec.encode_blob(data, k, m)
        assert len(fragments) == k + m
        for drop in range(m + 1):
            for lost in itertools.combinations(range(k + m), drop):
                kept = {s: fragments[s] for s in range(k + m)
                        if s not in lost}
                assert codec.decode_blob(kept, k, m, len(data)) == data, lost

    def test_beyond_m_erasures_is_undecodable(self):
        data = b"x" * 64
        fragments = codec.encode_blob(data, 4, 2)
        kept = {s: fragments[s] for s in range(3)}  # only 3 < k survive
        with pytest.raises(ValueError, match="need >= 4 fragments"):
            codec.decode_blob(kept, 4, 2, len(data))

    def test_empty_payload_roundtrip(self):
        fragments = codec.encode_blob(b"", 4, 2)
        assert all(f == b"" for f in fragments)
        kept = {s: fragments[s] for s in (0, 2, 4, 5)}
        assert codec.decode_blob(kept, 4, 2, 0) == b""

    def test_tensor_numpy_encode_decode_parity(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, size=(4, 96), dtype=np.uint8)
        host = codec.encode_np(data, 2)
        dev = np.asarray(codec.encode(jnp.asarray(data), 2))
        assert (host == dev).all()
        slots = (1, 3, 4, 5)  # parity-including survivor set
        frag = host[list(slots)]
        back_h = codec.decode_np(frag, slots, 4, 2)
        back_d = np.asarray(codec.decode(jnp.asarray(frag), slots, 4, 2))
        assert (back_h == data).all()
        assert (back_h == back_d).all()

    def test_repair_fragments_rebuilds_exact_rows(self):
        data = bytes(range(256)) * 3
        fragments = codec.encode_blob(data, 4, 2)
        kept = {s: fragments[s] for s in (0, 1, 4, 5)}
        rebuilt = codec.repair_fragments(kept, [2, 3], 4, 2, len(data))
        assert rebuilt[2] == fragments[2] and rebuilt[3] == fragments[3]

    def test_fragment_framing_and_keys(self):
        packed = codec.pack_fragment(b"rowbytes", 1234)
        assert codec.unpack_fragment(packed) == (1234, b"rowbytes")
        key = codec.frag_key("dir/f1.txt", 5)
        assert codec.parse_frag_key(key) == ("dir/f1.txt", 5)
        assert codec.parse_frag_key("plain.txt") is None
        assert codec.parse_frag_key("odd#sx") is None

    def test_quorums_owned_by_quorum_py(self):
        assert stripe_read_quorum(4, 2) == 4
        assert stripe_write_quorum(4, 2, 0) == 6
        assert stripe_write_quorum(4, 2, 1) == 5
        with pytest.raises(ValueError):
            stripe_write_quorum(4, 2, 2)  # slack must stay <= m - 1
        with pytest.raises(ValueError):
            stripe_read_quorum(0, 2)
        with pytest.raises(ValueError):
            codec.parity_matrix(200, 100)  # k + m > 256


# ---------------------------------------------------------------------------
# planner — tensor placement/repair + host twins
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_place_stripes_rack_disjoint_and_deterministic(self):
        import jax
        import jax.numpy as jnp

        n = 64
        racks = jnp.arange(n) // 8  # 8 racks >= k+m=6
        alive = jnp.ones(n, dtype=bool)
        key = jax.random.PRNGKey(11)
        rows = np.asarray(planner.place_stripes(key, alive, racks, 32))
        again = np.asarray(planner.place_stripes(key, alive, racks, 32))
        assert (rows == again).all()  # pure function of the key
        for row in rows:
            placed = row[row >= 0]
            assert len(placed) == 6  # 24 oversampled draws over 8 racks
            assert len({int(x) // 8 for x in placed}) == 6  # rack-disjoint

    def test_stripe_repair_plan_most_endangered_first(self):
        import jax
        import jax.numpy as jnp

        n = 24
        width = STRIPE_K + STRIPE_M
        # 3 stripes with deficits 2, 0, 1 — the budget=2 cut must pick
        # stripe 0 (two dead holders) ahead of stripe 2 (one)
        holders = jnp.array([
            [0, 1, 2, 3, 4, 5],
            [6, 7, 8, 9, 10, 11],
            [12, 13, 14, 15, 16, 17],
        ], dtype=jnp.int32)
        alive = jnp.ones(n, dtype=bool).at[jnp.array([0, 1, 12])].set(False)
        plan = planner.plan_stripe_repairs_tensor(
            jax.random.PRNGKey(0), holders, jnp.int32(3), alive, alive,
            budget=2)
        assert int(plan.degraded) == 2
        assert not bool(plan.lost.any())
        assert plan.idx[0] == 0 and int(plan.need[0]) == 2  # worst first
        assert plan.idx[1] == 2 and int(plan.need[1]) == 1
        picks = np.asarray(plan.picks)
        # slot-aligned: only the holed slots get fresh (live, non-holder)
        assert set(np.nonzero(picks[0] >= 0)[0]) == {0, 1}
        assert set(np.nonzero(picks[1] >= 0)[0]) == {0}
        fresh = picks[picks >= 0]
        assert all(bool(alive[int(x)]) for x in fresh)
        again = planner.plan_stripe_repairs_tensor(
            jax.random.PRNGKey(0), holders, jnp.int32(3), alive, alive,
            budget=2)
        assert (np.asarray(again.picks) == picks).all()  # keyed determinism

    def test_stripe_below_k_is_lost_not_planned(self):
        import jax
        import jax.numpy as jnp

        holders = jnp.array([[0, 1, 2, 3, 4, 5]], dtype=jnp.int32)
        alive = jnp.ones(8, dtype=bool).at[jnp.array([0, 1, 2])].set(False)
        plan = planner.plan_stripe_repairs_tensor(
            jax.random.PRNGKey(0), holders, jnp.int32(1), alive, alive,
            budget=4)
        assert bool(plan.lost[0])  # 3 live < k=4: unreconstructable
        assert not bool(plan.valid.any())

    def test_place_stripe_host_rack_balance_bound(self):
        # 8 racks: full disjointness; 4 racks: per-rack load <= 2 = m
        for n_racks, bound in ((8, 1), (4, 2)):
            members = list(range(n_racks * 8))
            racks = {i: i // 8 for i in members}
            for seed in range(12):
                chosen = planner.place_stripe(
                    members, racks, random.Random(seed))
                assert len(chosen) == 6 and len(set(chosen)) == 6
                loads: dict[int, int] = {}
                for node in chosen:
                    loads[racks[node]] = loads.get(racks[node], 0) + 1
                assert max(loads.values()) <= bound, (n_racks, seed)

    def test_pick_repair_targets_fills_least_loaded_racks(self):
        racks = {i: i // 4 for i in range(16)}  # 4 racks of 4
        rack_load = {0: 2, 1: 2, 2: 0, 3: 0}  # survivors crowd racks 0/1
        picks = planner.pick_repair_targets(
            list(range(16)), racks, rack_load, need=2, rng=random.Random(5))
        assert len(picks) == 2
        assert {racks[p] for p in picks} == {2, 3}  # emptiest racks first


# ---------------------------------------------------------------------------
# cluster — the n=32 put/get/rack-kill/repair smoke
# ---------------------------------------------------------------------------


def _stripe_cluster(n=32, seed=1):
    from gossipfs_tpu.sdfs.cluster import SDFSCluster

    return SDFSCluster(n, seed=seed, redundancy="stripe", rack_size=8)


class TestStripeCluster:
    def test_put_get_rack_kill_repair_no_loss(self):
        cl = _stripe_cluster()
        payloads = {f"f{i}.txt": bytes([i]) * (100 + 31 * i)
                    for i in range(8)}
        for now, (name, data) in enumerate(payloads.items()):
            assert cl.put(name, data, now=100 * (now + 1))
        # kill rack 1 entirely — at 4 racks the balance bound keeps every
        # stripe's per-rack exposure <= m=2, so nothing is lost
        view = [x for x in range(32) if not 8 <= x < 16]
        cl.update_membership(view, now=1000)
        assert cl.lost_files() == []
        for name, data in payloads.items():
            assert cl.get(name) == data  # mid-kill reads reconstruct
        # budgeted drain: most-endangered-first within each pass
        total_plans = 0
        for _ in range(12):
            plans = cl.fail_recover(budget=3)
            total_plans += len(plans)
            survivors = [len(p.survivors) for p in plans]
            assert survivors == sorted(survivors)
            if not plans and not cl.last_repair_pending:
                break
        assert total_plans > 0
        # repair restored full strength on live nodes only
        live = set(cl.live)
        for name, data in payloads.items():
            slots = cl.ls(name)
            assert all(nd in live for nd in slots)
            assert cl.get(name) == data
        # repair_copies counts FRAGMENTS rebuilt; a single stripe plan
        # can rebuild several (rack kill costs up to m per stripe)
        assert cl.repair_copies >= total_plans
        assert cl.repair_bytes_written > 0

    def test_overwrite_bumps_version_and_rewrites_all_slots(self):
        cl = _stripe_cluster(n=16)
        assert cl.put("f.txt", b"v1" * 50, now=10)
        slots1 = list(cl.ls("f.txt"))
        _, v1, len1 = cl.master.stripe_file_info("f.txt")
        assert cl.put("f.txt", b"longer-v2" * 40, now=200)
        slots2, v2, len2 = cl.master.stripe_file_info("f.txt")
        assert slots2 == slots1  # placement is once per lifetime
        assert v2 > v1 and len2 == 9 * 40
        assert cl.get("f.txt") == b"longer-v2" * 40
        # every slot rewrote: no fragment is stale beyond the write slack
        stale = sum(
            1 for slot, nd in enumerate(slots2)
            if cl.stores[nd].version(codec.frag_key("f.txt", slot)) < v2
        )
        assert stale == 0

    def test_delete_drops_fragments_on_live_nodes(self):
        cl = _stripe_cluster(n=16)
        assert cl.put("gone.txt", b"data" * 32, now=5)
        assert cl.delete("gone.txt")
        assert "gone.txt" not in cl.master.stripes
        for i in cl.live:
            assert not any("gone.txt#" in k
                           for k in cl.stores[i].listing())
        assert cl.get("gone.txt") is None

    def test_election_rebuilds_stripes_from_frame_headers(self):
        cl = _stripe_cluster(n=16)
        data = {"a.txt": b"A" * 777, "b.txt": b"B" * 130}
        for now, (name, blob) in enumerate(data.items()):
            assert cl.put(name, blob, now=50 * (now + 1))
        versions = {n: cl.master.stripes[n].version for n in data}
        cl.update_membership([x for x in range(16) if x != 0], now=900)
        assert cl.master_node != 0  # election happened
        for name, blob in data.items():
            info = cl.master.stripes[name]
            assert info.version == versions[name]
            assert info.length == len(blob)  # recovered from frame header
            assert cl.get(name) == blob

    def test_losing_more_than_m_fragments_is_reported_lost(self):
        cl = _stripe_cluster(n=16)
        assert cl.put("doomed.txt", b"z" * 64, now=5)
        holders = [nd for nd in cl.ls("doomed.txt") if nd >= 0]
        dead = set(holders[: STRIPE_M + 1])  # one past the parity margin
        cl.update_membership([x for x in range(16) if x not in dead],
                             now=100)
        assert cl.lost_files() == ["doomed.txt"]


# ---------------------------------------------------------------------------
# event-replay durability ledger (traffic/audit.py) — stripe semantics
# ---------------------------------------------------------------------------


class TestStripeAudit:
    def _ev(self, rnd, kind, subject=-1, **detail):
        from gossipfs_tpu.obs.schema import Event

        return Event(round=rnd, observer=-1, subject=subject, kind=kind,
                     detail=detail)

    def test_per_slot_ledger_counts_recoverable_slots(self):
        from gossipfs_tpu.traffic.audit import durability_from_events

        put = self._ev(1, "stripe_put", file="f", version=1,
                       fragments=[1, 2, 3], k=2, m=1)
        # k=2: losing one holder is fine, repairing it keeps the file
        # alive through the loss of another
        facts = durability_from_events([
            put, self._ev(2, "crash", subject=2),
            self._ev(3, "stripe_repair", file="f", version=1,
                     slots=[1], targets=[4]),
            self._ev(4, "crash", subject=3),
        ])
        assert facts["lost"] == 0 and facts["repair_events"] == 1
        # without the repair the same crashes cross the MDS line
        facts = durability_from_events([
            put, self._ev(2, "crash", subject=2),
            self._ev(4, "crash", subject=3),
        ])
        assert facts["lost"] == 1 and facts["lost_files"] == ["f"]

    def test_rejoined_stale_holder_does_not_double_count(self):
        from gossipfs_tpu.traffic.audit import durability_from_events

        # node 2's copy of slot 1 goes stale at v2; the repair lands slot
        # 1 on node 4.  node 2 rejoining must not count as a second
        # recoverable slot — slot-keyed accounting collapses both to ONE
        facts = durability_from_events([
            self._ev(1, "stripe_put", file="f", version=1,
                     fragments=[1, 2, 3], k=2, m=1),
            self._ev(2, "crash", subject=2),
            self._ev(3, "stripe_put", file="f", version=2,
                     fragments=[1, -1, 3], k=2, m=1),
            self._ev(4, "join", subject=2),
            self._ev(5, "crash", subject=3),
            self._ev(6, "crash", subject=1),
        ])
        # live holders: node 2 (slot 1, stale v1) — zero fresh slots
        assert facts["lost"] == 1

    def test_delete_retires_stripe_state(self):
        from gossipfs_tpu.traffic.audit import durability_from_events

        facts = durability_from_events([
            self._ev(1, "stripe_put", file="f", version=1,
                     fragments=[1, 2, 3], k=2, m=1),
            self._ev(2, "replica_delete", file="f"),
            self._ev(3, "crash", subject=1),
            self._ev(3, "crash", subject=2),
            self._ev(3, "crash", subject=3),
        ])
        assert facts["lost"] == 0 and facts["files_acked"] == 0


# ---------------------------------------------------------------------------
# harness smoke + the committed regression case + vitals rendering
# ---------------------------------------------------------------------------


class TestStripeTraffic:
    def test_rack_kill_smoke_n32_no_acked_write_loss(self):
        """The tier-1 erasure smoke: preload + rack kill + budgeted
        repair at n=32, all three durability accountings in exact
        agreement with zero acked writes lost."""
        from gossipfs_tpu.traffic.harness import repair_storm
        from gossipfs_tpu.traffic.workload import WorkloadSpec

        spec = WorkloadSpec(rate=4.0, n_keys=24, payload_cap=4096,
                            seed=3, redundancy="stripe")
        out = repair_storm(32, spec, files=24, rack=(8, 8),
                           repair_budget=6, seed=3)
        d = out["durability"]
        assert d["harness"]["lost"] == 0
        assert d["events"]["lost"] == 0
        assert d["match"] and d["monitor"]["ok"]
        assert d["monitor"]["match_events"]
        assert out["repairs_total"] > 0
        assert out["max_repairs_per_round"] <= 6  # the budget binds
        assert out["repair_bytes_written"] > 0
        # stripe vitals are REAL MEASUREMENTS here, not fabricated zeros
        assert out["traffic_vitals"]["fragments_lost"] == 0

    def test_committed_rackkill_case_replays(self):
        """regressions/stripe_rackkill_n256.json — the cohort-scale
        stripe rack-kill, replayed through the campaign driver's
        traffic-case branch (the tier-1 contract for committed cases)."""
        from gossipfs_tpu import campaigns

        out = campaigns.run_case("regressions/stripe_rackkill_n256.json")
        assert out["reproduced"], out["row"]["verdict"]
        assert out["row"]["lost"] == 0
        assert out["row"]["repairs_total"] > 0

    def test_stripe_vitals_na_never_zero_both_ways(self):
        """stripes_degraded / fragments_lost ride VITALS_FIELDS: absent
        in replica mode (renders n/a — the mode has no stripes to
        measure), present as real measured values in stripe mode."""
        from gossipfs_tpu.cosim import CoSim
        from gossipfs_tpu.obs import schema
        from gossipfs_tpu.shim import cli
        from gossipfs_tpu.traffic.harness import traffic_config

        assert "stripes_degraded" in schema.VITALS_FIELDS
        assert "fragments_lost" in schema.VITALS_FIELDS
        for kind in ("stripe_put", "stripe_repair", "stripe_lost"):
            assert kind in schema.EVENT_KINDS, kind

        replica = CoSim(traffic_config(16), seed=0)
        doc = replica.traffic_status()
        assert "stripes_degraded" not in doc
        assert "fragments_lost" not in doc
        out = io.StringIO()
        cli.dispatch(replica, "traffic status", out=out)
        assert "stripes degraded=n/a" in out.getvalue()
        assert "fragments lost=n/a" in out.getvalue()

        stripe = CoSim(traffic_config(16), seed=0, redundancy="stripe",
                       rack_size=8)
        assert stripe.put("v.txt", b"x" * 64, confirm=lambda: True)
        doc = stripe.traffic_status()
        assert doc["stripes_degraded"] == 0  # measured clean, not absent
        assert doc["fragments_lost"] == 0
        out = io.StringIO()
        cli.dispatch(stripe, "traffic status", out=out)
        assert "stripes degraded=0" in out.getvalue()
        assert "fragments lost=0" in out.getvalue()

    def test_workload_spec_validates_stripe_knobs(self):
        from gossipfs_tpu.traffic.workload import WorkloadSpec

        with pytest.raises(ValueError, match="unknown redundancy"):
            WorkloadSpec(redundancy="raid6")
        with pytest.raises(ValueError, match="stripe_k and stripe_m"):
            WorkloadSpec(redundancy="stripe", stripe_k=0)
        spec = WorkloadSpec(redundancy="stripe")
        assert (spec.stripe_k, spec.stripe_m) == (STRIPE_K, STRIPE_M)
