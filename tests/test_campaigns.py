"""Campaign driver + committed regression cases (gossipfs_tpu/campaigns/).

Coverage map:
  * the committed regression case replays deterministically and the
    monitor flags it (the tier-1 smoke the acceptance criteria name);
  * a mild severity point of the same family is CLEARED — the monitor
    verdict discriminates, it doesn't just always fire;
  * bisect finds the severity knee between a passing and a violating
    endpoint, and the grid sweep's breaking set brackets it;
  * the ledger is a ``gossipfs-obs/v1`` stream tools/timeline.py
    ingests unchanged (header recognized, verdict rows loaded as
    events);
  * family builders honor the avoid set (fault rules never overlap the
    tracked TTD probes) and reject unknown knobs.
"""

from __future__ import annotations

import importlib.util
import json
import pathlib

import pytest

from gossipfs_tpu import campaigns

pytestmark = pytest.mark.campaign

REPO = pathlib.Path(__file__).resolve().parents[1]
CASE = REPO / "regressions" / "flap_storm_n256.json"


def _timeline():
    spec = importlib.util.spec_from_file_location(
        "timeline_tool", REPO / "tools" / "timeline.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRegressionCase:
    def test_committed_flap_storm_reproduces(self):
        """THE tier-1 smoke: the breaking point the round-13 campaign
        bisected (flap down=3 at t_fail=5, N=256) replays bit-identically
        and the streaming monitor flags the same invariant."""
        out = campaigns.run_case(CASE)
        assert out["reproduced"], out
        assert out["row"]["verdict"] == "violated"
        assert "fpr_storm" in out["row"]["monitor"]["by_invariant"]
        # the committed evidence window rides the row
        assert out["row"]["violation_window"]

    def test_case_file_is_self_contained(self):
        doc = json.loads(CASE.read_text())
        assert doc["schema"] == campaigns.driver.CASE_SCHEMA
        assert doc["expect"]["verdict"] == "violated"
        assert doc["config"]["n"] == 256
        # the embedded scenario is a valid declarative schedule
        from gossipfs_tpu.scenarios import FaultScenario

        sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
        assert sc.flapping and sc.n == 256

    def test_mild_point_clears(self):
        """One notch below the committed knee the monitor CLEARS the
        run — deterministically, with the TTD probes intact.  Runs
        through the driver's sweep entry so the fault nodes avoid the
        tracked victims, exactly like the committed campaign."""
        out = campaigns.sweep_axis("flap", 64, (2,), t_fail=5)
        (row,) = out["rows"]
        assert row["verdict"] == "pass", row["monitor"]
        assert row["estimators"]["detected"] == row["estimators"][
            "tracked_crashes"] == 4
        assert row["estimators"]["ttd_first_median"] == 5


class TestDriver:
    def test_bisect_finds_knee_and_ledger_ingests(self, tmp_path):
        led = campaigns.CampaignLedger(
            tmp_path / "ledger.jsonl", family="flap", n=64, axis="down")
        out = campaigns.bisect_axis("flap", 64, 2, 6, t_fail=5,
                                    ledger=led)
        led.close()
        assert out["breaking_point"] == 3
        by = {r["axis_value"]: r["verdict"] for r in out["rows"]}
        assert by[2] == "pass" and by[3] == "violated"

        # the ledger is an obs/v1 stream: timeline ingests it unchanged
        tl = _timeline()
        header, events = tl.load_stream(str(tmp_path / "ledger.jsonl"))
        assert header["schema"] == "gossipfs-obs/v1"
        assert header["family"] == "flap" and header["axis"] == "down"
        verdicts = [e for e in events if e.kind == "campaign_verdict"]
        assert len(verdicts) == out["evals"]
        assert all("verdict" in e.detail for e in verdicts)
        doc = tl.analyze([header], events)  # no crash, just ingestion
        assert doc["events"] == len(verdicts)

    def test_sweep_brackets_breaking_set(self):
        out = campaigns.sweep_axis("flap", 64, (2, 4), t_fail=5)
        assert out["breaking"] == [4]

    def test_outage_family_violates(self):
        """A correlated blackout: the isolated rack confirms the whole
        far cluster (and vice versa) — an FPR storm by construction."""
        sc = campaigns.make_scenario("outage", 64, 24, size=6, length=12)
        row = campaigns.run_scenario(64, sc, t_fail=5)
        assert row["verdict"] == "violated"
        assert "fpr_storm" in row["monitor"]["by_invariant"]
        assert row["estimators"]["split_brain_rounds"] > 0

    def test_family_builders_avoid_and_validate(self):
        from gossipfs_tpu.scenarios import FaultScenario

        sc = campaigns.make_scenario("flap", 64, 10, avoid={0, 1, 2},
                                     down=3)
        assert isinstance(sc, FaultScenario)
        assert not (set(sc.flapping[0].nodes) & {0, 1, 2})
        with pytest.raises(ValueError, match="unknown family"):
            campaigns.make_scenario("nope", 64, 10)
        with pytest.raises(ValueError, match="knobs"):
            campaigns.make_scenario("flap", 64, 10, stride=3)
        # fixing the swept axis as a knob is rejected up front (before
        # any run or ledger row), not as a mid-campaign TypeError
        with pytest.raises(ValueError, match="severity axis"):
            campaigns.sweep_axis("flap", 16, (3,), down=4)
        with pytest.raises(ValueError, match="severity axis"):
            campaigns.bisect_axis("flap", 16, 2, 6, down=4)

    def test_case_roundtrip(self, tmp_path):
        """write_case -> run_case closes the loop for a fresh breaking
        point (the --commit path's contract)."""
        from gossipfs_tpu.obs.monitor import MonitorParams

        sc = campaigns.make_scenario("flap", 64, 24, down=4)
        row = campaigns.run_scenario(64, sc, t_fail=5)
        assert row["verdict"] == "violated"
        path = tmp_path / "case.json"
        campaigns.write_case(
            path, sc, t_fail=5, t_suspect=0, seed=0, track=4,
            params=MonitorParams.from_dict(row["monitor_params"]),
            expect={"verdict": "violated", "invariants": ["fpr_storm"]},
        )
        out = campaigns.run_case(path)
        assert out["reproduced"], out
