"""Campaign driver + committed regression cases (gossipfs_tpu/campaigns/).

Coverage map:
  * the committed regression case replays deterministically and the
    monitor flags it (the tier-1 smoke the acceptance criteria name);
  * a mild severity point of the same family is CLEARED — the monitor
    verdict discriminates, it doesn't just always fire;
  * bisect finds the severity knee between a passing and a violating
    endpoint, and the grid sweep's breaking set brackets it;
  * the ledger is a ``gossipfs-obs/v1`` stream tools/timeline.py
    ingests unchanged (header recognized, verdict rows loaded as
    events);
  * family builders honor the avoid set (fault rules never overlap the
    tracked TTD probes) and reject unknown knobs.
"""

from __future__ import annotations

import importlib.util
import json
import pathlib

import pytest

from gossipfs_tpu import campaigns

pytestmark = pytest.mark.campaign

REPO = pathlib.Path(__file__).resolve().parents[1]
CASE = REPO / "regressions" / "flap_storm_n256.json"


def _timeline():
    spec = importlib.util.spec_from_file_location(
        "timeline_tool", REPO / "tools" / "timeline.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestRegressionCase:
    def test_committed_flap_storm_reproduces(self):
        """THE tier-1 smoke: the breaking point the round-13 campaign
        bisected (flap down=3 at t_fail=5, N=256) replays bit-identically
        and the streaming monitor flags the same invariant."""
        out = campaigns.run_case(CASE)
        assert out["reproduced"], out
        assert out["row"]["verdict"] == "violated"
        assert "fpr_storm" in out["row"]["monitor"]["by_invariant"]
        # the committed evidence window rides the row
        assert out["row"]["violation_window"]

    def test_case_file_is_self_contained(self):
        doc = json.loads(CASE.read_text())
        assert doc["schema"] == campaigns.driver.CASE_SCHEMA
        assert doc["expect"]["verdict"] == "violated"
        assert doc["config"]["n"] == 256
        # the embedded scenario is a valid declarative schedule
        from gossipfs_tpu.scenarios import FaultScenario

        sc = FaultScenario.from_json(json.dumps(doc["scenario"]))
        assert sc.flapping and sc.n == 256

    def test_mild_point_clears(self):
        """One notch below the committed knee the monitor CLEARS the
        run — deterministically, with the TTD probes intact.  Runs
        through the driver's sweep entry so the fault nodes avoid the
        tracked victims, exactly like the committed campaign."""
        out = campaigns.sweep_axis("flap", 64, (2,), t_fail=5)
        (row,) = out["rows"]
        assert row["verdict"] == "pass", row["monitor"]
        assert row["estimators"]["detected"] == row["estimators"][
            "tracked_crashes"] == 4
        assert row["estimators"]["ttd_first_median"] == 5


class TestDriver:
    def test_bisect_finds_knee_and_ledger_ingests(self, tmp_path):
        led = campaigns.CampaignLedger(
            tmp_path / "ledger.jsonl", family="flap", n=64, axis="down")
        out = campaigns.bisect_axis("flap", 64, 2, 6, t_fail=5,
                                    ledger=led)
        led.close()
        assert out["breaking_point"] == 3
        by = {r["axis_value"]: r["verdict"] for r in out["rows"]}
        assert by[2] == "pass" and by[3] == "violated"

        # the ledger is an obs/v1 stream: timeline ingests it unchanged
        tl = _timeline()
        header, events = tl.load_stream(str(tmp_path / "ledger.jsonl"))
        assert header["schema"] == "gossipfs-obs/v1"
        assert header["family"] == "flap" and header["axis"] == "down"
        verdicts = [e for e in events if e.kind == "campaign_verdict"]
        assert len(verdicts) == out["evals"]
        assert all("verdict" in e.detail for e in verdicts)
        doc = tl.analyze([header], events)  # no crash, just ingestion
        assert doc["events"] == len(verdicts)

    def test_sweep_brackets_breaking_set(self):
        out = campaigns.sweep_axis("flap", 64, (2, 4), t_fail=5)
        assert out["breaking"] == [4]

    def test_outage_family_violates(self):
        """A correlated blackout: the isolated rack confirms the whole
        far cluster (and vice versa) — an FPR storm by construction."""
        sc = campaigns.make_scenario("outage", 64, 24, size=6, length=12)
        row = campaigns.run_scenario(64, sc, t_fail=5)
        assert row["verdict"] == "violated"
        assert "fpr_storm" in row["monitor"]["by_invariant"]
        assert row["estimators"]["split_brain_rounds"] > 0

    def test_family_builders_avoid_and_validate(self):
        from gossipfs_tpu.scenarios import FaultScenario

        sc = campaigns.make_scenario("flap", 64, 10, avoid={0, 1, 2},
                                     down=3)
        assert isinstance(sc, FaultScenario)
        assert not (set(sc.flapping[0].nodes) & {0, 1, 2})
        with pytest.raises(ValueError, match="unknown family"):
            campaigns.make_scenario("nope", 64, 10)
        with pytest.raises(ValueError, match="knobs"):
            campaigns.make_scenario("flap", 64, 10, stride=3)
        # fixing the swept axis as a knob is rejected up front (before
        # any run or ledger row), not as a mid-campaign TypeError
        with pytest.raises(ValueError, match="severity axis"):
            campaigns.sweep_axis("flap", 16, (3,), down=4)
        with pytest.raises(ValueError, match="severity axis"):
            campaigns.bisect_axis("flap", 16, 2, 6, down=4)

    def test_case_roundtrip(self, tmp_path):
        """write_case -> run_case closes the loop for a fresh breaking
        point (the --commit path's contract)."""
        from gossipfs_tpu.obs.monitor import MonitorParams

        sc = campaigns.make_scenario("flap", 64, 24, down=4)
        row = campaigns.run_scenario(64, sc, t_fail=5)
        assert row["verdict"] == "violated"
        path = tmp_path / "case.json"
        campaigns.write_case(
            path, sc, t_fail=5, t_suspect=0, seed=0, track=4,
            params=MonitorParams.from_dict(row["monitor_params"]),
            expect={"verdict": "violated", "invariants": ["fpr_storm"]},
        )
        out = campaigns.run_case(path)
        assert out["reproduced"], out


STORM_CASE = REPO / "regressions" / "outage_storm_n256.json"
ABSORBED_CASE = REPO / "regressions" / "outage_absorbed_n256.json"
MILD_UDP_CASE = REPO / "regressions" / "outage_mild_udp_n24.json"
MILD_DELTA_UDP_CASE = REPO / "regressions" / "outage_mild_delta_udp_n24.json"


class TestOutageAbsorption:
    """Round 14: correlated failure as a first-class absorbed fault —
    the committed storm + its local-health twin, the knob surface, and
    the socket-engine runners."""

    def test_committed_outage_storm_reproduces(self):
        """The round-13 designed-in storm as a standing regression: a
        2-node blackout past the detection window storms the whole
        cluster's FPR by construction (pre-fix verdict recorded in the
        case metadata)."""
        out = campaigns.run_case(STORM_CASE)
        assert out["reproduced"], out
        assert out["row"]["verdict"] == "violated"
        assert "fpr_storm" in out["row"]["monitor"]["by_invariant"]
        doc = json.loads(STORM_CASE.read_text())
        assert "storm" in doc["finding"]

    def test_committed_absorbed_twin_passes(self):
        """The post-fix twin: the same outage family under the
        LOCALHEALTH_r14 chosen knobs clears every invariant — the
        Lifeguard stretch absorbs the rack while the tracked probes
        stay within +1 round of the lh-off baseline."""
        out = campaigns.run_case(ABSORBED_CASE)
        assert out["reproduced"], out
        row = out["row"]
        assert row["verdict"] == "pass"
        assert row["lh_multiplier"] > 0
        # the absorption numbers the twin's metadata claims: FPR in the
        # t_fail=5-class floor, TTD median == the lh-off baseline (6 at
        # t_fail=3 + t_suspect=3) + 1
        assert row["estimators"]["false_positive_rate"] <= 1e-6
        assert row["estimators"]["ttd_first_median"] <= 7.0
        doc = json.loads(ABSORBED_CASE.read_text())
        assert doc["prefix_verdict"]["verdict"] == "violated"

    def test_udp_engine_campaign_smoke(self):
        """THE tier-1 fast-lane udp-engine smoke: one mild committed
        case end-to-end — real sockets, the scenario at the send hook,
        the recorded gossipfs-obs/v1 stream fed back through
        StreamMonitor.feed_jsonl — with the verdict agreeing with the
        tensor replay on every invariant both engines check."""
        out = campaigns.run_case_engine(MILD_UDP_CASE, engine="udp",
                                        period=0.05)
        assert out["reproduced"], out
        assert out["agreement"]["match"], out["agreement"]
        assert out["engine_verdict"] == out["tensor_verdict"] == "pass"
        # the stream really went through the file seam and carried the
        # udp ground-truth round_tick rows
        from gossipfs_tpu.obs.recorder import load_stream

        header, events = load_stream(out["engine_row"]["trace"])
        kinds = {e.kind for e in events}
        assert "round_tick" in kinds and "crash" in kinds

    def test_udp_engine_delta_campaign_smoke(self):
        """THE tier-1 fast-lane delta-dissemination smoke (round 20):
        the mild case's delta twin end-to-end over UdpCluster — the
        membership refresh rides bounded delta frames (changed-first +
        rr tail, cap 16) with a full anti-entropy push every 4th round,
        and the verdict must stay pass AND agree with the tensor
        replay: bounded piggybacking loses no detection fidelity."""
        out = campaigns.run_case_engine(MILD_DELTA_UDP_CASE, engine="udp",
                                        period=0.05)
        assert out["reproduced"], out
        assert out["agreement"]["match"], out["agreement"]
        assert out["engine_verdict"] == out["tensor_verdict"] == "pass"
        # delta mode really engaged on the wire: both frame kinds flowed
        # (deltas between anti-entropy rounds, full lists on them)
        wire = out["engine_row"]["wire"]
        assert wire["frames_delta"] > 0, wire
        assert wire["frames_full"] > 0, wire

    def test_native_engine_campaign_smoke(self):
        """THE tier-1 fast-lane native-engine smoke (round 16): the
        same mild committed case end-to-end over the C++ epoll engine —
        the scenario compiled to the in-engine send-gate table, the
        drained gossipfs-obs/v1 stream fed back through
        StreamMonitor.feed_jsonl — verdict agreement with the tensor
        replay on every invariant, fpr_storm INCLUDED (native
        round_ticks carry in-process ground truth)."""
        import shutil

        if shutil.which("g++") is None or shutil.which("make") is None:
            pytest.skip("no native toolchain")
        out = campaigns.run_case_engine(MILD_UDP_CASE, engine="native")
        assert out["reproduced"], out
        assert out["agreement"]["match"], out["agreement"]
        assert out["engine_verdict"] == out["tensor_verdict"] == "pass"
        assert "fpr_storm" in out["agreement"]["compared"]
        # the stream went through the file seam with ground-truth ticks
        # AND the per-round latency histogram evidence rode the row
        from gossipfs_tpu.obs.recorder import load_stream

        header, events = load_stream(out["engine_row"]["trace"])
        kinds = {e.kind for e in events}
        assert {"round_tick", "crash", "confirm", "remove",
                "scenario_arm"} <= kinds
        assert out["engine_row"]["tick_ms"]["count"] > 0

    def test_nativecampaign_matrix_artifact(self):
        """The committed three-engine verdict matrix — re-anchored at
        round 20 from NATIVECAMPAIGN_r16.json to COHORT_r20.json
        (`tools/campaign.py --matrix --ab`): the matrix nests under
        "matrix", the delta A/B under "ab", and the cohort-exact
        native lane now reaches n=1024 (the delta-dissemination
        regression case).  Contract otherwise unchanged: every native
        row COHORT-EXACT and reproduced (storm/absorption pair
        included, n=256), every committed case covered, full agreement
        (scaled-reference knife-edges only in rescale_boundaries —
        with the committed expectation still met) — plus the A/B
        payoff gates: headline payload reduction >= the committed
        target at n=1024, every delta cell's p50 tick inside
        native_period(n), zero false positives in every cell."""
        cohort = json.loads((REPO / "COHORT_r20.json").read_text())
        assert cohort["schema"] == "gossipfs-cohort/v1"
        assert cohort["ok"] is True
        assert cohort["native_cohort_max_n"] >= 1024
        ab = cohort["ab"]
        assert ab["ok"] is True
        assert ab["headline_reduction"] >= ab["target_reduction"] >= 4.0
        assert ab["zero_false_positives"] is True
        assert ab["p50_within_budget"] is True
        art = cohort["matrix"]
        assert art["schema"] == "gossipfs-nativecampaign/v1"
        assert art["all_agree"] is True
        assert art["native_cohort_max_n"] >= 1024
        # the matrix covers every committed GOSSIP case; traffic-plane
        # cases (a "traffic" block instead of a "scenario") replay on
        # the durability harness, not the engine matrix — see
        # campaigns.run_traffic_case_doc and test_erasure.py — and
        # conformance schedule docs (gossipfs-conformance/v1) replay on
        # the conformance harness — see tools/conformance.py --replay
        # and test_conformance.py
        committed = {
            p.name for p in (REPO / "regressions").glob("*.json")
            if "traffic" not in (doc := json.loads(p.read_text()))
            and doc.get("schema") != "gossipfs-conformance/v1"
        }
        assert set(art["cases"]) == committed
        for name, row in art["cases"].items():
            nat = row["native"]
            assert nat["scaled_from"] is None, (name, "not cohort-exact")
            assert nat["n"] == row["n"]
            assert nat["reproduced"] and nat["agreement"]["match"], name
            assert nat["tick_ms"]["count"] > 0, (name, "no latency rows")
        pair = art["cases"]
        assert pair["outage_storm_n256.json"]["native"]["verdict"] == \
            "violated"
        assert pair["outage_absorbed_n256.json"]["native"]["verdict"] == \
            "pass"
        for b in art["rescale_boundaries"]:
            # scaled_reference_flips: the engine sides with the
            # committed cohort against a flipped scaled reference;
            # knee_at_boundary: a bisected knee straddles the threshold
            # on a jittered transport — the mismatch must stay confined
            # to the case's own expected invariants
            assert b["reason"] in ("scaled_reference_flips",
                                   "knee_at_boundary"), b
            if b["reason"] == "scaled_reference_flips":
                assert b["engine_verdict"] == b["committed_expect"], b
            else:
                case = art["cases"][b["case"]]
                assert set(b["mismatched"]) <= set(
                    case["expect"].get("invariants", [])), b

    def test_scale_case_semantics(self):
        """scale_case re-makes the family point at the new n: severity
        knobs preserved, fault nodes re-avoid the scaled victims, and
        the Lifeguard fraction rescales to keep its ABSOLUTE suspect
        count (1/64 at n=256 -> 1/16 at n=64)."""
        from gossipfs_tpu.bench.run import tracked_victims
        from gossipfs_tpu.scenarios import FaultScenario

        doc = campaigns.load_case(ABSORBED_CASE)
        scaled = campaigns.scale_case(doc, 64)
        assert scaled["config"]["n"] == 64
        assert scaled["scaled_from"] == 256
        sc = FaultScenario.from_json(json.dumps(scaled["scenario"]))
        assert sc.n == 64
        out = sc.outages[0]
        assert len(out.nodes) == len(
            FaultScenario.from_json(
                json.dumps(doc["scenario"])).outages[0].nodes)
        assert not (set(out.nodes)
                    & set(tracked_victims(64, doc["config"]["track"])))
        assert scaled["config"]["lh_frac"] == pytest.approx(
            doc["config"]["lh_frac"] * 4)
        with pytest.raises(ValueError, match="family"):
            campaigns.scale_case({"config": {"n": 8}}, 4)

    @pytest.mark.slow
    def test_knob_surface_discriminates(self):
        """The knob surface's three regimes at a small cohort: the raw
        t_fail=5 outage storms, the quiet baselines are clean, and the
        surface rows carry the absorption verdict machinery (the full
        N=256 map is the committed LOCALHEALTH_r14.json)."""
        out = campaigns.knob_surface(
            64, [6], [(4, 0.0625)], t_fail=2, t_suspect=3, crash_at=12)
        assert out["baselines"]["t5_quiet"]["false_positives"] == 0
        assert out["baselines"]["t5_outage"]["6"]["verdict"] == "violated"
        row = out["rows"][0]
        assert set(row) >= {"absorbed", "ttd_growth_outage",
                            "ttd_growth_quiet", "outage", "quiet"}

    @pytest.mark.slow
    def test_udp_engine_absorbs_committed_twin(self):
        """The committed n=64 absorption twin over REAL sockets: the
        Lifeguard stretch must absorb the rack on the asyncio engine
        too — verdict pass, agreeing with the tensor replay on all four
        invariants (the UDPCAMPAIGN_r14 evidence, re-derived)."""
        out = campaigns.run_case_engine(
            REPO / "regressions" / "outage_absorbed_udp_n64.json",
            engine="udp", period=0.1)
        assert out["reproduced"], out
        assert out["engine_verdict"] == out["tensor_verdict"] == "pass"
        assert "no_confirm_without_suspect" in out["agreement"]["compared"]

    @pytest.mark.slow
    def test_deploy_engine_campaign_runner(self):
        """The deploy lane end to end: scenario + suspicion pushed over
        the (backoff-hardened) control plane, kill -9 probes, per-node
        schema logs merged and fed through StreamMonitor.feed_jsonl —
        verdict agreement over the invariants a deploy stream can
        actually evaluate (fpr_storm needs ground-truth round_ticks and
        is excluded; a campaign FINISHING under an armed fault window
        is the graceful-degradation evidence)."""
        out = campaigns.run_case_engine(MILD_UDP_CASE, engine="deploy",
                                        scale_n=8, period=0.1)
        assert out["agreement"]["match"], out["agreement"]
        assert "fpr_storm" not in out["agreement"]["compared"]
        assert out["engine_row"]["observed_round_ticks"] == 0
        # the merged node logs really were schema streams with events
        from gossipfs_tpu.obs.recorder import load_stream

        _, events = load_stream(out["engine_row"]["trace"])
        assert events, "deploy logs merged into an empty stream"
