"""Property tests for the gossip merge — the join-semilattice obligations.

SURVEY.md §4: the reference's max-merge (MergeMemberList, reference:
slave/slave.go:414-440) is a join-semilattice — idempotent, commutative,
associative — which is exactly what makes anti-entropy gossip converge.
The tensorized merge must inherit those laws; here they appear as
invariances of one `gossip_round` under edge-list transformations:

  commutative+associative  <=>  permuting each receiver's in-edge list
                                cannot change anything
  idempotent               <=>  merging the same sender's view twice
                                (duplicate edge) cannot change anything
  self-merge neutral       <=>  receiving your own datagram is a no-op
  monotone                 <=>  a merge can only advance heartbeat counts

Run on a mid-run state (after churn) so tables disagree and the merge has
real work to do.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import gossip_round, run_rounds
from gossipfs_tpu.core.state import MEMBER, RoundEvents, init_state
from gossipfs_tpu.core.topology import random_in_edges

KEY = jax.random.PRNGKey(11)


def _mid_run_state(cfg, rounds=12, crash_rate=0.05):
    state = init_state(cfg)
    state, _, _ = run_rounds(state, cfg, rounds, KEY, crash_rate=crash_rate)
    return state


def _round(state, cfg, edges):
    return gossip_round(state, RoundEvents.none(cfg.n), edges, cfg)


@pytest.fixture(params=[
    "xla",
    pytest.param(  # interpreter-mode pallas: deep but slow; XLA param
        "pallas_interpret", marks=pytest.mark.slow),  # covers the algebra
])
def cfg(request):
    n = 128 if request.param == "pallas_interpret" else 48
    return SimConfig(n=n, topology="random", fanout=5, merge_kernel=request.param)


def _assert_states_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestMergeSemilattice:
    def test_edge_order_invariance(self, cfg):
        """Commutativity + associativity: each receiver folds its F sender
        views with max, so the order the datagrams arrive in is invisible."""
        state = _mid_run_state(cfg)
        edges = random_in_edges(KEY, cfg.n, cfg.fanout)
        perm = jax.random.permutation(KEY, cfg.fanout)
        base = _round(state, cfg, edges)
        got = _round(state, cfg, edges[:, perm])
        _assert_states_equal(base[0], got[0])
        _assert_states_equal(base[1], got[1])

    def test_duplicate_edge_idempotent(self, cfg):
        """Idempotence: merging the same membership list twice is merging
        it once (max(x, x) = x per entry)."""
        state = _mid_run_state(cfg)
        edges = random_in_edges(KEY, cfg.n, cfg.fanout)
        dup = jnp.concatenate([edges, edges[:, :1]], axis=1)
        base = _round(state, cfg, edges)
        got = _round(state, cfg, dup)
        _assert_states_equal(base[0], got[0])

    def test_self_edge_neutral(self, cfg):
        """Receiving your own datagram merges your own table into itself —
        a no-op (the reference never self-sends, but a duplicate network
        would be harmless; max-merge makes that a theorem, not luck)."""
        state = _mid_run_state(cfg)
        edges = random_in_edges(KEY, cfg.n, cfg.fanout)
        self_col = jnp.arange(cfg.n, dtype=jnp.int32)[:, None]
        base = _round(state, cfg, edges)
        got = _round(state, cfg, jnp.concatenate([edges, self_col], axis=1))
        _assert_states_equal(base[0], got[0])

    def test_merge_monotone(self, cfg):
        """Heartbeat counts never regress for entries that stay MEMBER at a
        live receiver (max-merge only raises; stamps only refresh)."""
        state = _mid_run_state(cfg)
        edges = random_in_edges(KEY, cfg.n, cfg.fanout)
        out, _, _, _ = _round(state, cfg, edges)
        stays = (
            state.alive[:, None]
            & out.alive[:, None]
            & (state.status == MEMBER)
            & (out.status == MEMBER)
        )
        before = jnp.where(stays, state.hb_true(), 0)
        after = jnp.where(stays, out.hb_true(), 0)
        assert bool(jnp.all(after >= before))
