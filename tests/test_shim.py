"""CLI REPL + event log: the reference's user surface (README.md:8-30)."""

import io

import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.shim.cli import dispatch
from gossipfs_tpu.utils.eventlog import EventLog


def run(sim, *lines):
    out = io.StringIO()
    for line in lines:
        assert dispatch(sim, line, out=out)
    return out.getvalue()


class TestCli:
    def test_membership_verbs(self):
        sim = CoSim(SimConfig(n=8))
        out = run(sim, "advance 2", "lsm 0", "IP")
        assert "round=2" in out
        assert "[0, 1, 2, 3, 4, 5, 6, 7]" in out

    def test_crash_then_lsm_shrinks(self):
        sim = CoSim(SimConfig(n=8))
        run(sim, "advance 2", "crash 5", "advance 10")
        out = run(sim, "lsm 0", "IP", "events", "grep Failure")
        assert "5" not in out.splitlines()[0].replace("15", "")
        assert "Failure Detected" in out or "failure" in out.lower()

    def test_put_get_roundtrip_via_files(self, tmp_path):
        src = tmp_path / "local.txt"
        src.write_bytes(b"cli payload")
        dst = tmp_path / "out.txt"
        sim = CoSim(SimConfig(n=8))
        out = run(
            sim,
            "advance 2",
            f"put {src} remote.txt",
            "ls remote.txt",
            "show_metadata",
            f"get remote.txt {dst}",
            "store 0",
        )
        assert "ok" in out
        assert dst.read_bytes() == b"cli payload"
        assert "remote.txt: v1" in out

    def test_delete_and_missing_file(self, tmp_path):
        dst = tmp_path / "x"
        sim = CoSim(SimConfig(n=8))
        out = run(sim, "advance 2", f"get nope.txt {dst}", "delete nope.txt")
        assert out.count("No File Found") == 2

    def test_unknown_command(self):
        sim = CoSim(SimConfig(n=8))
        assert "unknown command" in run(sim, "frobnicate")

    def test_quit(self):
        sim = CoSim(SimConfig(n=8))
        assert not dispatch(sim, "quit", out=io.StringIO())


class TestEventLog:
    def test_grep_and_file_mirror(self, tmp_path):
        path = tmp_path / "Machine.log"
        log = EventLog(path)
        log.write("Failure Detected of node 3 by 1", round=7, kind="failure_detected")
        log.write("put a.txt -> ok", round=8, kind="put")
        assert len(log.grep("Failure Detected")) == 1
        assert log.grep("nomatch") == []
        log.close()
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 2 and "failure_detected" in lines[0]


class TestConfirmPrompt:
    """The interactive write-conflict prompt (reference: server.go:144-153).

    A second put inside the 60-round conflict window must ask the human at
    the REPL, read the answer from the REPL's own input stream, and default
    to reject on timeout (server.go:172).
    """

    def _sim_with_conflict(self, tmp_path):
        sim = CoSim(SimConfig(n=8))
        src = tmp_path / "f.txt"
        src.write_bytes(b"v1")
        run(sim, "advance 2", f"put {src} wiki.txt")
        return sim, src

    def test_prompt_accepts_yes(self, tmp_path):
        sim, src = self._sim_with_conflict(tmp_path)
        out = io.StringIO()
        answers = io.StringIO("y\n")
        assert dispatch(sim, f"put {src} wiki.txt", out=out, in_stream=answers)
        text = out.getvalue()
        assert "Overwrite?" in text
        assert "ok" in text
        # the confirmed overwrite bumped the version
        assert sim.cluster.master.file_info("wiki.txt")[1] == 2

    def test_prompt_rejects_no_and_default(self, tmp_path):
        sim, src = self._sim_with_conflict(tmp_path)
        for answer in ("n\n", "\n", "nope\n"):
            out = io.StringIO()
            dispatch(sim, f"put {src} wiki.txt", out=out,
                     in_stream=io.StringIO(answer))
            assert "Write-Write conflicts!" in out.getvalue()
        assert sim.cluster.master.file_info("wiki.txt")[1] == 1

    def test_no_prompt_outside_conflict_window(self, tmp_path):
        sim, src = self._sim_with_conflict(tmp_path)
        run(sim, "advance 61")  # past WRITE_CONFLICT_WINDOW
        out = io.StringIO()
        # in_stream that would fail if read: the prompt must not fire
        dispatch(sim, f"put {src} wiki.txt", out=out, in_stream=None)
        assert "Overwrite?" not in out.getvalue()
        assert "ok" in out.getvalue()

    @pytest.mark.slow  # real-subprocess timeout wait; the in-process
    # prompt tests cover the behavior
    def test_prompt_timeout_rejects_subprocess(self, tmp_path):
        """pexpect-style: a real CLI process with a silent stdin hits the
        timeout path and rejects (the reference's 30 s default-deny)."""
        import subprocess
        import sys
        import threading
        import time

        src = tmp_path / "f.txt"
        src.write_bytes(b"v1")
        proc = subprocess.Popen(
            [sys.executable, "-m", "gossipfs_tpu.shim.cli", "--n", "8",
             "--confirm-timeout", "0.6"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=_cpu_env(),
        )
        lines: list[str] = []
        reader = threading.Thread(
            target=lambda: lines.extend(iter(proc.stdout.readline, "")),
            daemon=True,
        )
        reader.start()
        # exactly these three lines, then stdin stays SILENT: the prompt's
        # select must expire on its own (writing more before the timeout
        # message appears would be read as the prompt's answer)
        proc.stdin.write(f"advance 2\nput {src} wiki.txt\nput {src} wiki.txt\n")
        proc.stdin.flush()
        deadline = time.time() + 90
        while time.time() < deadline:
            if any("confirmation timed out" in ln for ln in lines):
                break
            time.sleep(0.2)
        proc.stdin.write("show_metadata\nquit\n")
        proc.stdin.flush()
        proc.stdin.close()
        proc.wait(timeout=60)
        reader.join(timeout=10)
        out = "".join(lines)
        assert "Overwrite?" in out
        assert "confirmation timed out" in out
        assert "Write-Write conflicts!" in out
        assert "wiki.txt: v1" in out  # the rejected put did not commit


def _cpu_env():
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


class TestPerNodeLogs:
    """Per-node log views + distributed grep (logger.go:28-44,
    server.go:55-72): each machine's entries are attributed to the node
    that would have written them to its own Machine.log, and grep can be
    scoped to one observer — the reference's grep-across-machines
    methodology."""

    def _detected_sim(self):
        # two crashes far apart on the ring: their first-detecting
        # observers differ, so per-node views genuinely diverge
        sim = CoSim(SimConfig(n=10))
        run(sim, "advance 2", "crash 6", "crash 2", "advance 12")
        detections = sim.log.grep("Failure Detected")
        assert len({e["node"] for e in detections}) >= 2, (
            "scenario must produce detections from distinct observers"
        )
        return sim, detections

    def test_node_scoped_grep_differs_per_observer(self):
        sim, detections = self._detected_sim()
        observers = {e["node"] for e in detections}
        # ring detection: specific neighbors fire, others never do
        non_observer = next(
            k for k in range(10)
            if k not in observers and k not in (6, 2)
        )
        some_observer = next(iter(observers))
        seen = sim.log.grep("Failure Detected", node=some_observer)
        unseen = sim.log.grep("Failure Detected", node=non_observer)
        assert seen and not unseen
        assert seen != sim.log.grep("Failure Detected")  # scoped < global
        # every scoped result is really that observer's own entry
        assert all(e["node"] == some_observer for e in seen)

    def test_node_view_is_that_machines_log(self):
        sim, detections = self._detected_sim()
        obs = detections[0]["node"]
        view = sim.log.node_view(obs)
        assert view and all(e.get("node") == obs for e in view)
        # the union of node views plus unattributed entries is the stream
        attributed = [e for e in sim.log.entries if "node" in e]
        assert sorted(
            (e["message"] for k in range(10) for e in sim.log.node_view(k))
        ) == sorted(e["message"] for e in attributed)

    def test_grep_rpc_node_filter(self):
        """The Grep RPC's node filter over the live gRPC surface."""
        from gossipfs_tpu.shim.client import ShimClient
        from gossipfs_tpu.shim.service import ShimServer

        sim = CoSim(SimConfig(n=10))
        server = ShimServer(sim).start()
        try:
            client = ShimClient(server.address)
            client.call("Advance", rounds=2)
            client.crash(6)
            client.call("Advance", rounds=12)
            all_lines = client.call("Grep", pattern="Failure Detected")["lines"]
            assert all_lines
            obs = int(all_lines[0]["node"])
            scoped = client.call(
                "Grep", pattern="Failure Detected", node=obs
            )["lines"]
            assert scoped and all(int(e["node"]) == obs for e in scoped)
            other = next(
                k for k in range(10)
                if k != 6 and k not in {int(e["node"]) for e in all_lines}
            )
            assert client.call(
                "Grep", pattern="Failure Detected", node=other
            )["lines"] == []
            client.close()
        finally:
            server.stop()

    def test_cli_grep_node_arg(self):
        sim, detections = self._detected_sim()
        obs = detections[0]["node"]
        out = io.StringIO()
        dispatch(sim, f"grep --node {obs} Failure Detected", out=out)
        text = out.getvalue()
        assert "Failure Detected" in text
        assert all(f"'node': {obs}" in ln for ln in text.splitlines() if ln)
        # a digit-final pattern is NOT reinterpreted as a node filter
        out2 = io.StringIO()
        dispatch(sim, "grep of node 6", out=out2)
        assert "Failure Detected of node 6" in out2.getvalue()
