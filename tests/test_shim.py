"""CLI REPL + event log: the reference's user surface (README.md:8-30)."""

import io

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.cosim import CoSim
from gossipfs_tpu.shim.cli import dispatch
from gossipfs_tpu.utils.eventlog import EventLog


def run(sim, *lines):
    out = io.StringIO()
    for line in lines:
        assert dispatch(sim, line, out=out)
    return out.getvalue()


class TestCli:
    def test_membership_verbs(self):
        sim = CoSim(SimConfig(n=8))
        out = run(sim, "advance 2", "lsm 0", "IP")
        assert "round=2" in out
        assert "[0, 1, 2, 3, 4, 5, 6, 7]" in out

    def test_crash_then_lsm_shrinks(self):
        sim = CoSim(SimConfig(n=8))
        run(sim, "advance 2", "crash 5", "advance 10")
        out = run(sim, "lsm 0", "IP", "events", "grep Failure")
        assert "5" not in out.splitlines()[0].replace("15", "")
        assert "Failure Detected" in out or "failure" in out.lower()

    def test_put_get_roundtrip_via_files(self, tmp_path):
        src = tmp_path / "local.txt"
        src.write_bytes(b"cli payload")
        dst = tmp_path / "out.txt"
        sim = CoSim(SimConfig(n=8))
        out = run(
            sim,
            "advance 2",
            f"put {src} remote.txt",
            "ls remote.txt",
            "show_metadata",
            f"get remote.txt {dst}",
            "store 0",
        )
        assert "ok" in out
        assert dst.read_bytes() == b"cli payload"
        assert "remote.txt: v1" in out

    def test_delete_and_missing_file(self, tmp_path):
        dst = tmp_path / "x"
        sim = CoSim(SimConfig(n=8))
        out = run(sim, "advance 2", f"get nope.txt {dst}", "delete nope.txt")
        assert out.count("No File Found") == 2

    def test_unknown_command(self):
        sim = CoSim(SimConfig(n=8))
        assert "unknown command" in run(sim, "frobnicate")

    def test_quit(self):
        sim = CoSim(SimConfig(n=8))
        assert not dispatch(sim, "quit", out=io.StringIO())


class TestEventLog:
    def test_grep_and_file_mirror(self, tmp_path):
        path = tmp_path / "Machine.log"
        log = EventLog(path)
        log.write("Failure Detected of node 3 by 1", round=7, kind="failure_detected")
        log.write("put a.txt -> ok", round=8, kind="put")
        assert len(log.grep("Failure Detected")) == 1
        assert log.grep("nomatch") == []
        log.close()
        lines = path.read_text().strip().splitlines()
        assert len(lines) == 2 and "failure_detected" in lines[0]
