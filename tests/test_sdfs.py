"""SDFS control-plane logic: placement, quorum, master, election, cluster ops.

Behavioral parity targets cited per test; two documented divergences from the
reference are bug *fixes* (placement can reach the last member; repair plans
cover every deficient file), each covered explicitly.
"""

import random

import pytest

from gossipfs_tpu.sdfs import election, placement
from gossipfs_tpu.sdfs.cluster import SDFSCluster
from gossipfs_tpu.sdfs.master import SDFSMaster
from gossipfs_tpu.sdfs.quorum import quorum
from gossipfs_tpu.sdfs.store import LocalStore
from gossipfs_tpu.sdfs.types import WRITE_CONFLICT_WINDOW


class TestPlacement:
    def test_four_distinct_replicas(self):
        rng = random.Random(0)
        nodes = placement.place(list(range(10)), rng)
        assert len(nodes) == 4 and len(set(nodes)) == 4

    def test_small_cluster_gets_everyone(self):
        rng = random.Random(0)
        assert sorted(placement.place([3, 7], rng)) == [3, 7]

    def test_last_member_is_reachable(self):
        # the reference's Intn(len-1) can never pick the last snapshot member
        # (master/master.go:129-150, latent bug); we place uniformly
        rng = random.Random(0)
        hit_last = any(
            9 in placement.place(list(range(10)), rng) for _ in range(200)
        )
        assert hit_last


class TestQuorum:
    def test_reference_integer_division(self):
        # floor((n+1)/2): 2-of-4 in the deployed code (slave.go:717-722),
        # not the report's claimed 3-of-4
        assert quorum(4) == 2
        assert quorum(3) == 2
        assert quorum(5) == 3
        assert quorum(1) == 1


class TestMaster:
    def test_put_allocates_once_and_bumps_version(self):
        m = SDFSMaster()
        m.update_member(list(range(8)))
        nodes1, v1 = m.handle_put("a.txt", now=0)
        nodes2, v2 = m.handle_put("a.txt", now=100)
        assert v1 == 1 and v2 == 2
        assert nodes1 == nodes2  # placement happens once per file lifetime

    def test_write_conflict_window(self):
        # 60-round write-write window (master.go:214-229)
        m = SDFSMaster()
        m.update_member(list(range(8)))
        m.handle_put("a.txt", now=10)
        assert m.updated_recently("a.txt", now=10 + WRITE_CONFLICT_WINDOW - 1)
        assert not m.updated_recently("a.txt", now=10 + WRITE_CONFLICT_WINDOW)

    def test_file_info_and_delete(self):
        m = SDFSMaster()
        m.update_member(list(range(8)))
        assert m.file_info("nope") == ([], -1)  # Get_file_info absent case
        nodes, _ = m.handle_put("a.txt", now=0)
        assert m.file_info("a.txt") == (nodes, 1)
        assert sorted(m.delete("a.txt")) == sorted(nodes)
        assert m.file_info("a.txt") == ([], -1)

    def test_repair_plans_every_deficient_file(self):
        # the reference resets its plan map inside the per-file loop so only
        # the last deficient file survives (master.go:118); fixed here
        m = SDFSMaster(seed=1)
        m.update_member(list(range(10)))
        for name in ("a", "b", "c"):
            m.handle_put(name, now=0)
        # kill two nodes that appear in replica sets
        victims = set(m.files["a"].node_list[:1]) | set(m.files["b"].node_list[:1])
        live = [x for x in range(10) if x not in victims]
        plans = m.plan_repairs(live)
        planned = {p.file for p in plans}
        deficient = {n for n in ("a", "b", "c") if victims & set(m.files[n].node_list)}
        assert deficient <= planned  # every deficient file got a plan
        for plan in plans:
            assert len(plan.survivors) + len(plan.new_nodes) == 4
            assert set(plan.survivors) | set(plan.new_nodes) <= set(live)
            assert plan.source in plan.survivors
            # metadata commits only after the copies succeed
            m.commit_repair(plan.file, list(plan.survivors) + list(plan.new_nodes))
            info = m.files[plan.file]
            assert len(info.node_list) == 4 and set(info.node_list) <= set(live)

    def test_unrecoverable_file_left_alone(self):
        m = SDFSMaster(seed=1)
        m.update_member(list(range(5)))
        m.handle_put("a", now=0)
        dead = set(m.files["a"].node_list)
        live = [x for x in range(5) if x not in dead]
        plans = m.plan_repairs(live)
        assert plans == []  # every replica lost -> nothing to copy from


class TestElection:
    def test_successor_is_lowest_member(self):
        # fixed-candidate majority voting, lowest member wins (slave.go:930-984)
        assert election.successor([5, 2, 9]) == 2
        assert election.successor([]) is None

    def test_majority_tally(self):
        assert election.tally({1, 2, 3}, 5)
        assert not election.tally({1, 2}, 5)

    def test_rebuild_keeps_top4_by_version(self):
        # rebuild_file_meta: holders sorted by version, top 4 kept, version =
        # max seen (slave.go:986-1043)
        registries = {
            1: {"f": 3},
            2: {"f": 5},
            3: {"f": 5},
            4: {"f": 4},
            5: {"f": 1},
            6: {"g": 2},
        }
        meta = election.rebuild_metadata(registries, now=7)
        assert meta["f"].version == 5
        assert len(meta["f"].node_list) == 4
        assert 5 not in meta["f"].node_list  # lowest version loses the cut
        assert meta["g"].node_list == [6]


class TestLocalStore:
    def test_roundtrip_and_versions(self, tmp_path):
        s = LocalStore(root=tmp_path)
        s.put("f.txt", b"hello", version=2)
        assert s.get("f.txt") == b"hello"
        assert s.version("f.txt") == 2
        assert s.version("missing") == -1
        assert s.listing() == {"f.txt": 2}
        assert s.delete("f.txt") and not s.delete("f.txt")
        assert s.get("f.txt") is None


class TestCluster:
    def test_put_get_delete_roundtrip(self):
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"data", now=0)
        assert c.get("a.txt") == b"data"
        assert len(c.ls("a.txt")) == 4
        assert c.delete("a.txt")
        assert c.get("a.txt") is None

    def test_write_conflict_requires_confirmation(self):
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"v1", now=0)
        # conflicting put inside the 60-round window: default = rejected
        assert not c.put("a.txt", b"v2", now=30)
        # explicit confirmation overrides (Ask_for_confirmation, server.go:155-177)
        assert c.put("a.txt", b"v2", now=30, confirm=lambda: True)
        assert c.get("a.txt") == b"v2"

    def test_quorum_survives_replica_deaths(self):
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"data", now=0)
        replicas = c.ls("a.txt")
        c.update_membership([x for x in range(8) if x not in replicas[:2]])
        # 2 of 4 replicas dead == exactly quorum alive -> reads still work
        assert c.get("a.txt") == b"data"

    def test_fail_recover_restores_replication(self):
        c = SDFSCluster(n=10, seed=0)
        assert c.put("a.txt", b"data", now=0)
        victim = c.ls("a.txt")[0]
        live = [x for x in range(10) if x != victim]
        c.update_membership(live)
        plans = c.fail_recover()
        assert len(plans) == 1
        new_replicas = c.ls("a.txt")
        assert len(new_replicas) == 4 and victim not in new_replicas
        for node in new_replicas:
            assert c.stores[node].get("a.txt") == b"data"

    def test_fail_recover_commits_only_successful_copies(self):
        # a planned copy target that is dead-but-undetected must not become a
        # phantom replica: metadata keeps the file under-replicated so the
        # next recovery pass retries (divergence from master.go:118 noted in
        # SDFSMaster.plan_repairs)
        c = SDFSCluster(n=6, seed=0)
        assert c.put("a.txt", b"data", now=0)
        replicas = c.ls("a.txt")
        victim, survivors = replicas[0], replicas[1:]
        live = [x for x in range(6) if x != victim]
        # every placement candidate (live non-replica) refuses connections
        reach = [x for x in live if x in replicas]
        c.update_membership(live, reachable=reach)
        assert c.fail_recover() == []  # no reachable candidates -> no repair
        # no phantom replicas: nothing beyond the original set is listed,
        # and nothing new holds bytes
        assert set(c.ls("a.txt")) <= set(replicas)
        assert all(
            c.stores[x].get("a.txt") is None for x in live if x not in replicas
        )
        # targets come back up -> repair retries and completes
        c.update_membership(live, reachable=live)
        c.fail_recover()
        healed = c.ls("a.txt")
        assert len(healed) == 4
        for node in healed:
            assert c.stores[node].get("a.txt") == b"data"

    def test_fail_recover_falls_through_empty_source(self):
        # a survivor listed in node_list may hold no bytes (quorum-acked put
        # while it was unreachable, then rejoined); recovery must fall
        # through to a survivor that actually has the data
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"data", now=0)
        replicas = c.ls("a.txt")
        victim = replicas[-1]
        c.stores[replicas[0]].delete("a.txt")  # first survivor is empty
        c.update_membership([x for x in range(8) if x != victim])
        c.fail_recover()
        healed = c.ls("a.txt")
        assert len(healed) == 4 and victim not in healed
        assert c.get("a.txt") == b"data"  # read-repair also refills the gap

    def test_fail_recover_skips_stale_version_source(self):
        # a survivor can hold bytes one version behind (rejoined after a
        # quorum-acked put it missed): it must not seed copies, else old
        # bytes get re-stamped as the current version
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"v1", now=0)
        replicas = c.ls("a.txt")
        straggler, victim = replicas[0], replicas[-1]
        # straggler misses the v2 write (unreachable during the put)
        c.update_membership(list(range(8)), reachable=[x for x in range(8) if x != straggler])
        assert c.put("a.txt", b"v2", now=100)
        assert c.stores[straggler].version("a.txt") == 1  # stale bytes kept
        # victim dies; straggler (back up) is the plan's first source
        c.update_membership([x for x in range(8) if x != victim])
        executed = c.fail_recover()
        # the reported source is the survivor that actually served the bytes
        for plan in executed:
            if plan.file == "a.txt":
                assert plan.source != straggler
                assert c.stores[plan.source].version("a.txt") == 2
        for node in c.ls("a.txt"):
            blob = c.stores[node].get("a.txt")
            if c.stores[node].version("a.txt") == 2 and blob is not None:
                assert blob == b"v2"  # nobody serves v1 bytes stamped v2

    def test_plan_repairs_is_pure_wrt_members(self):
        # a planning call with a stale/shrunken snapshot must not redirect
        # subsequent placement (the shim's GetUpdateMeta is planning-only)
        m = SDFSMaster(seed=0)
        m.update_member(list(range(12)))
        m.handle_put("a", now=0)
        m.plan_repairs([0, 1], reachable={0, 1})
        assert m.members == list(range(12))
        m.handle_put("b", now=0)
        replicas, _ = m.file_info("b")
        assert len(replicas) == 4  # placed over all 12, not the [0,1] snapshot
        # determinism: a twin master that never planned places identically
        # (planning must not advance the shared placement RNG)
        twin = SDFSMaster(seed=0)
        twin.update_member(list(range(12)))
        twin.handle_put("a", now=0)
        twin.handle_put("b", now=0)
        assert twin.files["b"].node_list == list(replicas)

    def test_fail_recover_returns_only_executed_plans(self):
        # skipped plans (no reachable copy targets) must not be reported as
        # repairs — the event log / bench would otherwise claim copies that
        # never happened
        c = SDFSCluster(n=6, seed=0)
        assert c.put("a.txt", b"data", now=0)
        replicas = c.ls("a.txt")
        victim = replicas[0]
        live = [x for x in range(6) if x != victim]
        # all placement candidates refuse connections -> plan exists, 0 copies
        c.update_membership(live, reachable=[x for x in live if x in replicas])
        assert c.fail_recover() == []
        # candidates back up -> the retry executes and is reported
        c.update_membership(live, reachable=live)
        executed = c.fail_recover()
        assert len(executed) == 1
        assert all(
            c.stores[n].get("a.txt") == b"data" for n in executed[0].new_nodes
        )

    def test_plan_repairs_requires_reachable_source(self):
        m = SDFSMaster(seed=0)
        m.update_member(list(range(8)))
        m.handle_put("a", now=0)
        nodes = m.files["a"].node_list
        # all surviving replicas unreachable: no plan, metadata untouched
        live = list(range(8))
        plans = m.plan_repairs(
            [x for x in live if x != nodes[0]],
            reachable={x for x in live if x not in nodes},
        )
        assert plans == []
        assert m.files["a"].node_list == nodes

    def test_minority_cannot_elect_master(self):
        # majority is counted against the member list (slave.go:968-984): 3
        # reachable nodes out of a 9-member view must not rebuild metadata
        c = SDFSCluster(n=10, seed=0)
        old = c.master_node
        live = [x for x in range(10) if x != old]
        c.update_membership(live, reachable=live[:3])
        assert c.master_node == old  # election stalled
        c.update_membership(live, reachable=live)
        assert c.master_node == min(live)

    def test_master_death_triggers_election_and_rebuild(self):
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"data", now=0)
        old_master = c.master_node
        live = [x for x in range(8) if x != old_master]
        c.update_membership(live)
        assert c.master_node == min(live)  # lowest member wins
        # metadata survived via rebuild from local registries
        assert c.get("a.txt") == b"data"
        assert len(c.ls("a.txt")) >= 1

    def test_read_repair_updates_stale_replica(self):
        c = SDFSCluster(n=8, seed=0)
        assert c.put("a.txt", b"v1", now=0)
        assert c.put("a.txt", b"v2", now=100)
        stale = c.ls("a.txt")[0]
        c.stores[stale].put("a.txt", b"v1", version=1)  # simulate missed write
        assert c.get("a.txt") == b"v2"
        # the stale replica self-repaired (slave.go:799-813)
        assert c.stores[stale].get("a.txt") == b"v2"
        assert c.stores[stale].version("a.txt") == 2


class TestBatchRepairPlanner:
    """The vectorized array-diff planner (config-5 scale) makes the same
    DECISIONS as the per-file loop: same deficient files, same sources,
    same copy counts, valid candidates — only the uniform draws differ."""

    def _master_with_files(self, n_files, members, seed=5):
        from gossipfs_tpu.sdfs.master import SDFSMaster

        m = SDFSMaster(seed=seed)
        m.update_member(members)
        for f in range(n_files):
            m.handle_put(f"f{f}.txt", now=0)
        return m

    def test_batch_matches_loop_decisions(self):
        import dataclasses

        from gossipfs_tpu.sdfs import master as master_mod

        members = list(range(64))
        m = self._master_with_files(100, members)  # >= threshold -> batch
        # clone metadata into a second, loop-path master
        m2 = master_mod.SDFSMaster(seed=5)
        m2.update_member(members)
        m2.files = {
            k: dataclasses.replace(v, node_list=list(v.node_list))
            for k, v in m.files.items()
        }
        # kill a third of the membership
        live = [x for x in members if x % 3 != 0]
        reach = set(live)
        batch_plans = {p.file: p for p in m.plan_repairs(live, reachable=reach)}
        old_thresh = master_mod.BATCH_PLAN_THRESHOLD
        master_mod.BATCH_PLAN_THRESHOLD = 10**9  # force the loop path
        try:
            loop_plans = {p.file: p for p in m2.plan_repairs(live, reachable=reach)}
        finally:
            master_mod.BATCH_PLAN_THRESHOLD = old_thresh
        assert set(batch_plans) == set(loop_plans)
        for name, lp in loop_plans.items():
            bp = batch_plans[name]
            assert bp.source == lp.source
            assert bp.version == lp.version
            assert set(bp.survivors) == set(lp.survivors)
            assert len(bp.new_nodes) == len(lp.new_nodes)
            # picks are valid: reachable, distinct, not already replicas
            assert len(set(bp.new_nodes)) == len(bp.new_nodes)
            for node in bp.new_nodes:
                assert node in reach
                assert node not in lp.survivors

    def test_batch_no_reachable_source_skips(self):
        m = self._master_with_files(80, list(range(32)))
        name = next(iter(m.files))
        replicas = m.files[name].node_list
        live = [x for x in range(32) if x != replicas[0]]
        # reachable excludes every remaining replica of `name`
        reach = set(live) - set(replicas)
        plans = m.plan_repairs(live, reachable=reach)
        assert name not in {p.file for p in plans}

    def test_batch_unrecoverable_file_skipped(self):
        m = self._master_with_files(70, list(range(16)))
        name = next(iter(m.files))
        dead = set(m.files[name].node_list)
        live = [x for x in range(16) if x not in dead]
        plans = m.plan_repairs(live)
        assert name not in {p.file for p in plans}
