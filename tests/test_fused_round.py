"""Fused-tick round == separate-pass round, bit for bit.

The barrier-fused round (core/rounds._round_core_fused) recomputes the
heartbeat tick around the merge kernel for crash-only scans on the XLA
merge paths.  It must be indistinguishable from the separate-pass round
the golden-parity suite pins to the reference protocol: same states, same
detection/convergence rounds, same per-round metrics.

The interpret-mode tests cross-check the stripe/arc production kernels
(whose configs route to the separate-pass round, see _fused_ok) against
the barrier-fused XLA round — two maximally different implementations of
the same round must agree exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state


def _run(cfg: SimConfig, rounds: int, crash_rate: float, seed: int = 0):
    key = jax.random.PRNGKey(seed)
    state = init_state(cfg)
    return run_rounds(state, cfg, rounds, key, crash_rate=crash_rate)


def _assert_same(a, b):
    fa, ca, pa = a
    fb, cb, pb = b
    assert jnp.array_equal(fa.hb, fb.hb)
    assert jnp.array_equal(fa.age, fb.age)
    assert jnp.array_equal(fa.status, fb.status)
    assert jnp.array_equal(fa.alive, fb.alive)
    assert jnp.array_equal(fa.hb_base, fb.hb_base)
    assert jnp.array_equal(ca.first_detect, cb.first_detect)
    assert jnp.array_equal(ca.first_observer, cb.first_observer)
    assert jnp.array_equal(ca.converged, cb.converged)
    assert jnp.array_equal(pa.true_detections, pb.true_detections)
    assert jnp.array_equal(pa.false_positives, pb.false_positives)
    assert jnp.array_equal(pa.n_alive, pb.n_alive)


@pytest.mark.parametrize(
    "topology,view_dtype,hb_dtype",
    [
        ("random", "int16", "int32"),
        ("random", "int16", "int16"),
        ("random", "int8", "int8"),
        ("random_arc", "int8", "int8"),
        ("random_arc", "int16", "int32"),
    ],
)
def test_fused_matches_unfused(topology, view_dtype, hb_dtype):
    base = SimConfig(
        n=128,
        topology=topology,
        fanout=5,
        remove_broadcast=False,
        fresh_cooldown=True,
        view_dtype=view_dtype,
        hb_dtype=hb_dtype,
    )
    fused = _run(dataclasses.replace(base, fused_tick="auto"), 40, 0.02)
    plain = _run(dataclasses.replace(base, fused_tick="off"), 40, 0.02)
    _assert_same(fused, plain)


def test_fused_small_group_refresh_parity():
    """Fused rounds handle the min_group refresh path identically (most of
    the cluster dead, survivors only refresh timestamps)."""
    base = SimConfig(
        n=128,
        topology="random",
        fanout=4,
        remove_broadcast=False,
        fresh_cooldown=True,
    )
    mask = jnp.arange(128) < 3  # below min_group=4 from the start
    key = jax.random.PRNGKey(3)
    out = {}
    for mode in ("auto", "off"):
        cfg = dataclasses.replace(base, fused_tick=mode)
        out[mode] = run_rounds(init_state(cfg, mask), cfg, 20, key, crash_rate=0.0)
    _assert_same(out["auto"], out["off"])


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
def test_stripe_kernel_round_matches_xla_fused():
    """Unfused stripe-kernel round (interpret) == barrier-fused XLA round."""
    base = SimConfig(
        n=4096,
        topology="random",
        fanout=6,
        remove_broadcast=False,
        fresh_cooldown=True,
        view_dtype="int8",
        hb_dtype="int8",
        merge_block_c=4096,
    )
    key = jax.random.PRNGKey(5)
    out = {}
    for kernel in ("xla", "pallas_stripe_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        out[kernel] = run_rounds(init_state(cfg), cfg, 8, key, crash_rate=0.01)
    _assert_same(out["pallas_stripe_interpret"], out["xla"])


@pytest.mark.slow  # N=4096 interpreter-mode kernel run
def test_arc_kernel_round_matches_xla_fused():
    """Unfused arc-kernel round (interpret) == barrier-fused XLA round."""
    base = SimConfig(
        n=4096,
        topology="random_arc",
        fanout=6,
        remove_broadcast=False,
        fresh_cooldown=True,
        view_dtype="int8",
        hb_dtype="int8",
        merge_block_c=4096,
    )
    key = jax.random.PRNGKey(7)
    out = {}
    for kernel in ("xla", "pallas_stripe_interpret"):
        cfg = dataclasses.replace(base, merge_kernel=kernel)
        out[kernel] = run_rounds(init_state(cfg), cfg, 8, key, crash_rate=0.01)
    _assert_same(out["pallas_stripe_interpret"], out["xla"])


def test_crash_only_events_static_is_bit_identical():
    """``crash_only_events=True`` with a crash-only schedule must reproduce
    the default event path exactly — it only switches the compiled round to
    the lean (no leave/join rewrites, stats-capable) form."""
    import numpy as np

    cfg = SimConfig(
        n=128, topology="random", fanout=5,
        remove_broadcast=False, fresh_cooldown=True,
        view_dtype="int8", hb_dtype="int8",
    )
    n, rounds = cfg.n, 30
    crash = np.zeros((rounds, n), dtype=bool)
    crash[8, [3, 77]] = True
    zeros = jnp.zeros((rounds, n), dtype=bool)
    from gossipfs_tpu.core.state import RoundEvents

    events = RoundEvents(crash=jnp.asarray(crash), leave=zeros, join=zeros)
    key = jax.random.PRNGKey(11)
    out = {}
    for lean in (False, True):
        out[lean] = run_rounds(
            init_state(cfg), cfg, rounds, key, events=events,
            crash_rate=0.01, crash_only_events=lean,
        )
    _assert_same(out[True], out[False])
