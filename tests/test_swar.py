"""Exhaustive per-byte verification of the SWAR word primitives.

Every compare/select/arithmetic primitive in ops/swar.py is checked over
ALL 256 x 256 int8 operand pairs (packed 4 per word) against the plain
numpy int8 semantics the lanes formulation uses — the ground truth the
SWAR elementwise path (config.elementwise="swar") must reproduce
bit-for-bit.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from gossipfs_tpu.ops import swar


def _all_pairs():
    """Every (x, y) int8 byte pair, packed 4 pairs per word."""
    b = np.arange(-128, 128, dtype=np.int8)
    x = np.repeat(b, 256)           # 65,536 bytes
    y = np.tile(b, 256)
    return x, y


X8, Y8 = _all_pairs()
XW = swar.pack(jnp.asarray(X8).reshape(1, -1))
YW = swar.pack(jnp.asarray(Y8).reshape(1, -1))


def _bytes(w) -> np.ndarray:
    return np.asarray(swar.unpack(w)).reshape(-1)


def _mask_bytes(h) -> np.ndarray:
    """hmask word -> per-byte bool."""
    return (_bytes(h).view(np.uint8) & 0x80) != 0


@pytest.mark.parametrize("name,fn,ref", [
    ("eq", swar.eq, lambda x, y: x == y),
    ("ne", swar.ne, lambda x, y: x != y),
    ("ges", swar.ges, lambda x, y: x >= y),
    ("gts", swar.gts, lambda x, y: x > y),
    ("les", swar.les, lambda x, y: x <= y),
])
def test_compares_exhaustive(name, fn, ref):
    got = _mask_bytes(fn(XW, YW))
    np.testing.assert_array_equal(got, ref(X8, Y8), err_msg=name)


@pytest.mark.parametrize("name,fn,ref", [
    ("add", swar.add, lambda x, y: (x + y).astype(np.int8)),
    ("sub", swar.sub, lambda x, y: (x - y).astype(np.int8)),
    ("maxs", swar.maxs, np.maximum),
    ("mins", swar.mins, np.minimum),
])
def test_arith_exhaustive(name, fn, ref):
    got = _bytes(fn(XW, YW))
    with np.errstate(over="ignore"):
        want = ref(X8.astype(np.int16), Y8.astype(np.int16)).astype(np.int8) \
            if name in ("add", "sub") else ref(X8, Y8)
    np.testing.assert_array_equal(got, want, err_msg=name)


def test_select_exhaustive():
    m = swar.to_bytes(swar.ges(XW, YW))
    got = _bytes(swar.sel(m, XW, YW))
    np.testing.assert_array_equal(got, np.where(X8 >= Y8, X8, Y8))


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.integers(-128, 128, size=(3, 5, 16), dtype=np.int8)
    w = swar.pack(jnp.asarray(x))
    assert w.shape == (3, 5, 4) and w.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(swar.unpack(w)), x)


def test_word_constants():
    assert swar.word(0x80) == swar.H
    assert swar.word(0xFF) == -1
    assert swar.word(3) == 0x03030303
    # the int32 range is respected (no Python-int overflow leaking in)
    assert -(1 << 31) <= swar.word(0xFE) < (1 << 31)


def test_run_rounds_swar_matches_lanes_xla_path():
    """Fast lane: the XLA swar epilogues (_tick_swar /
    _membership_update_swar, core/rounds.py) reproduce the lanes scan
    bit-for-bit over a churn + rejoin horizon — matrix events included,
    so the introducer pushes, rebase-shift renormalization, and the
    remove-broadcast-free cooldown chain all cross the packed-word ops."""
    import dataclasses

    import jax

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state

    base = SimConfig(n=256, topology="random", fanout=6,
                     remove_broadcast=False, fresh_cooldown=True,
                     t_cooldown=12, view_dtype="int8", hb_dtype="int8")
    key = jax.random.PRNGKey(7)
    out = {}
    for ew in ("lanes", "swar"):
        cfg = dataclasses.replace(base, elementwise=ew)
        out[ew] = run_rounds(init_state(cfg), cfg, 12, key,
                             crash_rate=0.02, rejoin_rate=0.01)
    (fl, cl, pl), (fs, cs, ps) = out["lanes"], out["swar"]
    for name in ("hb", "age", "status", "alive", "hb_base"):
        assert jnp.array_equal(getattr(fl, name), getattr(fs, name)), name
    assert jnp.array_equal(cl.first_detect, cs.first_detect)
    assert jnp.array_equal(cl.converged, cs.converged)
    assert jnp.array_equal(pl.true_detections, ps.true_detections)
    assert jnp.array_equal(pl.false_positives, ps.false_positives)


def test_run_rounds_swar_matches_lanes_remove_broadcast():
    """The reference-faithful fault model (remove_broadcast on): the swar
    tick's cross-receiver OR-reduce of the packed fail masks must match
    the lanes formulation's jnp.any over the bool fail matrix."""
    import dataclasses

    import jax

    from gossipfs_tpu.config import SimConfig
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.core.state import init_state

    base = SimConfig(n=128, topology="random", fanout=5,
                     view_dtype="int8", hb_dtype="int8")
    key = jax.random.PRNGKey(3)
    out = {}
    for ew in ("lanes", "swar"):
        cfg = dataclasses.replace(base, elementwise=ew)
        out[ew] = run_rounds(init_state(cfg), cfg, 10, key,
                             crash_rate=0.03, rejoin_rate=0.02)
    (fl, _, pl), (fs, _, ps) = out["lanes"], out["swar"]
    for name in ("hb", "age", "status", "alive"):
        assert jnp.array_equal(getattr(fl, name), getattr(fs, name)), name
    assert jnp.array_equal(pl.true_detections, ps.true_detections)
    assert jnp.array_equal(pl.false_positives, ps.false_positives)


def test_bool_mask_uniform_words():
    m = swar.bool_mask(jnp.asarray([True, False]))
    np.testing.assert_array_equal(np.asarray(m), [-1, 0])
    # serves as a full-byte select mask directly
    a = swar.pack(jnp.arange(8, dtype=jnp.int8).reshape(1, 8))
    got = swar.sel(m.reshape(1, 2), a, jnp.zeros_like(a))
    np.testing.assert_array_equal(
        np.asarray(swar.unpack(got)).reshape(-1),
        [0, 1, 2, 3, 0, 0, 0, 0],
    )
