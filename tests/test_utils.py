"""Profiling + distributed helpers (single-process behaviors)."""

from __future__ import annotations

import jax

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.parallel import distributed
from gossipfs_tpu.utils.profiling import time_rounds, trace


def test_time_rounds_reports_positive_rates():
    cfg = SimConfig(n=64, topology="random", fanout=3, remove_broadcast=False,
                    fresh_cooldown=True)
    report = time_rounds(
        init_state(cfg), cfg, jax.random.PRNGKey(0), short=2, long=6
    )
    assert report["seconds_per_round"] > 0
    assert report["rounds_per_sec"] > 0
    assert report["dispatch_overhead_s"] >= 0


def test_trace_writes_profile(tmp_path):
    cfg = SimConfig(n=16)
    with trace(tmp_path):
        jax.block_until_ready(init_state(cfg).hb)
    assert any(tmp_path.rglob("*"))  # profiler emitted something


def test_initialize_noop_single_process(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed.initialize() is False


def test_global_mesh_covers_all_devices():
    mesh = distributed.global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("shard",)
