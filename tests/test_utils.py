"""Profiling + distributed helpers (single-process behaviors)."""

from __future__ import annotations

import gzip
import json

import jax
import pytest

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.parallel import distributed
from gossipfs_tpu.utils.profiling import op_breakdown, time_rounds, trace


def test_time_rounds_reports_positive_rates():
    cfg = SimConfig(n=64, topology="random", fanout=3, remove_broadcast=False,
                    fresh_cooldown=True)
    report = time_rounds(
        init_state(cfg), cfg, jax.random.PRNGKey(0), short=2, long=6
    )
    assert report["seconds_per_round"] > 0
    assert report["rounds_per_sec"] > 0
    assert report["dispatch_overhead_s"] >= 0


@pytest.mark.slow  # the profiler's start/stop + TF-event flush is ~30 s on
# this 1-core box regardless of workload size; the fast lane covers the
# analysis path on a synthetic capture below
def test_trace_writes_profile(tmp_path):
    cfg = SimConfig(n=16)
    with trace(tmp_path):
        jax.block_until_ready(init_state(cfg).hb)
    assert any(tmp_path.rglob("*"))  # profiler emitted something


def test_op_breakdown_parses_synthetic_capture(tmp_path):
    """Fast-lane coverage of the trace ANALYSIS path (op_breakdown):
    a hand-built perfetto capture in the profiler's on-disk layout must
    aggregate device-op durations by name.  The slow lane runs the real
    jax.profiler end-to-end (test_trace_writes_profile)."""
    d = tmp_path / "plugins" / "profile" / "2026_07_31"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1500,
         "name": "fusion.1"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 2000, "dur": 500,
         "name": "fusion.1"},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 0, "dur": 300,
         "name": "copy.2"},
    ]
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    rows = op_breakdown(tmp_path)
    by_name = {r["name"]: r for r in rows}
    assert by_name["fusion.1"]["count"] == 2
    assert by_name["fusion.1"]["total_ms"] == 2.0
    assert rows[0]["name"] == "fusion.1"  # sorted by total


def test_initialize_noop_single_process(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed.initialize() is False


def test_global_mesh_covers_all_devices():
    mesh = distributed.global_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("shard",)
