"""Checkpoint/resume: a restored run continues the exact trajectory."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint


def test_resume_matches_uninterrupted(tmp_path):
    cfg = SimConfig(
        n=64, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(11)
    state = init_state(cfg)

    # uninterrupted 20 rounds
    full, _, _ = run_rounds(state, cfg, 20, key, crash_rate=0.05, rejoin_rate=0.02)

    # 10 rounds -> checkpoint -> restore -> 10 more
    half, _, _ = run_rounds(state, cfg, 10, key, crash_rate=0.05, rejoin_rate=0.02)
    save_checkpoint(tmp_path / "ckpt", half, key)
    restored_state, restored_key = restore_checkpoint(tmp_path / "ckpt", cfg)
    assert int(restored_state.round) == 10
    resumed, _, _ = run_rounds(
        restored_state, cfg, 10, restored_key, crash_rate=0.05, rejoin_rate=0.02
    )

    assert jnp.array_equal(full.hb, resumed.hb)
    assert jnp.array_equal(full.age, resumed.age)
    assert jnp.array_equal(full.status, resumed.status)
    assert jnp.array_equal(full.alive, resumed.alive)
    assert int(full.round) == int(resumed.round) == 20


def test_restore_onto_mesh_resumes_sharded_run(tmp_path):
    from gossipfs_tpu.parallel.mesh import make_mesh, shard_state, state_shardings

    cfg = SimConfig(
        n=32, topology="random", fanout=3, remove_broadcast=False,
        fresh_cooldown=True,
    )
    mesh = make_mesh(8)
    key = jax.random.PRNGKey(0)
    state = shard_state(init_state(cfg), mesh)
    state, _, _ = run_rounds(state, cfg, 5, key, crash_rate=0.05)
    save_checkpoint(tmp_path / "ckpt", state, key)
    restored, rkey = restore_checkpoint(tmp_path / "ckpt", cfg, mesh=mesh)
    # arrays come back already on their run shardings...
    assert restored.hb.sharding == state_shardings(mesh).hb
    assert jnp.array_equal(restored.hb, state.hb)
    # ...so the resumed sharded scan runs directly (this failed before the
    # mesh-aware restore: the key came back committed to one device)
    cont, _, _ = run_rounds(restored, cfg, 3, rkey, crash_rate=0.05)
    assert int(cont.round) == 8


def test_legacy_int32_age_checkpoint_restores_clamped(tmp_path):
    """Pre-int8-lane checkpoints stored age as unclamped int32.

    Orbax silently casts to the abstract target's dtype on restore, so a
    naive int8 target would wrap a legacy age of 200 to -56 (evading the
    ``age > t_fail`` detector for ~60 extra rounds).  restore_checkpoint
    must instead clamp legacy ages into the int8 saturation regime.
    """
    import orbax.checkpoint as ocp

    from gossipfs_tpu.config import AGE_CLAMP

    cfg = SimConfig(
        n=16, topology="random", fanout=3, remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(3)
    state = init_state(cfg)
    legacy = state._asdict()
    legacy["age"] = jnp.full((cfg.n, cfg.n), 200, jnp.int32)
    path = (tmp_path / "legacy").resolve()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"state": legacy, "key": key}, force=True)

    restored, _ = restore_checkpoint(path, cfg)
    assert restored.age.dtype == jnp.int8
    assert jnp.all(restored.age == AGE_CLAMP)
