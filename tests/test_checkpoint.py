"""Checkpoint/resume: a restored run continues the exact trajectory."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossipfs_tpu.config import SimConfig
from gossipfs_tpu.core.rounds import run_rounds
from gossipfs_tpu.core.state import init_state
from gossipfs_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint


def test_resume_matches_uninterrupted(tmp_path):
    cfg = SimConfig(
        n=64, topology="random", fanout=4, remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(11)
    state = init_state(cfg)

    # uninterrupted 20 rounds
    full, _, _ = run_rounds(state, cfg, 20, key, crash_rate=0.05, rejoin_rate=0.02)

    # 10 rounds -> checkpoint -> restore -> 10 more
    half, _, _ = run_rounds(state, cfg, 10, key, crash_rate=0.05, rejoin_rate=0.02)
    save_checkpoint(tmp_path / "ckpt", half, key)
    restored_state, restored_key = restore_checkpoint(tmp_path / "ckpt", cfg)
    assert int(restored_state.round) == 10
    resumed, _, _ = run_rounds(
        restored_state, cfg, 10, restored_key, crash_rate=0.05, rejoin_rate=0.02
    )

    assert jnp.array_equal(full.hb, resumed.hb)
    assert jnp.array_equal(full.age, resumed.age)
    assert jnp.array_equal(full.status, resumed.status)
    assert jnp.array_equal(full.alive, resumed.alive)
    assert int(full.round) == int(resumed.round) == 20


def test_restore_onto_mesh_resumes_sharded_run(tmp_path):
    from gossipfs_tpu.parallel.mesh import make_mesh, shard_state, state_shardings

    cfg = SimConfig(
        n=32, topology="random", fanout=3, remove_broadcast=False,
        fresh_cooldown=True,
    )
    mesh = make_mesh(8)
    key = jax.random.PRNGKey(0)
    state = shard_state(init_state(cfg), mesh)
    state, _, _ = run_rounds(state, cfg, 5, key, crash_rate=0.05)
    save_checkpoint(tmp_path / "ckpt", state, key)
    restored, rkey = restore_checkpoint(tmp_path / "ckpt", cfg, mesh=mesh)
    # arrays come back already on their run shardings...
    assert restored.hb.sharding == state_shardings(mesh).hb
    assert jnp.array_equal(restored.hb, state.hb)
    # ...so the resumed sharded scan runs directly (this failed before the
    # mesh-aware restore: the key came back committed to one device)
    cont, _, _ = run_rounds(restored, cfg, 3, rkey, crash_rate=0.05)
    assert int(cont.round) == 8


def test_legacy_int32_age_checkpoint_restores_clamped(tmp_path):
    """Pre-int8-lane checkpoints stored age as unclamped int32.

    Orbax silently casts to the abstract target's dtype on restore, so a
    naive int8 target would wrap a legacy age of 200 to -56 (evading the
    ``age > t_fail`` detector for ~60 extra rounds).  restore_checkpoint
    must instead clamp legacy ages into the int8 saturation regime.
    """
    import orbax.checkpoint as ocp

    from gossipfs_tpu.config import AGE_CLAMP

    cfg = SimConfig(
        n=16, topology="random", fanout=3, remove_broadcast=False,
        fresh_cooldown=True,
    )
    key = jax.random.PRNGKey(3)
    state = init_state(cfg)
    legacy = state._asdict()
    legacy["age"] = jnp.full((cfg.n, cfg.n), 200, jnp.int32)
    # pre-hb_base-era checkpoints lack the per-subject base lane entirely
    del legacy["hb_base"]
    path = (tmp_path / "legacy").resolve()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"state": legacy, "key": key}, force=True)

    restored, _ = restore_checkpoint(path, cfg)
    assert restored.age.dtype == jnp.int8
    assert jnp.all(restored.age == AGE_CLAMP)
    assert jnp.array_equal(restored.hb_base, jnp.zeros((cfg.n,), jnp.int32))


def test_int32_checkpoint_migrates_to_int16_without_wrapping(tmp_path):
    """Resuming an absolute-int32-era checkpoint under hb_dtype='int16'
    must renormalize counters above the int16 range against a fresh base,
    not silently wrap them (the same hazard the age lane guards against)."""
    import dataclasses

    from gossipfs_tpu.utils.checkpoint import save_checkpoint

    cfg32 = SimConfig(n=128, topology="random", fanout=6, hb_dtype="int32")
    key = jax.random.PRNGKey(9)
    state = init_state(cfg32)
    # simulate a >32k-round run: counters far past the int16 range
    state = state._replace(hb=state.hb + 100_000)
    path = (tmp_path / "wide").resolve()
    save_checkpoint(path, state, key)

    cfg16 = dataclasses.replace(cfg32, hb_dtype="int16")
    restored, _ = restore_checkpoint(path, cfg16)
    assert restored.hb.dtype == jnp.int16
    # true counters survive exactly (100_000 would have wrapped to -31072)
    assert jnp.array_equal(restored.hb_true(), state.hb)

    # and the reverse migration recovers the absolute encoding
    path2 = (tmp_path / "narrow").resolve()
    save_checkpoint(path2, restored, key)
    back, _ = restore_checkpoint(path2, cfg32)
    assert back.hb.dtype == jnp.int32
    assert jnp.array_equal(back.hb, state.hb)
    assert jnp.all(back.hb_base == 0)


def test_int16_hb_checkpoint_roundtrip(tmp_path):
    """hb_dtype='int16' states (relative counters + hb_base) survive
    save/restore and continue identically to an uninterrupted run."""
    from gossipfs_tpu.core.rounds import run_rounds
    from gossipfs_tpu.utils.checkpoint import save_checkpoint

    cfg = SimConfig(n=128, topology="random", fanout=6, hb_dtype="int16")
    key = jax.random.PRNGKey(7)
    state = init_state(cfg)
    state, _, _ = run_rounds(state, cfg, 6, key, crash_rate=0.05)
    path = (tmp_path / "ck16").resolve()
    save_checkpoint(path, state, key)
    restored, rkey = restore_checkpoint(path, cfg)
    assert restored.hb.dtype == jnp.int16
    cont_a, _, _ = run_rounds(state, cfg, 5, key)
    cont_b, _, _ = run_rounds(restored, cfg, 5, rkey)
    for a, b in zip(jax.tree.leaves(cont_a), jax.tree.leaves(cont_b)):
        assert jnp.array_equal(a, b)


def test_narrow_checkpoint_sentinels_quarantined_on_int32_restore(tmp_path):
    """A narrow-era checkpoint's floor sentinels (unknown counters) must not
    decode into ordinary heartbeat values under an int32 restore target —
    they are quarantined far above the gossip window, so they spread to
    nobody, age out, and can never suppress detection (the fabricated-
    counter corner the hb_floor payload field exists to close)."""
    import dataclasses

    cfg8 = SimConfig(
        n=128, topology="random", fanout=6,
        view_dtype="int8", hb_dtype="int8",
    )
    state = init_state(cfg8)
    # hand-craft a narrow-era state: a positive base with one stored floor
    # sentinel and one ordinary relative counter
    floor = jnp.iinfo(jnp.int8).min
    hb = state.hb.at[3, 5].set(floor).at[4, 5].set(7)
    state = state._replace(
        hb=hb, hb_base=state.hb_base.at[5].set(1000),
    )
    path = (tmp_path / "ck8").resolve()
    save_checkpoint(path, state, jax.random.PRNGKey(0))

    cfg32 = dataclasses.replace(cfg8, view_dtype="int16", hb_dtype="int32")
    restored, _ = restore_checkpoint(path, cfg32)
    assert restored.hb.dtype == jnp.int32
    # the ordinary counter decodes to its true value...
    assert int(restored.hb[4, 5]) == 1007
    # ...while the sentinel becomes a quarantine value far above any
    # reachable counter (not base + floor = 872, a plausible fabrication)
    assert int(restored.hb[3, 5]) == 2 ** 30
